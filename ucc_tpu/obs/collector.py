"""Continuous telemetry collector — the flight recorder as a control loop.

PR 9's flight recorder answers *what happened* only when something
triggers a dump: diagnosis is forensic. This module closes the loop the
100k+ GPUs paper (PAPERS.md) describes as the production regime:

- a background **collection service** (``UCC_COLLECT=y``, owned by the
  context lifecycle) that periodically snapshots every watched team's
  ring *window* (events since the previous window) and gathers it
  cross-rank over the service-team transport — the same PR-8 k-ary
  ``TransportOob`` tree on-demand collection rides;
- **per-pod merge before forwarding up**: window snapshots are
  exchanged inside each HierTree level-0 group, each group reduces its
  raw rings to a compact severity summary, and only the summaries
  travel between group leaders — no rank ever holds O(world) raw
  rings;
- a rolling **on-disk trace store** (bounded JSON-line segments,
  ``UCC_COLLECT_DIR``) that ``ucc_fr`` can merge and tail;
- an incremental **straggler scorer** (obs/diagnose.StragglerScorer):
  per-rank EWMA slowness fed by the three window-scoped straggler
  signals, with hysteresis so a rank must *stay* slow to stay flagged;
- the **feedback edge**: a per-team :class:`RankBias` table that
  selection consults — ScoreMap candidate ordering demotes ring-family
  algorithms whose critical path serializes through a flagged rank,
  the online tuner weights rank-0 medians, the cost model scales a
  flagged rank's link terms, and the hier tree demotes flagged ranks
  from leader positions at (re)build.

Divergence safety — the part that makes feedback *safe* to wire into
selection: every rank derives the flagged set from the SAME global
summary (stage-3 rebroadcast), and a new table only takes effect at a
deterministic flight-sequence index (``apply_at`` = the window's max
observed ``flight_seq`` + ``UCC_RANK_BIAS_SLACK``) — the same
switch-at-a-post-index design the tuner's decision bcast uses, because
any cross-rank divergence in candidate order deadlocks the team
(score/score_map._cand_order).

Threading model: the collector THREAD only marks windows due on a
timer; all transport work (posting/polling the window exchanges) runs
from ``Context.progress()`` on the application's progress thread, so
the collector never races the cooperative progress loop.
"""
from __future__ import annotations

import json
import os
import pickle
import threading
import time
import weakref
from typing import Any, Dict, FrozenSet, List, Optional

from ..status import Status
from ..utils.config import (ConfigField, ConfigTable, parse_bool,
                            parse_double, parse_string, parse_uint,
                            register_table)
from ..utils.log import get_logger

logger = get_logger("obs")

_COLLECT_CONFIG = register_table(ConfigTable(
    prefix="", name="obs/collector", fields=[
        ConfigField("COLLECT", "n",
                    "continuous telemetry collection: a background "
                    "service gathers flight-recorder ring windows "
                    "cross-rank over the service team, merges them "
                    "per-pod along the hier tree, scores per-rank "
                    "slowness, and publishes a RankBias table that "
                    "algorithm selection consults. n = forensic-only "
                    "flight recorder (dump-triggered collection)",
                    parse_string),
        ConfigField("COLLECT_INTERVAL", "30.0",
                    "seconds between collection windows (the timer that "
                    "marks a window due; exchanges run on the progress "
                    "thread)", parse_double),
        ConfigField("COLLECT_SAMPLE", "1",
                    "collect every Nth window: window indices not "
                    "divisible by N are skipped without any exchange "
                    "(deterministic across ranks). 1 = every window",
                    parse_uint),
        ConfigField("COLLECT_DIR", "ucc_traces",
                    "rolling on-disk trace store: per-pod merged window "
                    "dumps and global severity summaries appended as "
                    "JSON lines into bounded segment files; read with "
                    "`ucc_fr <dir>` / `ucc_fr <dir> --tail N`. Empty "
                    "disables the store", parse_string),
        ConfigField("COLLECT_SEGMENT_BYTES", "4194304",
                    "trace-store segment rotation threshold (bytes)",
                    parse_uint),
        ConfigField("COLLECT_SEGMENTS", "8",
                    "trace-store segments kept per process; the oldest "
                    "is deleted on rotation", parse_uint),
        ConfigField("RANK_BIAS", "y",
                    "feed collector straggler findings back into "
                    "algorithm selection: flagged ranks demote "
                    "ring-family candidates in the score map, weight "
                    "tuner medians, scale cost-model link terms, and "
                    "are demoted from hier-tree leader positions at "
                    "team (re)build. n = observe-only collection",
                    parse_string),
        ConfigField("RANK_BIAS_DECAY", "0.5",
                    "EWMA weight of the newest window's severity in a "
                    "rank's slowness score (0..1; higher reacts faster)",
                    parse_double),
        ConfigField("RANK_BIAS_FLAG_ON", "0.7",
                    "slowness score a rank must reach (with "
                    "UCC_RANK_BIAS_WINDOWS consecutive slow windows) to "
                    "be flagged", parse_double),
        ConfigField("RANK_BIAS_FLAG_OFF", "0.2",
                    "hysteresis: a flagged rank unflags only once its "
                    "score decays below this", parse_double),
        ConfigField("RANK_BIAS_WINDOWS", "2",
                    "consecutive slow windows required before a rank "
                    "can be flagged (transient spikes never flag)",
                    parse_uint),
        ConfigField("RANK_BIAS_PENALTY", "4096",
                    "score-map penalty per flagged member on the "
                    "critical path of a ring-family candidate; any "
                    "penalized candidate orders after every unpenalized "
                    "one (user-forced `inf` scores are exempt)",
                    parse_uint),
        ConfigField("RANK_BIAS_SLACK", "16",
                    "flight-sequence posts between a window's global "
                    "summary and the deterministic index at which every "
                    "rank applies the new RankBias to selection (the "
                    "tuner-style divergence-free switch point)",
                    parse_uint),
        ConfigField("RANK_BIAS_SLOW_MULT", "4.0",
                    "slowness multiplier on a flagged rank's cost-model "
                    "link terms (and the tuner's ring-family medians): "
                    "searched/tuned programs price traffic through a "
                    "flagged rank this many times slower",
                    parse_double),
    ]))


class _Knobs:
    """Resolved collector knobs; module-level so tests can override via
    :func:`configure` without touching the environment."""

    def __init__(self):
        from ..utils.config import Config
        self.enabled = False
        self.interval = 30.0
        self.sample = 1
        self.dir = "ucc_traces"
        self.segment_bytes = 4 << 20
        self.segments = 8
        self.bias = True
        self.decay = 0.5
        self.flag_on = 0.7
        self.flag_off = 0.2
        self.windows = 2
        self.penalty = 4096
        self.slack = 16
        self.slow_mult = 4.0
        try:
            cfg = Config(_COLLECT_CONFIG)
            try:
                self.enabled = parse_bool(str(cfg.collect))
            except ValueError:
                self.enabled = False
            self.interval = max(0.05, float(cfg.collect_interval))
            self.sample = max(1, int(cfg.collect_sample))
            self.dir = str(cfg.collect_dir)
            self.segment_bytes = max(4096, int(cfg.collect_segment_bytes))
            self.segments = max(1, int(cfg.collect_segments))
            try:
                self.bias = parse_bool(str(cfg.rank_bias))
            except ValueError:
                self.bias = True
            self.decay = min(1.0, max(0.01, float(cfg.rank_bias_decay)))
            self.flag_on = float(cfg.rank_bias_flag_on)
            self.flag_off = float(cfg.rank_bias_flag_off)
            self.windows = max(1, int(cfg.rank_bias_windows))
            self.penalty = int(cfg.rank_bias_penalty)
            self.slack = max(1, int(cfg.rank_bias_slack))
            self.slow_mult = max(1.0, float(cfg.rank_bias_slow_mult))
        except Exception:  # noqa: BLE001 - knob resolution never breaks import
            pass


KNOBS = _Knobs()
ENABLED = KNOBS.enabled


def configure(**kw) -> None:
    """Runtime (re)configuration (tests/embedders; env read at import).
    Keyword names match :class:`_Knobs` attributes plus ``enabled``."""
    global ENABLED
    for k, v in kw.items():
        if not hasattr(KNOBS, k):
            raise AttributeError(f"unknown collector knob {k!r}")
        setattr(KNOBS, k, v)
    ENABLED = KNOBS.enabled


# ---------------------------------------------------------------------------
# RankBias — the feedback table selection consults
# ---------------------------------------------------------------------------

#: algorithm-name tokens whose critical path serializes through EVERY
#: team member (one slow rank stalls each round): the candidates a
#: flagged rank demotes. Tree/knomial families route around a slow leaf.
_RING_TOKENS = ("ring", "sliding", "sra")


def is_ring_family(alg_name: str, gen: str = "") -> bool:
    s = f"{alg_name or ''} {gen or ''}".lower()
    return any(tok in s for tok in _RING_TOKENS)


class RankBias:
    """Per-team straggler feedback table published by the collector.

    ``flagged`` holds TEAM ranks currently scored slow (hysteresis in
    the scorer keeps it stable); ``scores`` the underlying EWMA values.
    A new table is staged by :meth:`publish` and only promoted by
    :meth:`tick` once the team's flight sequence reaches the staged
    ``apply_at`` — every rank ticks at the same program-order points, so
    the flagged set (and therefore candidate order) can never diverge
    across ranks mid-stream.
    """

    __slots__ = ("penalty", "slow_mult", "flagged", "scores", "window",
                 "_pending", "first_flag_window")

    def __init__(self, penalty: Optional[int] = None,
                 slow_mult: Optional[float] = None):
        self.penalty = KNOBS.penalty if penalty is None else int(penalty)
        self.slow_mult = KNOBS.slow_mult if slow_mult is None \
            else float(slow_mult)
        self.flagged: FrozenSet[int] = frozenset()
        self.scores: Dict[int, float] = {}
        self.window = -1
        self._pending = None
        #: window index of the first nonempty flagged set ever published
        #: (drill/accounting: "flagged within N windows")
        self.first_flag_window: Optional[int] = None

    # -- collector side -------------------------------------------------
    def publish(self, flagged, scores: Dict[int, float], window: int,
                apply_at: int) -> None:
        flagged = frozenset(flagged)
        if flagged and self.first_flag_window is None:
            self.first_flag_window = int(window)
        p = self._pending
        if p is not None and p[1] == flagged:
            # same flagged set re-published: refresh the observations
            # but KEEP the original switch index — re-staging with a
            # fresh apply_at every window would forever push the switch
            # past the post frontier of a team that posts fewer than
            # `slack` collectives per window, and the table would never
            # take effect
            self._pending = (p[0], flagged, dict(scores), int(window))
            return
        if p is None and flagged == self.flagged:
            # no candidate-order change: fold fresh scores in place
            # (selection only reads `flagged`, so this cannot diverge)
            self.scores = dict(scores)
            self.window = int(window)
            return
        self._pending = (int(apply_at), flagged, dict(scores),
                         int(window))

    # -- dispatch side --------------------------------------------------
    def tick(self, flight_seq: int) -> None:
        """Promote a staged table once the deterministic switch index is
        reached. Called from dispatch in program order on every rank."""
        p = self._pending
        if p is not None and flight_seq >= p[0]:
            self._pending = None
            _, self.flagged, self.scores, self.window = p

    def penalty_units(self, cand) -> int:
        """Flagged members on *cand*'s critical path: ring-family
        candidates serialize through every member, so they pay one unit
        per flagged rank; tree-family candidates pay none."""
        if not self.flagged:
            return 0
        if is_ring_family(getattr(cand, "alg_name", "") or "",
                          getattr(cand, "gen", "") or ""):
            return len(self.flagged)
        return 0

    def reorder(self, cands: List[Any]) -> List[Any]:
        """Bias-aware candidate order (ScoreMap.lookup): any candidate
        paying a penalty sorts after every unpenalized candidate
        (user-forced SCORE_MAX entries are exempt — an explicit `inf`
        still outranks feedback), and penalized candidates order among
        themselves by score minus ``penalty`` per flagged member.
        Deterministic: the input order and the flagged set are identical
        on every rank, so the output is too."""
        if not self.flagged:
            return cands
        from ..score.score import SCORE_MAX

        def key(p):
            i, r = p
            u = 0 if r.score >= SCORE_MAX else self.penalty_units(r)
            return (1 if u else 0, -(r.score - u * self.penalty), i)

        return [r for _, r in sorted(enumerate(cands), key=key)]

    def time_multiplier(self, alg_name: str, gen: str = "") -> float:
        """Measured-time weight the tuner's rank-0 decision applies: a
        ring-family candidate's median is inflated per flagged member,
        so a straggler-serialized winner must beat the alternatives by
        the slowness factor to stay the winner."""
        if not self.flagged or not is_ring_family(alg_name, gen):
            return 1.0
        return 1.0 + (self.slow_mult - 1.0) * len(self.flagged)

    def slow_map(self) -> Dict[int, float]:
        """{team rank: multiplier} for the cost model's per-rank
        slowness scaling (score/cost.CostModel.features)."""
        return {r: self.slow_mult for r in self.flagged}

    def describe(self) -> str:
        if not self.flagged and not self.scores:
            return "rank bias: clean"
        segs = [f"rank bias (window {self.window}):"]
        for r in sorted(self.scores):
            mark = " FLAGGED" if r in self.flagged else ""
            segs.append(f" r{r}={self.scores[r]:.2f}{mark}")
        return "".join(segs)


# ---------------------------------------------------------------------------
# rolling on-disk trace store
# ---------------------------------------------------------------------------

class TraceStore:
    """Bounded JSON-line segment files under one directory. Rotation is
    size-based; at most ``max_segments`` segments are kept per process
    (older ones deleted oldest-first). Segment names embed the pid so
    multi-process jobs sharing a directory never interleave writes."""

    def __init__(self, dirpath: str, segment_bytes: int,
                 max_segments: int):
        self.dir = dirpath
        self.segment_bytes = int(segment_bytes)
        self.max_segments = max(1, int(max_segments))
        self._lock = threading.Lock()
        self._seq = 0
        self._cur: Optional[str] = None
        self._cur_bytes = 0

    def _segment_name(self, seq: int) -> str:
        return os.path.join(self.dir,
                            f"fr-{os.getpid()}-{seq:06d}.jsonl")

    def _my_segments(self) -> List[str]:
        try:
            names = sorted(n for n in os.listdir(self.dir)
                           if n.startswith(f"fr-{os.getpid()}-")
                           and n.endswith(".jsonl"))
        except OSError:
            return []
        return [os.path.join(self.dir, n) for n in names]

    def append(self, rec: Dict[str, Any]) -> Optional[str]:
        """Append one record; returns the segment path written (None on
        store failure — telemetry must never raise into the caller)."""
        line = json.dumps(rec, default=str) + "\n"
        with self._lock:
            try:
                os.makedirs(self.dir, exist_ok=True)
                if self._cur is None or \
                        self._cur_bytes >= self.segment_bytes:
                    self._rotate()
                with open(self._cur, "a") as fh:
                    fh.write(line)
                self._cur_bytes += len(line)
                return self._cur
            except OSError:
                logger.exception("trace store append failed")
                return None

    def _rotate(self) -> None:
        self._seq += 1
        self._cur = self._segment_name(self._seq)
        self._cur_bytes = 0
        segs = self._my_segments()
        # the new segment doesn't exist yet; +1 accounts for it
        excess = len(segs) + 1 - self.max_segments
        for path in segs[:max(0, excess)]:
            try:
                os.remove(path)
            except OSError:
                pass


def load_dir_records(dirpath: str,
                     tail: Optional[int] = None) -> List[Dict[str, Any]]:
    """Read trace-store records from *dirpath* (all processes'
    segments, oldest-first by mtime then name). ``tail`` keeps only the
    N freshest segments — the `ucc_fr --tail` view of a long-running
    store."""
    try:
        names = [n for n in os.listdir(dirpath) if n.endswith(".jsonl")]
    except OSError:
        return []
    paths = [os.path.join(dirpath, n) for n in names]

    def order(p):
        try:
            return (os.stat(p).st_mtime, p)
        except OSError:
            return (0.0, p)

    paths.sort(key=order)
    if tail is not None:
        paths = paths[-max(1, int(tail)):]
    recs: List[Dict[str, Any]] = []
    for p in paths:
        try:
            with open(p) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict):
                        recs.append(rec)
        except OSError:
            continue
    return recs


# ---------------------------------------------------------------------------
# per-team window state machine
# ---------------------------------------------------------------------------

def _window_events(events: List[dict], cut: float) -> List[dict]:
    """Events newer than *cut*, PLUS the post events of any completion
    inside the window (the scorer's duration join needs the post even
    when it predates the window)."""
    if cut <= 0.0:
        return list(events)
    out = [ev for ev in events if (ev.get("t") or 0.0) > cut]
    need = {ev.get("seq") for ev in out
            if ev.get("ev") == "cmpl" and ev.get("seq") is not None}
    if need:
        have = {ev.get("seq") for ev in out if ev.get("ev") == "post"}
        for ev in events:
            if ev.get("ev") == "post" and ev.get("seq") in need and \
                    ev.get("seq") not in have and \
                    (ev.get("t") or 0.0) <= cut:
                out.append(ev)
        # restore ring (time) order: the cmpl->post join walks events in
        # sequence and a post appended AFTER its cmpl never joins
        out.sort(key=lambda ev: ev.get("t") or 0.0)
    return out


class _TeamWatch:
    """One watched team's continuous-collection state: window counters,
    the 3-stage hierarchical exchange in flight (if any), the
    incremental scorer, and the published RankBias."""

    # exchange stages of one sampled window
    ST_GATHER = 1      # intra-group allgather of raw window snapshots
    ST_LEADERS = 2     # leaders-only allgather of pod summaries
    ST_BCAST = 3       # intra-group rebroadcast of the global summary

    def __init__(self, service: "CollectorService", team):
        from . import diagnose
        self.service = service
        self.team_ref = weakref.ref(team)
        self.window = 0            # next window index to run
        self.due = 0               # windows the timer has marked due
        self.stage = 0             # 0 = idle
        self.cut_t = 0.0           # ring high-water mark (monotonic)
        self._req = None
        self._deadline = 0.0
        self._pod_summary: Optional[dict] = None
        self._global: Optional[dict] = None
        # level-0 group (team ranks) + group leaders from the hier tree:
        # the per-pod merge domain. Flat/single-node teams collapse to
        # one group covering the team (stage 2/3 skipped).
        tree = None
        try:
            if team.topo is not None and team.size > 1:
                tree = team.topo.hier_tree()
        except Exception:  # noqa: BLE001 - a topology quirk must not
            logger.exception("collector: hier tree build failed; "
                             "using a flat group")
        if tree is not None and len(tree.level(0).groups) > 1:
            self.group = list(tree.group(0, team.rank))
            self.leaders = [g[0] for g in tree.level(0).groups]
        else:
            self.group = list(range(team.size))
            self.leaders = [self.group[0]]
        self.is_leader = team.rank == self.group[0]
        self.is_top = team.rank == self.leaders[0]
        k = KNOBS
        self.scorer = diagnose.StragglerScorer(
            decay=k.decay, flag_on=k.flag_on, flag_off=k.flag_off,
            windows=k.windows)
        self.bias = RankBias() if k.bias else None
        if self.bias is not None:
            team.rank_bias = self.bias

    # ------------------------------------------------------------------
    def _oob(self, team, members: List[int], stage: int):
        from ..core.oob import TransportOob
        svc = team.service_team
        member_ctx = [int(team.ctx_map.eval(r)) for r in members]
        return TransportOob(
            svc.comp_context, svc.transport, member_ctx,
            team.context.rank,
            ("fcw", team.team_key, self.window, stage), team.epoch)

    def _snapshot_window(self, team) -> dict:
        rec = getattr(team.context, "flight", None)
        snap = rec.snapshot() if rec is not None else {
            "rank": team.rank, "uid": "", "pid": os.getpid(),
            "events": [], "wire": [], "dropped": 0}
        cut = self.cut_t
        snap["events"] = _window_events(snap.get("events") or [], cut)
        # drop the collector's OWN exchange traffic ("fcw" space keys):
        # self-observation would otherwise dominate quiet windows and
        # feed the wire-lag detector rounds the app never ran
        snap["wire"] = [w for w in (snap.get("wire") or [])
                        if (w.get("t") or 0.0) > cut
                        and "fcw" not in str(w.get("tkey"))]
        snap["window"] = self.window
        # per-tenant QoS counters ride with the window: queue-wait per
        # team, lane depths, inversion/starvation counters since the
        # last window (schedule/progress.qos_snapshot). Observational —
        # persisted in the pod record for ucc_fr/offline analysis.
        try:
            snap["qos"] = team.context.progress_queue.qos_snapshot(
                reset=True)
        except Exception:  # noqa: BLE001 - telemetry must never take
            # down the window exchange
            pass
        return snap

    def step(self) -> None:
        team = self.team_ref()
        if team is None or team._destroyed or team._shrunk:
            self.service.unwatch(self)
            return
        if self.stage == 0:
            if self.due <= self.window:
                return
            if self.window % KNOBS.sample:
                self.window += 1        # unsampled window: no exchange
                return
            self._start(team)
            return
        req = self._req
        if req is None:
            return
        try:
            st = req.test()
        except Exception as e:  # noqa: BLE001 - a torn-down transport
            # mid-window degrades to an abandoned window, never a raise
            logger.warning("collector window %d exchange failed: %s",
                           self.window, e)
            self._abandon()
            return
        if st == Status.IN_PROGRESS:
            if time.monotonic() > self._deadline:
                logger.warning(
                    "collector window %d stage %d timed out; abandoning",
                    self.window, self.stage)
                self._abandon()
            return
        try:
            self._advance(team, req.result)
        except Exception:  # noqa: BLE001 - telemetry must never take
            # down the progress loop
            logger.exception("collector window %d stage %d failed",
                             self.window, self.stage)
            self._abandon()

    def _start(self, team) -> None:
        svc = team.service_team
        if svc is None or getattr(svc, "transport", None) is None or \
                team.size <= 1:
            # no exchange channel: local-only scoring (size-1 teams) —
            # a window over this rank alone carries no peer comparison,
            # so just advance the high-water mark
            self.cut_t = time.monotonic()
            self.window += 1
            return
        snap = self._snapshot_window(team)
        payload = pickle.dumps({"fseq": team.flight_seq, "snap": snap})
        self._req = self._oob(team, self.group, self.ST_GATHER)\
            .allgather(payload)
        self.stage = self.ST_GATHER
        self._deadline = time.monotonic() + max(30.0, KNOBS.interval * 2)
        # the next window's events start where this snapshot ended
        self.cut_t = time.monotonic()

    def _advance(self, team, result) -> None:
        from . import diagnose
        if self.stage == self.ST_GATHER:
            msgs = [pickle.loads(b) for b in result]
            pod = {"version": 1, "kind": "flight_merged",
                   "reason": "collect", "ts": time.time(),
                   "pid": os.getpid(), "window": self.window,
                   "team": team.id, "team_size": team.size,
                   # membership epoch: pre- and post-change windows of
                   # the same logical job merge cleanly in the trace
                   # store (readers key on (team, epoch, window))
                   "epoch": int(getattr(team, "epoch", 0)),
                   "absent_ranks": [],
                   "ranks": {str(r): m["snap"]
                             for r, m in zip(self.group, msgs)}}
            idx = diagnose._index(pod)
            sev = self.scorer.observe(pod, _idx=idx)
            self._pod_summary = {
                "ranks": list(self.group),
                "sev": {int(r): float(s) for r, s in sev.items()},
                "max_fseq": max(int(m.get("fseq") or 0) for m in msgs),
            }
            if len(self.leaders) > 1:
                # compact per-collective durations ride up with the
                # summary so leaders can run CROSS-pod outlier detection
                # (the >=3-rank duration signal is blind inside a small
                # pod). Only interval features cross the pod boundary:
                # durations compare across hosts, raw monotonic wire
                # timestamps do not.
                durs: Dict[Any, Dict[int, float]] = {}
                for r, ri in idx.items():
                    for key, d in ri.durs.items():
                        durs.setdefault(key, {})[int(r)] = float(d)
                self._pod_summary["durs"] = durs
            if self.is_leader:
                self.service.store_append(pod)
            if len(self.leaders) > 1:
                if self.is_leader:
                    self._req = self._oob(team, self.leaders,
                                          self.ST_LEADERS).allgather(
                        pickle.dumps(self._pod_summary))
                    self.stage = self.ST_LEADERS
                else:
                    # non-leaders park until the leader rebroadcasts
                    self._req = self._oob(team, self.group,
                                          self.ST_BCAST).allgather(b"")
                    self.stage = self.ST_BCAST
                return
            # single group: the pod summary IS the global summary
            self._apply(team, self._merge_summaries([self._pod_summary]))
            return
        if self.stage == self.ST_LEADERS:
            summaries = [pickle.loads(b) for b in result]
            self._global = self._merge_summaries(summaries)
            if len(self.group) > 1:
                self._req = self._oob(team, self.group,
                                      self.ST_BCAST).allgather(
                    pickle.dumps(self._global))
                self.stage = self.ST_BCAST
                return
            self._apply(team, self._global)
            return
        if self.stage == self.ST_BCAST:
            # the leader's entry (group position 0) carries the global
            # summary; everyone else contributed b""
            data = result[0]
            if not data and self._global is not None:
                g = self._global
            else:
                g = pickle.loads(data) if data else None
            if g is None:
                logger.warning("collector window %d: empty global "
                               "summary; abandoning", self.window)
                self._abandon()
                return
            self._apply(team, g)

    def _merge_summaries(self, summaries: List[dict]) -> dict:
        ranks: List[int] = []
        sev: Dict[int, float] = {}
        max_fseq = 0
        durs: Dict[Any, Dict[int, float]] = {}
        for s in summaries:
            ranks.extend(int(r) for r in s.get("ranks") or ())
            for r, v in (s.get("sev") or {}).items():
                sev[int(r)] = sev.get(int(r), 0.0) + float(v)
            max_fseq = max(max_fseq, int(s.get("max_fseq") or 0))
            for key, per in (s.get("durs") or {}).items():
                dst = durs.setdefault(key, {})
                for r, d in per.items():
                    dst[int(r)] = float(d)
        # cross-pod duration outliers: every leader merges the same
        # summary list, so this runs identically on each — no extra
        # exchange needed for the verdict to agree
        slow: Dict[int, int] = {}
        factor, min_s = self.scorer.factor, self.scorer.min_s
        for per in durs.values():
            if len(per) < 3:
                continue
            vals = sorted(per.values())
            n = len(vals)
            med = vals[n // 2] if n % 2 else \
                0.5 * (vals[n // 2 - 1] + vals[n // 2])
            r_max = max(per, key=lambda r: per[r])
            if per[r_max] > max(med * factor, med + min_s):
                slow[r_max] = slow.get(r_max, 0) + 1
        for r in slow:
            sev[r] = sev.get(r, 0.0) + 1.0
        return {"ranks": sorted(set(ranks)), "sev": sev,
                "max_fseq": max_fseq}

    def _apply(self, team, g: dict) -> None:
        flagged = self.scorer.update(g.get("sev") or {},
                                     g.get("ranks") or ())
        if self.bias is not None:
            apply_at = int(g.get("max_fseq") or 0) + KNOBS.slack
            self.bias.publish(flagged, self.scorer.scores, self.window,
                              apply_at)
        if self.is_top:
            self.service.store_append({
                "version": 1, "kind": "collect_summary",
                "ts": time.time(), "team": team.id,
                "epoch": int(getattr(team, "epoch", 0)),
                "window": self.window,
                "sev": {str(r): round(v, 4)
                        for r, v in (g.get("sev") or {}).items()},
                "scores": {str(r): round(v, 4)
                           for r, v in self.scorer.scores.items()},
                "flagged": sorted(flagged),
                "apply_at": int(g.get("max_fseq") or 0) + KNOBS.slack,
            })
        if flagged:
            logger.info("collector: team %s window %d flagged rank(s) "
                        "%s", team.id, self.window,
                        ",".join(str(r) for r in sorted(flagged)))
        self._finish_window()

    def _abandon(self) -> None:
        self._finish_window()

    def _finish_window(self) -> None:
        self._req = None
        self._pod_summary = None
        self._global = None
        self.stage = 0
        self.window += 1


# ---------------------------------------------------------------------------
# per-context service
# ---------------------------------------------------------------------------

class CollectorService:
    """Per-context collection service: owns the window timer thread and
    drives every watched team's window state machine from the progress
    path (``Context.progress`` calls :meth:`step`)."""

    def __init__(self, context):
        self.context_ref = weakref.ref(context)
        self._watches: List[_TeamWatch] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.store: Optional[TraceStore] = None
        if KNOBS.dir:
            self.store = TraceStore(KNOBS.dir, KNOBS.segment_bytes,
                                    KNOBS.segments)
        self._thread = threading.Thread(
            target=self._timer_loop, daemon=True,
            name=f"ucc-collector-{getattr(context, 'rank', 0)}")
        self._thread.start()

    # -- team registry --------------------------------------------------
    def watch(self, team) -> Optional[_TeamWatch]:
        """Start continuous collection for *team* (called at team
        activation). Returns the watch, or None for unwatchable teams."""
        if team.size <= 1:
            return None
        w = _TeamWatch(self, team)
        with self._lock:
            self._watches.append(w)
        return w

    def unwatch(self, watch: _TeamWatch) -> None:
        with self._lock:
            try:
                self._watches.remove(watch)
            except ValueError:
                pass

    def flagged_ctx(self) -> FrozenSet[int]:
        """Union of flagged ranks across watched teams, as CONTEXT
        ranks — the view a NEW team's bootstrap exchange publishes so
        its hier tree can demote stragglers from leader positions."""
        out = set()
        with self._lock:
            watches = list(self._watches)
        for w in watches:
            team = w.team_ref()
            if team is None or w.bias is None:
                continue
            for tr in w.bias.flagged:
                try:
                    out.add(int(team.ctx_map.eval(tr)))
                except Exception:  # noqa: BLE001 - a torn-down map
                    continue
        return frozenset(out)

    def watch_for(self, team) -> Optional[_TeamWatch]:
        """The watch driving *team*'s windows, if any (tools/drills)."""
        with self._lock:
            for w in self._watches:
                if w.team_ref() is team:
                    return w
        return None

    def handoff(self, old_team, new_team) -> None:
        """Membership-change telemetry continuity (Team.shrink / grow):
        carry the retired team's straggler-learning state into the
        successor's watch so the new epoch does not relearn flags from
        scratch. Rank-keyed state is remapped THROUGH context ranks
        (old team rank -> ctx -> new team rank) — the rank set is no
        longer monotone once teams can grow. The successor's window
        index deliberately restarts at 0: exchange keys embed the
        window index, and a joiner's watch has no pre-grow count to
        agree with — epoch stamps in the records keep the pre-/post-
        change windows mergeable instead. Survivors inherit the ring
        high-water mark (no event re-reported across the change);
        joiners keep cut 0, so their ``boot:*`` spans land in the
        merged first window."""
        old_w = self.watch_for(old_team)
        new_w = self.watch_for(new_team)
        if old_w is not None:
            self.unwatch(old_w)   # retired teams stop exchanging NOW
        if old_w is None or new_w is None:
            return
        ctx_to_new = {}
        for i in range(new_team.size):
            try:
                ctx_to_new[int(new_team.ctx_map.eval(i))] = i
            except Exception:  # noqa: BLE001 - torn-down map: no carry
                return

        def remap(d):
            out = {}
            for r, v in d.items():
                try:
                    c = int(old_team.ctx_map.eval(int(r)))
                except Exception:  # noqa: BLE001 - rank gone from map
                    continue
                nr = ctx_to_new.get(c)
                if nr is not None:
                    out[nr] = v
            return out

        sc_old, sc_new = old_w.scorer, new_w.scorer
        sc_new.scores = remap(sc_old.scores)
        sc_new.streaks = remap(sc_old.streaks)
        sc_new.flagged = set(remap({r: r for r in sc_old.flagged}))
        sc_new.windows_seen = sc_old.windows_seen
        new_w.cut_t = old_w.cut_t if new_w.cut_t == 0.0 else new_w.cut_t
        if old_w.bias is not None and new_w.bias is not None:
            # promoted state only: a table still staged on the retired
            # team applied at a flight index of the OLD epoch's program
            # order, which does not exist on the successor — it will be
            # re-learned within a window if still true
            new_w.bias.flagged = frozenset(
                remap({r: r for r in old_w.bias.flagged}))
            new_w.bias.scores = remap(old_w.bias.scores)
        logger.info(
            "collector handoff: team %s -> %s (epoch %s): carried "
            "%d score(s), flagged %s", old_team.id, new_team.id,
            getattr(new_team, "epoch", "?"), len(sc_new.scores),
            sorted(sc_new.flagged) or "none")

    def windows_run(self) -> int:
        """Highest completed window index across watched teams — how
        many collection windows actually closed (soak/tool reporting)."""
        with self._lock:
            return max((w.window for w in self._watches), default=0)

    def store_append(self, rec: Dict[str, Any]) -> None:
        if self.store is not None:
            self.store.append(rec)

    # -- progress-path driver -------------------------------------------
    def step(self) -> None:
        with self._lock:
            watches = list(self._watches)
        for w in watches:
            w.step()

    # -- timer thread ---------------------------------------------------
    def _timer_loop(self) -> None:
        while not self._stop.wait(KNOBS.interval):
            with self._lock:
                watches = list(self._watches)
            for w in watches:
                w.due += 1

    def stop(self) -> None:
        self._stop.set()


def maybe_create(context) -> Optional[CollectorService]:
    """Context.__init__ hook: a service when UCC_COLLECT is on, else
    None (the zero-cost default — dispatch and progress guard on the
    attribute)."""
    if not ENABLED:
        return None
    return CollectorService(context)
