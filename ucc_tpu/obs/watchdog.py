"""Stall watchdog — turns silent hangs into actionable state dumps.

Motivation: round 5 ended with the chip wedged for the whole round and
`TPU_PROBE_r05.log` all ``hang`` — no way to tell WHICH collective,
task, or peer was stuck. This module hooks the progress queue
(schedule/progress.py): any task IN_PROGRESS past a soft deadline
(``UCC_WATCHDOG_TIMEOUT`` seconds; unset/0 = off, the default) fires a
ONE-SHOT diagnostic dump — every in-flight task with its collective,
algorithm, round/slots, outstanding peers and tags, the progress-queue
depth, and every live team's state-machine position (CL_AGREE dwell is
named explicitly: the advisor-confirmed silent-hang path in
core/team.py) — to the log at ERROR and as a JSON line appended to
``UCC_WATCHDOG_FILE``.

Zero-cost when off: the progress loop guards with ``watchdog.ENABLED``
(a module-level boolean) before calling in, and even when on the scan
itself is throttled to one per ``_SCAN_PERIOD`` seconds.

PR 2 adds the escalation ladder (``UCC_WATCHDOG_ACTION``): ``dump``
(default) only diagnoses; ``cancel`` additionally cancels any task
still IN_PROGRESS past the HARD deadline (``UCC_WATCHDOG_HARD_TIMEOUT``,
default 2x the soft one) with ERR_TIMED_OUT — unwinding its posted
transport ops instead of orphaning them; ``abort`` cancels EVERY
in-flight task once any one crosses the hard deadline, and fails
stalled team creates, converting a wedged process into a bounded
all-errors outcome (the Meta timeout→abort→re-init ladder's middle
rungs; re-init is the caller's move).
"""
from __future__ import annotations

import json
import os
import time
import weakref
from typing import Any, Dict, List, Optional, Set, Tuple

from ..status import Status
from ..utils.log import get_logger

logger = get_logger("obs")

try:
    TIMEOUT: float = float(os.environ.get("UCC_WATCHDOG_TIMEOUT", "0") or 0)
except ValueError:
    TIMEOUT = 0.0
ENABLED: bool = TIMEOUT > 0
_file: str = os.environ.get("UCC_WATCHDOG_FILE", "ucc_watchdog.json")
ACTION: str = os.environ.get("UCC_WATCHDOG_ACTION", "dump").strip().lower()
if ACTION not in ("dump", "cancel", "abort"):
    logger.warning("unknown UCC_WATCHDOG_ACTION %r; using 'dump'", ACTION)
    ACTION = "dump"
try:
    HARD_TIMEOUT: float = float(
        os.environ.get("UCC_WATCHDOG_HARD_TIMEOUT", "0") or 0)
except ValueError:
    HARD_TIMEOUT = 0.0
if HARD_TIMEOUT <= 0:
    HARD_TIMEOUT = 2 * TIMEOUT

_SCAN_PERIOD = 1.0
_last_scan = 0.0
#: one-shot guards: task seq nums / (team id, state) already reported
_fired_tasks: Set[int] = set()
_fired_teams: Set[Tuple[Any, str]] = set()

#: every Team registers here at construction (cheap, not a hot path) so
#: a dump can name state-machine positions even for teams that never
#: reach the progress queue (the team-create hang class)
TEAMS: "weakref.WeakSet" = weakref.WeakSet()


def configure(timeout: float, file: Optional[str] = None,
              action: Optional[str] = None,
              hard_timeout: Optional[float] = None) -> None:
    """Runtime enable/disable (tests and embedders; env read at import)."""
    global TIMEOUT, ENABLED, _file, _last_scan, ACTION, HARD_TIMEOUT
    TIMEOUT = float(timeout)
    ENABLED = TIMEOUT > 0
    if file is not None:
        _file = file
    if action is not None:
        if action not in ("dump", "cancel", "abort"):
            raise ValueError(f"watchdog action must be dump|cancel|abort, "
                             f"got {action!r}")
        ACTION = action
    HARD_TIMEOUT = float(hard_timeout) if hard_timeout is not None \
        else 2 * TIMEOUT
    _last_scan = 0.0


def reset() -> None:
    """Clear one-shot state (tests)."""
    _fired_tasks.clear()
    _fired_teams.clear()


def register_team(team: Any) -> None:
    TEAMS.add(team)


def note_rank_failure(ranks, source: str = "", detail: str = "") -> None:
    """Append a ``rank_failed`` evidence line to the watchdog file
    (called by fault/health on detection). Only when the watchdog is
    armed — CI harnesses (tools/tpu_probe.py, tools/snapshot_gate.py)
    always arm it, and parse this line to classify a run
    ``rank_failed(ranks=...)`` instead of ``hang``/``timeout``."""
    if not ENABLED:
        return
    rec = {"ts": time.time(), "pid": os.getpid(), "reason": "rank_failed",
           "failed_ranks": sorted(int(r) for r in ranks),
           "source": source, "detail": detail}
    try:
        with open(_file, "a") as fh:
            fh.write(json.dumps(rec, default=str) + "\n")
    except OSError:
        logger.exception("watchdog rank-failure note write failed")


def note_integrity(kind: str, ranks, detail: str = "") -> None:
    """Append a data-integrity evidence line (``wire_mismatch`` /
    ``digest_mismatch`` / ``quarantine``) naming the attributed ctx
    ranks — the snapshot-gate/soak classifier reads these to tell
    detected corruption from silent corruption from hangs."""
    if not ENABLED:
        return
    rec = {"ts": time.time(), "pid": os.getpid(), "reason": "integrity",
           "kind": kind, "ranks": sorted(int(r) for r in ranks),
           "detail": detail}
    try:
        with open(_file, "a") as fh:
            fh.write(json.dumps(rec, default=str) + "\n")
    except OSError:
        logger.exception("watchdog integrity note write failed")


# ---------------------------------------------------------------------------
# scan — called from ProgressQueue.progress() under `if watchdog.ENABLED:`
# ---------------------------------------------------------------------------

def check(queue: Any, now: Optional[float] = None) -> bool:
    """Scan one progress queue + the team registry for stalls; fire a
    dump for each newly-detected one. Returns True when a dump fired.

    The scan throttle is PER QUEUE: a process with several contexts
    (in-process multi-rank jobs, the test harness shape) calls check
    from every context's progress loop, and a single global stamp would
    hand the one scan slot per second to whichever queue polls first —
    starving the queue that actually holds the stuck task (found by the
    PR-2 verify drive: escalation needs two scans of the right queue,
    which a 4-context job delivered only every ~8s). The module-level
    ``_last_scan`` survives as a test hook: zeroing it forces the next
    check through regardless of the per-queue stamp."""
    global _last_scan
    if now is None:
        now = time.monotonic()
    last_q = getattr(queue, "_wd_last_scan", 0.0)
    if now - last_q < _SCAN_PERIOD and now - _last_scan < _SCAN_PERIOD:
        return False
    queue._wd_last_scan = now
    _last_scan = now

    stalled: List[Any] = []
    for task in list(getattr(queue, "_q", ())):
        if task.start_time and (now - task.start_time) > TIMEOUT and \
                task.seq_num not in _fired_tasks:
            _fired_tasks.add(task.seq_num)
            stalled.append(task)

    stalled_teams: List[Any] = []
    for team in list(TEAMS):
        state = getattr(team, "state", None)
        if state is None or getattr(state, "name", "") in ("ACTIVE",
                                                           "FAILED"):
            continue
        dwell = now - getattr(team, "state_since", now)
        if dwell > TIMEOUT and (id(team), state.name) not in _fired_teams:
            _fired_teams.add((id(team), state.name))
            stalled_teams.append(team)

    fired = False
    if stalled or stalled_teams:
        dump_state(queue, stalled, stalled_teams, now)
        fired = True
    if ACTION != "dump":
        fired = _escalate(queue, now) or fired
    return fired


def _escalate(queue: Any, now: float) -> bool:
    """The cancel/abort rungs: tasks IN_PROGRESS past HARD_TIMEOUT are
    cancelled (ERR_TIMED_OUT) — under ``abort``, one hard-stalled task
    condemns every in-flight task, since a collective stack with one
    wedged collective rarely has healthy neighbors (they share the
    fabric and usually the team), and stalled team creates are failed
    so ``create_test`` returns instead of spinning forever."""
    q = list(getattr(queue, "_q", ()))
    hard = [t for t in q
            if not t.is_completed() and getattr(t, "start_time", 0)
            and (now - t.start_time) > HARD_TIMEOUT]
    acted = False
    if ACTION == "abort":
        # only the abort rung condemns team creates: an operator who
        # opted into per-task cancel did not opt into failing a
        # legitimately slow large-job bootstrap
        for team in list(TEAMS):
            state = getattr(team, "state", None)
            if state is None or getattr(state, "name", "") in ("ACTIVE",
                                                               "FAILED"):
                continue
            dwell = now - getattr(team, "state_since", now)
            if dwell > HARD_TIMEOUT:
                fail = getattr(team, "fail", None)
                if fail is None:
                    continue
                try:
                    fail(Status.ERR_TIMED_OUT,
                         f"watchdog abort: create stalled {dwell:.1f}s "
                         f"in {state.name}")
                except Exception:  # noqa: BLE001
                    logger.exception("watchdog team fail raised")
                acted = True
    if hard:
        targets = [t for t in q if not t.is_completed()] \
            if ACTION == "abort" else hard
        # failure attribution (UCC_FT=shrink): before cancelling, report
        # each hard-stalled task's outstanding recv peers to the health
        # registry as suspects — a suspect whose heartbeat is also stale
        # is confirmed failed, feeding the shrink pipeline
        reg = getattr(queue, "_ft_health", None)
        if reg is not None:
            for t in hard:
                try:
                    reg.suspect_task_peers(t, now)
                except Exception:  # noqa: BLE001 - attribution best-effort
                    pass
        for t in targets:
            logger.error(
                "WATCHDOG: %s: cancelling task %s seq %s (coll=%s alg=%s) "
                "stuck > %.1fs", ACTION, type(t).__name__,
                getattr(t, "seq_num", "?"), getattr(t, "coll_name", None),
                getattr(t, "alg_name", None), HARD_TIMEOUT)
            cancel = getattr(t, "cancel", None)
            if cancel is None:
                continue
            try:
                cancel(Status.ERR_TIMED_OUT)
            except Exception:  # noqa: BLE001 - escalation must never kill
                logger.exception("watchdog cancel raised")
        acted = True
    return acted


# ---------------------------------------------------------------------------
# the dump
# ---------------------------------------------------------------------------

def _describe_task(task: Any, now: float) -> Dict[str, Any]:
    describe = getattr(task, "obs_describe", None)
    if describe is not None:
        try:
            return describe(now)
        except Exception:  # noqa: BLE001 - diagnostics must never raise
            pass
    return {"task": type(task).__name__,
            "seq": getattr(task, "seq_num", None),
            "status": getattr(getattr(task, "status", None), "name", "?")}


def _describe_team(team: Any, now: float) -> Dict[str, Any]:
    state = getattr(team, "state", None)
    d: Dict[str, Any] = {
        "team_id": getattr(team, "id", None),
        "rank": getattr(team, "rank", None),
        "size": getattr(team, "size", None),
        "state": getattr(state, "name", "?"),
        "dwell_s": round(now - getattr(team, "state_since", now), 3),
    }
    if getattr(state, "name", "") == "CL_AGREE":
        # the known silent-hang path: a peer that failed every CL create
        # and never posted its agreement allgather (core/team.py
        # _cl_agree_step) leaves everyone else parked exactly here
        d["hint"] = ("stuck in CL_AGREE: a peer likely failed CL create "
                     "and never posted the agreement allgather; its "
                     "local CL set is the thing to inspect")
    return d


def _occupancy_section() -> List[Dict[str, Any]]:
    """Mailbox backlog per live endpoint (unexpected-queue length,
    posted recvs, native slot-table in-use) — a backlog is invisible
    until it becomes a stall, so the dump samples it explicitly. Rows
    from the cross-process arena endpoints ride along (parked traffic +
    payload-block pressure per attached arena): block-class exhaustion
    there stalls exactly like a mailbox backlog but lives in another
    process's address space, so it has to be sampled from the shared
    segment."""
    rows: List[Dict[str, Any]] = []
    try:
        from ..tl.host.transport import occupancy_snapshot
        rows.extend(occupancy_snapshot())
    except Exception:  # noqa: BLE001 - diagnostics must never raise
        pass
    try:
        from ..tl.ipc import occupancy_snapshot as ipc_occupancy
        rows.extend(ipc_occupancy())
    except Exception:  # noqa: BLE001 - diagnostics must never raise
        pass
    return rows


def _config_provenance() -> Dict[str, Any]:
    """Resolved configuration in effect — so a pod-scale hang dump
    names the layer configuration without a repro: quant policy, tuner
    decisions (learned score rows), and the resolved hier tree
    (levels/leaders) per live team."""
    cfg: Dict[str, Any] = {
        "quant": {k: v for k, v in os.environ.items()
                  if k.startswith("UCC_QUANT")} or {"UCC_QUANT": "off"},
        "tuner": {"mode": os.environ.get("UCC_TUNER", "off") or "off"},
        "ft": os.environ.get("UCC_FT", "none") or "none",
    }
    teams = []
    for team in list(TEAMS):
        if getattr(getattr(team, "state", None), "name", "") != "ACTIVE":
            continue
        d: Dict[str, Any] = {"team_id": getattr(team, "id", None),
                             "size": getattr(team, "size", None),
                             "epoch": getattr(team, "epoch", 0)}
        try:
            sm = getattr(team, "score_map", None)
            if sm is not None:
                learned = [ln.strip() for ln in
                           sm.print_info("").splitlines()
                           if "learned" in ln]
                if learned:
                    d["tuner_learned"] = learned[:32]
        except Exception:  # noqa: BLE001
            pass
        try:
            for cl in getattr(team, "cl_teams", ()) or ():
                describe = getattr(cl, "describe_topology", None)
                if describe is not None:
                    d.setdefault("hier", {})[getattr(cl, "name", "?")] = \
                        describe().splitlines()
        except Exception:  # noqa: BLE001
            pass
        if len(d) > 3:
            teams.append(d)
    if teams:
        cfg["teams"] = teams
    return cfg


def dump_state(queue: Any, stalled: List[Any], stalled_teams: List[Any],
               now: Optional[float] = None,
               reason: str = "watchdog") -> Dict[str, Any]:
    """Build + emit the diagnostic report (log ERROR + JSON line)."""
    if now is None:
        now = time.monotonic()
    in_flight = [_describe_task(t, now)
                 for t in list(getattr(queue, "_q", ()))]
    report = {
        "ts": time.time(),
        "pid": os.getpid(),
        "reason": reason,
        "timeout_s": TIMEOUT,
        "progress_queue_depth": len(getattr(queue, "_q", ())),
        "stalled_tasks": [_describe_task(t, now) for t in stalled],
        "in_flight_tasks": in_flight,
        "teams": [_describe_team(t, now) for t in list(TEAMS)],
        "stalled_teams": [_describe_team(t, now) for t in stalled_teams],
        "transports": _occupancy_section(),
        "config": _config_provenance(),
    }
    # flight-recorder fold-in: collect every ring this process can see,
    # diagnose (desync / straggler / missing participant), and carry the
    # verdict inside the watchdog report — the dump that previously said
    # "something is stuck" now names the culprit when the rings can
    from . import flight as _flight
    if _flight.ENABLED:
        try:
            from . import diagnose as _diagnose
            merged = _flight.collect_process(None, reason=reason)
            diag = _diagnose.diagnose(merged)
            report["flight_diagnosis"] = diag
            merged["diagnosis"] = diag
            _flight.dump_merged(merged, diagnose=False)
            for line in diag.get("summary", ())[:8]:
                logger.error("WATCHDOG flight diagnosis: %s", line)
        except Exception:  # noqa: BLE001 - diagnostics must never raise
            logger.exception("flight diagnosis failed")
    for t in report["stalled_tasks"]:
        logger.error(
            "WATCHDOG: task stalled > %.1fs: %s", TIMEOUT,
            json.dumps(t, default=str))
    for t in report["stalled_teams"]:
        logger.error(
            "WATCHDOG: team create stalled > %.1fs in %s: %s", TIMEOUT,
            t.get("state"), json.dumps(t, default=str))
    logger.error(
        "WATCHDOG: state dump (%d in-flight, queue depth %d) -> %s",
        len(in_flight), report["progress_queue_depth"], _file)
    try:
        with open(_file, "a") as fh:
            fh.write(json.dumps(report, default=str) + "\n")
    except OSError:
        logger.exception("watchdog dump write failed")
    return report
