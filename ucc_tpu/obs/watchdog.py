"""Stall watchdog — turns silent hangs into actionable state dumps.

Motivation: round 5 ended with the chip wedged for the whole round and
`TPU_PROBE_r05.log` all ``hang`` — no way to tell WHICH collective,
task, or peer was stuck. This module hooks the progress queue
(schedule/progress.py): any task IN_PROGRESS past a soft deadline
(``UCC_WATCHDOG_TIMEOUT`` seconds; unset/0 = off, the default) fires a
ONE-SHOT diagnostic dump — every in-flight task with its collective,
algorithm, round/slots, outstanding peers and tags, the progress-queue
depth, and every live team's state-machine position (CL_AGREE dwell is
named explicitly: the advisor-confirmed silent-hang path in
core/team.py) — to the log at ERROR and as a JSON line appended to
``UCC_WATCHDOG_FILE``.

Zero-cost when off: the progress loop guards with ``watchdog.ENABLED``
(a module-level boolean) before calling in, and even when on the scan
itself is throttled to one per ``_SCAN_PERIOD`` seconds.
"""
from __future__ import annotations

import json
import os
import time
import weakref
from typing import Any, Dict, List, Optional, Set, Tuple

from ..utils.log import get_logger

logger = get_logger("obs")

try:
    TIMEOUT: float = float(os.environ.get("UCC_WATCHDOG_TIMEOUT", "0") or 0)
except ValueError:
    TIMEOUT = 0.0
ENABLED: bool = TIMEOUT > 0
_file: str = os.environ.get("UCC_WATCHDOG_FILE", "ucc_watchdog.json")

_SCAN_PERIOD = 1.0
_last_scan = 0.0
#: one-shot guards: task seq nums / (team id, state) already reported
_fired_tasks: Set[int] = set()
_fired_teams: Set[Tuple[Any, str]] = set()

#: every Team registers here at construction (cheap, not a hot path) so
#: a dump can name state-machine positions even for teams that never
#: reach the progress queue (the team-create hang class)
TEAMS: "weakref.WeakSet" = weakref.WeakSet()


def configure(timeout: float, file: Optional[str] = None) -> None:
    """Runtime enable/disable (tests and embedders; env read at import)."""
    global TIMEOUT, ENABLED, _file, _last_scan
    TIMEOUT = float(timeout)
    ENABLED = TIMEOUT > 0
    if file is not None:
        _file = file
    _last_scan = 0.0


def reset() -> None:
    """Clear one-shot state (tests)."""
    _fired_tasks.clear()
    _fired_teams.clear()


def register_team(team: Any) -> None:
    TEAMS.add(team)


# ---------------------------------------------------------------------------
# scan — called from ProgressQueue.progress() under `if watchdog.ENABLED:`
# ---------------------------------------------------------------------------

def check(queue: Any, now: Optional[float] = None) -> bool:
    """Scan one progress queue + the team registry for stalls; fire a
    dump for each newly-detected one. Returns True when a dump fired."""
    global _last_scan
    if now is None:
        now = time.monotonic()
    if now - _last_scan < _SCAN_PERIOD:
        return False
    _last_scan = now

    stalled: List[Any] = []
    for task in list(getattr(queue, "_q", ())):
        if task.start_time and (now - task.start_time) > TIMEOUT and \
                task.seq_num not in _fired_tasks:
            _fired_tasks.add(task.seq_num)
            stalled.append(task)

    stalled_teams: List[Any] = []
    for team in list(TEAMS):
        state = getattr(team, "state", None)
        if state is None or getattr(state, "name", "") in ("ACTIVE",
                                                           "FAILED"):
            continue
        dwell = now - getattr(team, "state_since", now)
        if dwell > TIMEOUT and (id(team), state.name) not in _fired_teams:
            _fired_teams.add((id(team), state.name))
            stalled_teams.append(team)

    if not stalled and not stalled_teams:
        return False
    dump_state(queue, stalled, stalled_teams, now)
    return True


# ---------------------------------------------------------------------------
# the dump
# ---------------------------------------------------------------------------

def _describe_task(task: Any, now: float) -> Dict[str, Any]:
    describe = getattr(task, "obs_describe", None)
    if describe is not None:
        try:
            return describe(now)
        except Exception:  # noqa: BLE001 - diagnostics must never raise
            pass
    return {"task": type(task).__name__,
            "seq": getattr(task, "seq_num", None),
            "status": getattr(getattr(task, "status", None), "name", "?")}


def _describe_team(team: Any, now: float) -> Dict[str, Any]:
    state = getattr(team, "state", None)
    d: Dict[str, Any] = {
        "team_id": getattr(team, "id", None),
        "rank": getattr(team, "rank", None),
        "size": getattr(team, "size", None),
        "state": getattr(state, "name", "?"),
        "dwell_s": round(now - getattr(team, "state_since", now), 3),
    }
    if getattr(state, "name", "") == "CL_AGREE":
        # the known silent-hang path: a peer that failed every CL create
        # and never posted its agreement allgather (core/team.py
        # _cl_agree_step) leaves everyone else parked exactly here
        d["hint"] = ("stuck in CL_AGREE: a peer likely failed CL create "
                     "and never posted the agreement allgather; its "
                     "local CL set is the thing to inspect")
    return d


def dump_state(queue: Any, stalled: List[Any], stalled_teams: List[Any],
               now: Optional[float] = None,
               reason: str = "watchdog") -> Dict[str, Any]:
    """Build + emit the diagnostic report (log ERROR + JSON line)."""
    if now is None:
        now = time.monotonic()
    in_flight = [_describe_task(t, now)
                 for t in list(getattr(queue, "_q", ()))]
    report = {
        "ts": time.time(),
        "pid": os.getpid(),
        "reason": reason,
        "timeout_s": TIMEOUT,
        "progress_queue_depth": len(getattr(queue, "_q", ())),
        "stalled_tasks": [_describe_task(t, now) for t in stalled],
        "in_flight_tasks": in_flight,
        "teams": [_describe_team(t, now) for t in list(TEAMS)],
        "stalled_teams": [_describe_team(t, now) for t in stalled_teams],
    }
    for t in report["stalled_tasks"]:
        logger.error(
            "WATCHDOG: task stalled > %.1fs: %s", TIMEOUT,
            json.dumps(t, default=str))
    for t in report["stalled_teams"]:
        logger.error(
            "WATCHDOG: team create stalled > %.1fs in %s: %s", TIMEOUT,
            t.get("state"), json.dumps(t, default=str))
    logger.error(
        "WATCHDOG: state dump (%d in-flight, queue depth %d) -> %s",
        len(in_flight), report["progress_queue_depth"], _file)
    try:
        with open(_file, "a") as fh:
            fh.write(json.dumps(report, default=str) + "\n")
    except OSError:
        logger.exception("watchdog dump write failed")
    return report
