"""Cluster flight recorder — always-on collective event rings.

The operability tool Meta's "Collective Communication for 100k+ GPUs"
(PAPERS.md) names as load-bearing at scale: every rank keeps a small,
fixed-size ring of compact collective lifecycle events (post / start /
round / complete / cancel / fence, with team key + epoch, collective,
algorithm, message size and monotonic timestamps), cheap enough to leave
on in production (``UCC_FLIGHT=y`` is the default; ``UCC_FLIGHT=n``
removes every append). When something goes wrong — a watchdog hard
escalation, a rank-failure detection, an operator ``SIGUSR2``, or the
``ucc_fr`` CLI — the rings are collected across ranks into one merged
dump that ``obs/diagnose.py`` turns into an answer: *which rank posted a
mismatched collective, which rank is the straggler, what was in flight
when rank 7 died.*

Design notes:

- **Rings are preallocated, allocation-free, and wait-free.** Events
  live in fixed-size typed columns (``array('d')``/``array('q')``), with
  strings and team keys interned to small integers — an append is a
  handful of unboxed scalar stores, allocating NOTHING. This matters
  beyond raw speed: an always-on recorder that allocated a tuple per
  event would feed CPython's generational GC a constant stream of
  surviving young objects (each ring slot keeps them alive), and the
  promotion pressure measurably taxes every collection of a large
  process — the A/B on the 8K allreduce point showed ~7% from exactly
  that, collapsing under raised GC thresholds. Column stores never
  enter the GC at all. Depth is rounded to a power of two so the wrap
  is a mask. Concurrent appends (ThreadMode MULTIPLE) may very rarely
  tear one slot's fields across two events — a corrupt event the
  diagnosis tolerates, the classic flight-recorder trade, never a lock
  on the hot path.
- **Binding follows the PR-3 ``_instr`` pattern**: producers cache a ring
  reference once (the transport endpoint at construction, the
  CollRequest at init), so the steady-state cost is one attribute test
  when off and one append when on.
- **Two rings per rank.** The *coll* ring holds collective lifecycle
  events; the *wire* ring holds per-message round events (send kind
  transitions: direct/eager/rndv/fenced — including the native matcher's,
  which routes through the same transport counter). Message storms
  therefore cannot evict the lifecycle history diagnosis needs.
- **Collection degrades gracefully.** ``collect_process`` merges every
  ring registered in this process (the in-process multi-rank shape;
  watchdog and rank-failure triggers use it because peers cannot be
  assumed to cooperate mid-hang). ``collect_team`` is the cooperative
  cross-rank gather over the service-team transport, reusing the PR-8
  k-ary ``TransportOob`` tree among ranks believed alive — known-dead
  ranks are excluded up front and NAMED in ``absent_ranks`` instead of
  wedging the gather.
"""
from __future__ import annotations

import json
import os
import signal
import threading
import time
import weakref
from array import array
from typing import Any, Dict, List, Optional

from ..status import Status
from ..utils.config import (ConfigField, ConfigTable, parse_bool,
                            parse_string, parse_uint, register_table)
from ..utils.log import get_logger

logger = get_logger("obs")

_FLIGHT_CONFIG = register_table(ConfigTable(
    prefix="", name="obs/flight", fields=[
        ConfigField("FLIGHT", "y",
                    "always-on flight recorder: per-rank ring of compact "
                    "collective lifecycle events (post/start/round/"
                    "complete/cancel/fence). Collected across ranks and "
                    "diagnosed on watchdog escalation, rank failure, "
                    "SIGUSR2, or via the ucc_fr CLI. n removes every "
                    "ring append", parse_string),
        ConfigField("FLIGHT_DEPTH", "2048",
                    "events kept per ring (rounded up to a power of "
                    "two); each rank keeps one collective-lifecycle ring "
                    "and one wire ring of this depth", parse_uint),
        ConfigField("FLIGHT_FILE", "ucc_flight.json",
                    "flight-dump destination: one JSON line per local "
                    "ring dump or merged cross-rank collection; read "
                    "with `ucc_fr <file>`", parse_string),
    ]))


def _resolve_knobs():
    from ..utils.config import Config
    try:
        cfg = Config(_FLIGHT_CONFIG)
        try:
            enabled = parse_bool(str(cfg.flight))
        except ValueError:
            enabled = True
        depth = int(cfg.flight_depth) or 2048
        return enabled, depth, str(cfg.flight_file)
    except Exception:  # noqa: BLE001 - knob resolution must never break import
        return True, 2048, "ucc_flight.json"


ENABLED, _DEPTH, _file = _resolve_knobs()

#: schema version stamped into every dump (ucc_fr refuses records it
#: does not understand instead of mis-diagnosing them)
DUMP_VERSION = 1

# event kinds (coll ring)
EV_POST = "post"
EV_START = "start"
EV_COMPLETE = "cmpl"
EV_CANCEL = "cancel"
EV_FENCE = "fence"
# wire-ring kind codes (send transitions, transport.py _count_send,
# plus the device-collective lifecycle pair: "dev_launch" = the
# rendezvous dispatched the compiled program, "dev_ready" = device
# completion observed — XLA/ring_dma collectives previously had no
# wire-round visibility, so ucc_fr could not attribute device-side
# stragglers; the per-rank launch timestamps share a (team, tag, slot)
# key across ranks, which is exactly what the wire-lag signal joins on)
WIRE_KINDS = ("direct", "eager", "rndv", "fenced", "dev_launch",
              "dev_ready")


def _pow2(n: int) -> int:
    n = max(16, int(n))
    return 1 << (n - 1).bit_length()


class _Interner:
    """Hashable object -> small int, with reverse lookup for decode.
    Code 0 is reserved for None/empty. Growth is bounded by the label
    vocabulary (coll/alg/stage/status names, team keys, service tags)."""

    __slots__ = ("ids", "objs")

    def __init__(self):
        self.ids: Dict[Any, int] = {None: 0, "": 0}
        self.objs: List[Any] = [None]

    def code(self, obj) -> int:
        i = self.ids.get(obj)
        if i is None:
            i = self.ids[obj] = len(self.objs)
            self.objs.append(obj)
        return i

    def obj(self, i: int):
        return self.objs[i] if 0 <= i < len(self.objs) else None


_EV_CODES = {EV_POST: 1, EV_START: 2, EV_COMPLETE: 3, EV_CANCEL: 4,
             EV_FENCE: 5}
_EV_NAMES = {v: k for k, v in _EV_CODES.items()}
_WIRE_CODES = {k: i for i, k in enumerate(WIRE_KINDS)}


class CollRing:
    """Collective-lifecycle ring: fixed typed columns, allocation-free
    appends (see module doc). ``append`` takes pre-coded ints only."""

    __slots__ = ("idx", "mask", "ts", "ev", "team", "epoch", "fseq",
                 "seq", "coll", "alg", "stage", "auxf", "auxi", "strs")

    def __init__(self, depth: int, strs: _Interner):
        d = _pow2(depth)
        self.mask = d - 1
        self.idx = 0
        self.ts = array("d", bytes(8 * d))
        self.auxf = array("d", bytes(8 * d))
        for name in ("ev", "team", "epoch", "fseq", "seq", "coll", "alg",
                     "stage", "auxi"):
            setattr(self, name, array("q", bytes(8 * d)))
        self.strs = strs

    def append(self, ev: int, team: int, epoch: int, fseq: int, seq: int,
               coll: int, alg: int, stage: int, auxf: float,
               auxi: int) -> None:
        i = self.idx & self.mask
        self.ts[i] = time.monotonic()
        self.ev[i] = ev
        self.team[i] = team
        self.epoch[i] = epoch
        self.fseq[i] = fseq
        self.seq[i] = seq
        self.coll[i] = coll
        self.alg[i] = alg
        self.stage[i] = stage
        self.auxf[i] = auxf
        self.auxi[i] = auxi
        self.idx += 1

    @property
    def dropped(self) -> int:
        return max(0, self.idx - self.mask - 1)

    def events(self) -> List[Dict[str, Any]]:
        """JSON-safe decode, oldest-first (cold: collection/dump only)."""
        n = min(self.idx, self.mask + 1)
        first = (self.idx - n) & self.mask
        strs = self.strs
        out = []
        for j in range(n):
            i = (first + j) & self.mask
            evc = self.ev[i]
            ev = _EV_NAMES.get(evc)
            if ev is None:
                continue
            team = self.team[i]
            seq = self.seq[i]
            d: Dict[str, Any] = {
                "t": self.ts[i], "ev": ev,
                "team": (strs.obj(-team - 2) if team <= -2 else
                         (None if team == -1 else team)),
                "epoch": self.epoch[i],
                "seq": None if seq == -1 else seq,
            }
            if self.fseq[i] != -1:
                d["fseq"] = self.fseq[i]
            coll = strs.obj(self.coll[i])
            alg = strs.obj(self.alg[i])
            stage = strs.obj(self.stage[i])
            if coll:
                d["coll"] = coll
            if alg:
                d["alg"] = alg
            if stage:
                d["stage"] = stage
            if evc == 1:                       # post
                d["size"] = self.auxi[i]
            elif evc == 3:                     # cmpl
                d["dur_s"] = self.auxf[i]
                d["status"] = strs.obj(self.auxi[i]) or "?"
            elif evc == 4:                     # cancel
                d["status"] = strs.obj(self.auxi[i]) or "?"
            elif evc == 5:                     # fence
                d["purged"] = self.auxi[i]
            elif self.auxi[i] != -1:           # start: tag
                d["tag"] = self.auxi[i]
            out.append(d)
        return out


class WireRing:
    """Per-message round ring (send kind transitions). Same typed-column
    discipline; the team key and any non-int tag are interned."""

    __slots__ = ("idx", "mask", "ts", "kind", "tkey", "epoch", "tag",
                 "slot", "nbytes", "objs")

    def __init__(self, depth: int, objs: _Interner):
        d = _pow2(depth)
        self.mask = d - 1
        self.idx = 0
        self.ts = array("d", bytes(8 * d))
        for name in ("kind", "tkey", "epoch", "tag", "slot", "nbytes"):
            setattr(self, name, array("q", bytes(8 * d)))
        self.objs = objs

    def append(self, kind: str, key, nbytes: int) -> None:
        """One round event. *key* is the transport TagKey
        (team_key, epoch, coll_tag, slot, src)."""
        i = self.idx & self.mask
        self.ts[i] = time.monotonic()
        self.kind[i] = _WIRE_CODES.get(kind, 3)
        self.tkey[i] = self.objs.code(key[0])
        self.epoch[i] = key[1]
        tag = key[2]
        # int tags stored as-is (>= 0); tuple tags (service/active-set
        # spaces) interned into the negative range
        self.tag[i] = tag if type(tag) is int \
            else -(self.objs.code(tag) + 1)
        self.slot[i] = key[3]
        self.nbytes[i] = nbytes
        self.idx += 1

    @property
    def dropped(self) -> int:
        return max(0, self.idx - self.mask - 1)

    def events(self) -> List[Dict[str, Any]]:
        n = min(self.idx, self.mask + 1)
        first = (self.idx - n) & self.mask
        objs = self.objs
        out = []
        for j in range(n):
            i = (first + j) & self.mask
            tag = self.tag[i]
            k = self.kind[i]
            out.append({
                "t": self.ts[i], "ev": "snd",
                "kind": WIRE_KINDS[k] if 0 <= k < len(WIRE_KINDS)
                else "?",
                "tkey": _keystr(objs.obj(self.tkey[i])),
                "epoch": self.epoch[i],
                "tag": tag if tag >= 0 else str(objs.obj(-tag - 1)),
                "slot": self.slot[i], "nbytes": self.nbytes[i],
            })
        return out


class FlightRecorder:
    """Per-context (per-rank) pair of rings plus identity. Attached as
    ``context.flight``; registered process-wide so in-process collection
    can reach every rank's ring."""

    __slots__ = ("coll", "wire", "rank", "uid", "pid", "t0", "_strs",
                 "__weakref__")

    def __init__(self, rank: int, uid: str, depth: Optional[int] = None):
        d = depth if depth is not None else _DEPTH
        self._strs = _Interner()
        self.coll = CollRing(d, self._strs)
        self.wire = WireRing(d, self._strs)
        self.rank = int(rank)
        self.uid = uid
        self.pid = os.getpid()
        self.t0 = time.monotonic()

    # ------------------------------------------------------------------
    # recording helpers (hot-ish: one call per collective lifecycle step;
    # producers that run per message append to self.wire directly)
    def post(self, team_id, epoch: int, fseq: int, seq: int, coll: str,
             alg: str, msgsize: int) -> None:
        s = self._strs
        self.coll.append(1, team_id if team_id is not None else -1,
                         epoch, fseq, seq, s.code(coll), s.code(alg), 0,
                         0.0, msgsize)

    def start(self, team_id, epoch: int, seq: int, coll, alg,
              stage, tag) -> None:
        s = self._strs
        self.coll.append(2, team_id if team_id is not None else -1,
                         epoch, -1, seq, s.code(coll), s.code(alg),
                         s.code(stage), 0.0,
                         tag if type(tag) is int else -1)

    def complete(self, team_id, epoch: int, seq: int, coll, alg, stage,
                 dur_s: float, status: str) -> None:
        s = self._strs
        self.coll.append(3, team_id if team_id is not None else -1,
                         epoch, -1, seq, s.code(coll), s.code(alg),
                         s.code(stage), dur_s, s.code(status))

    def cancel(self, team_id, epoch: int, seq: int, coll, alg,
               status: str) -> None:
        s = self._strs
        self.coll.append(4, team_id if team_id is not None else -1,
                         epoch, -1, seq, s.code(coll), s.code(alg), 0,
                         0.0, s.code(status))

    def fence(self, team_key, min_epoch: int, purged: int) -> None:
        # the fenced tag space is a team KEY, not a team id: interned
        # and stored in the negative id range of the team column
        code = self._strs.code(_keystr(team_key))
        self.coll.append(5, -code - 2, min_epoch, -1, -1, 0, 0, 0,
                         0.0, purged)

    def membership(self, team_id, epoch: int, kind: str,
                   detail: str) -> None:
        """Membership-change marker (shrink / grow / join): rides the
        coll ring as a completed ``membership`` event, so a merged trace
        shows each epoch boundary inline with the collectives it fences
        — including on a JOINER whose ring has no pre-change history."""
        self.complete(team_id, epoch, -1, "membership", kind, detail,
                      0.0, "OK")

    # ------------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """JSON-safe decode of both rings (cold path)."""
        return {
            "rank": self.rank,
            "uid": self.uid,
            "pid": self.pid,
            "t0": self.t0,
            "dropped": self.coll.dropped + self.wire.dropped,
            "events": self.coll.events(),
            "wire": self.wire.events(),
        }


def _keystr(k) -> str:
    return k if isinstance(k, str) else repr(k)


# ---------------------------------------------------------------------------
# process registry
# ---------------------------------------------------------------------------

#: context uid -> FlightRecorder. Weak: a recorder lives exactly as long
#: as its context (tests create hundreds of contexts per process).
_RECORDERS: "weakref.WeakValueDictionary[str, FlightRecorder]" = \
    weakref.WeakValueDictionary()
_REG_LOCK = threading.Lock()


def register_context(context) -> Optional[FlightRecorder]:
    """Create + register this context's recorder (``Context.__init__``).
    Returns None when the recorder is disabled — callers keep a None
    ``context.flight`` and every producer's one-branch guard stays
    false."""
    if not ENABLED:
        return None
    rec = FlightRecorder(getattr(context, "rank", 0),
                         getattr(context, "_ctx_uid", ""))
    with _REG_LOCK:
        _RECORDERS[rec.uid] = rec
    return rec


def recorders() -> List[FlightRecorder]:
    with _REG_LOCK:
        return list(_RECORDERS.values())


def configure(enabled: Optional[bool] = None, depth: Optional[int] = None,
              file: Optional[str] = None) -> None:
    """Runtime (re)configuration (tests/embedders; env read at import).
    Existing recorders keep their rings; *depth* applies to recorders
    created afterwards."""
    global ENABLED, _DEPTH, _file
    if enabled is not None:
        ENABLED = bool(enabled)
    if depth is not None:
        _DEPTH = int(depth)
    if file is not None:
        _file = file


# ---------------------------------------------------------------------------
# collection
# ---------------------------------------------------------------------------

def _merged_skeleton(reason: str) -> Dict[str, Any]:
    return {"version": DUMP_VERSION, "kind": "flight_merged",
            "reason": reason, "ts": time.time(), "pid": os.getpid(),
            "ranks": {}, "absent_ranks": []}


def collect_process(context=None, reason: str = "explicit"
                    ) -> Dict[str, Any]:
    """Merge every ring reachable INSIDE this process. With *context*,
    scope to that context's job (peers resolved through the context OOB
    address storage — uid per rank); without, merge every registered
    recorder. This is the trigger-side collection: watchdog escalation
    and rank-failure detection cannot assume remote ranks will
    cooperate, so they take what the process can see and name the rest
    absent."""
    merged = _merged_skeleton(reason)
    with _REG_LOCK:
        by_uid = dict(_RECORDERS)
    if context is not None and getattr(context, "addr_storage", None):
        for r, entry in enumerate(context.addr_storage):
            uid = entry.get("uid", "") if isinstance(entry, dict) else ""
            rec = by_uid.get(uid)
            if rec is None and r == context.rank:
                # no-OOB contexts don't exchange uids; our own ring is
                # reachable directly
                rec = getattr(context, "flight", None)
            if rec is not None:
                merged["ranks"][str(r)] = rec.snapshot()
            else:
                merged["absent_ranks"].append(r)
    else:
        for rec in by_uid.values():
            merged["ranks"].setdefault(str(rec.rank), rec.snapshot())
    return merged


class FlightCollection:
    """Nonblocking cross-rank ring gather over a team's service-team
    transport (the PR-8 k-ary ``TransportOob`` tree), among the members
    believed ALIVE — ranks known dead (health registry, fault-injection
    kills) are excluded from the exchange and listed in the result's
    ``absent_ranks``, so collection past a killed rank yields a partial
    dump instead of a hang. Every surviving member must drive ``test()``
    (the TransportOob polling contract). ``result`` is the merged dump,
    identical on every member."""

    def __init__(self, team, reason: str = "explicit",
                 timeout: float = 30.0):
        from ..core.oob import TransportOob
        from ..fault import inject as fault
        self.team = team
        self.reason = reason
        self.status = Status.IN_PROGRESS
        self.result: Optional[Dict[str, Any]] = None
        self._timeout = timeout
        self._deadline = time.monotonic() + timeout
        ctx = team.context
        svc = team.service_team
        if svc is None or getattr(svc, "transport", None) is None:
            # no transport-backed service team (size-1 / facade teams):
            # local-only "collection" — still carries this rank's ring
            rec = getattr(ctx, "flight", None)
            self._req = None
            self._members = [team.rank]
            self._dead = []
            self._local_snap = rec.snapshot() if rec is not None else None
            return
        dead_ctx = set()
        reg = getattr(ctx, "health", None)
        if reg is not None:
            dead_ctx |= reg.dead_set()
        if fault.ENABLED:
            dead_ctx |= {r for r in fault.SPEC.kill}
        members, dead = [], []
        for tr in range(team.size):
            cr = int(team.ctx_map.eval(tr))
            (dead if cr in dead_ctx else members).append(tr)
        self._members = members
        self._dead = dead
        seq = getattr(team, "_flight_collect_seq", 0)
        team._flight_collect_seq = seq + 1
        member_ctx = [int(team.ctx_map.eval(r)) for r in members]
        # kept for the wait loop: a member that dies MID-collection shows
        # up as fresh health/fault evidence against these ctx ranks
        self._member_ctx = member_ctx
        self._dead_ctx0 = set(dead_ctx)
        oob = TransportOob(svc.comp_context, svc.transport, member_ctx,
                           ctx.rank, ("flight", team.team_key, seq),
                           team.epoch)
        import pickle
        rec = getattr(ctx, "flight", None)
        snap = rec.snapshot() if rec is not None else {
            "rank": ctx.rank, "uid": "", "pid": os.getpid(),
            "events": [], "wire": [], "dropped": 0}
        self._req = oob.allgather(pickle.dumps(snap))
        self._local_snap = None

    def test(self) -> Status:
        if self.status != Status.IN_PROGRESS:
            return self.status
        if self._req is None:
            self._finish([self._local_snap]
                         if self._local_snap is not None else None)
            return self.status
        try:
            st = self._req.test()
        except Exception as e:  # noqa: BLE001 - a torn-down transport mid-
            # collection degrades to a partial local view, never a raise
            logger.warning("flight collection exchange failed: %s", e)
            self._finish(None)
            return self.status
        if st == Status.IN_PROGRESS:
            died = self._died_mid_collection()
            if died:
                logger.warning(
                    "flight collection (%s): member rank(s) %s died "
                    "mid-collection; returning the partial dump now",
                    self.reason, ",".join(str(r) for r in died))
                self._finish(None, dead_now=died)
                return self.status
            if time.monotonic() > self._deadline:
                logger.warning(
                    "flight collection (%s) timed out after %.1fs; "
                    "degrading to the in-process view", self.reason,
                    self._timeout)
                self._finish(None)
            return self.status
        import pickle
        self._finish([pickle.loads(b) for b in self._req.result])
        return self.status

    def _died_mid_collection(self) -> List[int]:
        """Team ranks among the exchange members with FRESH death
        evidence (health registry / fault kills) that arrived after the
        exchange started. The up-front exclusion in ``__init__`` only
        sees deaths known at post time; without this check a rank dying
        mid-collection degrades the whole dump via the full deadline."""
        from ..fault import inject as fault
        ctx = self.team.context
        dead_ctx = set()
        reg = getattr(ctx, "health", None)
        if reg is not None:
            dead_ctx |= reg.dead_set()
        if fault.ENABLED:
            dead_ctx |= {r for r in fault.SPEC.kill}
        fresh = dead_ctx - self._dead_ctx0 - {ctx.rank}
        if not fresh:
            return []
        return sorted(tr for tr, cr in zip(self._members,
                                           self._member_ctx)
                      if cr in fresh)

    def _finish(self, snaps, dead_now: Optional[List[int]] = None
                ) -> None:
        team = self.team
        merged = _merged_skeleton(self.reason)
        if snaps is None:
            # timeout/failure/mid-death fallback: whatever this process
            # can see
            proc = collect_process(team.context, self.reason)
            merged["ranks"] = proc["ranks"]
            merged["partial"] = True
            present = {int(r) for r in merged["ranks"]}
            merged["absent_ranks"] = sorted(
                (set(range(team.size)) - present) | set(dead_now or ()))
            if dead_now:
                merged["mid_collection_dead"] = sorted(dead_now)
        else:
            for tr, snap in zip(self._members, snaps):
                merged["ranks"][str(tr)] = snap
            merged["absent_ranks"] = sorted(self._dead)
            if self._dead:
                merged["partial"] = True
        merged["team"] = getattr(team, "id", None)
        merged["team_size"] = getattr(team, "size", None)
        self.result = merged
        self.status = Status.OK


def collect_team_post(team, reason: str = "explicit",
                      timeout: float = 30.0) -> FlightCollection:
    """Post a cooperative cross-rank collection (every surviving member
    of *team* must call this in the same program order and poll
    ``test()`` while progressing its context)."""
    return FlightCollection(team, reason, timeout)


def collect_team(team, reason: str = "explicit",
                 timeout: float = 30.0) -> Dict[str, Any]:
    """Blocking convenience over :func:`collect_team_post` — usable when
    the other members progress concurrently (threads/processes)."""
    req = collect_team_post(team, reason, timeout)
    while req.test() == Status.IN_PROGRESS:
        team.context.progress()
        time.sleep(0)
    assert req.result is not None
    return req.result


# ---------------------------------------------------------------------------
# dumps
# ---------------------------------------------------------------------------

_dump_lock = threading.Lock()


def dump_merged(merged: Dict[str, Any], path: Optional[str] = None,
                diagnose: bool = True) -> str:
    """Append one merged dump (with its diagnosis folded in) as a JSON
    line; returns the path written."""
    path = path or _file
    if diagnose and "diagnosis" not in merged:
        try:
            from . import diagnose as _dz
            merged["diagnosis"] = _dz.diagnose(merged)
        except Exception:  # noqa: BLE001 - diagnostics must never raise
            logger.exception("flight diagnosis failed; dumping raw")
    try:
        with _dump_lock, open(path, "a") as fh:
            fh.write(json.dumps(merged, default=str) + "\n")
    except OSError:
        logger.exception("flight dump write failed")
    return path


def dump_local(recorder: FlightRecorder, reason: str = "explicit",
               path: Optional[str] = None) -> str:
    """Append one rank's ring snapshot as a JSON line (the per-rank
    building block ``ucc_fr`` merges offline)."""
    path = path or _file
    rec = {"version": DUMP_VERSION, "kind": "flight_local",
           "reason": reason, "ts": time.time()}
    rec.update(recorder.snapshot())
    try:
        with _dump_lock, open(path, "a") as fh:
            fh.write(json.dumps(rec, default=str) + "\n")
    except OSError:
        logger.exception("flight dump write failed")
    return path


def dump_all_local(reason: str = "explicit",
                   path: Optional[str] = None) -> int:
    """Dump every recorder registered in this process (SIGUSR2 path);
    returns the number written."""
    n = 0
    for rec in recorders():
        dump_local(rec, reason, path)
        n += 1
    return n


# ---------------------------------------------------------------------------
# triggers: rank failure + SIGUSR2 (watchdog escalation calls
# collect_process itself so the diagnosis lands inside its report)
# ---------------------------------------------------------------------------

def on_rank_failure(ctx_rank: int, source: str = "",
                    detail: str = "") -> None:
    """Rank-failure trigger (fault/health): collect what this process
    can see, diagnose, and dump with the failed rank named — the
    "what was in flight when rank N died" record. One shot per rank."""
    if not ENABLED:
        return
    noted = _failure_noted
    if ctx_rank in noted:
        return
    noted.add(ctx_rank)
    try:
        merged = collect_process(None, reason="rank_failed")
        merged["failed_rank"] = int(ctx_rank)
        merged["source"] = source
        if detail:
            merged["detail"] = detail
        dump_merged(merged)
    except Exception:  # noqa: BLE001 - diagnostics must never raise
        logger.exception("flight rank-failure dump failed")


_failure_noted: set = set()


def on_integrity(kind: str, ctx_rank: int, detail: str = "") -> None:
    """Data-integrity trigger (integrity subsystem): record the event in
    every ring this process can see (the merged dump then shows the
    corruption inline with the collectives around it), and on
    ``quarantine`` also dump — the "what was in flight when rank N was
    quarantined" record, one shot per rank like the failure path."""
    if not ENABLED:
        return
    for rec in recorders():
        rec.complete(-1, -1, -1, "integrity", kind,
                     f"ctx_rank={ctx_rank}", 0.0, "ERR_DATA_CORRUPTED")
    if kind != "quarantine" or ctx_rank in _integrity_noted:
        return
    _integrity_noted.add(ctx_rank)
    try:
        merged = collect_process(None, reason="quarantine")
        merged["quarantined_rank"] = int(ctx_rank)
        if detail:
            merged["detail"] = detail
        dump_merged(merged)
    except Exception:  # noqa: BLE001 - diagnostics must never raise
        logger.exception("flight quarantine dump failed")


_integrity_noted: set = set()


def reset() -> None:
    """Clear trigger one-shots (tests)."""
    _failure_noted.clear()
    _integrity_noted.clear()


_prev_sigusr2 = None
_signal_armed = False


def _sigusr2(signum, frame) -> None:
    # same no-inline-dump rule as obs.metrics: a short-lived thread waits
    # its turn instead of deadlocking a lock the main thread holds
    if ENABLED:
        threading.Thread(target=dump_all_local,
                         kwargs={"reason": "SIGUSR2"}, daemon=True,
                         name="ucc-flight-sigusr2").start()
    prev = _prev_sigusr2
    if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
        prev(signum, frame)


def _arm_signal() -> None:
    """Chain onto SIGUSR2 WITHOUT unseating an earlier handler (the
    metrics registry arms the same signal)."""
    global _prev_sigusr2, _signal_armed
    if _signal_armed:
        return
    try:
        _prev_sigusr2 = signal.getsignal(signal.SIGUSR2)
        signal.signal(signal.SIGUSR2, _sigusr2)
        _signal_armed = True
    except (ValueError, OSError):
        pass   # off-main-thread import: lose the signal, keep the rings


if ENABLED:
    _arm_signal()
