"""The injector — probabilistic drop/delay/error/rank-kill, seeded.

Two injection boundaries, chosen to be the two places where every host
collective necessarily passes:

- **transport boundary** (tl/host/task.py ``send_nb``/``recv_nb``):
  ``send_action()`` may drop the message (returning a pre-completed
  request so the sender proceeds while the receiver starves — the
  classic lost-packet hang the cancellation layer must bound), delay
  its delivery (the real send fires from ``progress()`` once the due
  time passes), or fail the post outright. ``recv_action()`` only
  errors (a recv is a local op; losing it is the same as dropping the
  matching send).
- **task boundary** (schedule/task.py ``CollTask.post``):
  ``post_inject()`` may fail a task before it touches the wire — the
  exact shape of failure the runtime score-map fallback can retry —
  and simulates killed ranks by failing every post on them.

Determinism: one ``random.Random(UCC_FAULT_SEED)`` drives every
decision, so a failing soak iteration replays bit-identically under the
same seed and spec. All of this is COLD unless ``UCC_FAULT`` is set:
call sites guard with ``if inject.ENABLED:`` (module-level boolean,
same zero-cost pattern as ``obs.metrics`` / ``obs.watchdog``).
"""
from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Set, Tuple

from ..status import Status


@dataclass
class FaultSpec:
    """Parsed ``UCC_FAULT`` spec."""

    drop: float = 0.0          # P(send dropped)
    delay: float = 0.0         # P(send delayed)
    delay_s: float = 0.0       # delay duration
    delay_rank: Optional[int] = None   # pin delays to one ctx rank
    error: float = 0.0         # P(send/recv post fails)
    post_error: float = 0.0    # P(task post fails before wire traffic)
    kill: Set[int] = field(default_factory=set)   # dead ctx ranks
    corrupt: float = 0.0       # P(send payload bit-flipped in flight)
    corrupt_rank: Optional[int] = None  # pin corruption to one ctx rank

    @property
    def active(self) -> bool:
        return bool(self.drop or self.delay or self.error
                    or self.post_error or self.kill or self.corrupt)


def parse_spec(s: str) -> FaultSpec:
    """Parse ``drop=P,delay=P:S,delay_rank=R,error=P,post_error=P,
    kill=R[+R..],corrupt=P,corrupt_rank=R``. ``delay_rank`` pins send
    delays to one ctx rank — the controlled-straggler drill the
    flight-recorder diagnosis smoke uses (a known culprit the diagnosis
    must name); ``corrupt_rank`` likewise pins payload bit-flips to one
    sender (the controlled-corruptor drill). Unknown keys raise:
    a typo'd fault drill that silently injects nothing would report a
    no-hang pass it never earned."""
    spec = FaultSpec()
    s = (s or "").strip()
    if not s or s.lower() in ("n", "no", "off", "0"):
        return spec
    for tok in s.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if "=" not in tok:
            raise ValueError(f"invalid UCC_FAULT token '{tok}'")
        k, v = tok.split("=", 1)
        k = k.strip().lower()
        if k == "drop":
            spec.drop = float(v)
        elif k == "delay":
            if ":" in v:
                p, d = v.split(":", 1)
                spec.delay, spec.delay_s = float(p), float(d)
            else:
                spec.delay, spec.delay_s = float(v), 0.001
        elif k == "delay_rank":
            spec.delay_rank = int(v)
        elif k == "error":
            spec.error = float(v)
        elif k == "post_error":
            spec.post_error = float(v)
        elif k == "kill":
            spec.kill = {int(r) for r in v.split("+") if r.strip() != ""}
        elif k == "corrupt":
            spec.corrupt = float(v)
        elif k == "corrupt_rank":
            spec.corrupt_rank = int(v)
        else:
            raise ValueError(f"unknown UCC_FAULT key '{k}'")
    for p in (spec.drop, spec.delay, spec.error, spec.post_error,
              spec.corrupt):
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"UCC_FAULT probability {p} out of [0,1]")
    return spec


# ---------------------------------------------------------------------------
# module state (env-driven at import; configure() for tests/embedders)
# ---------------------------------------------------------------------------

SPEC: FaultSpec = FaultSpec()
ENABLED: bool = False
_rng = random.Random(0)
_lock = threading.Lock()
#: deferred deliveries: (due_monotonic, thunk)
_pending: List[Tuple[float, Callable[[], None]]] = []
#: decision counters (diagnostics + soak reports; not the metrics
#: registry — injection must work with UCC_STATS off)
COUNTS = {"drop": 0, "delay": 0, "error": 0, "post_error": 0, "kill": 0,
          "corrupt": 0}


def configure(spec: str = "", seed: Optional[int] = None) -> None:
    """Runtime (re)configuration. Empty spec disables. Reseeds the RNG
    so a configure() call is a deterministic replay point."""
    global SPEC, ENABLED, _rng
    SPEC = parse_spec(spec) if isinstance(spec, str) else spec
    ENABLED = SPEC.active
    _rng = random.Random(0 if seed is None else seed)
    with _lock:
        _pending.clear()
    for k in COUNTS:
        COUNTS[k] = 0


def reset() -> None:
    """Disable injection and drop all deferred deliveries (tests)."""
    configure("")


def pause() -> bool:
    """Temporarily stop injecting (e.g. while a soak harness re-creates
    a poisoned team); returns the previous enabled state for restore()."""
    global ENABLED
    prev = ENABLED
    ENABLED = False
    return prev


def restore(prev: bool) -> None:
    global ENABLED
    ENABLED = prev and SPEC.active


# ---------------------------------------------------------------------------
# decisions — called only under `if inject.ENABLED:`
# ---------------------------------------------------------------------------

def killed(ctx_rank: Optional[int]) -> bool:
    return ctx_rank is not None and ctx_rank in SPEC.kill


def send_action(ctx_rank: Optional[int] = None):
    """Decide the fate of one send. Returns None (deliver normally),
    "drop", "error", or ("delay", seconds)."""
    if killed(ctx_rank):
        COUNTS["kill"] += 1
        return "drop"
    r = _rng.random()
    if r < SPEC.drop:
        COUNTS["drop"] += 1
        return "drop"
    r -= SPEC.drop
    if r < SPEC.error:
        COUNTS["error"] += 1
        return "error"
    r -= SPEC.error
    if r < SPEC.delay and (SPEC.delay_rank is None or
                           ctx_rank == SPEC.delay_rank):
        COUNTS["delay"] += 1
        return ("delay", SPEC.delay_s)
    return None


def corrupt_action(ctx_rank: Optional[int] = None) -> bool:
    """Decide whether THIS send's payload gets corrupted. Independent of
    the drop/error/delay lottery (a corrupted message still arrives —
    that is the whole point: silent unless integrity checking catches
    it). ``corrupt_rank`` pins the fault to one sender, the
    controlled-corruptor drill the attestation attribution test needs."""
    if not SPEC.corrupt:
        return False
    if SPEC.corrupt_rank is not None and ctx_rank != SPEC.corrupt_rank:
        return False
    if _rng.random() < SPEC.corrupt:
        COUNTS["corrupt"] += 1
        return True
    return False


def corrupt_send(data):
    """Apply the corruption: one seeded bit flip in a COPY of the send
    payload. Returns ``(corrupted_u8_array, clean_crc)`` where
    *clean_crc* is the crc32 of the ORIGINAL bytes — handed to the
    matcher as the send-side checksum, so the injection models
    corruption IN FLIGHT (after the sender checksummed correct data),
    the only kind a wire crc can catch. Zero-length payloads are
    returned unchanged (nothing to flip)."""
    import zlib

    import numpy as np
    u8 = np.frombuffer(data, dtype=np.uint8) if not isinstance(
        data, np.ndarray) else np.ascontiguousarray(data).view(np.uint8)
    u8 = u8.reshape(-1)
    clean_crc = zlib.crc32(u8) & 0xFFFFFFFF
    if u8.size == 0:
        return u8, clean_crc
    out = u8.copy()
    i = _rng.randrange(out.size)
    out[i] ^= 1 << _rng.randrange(8)
    return out, clean_crc


def recv_action(ctx_rank: Optional[int] = None):
    """Decide the fate of one recv post: None or "error"."""
    if _rng.random() < SPEC.error:
        COUNTS["error"] += 1
        return "error"
    return None


def post_inject(task) -> Optional[Status]:
    """Task-boundary injection: returns an error Status to fail the task
    at post (before any wire traffic), or None to proceed. Killed ranks
    fail every post — the local half of simulating a dead process; the
    remote half is their sends being dropped."""
    rank = _task_ctx_rank(task)
    if killed(rank):
        COUNTS["kill"] += 1
        return Status.ERR_NO_MESSAGE
    if SPEC.post_error and not getattr(task, "flags_internal", False) \
            and task.schedule is None and _rng.random() < SPEC.post_error:
        # top-level tasks only: failing one child of a live schedule
        # tests the error cascade, but failing the task pre-post is the
        # runtime-fallback shape this hook exists to exercise
        COUNTS["post_error"] += 1
        return Status.ERR_NO_RESOURCE
    return None


def _task_ctx_rank(task) -> Optional[int]:
    team = getattr(task, "team", None)
    core = getattr(team, "core_team", team)
    ctx = getattr(core, "context", None)
    return getattr(ctx, "rank", None)


# ---------------------------------------------------------------------------
# deferred delivery (the "delay" action)
# ---------------------------------------------------------------------------

class DelayedSendReq:
    """Proxy returned for a delayed send: pending until the deferred
    thunk installs the real request."""

    __slots__ = ("real", "cancelled")

    def __init__(self):
        self.real = None
        self.cancelled = False

    def test(self) -> bool:
        if self.cancelled:
            return True
        return bool(self.real is not None and self.real.test())

    @property
    def error(self):
        return getattr(self.real, "error", None) if self.real is not None \
            else None

    def cancel(self) -> None:
        self.cancelled = True
        c = getattr(self.real, "cancel", None)
        if c is not None:
            c()


def defer(delay_s: float, thunk: Callable[[], None]) -> None:
    with _lock:
        _pending.append((time.monotonic() + delay_s, thunk))


def progress(now: Optional[float] = None) -> int:
    """Release due deferred deliveries; called from the progress queue
    under `if inject.ENABLED:`. Returns the number released."""
    if not _pending:
        return 0
    if now is None:
        now = time.monotonic()
    with _lock:
        due = [t for t in _pending if t[0] <= now]
        if not due:
            return 0
        _pending[:] = [t for t in _pending if t[0] > now]
    for _, thunk in due:
        try:
            thunk()
        except Exception:  # noqa: BLE001 - a late delivery into a torn-down
            # endpoint must not kill the caller's progress loop
            pass
    return len(due)


# env-driven arming (import time, like obs.metrics / obs.watchdog)
_env_spec = os.environ.get("UCC_FAULT", "")
if _env_spec:
    try:
        _seed = int(os.environ.get("UCC_FAULT_SEED", "0") or 0)
    except ValueError:
        _seed = 0
    try:
        configure(_env_spec, _seed)
    except ValueError:
        from ..utils.log import get_logger
        get_logger("fault").exception("invalid UCC_FAULT spec %r — "
                                      "injection DISABLED", _env_spec)
