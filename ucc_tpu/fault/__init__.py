"""Fault-injection subsystem — deterministic failure drills.

The fourth pillar of the fault-tolerance layer (PR 2): production
collective stacks earn their no-hang guarantees by *injecting* failures
continuously, not by waiting for the fabric to provide them (PAPERS.md
"Collective Communication for 100k+ GPUs" runs timeout→abort→re-init
drills as part of the runtime's own qualification). This package gives
the TPU build the same muscle:

- ``fault.inject`` — env-driven (``UCC_FAULT=spec``, seeded by
  ``UCC_FAULT_SEED``) probabilistic drop / delay / error / rank-kill at
  the transport boundary (tl/host send/recv) and the task boundary
  (CollTask.post). Zero-cost when unset: hot paths guard with the
  module-level ``inject.ENABLED`` boolean, the same trick as ``obs``.
- ``fault.soak`` — the soak harness: runs the collective matrix under
  injection and asserts the no-hang invariant (every rank reaches a
  terminal status within the deadline, whatever was injected).

Spec grammar (comma-separated)::

    UCC_FAULT=drop=0.01,delay=0.05:0.003,error=0.02,post_error=0.01,kill=2
    UCC_FAULT_SEED=7

``drop=P``            drop a send with probability P (message lost)
``delay=P:S``         delay a send's delivery by S seconds with prob P
``error=P``           fail a send/recv post with ERR_NO_MESSAGE
``post_error=P``      fail a task at post() before any wire traffic
``kill=R[+R2..]``     simulate dead rank(s): ctx rank R drops every
                      send and fails every task post

PR 4 adds the recovery half — failures stop being merely *bounded* and
become *survivable* (detect → attribute → agree → shrink → resume):

- ``fault.health`` — peer liveness under ``UCC_FT=shrink``: heartbeat
  board + per-context ``HealthRegistry`` converging on a named
  failed-rank set from heartbeats, transport fail-fast evidence,
  watchdog escalation, and kill injection; cancels in-flight work on
  dead-rank teams with ``ERR_RANK_FAILED``.
- ``fault.agree`` — fault-tolerant agreement over the service team:
  survivors converge on the same (failed set, recovery epoch) while
  routing around dead members; feeds ``Team.shrink``.

Call sites import the owning module (``from ..fault import inject``) so
runtime reconfiguration stays visible — a re-exported boolean would be a
stale copy.
"""
from . import health, inject  # noqa: F401

__all__ = ["health", "inject"]
