"""Soak harness — the collective matrix under fault injection.

Runs an in-process multi-rank job (thread OOB, the gtest UccJob shape)
through ``iterations`` collectives drawn round-robin from the matrix
while ``fault.inject`` drops / delays / errors / kills, and asserts the
**no-hang invariant**: every rank's request reaches a terminal status
within ``iter_deadline_s`` of posting, whatever was injected. Success
of the *collective* is explicitly NOT asserted — a drilled fault is
supposed to fail things; it is the *unbounded* outcome (a rank parked
IN_PROGRESS forever, the round-5 probe-log wall of ``hang``) that is
the bug.

Per-collective timeouts (CollArgs TIMEOUT flag) are the first
resolution rung: the progress queue cancels timed-out tasks, unwinding
their posted transport ops. A team whose iteration faulted is
re-created before the next one — cancellation is local, so the team's
tag space is undefined afterwards (README "Fault tolerance"), exactly
like the reference's abort→re-init contract.

Used by ``tests/test_fault.py``; runnable standalone::

    python -m ucc_tpu.fault.soak --ranks 4 --iterations 200 \
        --spec 'drop=0.01,delay=0.05:0.003,error=0.02,post_error=0.01'
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from . import inject


_DEFAULT_SPEC = "drop=0.01,delay=0.05:0.003,error=0.02,post_error=0.01"


def _make_job(n: int):
    """N contexts bootstrapped by a thread OOB; returns (contexts, libs)."""
    import ucc_tpu
    from ucc_tpu import Context, ContextParams, ThreadOobWorld
    world = ThreadOobWorld(n)
    libs = [ucc_tpu.init() for _ in range(n)]
    ctxs: List = [None] * n
    errs: List = []

    def mk(r):
        try:
            ctxs[r] = Context(libs[r], ContextParams(oob=world.endpoint(r)))
        except Exception as e:  # noqa: BLE001 - reported below
            errs.append((r, e))

    ths = [threading.Thread(target=mk, args=(r,)) for r in range(n)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(timeout=60)
    if errs:
        raise RuntimeError(f"soak context create failed: {errs}")
    return ctxs


def _make_team(ctxs, deadline_s: float = 30.0):
    from ucc_tpu import Status, TeamParams, ThreadOobWorld, UccError
    world = ThreadOobWorld(len(ctxs))
    teams = [c.create_team_post(TeamParams(oob=world.endpoint(i)))
             for i, c in enumerate(ctxs)]
    deadline = time.monotonic() + deadline_s
    while True:
        sts = [t.create_test() for t in teams]
        for c in ctxs:
            c.progress()
        if all(s == Status.OK for s in sts):
            return teams
        bad = [s for s in sts if s.is_error]
        if bad:
            raise UccError(bad[0], "soak team create failed")
        if time.monotonic() > deadline:
            raise TimeoutError("soak team create timed out")


def _coll_args(coll: str, rank: int, n: int, count: int, bufs: Dict,
               timeout_s: float):
    from ucc_tpu import (BufferInfo, CollArgs, CollArgsFlags, CollType,
                        DataType, ReductionOp)
    flags = CollArgsFlags.TIMEOUT
    if coll == "barrier":
        return CollArgs(coll_type=CollType.BARRIER, flags=flags,
                        timeout=timeout_s)
    src = np.full(count, rank + 1.0, np.float64)
    if coll == "allreduce":
        dst = bufs.setdefault(rank, {}).setdefault(
            "ar", np.zeros(count, np.float64))
        return CollArgs(coll_type=CollType.ALLREDUCE,
                        src=BufferInfo(src, count, DataType.FLOAT64),
                        dst=BufferInfo(dst, count, DataType.FLOAT64),
                        op=ReductionOp.SUM, flags=flags, timeout=timeout_s)
    if coll == "bcast":
        buf = bufs.setdefault(rank, {}).setdefault(
            "bc", np.zeros(count, np.float64))
        if rank == 0:
            buf[:] = 42.0
        return CollArgs(coll_type=CollType.BCAST,
                        src=BufferInfo(buf, count, DataType.FLOAT64),
                        root=0, flags=flags, timeout=timeout_s)
    if coll == "reduce":
        dst = bufs.setdefault(rank, {}).setdefault(
            "rd", np.zeros(count, np.float64))
        return CollArgs(coll_type=CollType.REDUCE,
                        src=BufferInfo(src, count, DataType.FLOAT64),
                        dst=BufferInfo(dst, count, DataType.FLOAT64),
                        op=ReductionOp.SUM, root=0, flags=flags,
                        timeout=timeout_s)
    if coll == "allgather":
        dst = bufs.setdefault(rank, {}).setdefault(
            "ag", np.zeros(count * n, np.float64))
        return CollArgs(coll_type=CollType.ALLGATHER,
                        src=BufferInfo(src, count, DataType.FLOAT64),
                        dst=BufferInfo(dst, count * n, DataType.FLOAT64),
                        flags=flags, timeout=timeout_s)
    if coll == "alltoall":
        src_a = np.arange(count * n, dtype=np.float64) + rank
        dst = bufs.setdefault(rank, {}).setdefault(
            "a2a", np.zeros(count * n, np.float64))
        return CollArgs(coll_type=CollType.ALLTOALL,
                        src=BufferInfo(src_a, count * n, DataType.FLOAT64),
                        dst=BufferInfo(dst, count * n, DataType.FLOAT64),
                        flags=flags, timeout=timeout_s)
    raise ValueError(f"unknown soak collective {coll!r}")


DEFAULT_MATRIX = ("allreduce", "bcast", "allgather", "reduce", "alltoall",
                  "barrier")


def run_soak(n_ranks: int = 4, iterations: int = 200,
             spec: str = _DEFAULT_SPEC, seed: int = 0,
             coll_timeout_s: float = 0.5, iter_deadline_s: float = 10.0,
             count: int = 64,
             matrix=DEFAULT_MATRIX, collect: bool = False) -> Dict:
    """Run the drill; returns a report dict:

    ``iterations`` run, per-outcome ``outcomes`` counts (terminal
    statuses by name), ``hangs`` (iterations where some rank was still
    IN_PROGRESS at the deadline — MUST be empty), ``injected`` decision
    counts, ``teams_recreated``. With ``collect`` the continuous
    telemetry collector runs alongside the fault drill (soaking the
    window exchange against injected drops/delays/errors too) and the
    report gains a ``collector`` section: windows that closed and the
    union of context ranks the straggler scorer flagged.
    """
    from ucc_tpu import Status

    inject.reset()
    prev_knobs = None
    if collect:
        # arm the telemetry pipeline BEFORE context creation (the
        # service is created from Context.__init__); no on-disk store —
        # the soak only wants the scorer/bias path under fire
        from ..obs import collector as _collector
        from ..obs import flight as _flight
        prev_knobs = (_collector.KNOBS.enabled, _collector.KNOBS.interval,
                      _collector.KNOBS.dir, _flight.ENABLED)
        _flight.configure(enabled=True)
        _collector.configure(enabled=True, interval=0.25, dir="")
    ctxs = _make_job(n_ranks)
    teams = _make_team(ctxs)
    report: Dict = {"iterations": 0, "outcomes": {}, "hangs": [],
                    "teams_recreated": 0, "spec": spec, "seed": seed}
    bufs: Dict = {}
    inject.configure(spec, seed)
    try:
        for it in range(iterations):
            coll = matrix[it % len(matrix)]
            try:
                reqs = [t.collective_init(
                    _coll_args(coll, r, n_ranks, count, bufs,
                               coll_timeout_s))
                        for r, t in enumerate(teams)]
                for rq in reqs:
                    rq.post()
            except Exception as e:  # noqa: BLE001 - init/post-time faults
                # (post_error on a killed rank, fallback exhaustion) are
                # a terminal outcome for the iteration, not a hang
                key = f"init_error({type(e).__name__})"
                report["outcomes"][key] = report["outcomes"].get(key, 0) + 1
                report["iterations"] += 1
                prev = inject.pause()
                teams = _recreate(teams, ctxs, report)
                inject.restore(prev)
                continue
            deadline = time.monotonic() + iter_deadline_s
            while time.monotonic() < deadline:
                for c in ctxs:
                    c.progress()
                if all(rq.test() != Status.IN_PROGRESS for rq in reqs):
                    break
            sts = [rq.test() for rq in reqs]
            stuck = [r for r, s in enumerate(sts)
                     if s == Status.IN_PROGRESS]
            if stuck:
                # invariant violation: record, then cancel so the soak
                # itself can continue past the broken iteration
                report["hangs"].append(
                    {"iteration": it, "coll": coll, "ranks": stuck,
                     "statuses": [s.name for s in sts]})
                for r in stuck:
                    reqs[r].task.cancel(Status.ERR_TIMED_OUT)
            for s in sts:
                report["outcomes"][s.name] = \
                    report["outcomes"].get(s.name, 0) + 1
            for rq in reqs:
                try:
                    rq.finalize()
                except Exception:  # noqa: BLE001
                    pass
            report["iterations"] += 1
            if any(s != Status.OK for s in sts):
                # the faulted team's tag space is poisoned (peers may
                # hold stale unexpected messages under tags a future
                # collective will reuse) — re-create it, injection
                # paused, mirroring abort→re-init
                prev = inject.pause()
                teams = _recreate(teams, ctxs, report)
                inject.restore(prev)
    finally:
        report["injected"] = dict(inject.COUNTS)   # before reset zeroes it
        inject.reset()
        if collect:
            flagged: set = set()
            windows = 0
            for c in ctxs:
                col = getattr(c, "collector", None)
                if col is None:
                    continue
                try:
                    flagged |= set(col.flagged_ctx())
                    windows = max(windows, col.windows_run())
                except Exception:  # noqa: BLE001 - reporting only
                    pass
            report["collector"] = {"windows": windows,
                                   "flagged_ctx": sorted(flagged)}
        for t in teams:
            try:
                t.destroy()
            except Exception:  # noqa: BLE001
                pass
        for c in ctxs:
            try:
                c.destroy()
            except Exception:  # noqa: BLE001
                pass
        if prev_knobs is not None:
            from ..obs import collector as _collector
            from ..obs import flight as _flight
            _collector.configure(enabled=prev_knobs[0],
                                 interval=prev_knobs[1], dir=prev_knobs[2])
            _flight.configure(enabled=prev_knobs[3])
    return report


def _recreate(teams, ctxs, report):
    for t in teams:
        try:
            t.destroy()
        except Exception:  # noqa: BLE001
            pass
    report["teams_recreated"] += 1
    return _make_team(ctxs)


# ---------------------------------------------------------------------------
# kill + shrink scenario (UCC_FT=shrink acceptance drill)
# ---------------------------------------------------------------------------

def run_kill_shrink_soak(n_ranks: int = 4, kill_rank: int = 2,
                         pre_iters: int = 6, post_iters: int = 60,
                         hb_interval: float = 0.02,
                         hb_timeout: float = 0.3,
                         iter_deadline_s: float = 15.0,
                         count: int = 64,
                         matrix=DEFAULT_MATRIX,
                         plans: bool = False) -> Dict:
    """The full recovery pipeline under drill: run the matrix healthy,
    kill one rank mid-run (``UCC_FAULT=kill``), assert every survivor
    observes ``ERR_RANK_FAILED`` naming it, shrink, then complete
    *post_iters* more matrix collectives on the shrunk team — with zero
    ranks left IN_PROGRESS anywhere (the no-hang invariant, upgraded to
    a *resume* guarantee).

    Returns a report dict; ``report["violations"]`` MUST be empty.
    """
    from ucc_tpu import Status
    from . import health

    inject.reset()
    prev_mode, prev_int, prev_to = (health.MODE, health.HEARTBEAT_INTERVAL,
                                    health.HEARTBEAT_TIMEOUT)
    health.configure("shrink", interval=hb_interval, timeout=hb_timeout)
    # plan-mode drill (ISSUE 12): force the allreduces onto the native
    # execution-plan path (ring bridge) so the kill->shrink pipeline is
    # exercised with Python off the data path — ucc_plan_cancel must
    # withdraw posted recvs and a pre-shrink plan's sends must be fenced
    import os
    plan_env = None
    if plans:
        plan_env = {k: os.environ.get(k)
                    for k in ("UCC_GEN_NATIVE", "UCC_TL_SHM_TUNE")}
        os.environ["UCC_GEN_NATIVE"] = "y"
        os.environ["UCC_TL_SHM_TUNE"] = "allreduce:@ring:inf"
    ctxs = _make_job(n_ranks)
    teams = _make_team(ctxs)
    # matcher/stale_send_fenced defaults: _probe_stale_send_fence may
    # find no probeable transport and return without setting either key
    report: Dict = {"pre_iters": 0, "post_iters": 0, "violations": [],
                    "outcomes": {}, "detected": {}, "agreed": {},
                    "matcher": None, "stale_send_fenced": None}
    if plans:
        report["plan_mode"] = False
        report["plan_recvs_withdrawn"] = 0
        report["plan_stale_fenced"] = None
    bufs: Dict = {}
    new_teams = None
    try:
        # -- healthy warm-up ------------------------------------------
        for it in range(pre_iters):
            coll = matrix[it % len(matrix)]
            _drive_iter(ctxs, teams, coll, n_ranks, count, bufs,
                        iter_deadline_s, report, "pre", range(n_ranks))
            report["pre_iters"] += 1

        # -- kill one rank --------------------------------------------
        killed_ctx = ctxs[kill_rank].rank
        inject.configure(f"kill={killed_ctx}", seed=0)
        survivors = [r for r in range(n_ranks) if r != kill_rank]
        report["killed"] = {"team_rank": kill_rank, "ctx_rank": killed_ctx}

        # post one matrix iteration across the kill: survivors must
        # reach ERR_RANK_FAILED naming the dead rank (fail-fast or
        # health-cancel), nobody may park IN_PROGRESS
        reqs = {}
        for r in survivors:
            try:
                reqs[r] = teams[r].collective_init(
                    _coll_args("allreduce", r, n_ranks, count, bufs, 0.0))
                reqs[r].post()
            except Exception as e:  # noqa: BLE001
                report["violations"].append(
                    f"survivor {r} post raised {type(e).__name__}: {e}")
        deadline = time.monotonic() + iter_deadline_s
        while time.monotonic() < deadline:
            for c in ctxs:
                c.progress()
            if all(rq.test() != Status.IN_PROGRESS for rq in reqs.values()):
                break
        if plans:
            # BEFORE finalize (which releases the plan): the drilled
            # invariant is that cancellation withdrew the stalled plans'
            # posted recvs natively (cancel-skip), so no late send from
            # the dead epoch can scribble into reclaimed buffers
            for r, rq in reqs.items():
                t = getattr(rq, "task", None)
                p = getattr(t, "_plan", None)
                if p is not None:
                    report["plan_mode"] = True
                    try:
                        report["plan_recvs_withdrawn"] += \
                            p.counters()["withdrawn"]
                    except Exception:  # noqa: BLE001
                        pass
        for r, rq in reqs.items():
            st = rq.test()
            named = rq.failed_ranks or []
            report["detected"][r] = {"status": st.name, "ranks": named}
            if st == Status.IN_PROGRESS:
                report["violations"].append(
                    f"survivor {r} still IN_PROGRESS after kill")
                rq.task.cancel(Status.ERR_TIMED_OUT)
            elif st != Status.ERR_RANK_FAILED:
                report["violations"].append(
                    f"survivor {r} saw {st.name}, not ERR_RANK_FAILED")
            elif killed_ctx not in named:
                report["violations"].append(
                    f"survivor {r} attribution {named} misses ctx rank "
                    f"{killed_ctx}")
            try:
                rq.finalize()
            except Exception:  # noqa: BLE001
                pass

        # -- agree + shrink -------------------------------------------
        shrinks = {r: teams[r].shrink_post() for r in survivors}
        deadline = time.monotonic() + iter_deadline_s
        while time.monotonic() < deadline:
            for c in ctxs:
                c.progress()
            # NOTE: every request must be polled each pass (list, not a
            # short-circuiting all()): ShrinkRequest.test() is what
            # drives the rebuild's OOB rounds, like create_test
            sts = [s.test() for s in shrinks.values()]
            if all(st != Status.IN_PROGRESS for st in sts):
                break
        for r, s in shrinks.items():
            st = s.test()
            report["agreed"][r] = {"status": st.name,
                                   "dead": s.failed_ranks,
                                   "epoch": s.epoch}
            if st != Status.OK:
                report["violations"].append(
                    f"survivor {r} shrink failed: {st.name}")
        views = {(tuple(v["dead"] or ()), v["epoch"])
                 for v in report["agreed"].values()}
        if len(views) > 1:
            report["violations"].append(
                f"survivors diverged on (dead set, epoch): {views}")
        if not report["violations"]:
            new_teams = [shrinks[r].new_team for r in survivors]
            # regression probe: a STALE pre-shrink send posted after the
            # fence must be discarded at the match boundary (n_fenced),
            # never parked where a recycled buffer could meet it. Runs on
            # whichever matcher the endpoint actually uses — the native
            # v2 core fences too, so UCC_FT=shrink no longer pins the
            # python matcher.
            _probe_stale_send_fence(teams[survivors[0]], report)
            if plans:
                _probe_stale_plan_fence(teams[survivors[0]], report)

        # -- resume on the shrunk team --------------------------------
        if new_teams:
            nbufs: Dict = {}
            nn = len(survivors)
            for it in range(post_iters):
                coll = matrix[it % len(matrix)]
                _drive_iter([ctxs[r] for r in survivors], new_teams, coll,
                            nn, count, nbufs, iter_deadline_s, report,
                            "post", survivors, check=True)
                report["post_iters"] += 1
    finally:
        report["injected"] = dict(inject.COUNTS)
        inject.reset()
        health.configure(prev_mode, interval=prev_int, timeout=prev_to)
        if plan_env is not None:
            for k, v in plan_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        if plans:
            if not report.get("plan_mode"):
                report["violations"].append(
                    "plan drill: native execution plans did not engage "
                    "(native core unavailable?)")
            elif not report.get("plan_recvs_withdrawn"):
                report["violations"].append(
                    "plan drill: cancellation withdrew no plan-posted "
                    "recvs")
            elif report.get("plan_stale_fenced") is False:
                report["violations"].append(
                    "plan drill: a pre-shrink plan send was NOT fenced")
        for t in list(teams) + list(new_teams or ()):
            try:
                t.destroy()
            except Exception:  # noqa: BLE001
                pass
        for c in ctxs:
            try:
                c.destroy()
            except Exception:  # noqa: BLE001
                pass
    return report


# ---------------------------------------------------------------------------
# cross-process scenario: one WHOLE OS process killed (ipc arena drill)
# ---------------------------------------------------------------------------

def _free_port_pair() -> int:
    """Adjacent free port pair held simultaneously (the TcpStoreOob
    bootstrap binds *port* for the context world and *port+1* for the
    team world; probing them separately races other listeners)."""
    import socket as _s
    while True:
        a = _s.socket()
        a.bind(("127.0.0.1", 0))
        port = a.getsockname()[1]
        b = _s.socket()
        try:
            b.bind(("127.0.0.1", port + 1))
        except OSError:
            a.close()
            b.close()
            continue
        a.close()
        b.close()
        return port


def _procs_rank_main(rank, size, port, lib, killed_ev, victim, pre_iters,
                     post_iters, count, deadline_s, q):
    """One rank of the cross-process drill (a thread inside its hosting
    worker process). Victim ranks park on progress until the parent
    SIGKILLs their process; survivors cross the kill, shrink, resume."""
    import ucc_tpu
    from ucc_tpu import ContextParams, Status, TcpStoreOob, TeamParams

    rep: Dict = {"rank": rank, "violations": [], "pre": 0, "post": 0}
    ctx = None
    try:
        oob = TcpStoreOob(rank, size, port=port)
        ctx = ucc_tpu.Context(lib, ContextParams(oob=oob))
        team = ctx.create_team(TeamParams(oob=TcpStoreOob(rank, size,
                                                          port=port + 1)))
        bufs: Dict = {}

        def drive(t, coll, n, my_rank, b, check=False):
            rq = t.collective_init(_coll_args(coll, my_rank, n, count, b,
                                              0.0))
            rq.post()
            end = time.monotonic() + deadline_s
            while time.monotonic() < end:
                ctx.progress()
                if rq.test() != Status.IN_PROGRESS:
                    break
            st = rq.test()
            if st == Status.IN_PROGRESS:
                rep["violations"].append(
                    f"{coll} IN_PROGRESS past deadline")
                rq.task.cancel(Status.ERR_TIMED_OUT)
            elif check and st != Status.OK:
                rep["violations"].append(f"{coll} failed: {st.name}")
            elif check and coll == "allreduce":
                expected = sum(g + 1.0 for g in range(n))
                if not np.allclose(b[my_rank]["ar"], expected):
                    rep["violations"].append(
                        f"{coll} wrong result {b[my_rank]['ar'][0]} != "
                        f"{expected}")
            try:
                rq.finalize()
            except Exception:  # noqa: BLE001
                pass
            return st

        # -- healthy matrix on the full cross-process team -------------
        for it in range(pre_iters * len(DEFAULT_MATRIX)):
            drive(team, DEFAULT_MATRIX[it % len(DEFAULT_MATRIX)], size,
                  rank, bufs, check=True)
            rep["pre"] += 1
        q.put(("ready", rank))
        if victim:
            while True:            # parked until the parent's SIGKILL
                ctx.progress()
                time.sleep(0.001)
        killed_ev.wait(timeout=120)

        # -- collective across the kill: detect + attribute ------------
        rq = team.collective_init(_coll_args("allreduce", rank, size,
                                             count, bufs, 0.0))
        rq.post()
        end = time.monotonic() + deadline_s
        while time.monotonic() < end:
            ctx.progress()
            if rq.test() != Status.IN_PROGRESS:
                break
        st = rq.test()
        rep["detected"] = {"status": st.name,
                           "ranks": sorted(rq.failed_ranks or [])}
        if st == Status.IN_PROGRESS:
            rep["violations"].append("IN_PROGRESS after process kill")
            rq.task.cancel(Status.ERR_TIMED_OUT)
        elif st != Status.ERR_RANK_FAILED:
            rep["violations"].append(
                f"saw {st.name} after process kill, not ERR_RANK_FAILED")
        try:
            rq.finalize()
        except Exception:  # noqa: BLE001
            pass

        # -- agree + shrink among the survivors ------------------------
        s = team.shrink_post()
        end = time.monotonic() + 60
        while time.monotonic() < end:
            ctx.progress()
            if s.test() != Status.IN_PROGRESS:
                break
        if s.test() != Status.OK:
            rep["violations"].append(f"shrink failed: {s.test().name}")
            q.put(("report", rank, rep))
            return
        rep["agreed"] = {"epoch": s.epoch,
                         "dead": sorted(s.failed_ranks or [])}
        new_team = s.new_team

        # -- resume: checked matrix on the shrunk team -----------------
        nn = new_team.size
        my = getattr(new_team, "rank", rank)
        nbufs: Dict = {}
        for it in range(post_iters):
            drive(new_team, DEFAULT_MATRIX[it % len(DEFAULT_MATRIX)], nn,
                  my, nbufs, check=True)
            rep["post"] += 1
        q.put(("report", rank, rep))
        try:
            new_team.destroy()
            team.destroy()
        except Exception:  # noqa: BLE001
            pass
    except Exception as e:  # noqa: BLE001
        import traceback
        rep["violations"].append(
            f"rank raised {type(e).__name__}: {e}\n"
            f"{traceback.format_exc()}")
        q.put(("report", rank, rep))
    finally:
        if ctx is not None:
            try:
                ctx.destroy()
            except Exception:  # noqa: BLE001
                pass


def _procs_worker(ranks, size, port, q, killed_ev, victim, pre_iters,
                  post_iters, count, deadline_s):
    """One OS process hosting *ranks* (a thread per rank) of the
    cross-process drill. Forced onto the ipc TL: every payload between
    the processes rides the shared arena."""
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.setdefault("UCC_TLS", "ipc,self")
        import ucc_tpu
        from . import health
        health.configure("shrink", interval=0.05, timeout=2.0)
        # component discovery is not re-entrant: init libs on the main
        # thread, the rank threads only drive the data path
        libs = {r: ucc_tpu.init() for r in ranks}
        ths = [threading.Thread(
            target=_procs_rank_main,
            args=(r, size, port, libs[r], killed_ev, victim, pre_iters,
                  post_iters, count, deadline_s, q), daemon=True)
            for r in ranks]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=600)
    except Exception as e:  # noqa: BLE001
        import traceback
        for r in ranks:
            q.put(("report", r, {"rank": r, "violations": [
                f"worker crashed: {e}\n{traceback.format_exc()}"]}))


def run_procs_kill_shrink(n_procs: int = 2, ranks_per: int = 2,
                          pre_iters: int = 1, post_iters: int = 12,
                          count: int = 64,
                          iter_deadline_s: float = 20.0) -> Dict:
    """The cross-process recovery drill: *n_procs* OS processes host
    ``ranks_per`` ranks each over one shared-memory arena
    (``UCC_TLS=ipc,self``); after a healthy matrix the LAST process is
    SIGKILLed whole — no goodbye, exactly a crashed node. Survivors
    must detect via the arena pid board (heartbeats stop AND the pid is
    conclusively gone), agree on the dead set, shrink, and run a
    checked matrix on the shrunk team.

    Returns a report dict; ``report["violations"]`` MUST be empty.
    """
    import multiprocessing as mp
    import queue as _q

    size = n_procs * ranks_per
    victim = n_procs - 1
    splits = [tuple(range(p * ranks_per, (p + 1) * ranks_per))
              for p in range(n_procs)]
    port = _free_port_pair()
    mctx = mp.get_context("spawn")
    # one queue PER process, never shared across the kill boundary: a
    # shared mp.Queue's write lock is a plain semaphore, and SIGKILLing
    # the victim while its feeder thread holds it (it was just
    # descheduled between send_bytes and release — routine on one core)
    # orphans the lock and wedges every survivor's feeder forever
    qs = [mctx.Queue() for _ in range(n_procs)]
    killed_ev = mctx.Event()
    procs = [mctx.Process(target=_procs_worker,
                          args=(splits[p], size, port, qs[p], killed_ev,
                                p == victim, pre_iters, post_iters,
                                count, iter_deadline_s))
             for p in range(n_procs)]
    survivors = [r for p in range(n_procs) if p != victim
                 for r in splits[p]]
    report: Dict = {"procs": n_procs, "ranks": size, "violations": [],
                    "killed": {"proc": victim,
                               "ctx_ranks": sorted(splits[victim])},
                    "per_rank": {}}
    for p in procs:
        p.start()
    def drain(sources, done, timeout_s):
        deadline = time.monotonic() + timeout_s
        while not done() and time.monotonic() < deadline:
            got = False
            for qq in sources:
                try:
                    msg = qq.get_nowait()
                except _q.Empty:
                    continue
                except (EOFError, OSError):
                    continue               # writer died mid-frame
                got = True
                if msg[0] == "ready":
                    ready.add(msg[1])
                else:
                    report["per_rank"][msg[1]] = msg[2]
            if not got:
                time.sleep(0.05)

    try:
        ready: set = set()
        drain(qs, lambda: len(ready) >= size, 240)
        if len(ready) < size:
            report["violations"].append(
                f"only ranks {sorted(ready)} of {size} reached the kill "
                f"point")
            return report

        procs[victim].kill()                       # SIGKILL, whole process
        procs[victim].join(timeout=30)
        killed_ev.set()

        # only survivor queues from here: the victim's pipe may hold a
        # truncated frame
        drain([qs[p] for p in range(n_procs) if p != victim],
              lambda: len(report["per_rank"]) >= len(survivors), 300)

        dead_expect = set(splits[victim])
        views = set()
        for r in survivors:
            rep = report["per_rank"].get(r)
            if rep is None:
                report["violations"].append(f"rank {r} never reported")
                continue
            for v in rep.get("violations", ()):
                report["violations"].append(f"rank {r}: {v}")
            det = rep.get("detected") or {}
            if not dead_expect & set(det.get("ranks", ())):
                report["violations"].append(
                    f"rank {r} attribution {det.get('ranks')} misses the "
                    f"killed process ranks {sorted(dead_expect)}")
            agreed = rep.get("agreed")
            if agreed is not None:
                views.add((tuple(agreed["dead"]), agreed["epoch"]))
                if not dead_expect <= set(agreed["dead"]):
                    report["violations"].append(
                        f"rank {r} shrank without the whole killed "
                        f"process: {agreed['dead']}")
            if rep.get("post", 0) < post_iters:
                report["violations"].append(
                    f"rank {r} resumed only {rep.get('post', 0)}/"
                    f"{post_iters} post-shrink iterations")
        if len(views) > 1:
            report["violations"].append(
                f"survivors diverged on (dead set, epoch): {views}")
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
    return report


# ---------------------------------------------------------------------------
# corruption storm: wire checksums -> strikes -> quarantine (ISSUE 19)
# ---------------------------------------------------------------------------

def run_corrupt_soak(n_ranks: int = 4, corrupt_rank: int = 1,
                     strikes: int = 3, pre_iters: int = 4,
                     post_iters: int = 60, storm_rounds_max: int = 10,
                     count: int = 256, coll_timeout_s: float = 2.0,
                     iter_deadline_s: float = 15.0,
                     matrix=DEFAULT_MATRIX) -> Dict:
    """Integrity acceptance drill: one rank corrupts EVERY payload it
    sends (``UCC_FAULT=corrupt=1.0,corrupt_rank=R`` — in-flight model,
    the frame still carries the clean payload's crc32), integrity runs
    in ``verify`` mode, and the pipeline under test is

        wire crc mismatch at delivery -> ERR_DATA_CORRUPTED naming the
        sender -> strike ledger -> quarantine (HealthRegistry) ->
        shrink excludes the corruptor -> checked matrix on the survivors

    The storm runs allreduce only: on the forced ring the corruptor's
    downstream neighbour is the sole direct receiver, so it accumulates
    exactly one strike per round and quarantine must trip in exactly
    ``strikes`` detected rounds (more is a violation — detection that
    does not escalate).  Allreduces are forced onto NATIVE EXECUTION
    PLANS; the pinned corruptor interprets (rank-variant plan engage)
    while its peers keep the C matcher's crc verify on the data path,
    which is precisely the deployment shape the drill certifies.

    Non-detecting ranks are starved of contributions each round; they
    carry a per-collective TIMEOUT so they cancel instead of parking
    (timeouts are acceptable collateral, hangs are violations; an
    all-OK round with a wrong result is the cardinal sin: silent
    corruption).  ``report["violations"]`` MUST be empty.
    """
    import os
    from ucc_tpu import Status
    from .. import integrity
    from ..status import DataCorruptedError
    from . import health

    inject.reset()
    prev_hb = (health.MODE, health.HEARTBEAT_INTERVAL,
               health.HEARTBEAT_TIMEOUT)
    # all three BEFORE context create: health registries and the native
    # mailboxes' integrity arming are wired up in Context.__init__
    health.configure("shrink", interval=0.05, timeout=2.0)
    integrity.configure(mode="verify", sample=1, strikes=strikes)
    plan_env = {k: os.environ.get(k)
                for k in ("UCC_GEN_NATIVE", "UCC_TL_SHM_TUNE")}
    os.environ["UCC_GEN_NATIVE"] = "y"
    os.environ["UCC_TL_SHM_TUNE"] = "allreduce:@ring:inf"
    ctxs = _make_job(n_ranks)
    teams = _make_team(ctxs)
    corrupt_ctx = ctxs[corrupt_rank].rank
    report: Dict = {"pre_iters": 0, "storm_rounds": 0, "post_iters": 0,
                    "violations": [], "outcomes": {}, "detections": 0,
                    "quarantined": False, "rounds_to_quarantine": None,
                    "corruptor": {"team_rank": corrupt_rank,
                                  "ctx_rank": corrupt_ctx},
                    "mode": "verify", "strikes": strikes,
                    "teams_recreated": 0,
                    "plan_mode": False, "agreed": {},
                    "matcher": None, "stale_send_fenced": None}
    bufs: Dict = {}
    new_teams = None
    try:
        # -- healthy warm-up (no injection, results checked) -----------
        for it in range(pre_iters):
            coll = matrix[it % len(matrix)]
            _drive_iter(ctxs, teams, coll, n_ranks, count, bufs,
                        iter_deadline_s, report, "pre", range(n_ranks))
            report["pre_iters"] += 1

        # -- the storm -------------------------------------------------
        # armed only now: team create's service collectives stay clean
        inject.configure(f"corrupt=1.0,corrupt_rank={corrupt_ctx}", seed=0)
        expected = sum(g + 1.0 for g in range(n_ranks))
        for rnd in range(storm_rounds_max):
            injected_before = inject.COUNTS.get("corrupt", 0)
            reqs = [t.collective_init(
                _coll_args("allreduce", r, n_ranks, count, bufs,
                           coll_timeout_s))
                    for r, t in enumerate(teams)]
            for rq in reqs:
                rq.post()
            done: List = [None] * n_ranks
            deadline = time.monotonic() + iter_deadline_s
            while time.monotonic() < deadline and any(d is None
                                                      for d in done):
                for c in ctxs:
                    c.progress()
                for i, rq in enumerate(reqs):
                    if done[i] is not None:
                        continue
                    try:
                        st = rq.test()
                    except DataCorruptedError as e:
                        # the attestation hook raises; wire-path
                        # corruption instead RETURNS the error status
                        done[i] = (Status.ERR_DATA_CORRUPTED,
                                   sorted(e.ranks))
                        continue
                    if st != Status.IN_PROGRESS:
                        done[i] = (st, sorted(getattr(
                            rq.task, "corrupt_ranks", ()) or ()))
            report["storm_rounds"] += 1
            # native plans must carry the peers' data path (the pinned
            # corruptor itself interprets, by design) — probe BEFORE
            # finalize releases the plan
            if any(getattr(rq.task, "_plan", None) is not None
                   for r, rq in enumerate(reqs) if r != corrupt_rank):
                report["plan_mode"] = True
            hung = [r for r, d in enumerate(done) if d is None]
            for r in hung:
                report["violations"].append(
                    f"storm round {rnd}: rank {r} IN_PROGRESS past "
                    f"deadline")
                reqs[r].task.cancel(Status.ERR_TIMED_OUT)
                done[r] = (Status.ERR_TIMED_OUT, [])
            detectors = [r for r, (st, _) in enumerate(done)
                         if st == Status.ERR_DATA_CORRUPTED]
            for r, (st, _) in enumerate(done):
                key = f"storm:{st.name}"
                report["outcomes"][key] = report["outcomes"].get(key, 0) + 1
            injected = inject.COUNTS.get("corrupt", 0) - injected_before
            if detectors:
                report["detections"] += 1
                for r in detectors:
                    named = done[r][1]
                    if corrupt_ctx not in named:
                        report["violations"].append(
                            f"storm round {rnd}: rank {r} attribution "
                            f"{named} misses ctx rank {corrupt_ctx}")
            elif all(st == Status.OK for st, _ in done):
                for g in range(n_ranks):
                    if not np.allclose(bufs[g]["ar"], expected):
                        report["violations"].append(
                            f"storm round {rnd}: SILENT CORRUPTION — "
                            f"rank {g} result {bufs[g]['ar'][0]} != "
                            f"{expected} with no rank reporting "
                            f"ERR_DATA_CORRUPTED")
                        break
            elif injected:
                report["violations"].append(
                    f"storm round {rnd}: {injected} corrupted sends "
                    f"went undetected (outcomes "
                    f"{[st.name for st, _ in done]})")
            for rq in reqs:
                try:
                    rq.finalize()
                except Exception:  # noqa: BLE001
                    pass
            quarantined = any(
                corrupt_ctx in (ctxs[r].health.dead_set()
                                if ctxs[r].health else ())
                for r in range(n_ranks) if r != corrupt_rank)
            if quarantined:
                report["quarantined"] = True
                report["rounds_to_quarantine"] = rnd + 1
                break
            # the faulted team's tag space is poisoned (run_soak
            # contract); strike ledgers and health live on the CONTEXT,
            # so they survive the re-create
            prev = inject.pause()
            teams = _recreate(teams, ctxs, report)
            inject.restore(prev)

        if not report["quarantined"]:
            report["violations"].append(
                f"corruptor not quarantined after {report['storm_rounds']}"
                f" storm rounds ({report['detections']} detected)")
        elif report["detections"] > strikes:
            report["violations"].append(
                f"quarantine took {report['detections']} detected rounds;"
                f" strike threshold is {strikes}")
        if not report["plan_mode"]:
            report["violations"].append(
                "storm ran without native execution plans on the "
                "peers (native core unavailable?)")

        # -- shrink the corruptor out ---------------------------------
        # injection stays armed: the quarantined rank no longer sends,
        # so nothing fires — exactly the production posture
        if report["quarantined"]:
            survivors = [r for r in range(n_ranks) if r != corrupt_rank]
            sctxs = [ctxs[r] for r in survivors]
            shrinks = {r: teams[r].shrink_post() for r in survivors}
            deadline = time.monotonic() + iter_deadline_s
            while time.monotonic() < deadline:
                for c in sctxs:
                    c.progress()
                # poll every request each pass — test() drives the OOB
                # rebuild rounds (a short-circuiting all() deadlocks)
                sts = [s.test() for s in shrinks.values()]
                if all(st != Status.IN_PROGRESS for st in sts):
                    break
            for r, s in shrinks.items():
                st = s.test()
                report["agreed"][r] = {"status": st.name,
                                       "dead": s.failed_ranks,
                                       "epoch": s.epoch}
                if st != Status.OK:
                    report["violations"].append(
                        f"survivor {r} shrink failed: {st.name}")
                elif corrupt_ctx not in (s.failed_ranks or ()):
                    report["violations"].append(
                        f"survivor {r} shrank without the corruptor: "
                        f"{s.failed_ranks}")
            views = {(tuple(v["dead"] or ()), v["epoch"])
                     for v in report["agreed"].values()}
            if len(views) > 1:
                report["violations"].append(
                    f"survivors diverged on (dead set, epoch): {views}")
            if not report["violations"]:
                new_teams = [shrinks[r].new_team for r in survivors]
                _probe_stale_send_fence(teams[survivors[0]], report)

            # -- checked matrix on the shrunk team --------------------
            if new_teams:
                nbufs: Dict = {}
                nn = len(survivors)
                for it in range(post_iters):
                    coll = matrix[it % len(matrix)]
                    _drive_iter(sctxs, new_teams, coll, nn, count, nbufs,
                                iter_deadline_s, report, "post",
                                survivors, check=True)
                    report["post_iters"] += 1
    finally:
        report["injected"] = dict(inject.COUNTS)
        inject.reset()
        integrity.reset()
        health.configure(prev_hb[0], interval=prev_hb[1],
                         timeout=prev_hb[2])
        for k, v in plan_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        for t in list(teams) + list(new_teams or ()):
            try:
                t.destroy()
            except Exception:  # noqa: BLE001
                pass
        for c in ctxs:
            try:
                c.destroy()
            except Exception:  # noqa: BLE001
                pass
    return report


# ---------------------------------------------------------------------------
# churn scenario: interleaved kill -> shrink -> grow cycles (ISSUE 17)
# ---------------------------------------------------------------------------

def _drive_requests(ctxs, reqs, deadline_s: float) -> bool:
    """Poll *reqs* (membership requests: shrink/grow/join) to terminal.
    Every request is polled each pass — their ``test()`` is what drives
    the OOB rebuild rounds, so a short-circuiting ``all()`` deadlocks."""
    from ucc_tpu import Status
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        for c in ctxs:
            c.progress()
        sts = [rq.test() for rq in reqs]
        if all(st != Status.IN_PROGRESS for st in sts):
            return True
    return False


def run_churn_soak(n_ranks: int = 4, cycles: int = 2,
                   iters_per_epoch: int = 4, post_iters: int = 60,
                   hb_interval: float = 0.02, hb_timeout: float = 0.3,
                   iter_deadline_s: float = 15.0,
                   membership_deadline_s: float = 30.0,
                   count: int = 64, matrix=DEFAULT_MATRIX,
                   plans: bool = False, collect: bool = False) -> Dict:
    """The elastic-membership drill: *cycles* interleaved
    kill -> detect -> shrink -> grow(rejoin) rounds with matrix
    collectives in flight on EVERY epoch, followed by a false-suspicion
    round (a live rank is excluded by hint, then re-admitted through the
    join path) and >= *post_iters* checked collectives on the final
    team.

    Asserted invariants (anything else lands in ``violations``):

    - no rank is ever parked IN_PROGRESS past a deadline (no-hang);
    - every survivor observes ERR_RANK_FAILED naming the killed rank;
    - shrink and grow converge to identical (membership, epoch) views;
    - the epoch fence discards stale traffic in BOTH directions
      (``fenced`` counts a pre-shrink send killed by the shrink fence
      and a pre-grow send killed by the grow fence, per cycle);
    - the falsely-suspected rank is demonstrably re-admitted: revived
      out of the survivors' dead sets and serving checked collectives
      on the new epoch (``readmitted``);
    - the final membership equals the initial one and *post_iters*
      collectives complete correctly on it (``post_churn_ok``).
    """
    import os

    from ucc_tpu import Status, TeamParams, ThreadOobWorld
    from ucc_tpu.core.team import Team

    from . import health

    inject.reset()
    prev_mode, prev_int, prev_to = (health.MODE, health.HEARTBEAT_INTERVAL,
                                    health.HEARTBEAT_TIMEOUT)
    health.configure("shrink", interval=hb_interval, timeout=hb_timeout)
    plan_env = None
    if plans:
        # native-matcher mode: allreduces ride the generated native plan
        # path, so both fence directions are drilled against the C v2
        # matcher rather than the python mailbox
        plan_env = {k: os.environ.get(k)
                    for k in ("UCC_GEN_NATIVE", "UCC_TL_SHM_TUNE")}
        os.environ["UCC_GEN_NATIVE"] = "y"
        os.environ["UCC_TL_SHM_TUNE"] = "allreduce:@ring:inf"
    prev_knobs = None
    if collect:
        from ..obs import collector as _collector
        from ..obs import flight as _flight
        prev_knobs = (_collector.KNOBS.enabled, _collector.KNOBS.interval,
                      _collector.KNOBS.dir, _flight.ENABLED)
        _flight.configure(enabled=True)
        _collector.configure(enabled=True, interval=0.25, dir="")
    ctxs = _make_job(n_ranks)
    teams = _make_team(ctxs)
    report: Dict = {"cycles": 0, "violations": [], "outcomes": {},
                    "fenced": {"shrink": 0, "grow": 0},
                    "epochs": [], "post_churn_ok": 0,
                    "readmitted": False, "matcher": None,
                    "injected": {}}
    bufs: Dict = {}
    all_teams: List = list(teams)    # every team ever built, for teardown

    def _note_injected():
        for k, v in dict(inject.COUNTS).items():
            report["injected"][k] = report["injected"].get(k, 0) + v

    def _probe(old_team, direction: str):
        # reuse the shrink probe: it posts into epoch 0 — the pre-change
        # tag space — so it regression-tests the fence whichever
        # direction retired the team
        sub: Dict = {"violations": [], "stale_send_fenced": None,
                     "matcher": None}
        _probe_stale_send_fence(old_team, sub)
        if sub["matcher"] is not None:
            report["matcher"] = sub["matcher"]
        if sub["stale_send_fenced"]:
            report["fenced"][direction] += 1
        for v in sub["violations"]:
            report["violations"].append(f"{direction} fence: {v}")

    def _membership_change(cur, dead_team_rank, dead_ctx, hint=False):
        """One shrink(+probe) -> iters -> grow(rejoin)(+probe) -> iters
        round. *cur* maps ctx index -> its current Team; returns the
        next such map (full membership again) or None on failure."""
        survivors = sorted(i for i in cur if i != dead_team_rank)
        shrinks = {}
        for i in survivors:
            try:
                # dead_hint is in TEAM ranks; after the first grow the
                # joiner sits at the tail, so team rank != ctx rank
                t = cur[i]
                hint_ranks = [r for r in range(t.size)
                              if int(t.ctx_map.eval(r)) == dead_ctx] \
                    if hint else None
                shrinks[i] = t.shrink_post(dead_hint=hint_ranks)
            except Exception as e:  # noqa: BLE001
                report["violations"].append(
                    f"ctx {i} shrink_post raised {type(e).__name__}: {e}")
                return None
        sctxs = [ctxs[i] for i in survivors]
        if not _drive_requests(sctxs, list(shrinks.values()),
                               membership_deadline_s):
            report["violations"].append(
                f"shrink (dead ctx {dead_ctx}) hung past "
                f"{membership_deadline_s}s")
            return None
        views = set()
        for i, s in shrinks.items():
            st = s.test()
            if st != Status.OK:
                report["violations"].append(
                    f"ctx {i} shrink failed: {st.name}")
                return None
            views.add((tuple(s.failed_ranks or ()), s.epoch))
        if len(views) > 1:
            report["violations"].append(
                f"shrink views diverged: {views}")
            return None
        report["epochs"].append(next(iter(views))[1])
        _probe(cur[survivors[0]], "shrink")
        shrunk = {i: shrinks[i].new_team for i in survivors}
        nbufs: Dict = {}
        for it in range(iters_per_epoch):
            _drive_iter(sctxs, [shrunk[i] for i in survivors],
                        matrix[it % len(matrix)], len(survivors), count,
                        nbufs, iter_deadline_s, report,
                        f"shrunk-e{report['epochs'][-1]}", survivors)
        all_teams.extend(shrunk.values())
        # the excluded rank comes back: clear the drill fault, retire its
        # stale pre-shrink team, and re-admit it through the join path
        _note_injected()
        inject.reset()
        try:
            cur[dead_team_rank].destroy()
        except Exception:  # noqa: BLE001
            pass
        grows = {}
        for i in survivors:
            try:
                grows[i] = shrunk[i].grow_post([dead_ctx])
            except Exception as e:  # noqa: BLE001
                report["violations"].append(
                    f"ctx {i} grow_post raised {type(e).__name__}: {e}")
                return None
        try:
            join = Team.join_post(ctxs[dead_team_rank])
        except Exception as e:  # noqa: BLE001
            report["violations"].append(
                f"ctx {dead_team_rank} join_post raised "
                f"{type(e).__name__}: {e}")
            return None
        if not _drive_requests(ctxs, list(grows.values()) + [join],
                               membership_deadline_s):
            report["violations"].append(
                f"grow (rejoin ctx {dead_ctx}) hung past "
                f"{membership_deadline_s}s")
            return None
        gviews = set()
        for i, g in grows.items():
            st = g.test()
            if st != Status.OK:
                report["violations"].append(
                    f"ctx {i} grow failed: {st.name}")
                return None
            gviews.add(g.epoch)
        if join.test() != Status.OK:
            report["violations"].append(
                f"ctx {dead_team_rank} join failed: {join.test().name}")
            return None
        gviews.add(join.epoch)
        if len(gviews) > 1:
            report["violations"].append(
                f"grow epochs diverged: {gviews}")
            return None
        report["epochs"].append(next(iter(gviews)))
        _probe(shrunk[survivors[0]], "grow")
        nxt = {i: grows[i].new_team for i in survivors}
        nxt[dead_team_rank] = join.new_team
        all_teams.extend(nxt.values())
        gbufs: Dict = {}
        order = sorted(nxt)
        for it in range(iters_per_epoch):
            _drive_iter([ctxs[i] for i in order], [nxt[i] for i in order],
                        matrix[it % len(matrix)], len(order), count,
                        gbufs, iter_deadline_s, report,
                        f"grown-e{report['epochs'][-1]}", order)
        return nxt

    cur = {i: teams[i] for i in range(n_ranks)}
    try:
        # -- kill -> shrink -> grow cycles ----------------------------
        for cyc in range(cycles):
            kill_team_rank = 1 + (cyc % (n_ranks - 1))
            killed_ctx = ctxs[kill_team_rank].rank
            inject.configure(f"kill={killed_ctx}", seed=cyc)
            survivors = sorted(i for i in cur if i != kill_team_rank)
            # collective across the kill: every survivor must reach
            # ERR_RANK_FAILED naming the dead rank, nobody parks
            reqs = {}
            for i in survivors:
                try:
                    reqs[i] = cur[i].collective_init(
                        _coll_args("allreduce", i, n_ranks, count, bufs,
                                   0.0))
                    reqs[i].post()
                except Exception as e:  # noqa: BLE001
                    report["violations"].append(
                        f"cycle {cyc}: survivor {i} post raised "
                        f"{type(e).__name__}: {e}")
            deadline = time.monotonic() + iter_deadline_s
            while time.monotonic() < deadline:
                for i in survivors:
                    ctxs[i].progress()
                if all(rq.test() != Status.IN_PROGRESS
                       for rq in reqs.values()):
                    break
            for i, rq in reqs.items():
                st = rq.test()
                if st == Status.IN_PROGRESS:
                    report["violations"].append(
                        f"cycle {cyc}: survivor {i} IN_PROGRESS after "
                        "kill")
                    rq.task.cancel(Status.ERR_TIMED_OUT)
                elif st != Status.ERR_RANK_FAILED:
                    report["violations"].append(
                        f"cycle {cyc}: survivor {i} saw {st.name}, not "
                        "ERR_RANK_FAILED")
                elif killed_ctx not in (rq.failed_ranks or []):
                    report["violations"].append(
                        f"cycle {cyc}: survivor {i} attribution "
                        f"{rq.failed_ranks} misses ctx {killed_ctx}")
                try:
                    rq.finalize()
                except Exception:  # noqa: BLE001
                    pass
            nxt = _membership_change(cur, kill_team_rank, killed_ctx)
            if nxt is None:
                return report
            cur = nxt
            report["cycles"] += 1

        # -- false suspicion: exclude a LIVE rank, re-admit it --------
        victim = n_ranks - 1
        victim_ctx = ctxs[victim].rank
        nxt = _membership_change(cur, victim, victim_ctx, hint=True)
        if nxt is None:
            return report
        cur = nxt
        readmitted = True
        for i in cur:
            if i == victim:
                continue
            reg = getattr(ctxs[i], "health", None)
            if reg is not None and victim_ctx in reg.dead_set():
                readmitted = False
        if not readmitted:
            report["violations"].append(
                f"falsely-suspected ctx {victim_ctx} still in a "
                "survivor dead set after rejoin")
        report["readmitted"] = readmitted

        # -- post-churn: checked collectives on the final epoch -------
        if sorted(cur) != list(range(n_ranks)):
            report["violations"].append(
                f"post-churn membership {sorted(cur)} != full "
                f"{list(range(n_ranks))}")
            return report
        pbufs: Dict = {}
        order = sorted(cur)
        for it in range(post_iters):
            before = len(report["violations"])
            _drive_iter([ctxs[i] for i in order], [cur[i] for i in order],
                        matrix[it % len(matrix)], n_ranks, count, pbufs,
                        iter_deadline_s, report, "post-churn", order,
                        check=True)
            if len(report["violations"]) == before:
                report["post_churn_ok"] += 1
    finally:
        _note_injected()
        inject.reset()
        health.configure(prev_mode, interval=prev_int, timeout=prev_to)
        if plan_env is not None:
            for k, v in plan_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        if collect:
            flagged: set = set()
            windows = 0
            for c in ctxs:
                col = getattr(c, "collector", None)
                if col is None:
                    continue
                try:
                    flagged |= set(col.flagged_ctx())
                    windows = max(windows, col.windows_run())
                except Exception:  # noqa: BLE001 - reporting only
                    pass
            report["collector"] = {"windows": windows,
                                   "flagged_ctx": sorted(flagged)}
        if report["fenced"]["shrink"] == 0 and report["cycles"]:
            report["violations"].append(
                "no pre-shrink send was fenced across the whole churn")
        if report["fenced"]["grow"] == 0 and report["cycles"]:
            report["violations"].append(
                "no pre-grow send was fenced across the whole churn")
        for t in all_teams:
            try:
                t.destroy()
            except Exception:  # noqa: BLE001
                pass
        for c in ctxs:
            try:
                c.destroy()
            except Exception:  # noqa: BLE001
                pass
        if prev_knobs is not None:
            from ..obs import collector as _collector
            from ..obs import flight as _flight
            _collector.configure(enabled=prev_knobs[0],
                                 interval=prev_knobs[1],
                                 dir=prev_knobs[2])
            _flight.configure(enabled=prev_knobs[3])
    return report


# ---------------------------------------------------------------------------
# multi-tenant scenario: N teams x kill x grow x priority-inversion probe
# ---------------------------------------------------------------------------

def _make_teams_mt(ctxs, priority=None, deadline_s: float = 60.0):
    """One team across *ctxs* with an explicit priority class."""
    from ucc_tpu import Status, TeamParams, ThreadOobWorld, UccError
    world = ThreadOobWorld(len(ctxs))
    teams = [c.create_team_post(TeamParams(oob=world.endpoint(i),
                                           priority=priority))
             for i, c in enumerate(ctxs)]
    deadline = time.monotonic() + deadline_s
    while True:
        # list comp, not a generator: every rank's create state machine
        # must step each pass or the OOB exchange deadlocks
        sts = [t.create_test() for t in teams]
        for c in ctxs:
            c.progress()
        if all(s == Status.OK for s in sts):
            return teams
        bad = [s for s in sts if s.is_error]
        if bad:
            raise UccError(bad[0], "mt soak team create failed")
        if time.monotonic() > deadline:
            raise TimeoutError("mt soak team create timed out")


def run_multi_tenant_soak(n_ranks: int = 4, n_teams: int = 3,
                          rounds: int = 5, burst: int = 6,
                          post_rounds: int = 5, kill_rank: int = 2,
                          hb_interval: float = 0.02,
                          hb_timeout: float = 0.3,
                          iter_deadline_s: float = 15.0,
                          membership_deadline_s: float = 30.0,
                          count: int = 32) -> Dict:
    """The multi-tenant service drill: *n_teams* teams share one
    progress engine per rank — team 0 is the latency class (priority 3),
    the rest are bulk (priority 0) with small-collective coalescing ON.
    Phases:

    1. mixed traffic: every round the bulk teams post a *burst* of
       coalesce-eligible allreduces, then the latency team posts a
       probe per rank (completion-callback timed);
    2. kill one rank mid-traffic: every surviving tenant's in-flight
       work — including members HELD by a coalescer and batches already
       sealed into fused carriers — must reach a terminal status within
       the deadline (the no-hang invariant extended to the batching
       layer), with the failure attributed;
    3. recovery: every team shrinks among the survivors, then grows the
       revived rank back in (sequential join per team);
    4. post-recovery mixed traffic with checked statuses, and the
       priority-inversion probe: per-context ``qos_snapshot`` counters
       (inversions, starvation gauge) recorded in the report —
       starvation past 1s is a violation.

    Returns a report dict; ``report["violations"]`` MUST be empty.
    """
    from ucc_tpu import BufferInfo, CollArgs, CollType, DataType, Status
    from ucc_tpu.constants import ReductionOp
    from ucc_tpu.core import coalesce as _coal
    from ucc_tpu.core.team import Team

    from . import health

    inject.reset()
    prev_mode, prev_int, prev_to = (health.MODE, health.HEARTBEAT_INTERVAL,
                                    health.HEARTBEAT_TIMEOUT)
    health.configure("shrink", interval=hb_interval, timeout=hb_timeout)
    prev_coal = (_coal.ENABLED, _coal.LIMIT_BYTES,
                 round(_coal.WINDOW_S * 1e6), _coal.MAX_BATCH)
    _coal.configure(enabled=True)
    report: Dict = {"teams": n_teams, "ranks": n_ranks, "rounds": 0,
                    "post_rounds_ok": 0, "violations": [], "outcomes": {},
                    "detected": {}, "shrunk_epochs": {}, "grown_epochs": {},
                    "hi_probe_ms": {}, "qos": {}, "fused_batches": 0}
    ctxs = _make_job(n_ranks)
    # team 0 = latency class; teams 1.. = bulk tenants (coalesced)
    cur: List[Dict] = []
    for t in range(n_teams):
        per = _make_teams_mt(ctxs, priority=(3 if t == 0 else 0))
        cur.append({i: per[i] for i in range(n_ranks)})
    all_teams: List = [tm for per in cur for tm in per.values()]

    def _ar_args(cb=None):
        a = CollArgs(coll_type=CollType.ALLREDUCE, op=ReductionOp.SUM,
                     src=BufferInfo(np.ones(count, np.float32), count,
                                    DataType.FLOAT32),
                     dst=BufferInfo(np.zeros(count, np.float32), count,
                                    DataType.FLOAT32))
        a.cb = cb
        return a

    def _mixed_round(members, phase, check=False):
        """One bulk-burst + latency-probe round over *members* (ctx
        index -> per-team Team maps). Returns hi-probe latencies (ms)."""
        order = sorted(members[0])
        reqs, lats = [], []
        for per in members[1:]:
            for _ in range(burst):
                for i in order:
                    rq = per[i].collective_init(_ar_args())
                    rq.post()
                    reqs.append(rq)
        done = {}

        def _stamp(i):
            def _cb(_t, _st):
                done[i] = time.perf_counter()
            return _cb

        t0 = {}
        hi = []
        for i in order:
            t0[i] = time.perf_counter()
            rq = members[0][i].collective_init(_ar_args(cb=_stamp(i)))
            rq.post()
            hi.append(rq)
            reqs.append(rq)
        deadline = time.monotonic() + iter_deadline_s
        while time.monotonic() < deadline:
            for i in order:
                ctxs[i].progress()
            if all(rq.test() != Status.IN_PROGRESS for rq in reqs):
                break
        sts = [rq.test() for rq in reqs]
        for s in sts:
            key = f"{phase}:{s.name}"
            report["outcomes"][key] = report["outcomes"].get(key, 0) + 1
        stuck = sum(1 for s in sts if s == Status.IN_PROGRESS)
        if stuck:
            report["violations"].append(
                f"{phase}: {stuck} request(s) IN_PROGRESS past deadline")
            for rq in reqs:
                if rq.test() == Status.IN_PROGRESS:
                    rq.task.cancel(Status.ERR_TIMED_OUT)
        elif check and any(s != Status.OK for s in sts):
            bad = sorted({s.name for s in sts if s != Status.OK})
            report["violations"].append(f"{phase}: failures {bad}")
        for i in order:
            if i in done:
                lats.append((done[i] - t0[i]) * 1e3)
        for rq in reqs:
            try:
                rq.finalize()
            except Exception:  # noqa: BLE001
                pass
        return lats

    try:
        # -- phase 1: healthy mixed traffic ---------------------------
        hi_lats: List[float] = []
        for _ in range(rounds):
            hi_lats.extend(_mixed_round(cur, "mixed", check=True))
            report["rounds"] += 1

        # -- phase 2: kill one rank mid-traffic -----------------------
        killed_ctx = ctxs[kill_rank].rank
        survivors = [i for i in range(n_ranks) if i != kill_rank]
        report["killed"] = {"team_rank": kill_rank, "ctx_rank": killed_ctx}
        inject.configure(f"kill={killed_ctx}", seed=0)
        reqs = {}
        for t, per in enumerate(cur):
            for i in survivors:
                try:
                    rq = per[i].collective_init(_ar_args())
                    rq.post()
                    reqs[(t, i)] = rq
                except Exception as e:  # noqa: BLE001
                    report["violations"].append(
                        f"kill: team {t} rank {i} post raised "
                        f"{type(e).__name__}: {e}")
        deadline = time.monotonic() + iter_deadline_s
        while time.monotonic() < deadline:
            for i in survivors:
                ctxs[i].progress()
            if all(rq.test() != Status.IN_PROGRESS
                   for rq in reqs.values()):
                break
        attributed = 0
        for (t, i), rq in reqs.items():
            st = rq.test()
            report["detected"][f"t{t}r{i}"] = st.name
            if st == Status.IN_PROGRESS:
                report["violations"].append(
                    f"kill: team {t} rank {i} IN_PROGRESS after kill "
                    "(held/fused member not aborted?)")
                rq.task.cancel(Status.ERR_TIMED_OUT)
            elif not st.is_error:
                report["violations"].append(
                    f"kill: team {t} rank {i} saw {st.name}, expected "
                    "an error")
            if killed_ctx in (rq.failed_ranks or []):
                attributed += 1
            try:
                rq.finalize()
            except Exception:  # noqa: BLE001
                pass
        if reqs and not attributed:
            report["violations"].append(
                f"kill: no survivor attributed the failure to ctx "
                f"{killed_ctx}")

        # -- phase 3: shrink every tenant among the survivors ---------
        shrunk: List[Dict] = []
        for t, per in enumerate(cur):
            shrinks = {}
            for i in survivors:
                try:
                    shrinks[i] = per[i].shrink_post()
                except Exception as e:  # noqa: BLE001
                    report["violations"].append(
                        f"shrink: team {t} rank {i} raised "
                        f"{type(e).__name__}: {e}")
                    return report
            if not _drive_requests([ctxs[i] for i in survivors],
                                   list(shrinks.values()),
                                   membership_deadline_s):
                report["violations"].append(f"shrink: team {t} hung")
                return report
            views = set()
            for i, s in shrinks.items():
                if s.test() != Status.OK:
                    report["violations"].append(
                        f"shrink: team {t} rank {i} failed "
                        f"{s.test().name}")
                    return report
                views.add((tuple(s.failed_ranks or ()), s.epoch))
            if len(views) > 1:
                report["violations"].append(
                    f"shrink: team {t} views diverged {views}")
                return report
            report["shrunk_epochs"][f"t{t}"] = next(iter(views))[1]
            shrunk.append({i: shrinks[i].new_team for i in survivors})
            all_teams.extend(shrunk[-1].values())
        # traffic must flow for every tenant on the shrunk epoch
        _mixed_round(shrunk, "shrunk", check=True)

        # -- phase 4: grow the revived rank back into every team ------
        inject.reset()
        for per in cur:
            try:
                per[kill_rank].destroy()
            except Exception:  # noqa: BLE001
                pass
        grown: List[Dict] = []
        for t, per in enumerate(shrunk):
            grows = {}
            for i in survivors:
                try:
                    grows[i] = per[i].grow_post([killed_ctx])
                except Exception as e:  # noqa: BLE001
                    report["violations"].append(
                        f"grow: team {t} rank {i} raised "
                        f"{type(e).__name__}: {e}")
                    return report
            try:
                join = Team.join_post(ctxs[kill_rank])
            except Exception as e:  # noqa: BLE001
                report["violations"].append(
                    f"grow: team {t} join raised {type(e).__name__}: {e}")
                return report
            if not _drive_requests(ctxs, list(grows.values()) + [join],
                                   membership_deadline_s):
                report["violations"].append(f"grow: team {t} hung")
                return report
            epochs = set()
            for i, g in grows.items():
                if g.test() != Status.OK:
                    report["violations"].append(
                        f"grow: team {t} rank {i} failed {g.test().name}")
                    return report
                epochs.add(g.epoch)
            if join.test() != Status.OK:
                report["violations"].append(
                    f"grow: team {t} join failed {join.test().name}")
                return report
            epochs.add(join.epoch)
            if len(epochs) > 1:
                report["violations"].append(
                    f"grow: team {t} epochs diverged {epochs}")
                return report
            report["grown_epochs"][f"t{t}"] = next(iter(epochs))
            nxt = {i: grows[i].new_team for i in survivors}
            nxt[kill_rank] = join.new_team
            grown.append(nxt)
            all_teams.extend(nxt.values())

        # -- phase 5: post-recovery traffic + inversion probe ---------
        for _ in range(post_rounds):
            before = len(report["violations"])
            hi_lats.extend(_mixed_round(grown, "post", check=True))
            if len(report["violations"]) == before:
                report["post_rounds_ok"] += 1
        if hi_lats:
            arr = sorted(hi_lats)
            report["hi_probe_ms"] = {
                "n": len(arr),
                "p50": round(arr[len(arr) // 2], 3),
                "max": round(arr[-1], 3)}
        report["fused_batches"] = sum(
            getattr(tm.coalescer, "_fused_seq", 0)
            for per in grown for tm in per.values()
            if getattr(tm, "coalescer", None) is not None)
        # priority-inversion probe: the lanes' own counters. Inversions
        # are recorded (timing-dependent, not a hard failure); actual
        # starvation — a queued task aged past 1s — is a violation.
        inv, starve = 0, 0.0
        for i, c in enumerate(ctxs):
            try:
                snap = c.progress_queue.qos_snapshot()
            except Exception:  # noqa: BLE001 - probe is observational
                continue
            report["qos"][f"ctx{i}"] = snap
            inv += snap.get("inversions", 0)
            starve = max(starve, snap.get("starvation_max_ms", 0.0))
        report["priority_inversions"] = inv
        report["starvation_max_ms"] = round(starve, 3)
        if starve > 1000.0:
            report["violations"].append(
                f"priority lanes starved a task for {starve:.0f}ms")
    finally:
        report["injected"] = dict(inject.COUNTS)
        inject.reset()
        health.configure(prev_mode, interval=prev_int, timeout=prev_to)
        _coal.configure(enabled=prev_coal[0], limit=prev_coal[1],
                        window_us=prev_coal[2], max_batch=prev_coal[3])
        for tm in all_teams:
            try:
                tm.destroy()
            except Exception:  # noqa: BLE001
                pass
        for c in ctxs:
            try:
                c.destroy()
            except Exception:  # noqa: BLE001
                pass
    return report


def _probe_stale_plan_fence(old_team, report) -> None:
    """Native-plan twin of ``_probe_stale_send_fence``: build a one-op
    plan keyed to the OLD (fenced) epoch and post it — the C executor's
    push must be discarded at the match boundary with the plan counting
    the fenced send (no hang, ``n_fenced`` ticks)."""
    from ..tl.host.transport import InProcTransport
    for team_key, tr in old_team._tl_tag_spaces():
        if not isinstance(tr, InProcTransport):
            continue
        try:
            from ..dsl.plan import stale_fence_probe
            before = tr.n_fenced
            ok = stale_fence_probe(tr, team_key)
        except Exception as e:  # noqa: BLE001 - the probe itself failing
            # is a violation (it means plans cannot run on this matcher)
            report["plan_stale_fenced"] = False
            report["violations"].append(f"plan fence probe raised: {e}")
            return
        report["plan_stale_fenced"] = ok
        if ok:
            report["plan_fenced_counter"] = tr.n_fenced - before
        return
    report["plan_stale_fenced"] = None


def _probe_stale_send_fence(old_team, report) -> None:
    """Post a send into the OLD (fenced) epoch of a shrunk team and
    assert it is discarded at the matching boundary: the send completes
    (the sender must not wait forever) and the endpoint's ``n_fenced``
    counter ticks. Records which matcher handled it."""
    import numpy as np
    from ..tl.host.transport import InProcTransport
    for team_key, tr in old_team._tl_tag_spaces():
        # select loopback-capable endpoints BY TYPE: catching TypeError
        # around the send itself would also swallow a TypeError from the
        # native key-packing/push path this probe exists to regression-
        # test (socket TL endpoints have a different send_nb signature)
        if not isinstance(tr, InProcTransport):
            continue
        before = tr.n_fenced
        # epoch 0 is the pre-shrink tag space; any coll tag/slot works
        key = (team_key, 0, (1 << 20) + 1, 999, 0)
        req = tr.send_nb(tr, key, np.ones(8, np.uint8))
        ok = bool(req.test()) and tr.n_fenced == before + 1
        report["stale_send_fenced"] = ok
        report["matcher"] = ("native"
                             if getattr(tr, "native", None) is not None
                             else "python")
        if not ok:
            report["violations"].append(
                "stale pre-shrink send was not fenced "
                f"(n_fenced {before} -> {tr.n_fenced})")
        return
    report["stale_send_fenced"] = None


def _drive_iter(ctxs, teams, coll, n, count, bufs, deadline_s, report,
                phase, rank_labels, check=False):
    """Post one matrix collective on every team member, drive to
    terminal, record outcomes; flags hangs and (optionally) failures as
    violations."""
    import numpy as np
    from ucc_tpu import Status
    reqs = [t.collective_init(_coll_args(coll, r, n, count, bufs, 0.0))
            for r, t in enumerate(teams)]
    for rq in reqs:
        rq.post()
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        for c in ctxs:
            c.progress()
        # poll EVERY request each pass (list, not a short-circuiting
        # all()): in UCC_INTEGRITY=verify the sampled attestation digest
        # exchange is driven from each request's own test(), so skipping
        # the tail would starve the exchange until its abandon timeout
        sts = [rq.test() for rq in reqs]
        if all(st != Status.IN_PROGRESS for st in sts):
            break
    sts = [rq.test() for rq in reqs]
    for s in sts:
        key = f"{phase}:{s.name}"
        report["outcomes"][key] = report["outcomes"].get(key, 0) + 1
    stuck = [r for r, s in zip(rank_labels, sts) if s == Status.IN_PROGRESS]
    if stuck:
        report["violations"].append(
            f"{phase} iter {coll}: ranks {stuck} IN_PROGRESS past deadline")
        for r, rq in zip(rank_labels, reqs):
            if rq.test() == Status.IN_PROGRESS:
                rq.task.cancel(Status.ERR_TIMED_OUT)
    elif check:
        bad = [r for r, s in zip(rank_labels, sts) if s != Status.OK]
        if bad:
            report["violations"].append(
                f"{phase} iter {coll}: ranks {bad} failed "
                f"({[s.name for s in sts]})")
        elif coll == "allreduce":
            expected = sum(g + 1.0 for g in range(n))
            for g in range(n):
                got = bufs[g]["ar"]
                if not np.allclose(got, expected):
                    report["violations"].append(
                        f"{phase} iter {coll}: rank {g} wrong result "
                        f"{got[0]} != {expected}")
    for rq in reqs:
        try:
            rq.finalize()
        except Exception:  # noqa: BLE001
            pass


def main(argv=None) -> int:
    import argparse
    import json
    ap = argparse.ArgumentParser(prog="python -m ucc_tpu.fault.soak")
    ap.add_argument("--ranks", type=int, default=4)
    ap.add_argument("--iterations", type=int, default=200)
    ap.add_argument("--spec", default=_DEFAULT_SPEC)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--coll-timeout", type=float, default=0.5)
    ap.add_argument("--iter-deadline", type=float, default=10.0)
    ap.add_argument("--collect", action="store_true",
                    help="run the continuous telemetry collector during "
                    "the soak; the report gains a 'collector' section "
                    "(windows closed, flagged context ranks)")
    ap.add_argument("--kill-shrink", action="store_true",
                    help="run the kill+shrink recovery drill instead of "
                    "the probabilistic soak (UCC_FT=shrink pipeline)")
    ap.add_argument("--kill-rank", type=int, default=2)
    ap.add_argument("--post-iters", type=int, default=60)
    ap.add_argument("--churn", action="store_true",
                    help="run the elastic-membership churn drill: "
                    "interleaved kill->shrink->grow(rejoin) cycles with "
                    "collectives in flight on every epoch, a false-"
                    "suspicion re-admission round, and checked post-"
                    "churn collectives (UCC_FT=shrink + Team.grow)")
    ap.add_argument("--cycles", type=int, default=2,
                    help="with --churn: kill->shrink->grow cycles to run")
    ap.add_argument("--multi", action="store_true",
                    help="run the multi-tenant drill: N teams of mixed "
                    "priority share one progress engine (bulk tenants "
                    "coalescing), a rank is killed mid-traffic, every "
                    "team shrinks and grows the rank back, and the "
                    "priority-inversion/starvation counters are probed")
    ap.add_argument("--mt-teams", type=int, default=3,
                    help="with --multi: tenant teams (first is the "
                    "latency class)")
    ap.add_argument("--mt-rounds", type=int, default=5,
                    help="with --multi: mixed-traffic rounds per phase")
    ap.add_argument("--mt-burst", type=int, default=6,
                    help="with --multi: bulk posts per team-rank per "
                    "round")
    ap.add_argument("--corrupt", action="store_true",
                    help="run the corruption-storm integrity drill: one "
                    "rank corrupts every send (clean crc on the frame), "
                    "wire checksums must detect+attribute 100%% of "
                    "rounds, the strike ledger must quarantine the "
                    "corruptor within --strikes detections, and the "
                    "shrunk team must run a checked matrix "
                    "(UCC_INTEGRITY=verify + UCC_FT=shrink + native "
                    "plans)")
    ap.add_argument("--corrupt-rank", type=int, default=1,
                    help="with --corrupt: team rank that corrupts")
    ap.add_argument("--strikes", type=int, default=3,
                    help="with --corrupt: quarantine threshold "
                    "(UCC_INTEGRITY_STRIKES)")
    ap.add_argument("--procs", type=int, default=0,
                    help="run the CROSS-PROCESS kill+shrink drill: N OS "
                    "processes host --ranks ranks over one shared-memory "
                    "arena (UCC_TLS=ipc,self), the last process is "
                    "SIGKILLed whole, survivors must detect via the "
                    "arena pid board, agree, shrink and resume a "
                    "checked matrix")
    ap.add_argument("--plans", action="store_true",
                    help="with --kill-shrink: run the drill with the "
                    "allreduces forced onto NATIVE EXECUTION PLANS "
                    "(UCC_GEN_NATIVE=y, ring bridge) and assert "
                    "ucc_plan_cancel withdrew posted recvs and a "
                    "pre-shrink plan send is fenced")
    args = ap.parse_args(argv)
    if args.procs:
        report = run_procs_kill_shrink(
            n_procs=args.procs,
            ranks_per=max(1, args.ranks // args.procs),
            post_iters=args.post_iters)
        print(json.dumps(report, indent=1))
        return 1 if report["violations"] else 0
    if args.corrupt:
        report = run_corrupt_soak(args.ranks,
                                  corrupt_rank=args.corrupt_rank,
                                  strikes=args.strikes,
                                  post_iters=args.post_iters)
        print(json.dumps(report, indent=1))
        return 1 if report["violations"] else 0
    if args.multi:
        report = run_multi_tenant_soak(args.ranks, n_teams=args.mt_teams,
                                       rounds=args.mt_rounds,
                                       burst=args.mt_burst,
                                       post_rounds=args.mt_rounds,
                                       kill_rank=args.kill_rank)
        print(json.dumps(report, indent=1))
        return 1 if report["violations"] else 0
    if args.churn:
        report = run_churn_soak(args.ranks, cycles=args.cycles,
                                post_iters=args.post_iters,
                                plans=args.plans, collect=args.collect)
        print(json.dumps(report, indent=1))
        return 1 if report["violations"] else 0
    if args.kill_shrink:
        report = run_kill_shrink_soak(args.ranks, args.kill_rank,
                                      post_iters=args.post_iters,
                                      plans=args.plans)
        print(json.dumps(report, indent=1))
        return 1 if report["violations"] else 0
    report = run_soak(args.ranks, args.iterations, args.spec, args.seed,
                      args.coll_timeout, args.iter_deadline,
                      collect=args.collect)
    print(json.dumps(report, indent=1))
    return 1 if report["hangs"] else 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
