"""Peer liveness — detect and attribute rank failures.

The *detect → attribute* front of the rank-failure recovery pipeline
(detect → attribute → agree → shrink → resume; README "Rank failure &
recovery"). PR 2 bounded hangs (watchdog → cancel/abort) but left a dead
rank anonymous: survivors timed out with ``ERR_TIMED_OUT`` and nobody
learned *which* rank died. This module gives every context a
``HealthRegistry`` that converges on a named failed-rank set from four
evidence sources:

- **heartbeats**: each context stamps a process-visible liveness board
  every ``UCC_HEARTBEAT_INTERVAL`` seconds from its progress loop; a
  peer whose stamp goes stale past ``UCC_HEARTBEAT_TIMEOUT`` is declared
  failed. (The board is in-process state — the productized form of the
  thread-OOB test harness, matching the in-proc transport. Multi-process
  deployments lean on the remaining three sources.)
- **transport evidence**: a send/recv post targeting a known-dead
  context rank fails fast with ``ERR_RANK_FAILED`` instead of
  black-holing until the watchdog fires (tl/host/task.py).
- **watchdog escalation**: a hard-stalled task's outstanding recv peers
  are reported as suspects; a suspect whose heartbeat is also stale is
  confirmed failed (obs/watchdog.py ``_escalate``).
- **fault injection**: ``UCC_FAULT=kill=R`` ranks never beat (and are
  self-reported), so drills exercise exactly the production detection
  path.

Everything is COLD unless ``UCC_FT=shrink``: the progress queue guards
with ``health.ENABLED`` (module boolean, same zero-cost pattern as
``obs.metrics`` / ``fault.inject``), so the default ``UCC_FT=none`` path
is byte-identical to the seed.

On detection the registry cancels every in-flight task whose team
contains a failed rank with ``Status.ERR_RANK_FAILED`` (stamping
``task.failed_ranks`` for attribution), bumps the
``rank_failures_detected`` metric, and — when the watchdog is armed —
appends a ``rank_failed`` evidence line to the watchdog file so
``tools/tpu_probe.py`` / ``tools/snapshot_gate.py`` classify the run
``rank_failed(ranks=...)`` instead of ``hang``.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional, Set

from ..status import Status
from ..utils.log import get_logger
from . import inject

logger = get_logger("fault")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


#: recovery mode: "none" (default; zero-cost, seed behavior) or "shrink"
#: (liveness + agreement + Team.shrink available)
MODE: str = os.environ.get("UCC_FT", "none").strip().lower() or "none"
if MODE not in ("none", "shrink"):
    logger.warning("unknown UCC_FT mode %r; using 'none'", MODE)
    MODE = "none"
ENABLED: bool = MODE == "shrink"
HEARTBEAT_INTERVAL: float = _env_float("UCC_HEARTBEAT_INTERVAL", 0.05)
HEARTBEAT_TIMEOUT: float = _env_float("UCC_HEARTBEAT_TIMEOUT", 2.0)

#: process-visible liveness board: context uid -> last heartbeat
#: (time.monotonic). Contexts publish their own stamp; registries read
#: their peers'.
_BOARD: Dict[str, float] = {}
_BOARD_LOCK = threading.Lock()


def configure(mode: str = "none", interval: Optional[float] = None,
              timeout: Optional[float] = None) -> None:
    """Runtime (re)configuration (tests/embedders; env read at import)."""
    global MODE, ENABLED, HEARTBEAT_INTERVAL, HEARTBEAT_TIMEOUT
    mode = (mode or "none").strip().lower()
    if mode not in ("none", "shrink"):
        raise ValueError(f"UCC_FT mode must be none|shrink, got {mode!r}")
    MODE = mode
    ENABLED = mode == "shrink"
    if interval is not None:
        HEARTBEAT_INTERVAL = float(interval)
    if timeout is not None:
        HEARTBEAT_TIMEOUT = float(timeout)


def reset() -> None:
    """Disable and clear the board (tests)."""
    configure("none")
    with _BOARD_LOCK:
        _BOARD.clear()
    _STANDALONE_NOTED.clear()


#: ranks already attributed when no registry exists (UCC_FAULT=kill
#: drill without UCC_FT): keeps the fail-fast path's metric per-rank,
#: not per-send
_STANDALONE_NOTED: Set[int] = set()


def note_dead_target(ctx_rank: int, registry: Optional["HealthRegistry"],
                     source: str = "send", detail: str = "") -> None:
    """Attribution for a post that targeted a known-dead rank (the
    fail-fast path, tl/host/task.py). Idempotent per rank; routes
    through the registry when one exists."""
    if registry is not None:
        registry.report_failure(ctx_rank, source, detail)
        return
    ctx_rank = int(ctx_rank)
    if ctx_rank in _STANDALONE_NOTED:
        return
    _STANDALONE_NOTED.add(ctx_rank)
    logger.error("rank failure detected: ctx rank %d (source=%s%s)",
                 ctx_rank, source, f": {detail}" if detail else "")
    from ..obs import flight, metrics, watchdog
    if metrics.ENABLED:
        metrics.inc("rank_failures_detected", component="fault", alg=source)
    watchdog.note_rank_failure([ctx_rank], source, detail)
    # flight recorder: dump what this process can see with the failed
    # rank named — the "what was in flight when rank N died" record
    flight.on_rank_failure(ctx_rank, source, detail)


# ---------------------------------------------------------------------------
# per-context registry
# ---------------------------------------------------------------------------

class HealthRegistry:
    """Per-context failed/suspected rank bookkeeping. Attached as
    ``context.health`` when FT is enabled; fed from the context's
    progress loop (``check``), the fail-fast transport path
    (``report_failure``), and watchdog escalation (``suspect_task_peers``).
    """

    def __init__(self, context):
        self.context = context
        self.uid: str = context._ctx_uid
        #: failed ctx ranks -> {"source", "ts", "detail"}
        self.dead: Dict[int, Dict[str, Any]] = {}
        #: ctx rank -> suspicion count (watchdog reports not yet
        #: corroborated by a stale heartbeat)
        self.suspected: Dict[int, int] = {}
        self._peer_uids: Dict[int, str] = {}
        self._t0 = time.monotonic()
        self._last_beat = 0.0
        self._last_poll = 0.0
        self._lock = threading.Lock()
        #: external liveness oracles, ``fn(ctx_rank) -> Optional[bool]``
        #: (True = positively alive, False = conclusively dead, None =
        #: no verdict). The in-process heartbeat board cannot see peers
        #: in OTHER processes; a cross-process transport (tl/ipc arena
        #: pid board) registers a source here so process death is
        #: detected without waiting for a watchdog escalation.
        self._sources: list = []

    # -- wiring --------------------------------------------------------
    def add_liveness_source(self, fn) -> None:
        """Register a cross-process liveness oracle (see ``_sources``)."""
        self._sources.append(fn)

    def _source_verdict(self, ctx_rank: int) -> Optional[bool]:
        for fn in self._sources:
            try:
                v = fn(ctx_rank)
            except Exception:  # noqa: BLE001 - oracles are best-effort
                continue
            if v is not None:
                return v
        return None

    def set_peers(self, uids: Dict[int, str]) -> None:
        """ctx rank -> context uid, learned from the context OOB address
        exchange (core/context.py stuffs each context's uid into the
        exchanged payload)."""
        self._peer_uids = {int(r): u for r, u in uids.items() if u}

    # -- evidence ------------------------------------------------------
    def beat(self, now: Optional[float] = None) -> None:
        """Publish my liveness stamp. A fault-injection-killed rank
        stops beating — the drill-side simulation of process death."""
        if inject.ENABLED and inject.killed(self.context.rank):
            self.report_failure(self.context.rank, "inject",
                                "UCC_FAULT kill of this rank")
            return
        with _BOARD_LOCK:
            _BOARD[self.uid] = now if now is not None else time.monotonic()

    def poll(self, now: Optional[float] = None) -> Set[int]:
        """Check peer heartbeats; returns the set of NEWLY failed ctx
        ranks detected this scan."""
        now = now if now is not None else time.monotonic()
        newly: Set[int] = set()
        for rank, uid in self._peer_uids.items():
            if rank == self.context.rank or rank in self.dead:
                continue
            with _BOARD_LOCK:
                last = _BOARD.get(uid)
            if last is None:
                # never beaten HERE: the board is process-local, so a
                # healthy peer in ANOTHER process never appears on it —
                # abstain rather than condemn, unless a registered
                # cross-process source (tl/ipc arena pid board) returns
                # a conclusive death verdict
                if self._source_verdict(rank) is False:
                    if self.report_failure(
                            rank, "liveness",
                            "peer process dead (arena pid probe)"):
                        newly.add(rank)
                continue
            if now - last > HEARTBEAT_TIMEOUT:
                if self.report_failure(
                        rank, "heartbeat",
                        f"no heartbeat for {now - last:.3f}s "
                        f"(timeout {HEARTBEAT_TIMEOUT}s)"):
                    newly.add(rank)
        return newly

    def report_failure(self, ctx_rank: int, source: str,
                       detail: str = "") -> bool:
        """Mark *ctx_rank* failed. Idempotent: returns True only on the
        first report (which logs, counts ``rank_failures_detected``, and
        leaves watchdog-file evidence for CI classification)."""
        ctx_rank = int(ctx_rank)
        with self._lock:
            if ctx_rank in self.dead:
                return False
            self.dead[ctx_rank] = {"source": source, "detail": detail,
                                   "ts": time.time()}
            self.suspected.pop(ctx_rank, None)
        logger.error("rank failure detected: ctx rank %d (source=%s%s)",
                     ctx_rank, source, f": {detail}" if detail else "")
        from ..obs import flight, metrics, watchdog
        if metrics.ENABLED:
            metrics.inc("rank_failures_detected", component="fault",
                        alg=source)
        watchdog.note_rank_failure(sorted(self.dead), source, detail)
        flight.on_rank_failure(ctx_rank, source, detail)
        return True

    def suspect(self, ctx_rank: int, source: str = "watchdog",
                now: Optional[float] = None) -> bool:
        """A soft report (e.g. watchdog escalation naming a stuck recv
        peer): confirmed as failed only when the peer's heartbeat is
        ALSO stale — a slow-but-alive peer must not be declared dead by
        one stuck collective. Returns True when confirmed."""
        ctx_rank = int(ctx_rank)
        if ctx_rank in self.dead:
            return True
        now = now if now is not None else time.monotonic()
        uid = self._peer_uids.get(ctx_rank)
        with _BOARD_LOCK:
            last = _BOARD.get(uid) if uid else None
        # a peer that never beat on THIS process's board (cross-process
        # peer) cannot be condemned by staleness — suspicion only,
        # unless a cross-process source returns a death verdict
        if last is not None and now - last > HEARTBEAT_TIMEOUT:
            return self.report_failure(
                ctx_rank, source, "stalled task peer with stale heartbeat")
        if last is None and self._source_verdict(ctx_rank) is False:
            return self.report_failure(
                ctx_rank, source,
                "stalled task peer whose process is dead (arena pid probe)")
        with self._lock:
            self.suspected[ctx_rank] = self.suspected.get(ctx_rank, 0) + 1
        return False

    def suspect_task_peers(self, task, now: Optional[float] = None) -> None:
        """Watchdog-escalation attribution: report the task's outstanding
        recv peers as suspects (they are who the task is waiting on)."""
        reqs = getattr(task, "__dict__", {}).get("_obs_reqs") or ()
        ctx_of = getattr(task, "_ctx_of", None)
        if ctx_of is None:
            return
        for kind, peer, _slot, req in list(reqs):
            if kind != "recv" or req.test():
                continue
            try:
                self.suspect(ctx_of(peer), "watchdog", now)
            except Exception:  # noqa: BLE001 - attribution is best-effort
                pass

    # -- queries -------------------------------------------------------
    def is_dead(self, ctx_rank: int) -> bool:
        return ctx_rank in self.dead

    def dead_set(self) -> Set[int]:
        return set(self.dead)

    def is_fresh(self, ctx_rank: int, now: Optional[float] = None) -> bool:
        """Positive liveness evidence: the peer's heartbeat stamp is
        within ``HEARTBEAT_TIMEOUT``. Used by the agreement's round
        deadline to avoid mis-suspecting a slow-but-alive survivor
        (the PR-4 race). A peer that never beat on THIS process's board
        yields False — absence of evidence, not evidence of life."""
        uid = self._peer_uids.get(int(ctx_rank))
        if not uid:
            return False
        with _BOARD_LOCK:
            last = _BOARD.get(uid)
        if last is None:
            # cross-process peer: a registered source's recent arena
            # beat is the same positive evidence
            return self._source_verdict(int(ctx_rank)) is True
        now = now if now is not None else time.monotonic()
        return now - last <= HEARTBEAT_TIMEOUT

    # -- elastic membership --------------------------------------------
    def revive(self, ctx_rank: int, source: str = "grow",
               detail: str = "") -> bool:
        """Re-admit *ctx_rank*: clear it from the failed/suspected sets
        and refresh its board stamp (a grace period so the next poll
        scan does not instantly re-condemn a joiner whose progress loop
        has not beaten yet). The reverse transition of
        ``report_failure``; used by ``Team.grow`` / ``Team.join`` when
        membership agreement admits the rank back. Returns True when
        the rank was previously marked dead."""
        ctx_rank = int(ctx_rank)
        with self._lock:
            was = self.dead.pop(ctx_rank, None)
            self.suspected.pop(ctx_rank, None)
        # re-admission wipes the integrity strike ledger too: a rank
        # quarantined for corruption rejoins with a clean slate (its
        # first post-rejoin mismatch starts a fresh budget, it is not
        # instantly re-quarantined on stale strikes)
        from .. import integrity
        integrity.clear_strikes(self.context, ctx_rank)
        _STANDALONE_NOTED.discard(ctx_rank)
        uid = self._peer_uids.get(ctx_rank)
        if uid:
            with _BOARD_LOCK:
                _BOARD[uid] = time.monotonic()
        if was is not None:
            logger.warning(
                "ctx rank %d re-admitted (source=%s%s; was dead via %s)",
                ctx_rank, source, f": {detail}" if detail else "",
                was.get("source", "?"))
        return was is not None

    # -- progress hook -------------------------------------------------
    def check(self, queue, now: Optional[float] = None) -> None:
        """Called from the owning context's progress loop (under
        ``health.ENABLED``): beat, poll peers, and bound every in-flight
        task that depends on a failed rank."""
        now = now if now is not None else time.monotonic()
        if now - self._last_beat >= HEARTBEAT_INTERVAL:
            self._last_beat = now
            self.beat(now)
        if now - self._last_poll >= HEARTBEAT_INTERVAL:
            self._last_poll = now
            self.poll(now)
            if self.dead:
                self._cancel_dead_team_tasks(queue)

    def _cancel_dead_team_tasks(self, queue) -> None:
        """Cancel (ERR_RANK_FAILED) every queued task whose team contains
        a failed rank — run on every poll scan, not just the detection
        transition, so a collective posted AFTER detection on a
        not-yet-shrunk team is bounded too."""
        dead = self.dead_set()

        def failed_for(task):
            members = _team_member_ctx_ranks(task.team)
            return members & dead if members else None

        cancel_queued_tasks(queue, failed_for, Status.ERR_RANK_FAILED)


def cancel_queued_tasks(queue, failed_for, status) -> int:
    """Shared bound-the-damage loop (used by the health scan and by
    ``Team._cancel_in_flight``): cancel every live queued task for which
    ``failed_for(task)`` returns a non-empty set of failed CONTEXT
    ranks, stamping ``task.failed_ranks`` for attribution. Recovery
    traffic (agreement tasks routing AROUND the dead ranks) is exempt
    via ``task._ft_exempt``. Returns the number cancelled."""
    n = 0
    for task in list(getattr(queue, "_q", ())):
        if task.is_completed() or getattr(task, "_ft_exempt", False):
            continue
        failed = failed_for(task)
        if not failed:
            continue
        task.failed_ranks = sorted(int(r) for r in failed)
        logger.warning(
            "cancelling %s seq %d: depends on failed ctx rank(s) %s",
            type(task).__name__, task.seq_num, task.failed_ranks)
        task.cancel(status)
        n += 1
    return n


def _team_member_ctx_ranks(team) -> Optional[Set[int]]:
    """Member context ranks of a task's team (TL team or core team),
    cached on the core team — O(size) once, O(1) per scan."""
    if team is None:
        return None
    core = getattr(team, "core_team", team)
    cached = getattr(core, "_ft_member_ctx", None)
    if cached is not None:
        return cached
    ctx_map = getattr(core, "ctx_map", None)
    size = getattr(core, "size", 0)
    if ctx_map is None:
        members = set(range(size))
    else:
        try:
            members = {int(ctx_map.eval(i)) for i in range(size)}
        except Exception:  # noqa: BLE001 - facade teams may lack maps
            return None
    try:
        core._ft_member_ctx = members
    except Exception:  # noqa: BLE001 - frozen/slotted facade
        pass
    return members


# ---------------------------------------------------------------------------
# progress-queue hook — called under `if health.ENABLED:`
# ---------------------------------------------------------------------------

def check(queue) -> None:
    reg = getattr(queue, "_ft_health", None)
    if reg is not None:
        reg.check(queue)
