"""Fault-tolerant agreement — survivors converge on (failed set, epoch).

The *agree* step of the recovery pipeline (detect → attribute → agree →
shrink → resume): before a team can shrink, every surviving rank must
adopt the SAME failed-rank set and recovery epoch, or the rebuilt teams
diverge in membership and deadlock their first collective — the exact
failure class PR 1's ``_cl_agree_step`` empty-set fix closed for CL
creation. Unlike that step's OOB allgather, this one must run while some
members are DEAD, so it routes around them: a simplified, ULFM-agreement-
shaped protocol over the service team's transport.

Protocol (rounds in lockstep, slot = round):

1. Each participant sends its current view ``(dead set, epoch)`` to
   every rank it believes alive, and posts recvs from the same set.
2. Arriving views are unioned in; a peer that becomes known-dead
   mid-round (named by another view, fail-fast ERR_RANK_FAILED on the
   post, or round-deadline expiry) has its pending recv cancelled and
   joins the dead set.
3. A round where every received view equals the sender's own view
   terminates the protocol. Termination is symmetric: if any rank
   observes all-equal(S), every survivor sent S that round, so every
   survivor observes all-equal(S) and stops at the same round. A
   non-terminal round grows someone's set, and sets are bounded by the
   team size, so the protocol converges in <= size+2 rounds absent new
   failures.
4. The agreed epoch is ``max(all exchanged epochs) + 1`` — identical
   everywhere because the exchanged views are identical.

Known limitation (documented, not hidden): a rank that dies *between* a
peer's termination and another peer's round-deadline can make the
late peer suspect the already-terminated one. Full ULFM agreement
(ERA) layers a coordinator to close this; here the round deadline is
sized well above the heartbeat timeout so detection almost always
precedes agreement, and a mis-suspected survivor is excluded (shrunk
away), never deadlocked — the bounded-outcome invariant holds.
"""
from __future__ import annotations

import time
from typing import Iterable, Optional, Set

import numpy as np

from ..status import RankFailedError, Status, UccError
from ..tl.host.task import HostCollTask
from ..utils.log import get_logger
from . import health

logger = get_logger("fault")

#: slot base for agreement rounds: far above any algorithm's round slots
#: (they top out in the hundreds) so a tuple-tagged agreement can never
#: collide with service-collective traffic on the same team
_AGREE_SLOT_BASE = 7000


class FtAgreement(HostCollTask):
    """Agreement task posted on the (old) team's service TL team by every
    survivor. On success, ``result_dead`` holds the agreed failed set in
    TEAM ranks and ``result_epoch`` the agreed next epoch."""

    coll_name = "ft_agree"
    alg_name = "flood"

    #: recovery traffic must not be cancelled by the health scan for
    #: depending on a team with dead members — routing around them is
    #: its entire job
    _ft_exempt = True

    def __init__(self, service_team, local_dead: Iterable[int],
                 epoch: int, round_timeout_s: float = 0.0):
        super().__init__(None, service_team)
        self.local_dead: Set[int] = {int(r) for r in local_dead}
        self.base_epoch = int(epoch)
        # the round deadline is the last-resort failure detector for
        # peers dying mid-agreement; default: comfortably above the
        # heartbeat timeout so ordinary detection wins
        self.round_timeout_s = round_timeout_s or max(
            1.0, 4 * health.HEARTBEAT_TIMEOUT)
        self.tag = ("ftagree", self.base_epoch)
        self.result_dead: Optional[Set[int]] = None
        self.result_epoch: Optional[int] = None

    # ------------------------------------------------------------------
    def _pack(self, dead: Set[int], epoch: int) -> np.ndarray:
        buf = np.full(self.gsize + 2, -1, dtype=np.int64)
        buf[0] = len(dead)
        buf[1] = epoch
        for i, r in enumerate(sorted(dead)):
            buf[2 + i] = r
        return buf

    @staticmethod
    def _unpack(buf: np.ndarray):
        n = int(buf[0])
        return {int(r) for r in buf[2:2 + n]}, int(buf[1])

    def run(self):
        size, me = self.gsize, self.grank
        my: Set[int] = set(self.local_dead)
        my.discard(me)
        epoch = self.base_epoch
        for rnd in range(size + 2):
            sent = frozenset(my)
            alive = [p for p in range(size) if p != me and p not in my]
            if not alive:
                break   # sole survivor: my view is the agreement
            payload = self._pack(my, epoch)
            rbufs = {}
            rreqs = {}
            for p in list(alive):
                try:
                    rbufs[p] = np.full(size + 2, -1, dtype=np.int64)
                    rreqs[p] = self.recv_nb(p, rbufs[p],
                                            slot=_AGREE_SLOT_BASE + rnd)
                    self.send_nb(p, payload, slot=_AGREE_SLOT_BASE + rnd)
                except RankFailedError:
                    # fail-fast attribution fired between the alive
                    # computation and the post: adopt it (in TEAM ranks —
                    # the exception carries ctx ranks) and route on
                    my.add(p)
                    req = rreqs.pop(p, None)
                    if req is not None:
                        req.cancel()
                    rbufs.pop(p, None)
            got = {}
            deadline = time.monotonic() + self.round_timeout_s
            while rreqs:
                yield
                for p, rq in list(rreqs.items()):
                    if p in my:
                        # named dead by an arrived view mid-round
                        rq.cancel()
                        del rreqs[p]
                        continue
                    if not rq.test():
                        continue
                    del rreqs[p]
                    if getattr(rq, "error", None):
                        my.add(p)   # errored delivery = failed peer
                        continue
                    peer_dead, peer_epoch = self._unpack(rbufs[p])
                    got[p] = peer_dead
                    epoch = max(epoch, peer_epoch)
                    my |= peer_dead
                    my.discard(me)
                if rreqs and time.monotonic() > deadline:
                    # last-resort detector: unresponsive peers are
                    # suspected dead (see module docstring limitation)
                    for p, rq in list(rreqs.items()):
                        logger.warning(
                            "ft agreement round %d: rank %d unresponsive "
                            "past %.1fs; suspecting it failed", rnd, p,
                            self.round_timeout_s)
                        my.add(p)
                        rq.cancel()
                        del rreqs[p]
            if my == sent and all(v == sent for p, v in got.items()
                                  if p not in my):
                self.result_dead = set(my)
                self.result_epoch = epoch + 1
                logger.info(
                    "ft agreement converged in %d round(s): dead=%s "
                    "epoch=%d", rnd + 1, sorted(my), self.result_epoch)
                return
        if len(my) >= size - 1:
            # everyone else is (believed) dead; trivially agreed
            self.result_dead = set(my)
            self.result_epoch = epoch + 1
            return
        raise UccError(Status.ERR_TIMED_OUT,
                       "ft agreement did not converge")
