"""Fault-tolerant agreement — survivors converge on (failed set, epoch).

The *agree* step of the recovery pipeline (detect → attribute → agree →
shrink → resume): before a team can shrink, every surviving rank must
adopt the SAME failed-rank set and recovery epoch, or the rebuilt teams
diverge in membership and deadlock their first collective — the exact
failure class PR 1's ``_cl_agree_step`` empty-set fix closed for CL
creation. Unlike that step's OOB allgather, this one must run while some
members are DEAD, so it routes around them: a simplified, ULFM-agreement-
shaped protocol over the service team's transport.

Protocol (rounds in lockstep, slot = round):

1. Each participant sends its current view ``(dead set, epoch)`` to
   every rank it believes alive, and posts recvs from the same set.
2. Arriving views are unioned in; a peer that becomes known-dead
   mid-round (named by another view, fail-fast ERR_RANK_FAILED on the
   post, or round-deadline expiry) has its pending recv cancelled and
   joins the dead set.
3. A round where every received view equals the sender's own view
   terminates the protocol. Termination is symmetric: if any rank
   observes all-equal(S), every survivor sent S that round, so every
   survivor observes all-equal(S) and stops at the same round. A
   non-terminal round grows someone's set, and sets are bounded by the
   team size, so the protocol converges in <= size+2 rounds absent new
   failures.
4. The agreed epoch is ``max(all exchanged epochs) + 1`` — identical
   everywhere because the exchanged views are identical.

Elastic extension (PR 17): views carry an *admit* proposal alongside the
dead set — ``(dead set, admit set, epoch)`` — so the same protocol that
agrees on who left also agrees on who JOINS (``Team.grow``). Admit sets
union exactly like dead sets and termination requires all-equal on both,
so every survivor adopts the same (dead, admit, epoch) triple.

The PR-4 mis-suspicion race — a slow-but-alive survivor whose agreement
sends land after a peer's round deadline was condemned and excluded —
is now folded against fresh health evidence: at deadline expiry a
pending peer whose heartbeat stamp is FRESH (``HealthRegistry.is_fresh``)
is granted up to ``UCC_FT_AGREE_GRACE`` deadline extensions instead of
being suspected; only heartbeat-stale peers are condemned immediately.
Suspicion stays monotone (a rank once added to the dead view is never
removed — un-suspecting would break the all-equal convergence
argument), so the fix is purely about *not adding* a rank the local
failure detector can still vouch for. When exclusion happens anyway
(grace exhausted, cross-process peer with no board stamp), the recovery
path is grow-based re-admission: the excluded survivor rejoins through
``Team.join`` on the next epoch.
"""
from __future__ import annotations

import os
import time
from typing import Iterable, Optional, Set

import numpy as np

from ..status import RankFailedError, Status, UccError
from ..tl.host.task import HostCollTask
from ..utils.log import get_logger
from . import health

logger = get_logger("fault")

#: slot base for agreement rounds: far above any algorithm's round slots
#: (they top out in the hundreds) so a tuple-tagged agreement can never
#: collide with service-collective traffic on the same team
_AGREE_SLOT_BASE = 7000

#: wire-format capacity for admit proposals: a fixed slab so every
#: participant computes the same buffer size without negotiating it
#: (grow batches are small — a handful of joiners per epoch, never a
#: team's worth)
_ADMIT_CAP = 32


def _agree_grace() -> int:
    """Max round-deadline extensions granted to a heartbeat-fresh peer
    before the last-resort suspicion fires anyway (``UCC_FT_AGREE_GRACE``,
    bounded so a wedged-but-beating process cannot stall agreement
    forever)."""
    try:
        return max(0, int(os.environ.get("UCC_FT_AGREE_GRACE", "") or 3))
    except ValueError:
        return 3


class FtAgreement(HostCollTask):
    """Agreement task posted on the (old) team's service TL team by every
    survivor. On success, ``result_dead`` holds the agreed failed set in
    TEAM ranks, ``result_admit`` the agreed joiner set in CONTEXT ranks
    (empty for plain shrink agreement), and ``result_epoch`` the agreed
    next epoch."""

    coll_name = "ft_agree"
    alg_name = "flood"

    #: recovery traffic must not be cancelled by the health scan for
    #: depending on a team with dead members — routing around them is
    #: its entire job
    _ft_exempt = True

    def __init__(self, service_team, local_dead: Iterable[int],
                 epoch: int, round_timeout_s: float = 0.0,
                 proposal: Optional[Iterable[int]] = None,
                 kind: str = "shrink"):
        super().__init__(None, service_team)
        self.local_dead: Set[int] = {int(r) for r in local_dead}
        #: ctx ranks proposed for admission (grow); capped by the wire
        #: format — a batch this large is a topology change, not a grow
        self.local_admit: Set[int] = {int(r) for r in (proposal or ())}
        if len(self.local_admit) > _ADMIT_CAP:
            raise UccError(
                Status.ERR_NOT_SUPPORTED,
                f"grow proposal of {len(self.local_admit)} joiners "
                f"exceeds the agreement wire capacity ({_ADMIT_CAP})")
        self.kind = kind
        self.base_epoch = int(epoch)
        # the round deadline is the last-resort failure detector for
        # peers dying mid-agreement; default: comfortably above the
        # heartbeat timeout so ordinary detection wins
        self.round_timeout_s = round_timeout_s or max(
            1.0, 4 * health.HEARTBEAT_TIMEOUT)
        # kind scopes the tag so a shrink and a grow agreement on the
        # same base epoch can never cross-match
        self.tag = ("ftagree", kind, self.base_epoch)
        self.result_dead: Optional[Set[int]] = None
        self.result_admit: Optional[Set[int]] = None
        self.result_epoch: Optional[int] = None

    # ------------------------------------------------------------------
    # wire format (int64): [n_dead, epoch, dead padded to gsize,
    #                       n_admit, admit padded to _ADMIT_CAP]
    def _buf_len(self) -> int:
        return self.gsize + 3 + _ADMIT_CAP

    def _pack(self, dead: Set[int], admit: Set[int],
              epoch: int) -> np.ndarray:
        buf = np.full(self._buf_len(), -1, dtype=np.int64)
        buf[0] = len(dead)
        buf[1] = epoch
        for i, r in enumerate(sorted(dead)):
            buf[2 + i] = r
        base = 2 + self.gsize
        buf[base] = len(admit)
        for i, r in enumerate(sorted(admit)):
            buf[base + 1 + i] = r
        return buf

    def _unpack(self, buf: np.ndarray):
        n = int(buf[0])
        base = 2 + self.gsize
        na = int(buf[base])
        dead = {int(r) for r in buf[2:2 + n]}
        admit = {int(r) for r in buf[base + 1:base + 1 + na]}
        return dead, admit, int(buf[1])

    def _is_fresh(self, peer_grank: int) -> bool:
        """Fresh-heartbeat check for the round-deadline race fix; False
        when no registry is wired (UCC_FT off) or no evidence exists."""
        reg = self._health_registry()
        if reg is None:
            return False
        try:
            return reg.is_fresh(self._ctx_of(peer_grank))
        except Exception:  # noqa: BLE001 - liveness lookup is best-effort
            return False

    def run(self):
        size, me = self.gsize, self.grank
        my: Set[int] = set(self.local_dead)
        my.discard(me)
        admit: Set[int] = set(self.local_admit)
        epoch = self.base_epoch
        grace = _agree_grace()
        for rnd in range(size + 2):
            sent = (frozenset(my), frozenset(admit))
            alive = [p for p in range(size) if p != me and p not in my]
            if not alive:
                break   # sole survivor: my view is the agreement
            payload = self._pack(my, admit, epoch)
            rbufs = {}
            rreqs = {}
            for p in list(alive):
                try:
                    rbufs[p] = np.full(self._buf_len(), -1, dtype=np.int64)
                    rreqs[p] = self.recv_nb(p, rbufs[p],
                                            slot=_AGREE_SLOT_BASE + rnd)
                    self.send_nb(p, payload, slot=_AGREE_SLOT_BASE + rnd)
                except RankFailedError:
                    # fail-fast attribution fired between the alive
                    # computation and the post: adopt it (in TEAM ranks —
                    # the exception carries ctx ranks) and route on
                    my.add(p)
                    req = rreqs.pop(p, None)
                    if req is not None:
                        req.cancel()
                    rbufs.pop(p, None)
            got = {}
            deadline = time.monotonic() + self.round_timeout_s
            extensions = grace
            while rreqs:
                yield
                for p, rq in list(rreqs.items()):
                    if p in my:
                        # named dead by an arrived view mid-round
                        rq.cancel()
                        del rreqs[p]
                        continue
                    if not rq.test():
                        continue
                    del rreqs[p]
                    if getattr(rq, "error", None):
                        my.add(p)   # errored delivery = failed peer
                        continue
                    peer_dead, peer_admit, peer_epoch = \
                        self._unpack(rbufs[p])
                    got[p] = (peer_dead, peer_admit)
                    epoch = max(epoch, peer_epoch)
                    my |= peer_dead
                    my.discard(me)
                    admit |= peer_admit
                if rreqs and time.monotonic() > deadline:
                    # last-resort detector, folded against fresh health
                    # evidence (the PR-4 race fix): a pending peer whose
                    # heartbeat is still fresh is granted a bounded
                    # deadline extension instead of being condemned —
                    # only heartbeat-stale peers are suspected outright
                    fresh = [p for p in rreqs if self._is_fresh(p)]
                    for p, rq in list(rreqs.items()):
                        if p in fresh and extensions > 0:
                            continue
                        logger.warning(
                            "ft agreement round %d: rank %d unresponsive "
                            "past %.1fs%s; suspecting it failed", rnd, p,
                            self.round_timeout_s,
                            " (grace exhausted)" if p in fresh else "")
                        my.add(p)
                        rq.cancel()
                        del rreqs[p]
                    if rreqs and extensions > 0:
                        extensions -= 1
                        deadline = time.monotonic() + self.round_timeout_s
                        logger.info(
                            "ft agreement round %d: extending deadline "
                            "for heartbeat-fresh rank(s) %s (%d grace "
                            "extension(s) left)", rnd, sorted(rreqs),
                            extensions)
            if sent == (frozenset(my), frozenset(admit)) and all(
                    v == sent for p, v in got.items() if p not in my):
                self.result_dead = set(my)
                self.result_admit = set(admit)
                self.result_epoch = epoch + 1
                logger.info(
                    "ft agreement converged in %d round(s): dead=%s "
                    "admit=%s epoch=%d", rnd + 1, sorted(my),
                    sorted(admit), self.result_epoch)
                return
        if len(my) >= size - 1:
            # everyone else is (believed) dead; trivially agreed
            self.result_dead = set(my)
            self.result_admit = set(admit)
            self.result_epoch = epoch + 1
            return
        raise UccError(Status.ERR_TIMED_OUT,
                       "ft agreement did not converge")
