"""TPU memory component — HBM-resident jax buffers.

Mirrors /root/reference/src/components/mc/cuda (cudaMalloc pools, pointer
attribute queries, async memcpy — mc_cuda.c / mc_cuda_resources.c) with the
JAX equivalents: device allocation is ``jax.device_put`` / ``jnp.empty`` on
a target device, memtype query inspects ``jax.Array`` placement, and
"memcpy" is host<->HBM staging. A small free-list pool of device buffers
keyed by (shape, dtype, device) plays the role of the reference's mpool-
backed cudaMalloc cache (scratch reuse without allocator round-trips —
on TPU this avoids repeated donation/defragmentation pressure).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..constants import MemoryType
from ..status import Status, UccError
from .base import MemAttr, MemoryComponent, register_mc


class McTpu(MemoryComponent):
    NAME = "tpu"
    MEM_TYPE = MemoryType.TPU

    def __init__(self, device=None):
        import jax
        self.jax = jax
        self.device = device
        self._pool: Dict[Tuple, List[Any]] = {}

    # ------------------------------------------------------------------
    def mem_query(self, obj: Any) -> Optional[MemAttr]:
        import jax
        if isinstance(obj, jax.Array):
            return MemAttr(MemoryType.TPU, base=obj, size=obj.nbytes)
        return None

    def alloc(self, size_bytes: int, dtype=np.uint8, device=None) -> Any:
        """Returns UNINITIALIZED memory (like cudaMalloc): recycled pool
        buffers keep their previous contents, and the cold path makes no
        zeroing promise either — callers must not rely on zeroed data."""
        import jax.numpy as jnp
        nd = np.dtype(dtype)
        count = size_bytes // nd.itemsize
        # normalize to a concrete device so alloc/free pool keys agree
        dev = device or self.device or self.jax.devices()[0]
        key = (count, nd.str, dev)
        pool = self._pool.get(key)
        if pool:
            return pool.pop()
        arr = jnp.empty((count,), dtype=nd)
        return self.jax.device_put(arr, dev)

    def free(self, buf: Any) -> None:
        if buf is None:
            return
        devs = list(buf.devices())
        key = (int(np.prod(buf.shape)), np.dtype(buf.dtype).str,
               devs[0] if len(devs) == 1 else None)
        self._pool.setdefault(key, []).append(buf)

    def memcpy(self, dst: Any, src: Any, size_bytes: int) -> Any:
        """Byte semantics matching McCpu: exactly size_bytes move, landing
        in dst's shape/dtype. jax.Arrays are immutable, so device
        destinations return the new array (caller rebinds); host
        destinations are filled in place.

        Device destinations never round-trip the DESTINATION through host:
        - full-buffer copy, same dtype: one device_put (D2D when src is on
          another device, H2D when src is host memory);
        - partial copy: the kept tail is sliced on device and concatenated
          with the incoming prefix there (bitcast to bytes), so only the
          src prefix ever crosses host<->device."""
        import jax
        import jax.numpy as jnp
        if isinstance(dst, np.ndarray):
            host = np.asarray(src).reshape(-1).view(np.uint8)[:size_bytes]
            dst.reshape(-1).view(np.uint8)[:size_bytes] = host
            return dst
        dev = list(dst.devices())[0] if isinstance(dst, jax.Array) else \
            self.device
        if size_bytes >= dst.nbytes and np.dtype(src.dtype) == \
                np.dtype(dst.dtype):
            flat = src if isinstance(src, jax.Array) else jnp.asarray(src)
            flat = jnp.ravel(flat)[:dst.size]
            return jax.device_put(flat.reshape(dst.shape), dev)
        esz = np.dtype(dst.dtype).itemsize
        if size_bytes % esz == 0 and np.dtype(src.dtype) == \
                np.dtype(dst.dtype):
            k = size_bytes // esz
            prefix = jax.device_put(jnp.ravel(
                src if isinstance(src, jax.Array) else jnp.asarray(src))[:k],
                dev)
            tail = jnp.ravel(dst)[k:]          # stays on device
            out = jnp.concatenate([prefix, tail]) if tail.size else prefix
            return out.reshape(dst.shape)
        # odd byte counts: host staging fallback (rare; sub-element copy)
        dst_host = np.array(dst).reshape(-1)
        src_u8 = np.asarray(src).reshape(-1).view(np.uint8)[:size_bytes]
        dst_host.view(np.uint8)[:size_bytes] = src_u8
        return jax.device_put(dst_host.reshape(dst.shape), dev)

    def memset(self, buf: Any, value: int, size_bytes: int) -> Any:
        import jax
        import jax.numpy as jnp
        if isinstance(buf, np.ndarray):
            buf.reshape(-1).view(np.uint8)[:size_bytes] = value
            return buf
        dev = list(buf.devices())[0]
        esz = np.dtype(buf.dtype).itemsize
        if size_bytes % esz == 0:
            # replicate the byte across one element host-side (esz bytes),
            # then fill/concatenate ON DEVICE — no buffer-sized transfer
            pat = np.frombuffer(bytes([value & 0xFF]) * esz,
                                dtype=buf.dtype)[0]
            k = size_bytes // esz
            filled = jnp.full((k,), pat, dtype=buf.dtype)
            tail = jnp.ravel(buf)[k:]
            out = jnp.concatenate([jax.device_put(filled, dev), tail]) \
                if tail.size else jax.device_put(filled, dev)
            return out.reshape(buf.shape)
        host = np.array(buf).reshape(-1)
        host.view(np.uint8)[:size_bytes] = value
        return jax.device_put(host.reshape(buf.shape), dev)

    def flush(self) -> None:
        self._pool.clear()


register_mc(McTpu())
