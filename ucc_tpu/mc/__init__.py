"""Memory components: dispatch (base), host (cpu), device (tpu), and the
host scratch mpool (pool) — importing the pool here registers its
``UCC_MC_POOL_*`` config table for ``ucc_info -cf``."""
from . import pool  # noqa: F401 - registers MC_POOL_CONFIG
from .pool import HostMemPool, ScratchLease, host_pool  # noqa: F401
