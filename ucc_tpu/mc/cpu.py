"""Host memory component (reference: src/components/mc/cpu, 255 LoC —
malloc-backed alloc + host memcpy/memset)."""
from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ..constants import MemoryType
from .base import MemAttr, MemoryComponent


def _as_u8(buf: Any) -> np.ndarray:
    """View any buffer-protocol object / ndarray as a flat uint8 array."""
    if isinstance(buf, np.ndarray):
        return buf.reshape(-1).view(np.uint8)
    return np.frombuffer(buf, dtype=np.uint8)


class McCpu(MemoryComponent):
    NAME = "cpu"
    MEM_TYPE = MemoryType.HOST

    def mem_query(self, obj: Any) -> Optional[MemAttr]:
        if isinstance(obj, (np.ndarray, bytes, bytearray, memoryview)):
            nb = obj.nbytes if isinstance(obj, np.ndarray) else len(obj)
            return MemAttr(MemoryType.HOST, base=obj, size=nb)
        return None

    def alloc(self, size_bytes: int) -> np.ndarray:
        return np.empty(size_bytes, dtype=np.uint8)

    def memcpy(self, dst: Any, src: Any, size_bytes: int) -> None:
        _as_u8(dst)[:size_bytes] = _as_u8(src)[:size_bytes]

    def memset(self, buf: Any, value: int, size_bytes: int) -> None:
        _as_u8(buf)[:size_bytes] = value
