"""Host scratch-buffer mpool — the hot-path memory component.

Models the reference's ``ucc_mc_cpu`` mpool (mc/cpu/mc_cpu.c:23-38:
``ucc_mc_cpu_config_table`` MPOOL_ELEM_SIZE / MPOOL_MAX_ELEMS backing
``ucc_mpool_get``-served scratch for every TL): collective algorithms
must not pay a fresh allocation on every post. Here the pool is
size-classed — power-of-two buckets of raw ``uint8`` arrays kept on
per-class free lists — and algorithms consume it through
:class:`ScratchLease`, a per-task set of leased buffers keyed by call
site that is returned to the pool when the task is finalized
(task-lifetime return, the ``ucc_mpool_put`` at task cleanup).

Why it matters: per-post ``np.empty`` + page-faulting fresh memory
dominates small/medium collective latency on the host TLs, and a
persistent collective (init once, post many) otherwise re-allocates
identical scratch every single post. With the pool, a steady-state
persistent loop performs ZERO allocations: the first post leases
(misses), every later post reuses the same lease without touching the
pool at all, and the lease outlives ``PipelinedSchedule`` fragment
retargeting so one fragment scratch set serves the whole window.

Knobs (``ucc_info -cf``; env wins over ``UCC_CONFIG_FILE``):

- ``UCC_MC_POOL_ENABLE`` (y): pooling on/off — off means every lease is
  a direct allocation (every ``get`` a miss). ``UCC_MC_POOL=n`` is an
  accepted shorthand.
- ``UCC_MC_POOL_MAX_ELEM_SIZE`` (64M): largest pooled bucket; bigger
  requests allocate directly and are never cached.
- ``UCC_MC_POOL_MAX_ELEMS`` (8): free-list cap per size class
  (reference MPOOL_MAX_ELEMS).
- ``UCC_MC_POOL_MAX_BYTES`` (256M): total cached-bytes cap across all
  classes; returns beyond it are dropped to the allocator.

Metrics: ``mc_pool_hit`` / ``mc_pool_miss`` counters and the
``mc_pool_bytes`` cached-bytes gauge (component ``mc``) when
``UCC_STATS`` is on; :meth:`HostMemPool.stats` exposes the same numbers
unconditionally so benchmarks and allocation-regression tests need no
stats file.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from ..obs import metrics
from ..utils.config import (Config, ConfigField, ConfigTable, parse_bool,
                            parse_memunits, parse_uint, register_table)

MC_POOL_CONFIG = register_table(ConfigTable(
    prefix="MC_POOL_", name="mc/pool", fields=[
        ConfigField("ENABLE", "y", "size-classed scratch mpool for host "
                    "collectives (reference ucc_mc_cpu mpool); off = every "
                    "scratch lease is a direct allocation. UCC_MC_POOL=n "
                    "is an accepted shorthand", parse_bool),
        ConfigField("MAX_ELEM_SIZE", "64M", "largest pooled bucket; bigger "
                    "requests bypass the pool (never cached)",
                    parse_memunits),
        ConfigField("MAX_ELEMS", "8", "free-list cap per size class "
                    "(reference MPOOL_MAX_ELEMS)", parse_uint),
        ConfigField("MAX_BYTES", "256M", "total cached-bytes cap across "
                    "all size classes", parse_memunits),
    ]))

#: buckets never go below this (keeps the class table small and lets a
#: tiny follow-up request reuse a prior tiny lease)
_MIN_BUCKET = 64


class HostMemPool:
    """Size-classed free-list pool of raw ``uint8`` arrays.

    ``get(nbytes)`` returns an array whose capacity is the smallest
    power-of-two bucket >= nbytes; ``put`` must receive that same
    array (not a view) and files it back on its class free list.
    """

    def __init__(self, enable: bool = True,
                 max_elem_size: int = 64 << 20,
                 max_elems: int = 8,
                 max_bytes: int = 256 << 20):
        self.enable = enable
        self.max_elem_size = int(max_elem_size)
        self.max_elems = int(max_elems)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._classes: Dict[int, List[np.ndarray]] = {}
        self.hits = 0
        self.misses = 0
        self.cached_bytes = 0
        self.leased = 0          # live leases (get - put), diagnostic only

    # ------------------------------------------------------------------
    @staticmethod
    def _bucket(nbytes: int) -> int:
        return max(_MIN_BUCKET, 1 << max(0, int(nbytes - 1).bit_length()))

    def get(self, nbytes: int) -> np.ndarray:
        nbytes = max(1, int(nbytes))
        buf = None
        hit = False
        # admission is by BUCKET capacity, matching put(): a request whose
        # bucket rounds past max_elem_size must go direct, or every lease
        # in (bucket/2, max_elem_size] would miss forever (get would hand
        # out a bucket put() refuses to cache)
        cap = self._bucket(nbytes)
        if self.enable and cap <= self.max_elem_size:
            with self._lock:
                lst = self._classes.get(cap)
                if lst:
                    buf = lst.pop()
                    self.cached_bytes -= cap
                    self.hits += 1
                    hit = True
                else:
                    self.misses += 1
                self.leased += 1
            if buf is None:
                buf = np.empty(cap, dtype=np.uint8)
        else:
            with self._lock:
                self.misses += 1
                self.leased += 1
            buf = np.empty(nbytes, dtype=np.uint8)
        if metrics.ENABLED:
            metrics.inc("mc_pool_hit" if hit else "mc_pool_miss",
                        component="mc")
        return buf

    def put(self, buf: np.ndarray) -> None:
        cap = int(buf.nbytes)
        with self._lock:
            self.leased = max(0, self.leased - 1)
            if (self.enable and cap <= self.max_elem_size and
                    cap == self._bucket(cap)):
                lst = self._classes.setdefault(cap, [])
                if (len(lst) < self.max_elems and
                        self.cached_bytes + cap <= self.max_bytes):
                    lst.append(buf)
                    self.cached_bytes += cap
        if metrics.ENABLED:
            metrics.gauge("mc_pool_bytes", self.cached_bytes, component="mc")

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "cached_bytes": self.cached_bytes,
                    "cached_elems": sum(len(v)
                                        for v in self._classes.values()),
                    "leased": self.leased}

    def trim(self) -> None:
        """Drop every cached free-list element (tests / memory pressure)."""
        with self._lock:
            self._classes.clear()
            self.cached_bytes = 0

    def reset_stats(self) -> None:
        with self._lock:
            self.hits = 0
            self.misses = 0


class ScratchLease:
    """A task's set of pool-leased scratch buffers, keyed by call site.

    ``get(key, shape, dtype)`` returns a typed view of a leased buffer;
    the same key on a later call (persistent re-post, pipelined fragment
    restart) reuses the lease in place when its capacity still fits —
    zero pool traffic, zero allocation. ``release()`` files every buffer
    back to the pool (idempotent); the owning task calls it from
    ``finalize_fn`` so lease lifetime == task lifetime.
    """

    __slots__ = ("_pool", "_bufs")

    def __init__(self, pool: HostMemPool):
        self._pool = pool
        self._bufs: Dict[Any, np.ndarray] = {}

    def get(self, key: Any, shape, dtype) -> np.ndarray:
        nd = np.dtype(dtype) if not isinstance(dtype, np.dtype) else dtype
        if isinstance(shape, (int, np.integer)):
            shape = (int(shape),)
        count = 1
        for s in shape:
            count *= int(s)
        nbytes = count * nd.itemsize
        buf = self._bufs.get(key)
        if buf is None or buf.nbytes < nbytes:
            if buf is not None:
                self._pool.put(buf)
            buf = self._bufs[key] = self._pool.get(nbytes)
        return buf[:nbytes].view(nd).reshape(shape)

    def release(self) -> None:
        bufs, self._bufs = self._bufs, {}
        for buf in bufs.values():
            self._pool.put(buf)

    def __len__(self) -> int:
        return len(self._bufs)


# ---------------------------------------------------------------------------
# process-global pool (the MC/CPU component instance)
# ---------------------------------------------------------------------------

_global_pool: Optional[HostMemPool] = None
_global_lock = threading.Lock()


def _pool_from_env() -> HostMemPool:
    cfg = Config(MC_POOL_CONFIG)
    enable = bool(cfg.enable)
    shorthand = os.environ.get("UCC_MC_POOL", "").strip().lower()
    if shorthand:
        enable = shorthand not in ("0", "n", "no", "off", "false")
    return HostMemPool(enable=enable,
                       max_elem_size=cfg.max_elem_size,
                       max_elems=cfg.max_elems,
                       max_bytes=cfg.max_bytes)


def host_pool() -> HostMemPool:
    """The process-global host scratch pool (lazy, env-configured)."""
    global _global_pool
    pool = _global_pool
    if pool is None:
        with _global_lock:
            pool = _global_pool
            if pool is None:
                pool = _global_pool = _pool_from_env()
    return pool


def reset_host_pool(pool: Optional[HostMemPool] = None) -> None:
    """Swap/clear the global pool (tests; embedders with custom caps)."""
    global _global_pool
    with _global_lock:
        _global_pool = pool
