"""Memory components (MC).

Reference: /root/reference/src/components/mc/ — dispatch by memory type with
an ops vtable {mem_query, mem_alloc, mem_free, memcpy, memset, flush}
(mc/base/ucc_mc_base.h:104-113). MC is how ``collective_init`` auto-detects
buffer memory type (ucc_coll.c:25-36).

TPU mapping: MemoryType.HOST -> numpy/host DRAM (mc/cpu); MemoryType.TPU ->
jax.Array in HBM (mc/tpu). Detection must not import jax unless a non-host
object shows up, keeping the host path dependency-light.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np

from ..constants import MemoryType
from ..status import Status, UccError


@dataclass
class MemAttr:
    """ucc_mem_attr_t: memory type + base/size when resolvable."""

    mem_type: MemoryType
    base: Any = None
    size: int = 0


class MemoryComponent:
    NAME = "base"
    MEM_TYPE = MemoryType.UNKNOWN

    def mem_query(self, obj: Any) -> Optional[MemAttr]:
        """Return MemAttr if *obj* belongs to this component, else None."""
        raise NotImplementedError

    def alloc(self, size_bytes: int) -> Any:
        raise NotImplementedError

    def free(self, buf: Any) -> None:
        pass

    def memcpy(self, dst: Any, src: Any, size_bytes: int) -> None:
        raise NotImplementedError

    def memset(self, buf: Any, value: int, size_bytes: int) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass


_components: Dict[MemoryType, MemoryComponent] = {}


def register_mc(mc: MemoryComponent) -> MemoryComponent:
    _components[mc.MEM_TYPE] = mc
    return mc


def get_mc(mem_type: MemoryType) -> MemoryComponent:
    _ensure_defaults()
    if mem_type not in _components:
        raise UccError(Status.ERR_NOT_FOUND,
                       f"no memory component for {mem_type.name}")
    return _components[mem_type]


def detect_mem_type(obj: Any) -> MemoryType:
    """ucc_coll.c:25-36 memtype auto-detection. numpy/buffer-protocol ->
    HOST; jax.Array -> TPU (or TPU_PINNED when committed to a CPU device
    while TPU is the default backend)."""
    _ensure_defaults()
    if obj is None:
        return MemoryType.HOST
    if isinstance(obj, np.ndarray) or isinstance(obj, (bytes, bytearray, memoryview)):
        return MemoryType.HOST
    # avoid importing jax for pure-host programs
    import sys
    if "jax" in sys.modules:
        import jax
        if isinstance(obj, jax.Array):
            # any jax.Array is "device memory" regardless of platform: the
            # TPU memtype means "handled by the XLA path" (on the virtual
            # CPU mesh used in tests the same codepath serves)
            return MemoryType.TPU
    if hasattr(obj, "__array_interface__") or hasattr(obj, "__buffer__"):
        return MemoryType.HOST
    return MemoryType.UNKNOWN


def _ensure_defaults() -> None:
    if MemoryType.HOST not in _components:
        from .cpu import McCpu
        register_mc(McCpu())
    if MemoryType.TPU not in _components:
        try:
            from . import tpu  # noqa: F401 - registers McTpu on import
        except ImportError:  # jax genuinely unavailable
            pass
