"""Core enums and flags of the ucc_tpu public API.

Feature-parity targets (reference /root/reference/src/ucc/api/ucc.h):
  - 16 collective types        (ucc.h:147-165)
  - 18 predefined datatypes    (ucc.h:203-221) + generic user datatypes
  - 13 reduction operations    (ucc.h:454-469) incl. AVG / MINLOC / MAXLOC
  - thread modes               (ucc.h:493-497)
  - coll-args flags            (ucc.h:1669-1727)
  - memory types               (ucc/api mem types; TPU HBM replaces CUDA)

The TPU build swaps the CUDA memory world for JAX/TPU: MemoryType.TPU means
"a jax.Array resident in device HBM"; HOST means numpy/CPU memory.
"""
from __future__ import annotations

import enum

import numpy as np
import ml_dtypes


class CollType(enum.IntFlag):
    """Collective operation types (bitflags, like ucc_coll_type_t ucc.h:147)."""

    BARRIER = 1 << 0
    BCAST = 1 << 1
    ALLREDUCE = 1 << 2
    REDUCE = 1 << 3
    ALLTOALL = 1 << 4
    ALLTOALLV = 1 << 5
    ALLGATHER = 1 << 6
    ALLGATHERV = 1 << 7
    GATHER = 1 << 8
    GATHERV = 1 << 9
    SCATTER = 1 << 10
    SCATTERV = 1 << 11
    REDUCE_SCATTER = 1 << 12
    REDUCE_SCATTERV = 1 << 13
    FANIN = 1 << 14
    FANOUT = 1 << 15


COLL_TYPE_ALL = CollType((1 << 16) - 1)
COLL_TYPE_LIST = list(CollType)
COLL_TYPE_NUM = 16

#: Rooted collectives — have a root rank whose buffers differ from non-roots
#: (cf. reference ucc_coll_utils.h root handling, ucc_coll.c:236 asymmetric path)
ROOTED_COLLS = (
    CollType.BCAST
    | CollType.REDUCE
    | CollType.GATHER
    | CollType.GATHERV
    | CollType.SCATTER
    | CollType.SCATTERV
    | CollType.FANIN
    | CollType.FANOUT
)


def coll_type_str(ct: CollType) -> str:
    """Pretty name like the reference's ucc_coll_type_str (ucc_coll_utils.h:263)."""
    try:
        return CollType(ct).name.lower()
    except ValueError:
        return f"coll_type_0x{int(ct):x}"


class MemoryType(enum.IntEnum):
    """Where a buffer lives. TPU replaces the reference's CUDA/ROCM axis."""

    HOST = 0          # numpy / host DRAM
    TPU = 1           # jax.Array in device HBM
    TPU_PINNED = 2    # host-pinned staging (device_put'able committed host array)
    UNKNOWN = 3

    # aliases keeping reference spellings meaningful in configs
    @classmethod
    def parse(cls, s: str) -> "MemoryType":
        s = s.strip().lower()
        aliases = {
            "host": cls.HOST, "cpu": cls.HOST,
            "tpu": cls.TPU, "cuda": cls.TPU, "device": cls.TPU, "hbm": cls.TPU,
            "tpu_pinned": cls.TPU_PINNED, "pinned": cls.TPU_PINNED,
        }
        if s not in aliases:
            raise ValueError(f"unknown memory type '{s}'")
        return aliases[s]


MEM_TYPE_NUM = 3  # HOST, TPU, TPU_PINNED participate in score maps


class ReductionOp(enum.IntEnum):
    """13 predefined reduction ops (ucc_reduction_op_t ucc.h:454-469)."""

    SUM = 0
    PROD = 1
    MAX = 2
    MIN = 3
    LAND = 4
    LOR = 5
    LXOR = 6
    BAND = 7
    BOR = 8
    BXOR = 9
    MINLOC = 10
    MAXLOC = 11
    AVG = 12


class DataType(enum.IntEnum):
    """18 predefined datatypes (ucc_datatype_t ucc.h:203-221).

    INT128/UINT128/FLOAT128/FLOAT128_COMPLEX exist for API parity; they have
    sizes (so copy-style colls work on raw bytes) but no numpy compute dtype,
    matching the reference where EC backends reject them (ec_cpu lacks them
    too on most builds).
    """

    INT8 = 0
    UINT8 = 1
    INT16 = 2
    UINT16 = 3
    INT32 = 4
    UINT32 = 5
    INT64 = 6
    UINT64 = 7
    INT128 = 8
    UINT128 = 9
    FLOAT16 = 10
    FLOAT32 = 11
    FLOAT64 = 12
    FLOAT128 = 13
    BFLOAT16 = 14
    FLOAT32_COMPLEX = 15
    FLOAT64_COMPLEX = 16
    FLOAT128_COMPLEX = 17


_DT_INFO = {
    DataType.INT8: (1, np.dtype(np.int8)),
    DataType.UINT8: (1, np.dtype(np.uint8)),
    DataType.INT16: (2, np.dtype(np.int16)),
    DataType.UINT16: (2, np.dtype(np.uint16)),
    DataType.INT32: (4, np.dtype(np.int32)),
    DataType.UINT32: (4, np.dtype(np.uint32)),
    DataType.INT64: (8, np.dtype(np.int64)),
    DataType.UINT64: (8, np.dtype(np.uint64)),
    DataType.INT128: (16, None),
    DataType.UINT128: (16, None),
    DataType.FLOAT16: (2, np.dtype(np.float16)),
    DataType.FLOAT32: (4, np.dtype(np.float32)),
    DataType.FLOAT64: (8, np.dtype(np.float64)),
    DataType.FLOAT128: (16, None),
    DataType.BFLOAT16: (2, np.dtype(ml_dtypes.bfloat16)),
    DataType.FLOAT32_COMPLEX: (8, np.dtype(np.complex64)),
    DataType.FLOAT64_COMPLEX: (16, np.dtype(np.complex128)),
    DataType.FLOAT128_COMPLEX: (32, None),
}

#: numpy dtype -> DataType (for memtype/dtype auto-detection)
_NP_TO_DT = {info[1]: dt for dt, info in _DT_INFO.items() if info[1] is not None}


def dt_size(dt: "DataType | GenericDataType") -> int:
    """Element size in bytes (ucc_dt_size analog)."""
    if isinstance(dt, GenericDataType):
        return dt.size
    return _DT_INFO[DataType(dt)][0]


def dt_numpy(dt: DataType) -> np.dtype:
    """numpy dtype for a predefined DataType; raises for 128-bit types."""
    nd = _DT_INFO[DataType(dt)][1]
    if nd is None:
        raise TypeError(f"{DataType(dt).name} has no host compute representation")
    return nd


def dt_from_numpy(nd) -> DataType:
    nd = np.dtype(nd)
    if nd not in _NP_TO_DT:
        raise TypeError(f"no predefined DataType for numpy dtype {nd}")
    return _NP_TO_DT[nd]


def dt_has_compute(dt: "DataType | GenericDataType") -> bool:
    if isinstance(dt, GenericDataType):
        return dt.reduce_cb is not None
    return _DT_INFO[DataType(dt)][1] is not None


#: dtypes representable in JAX on TPU (FLOAT64/complex run on CPU backend only)
def dt_jax(dt: DataType):
    import jax.numpy as jnp

    m = {
        DataType.INT8: jnp.int8, DataType.UINT8: jnp.uint8,
        DataType.INT16: jnp.int16, DataType.UINT16: jnp.uint16,
        DataType.INT32: jnp.int32, DataType.UINT32: jnp.uint32,
        DataType.INT64: jnp.int64, DataType.UINT64: jnp.uint64,
        DataType.FLOAT16: jnp.float16, DataType.FLOAT32: jnp.float32,
        DataType.FLOAT64: jnp.float64, DataType.BFLOAT16: jnp.bfloat16,
        DataType.FLOAT32_COMPLEX: jnp.complex64,
        DataType.FLOAT64_COMPLEX: jnp.complex128,
    }
    if DataType(dt) not in m:
        raise TypeError(f"{DataType(dt).name} not representable in jax")
    return m[DataType(dt)]


class GenericDataType:
    """User-defined datatype (ucc_dt_create_generic, ucc.h:289-433).

    pack/unpack/reduce callbacks operate on contiguous byte views. A generic
    dtype with no reduce_cb can be used only in non-reducing collectives,
    matching the reference contract.
    """

    __slots__ = ("size", "pack_cb", "unpack_cb", "reduce_cb", "name")

    def __init__(self, size: int, pack_cb=None, unpack_cb=None, reduce_cb=None,
                 name: str = "generic"):
        if size <= 0:
            raise ValueError("generic datatype size must be positive")
        self.size = int(size)
        self.pack_cb = pack_cb
        self.unpack_cb = unpack_cb
        self.reduce_cb = reduce_cb
        self.name = name

    def __repr__(self):
        return f"GenericDataType({self.name}, size={self.size})"


class ThreadMode(enum.IntEnum):
    """ucc_thread_mode_t (ucc.h:493-497)."""

    SINGLE = 0
    FUNNELED = 1
    MULTIPLE = 2


class CollSyncType(enum.IntEnum):
    """Synchronous vs non-synchronous collective model (ucc.h:521-524)."""

    NON_SYNC_COLLECTIVES = 0
    SYNC_COLLECTIVES = 1


class CollArgsFlags(enum.IntFlag):
    """ucc_coll_args_flags_t (ucc.h:1669-1727)."""

    IN_PLACE = 1 << 0
    PERSISTENT = 1 << 1
    COUNT_64BIT = 1 << 2
    DISPLACEMENTS_64BIT = 1 << 3
    CONTIG_SRC_BUFFER = 1 << 4
    CONTIG_DST_BUFFER = 1 << 5
    TIMEOUT = 1 << 6
    MEM_MAPPED_BUFFERS = 1 << 7
    MEM_MAP_SRC_MEMH = 1 << 8
    MEM_MAP_DST_MEMH = 1 << 9


class CollArgsHints(enum.IntFlag):
    """Optimization hints (ucc.h:1732-1766)."""

    OPTIMIZE_LATENCY = 1 << 0
    OPTIMIZE_BANDWIDTH = 1 << 1
    NO_MEMORY_REUSE = 1 << 2


class EventType(enum.IntEnum):
    """Task/schedule events (ucc_event_t, schedule/ucc_schedule.h:22-30)."""

    EVENT_COMPLETED = 0
    EVENT_SCHEDULE_STARTED = 1
    EVENT_TASK_STARTED = 2
    EVENT_COMPLETED_SCHEDULE = 3
    EVENT_ERROR = 4
    EVENT_LAST = 5


class EeType(enum.IntEnum):
    """Execution-engine types (ucc_ee_type_t). TPU replaces CUDA streams."""

    TPU_STREAM = 0     # triggered execution inside a jitted program
    CPU_THREAD = 1
    LAST = 2


class ErrorType(enum.IntEnum):
    """ucc_error_type_t (ucc.h:1803-1806)."""

    LOCAL = 0
    GLOBAL = 1
