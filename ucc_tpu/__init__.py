"""ucc_tpu — a TPU-native collective communication framework.

A ground-up redesign of the capabilities of UCC (openucx/ucc, mounted at
/root/reference) for TPU systems: the same layered architecture — public
API, core objects (lib/context/team/collective), selection engine, async
schedule DAGs, collective layers (CL) composing transport layers (TL),
memory/execution components (MC/EC), topology — but with the compute path
built on JAX/XLA/Pallas:

* TL/XLA runs a team's collectives as compiled shard_map programs over a
  ``jax.sharding.Mesh`` (ICI), replacing TL/NCCL+TL/CUDA.
* TL/SHM and TL/SOCKET provide host-side tagged-p2p algorithm suites
  (knomial/ring/DBT/Bruck/SRA...) for DCN and bootstrap, replacing TL/UCP.
* MC/TPU + EC/TPU manage HBM-resident jax buffers and Pallas reduce
  kernels, replacing MC/CUDA + EC/CUDA.
* CL/HIER composes ICI (intra-slice) with DCN (inter-host) hierarchically.

Quick start (single process, UCC-style objects)::

    import numpy as np, ucc_tpu
    lib = ucc_tpu.init()
    ctx = ucc_tpu.Context(lib)                     # no OOB -> 1-rank world
    team = ctx.create_team(ucc_tpu.TeamParams())
    src = np.arange(4, dtype=np.float32); dst = np.zeros_like(src)
    req = team.collective_init(ucc_tpu.CollArgs(
        coll_type=ucc_tpu.CollType.ALLREDUCE,
        src=ucc_tpu.BufferInfo(src, 4, ucc_tpu.DataType.FLOAT32),
        dst=ucc_tpu.BufferInfo(dst, 4, ucc_tpu.DataType.FLOAT32),
        op=ucc_tpu.ReductionOp.SUM))
    req.post(); req.wait()
"""

from .constants import (CollArgsFlags, CollArgsHints, CollSyncType, CollType,  # noqa: F401
                        DataType, EventType, GenericDataType, MemoryType,
                        ReductionOp, ThreadMode, coll_type_str, dt_size)
from .status import RankFailedError, Status, UccError, check  # noqa: F401
from .api.types import (ActiveSet, BufferInfo, BufferInfoV, CollArgs,  # noqa: F401
                        ContextAttr, ContextParams, ContextType, LibAttr,
                        LibParams, OobColl, OobRequest, TeamAttr, TeamParams)
from .core.lib import Lib, init  # noqa: F401
from .core.context import Context  # noqa: F401
from .core.team import Team, TeamState  # noqa: F401
from .core.coll import CollRequest, collective_init  # noqa: F401
from .core.oob import (SubsetOob, TcpStoreOob, TcpTreeOob,  # noqa: F401
                       ThreadOob, ThreadOobWorld, ThreadTreeOobWorld,
                       TreeOob, tree_layout)
from .core.ee import Ee, UccEvent  # noqa: F401
from . import ops  # noqa: F401

__version__ = "0.5.0"
