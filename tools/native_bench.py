"""Native-vs-Python tag matcher benchmark, both thread modes.

The v2 C++ matcher (native/ucc_tpu_core.cc) carries two claims that this
harness measures head-to-head against the in-GIL python matcher:

  * ThreadMode.MULTIPLE (default mode here): GIL-released matching wins
    when many OS threads drive progress concurrently — every rank in its
    own OS thread, a storm of small allreduces (tag-matcher thrash, the
    ucc_progress_queue_mt.c regime).
  * --single: ThreadMode.SINGLE, all ranks progressed cooperatively from
    ONE thread (the tests/gate regime). v1 measured ~2x SLOWER here
    (per-call ffi + pickled keys dominated); v2's packed binary keys and
    mapped completion window are required to hold parity.

Run directly for one matcher, or with --compare to spawn both matchers
in subprocesses and print the verdict. Output records match perftest's
--json shape (avg/min/max/p50/p99 us) plus colls_per_s.

    python tools/native_bench.py --compare            # MT verdict
    python tools/native_bench.py --compare --single   # ST verdict
    python tools/native_bench.py --json --single
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _stats(lats) -> dict:
    import numpy as np
    a = np.asarray(lats, dtype=np.float64) * 1e6
    return {"avg_us": round(float(a.mean()), 3),
            "min_us": round(float(a.min()), 3),
            "max_us": round(float(a.max()), 3),
            "p50_us": round(float(np.percentile(a, 50)), 3),
            "p99_us": round(float(np.percentile(a, 99)), 3)}


def _mode_of(ctx) -> str:
    # label from what actually ran, not the env: native is the default in
    # both thread modes, so an unset env IS a native run when available
    return ("native" if ctx.tl_contexts["shm"].obj.transport.native
            is not None else "python")


def run_multi(n: int, iters: int, count: int) -> dict:
    """ThreadMode.MULTIPLE: every rank posts + progresses from its own
    OS thread (concurrent matcher access; the GIL-release regime)."""
    import numpy as np
    import ucc_tpu
    from ucc_tpu import (BufferInfo, CollArgs, CollType, Context,
                         ContextParams, DataType, LibParams, ReductionOp,
                         TeamParams, ThreadMode, ThreadOobWorld)

    world = ThreadOobWorld(n)
    libs = [ucc_tpu.init(LibParams(thread_mode=ThreadMode.MULTIPLE))
            for _ in range(n)]
    ctxs = [None] * n

    def mk(r):
        ctxs[r] = Context(libs[r], ContextParams(oob=world.endpoint(r)))

    ths = [threading.Thread(target=mk, args=(r,)) for r in range(n)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(120)

    tw = ThreadOobWorld(n)
    teams = [None] * n
    errors = []
    barrier = threading.Barrier(n)
    lats0 = []
    t_wall = [0.0]

    def rank_main(r):
        try:
            team = ctxs[r].create_team(TeamParams(oob=tw.endpoint(r)))
            teams[r] = team
            src = np.full(count, float(r + 1), np.float64)
            dst = np.zeros(count, np.float64)

            def one():
                req = team.collective_init(CollArgs(
                    coll_type=CollType.ALLREDUCE,
                    src=BufferInfo(src, count, DataType.FLOAT64),
                    dst=BufferInfo(dst, count, DataType.FLOAT64),
                    op=ReductionOp.SUM))
                req.post()
                req.wait(timeout=120)

            for _ in range(max(2, iters // 10)):   # warmup
                one()
            barrier.wait()
            t0 = time.perf_counter()
            for _ in range(iters):
                if r == 0:
                    i0 = time.perf_counter()
                    one()
                    lats0.append(time.perf_counter() - i0)
                else:
                    one()
            if r == 0:
                t_wall[0] = time.perf_counter() - t0
        except Exception as e:  # noqa: BLE001
            errors.append((r, repr(e)))

    ths = [threading.Thread(target=rank_main, args=(r,)) for r in range(n)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(600)
    if errors:
        raise RuntimeError(f"bench failed: {errors}")
    mode = _mode_of(ctxs[0])
    for t in teams:
        t.destroy()
    for c in ctxs:
        c.destroy()
    wall = t_wall[0]
    return {"bench": "native", "threadmode": "multiple", "matcher": mode,
            "coll": "allreduce", "ranks": n, "count": count,
            "size_bytes": count * 8, "iters": iters,
            **_stats(lats0),
            "wall_s": round(wall, 4),
            "colls_per_s": round(iters / wall, 1) if wall else None}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=8, help="ranks")
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--count", type=int, default=64,
                    help="elements per allreduce (small = matcher-bound)")
    ap.add_argument("--single", action="store_true",
                    help="ThreadMode.SINGLE cooperative driver instead "
                    "of one OS thread per rank")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable record only: suppress the "
                    "human-readable summary line (stderr). stdout is "
                    "always one JSON record per run, matching perftest's "
                    "--json shape")
    ap.add_argument("--compare", action="store_true",
                    help="run python + native matchers in subprocesses "
                    "and print the verdict")
    args = ap.parse_args(argv)

    if not args.compare:
        fn = _run_single_impl if args.single else run_multi
        rec = fn(args.n, args.iters, args.count)
        print(json.dumps(rec))
        if not args.json:
            print(f"# {rec['matcher']} matcher ({rec['threadmode']}): "
                  f"{rec['colls_per_s']} colls/s, p50 {rec['p50_us']}us, "
                  f"p99 {rec['p99_us']}us over {rec['iters']} iters",
                  file=sys.stderr)
        return 0

    results = {}
    for mode, flag in (("python", "n"), ("native", "y")):
        env = dict(os.environ, UCC_TL_SHM_NATIVE=flag,
                   JAX_PLATFORMS="cpu")
        argv_child = [sys.executable, os.path.abspath(__file__),
                      "-n", str(args.n), "--iters", str(args.iters),
                      "--count", str(args.count)]
        if args.single:
            argv_child.append("--single")
        out = subprocess.run(argv_child, env=env, capture_output=True,
                             text=True, timeout=900)
        line = (out.stdout or "").strip().splitlines()[-1] if out.stdout \
            else ""
        if out.returncode != 0 or not line:
            print(f"# {mode} run failed rc={out.returncode}: "
                  f"{(out.stderr or '')[-300:]}", file=sys.stderr)
            return 1
        results[mode] = json.loads(line)
        print(line)
        # the record labels what ACTUALLY ran (_mode_of): a kill switch
        # (UCC_NATIVE=n) or a failed build in the child makes both runs
        # python — comparing them as native-vs-python is a silently
        # wrong baseline, so refuse instead
        got = results[mode].get("matcher")
        if got != mode:
            print(f"# {mode} run actually used matcher={got!r} "
                  f"(UCC_NATIVE kill switch? build failure?) — "
                  f"comparison is meaningless, aborting", file=sys.stderr)
            return 1
    ratio = results["python"]["wall_s"] / results["native"]["wall_s"]
    print(json.dumps({
        "threadmode": "single" if args.single else "multiple",
        "native_speedup_vs_python": round(ratio, 3),
        "python_colls_per_s": results["python"]["colls_per_s"],
        "native_colls_per_s": results["native"]["colls_per_s"],
        "verdict": "native wins" if ratio > 1.05 else
        ("parity" if ratio > 0.95 else "python wins")}))
    if not args.json:
        print(f"# {'single' if args.single else 'multiple'}: native "
              f"{ratio:.3f}x python "
              f"({results['native']['colls_per_s']} vs "
              f"{results['python']['colls_per_s']} colls/s)",
              file=sys.stderr)
    return 0


def _run_single_impl(n: int, iters: int, count: int) -> dict:
    """ThreadMode.SINGLE: one thread posts the collective on every rank
    and drives all contexts cooperatively (the tests/gate regime — the
    regime where the v1 matcher lost ~2x to python)."""
    import numpy as np
    from ucc_tpu import (BufferInfo, CollArgs, CollType, DataType,
                         ReductionOp, Status)
    sys.path.insert(0, os.path.join(REPO, "tests"))
    from harness import UccJob

    job = UccJob(n)
    try:
        teams = job.create_team()
        srcs = [np.full(count, float(r + 1), np.float64) for r in range(n)]
        dsts = [np.zeros(count, np.float64) for _ in range(n)]

        def one_round():
            reqs = [t.collective_init(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(srcs[r], count, DataType.FLOAT64),
                dst=BufferInfo(dsts[r], count, DataType.FLOAT64),
                op=ReductionOp.SUM)) for r, t in enumerate(teams)]
            for rq in reqs:
                rq.post()
            while not all(rq.test() != Status.IN_PROGRESS for rq in reqs):
                for c in job.contexts:
                    c.progress()
            for rq in reqs:
                assert rq.test() == Status.OK
                rq.finalize()

        for _ in range(max(2, iters // 10)):    # warmup
            one_round()
        lats = []
        t0 = time.perf_counter()
        for _ in range(iters):
            i0 = time.perf_counter()
            one_round()
            lats.append(time.perf_counter() - i0)
        wall = time.perf_counter() - t0
        mode = _mode_of(job.contexts[0])
    finally:
        job.cleanup()
    return {"bench": "native", "threadmode": "single", "matcher": mode,
            "coll": "allreduce", "ranks": n, "count": count,
            "size_bytes": count * 8, "iters": iters,
            **_stats(lats),
            "wall_s": round(wall, 4),
            "colls_per_s": round(iters / wall, 1) if wall else None}


if __name__ == "__main__":
    sys.exit(main())
