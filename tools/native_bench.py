"""Native-vs-Python tag matcher benchmark, both thread modes.

The v2 C++ matcher (native/ucc_tpu_core.cc) carries two claims that this
harness measures head-to-head against the in-GIL python matcher:

  * ThreadMode.MULTIPLE (default mode here): GIL-released matching wins
    when many OS threads drive progress concurrently — every rank in its
    own OS thread, a storm of small allreduces (tag-matcher thrash, the
    ucc_progress_queue_mt.c regime).
  * --single: ThreadMode.SINGLE, all ranks progressed cooperatively from
    ONE thread (the tests/gate regime). v1 measured ~2x SLOWER here
    (per-call ffi + pickled keys dominated); v2's packed binary keys and
    mapped completion window are required to hold parity.

Run directly for one matcher, or with --compare to spawn both matchers
in subprocesses and print the verdict. Output records match perftest's
--json shape (avg/min/max/p50/p99 us) plus colls_per_s.

    python tools/native_bench.py --compare            # MT verdict
    python tools/native_bench.py --compare --single   # ST verdict
    python tools/native_bench.py --json --single
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _stats(lats) -> dict:
    import numpy as np
    a = np.asarray(lats, dtype=np.float64) * 1e6
    return {"avg_us": round(float(a.mean()), 3),
            "min_us": round(float(a.min()), 3),
            "max_us": round(float(a.max()), 3),
            "p50_us": round(float(np.percentile(a, 50)), 3),
            "p99_us": round(float(np.percentile(a, 99)), 3)}


def _mode_of(ctx) -> str:
    # label from what actually ran, not the env: native is the default in
    # both thread modes, so an unset env IS a native run when available
    return ("native" if ctx.tl_contexts["shm"].obj.transport.native
            is not None else "python")


def run_multi(n: int, iters: int, count: int, f32: bool = False) -> dict:
    """ThreadMode.MULTIPLE: every rank posts + progresses from its own
    OS thread (concurrent matcher access; the GIL-release regime)."""
    import numpy as np
    import ucc_tpu
    from ucc_tpu import (BufferInfo, CollArgs, CollType, Context,
                         ContextParams, DataType, LibParams, ReductionOp,
                         TeamParams, ThreadMode, ThreadOobWorld)

    nd = np.float32 if f32 else np.float64
    ucc_dt = DataType.FLOAT32 if f32 else DataType.FLOAT64
    esz = 4 if f32 else 8
    world = ThreadOobWorld(n)
    libs = [ucc_tpu.init(LibParams(thread_mode=ThreadMode.MULTIPLE))
            for _ in range(n)]
    ctxs = [None] * n

    def mk(r):
        ctxs[r] = Context(libs[r], ContextParams(oob=world.endpoint(r)))

    ths = [threading.Thread(target=mk, args=(r,)) for r in range(n)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(120)

    tw = ThreadOobWorld(n)
    teams = [None] * n
    errors = []
    barrier = threading.Barrier(n)
    lats0 = []
    t_wall = [0.0]

    def rank_main(r):
        try:
            team = ctxs[r].create_team(TeamParams(oob=tw.endpoint(r)))
            teams[r] = team
            src = np.full(count, float(r + 1), nd)
            dst = np.zeros(count, nd)

            def one():
                req = team.collective_init(CollArgs(
                    coll_type=CollType.ALLREDUCE,
                    src=BufferInfo(src, count, ucc_dt),
                    dst=BufferInfo(dst, count, ucc_dt),
                    op=ReductionOp.SUM))
                req.post()
                req.wait(timeout=120)

            for _ in range(max(2, iters // 10)):   # warmup
                one()
            barrier.wait()
            t0 = time.perf_counter()
            for _ in range(iters):
                if r == 0:
                    i0 = time.perf_counter()
                    one()
                    lats0.append(time.perf_counter() - i0)
                else:
                    one()
            if r == 0:
                t_wall[0] = time.perf_counter() - t0
        except Exception as e:  # noqa: BLE001
            errors.append((r, repr(e)))

    ths = [threading.Thread(target=rank_main, args=(r,)) for r in range(n)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(600)
    if errors:
        raise RuntimeError(f"bench failed: {errors}")
    mode = _mode_of(ctxs[0])
    for t in teams:
        t.destroy()
    for c in ctxs:
        c.destroy()
    wall = t_wall[0]
    return {"bench": "native", "threadmode": "multiple", "matcher": mode,
            "coll": "allreduce", "ranks": n, "count": count,
            "size_bytes": count * esz, "iters": iters,
            **_stats(lats0),
            "wall_s": round(wall, 4),
            "colls_per_s": round(iters / wall, 1) if wall else None}


def run_plans(n: int, iters: int, sizes, algs, json_only: bool) -> int:
    """--plans: A/B per-round-Python (interpreted GeneratedCollTask) vs
    NATIVE-PLAN execution of the same verified programs on the MT shm
    mesh, one subprocess per (alg, size, mode) pair, plus a bitwise
    cross-check of the two modes (2/4/8 ranks, inplace + AVG included).
    One JSON record per line on stdout; pipe to BENCH_r12.json."""
    records = []
    for alg in algs:
        fam = "ring(1)" if alg.startswith("gen_ring_c1") else \
            "ring(2)" if alg.startswith("gen_ring_c2") else "rhd(0)"
        for size in sizes:
            count = max(64, size // 4)          # f32 elements
            it = max(10, min(iters, iters * 8192 // max(8192, size)))
            pair = {}
            for mode, flag in (("interpreted", "n"), ("plan", "y")):
                env = dict(os.environ, JAX_PLATFORMS="cpu",
                           UCC_GEN="y", UCC_GEN_FAMILIES=fam,
                           UCC_GEN_NATIVE=flag,
                           UCC_TL_SHM_TUNE=f"allreduce:@{alg}:inf")
                out = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "-n", str(n), "--iters", str(it),
                     "--count", str(count), "--f32", "--json"],
                    env=env, capture_output=True, text=True, timeout=900)
                line = (out.stdout or "").strip().splitlines()[-1] \
                    if out.stdout else ""
                if out.returncode != 0 or not line:
                    print(f"# plans bench failed ({alg} {size} {mode}) "
                          f"rc={out.returncode}: "
                          f"{(out.stderr or '')[-300:]}", file=sys.stderr)
                    return 1
                pair[mode] = json.loads(line)
            rec = {"bench": "plans", "threadmode": "multiple",
                   "coll": "allreduce", "alg": alg, "ranks": n,
                   "count": count, "size_bytes": count * 4, "iters": it,
                   "interp_p50_us": pair["interpreted"]["p50_us"],
                   "interp_p99_us": pair["interpreted"]["p99_us"],
                   "plan_p50_us": pair["plan"]["p50_us"],
                   "plan_p99_us": pair["plan"]["p99_us"],
                   "plan_speedup_p50": round(
                       pair["interpreted"]["p50_us"] /
                       max(1e-9, pair["plan"]["p50_us"]), 3),
                   "plan_colls_per_s": pair["plan"]["colls_per_s"],
                   "interp_colls_per_s":
                       pair["interpreted"]["colls_per_s"]}
            records.append(rec)
            print(json.dumps(rec))
            if not json_only:
                print(f"# {alg} {count * 4}B: plan p50 "
                      f"{rec['plan_p50_us']}us vs interp "
                      f"{rec['interp_p50_us']}us -> "
                      f"{rec['plan_speedup_p50']}x", file=sys.stderr)
    bit = _plans_bitwise()
    print(json.dumps(bit))
    if not json_only:
        print(f"# bitwise plan-vs-interpreted: {bit['verdict']} over "
              f"ranks {bit['ranks']}", file=sys.stderr)
    wins = [r for r in records
            if r["size_bytes"] <= 262144 and r["plan_speedup_p50"] >= 1.3]
    verdict = {"bench": "plans", "metric": "summary",
               "points_ge_1p3x_le_256k": len(wins),
               "bitwise_ok": bit["verdict"] == "identical",
               "best_speedup_p50": max(
                   (r["plan_speedup_p50"] for r in records), default=None)}
    print(json.dumps(verdict))
    return 0 if (len(wins) >= 2 and bit["verdict"] == "identical") else 1


def _plans_bitwise() -> dict:
    """Run one matrix of allreduces (SUM/AVG/MAX x inplace x dtypes) in
    BOTH modes across 2/4/8 ranks in subprocesses; compare result bytes."""
    rec = {"bench": "plans", "metric": "bitwise", "ranks": [2, 4, 8],
           "cases": 0, "mismatches": []}
    for n in (2, 4, 8):
        digests = {}
        for mode, flag in (("interp", "n"), ("plan", "y")):
            env = dict(os.environ, JAX_PLATFORMS="cpu", UCC_GEN="y",
                       UCC_GEN_FAMILIES="ring(1),rhd(0)",
                       UCC_GEN_NATIVE=flag,
                       UCC_TL_SHM_TUNE="allreduce:@gen_ring_c1:inf")
            out = subprocess.run(
                [sys.executable, "-m", "ucc_tpu.dsl.smoke",
                 "--plans-digest", str(n)],
                env=env, capture_output=True, text=True, timeout=600,
                cwd=REPO)
            line = (out.stdout or "").strip().splitlines()[-1] \
                if out.stdout else ""
            try:
                digests[mode] = json.loads(line)
            except ValueError:
                rec["mismatches"].append(
                    {"ranks": n, "mode": mode,
                     "error": (out.stderr or "no output")[-200:]})
                digests[mode] = None
        a, b = digests.get("interp"), digests.get("plan")
        if a and b:
            # "_"-prefixed keys are metadata (e.g. _plan_engaged, which
            # legitimately differs between the modes), not result digests
            cases = [k for k in a if not k.startswith("_")]
            rec["cases"] += len(cases)
            for k in cases:
                # None = the case timed out in that mode: never a match
                if a[k] is None or b.get(k) is None or a[k] != b.get(k):
                    rec["mismatches"].append({"ranks": n, "case": k})
            if not b.get("_plan_engaged", True):
                rec["mismatches"].append(
                    {"ranks": n, "case": "plan mode did not engage"})
    rec["verdict"] = "identical" if rec["cases"] and \
        not rec["mismatches"] else "MISMATCH"
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=8, help="ranks")
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--count", type=int, default=64,
                    help="elements per allreduce (small = matcher-bound)")
    ap.add_argument("--f32", action="store_true",
                    help="float32 payload (the plans A/B uses it: the "
                    "native reduce fast path)")
    ap.add_argument("--single", action="store_true",
                    help="ThreadMode.SINGLE cooperative driver instead "
                    "of one OS thread per rank")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable record only: suppress the "
                    "human-readable summary line (stderr). stdout is "
                    "always one JSON record per run, matching perftest's "
                    "--json shape")
    ap.add_argument("--compare", action="store_true",
                    help="run python + native matchers in subprocesses "
                    "and print the verdict")
    ap.add_argument("--plans", action="store_true",
                    help="A/B interpreted vs native-plan execution of "
                    "generated programs (gen_ring/gen_rhd) over a "
                    "message-size sweep + a bitwise cross-check "
                    "(BENCH_r12 harness)")
    ap.add_argument("--sizes", default="8192,65536,262144,1048576,4194304",
                    help="--plans: comma list of message sizes in bytes")
    args = ap.parse_args(argv)

    if args.plans:
        sizes = [int(s) for s in args.sizes.split(",") if s.strip()]
        # rhd at radix n (the direct exchange) is named per team size
        return run_plans(args.n, args.iters, sizes,
                         ("gen_ring_c1", f"gen_rhd_r{args.n}"),
                         args.json)

    if not args.compare:
        fn = _run_single_impl if args.single else run_multi
        rec = fn(args.n, args.iters, args.count, f32=args.f32)
        print(json.dumps(rec))
        if not args.json:
            print(f"# {rec['matcher']} matcher ({rec['threadmode']}): "
                  f"{rec['colls_per_s']} colls/s, p50 {rec['p50_us']}us, "
                  f"p99 {rec['p99_us']}us over {rec['iters']} iters",
                  file=sys.stderr)
        return 0

    results = {}
    for mode, flag in (("python", "n"), ("native", "y")):
        env = dict(os.environ, UCC_TL_SHM_NATIVE=flag,
                   JAX_PLATFORMS="cpu")
        argv_child = [sys.executable, os.path.abspath(__file__),
                      "-n", str(args.n), "--iters", str(args.iters),
                      "--count", str(args.count)]
        if args.single:
            argv_child.append("--single")
        out = subprocess.run(argv_child, env=env, capture_output=True,
                             text=True, timeout=900)
        line = (out.stdout or "").strip().splitlines()[-1] if out.stdout \
            else ""
        if out.returncode != 0 or not line:
            print(f"# {mode} run failed rc={out.returncode}: "
                  f"{(out.stderr or '')[-300:]}", file=sys.stderr)
            return 1
        results[mode] = json.loads(line)
        print(line)
        # the record labels what ACTUALLY ran (_mode_of): a kill switch
        # (UCC_NATIVE=n) or a failed build in the child makes both runs
        # python — comparing them as native-vs-python is a silently
        # wrong baseline, so refuse instead
        got = results[mode].get("matcher")
        if got != mode:
            print(f"# {mode} run actually used matcher={got!r} "
                  f"(UCC_NATIVE kill switch? build failure?) — "
                  f"comparison is meaningless, aborting", file=sys.stderr)
            return 1
    ratio = results["python"]["wall_s"] / results["native"]["wall_s"]
    print(json.dumps({
        "threadmode": "single" if args.single else "multiple",
        "native_speedup_vs_python": round(ratio, 3),
        "python_colls_per_s": results["python"]["colls_per_s"],
        "native_colls_per_s": results["native"]["colls_per_s"],
        "verdict": "native wins" if ratio > 1.05 else
        ("parity" if ratio > 0.95 else "python wins")}))
    if not args.json:
        print(f"# {'single' if args.single else 'multiple'}: native "
              f"{ratio:.3f}x python "
              f"({results['native']['colls_per_s']} vs "
              f"{results['python']['colls_per_s']} colls/s)",
              file=sys.stderr)
    return 0


def _run_single_impl(n: int, iters: int, count: int, f32: bool = False) -> dict:
    """ThreadMode.SINGLE: one thread posts the collective on every rank
    and drives all contexts cooperatively (the tests/gate regime — the
    regime where the v1 matcher lost ~2x to python)."""
    import numpy as np
    from ucc_tpu import (BufferInfo, CollArgs, CollType, DataType,
                         ReductionOp, Status)
    sys.path.insert(0, os.path.join(REPO, "tests"))
    from harness import UccJob

    nd = np.float32 if f32 else np.float64
    ucc_dt = DataType.FLOAT32 if f32 else DataType.FLOAT64
    esz = 4 if f32 else 8
    job = UccJob(n)
    try:
        teams = job.create_team()
        srcs = [np.full(count, float(r + 1), nd) for r in range(n)]
        dsts = [np.zeros(count, nd) for _ in range(n)]

        def one_round():
            reqs = [t.collective_init(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(srcs[r], count, ucc_dt),
                dst=BufferInfo(dsts[r], count, ucc_dt),
                op=ReductionOp.SUM)) for r, t in enumerate(teams)]
            for rq in reqs:
                rq.post()
            while not all(rq.test() != Status.IN_PROGRESS for rq in reqs):
                for c in job.contexts:
                    c.progress()
            for rq in reqs:
                assert rq.test() == Status.OK
                rq.finalize()

        for _ in range(max(2, iters // 10)):    # warmup
            one_round()
        lats = []
        t0 = time.perf_counter()
        for _ in range(iters):
            i0 = time.perf_counter()
            one_round()
            lats.append(time.perf_counter() - i0)
        wall = time.perf_counter() - t0
        mode = _mode_of(job.contexts[0])
    finally:
        job.cleanup()
    return {"bench": "native", "threadmode": "single", "matcher": mode,
            "coll": "allreduce", "ranks": n, "count": count,
            "size_bytes": count * esz, "iters": iters,
            **_stats(lats),
            "wall_s": round(wall, 4),
            "colls_per_s": round(iters / wall, 1) if wall else None}


if __name__ == "__main__":
    sys.exit(main())
