"""Native-vs-Python tag matcher benchmark under ThreadMode.MULTIPLE.

The C++ matcher (native/ucc_tpu_core.cc) exists for exactly one claim:
GIL-released matching should win when MANY OS threads drive progress
concurrently (single-threaded it measured ~2x SLOWER — per-call ffi +
key serialization dominate; tl/host/transport.py). This harness measures
that claim: an 8-rank ThreadMode.MULTIPLE world, every rank in its own
OS thread, a storm of small allreduces (tag-matcher thrash, the
ucc_progress_queue_mt.c regime). Run directly for one mode, or with
--compare to spawn both modes in subprocesses and print the verdict.

Output: one JSON line per mode
  {"mode": "native"|"python", "threads": N, "colls": K, "wall_s": ...,
   "colls_per_s": ...}
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def run_once(n: int, iters: int, count: int) -> dict:
    import numpy as np
    import ucc_tpu
    from ucc_tpu import (BufferInfo, CollArgs, CollType, Context,
                         ContextParams, DataType, LibParams, ReductionOp,
                         TeamParams, ThreadMode, ThreadOobWorld)

    world = ThreadOobWorld(n)
    libs = [ucc_tpu.init(LibParams(thread_mode=ThreadMode.MULTIPLE))
            for _ in range(n)]
    ctxs = [None] * n

    def mk(r):
        ctxs[r] = Context(libs[r], ContextParams(oob=world.endpoint(r)))

    ths = [threading.Thread(target=mk, args=(r,)) for r in range(n)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(120)

    tw = ThreadOobWorld(n)
    teams = [None] * n
    errors = []
    barrier = threading.Barrier(n)
    t_wall = [0.0]

    def rank_main(r):
        try:
            team = ctxs[r].create_team(TeamParams(oob=tw.endpoint(r)))
            teams[r] = team
            src = np.full(count, float(r + 1), np.float64)
            dst = np.zeros(count, np.float64)

            def one():
                req = team.collective_init(CollArgs(
                    coll_type=CollType.ALLREDUCE,
                    src=BufferInfo(src, count, DataType.FLOAT64),
                    dst=BufferInfo(dst, count, DataType.FLOAT64),
                    op=ReductionOp.SUM))
                req.post()
                req.wait(timeout=120)

            for _ in range(max(2, iters // 10)):   # warmup
                one()
            barrier.wait()
            t0 = time.perf_counter()
            for _ in range(iters):
                one()
            if r == 0:
                t_wall[0] = time.perf_counter() - t0
        except Exception as e:  # noqa: BLE001
            errors.append((r, repr(e)))

    ths = [threading.Thread(target=rank_main, args=(r,)) for r in range(n)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(600)
    if errors:
        raise RuntimeError(f"bench failed: {errors}")
    # label from what actually ran, not the env: ThreadMode.MULTIPLE
    # defaults to the native matcher, so an unset env IS a native run
    mode = "native" if ctxs[0].tl_contexts["shm"].obj.transport.native \
        is not None else "python"
    for t in teams:
        t.destroy()
    for c in ctxs:
        c.destroy()
    wall = t_wall[0]
    return {"mode": mode,
            "threads": n, "colls": iters, "count": count,
            "wall_s": round(wall, 4),
            "colls_per_s": round(iters / wall, 1) if wall else None}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=8, help="ranks/threads")
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--count", type=int, default=64,
                    help="elements per allreduce (small = matcher-bound)")
    ap.add_argument("--compare", action="store_true",
                    help="run both modes in subprocesses")
    args = ap.parse_args(argv)

    if not args.compare:
        print(json.dumps(run_once(args.n, args.iters, args.count)))
        return 0

    results = {}
    for mode, flag in (("python", "n"), ("native", "y")):
        env = dict(os.environ, UCC_TL_SHM_NATIVE=flag,
                   JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "-n", str(args.n),
             "--iters", str(args.iters), "--count", str(args.count)],
            env=env, capture_output=True, text=True, timeout=900)
        line = (out.stdout or "").strip().splitlines()[-1] if out.stdout \
            else ""
        if out.returncode != 0 or not line:
            print(f"# {mode} run failed rc={out.returncode}: "
                  f"{(out.stderr or '')[-300:]}", file=sys.stderr)
            return 1
        results[mode] = json.loads(line)
        print(line)
    ratio = results["python"]["wall_s"] / results["native"]["wall_s"]
    print(json.dumps({"native_speedup_vs_python": round(ratio, 3),
                      "verdict": "native wins" if ratio > 1.05 else
                      ("parity" if ratio > 0.95 else "python wins")}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
