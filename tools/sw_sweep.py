#!/usr/bin/env python
"""Sweep the sliding-window allreduce knobs (window bytes x in-flight
buffers) over real loopback TCP and print one JSON line per point.

Round-3 verdict weak #6: the one-sided win faded by 16 MiB (-2%) but
window=1M/inflight=2 were never swept; the reference exposes
num_buffers/window tuning for exactly this regime
(/root/reference/src/components/tl/ucp/allreduce/allreduce_sliding_window.h:36-38).
This tool measures each (msg, window, inflight) cell through
``perftest -c allreduce -p 4 -O`` with the socket TL forced, plus the
two-sided baseline per size, so the defaults can be set from data
(recorded in BASELINE.md).

Usage:  python tools/sw_sweep.py [--quick]
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MSGS = (4 << 20, 16 << 20, 64 << 20)
WINDOWS = (256 << 10, 1 << 20, 4 << 20)
INFLIGHTS = (2, 4, 8)


def _run_point(msg: int, onesided: bool, window: int = 0,
               inflight: int = 0, iters: int = 6) -> float:
    """avg latency (us) of one perftest cell, or -1 on failure."""
    env = dict(os.environ)
    env["UCC_TLS"] = "socket,self"
    # host-memory sweep: pin the cpu platform so each child skips the
    # (possibly wedged) accelerator probe instead of burning its timeout
    env["JAX_PLATFORMS"] = "cpu"
    if window:
        env["UCC_TL_SOCKET_ALLREDUCE_SW_WINDOW"] = str(window)
    if inflight:
        env["UCC_TL_SOCKET_ALLREDUCE_SW_INFLIGHT"] = str(inflight)
    argv = [sys.executable, "-m", "ucc_tpu.tools.perftest",
            "-c", "allreduce", "-p", "4", "-b", str(msg), "-e", str(msg),
            "-n", str(iters), "-w", "2"]
    if onesided:
        argv.append("-O")
    try:
        r = subprocess.run(argv, env=env, capture_output=True, text=True,
                           timeout=900, cwd=REPO)
    except subprocess.TimeoutExpired:
        return -1.0
    if r.returncode != 0:
        return -1.0
    for ln in reversed(r.stdout.strip().splitlines()):
        parts = ln.split()
        if len(parts) >= 3 and parts[0].isdigit():
            return float(parts[2])
    return -1.0


def main() -> None:
    quick = "--quick" in sys.argv
    msgs = MSGS[:1] if quick else MSGS
    out = []
    for msg in msgs:
        iters = 4 if msg >= (64 << 20) else 6
        base = _run_point(msg, onesided=False, iters=iters)
        print(json.dumps({"msg": msg, "mode": "two_sided",
                          "avg_us": base}), flush=True)
        for w in WINDOWS:
            for infl in INFLIGHTS:
                if quick and (w, infl) != (1 << 20, 2) and \
                        (w, infl) != (4 << 20, 4):
                    continue
                us = _run_point(msg, onesided=True, window=w,
                                inflight=infl, iters=iters)
                rec = {"msg": msg, "mode": "sliding_window", "window": w,
                       "inflight": infl, "avg_us": us,
                       "vs_two_sided": round(base / us, 3)
                       if us > 0 and base > 0 else None}
                out.append(rec)
                print(json.dumps(rec), flush=True)
    best = {}
    for rec in out:
        if rec["avg_us"] <= 0:
            continue
        m = rec["msg"]
        if m not in best or rec["avg_us"] < best[m]["avg_us"]:
            best[m] = rec
    print(json.dumps({"best_per_msg": {str(m): {
        "window": r["window"], "inflight": r["inflight"],
        "avg_us": r["avg_us"], "vs_two_sided": r["vs_two_sided"]}
        for m, r in sorted(best.items())}}), flush=True)


if __name__ == "__main__":
    main()
