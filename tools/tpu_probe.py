#!/usr/bin/env python
"""Periodic real-TPU liveness probe + artifact auto-capture (round 4).

The axon TPU tunnel wedges for hours and comes alive for minutes-long
windows (round 3 saw exactly two, at 10:25Z and 13:56Z).  This daemon
makes every recovery attempt *evidence*:

- every ``--interval`` seconds it spawns a throwaway subprocess that
  tries to enumerate devices and run one tiny matmul on the default
  (non-forced) platform, with a hard timeout + process-group kill;
- every attempt is appended to ``TPU_PROBE_r05.log`` with a timestamp
  and outcome (``hang``/``error``/``ok platform=...``);
- on success it runs the real-chip capture suite in INFORMATION-VALUE
  order (round-3 verdict: the window closed before the highest-value
  capture ran).  Round 4 order:
    1. the 12-case real-chip compile suite (10 ring_dma kernel families + 2 fused-attention mesh shapes) — the standing
       unknown: the only round-3 hardware run said "2 failed, 1
       passed" and the fix (454c1ef) was never re-validated.  On
       failure it RETRIES ONCE immediately to split flake from
       deterministic.  Full pytest output appends to
       ``TPU_CAPTURE_ring_dma.log`` whatever the outcome.
    2. the Pallas EC kernel smoke (seconds),
    3. ``bench.py`` -> ``BENCH_TPU_r05.json`` (platform-stamped),
    4. the short-path crossover sweep -> ``TPU_CROSSOVER_r05.json``
       (data for the accelerator SHORT_MSG_MAX auto value),
    5. the full size sweep -> ``BENCH_TPU_SWEEP_r05.json`` (longest).

Run supervised (restarts the probe loop if it ever dies — round-3
verdict #10: the daemon must stay armed across the whole round):

    nohup python tools/tpu_probe.py --supervise >/dev/null 2>&1 &

Mirrors the intent of the reference's perf capture flow
(/root/reference/tools/perf/ucc_pt_benchmark.cc) being run on real
hardware: numbers without a platform record are not evidence.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "TPU_PROBE_r05.log")
WATCHDOG_LOG = os.path.join(REPO, "TPU_WATCHDOG_r05.json")

PROBE_SRC = r"""
import jax
ds = jax.devices()
import jax.numpy as jnp
x = jnp.ones((256, 256), dtype=jnp.bfloat16)
(x @ x).block_until_ready()
print("PROBE_OK platform=%s kind=%s n=%d" % (
    ds[0].platform, getattr(ds[0], "device_kind", "?"), len(ds)))
"""


def log(line: str) -> None:
    stamp = time.strftime("%Y-%m-%dT%H:%M:%S%z")
    with open(LOG, "a") as f:
        f.write(f"{stamp} {line}\n")


def run_sub(argv, timeout, env=None):
    """Run argv in its own process group; kill the whole group on timeout."""
    full_env = dict(os.environ)
    # The probe wants the REAL platform: drop any cpu-forcing leftovers.
    full_env.pop("JAX_PLATFORMS", None)
    full_env.pop("XLA_FLAGS", None)
    # Arm the stall watchdog (ucc_tpu/obs/watchdog.py) in every child:
    # a wedged-chip round then leaves per-task state dumps (which
    # collective/algorithm/round/peers were in flight) in WATCHDOG_LOG
    # instead of this log's bare `hang` lines. ACTION=cancel escalates
    # at the hard deadline: stuck collectives are cancelled with
    # ERR_TIMED_OUT (posted ops unwound), so a wedged round exits as an
    # attributed `timeout(coll=...)` instead of eating the probe's
    # process-group kill.
    full_env.setdefault("UCC_WATCHDOG_TIMEOUT", "60")
    full_env.setdefault("UCC_WATCHDOG_ACTION", "cancel")
    # hard deadline must land BEFORE the probe's own process-group kill
    # (default --timeout 90s) or the cancel rung could never run: dump
    # at 60s, cancel at 80s, kill at 90s. Still clear of the 20-40s
    # worst-case first-compile stall of a healthy real-chip collective.
    full_env.setdefault("UCC_WATCHDOG_HARD_TIMEOUT", "80")
    full_env.setdefault("UCC_WATCHDOG_FILE", WATCHDOG_LOG)
    if env:
        full_env.update(env)
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=full_env, start_new_session=True, cwd=REPO)
    try:
        out, _ = proc.communicate(timeout=timeout)
        return proc.returncode, out
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            pass
        try:
            # BOUNDED reap: a child stuck in an uninterruptible ioctl
            # (the wedged-tunnel D-state, see jaxshim.ensure_live_backend)
            # ignores SIGKILL — abandon it rather than wedging the daemon
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        return None, ""


def _watchdog_size() -> int:
    try:
        return os.path.getsize(WATCHDOG_LOG)
    except OSError:
        return 0


def _watchdog_evidence(offset: int, path: str = None):
    """(stalled-collective names, summary) from the newest watchdog
    state dump written AFTER ``offset`` (the file size before this probe
    attempt) — the evidence that upgrades a bare `hang` into an
    attributed `timeout(coll=...)`. The offset guard matters: the dump
    file is shared by every child and never truncated, so without it a
    hang that produced no dump (e.g. wedged at the XLA layer) would be
    blamed on a stale dump from an earlier round. ``path`` defaults to
    this probe's WATCHDOG_LOG; tools/snapshot_gate.py reuses the parser
    against its own dump file."""
    try:
        with open(path or WATCHDOG_LOG) as f:
            f.seek(offset)
            last = None
            for line in f:
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("reason") == "rank_failed":
                    # rank-failure evidence notes (fault/health.py) are
                    # collected separately by _rank_failure_evidence;
                    # they are not stall dumps
                    continue
                last = line
            if not last:
                return [], ""
        rep = json.loads(last)
        stalled = rep.get("stalled_tasks") or rep.get("stalled_teams") or []
        names = [f"{t.get('coll') or t.get('state')}/"
                 f"{t.get('alg') or t.get('task') or ''}" for t in stalled]
        return names, (f"(watchdog: {len(stalled)} stalled, "
                       f"queue_depth={rep.get('progress_queue_depth')}, "
                       f"{','.join(names[:4])})")
    except (OSError, ValueError):
        return [], ""


def _rank_failure_evidence(offset: int, path: str = None):
    """Failed ranks named by ``rank_failed`` evidence lines written after
    ``offset`` (fault/health.py writes one per detection when the
    watchdog is armed). The union across lines is the attributed dead
    set — the third outcome class alongside hang/timeout/error."""
    ranks = set()
    source = ""
    try:
        with open(path or WATCHDOG_LOG) as f:
            f.seek(offset)
            for line in f:
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("reason") == "rank_failed":
                    ranks.update(int(r) for r in
                                 rec.get("failed_ranks") or ())
                    source = rec.get("source") or source
    except (OSError, ValueError):
        pass
    return sorted(ranks), source


def classify(rc, out: str, wd_offset: int):
    """Outcome taxonomy (ISSUE-2 + ISSUE-4 CI satellites): `ok`, `error`
    (child exited nonzero), `timeout(coll=...)` (child was killed or
    failed but the watchdog attributed the stall to named collectives),
    `rank_failed(ranks=...)` (the liveness layer attributed the failure
    to named dead ranks — the most specific evidence, so it wins), and
    bare `hang` only when there is genuinely no evidence — a wedge
    below the collective layer."""
    tail = out.strip().splitlines()[-1] if out.strip() else ""
    if rc == 0 and "PROBE_OK" in out:
        return "ok", tail
    failed, fsource = _rank_failure_evidence(wd_offset)
    if failed:
        return (f"rank_failed(ranks={','.join(str(r) for r in failed)})",
                f"(source={fsource}) {tail[-160:]}")
    names, summary = _watchdog_evidence(wd_offset)
    if rc is None:
        if names:
            return f"timeout(coll={','.join(sorted(set(names))[:4])})", \
                summary
        return "hang", summary
    if names:
        # armed UCC_WATCHDOG_ACTION=cancel: the child *exited* (nonzero)
        # because stuck collectives were cancelled — attribute it
        return f"timeout(coll={','.join(sorted(set(names))[:4])})", \
            f"{summary} {tail[-160:]}"
    return "error", tail[-200:]


def probe_once(timeout: float):
    wd_offset = _watchdog_size()
    rc, out = run_sub([sys.executable, "-c", PROBE_SRC], timeout)
    return classify(rc, out, wd_offset)


STATE = os.path.join(REPO, "TPU_PROBE_STATE.json")


def _load_state():
    try:
        with open(STATE) as f:
            return json.load(f)
    except Exception:  # noqa: BLE001
        return {}


def _save_state(state):
    with open(STATE, "w") as f:
        json.dump(state, f, indent=1)


MAX_ATTEMPTS = 3


def _exhausted(state, name):
    """A deterministically-failing artifact must not hog the single chip
    forever: cap attempts and treat the cap as terminal (the failure is
    itself recorded evidence in the log)."""
    n = state.get(name + "_attempts", 0)
    if state.get(name):
        return True
    if n >= MAX_ATTEMPTS:
        return True
    state[name + "_attempts"] = n + 1
    return False


def _ring_dma_once():
    """One run of the 12-case real-chip compile suite; returns
    (rc, out, tail).  UCC_TPU_REAL_CHIP=1 tells tests/conftest.py NOT
    to force the cpu platform — without it the "real chip" tests skip
    even during a live window (that is exactly what happened on the
    round-3 10:25 capture: rc=0 but '2 skipped')."""
    rc, out = run_sub(
        [sys.executable, "-m", "pytest", "tests/test_ring_dma.py",
         "-q", "--no-header", "-k", "RealChip or compiles_on_tpu",
         "--override-ini", "addopts="],
        timeout=900, env={"UCC_TPU_REAL_CHIP": "1"})
    tail = out.strip().splitlines()[-1] if out.strip() else ""
    # chip windows are minutes long: persist the FULL output so a
    # hardware-only failure is diagnosable after the tunnel wedges.
    # APPEND with a header — a later wedged attempt (empty out) must
    # not destroy the previous attempt's evidence
    with open(os.path.join(REPO, "TPU_CAPTURE_ring_dma.log"), "a") as f:
        f.write(f"==== attempt {time.strftime('%Y-%m-%dT%H:%M:%S%z')}"
                f" rc={rc} ====\n{out}\n")
    return rc, out, tail


def capture_artifacts():
    """Chip is alive: capture in information-value order (ring_dma
    families FIRST — the standing hardware unknown — then EC smoke,
    bench, crossover, full sweep).  Per-artifact success is persisted
    in TPU_PROBE_STATE.json so a daemon restart after a partial
    capture retries only what is missing."""
    state = _load_state()
    log("CAPTURE: starting real-chip artifact capture "
        f"(already done: {[k for k, v in state.items() if v is True]})")

    if not _exhausted(state, "ring_dma"):
        rc, out, tail = _ring_dma_once()
        log(f"CAPTURE: ring_dma real-chip test rc={rc} tail={tail!r}")
        # rc==0 with everything skipped is NOT success
        ok = rc == 0 and " passed" in out and " skipped" not in tail
        if not ok and rc is not None:
            # immediate one-retry in the same window: a second identical
            # failure means deterministic, a pass means flake — either
            # way the distinction is evidence (round-3 verdict #1)
            log("CAPTURE: ring_dma failed — immediate same-window retry")
            rc2, out2, tail2 = _ring_dma_once()
            log(f"CAPTURE: ring_dma retry rc={rc2} tail={tail2!r}")
            ok = rc2 == 0 and " passed" in out2 and " skipped" not in tail2
        state["ring_dma"] = ok
        _save_state(state)

    if not _exhausted(state, "ec"):
        rc, out = run_sub(
            [sys.executable, "-c",
             "from ucc_tpu.ec.tpu import EcTpu;"
             "from ucc_tpu.constants import DataType, ReductionOp;"
             "import jax, numpy as np, jax.numpy as jnp;"
             "assert jax.default_backend() == 'tpu', jax.default_backend();"
             "ec=EcTpu(); a=jnp.arange(4096,dtype=jnp.float32);"
             "t=ec.reduce(None,[a,a],4096,DataType.FLOAT32,"
             "ReductionOp.SUM);"
             "r=np.asarray(t.array);"
             "assert np.allclose(r, 2*np.arange(4096)), r[:4];"
             "print('EC_OK compiled-on-tpu', r[:2])"],
            timeout=600)
        log(f"CAPTURE: EC pallas smoke rc={rc} "
            f"tail={out.strip().splitlines()[-1] if out.strip() else ''!r}")
        state["ec"] = rc == 0
        _save_state(state)

    if not _exhausted(state, "bench"):
        rc, out = run_sub([sys.executable, "bench.py"], timeout=1200,
                          env={"UCC_BENCH_NO_FALLBACK": "1"})
        if rc == 0 and out.strip():
            line = out.strip().splitlines()[-1]
            try:
                rec = json.loads(line)
                # bench.py can fall back to the CPU mesh and still exit
                # 0 — a record without platform=tpu is NOT chip evidence
                if rec.get("detail", {}).get("platform") != "tpu":
                    log("CAPTURE: bench record not from tpu "
                        f"(platform={rec.get('detail', {}).get('platform')})"
                        " — rejected")
                else:
                    rec["captured_by"] = "tools/tpu_probe.py"
                    rec["captured_at"] = time.strftime(
                        "%Y-%m-%dT%H:%M:%S%z")
                    with open(os.path.join(REPO, "BENCH_TPU_r05.json"),
                              "w") as f:
                        json.dump(rec, f, indent=1)
                    log(f"CAPTURE: bench ok -> BENCH_TPU_r05.json {line}")
                    state["bench"] = True
            except ValueError:
                log(f"CAPTURE: bench output unparseable: {line[:200]}")
        else:
            log(f"CAPTURE: bench failed rc={rc} "
                f"tail={out.strip()[-200:]!r}")
        _save_state(state)

    if not _exhausted(state, "crossover"):
        # short-path crossover: where does host-staged eager actually
        # beat compiled dispatch on a real chip?  Sets the accelerator
        # SHORT_MSG_MAX auto value from data instead of the 4K guess
        # (tl/xla.py _short_msg_max; round-3 verdict weak #3)
        rc, out = run_sub(
            [sys.executable, "tools/crossover_bench.py"], timeout=1200)
        lines = [ln for ln in (out or "").strip().splitlines()
                 if ln.startswith("{")]
        rec = None
        if lines:
            try:
                rec = json.loads(lines[-1])
            except ValueError:
                rec = None
        if rc == 0 and rec and rec.get("platform") == "tpu":
            with open(os.path.join(REPO, "TPU_CROSSOVER_r05.json"),
                      "w") as f:
                json.dump(rec, f, indent=1)
            log("CAPTURE: crossover ok -> TPU_CROSSOVER_r05.json "
                f"crossover_bytes={rec.get('crossover_bytes')}")
            state["crossover"] = True
        else:
            log(f"CAPTURE: crossover failed rc={rc} "
                f"tail={(out or '').strip()[-200:]!r}")
        _save_state(state)

    if not _exhausted(state, "sweep"):
        # full size sweep on the real chip (each size is a fresh program
        # compile, so this is the longest capture — run it LAST; a wedge
        # mid-sweep still leaves the earlier artifacts). NO_FALLBACK +
        # a matched inner budget: the CPU rerun would be rejected below
        # anyway, and without the override bench's own 900s child cap
        # would kill a slow-compiling real-chip sweep early
        rc, out = run_sub([sys.executable, "bench.py", "--sweep"],
                          timeout=1800,
                          env={"UCC_BENCH_NO_FALLBACK": "1",
                               "UCC_BENCH_TIMEOUT": "1740"})
        lines = []
        for ln in (out or "").strip().splitlines():
            try:
                lines.append(json.loads(ln))
            except ValueError:
                continue
        # bench.py falls back to the virtual CPU mesh when the chip
        # wedges mid-run and still exits 0 — CPU-mesh records are NOT
        # real-chip evidence (the same rc==0-isn't-success trap as the
        # ring_dma capture); require the recorded platform to be tpu
        on_tpu = lines and all(
            r.get("detail", {}).get("platform") == "tpu" for r in lines)
        if rc == 0 and on_tpu:
            with open(os.path.join(REPO, "BENCH_TPU_SWEEP_r05.json"),
                      "w") as f:
                json.dump({"captured_at":
                           time.strftime("%Y-%m-%dT%H:%M:%S%z"),
                           "points": lines}, f, indent=1)
            log(f"CAPTURE: sweep ok -> BENCH_TPU_SWEEP_r05.json "
                f"({len(lines)} points)")
            state["sweep"] = True
        else:
            log(f"CAPTURE: sweep failed rc={rc} "
                f"tail={(out or '').strip()[-200:]!r}")
        _save_state(state)
    log("CAPTURE: done")
    return all(state.get(k) or
               state.get(k + "_attempts", 0) >= MAX_ATTEMPTS
               for k in ARTIFACTS)


ARTIFACTS = ("ring_dma", "ec", "bench", "crossover", "sweep")


def supervise(argv):
    """Keep the probe loop armed for the whole round (round-3 verdict
    #10: the daemon died repeatedly and live windows were nearly
    missed).  Restart the child on ANY exit, with a short backoff."""
    child_args = [sys.executable, os.path.abspath(__file__)] + argv
    while True:
        log(f"supervisor: launching probe loop {child_args[2:]}")
        proc = subprocess.Popen(child_args, cwd=REPO,
                                start_new_session=True)
        rc = proc.wait()
        log(f"supervisor: probe loop exited rc={rc}; restart in 30s")
        time.sleep(30)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=90.0)
    ap.add_argument("--timeout", type=float, default=90.0)
    ap.add_argument("--once", action="store_true")
    ap.add_argument("--supervise", action="store_true")
    args = ap.parse_args()

    if args.supervise:
        supervise([a for a in sys.argv[1:] if a != "--supervise"])
        return

    log(f"probe daemon start pid={os.getpid()} interval={args.interval}s "
        f"timeout={args.timeout}s")
    st = _load_state()
    captured = all(st.get(k) or st.get(k + "_attempts", 0) >= MAX_ATTEMPTS
                   for k in ARTIFACTS)
    while True:
        outcome, detail = probe_once(args.timeout)
        log(f"probe outcome={outcome} {detail}")
        if outcome == "ok" and not captured:
            captured = capture_artifacts()
        if args.once:
            break
        time.sleep(args.interval if not captured else args.interval * 4)


if __name__ == "__main__":
    main()
