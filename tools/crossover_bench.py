#!/usr/bin/env python
"""Measure the short-path crossover: at what message size does the
host-staged eager algorithm (TL/XLA ``short``) stop beating the
compiled shard_map dispatch?

The accelerator default for ``UCC_TL_XLA_SHORT_MSG_MAX`` ("auto") was
a guess (4 KiB) until this tool ran on a real chip (round-3 verdict
weak #3).  It times a persistent full-stack allreduce per size twice —
once with the short path forced (``SHORT_MSG_MAX`` huge) and once
disabled (``=0``) — and reports the first size where the compiled
program wins.  One JSON line on stdout; ``tools/tpu_probe.py`` stores
it as ``TPU_CROSSOVER_r04.json`` when captured on hardware.

Reference analog: the per-range crossover defaults the reference bakes
into its alg-select strings, e.g. allreduce ``0-4k:@0#4k-inf:@1``
(/root/reference/src/components/tl/ucp/allreduce/allreduce.h:24-25),
which upstream derived from exactly this kind of sweep.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SIZES_ELEMS = (1, 8, 64, 512, 4 << 10, 32 << 10, 256 << 10)  # 4B..1MiB f32


def _measure(ctxs, teams, devices, count, iters=40, warmup=4):
    import jax

    from bench import _persistent_reqs
    from ucc_tpu import Status

    n = len(devices)
    import jax.numpy as jnp
    srcs = [jax.device_put(jnp.ones((count,), jnp.float32), devices[r])
            for r in range(n)]
    argses, reqs = _persistent_reqs("allreduce", teams, ctxs, srcs, count, n)

    def one_round():
        for rq in reqs:
            rq.post()
        while any(rq.test() == Status.IN_PROGRESS for rq in reqs):
            for c in ctxs:
                c.progress()
        glob = getattr(reqs[0].task, "_out", None)
        jax.block_until_ready(
            glob if glob is not None else [a.dst.buffer for a in argses])

    for _ in range(warmup):
        one_round()
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        one_round()
        samples.append(time.perf_counter() - t0)
    for rq in reqs:
        rq.finalize()
    samples.sort()
    return samples[len(samples) // 2]


def main() -> None:
    import jax

    from bench import _make_job

    devices = jax.devices()
    n = len(devices)
    plat = devices[0].platform

    results = {}
    for mode, value in (("short", str(1 << 30)), ("compiled", "0")):
        os.environ["UCC_TL_XLA_SHORT_MSG_MAX"] = value
        ctxs, teams = _make_job(n)
        results[mode] = [
            _measure(ctxs, teams, devices, c) for c in SIZES_ELEMS]

    crossover = None
    points = []
    for i, c in enumerate(SIZES_ELEMS):
        s_us = results["short"][i] * 1e6
        x_us = results["compiled"][i] * 1e6
        points.append({"bytes": c * 4, "short_us": round(s_us, 2),
                       "compiled_us": round(x_us, 2)})
        if crossover is None and x_us < s_us:
            crossover = c * 4
    print(json.dumps({
        "platform": plat, "n_chips": n,
        "crossover_bytes": crossover,   # None = short wins everywhere swept
        "points": points,
        "note": "first size where compiled dispatch beats host-staged "
                "eager; feeds the SHORT_MSG_MAX auto default"}))


if __name__ == "__main__":
    main()
