#!/usr/bin/env python
"""Measure the short-path crossover: at what message size does the
host-staged eager algorithm (TL/XLA ``short``) stop beating the
compiled shard_map dispatch?

The accelerator default for ``UCC_TL_XLA_SHORT_MSG_MAX`` ("auto") was
a guess (4 KiB) until this tool ran on a real chip (round-3 verdict
weak #3).  It times a persistent full-stack allreduce per size twice —
once with the short path forced (``SHORT_MSG_MAX`` huge) and once
disabled (``=0``) — and reports the first size where the compiled
program wins.  One JSON line on stdout; ``tools/tpu_probe.py`` stores
it as ``TPU_CROSSOVER_r04.json`` when captured on hardware.

Reference analog: the per-range crossover defaults the reference bakes
into its alg-select strings, e.g. allreduce ``0-4k:@0#4k-inf:@1``
(/root/reference/src/components/tl/ucp/allreduce/allreduce.h:24-25),
which upstream derived from exactly this kind of sweep.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SIZES_ELEMS = (1, 8, 64, 512, 4 << 10, 32 << 10, 256 << 10)  # 4B..1MiB f32


def _measure(ctxs, teams, devices, count, iters=40, warmup=4):
    import jax

    from bench import _persistent_reqs
    from ucc_tpu import Status

    n = len(devices)
    import jax.numpy as jnp
    srcs = [jax.device_put(jnp.ones((count,), jnp.float32), devices[r])
            for r in range(n)]
    argses, reqs = _persistent_reqs("allreduce", teams, ctxs, srcs, count, n)

    def one_round():
        for rq in reqs:
            rq.post()
        while any(rq.test() == Status.IN_PROGRESS for rq in reqs):
            for c in ctxs:
                c.progress()
        glob = getattr(reqs[0].task, "_out", None)
        jax.block_until_ready(
            glob if glob is not None else [a.dst.buffer for a in argses])

    for _ in range(warmup):
        one_round()
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        one_round()
        samples.append(time.perf_counter() - t0)
    for rq in reqs:
        rq.finalize()
    samples.sort()
    return samples[len(samples) // 2]


def main() -> None:
    from bench import _force_cpu_if_requested, _make_job
    _force_cpu_if_requested()           # UCC_BENCH_CPU=1 smoke path
    import jax

    devices = jax.devices()
    n = len(devices)
    plat = devices[0].platform

    results = {}
    for mode, value in (("short", str(1 << 30)), ("compiled", "0")):
        os.environ["UCC_TL_XLA_SHORT_MSG_MAX"] = value
        ctxs, teams = _make_job(n)
        results[mode] = [
            _measure(ctxs, teams, devices, c) for c in SIZES_ELEMS]
        # tear the mode's job down before building the next one: on a
        # single real chip the second measurement must not share the
        # first job's contexts/cached programs/resident buffers
        for t in teams:
            t.destroy()
        for c in ctxs:
            c.destroy()

    points = []
    for i, c in enumerate(SIZES_ELEMS):
        points.append({"bytes": c * 4,
                       "short_us": round(results["short"][i] * 1e6, 2),
                       "compiled_us": round(
                           results["compiled"][i] * 1e6, 2)})
    # the crossover must PERSIST: a single noisy compiled win below a
    # larger short win must not set the threshold (the CPU smoke showed
    # exactly that shape). Take the largest size where short wins; the
    # crossover is the next swept size — compiled wins everywhere above.
    last_short_win = None
    for i, c in enumerate(SIZES_ELEMS):
        if results["short"][i] < results["compiled"][i]:
            last_short_win = i
    if last_short_win is None:
        crossover = 0                      # compiled wins everywhere:
                                           # nothing belongs on short
    elif last_short_win == len(SIZES_ELEMS) - 1:
        crossover = None                   # short wins at the top size
    else:
        crossover = SIZES_ELEMS[last_short_win + 1] * 4
    print(json.dumps({
        "platform": plat, "n_chips": n,
        "crossover_bytes": crossover,   # None = short wins everywhere swept
        "points": points,
        "note": "smallest swept size above which compiled dispatch beats "
                "host-staged eager PERSISTENTLY; feeds the SHORT_MSG_MAX "
                "auto default"}))


if __name__ == "__main__":
    main()
