#!/usr/bin/env python
"""Flight-recorder console (repo-root entry).

Thin shim over the packaged CLI — the implementation lives in
ucc_tpu/tools/fr.py (installed as the `ucc_fr` console script). Merges
per-rank flight dumps, runs the desync/straggler/missing-participant
diagnosis, exports Chrome-trace/Perfetto timelines, and can trigger a
live dump via SIGUSR2.

    python tools/fr.py ucc_flight.json --perfetto trace.json
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ucc_tpu.tools.fr import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
