#!/usr/bin/env python
"""Offline autotuner sweep (repo-root entry).

Thin shim over the packaged CLI — the implementation lives in
ucc_tpu/tools/tune.py (installed as the `ucc_tune` console script).
Sweeps every registered score-map candidate over a msg-size grid on a
live team and writes the topology-keyed tuning cache that
UCC_TUNER=offline|online loads at team activation.

    python tools/tune.py -p 4 -c allreduce -b 8 -e 1M
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ucc_tpu.tools.tune import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
