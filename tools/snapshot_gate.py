"""End-of-round snapshot gate (round-4 verdict #1d).

Round 4 shipped a red tree because the final commit was made without
running anything. This gate is the mechanical fix: it runs the FULL
suite and the driver's multichip dryrun and exits nonzero unless both
pass — run it before any end-of-round (or otherwise significant)
commit:

    python tools/snapshot_gate.py          # full gate (~5 min)
    python tools/snapshot_gate.py --quick  # import canary only (~5 s)

Exit 0 = safe to commit. Anything else = the tree is NOT shippable.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WATCHDOG_FILE = "/tmp/ucc_gate_watchdog.json"

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from tpu_probe import (_rank_failure_evidence,  # noqa: E402 - shared parser
                       _watchdog_evidence)


def _watchdog_outcome(offset: int) -> str:
    """Classify a failed/timed-out gate step from watchdog evidence
    written after ``offset``: `rank_failed(ranks=...)` when the liveness
    layer attributed it to named dead ranks (most specific evidence),
    `timeout(coll=...)` when the armed watchdog
    (UCC_WATCHDOG_ACTION=cancel) attributed the stall to named
    collectives, bare `hang` otherwise (wedged below the collective
    layer). Same taxonomy and parsers as tools/tpu_probe.py."""
    failed, _src = _rank_failure_evidence(offset, path=WATCHDOG_FILE)
    if failed:
        return f"rank_failed(ranks={','.join(str(r) for r in failed)})"
    names, _ = _watchdog_evidence(offset, path=WATCHDOG_FILE)
    if names:
        return f"timeout(coll={','.join(sorted(set(names))[:4])})"
    return "hang"


def _wd_size() -> int:
    try:
        return os.path.getsize(WATCHDOG_FILE)
    except OSError:
        return 0


def _run(title: str, argv, timeout: float, env=None) -> bool:
    print(f"[gate] {title} ...", flush=True)
    t0 = time.monotonic()
    wd_offset = _wd_size()
    # own session + group kill on timeout: pytest spawns multiprocessing
    # workers that inherit the captured pipes — killing only pytest would
    # leave the pipe open and block the post-kill read forever, hanging
    # the gate on exactly the broken tree it exists to catch
    try:
        import signal
        proc = subprocess.Popen(argv, cwd=REPO, env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True,
                                start_new_session=True)
        try:
            out, err = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            os.killpg(proc.pid, signal.SIGKILL)
            proc.wait(timeout=30)
            raise
        r = subprocess.CompletedProcess(argv, proc.returncode, out, err)
    except subprocess.TimeoutExpired:
        print(f"[gate] {title}: TIMEOUT after {timeout:.0f}s -> "
              f"{_watchdog_outcome(wd_offset)}", flush=True)
        return False
    dt = time.monotonic() - t0
    tail = "\n".join((r.stdout or "").strip().splitlines()[-3:])
    print(f"[gate] {title}: rc={r.returncode} in {dt:.0f}s\n{tail}",
          flush=True)
    if r.returncode != 0:
        print((r.stdout or "")[-3000:])
        print((r.stderr or "")[-2000:], file=sys.stderr)
    return r.returncode == 0


def _perf_baseline() -> float:
    """Reference allreduce busbw (GB/s/chip): BASELINE.json published
    value when present, else the most recent BENCH_r*.json record."""
    import glob
    import json
    try:
        with open(os.path.join(REPO, "BASELINE.json")) as fh:
            pub = json.load(fh).get("published", {})
        v = pub.get("allreduce_busbw_GBps")
        if v:
            return float(v)
    except (OSError, ValueError):
        pass
    best = 0.0
    for path in sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json"))):
        try:
            with open(path) as fh:
                rec = json.load(fh).get("parsed") or {}
            if rec.get("metric") == "allreduce_busbw_GBps":
                best = float(rec.get("value") or 0.0)  # latest round wins
        except (OSError, ValueError):
            continue
    return best


def _perf_smoke(env) -> None:
    """WARN-ONLY perf regression probe (never flips the gate's exit
    code — this box's run-to-run drift is real): run bench.py and
    compare allreduce busbw against the recorded baseline with a
    tolerance band (UCC_GATE_PERF_TOL, default 25%). Skip entirely with
    UCC_GATE_PERF=0."""
    import json
    if os.environ.get("UCC_GATE_PERF", "1").strip().lower() in \
            ("0", "n", "no", "off"):
        print("[gate] perf smoke: skipped (UCC_GATE_PERF=0)", flush=True)
        return
    base = _perf_baseline()
    if not base:
        print("[gate] perf smoke: no baseline busbw recorded; skipping",
              flush=True)
        return
    try:
        tol = float(os.environ.get("UCC_GATE_PERF_TOL", "0.25"))
    except ValueError:
        tol = 0.25
    print("[gate] perf smoke (warn-only) ...", flush=True)
    t0 = time.monotonic()
    # strip the gate's watchdog/fault/stats arming from the bench child:
    # any of them flips the TLs onto the instrumented per-message path,
    # biasing busbw low vs the baselines (recorded uninstrumented) and
    # hiding regressions in the cold-hook fast path
    bench_env = {k: v for k, v in env.items()
                 if not k.startswith(("UCC_WATCHDOG", "UCC_FAULT",
                                      "UCC_STATS", "UCC_PROFILE"))}
    try:
        r = subprocess.run([sys.executable, "bench.py"], cwd=REPO,
                           env=bench_env, capture_output=True, text=True,
                           timeout=900)
    except subprocess.TimeoutExpired:
        print("[gate] WARN: perf smoke timed out (not a gate failure)",
              flush=True)
        return
    value = None
    bench_error = None
    pool = {}
    for ln in (r.stdout or "").splitlines():
        if ln.startswith("{"):
            try:
                rec = json.loads(ln)
            except ValueError:
                continue
            if rec.get("metric") == "allreduce_busbw_GBps":
                detail = rec.get("detail") or {}
                if detail.get("error"):
                    # bench.py's all-backends-failed fallback record
                    # (value 0.0) is a broken bench run, not a perf
                    # regression — report it as such
                    bench_error = detail["error"]
                    continue
                value = float(rec.get("value") or 0.0)
                pool = detail.get("mc_pool") or {}
    dt = time.monotonic() - t0
    if value is None:
        reason = f"bench failed: {bench_error}" if bench_error else \
            "no busbw record produced"
        print(f"[gate] WARN: perf smoke — {reason} in {dt:.0f}s "
              f"(not a gate failure)", flush=True)
        return
    floor = base * (1.0 - tol)
    verdict = "OK" if value >= floor else \
        f"WARN: below baseline {base:.3f} - {tol:.0%} tolerance"
    print(f"[gate] perf smoke: allreduce busbw {value:.3f} GB/s/chip "
          f"(baseline {base:.3f}, floor {floor:.3f}, "
          f"pool hit-rate {pool.get('hit_rate', 'n/a')}, "
          f"steady allocs {pool.get('steady_state_allocs', 'n/a')}) "
          f"in {dt:.0f}s -> {verdict}", flush=True)


def _tuner_smoke(env) -> None:
    """WARN-ONLY autotuner probe (ISSUE 5 CI satellite, same warn-only
    harness as the PR-3 perf smoke): `ucc_tune --gate-smoke` sweeps one
    allreduce point, round-trips the winners through the tuning cache,
    and reports tuned vs default latency. Warn when the tuned selection
    is slower than the static default beyond the tolerance band
    (UCC_GATE_TUNER_TOL, default 25%) or the learned selection failed to
    engage. Skip with UCC_GATE_TUNER=0."""
    import json
    if os.environ.get("UCC_GATE_TUNER", "1").strip().lower() in \
            ("0", "n", "no", "off"):
        print("[gate] tuner smoke: skipped (UCC_GATE_TUNER=0)", flush=True)
        return
    try:
        tol = float(os.environ.get("UCC_GATE_TUNER_TOL", "0.25"))
    except ValueError:
        tol = 0.25
    print("[gate] tuner smoke (warn-only) ...", flush=True)
    t0 = time.monotonic()
    # same de-instrumentation as the perf smoke: watchdog/fault/stats
    # would bias both sides of the comparison onto the slow hook path
    smoke_env = {k: v for k, v in env.items()
                 if not k.startswith(("UCC_WATCHDOG", "UCC_FAULT",
                                      "UCC_STATS", "UCC_PROFILE",
                                      "UCC_TUNER"))}
    try:
        r = subprocess.run([sys.executable, "-m", "ucc_tpu.tools.tune",
                            "--gate-smoke"], cwd=REPO, env=smoke_env,
                           capture_output=True, text=True, timeout=600)
    except subprocess.TimeoutExpired:
        print("[gate] WARN: tuner smoke timed out (not a gate failure)",
              flush=True)
        return
    rec = None
    for ln in (r.stdout or "").splitlines():
        if ln.startswith("{"):
            try:
                cand = json.loads(ln)
            except ValueError:
                continue
            if cand.get("metric") == "tuner_gate_smoke":
                rec = cand
    dt = time.monotonic() - t0
    if rec is None or rec.get("error"):
        why = (rec or {}).get("error") or f"rc={r.returncode}, no record"
        print(f"[gate] WARN: tuner smoke — {why} in {dt:.0f}s "
              f"(not a gate failure)", flush=True)
        return
    tuned = float(rec.get("tuned_us") or 0.0)
    default = float(rec.get("default_us") or 0.0)
    ceil = default * (1.0 + tol)
    verdict = "OK"
    if not rec.get("learned_selection"):
        verdict = "WARN: learned selection did not engage"
    elif default and tuned > ceil:
        verdict = f"WARN: tuned slower than default + {tol:.0%} tolerance"
    print(f"[gate] tuner smoke: tuned {tuned:.1f}us vs default "
          f"{default:.1f}us (winner {rec.get('winner')}, ceiling "
          f"{ceil:.1f}us) in {dt:.0f}s -> {verdict}", flush=True)


def _quant_smoke(env) -> None:
    """WARN-ONLY quantized-collectives probe (ISSUE 6 CI satellite,
    same harness as the perf/tuner smokes): run the 4-rank 256KiB
    allreduce point over the wire-bound host path (socket TL — the DCN
    stand-in where wire bytes dominate; the in-process shm 'wire' is a
    memcpy) with UCC_QUANT=int8 and without, then check that the int8
    point (a) beats exact on wire bytes, (b) stays inside the error
    budget, and (c) reports its busbw speedup over the exact path.
    Skip with UCC_GATE_QUANT=0."""
    import json
    if os.environ.get("UCC_GATE_QUANT", "1").strip().lower() in \
            ("0", "n", "no", "off"):
        print("[gate] quant smoke: skipped (UCC_GATE_QUANT=0)", flush=True)
        return
    print("[gate] quant smoke (warn-only) ...", flush=True)
    t0 = time.monotonic()
    base_env = {k: v for k, v in env.items()
                if not k.startswith(("UCC_WATCHDOG", "UCC_FAULT",
                                     "UCC_STATS", "UCC_PROFILE",
                                     "UCC_QUANT"))}
    base_env["UCC_TLS"] = "socket,self"
    argv = [sys.executable, "-m", "ucc_tpu.tools.perftest",
            "-c", "allreduce", "-m", "host", "-p", "4",
            "-b", "256K", "-e", "256K", "-n", "8", "-w", "2",
            "--json", "-F"]

    def run_point(quant: bool):
        e = dict(base_env)
        av = list(argv)
        if quant:
            e["UCC_QUANT"] = "int8"
            av.append("--quant")
        try:
            r = subprocess.run(av, cwd=REPO, env=e, capture_output=True,
                               text=True, timeout=300)
        except subprocess.TimeoutExpired:
            return None
        for ln in (r.stdout or "").splitlines():
            if ln.startswith("{"):
                try:
                    return json.loads(ln)
                except ValueError:
                    continue
        return None

    q = run_point(True)
    e = run_point(False)
    dt = time.monotonic() - t0
    if not q or not e:
        print(f"[gate] WARN: quant smoke produced no record in {dt:.0f}s "
              f"(not a gate failure)", flush=True)
        return
    qd = (q.get("detail") or {}).get("quant") or {}
    problems = []
    if not str(qd.get("alg", "")).startswith("qint8"):
        problems.append(f"quantized alg not selected (got "
                        f"{qd.get('alg')})")
    # MEASURED transport bytes (the verification round's bytes_sent
    # delta) vs the minimum any exact algorithm must move — both
    # sides real, so a regression that stops compressing the actual
    # wire traffic fails this even if selection still looks right
    measured = qd.get("measured_wire_bytes_total")
    floor = qd.get("exact_wire_floor_bytes_total")
    if not measured or not floor:
        problems.append("no measured wire bytes in the quant record")
    elif measured >= floor:
        problems.append(f"measured wire bytes {measured} do not beat "
                        f"the exact floor {floor}")
    if not qd.get("within_budget"):
        problems.append(f"max_rel_err {qd.get('max_rel_err')} outside "
                        f"budget {qd.get('error_budget')}")
    q_bw = float(q.get("busbw_GBps") or 0.0)
    e_bw = float(e.get("busbw_GBps") or 0.0)
    ratio = q_bw / e_bw if e_bw else 0.0
    if e_bw and ratio < 1.0:
        problems.append(f"quant busbw below exact ({ratio:.2f}x)")
    verdict = "OK" if not problems else "WARN: " + "; ".join(problems)
    print(f"[gate] quant smoke: int8 {q_bw:.3f} vs exact {e_bw:.3f} "
          f"GB/s ({ratio:.2f}x), measured wire {measured}B vs exact "
          f"floor {floor}B (static ratio {qd.get('wire_ratio')}), "
          f"max_rel_err {qd.get('max_rel_err')} (budget "
          f"{qd.get('error_budget')}) in {dt:.0f}s -> {verdict}",
          flush=True)


def _native_smoke(env) -> None:
    """WARN-ONLY native-matcher probe (ISSUE 7 CI satellite, same
    harness as the other smokes): run tools/native_bench.py --compare in
    BOTH thread modes and check the v2 core's two claims — native >=
    python colls/s under concurrent progress threads, and within 5%
    single-threaded (where v1 lost ~2x). Skips itself when the core is
    not built. Disable with UCC_GATE_NATIVE=0."""
    import json
    if os.environ.get("UCC_GATE_NATIVE", "1").strip().lower() in \
            ("0", "n", "no", "off"):
        print("[gate] native smoke: skipped (UCC_GATE_NATIVE=0)",
              flush=True)
        return
    print("[gate] native smoke (warn-only) ...", flush=True)
    t0 = time.monotonic()
    # same de-instrumentation as the perf smoke: any armed subsystem
    # flips the TLs onto the instrumented per-message path and biases
    # both matchers low
    smoke_env = {k: v for k, v in env.items()
                 if not k.startswith(("UCC_WATCHDOG", "UCC_FAULT",
                                      "UCC_STATS", "UCC_PROFILE",
                                      "UCC_TL_SHM_NATIVE"))}
    sys.path.insert(0, REPO)
    try:
        from ucc_tpu.native import available
        if not available():
            print("[gate] native smoke: core not built; skipping",
                  flush=True)
            return
    except Exception:  # noqa: BLE001
        print("[gate] native smoke: core probe failed; skipping",
              flush=True)
        return

    def run_mode(single: bool):
        argv = [sys.executable, "tools/native_bench.py", "--compare",
                "--iters", "200"]
        if single:
            argv.append("--single")
        try:
            r = subprocess.run(argv, cwd=REPO, env=smoke_env,
                               capture_output=True, text=True, timeout=600)
        except subprocess.TimeoutExpired:
            return None
        for ln in reversed((r.stdout or "").strip().splitlines()):
            if ln.startswith("{") and "native_speedup_vs_python" in ln:
                try:
                    return json.loads(ln)
                except ValueError:
                    continue
        return None

    mt = run_mode(single=False)
    # ST parity sits inside the box's run-to-run noise (BASELINE round 7
    # records 0.93-1.50x across healthy runs): judge the MEDIAN of three
    # runs — the baseline's own methodology — so the warn doesn't fire
    # on a single unlucky draw and train operators to ignore it
    st_runs = [r for r in (run_mode(single=True) for _ in range(3))
               if r is not None]
    # lower-middle on even counts: with a lost run (subprocess timeout)
    # the optimistic pick would mask exactly the ST regression this
    # smoke exists to catch
    st = (sorted(st_runs, key=lambda r: float(
        r.get("native_speedup_vs_python") or 0.0))[(len(st_runs) - 1) // 2]
        if st_runs else None)
    dt = time.monotonic() - t0
    if mt is None or st is None:
        print(f"[gate] WARN: native smoke produced no verdict in "
              f"{dt:.0f}s (not a gate failure)", flush=True)
        return
    problems = []
    if float(mt.get("native_speedup_vs_python") or 0.0) < 1.0:
        problems.append(
            f"MT: native {mt.get('native_colls_per_s')} colls/s below "
            f"python {mt.get('python_colls_per_s')}")
    if float(st.get("native_speedup_vs_python") or 0.0) < 0.95:
        problems.append(
            f"ST: native {st.get('native_colls_per_s')} colls/s (median "
            f"of {len(st_runs)} runs) more "
            f"than 5% below python {st.get('python_colls_per_s')}")
    verdict = "OK" if not problems else "WARN: " + "; ".join(problems)
    print(f"[gate] native smoke: MT native "
          f"{mt.get('native_speedup_vs_python')}x python "
          f"({mt.get('native_colls_per_s')} vs "
          f"{mt.get('python_colls_per_s')} colls/s), ST "
          f"{st.get('native_speedup_vs_python')}x "
          f"({st.get('native_colls_per_s')} vs "
          f"{st.get('python_colls_per_s')}) in {dt:.0f}s -> {verdict}",
          flush=True)


def _scale_smoke(env) -> None:
    """WARN-ONLY pod-scale probe (ISSUE 8 CI satellite, same harness as
    the other smokes): simulate a 512-rank host-TL mesh (thread OOB
    bootstrapped through the TREE exchange, synthetic 8-pods × 8-nodes ×
    8-ranks layout), create the team, run the collective matrix, and
    check the round's two claims — bootstrap OOB rounds/fan-in scale
    logarithmically (rounds per allgather ≤ 2·tree-levels, per-store
    fan-in ≤ max(ppn, radix) instead of the flat store's n connections),
    and the N-level hier allreduce beats the flat DCN default on the
    measured cell (run on a min(n, 128)-rank mesh — see
    run_sim.cells_n). UCC_GATE_SCALE_N downsizes the mesh; skip with
    UCC_GATE_SCALE=0."""
    import json
    import math
    if os.environ.get("UCC_GATE_SCALE", "1").strip().lower() in \
            ("0", "n", "no", "off"):
        print("[gate] scale smoke: skipped (UCC_GATE_SCALE=0)", flush=True)
        return
    try:
        n = int(os.environ.get("UCC_GATE_SCALE_N", "512"))
    except ValueError:
        n = 512
    # pod shape that keeps >1 pod (3 hier levels) whenever the mesh has
    # >=2 nodes: 8-rank nodes, pods of at most 8 nodes but never more
    # than half the node count. A single-node mesh (UCC_GATE_SCALE_N<=8)
    # can only resolve 2 levels — expect that instead of warning on it.
    nodes = max(1, (n + 7) // 8)
    npp = max(1, min(8, nodes // 2))
    pods = (nodes + npp - 1) // npp
    want_levels = 3 if pods >= 2 else 2
    print(f"[gate] scale smoke ({n} ranks, ppn 8, {npp} nodes/pod, "
          f"warn-only) ...", flush=True)
    t0 = time.monotonic()
    smoke_env = {k: v for k, v in env.items()
                 if not k.startswith(("UCC_WATCHDOG", "UCC_FAULT",
                                      "UCC_STATS", "UCC_PROFILE"))}
    try:
        r = subprocess.run(
            [sys.executable, "-m", "ucc_tpu.tools.scale", "-n", str(n),
             "--ppn", "8", "--npp", str(npp), "--cell-sizes", "65536",
             "--cell-iters", "3", "--json"],
            cwd=REPO, env=smoke_env, capture_output=True, text=True,
            timeout=1500)
    except subprocess.TimeoutExpired:
        print("[gate] WARN: scale smoke timed out (not a gate failure)",
              flush=True)
        return
    rec = None
    for ln in (r.stdout or "").splitlines():
        if ln.startswith("{"):
            try:
                rec = json.loads(ln)
            except ValueError:
                continue
    dt = time.monotonic() - t0
    if rec is None or rec.get("error"):
        why = (rec or {}).get("error") or f"rc={r.returncode}, no record"
        print(f"[gate] WARN: scale smoke — {why} in {dt:.0f}s "
              f"(not a gate failure)", flush=True)
        return
    problems = []
    oob = (rec.get("oob") or {}).get("team") or {}
    levels = int(oob.get("levels") or 0)
    fanin = int(oob.get("max_fanin") or 0)
    rounds = float(oob.get("rounds_per_allgather_max") or 0.0)
    # the logarithmic claim: tree depth within log2(n), per-allgather
    # store rounds bounded by one up + one down pass of the tree, and
    # no store serving more than max(ppn, radix) members (flat = n)
    if not levels or levels > math.log2(max(2, n)):
        problems.append(f"tree depth {levels} not logarithmic for n={n}")
    if rounds > 2 * levels:
        problems.append(f"bootstrap rounds/allgather {rounds} exceed "
                        f"2*levels={2 * levels}")
    if not fanin or fanin >= n or fanin > 16:
        problems.append(f"store fan-in {fanin} not bounded (flat={n})")
    if len(rec.get("matrix") or []) < 6:
        problems.append(f"collective matrix incomplete: {rec.get('matrix')}")
    if int(rec.get("hier_levels") or 0) < want_levels:
        problems.append(f"hier resolved {rec.get('hier_levels')} levels, "
                        f"expected {want_levels} (pods not detected)")
    cells = rec.get("cells") or []
    best = max((c.get("hier_speedup") or 0.0 for c in cells), default=0.0)
    if best <= 1.0:
        problems.append(f"hier allreduce did not beat the flat DCN "
                        f"default on any cell (best {best}x)")
    verdict = "OK" if not problems else "WARN: " + "; ".join(problems)
    print(f"[gate] scale smoke: {n} ranks team_create "
          f"{rec.get('team_create_s')}s, tree levels {levels}, fan-in "
          f"{fanin} (flat {n}), rounds/allgather {rounds}, hier vs flat "
          f"DCN best {best}x @ {rec.get('cells_ranks')} ranks "
          f"in {dt:.0f}s -> {verdict}", flush=True)


def _gen_smoke(env) -> None:
    """WARN-ONLY collective-compiler probe (ISSUE 10 CI satellite, same
    harness as the other smokes): ``python -m ucc_tpu.dsl.smoke``
    compiles + statically verifies every built-in generated family,
    runs the collective matrix with a generated allreduce pinned, and
    drives the tuner end-to-end with generated candidates (sweep ->
    cache -> reload -> tuned activation must land on a LEARNED
    generated selection). Skip with UCC_GATE_GEN=0."""
    import json
    if os.environ.get("UCC_GATE_GEN", "1").strip().lower() in \
            ("0", "n", "no", "off"):
        print("[gate] gen smoke: skipped (UCC_GATE_GEN=0)", flush=True)
        return
    print("[gate] collective-compiler smoke (warn-only) ...", flush=True)
    t0 = time.monotonic()
    # same de-instrumentation as the other smokes, plus a clean GEN/
    # QUANT/TUNER slate: the smoke arms its own knobs per probe job
    smoke_env = {k: v for k, v in env.items()
                 if not k.startswith(("UCC_WATCHDOG", "UCC_FAULT",
                                      "UCC_STATS", "UCC_PROFILE",
                                      "UCC_GEN", "UCC_QUANT",
                                      "UCC_TUNER"))}
    try:
        r = subprocess.run(
            [sys.executable, "-m", "ucc_tpu.dsl.smoke"],
            cwd=REPO, env=smoke_env, capture_output=True, text=True,
            timeout=600)
    except subprocess.TimeoutExpired:
        print("[gate] WARN: gen smoke timed out (not a gate failure)",
              flush=True)
        return
    rec = None
    for ln in (r.stdout or "").splitlines():
        if ln.startswith("{"):
            try:
                cand = json.loads(ln)
            except ValueError:
                continue
            if cand.get("metric") == "gen_gate_smoke":
                rec = cand
    dt = time.monotonic() - t0
    if rec is None or rec.get("error"):
        why = (rec or {}).get("error") or f"rc={r.returncode}, no record"
        print(f"[gate] WARN: gen smoke — {why} in {dt:.0f}s "
              f"(not a gate failure)", flush=True)
        return
    problems = []
    if int(rec.get("programs_verified") or 0) < 6:
        problems.append(f"only {rec.get('programs_verified')} generated "
                        f"programs survived verification")
    if len(rec.get("matrix") or []) < 6:
        problems.append(f"collective matrix incomplete with a generated "
                        f"allreduce pinned: {rec.get('matrix')}")
    if not rec.get("pinned_engaged"):
        problems.append("TUNE-pinned generated allreduce did not engage")
    if not rec.get("learned_generated_selection"):
        problems.append(
            f"tuner round trip did not land on a learned generated "
            f"selection (winner {rec.get('tuned_winner')}, origin "
            f"{rec.get('tuned_origin')})")
    if not rec.get("tuned_dispatch_ok"):
        problems.append("tuned generated dispatch failed")
    verdict = "OK" if not problems else "WARN: " + "; ".join(problems)
    print(f"[gate] gen smoke: {rec.get('programs_verified')} programs "
          f"verified ({', '.join((rec.get('programs') or [])[:4])}...), "
          f"matrix {len(rec.get('matrix') or [])}/6 with "
          f"{rec.get('pinned_alg')} pinned, tuner round trip -> "
          f"{rec.get('tuned_winner')} ({rec.get('tuned_origin')} "
          f"{rec.get('tuned_gen')}) dispatched as "
          f"{rec.get('tuned_dispatch_alg')} in {dt:.0f}s -> {verdict}",
          flush=True)


def _search_smoke(env) -> None:
    """WARN-ONLY program-search probe (ISSUE 14 CI satellite):
    ``python -m ucc_tpu.dsl.smoke --search`` fits the alpha-beta cost
    model from a one-point generated sweep, runs a budgeted
    cost-model-guided search on a small mesh, and asserts that (a) a
    searched program verifies + registers (origin 'searched') +
    dispatches through the tuner-cache round trip, and (b) predicted
    cost ordering is sane — the best-predicted finalist lands in the
    measured top half. Skip with UCC_GATE_SEARCH=0."""
    import json
    if os.environ.get("UCC_GATE_SEARCH", "1").strip().lower() in \
            ("0", "n", "no", "off"):
        print("[gate] search smoke: skipped (UCC_GATE_SEARCH=0)",
              flush=True)
        return
    print("[gate] program-search smoke (warn-only) ...", flush=True)
    t0 = time.monotonic()
    smoke_env = {k: v for k, v in env.items()
                 if not k.startswith(("UCC_WATCHDOG", "UCC_FAULT",
                                      "UCC_STATS", "UCC_PROFILE",
                                      "UCC_GEN", "UCC_QUANT",
                                      "UCC_TUNER"))}
    try:
        r = subprocess.run(
            [sys.executable, "-m", "ucc_tpu.dsl.smoke", "--search"],
            cwd=REPO, env=smoke_env, capture_output=True, text=True,
            timeout=900)
    except subprocess.TimeoutExpired:
        print("[gate] WARN: search smoke timed out (not a gate failure)",
              flush=True)
        return
    rec = None
    for ln in (r.stdout or "").splitlines():
        if ln.startswith("{"):
            try:
                cand = json.loads(ln)
            except ValueError:
                continue
            if cand.get("metric") == "search_gate_smoke":
                rec = cand
    dt = time.monotonic() - t0
    if rec is None or rec.get("error"):
        why = (rec or {}).get("error") or f"rc={r.returncode}, no record"
        print(f"[gate] WARN: search smoke — {why} in {dt:.0f}s "
              f"(not a gate failure)", flush=True)
        return
    problems = []
    if not rec.get("winner"):
        problems.append("no measured winner")
    if not rec.get("searched_registered"):
        problems.append("no searched-origin candidate registered on "
                        "the fresh team")
    if not rec.get("dispatch_ok"):
        problems.append("tuned dispatch failed")
    if rec.get("searched_won") and rec.get("winner_dispatched") is False:
        problems.append(f"searched winner {rec.get('winner')} did not "
                        f"dispatch (got {rec.get('dispatch_alg')})")
    if rec.get("prediction_sane") is False:
        problems.append(f"best-predicted finalist ranked "
                        f"{rec.get('best_predicted_rank')} of "
                        f"{rec.get('finalists')} measured")
    verdict = "OK" if not problems else "WARN: " + "; ".join(problems)
    print(f"[gate] search smoke: winner {rec.get('winner')} "
          f"(predicted {rec.get('winner_predicted_us')}us, measured "
          f"{rec.get('winner_measured_us')}us, {rec.get('finalists')} "
          f"finalists, cost model {rec.get('cost_model')}), dispatched "
          f"as {rec.get('dispatch_alg')} in {dt:.0f}s -> {verdict}",
          flush=True)


def _devgen_smoke(env) -> None:
    """WARN-ONLY device-side compiler-backend probe (ISSUE 15 CI
    satellite): ``python -m ucc_tpu.dsl.smoke --device`` lowers +
    verifies every device family, runs the TPU-memtype collective
    matrix with a generated-device allreduce TUNE-pinned, and asserts
    the lowered program's result is bitwise-identical to the host
    interpreter running the same verified IR. Skip with
    UCC_GATE_DEVGEN=0."""
    import json
    if os.environ.get("UCC_GATE_DEVGEN", "1").strip().lower() in \
            ("0", "n", "no", "off"):
        print("[gate] devgen smoke: skipped (UCC_GATE_DEVGEN=0)",
              flush=True)
        return
    print("[gate] device-backend smoke (warn-only) ...", flush=True)
    t0 = time.monotonic()
    smoke_env = {k: v for k, v in env.items()
                 if not k.startswith(("UCC_WATCHDOG", "UCC_FAULT",
                                      "UCC_STATS", "UCC_PROFILE",
                                      "UCC_GEN", "UCC_QUANT",
                                      "UCC_TUNER"))}
    try:
        r = subprocess.run(
            [sys.executable, "-m", "ucc_tpu.dsl.smoke", "--device"],
            cwd=REPO, env=smoke_env, capture_output=True, text=True,
            timeout=600)
    except subprocess.TimeoutExpired:
        print("[gate] WARN: devgen smoke timed out (not a gate "
              "failure)", flush=True)
        return
    rec = None
    for ln in (r.stdout or "").splitlines():
        if ln.startswith("{"):
            try:
                cand = json.loads(ln)
            except ValueError:
                continue
            if cand.get("metric") == "devgen_gate_smoke":
                rec = cand
    dt = time.monotonic() - t0
    if rec is None or rec.get("error"):
        why = (rec or {}).get("error") or f"rc={r.returncode}, no record"
        print(f"[gate] WARN: devgen smoke — {why} in {dt:.0f}s "
              f"(not a gate failure)", flush=True)
        return
    problems = []
    if int(rec.get("programs_lowered") or 0) < 6:
        problems.append(f"only {rec.get('programs_lowered')} device "
                        "programs lowered")
    if len(rec.get("matrix") or []) < 4:
        problems.append(f"TPU-memtype matrix incomplete with a "
                        f"generated-device allreduce pinned: "
                        f"{rec.get('matrix')}")
    if not rec.get("pinned_engaged"):
        problems.append("TUNE-pinned generated-device allreduce did "
                        "not engage")
    if not rec.get("bitwise_identical"):
        problems.append("device-lowered result != host interpreter "
                        "(bitwise)")
    verdict = "OK" if not problems else "WARN: " + "; ".join(problems)
    print(f"[gate] devgen smoke: {rec.get('programs_lowered')} device "
          f"programs lowered, matrix {len(rec.get('matrix') or [])}/4 "
          f"with {rec.get('pinned_alg')} pinned, host-vs-device "
          f"bitwise={'yes' if rec.get('bitwise_identical') else 'NO'} "
          f"in {dt:.0f}s -> {verdict}", flush=True)


def _plans_smoke(env) -> None:
    """WARN-ONLY native execution-plan probe (ISSUE 12 CI satellite):
    ``python -m ucc_tpu.dsl.smoke --plans`` builds one generated
    allreduce as a NATIVE PLAN and asserts bitwise agreement with the
    interpreted path plus data-path ffi-crossings-per-collective == 1
    (the C debug counter). Skips cleanly when the native core is
    unavailable. Disable with UCC_GATE_PLANS=0."""
    import json
    if os.environ.get("UCC_GATE_PLANS", "1").strip().lower() in \
            ("0", "n", "no", "off"):
        print("[gate] plans smoke: skipped (UCC_GATE_PLANS=0)",
              flush=True)
        return
    print("[gate] native-plans smoke (warn-only) ...", flush=True)
    t0 = time.monotonic()
    smoke_env = {k: v for k, v in env.items()
                 if not k.startswith(("UCC_WATCHDOG", "UCC_FAULT",
                                      "UCC_STATS", "UCC_PROFILE",
                                      "UCC_GEN", "UCC_TUNER"))}
    try:
        r = subprocess.run(
            [sys.executable, "-m", "ucc_tpu.dsl.smoke", "--plans"],
            cwd=REPO, env=smoke_env, capture_output=True, text=True,
            timeout=600)
    except subprocess.TimeoutExpired:
        print("[gate] WARN: plans smoke timed out (not a gate failure)",
              flush=True)
        return
    rec = None
    for ln in (r.stdout or "").splitlines():
        if ln.startswith("{"):
            try:
                cand = json.loads(ln)
            except ValueError:
                continue
            if cand.get("metric") == "plan_gate_smoke":
                rec = cand
    dt = time.monotonic() - t0
    if rec is None or rec.get("error"):
        why = (rec or {}).get("error") or f"rc={r.returncode}, no record"
        print(f"[gate] WARN: plans smoke — {why} in {dt:.0f}s "
              f"(not a gate failure)", flush=True)
        return
    if not rec.get("native_available"):
        print(f"[gate] plans smoke: skipped cleanly (native core "
              f"unavailable) in {dt:.0f}s", flush=True)
        return
    problems = []
    if not rec.get("plan_engaged"):
        problems.append("native plan did not engage")
    if not rec.get("completed"):
        problems.append("a mode did not complete")
    if not rec.get("bitwise_identical"):
        problems.append("plan result != interpreted result (bitwise)")
    if rec.get("ffi_per_collective") != 1.0:
        problems.append(f"ffi crossings per collective = "
                        f"{rec.get('ffi_per_collective')} (want 1)")
    verdict = "OK" if not problems else "WARN: " + "; ".join(problems)
    print(f"[gate] plans smoke: engaged={rec.get('plan_engaged')}, "
          f"bitwise={rec.get('bitwise_identical')}, ffi/coll="
          f"{rec.get('ffi_per_collective')} in {dt:.0f}s -> {verdict}",
          flush=True)


def _fr_smoke(env) -> None:
    """WARN-ONLY flight-recorder diagnosis probe (ISSUE 9 CI satellite,
    same harness as the other smokes): `ucc_fr --smoke` runs a 4-rank
    job under UCC_FAULT=delay pinned to ONE rank (a known controlled
    straggler), collects the rings cross-rank over the service team,
    and the diagnosis must name exactly that rank plus the collective
    sequence(s) it was slow in. Skip with UCC_GATE_FR=0."""
    import json
    if os.environ.get("UCC_GATE_FR", "1").strip().lower() in \
            ("0", "n", "no", "off"):
        print("[gate] fr smoke: skipped (UCC_GATE_FR=0)", flush=True)
        return
    print("[gate] flight-recorder smoke (warn-only) ...", flush=True)
    t0 = time.monotonic()
    # the drill sets its own UCC_FAULT; strip the gate's watchdog arming
    # so escalation doesn't cancel the deliberately-delayed collectives
    smoke_env = {k: v for k, v in env.items()
                 if not k.startswith(("UCC_WATCHDOG", "UCC_FAULT",
                                      "UCC_STATS", "UCC_PROFILE"))}
    smoke_env["UCC_FLIGHT"] = "y"
    smoke_env["UCC_FLIGHT_FILE"] = "/tmp/ucc_gate_flight.json"
    try:
        r = subprocess.run(
            [sys.executable, "-m", "ucc_tpu.tools.fr", "--smoke"],
            cwd=REPO, env=smoke_env, capture_output=True, text=True,
            timeout=600)
    except subprocess.TimeoutExpired:
        print("[gate] WARN: fr smoke timed out (not a gate failure)",
              flush=True)
        return
    rec = None
    for ln in (r.stdout or "").splitlines():
        if ln.startswith("{"):
            try:
                cand = json.loads(ln)
            except ValueError:
                continue
            if cand.get("metric") == "fr_smoke":
                rec = cand
    dt = time.monotonic() - t0
    if rec is None or rec.get("error"):
        why = (rec or {}).get("error") or f"rc={r.returncode}, no record"
        print(f"[gate] WARN: fr smoke — {why} in {dt:.0f}s "
              f"(not a gate failure)", flush=True)
        return
    problems = []
    if rec.get("culprit_ranks") != [rec.get("pinned_rank")]:
        problems.append(
            f"diagnosis named rank(s) {rec.get('culprit_ranks')} "
            f"instead of the pinned rank {rec.get('pinned_rank')}")
    if not rec.get("stuck_seqs"):
        problems.append("no collective sequence attributed to the "
                        "straggler")
    verdict = "OK" if not problems else "WARN: " + "; ".join(problems)
    print(f"[gate] fr smoke: pinned rank {rec.get('pinned_rank')}, "
          f"diagnosed {rec.get('culprit_ranks')} over seqs "
          f"{rec.get('stuck_seqs')} in {dt:.0f}s -> {verdict}",
          flush=True)


def _feedback_smoke(env) -> None:
    """WARN-ONLY closed-loop telemetry probe (ISSUE 16 CI satellite):
    `ucc_fr --feedback-smoke` runs an 8-rank job with a ring allreduce
    pinned and UCC_FAULT=delay_rank on ONE rank while the continuous
    collector (UCC_COLLECT) windows the rings. The collector must flag
    the pinned rank within 2 collection windows WITHOUT any manual dump
    trigger, the published RankBias must move selection off the
    through-the-straggler ring, and post-feedback p99 must beat
    pre-feedback. Skip with UCC_GATE_FEEDBACK=0."""
    import json
    if os.environ.get("UCC_GATE_FEEDBACK", "1").strip().lower() in \
            ("0", "n", "no", "off"):
        print("[gate] feedback smoke: skipped (UCC_GATE_FEEDBACK=0)",
              flush=True)
        return
    print("[gate] telemetry-feedback smoke (warn-only) ...", flush=True)
    t0 = time.monotonic()
    # the drill arms its own fault/collector/TUNE knobs; strip the
    # gate's instrumentation plus any ambient collector config so the
    # probe measures the drill's configuration, not the caller's
    smoke_env = {k: v for k, v in env.items()
                 if not k.startswith(("UCC_WATCHDOG", "UCC_FAULT",
                                      "UCC_STATS", "UCC_PROFILE",
                                      "UCC_COLLECT", "UCC_RANK_BIAS",
                                      "UCC_TL_SHM_TUNE"))}
    smoke_env["UCC_FLIGHT"] = "y"
    try:
        r = subprocess.run(
            [sys.executable, "-m", "ucc_tpu.tools.fr",
             "--feedback-smoke"],
            cwd=REPO, env=smoke_env, capture_output=True, text=True,
            timeout=600)
    except subprocess.TimeoutExpired:
        print("[gate] WARN: feedback smoke timed out (not a gate "
              "failure)", flush=True)
        return
    rec = None
    for ln in (r.stdout or "").splitlines():
        if ln.startswith("{"):
            try:
                cand = json.loads(ln)
            except ValueError:
                continue
            if cand.get("metric") == "feedback_smoke":
                rec = cand
    dt = time.monotonic() - t0
    if rec is None or rec.get("error"):
        why = (rec or {}).get("error") or f"rc={r.returncode}, no record"
        print(f"[gate] WARN: feedback smoke — {why} in {dt:.0f}s "
              f"(not a gate failure)", flush=True)
        return
    problems = []
    if rec.get("pinned_rank") not in (rec.get("flagged") or []):
        problems.append(f"collector flagged {rec.get('flagged')} but "
                        f"not the pinned rank {rec.get('pinned_rank')}")
    if not rec.get("windows_to_flag") or rec["windows_to_flag"] > 2:
        problems.append(f"flag took {rec.get('windows_to_flag')} "
                        f"windows (budget 2)")
    if rec.get("post_alg") == rec.get("pre_alg"):
        problems.append(f"selection stayed on {rec.get('pre_alg')} "
                        f"after the flag")
    if not rec.get("post_p99_ms") or not rec.get("pre_p99_ms") or \
            rec["post_p99_ms"] >= rec["pre_p99_ms"]:
        problems.append(f"post-feedback p99 {rec.get('post_p99_ms')}ms "
                        f"did not beat pre {rec.get('pre_p99_ms')}ms")
    verdict = "OK" if not problems else "WARN: " + "; ".join(problems)
    print(f"[gate] feedback smoke: flagged {rec.get('flagged')} in "
          f"{rec.get('windows_to_flag')} window(s), selection "
          f"{rec.get('pre_alg')} -> {rec.get('post_alg')}, p99 "
          f"{rec.get('pre_p99_ms')}ms -> {rec.get('post_p99_ms')}ms "
          f"in {dt:.0f}s -> {verdict}", flush=True)


def _churn_smoke(env) -> None:
    """WARN-ONLY elastic-membership probe (ISSUE 17 CI satellite):
    ``python -m ucc_tpu.fault.soak --churn --cycles 2 --collect`` runs
    interleaved kill -> shrink -> grow(rejoin) cycles with collectives
    in flight on every epoch plus the false-suspicion re-admission
    round, and classifies any breakage (hang vs rank_failed vs
    grow-timeout) from the report. Skip with UCC_GATE_CHURN=0."""
    import json
    if os.environ.get("UCC_GATE_CHURN", "1").strip().lower() in \
            ("0", "n", "no", "off"):
        print("[gate] churn smoke: skipped (UCC_GATE_CHURN=0)",
              flush=True)
        return
    print("[gate] membership-churn smoke (warn-only) ...", flush=True)
    t0 = time.monotonic()
    # the drill arms its own fault/health/collector knobs; strip the
    # gate watchdog so escalation doesn't cancel mid-membership-change
    smoke_env = {k: v for k, v in env.items()
                 if not k.startswith(("UCC_WATCHDOG", "UCC_FAULT",
                                      "UCC_STATS", "UCC_PROFILE",
                                      "UCC_COLLECT", "UCC_FT"))}
    try:
        r = subprocess.run(
            [sys.executable, "-m", "ucc_tpu.fault.soak", "--churn",
             "--cycles", "2", "--collect"],
            cwd=REPO, env=smoke_env, capture_output=True, text=True,
            timeout=600)
    except subprocess.TimeoutExpired:
        # a gate-level timeout here IS the hang class: the drill's own
        # deadlines should have classified anything slower first
        print("[gate] WARN: churn smoke timed out — HANG class "
              "(not a gate failure)", flush=True)
        return
    rec = None
    try:
        rec = json.loads(r.stdout or "")
    except ValueError:
        for ln in (r.stdout or "").splitlines():
            if ln.startswith("{"):
                try:
                    rec = json.loads(ln)
                except ValueError:
                    continue
    dt = time.monotonic() - t0
    if rec is None:
        print(f"[gate] WARN: churn smoke — rc={r.returncode}, no report "
              f"in {dt:.0f}s (not a gate failure)", flush=True)
        return
    problems = []
    # classify violations so the gate log names the failure mode
    for v in rec.get("violations") or []:
        if "IN_PROGRESS" in v or "hung" in v:
            problems.append(f"hang: {v}")
        elif "ERR_RANK_FAILED" in v or "rank" in v.lower():
            problems.append(f"rank_failed: {v}")
        elif "timed out" in v.lower() or "TIMED_OUT" in v:
            problems.append(f"grow-timeout: {v}")
        else:
            problems.append(v)
    if rec.get("cycles", 0) < 2:
        problems.append(f"only {rec.get('cycles')} cycle(s) completed")
    fenced = rec.get("fenced") or {}
    if not fenced.get("shrink"):
        problems.append("no pre-shrink send fenced")
    if not fenced.get("grow"):
        problems.append("no pre-grow send fenced")
    if not rec.get("readmitted"):
        problems.append("falsely-suspected rank was not re-admitted")
    verdict = "OK" if not problems else "WARN: " + "; ".join(problems)
    print(f"[gate] churn smoke: cycles={rec.get('cycles')}, "
          f"epochs={rec.get('epochs')}, fenced={fenced}, "
          f"readmitted={rec.get('readmitted')}, post_churn_ok="
          f"{rec.get('post_churn_ok')}, matcher={rec.get('matcher')} "
          f"in {dt:.0f}s -> {verdict}", flush=True)


def _mt_smoke(env) -> None:
    """WARN-ONLY multi-tenant service probe (ISSUE 18 CI satellite):
    ``python -m ucc_tpu.fault.soak --multi`` shares one progress engine
    between a latency-class team and coalescing bulk tenants, kills a
    rank mid-traffic (held/fused members must abort, not hang), shrinks
    and grows every team, and probes the priority-lane counters —
    starvation past 1s or any hang is a violation. Skip with
    UCC_GATE_MT=0."""
    import json
    if os.environ.get("UCC_GATE_MT", "1").strip().lower() in \
            ("0", "n", "no", "off"):
        print("[gate] mt smoke: skipped (UCC_GATE_MT=0)", flush=True)
        return
    print("[gate] multi-tenant smoke (warn-only) ...", flush=True)
    t0 = time.monotonic()
    # the drill arms its own fault/health/coalesce knobs; strip the gate
    # watchdog so escalation doesn't cancel mid-membership-change
    smoke_env = {k: v for k, v in env.items()
                 if not k.startswith(("UCC_WATCHDOG", "UCC_FAULT",
                                      "UCC_STATS", "UCC_PROFILE",
                                      "UCC_COALESCE", "UCC_FT"))}
    try:
        r = subprocess.run(
            [sys.executable, "-m", "ucc_tpu.fault.soak", "--multi"],
            cwd=REPO, env=smoke_env, capture_output=True, text=True,
            timeout=600)
    except subprocess.TimeoutExpired:
        print("[gate] WARN: mt smoke timed out — HANG class "
              "(not a gate failure)", flush=True)
        return
    rec = None
    try:
        rec = json.loads(r.stdout or "")
    except ValueError:
        for ln in (r.stdout or "").splitlines():
            if ln.startswith("{"):
                try:
                    rec = json.loads(ln)
                except ValueError:
                    continue
    dt = time.monotonic() - t0
    if rec is None:
        print(f"[gate] WARN: mt smoke — rc={r.returncode}, no report "
              f"in {dt:.0f}s (not a gate failure)", flush=True)
        return
    problems = []
    for v in rec.get("violations") or []:
        if "IN_PROGRESS" in v or "hung" in v:
            problems.append(f"hang: {v}")
        elif "starved" in v:
            problems.append(f"starvation: {v}")
        else:
            problems.append(v)
    if not rec.get("post_rounds_ok"):
        problems.append("no checked post-recovery round completed")
    if not rec.get("fused_batches"):
        problems.append("bulk tenants dispatched no fused batches")
    verdict = "OK" if not problems else "WARN: " + "; ".join(problems)
    print(f"[gate] mt smoke: teams={rec.get('teams')}, "
          f"rounds={rec.get('rounds')}, post_ok={rec.get('post_rounds_ok')}, "
          f"fused={rec.get('fused_batches')}, "
          f"inversions={rec.get('priority_inversions')}, "
          f"starvation_max={rec.get('starvation_max_ms')}ms, "
          f"hi_probe={rec.get('hi_probe_ms')} in {dt:.0f}s -> {verdict}",
          flush=True)


def _integrity_smoke(env) -> None:
    """WARN-ONLY data-integrity probe (ISSUE 19 CI satellite):
    ``python -m ucc_tpu.fault.soak --corrupt`` runs the corruption
    storm — a pinned rank corrupts every send under
    ``UCC_INTEGRITY=verify`` — and classifies the failure mode that
    matters for integrity: SILENT (corruption reached a result without
    any rank reporting ERR_DATA_CORRUPTED — the worst class), DETECTED-
    BUT-NOT-QUARANTINED (the strike ledger did not escalate), and HANG
    (a rank parked instead of reaching a terminal status). Skip with
    UCC_GATE_INTEGRITY=0."""
    import json
    if os.environ.get("UCC_GATE_INTEGRITY", "1").strip().lower() in \
            ("0", "n", "no", "off"):
        print("[gate] integrity smoke: skipped (UCC_GATE_INTEGRITY=0)",
              flush=True)
        return
    print("[gate] corruption-storm integrity smoke (warn-only) ...",
          flush=True)
    t0 = time.monotonic()
    # the drill arms its own integrity/fault/health knobs; strip the
    # gate watchdog so escalation doesn't cancel mid-quarantine
    smoke_env = {k: v for k, v in env.items()
                 if not k.startswith(("UCC_WATCHDOG", "UCC_FAULT",
                                      "UCC_STATS", "UCC_PROFILE",
                                      "UCC_COLLECT", "UCC_FT",
                                      "UCC_INTEGRITY"))}
    try:
        r = subprocess.run(
            [sys.executable, "-m", "ucc_tpu.fault.soak", "--corrupt"],
            cwd=REPO, env=smoke_env, capture_output=True, text=True,
            timeout=600)
    except subprocess.TimeoutExpired:
        print("[gate] WARN: integrity smoke timed out — HANG class "
              "(not a gate failure)", flush=True)
        return
    rec = None
    try:
        rec = json.loads(r.stdout or "")
    except ValueError:
        for ln in (r.stdout or "").splitlines():
            if ln.startswith("{"):
                try:
                    rec = json.loads(ln)
                except ValueError:
                    continue
    dt = time.monotonic() - t0
    if rec is None:
        print(f"[gate] WARN: integrity smoke — rc={r.returncode}, no "
              f"report in {dt:.0f}s (not a gate failure)", flush=True)
        return
    problems = []
    for v in rec.get("violations") or []:
        if "SILENT" in v or "undetected" in v:
            problems.append(f"silent-corruption: {v}")
        elif "IN_PROGRESS" in v or "hung" in v:
            problems.append(f"hang: {v}")
        elif "quarantin" in v.lower():
            problems.append(f"no-quarantine: {v}")
        else:
            problems.append(v)
    if rec.get("storm_rounds", 0) and \
            rec.get("detections", 0) < rec["storm_rounds"]:
        problems.append(f"detected {rec.get('detections')}/"
                        f"{rec.get('storm_rounds')} storm rounds "
                        f"(must be 100%)")
    if rec.get("post_iters", 0) < 50:
        problems.append(f"only {rec.get('post_iters')} checked "
                        f"post-quarantine iterations (acceptance: 50)")
    verdict = "OK" if not problems else "WARN: " + "; ".join(problems)
    print(f"[gate] integrity smoke: detections={rec.get('detections')}/"
          f"{rec.get('storm_rounds')}, quarantined="
          f"{rec.get('quarantined')} in {rec.get('rounds_to_quarantine')}"
          f" round(s) (strikes={rec.get('strikes')}), post_ok="
          f"{rec.get('post_iters')}, plans={rec.get('plan_mode')}, "
          f"matcher={rec.get('matcher')} in {dt:.0f}s -> {verdict}",
          flush=True)


def _ipc_baseline() -> float:
    """Best arena-vs-socket p50 speedup from the committed BENCH_r20
    evidence (0.0 when the file is missing/unparseable)."""
    import json
    try:
        with open(os.path.join(REPO, "BENCH_r20.json")) as f:
            for ln in f:
                try:
                    rec = json.loads(ln)
                except ValueError:
                    continue
                if rec.get("metric") == "xproc_ipc_vs_socket_p50_speedup":
                    return float(rec.get("value") or 0.0)
    except OSError:
        pass
    return 0.0


def _ipc_smoke(env) -> None:
    """WARN-ONLY cross-process transport probe (ISSUE 20 CI satellite):
    run the 2-proc x 4-rank arena-vs-socket bench (``bench.py --ipc``)
    at a trimmed size set and compare the best arena-tier speedup
    against the committed BENCH_r20 baseline with a tolerance band
    (UCC_GATE_IPC_TOL, default 40% — the ratio of two p50s on a noisy
    box). Classifies the failure mode that matters for a shared-memory
    transport: HANG (a rank parked across the process boundary —
    matching or fence bug), ATTACH FAILURE (a leg died setting up the
    arena/teams), and REGRESSION (speedup below the band). Never flips
    the gate. Skip with UCC_GATE_IPC=0."""
    import json
    if os.environ.get("UCC_GATE_IPC", "1").strip().lower() in \
            ("0", "n", "no", "off"):
        print("[gate] ipc smoke: skipped (UCC_GATE_IPC=0)", flush=True)
        return
    try:
        tol = float(os.environ.get("UCC_GATE_IPC_TOL", "0.40"))
    except ValueError:
        tol = 0.40
    base = _ipc_baseline()
    print("[gate] cross-process transport smoke (warn-only) ...",
          flush=True)
    t0 = time.monotonic()
    # trimmed cells: one latency-bound, one at the matched-path ceiling,
    # one bandwidth-bound pooled/socket-only; the gate's watchdog/stats
    # arming stays out of the child for the same reason as _perf_smoke
    smoke_env = {k: v for k, v in env.items()
                 if not k.startswith(("UCC_WATCHDOG", "UCC_FAULT",
                                      "UCC_STATS", "UCC_PROFILE"))}
    smoke_env["UCC_XPROC_SIZES"] = "64K,8M,32M"
    smoke_env["UCC_XPROC_ITERS"] = "6"
    try:
        r = subprocess.run([sys.executable, "bench.py", "--ipc"],
                           cwd=REPO, env=smoke_env, capture_output=True,
                           text=True, timeout=900)
    except subprocess.TimeoutExpired:
        print("[gate] WARN: ipc smoke timed out — HANG class (a rank "
              "parked across the process boundary; not a gate failure)",
              flush=True)
        return
    summary, error = None, None
    for ln in (r.stdout or "").splitlines():
        if not ln.startswith("{"):
            continue
        try:
            rec = json.loads(ln)
        except ValueError:
            continue
        detail = rec.get("detail") or {}
        if detail.get("error"):
            error = f"{detail.get('transport')}: {detail['error']}"
        if rec.get("metric") == "xproc_ipc_vs_socket_p50_speedup":
            summary = rec
    dt = time.monotonic() - t0
    if error:
        print(f"[gate] WARN: ipc smoke — ATTACH/RUN FAILURE on leg "
              f"{error} in {dt:.0f}s (not a gate failure)", flush=True)
        return
    if summary is None:
        print(f"[gate] WARN: ipc smoke — rc={r.returncode}, no speedup "
              f"summary in {dt:.0f}s (not a gate failure)", flush=True)
        return
    value = float(summary.get("value") or 0.0)
    per_size = (summary.get("detail") or {}).get("per_size") or {}
    if base:
        floor = base * (1.0 - tol)
        verdict = "OK" if value >= floor else \
            f"WARN: REGRESSION below baseline {base:.2f}x - " \
            f"{tol:.0%} tolerance"
    else:
        floor = 0.0
        verdict = "OK (no baseline recorded)"
    print(f"[gate] ipc smoke: arena-vs-socket p50 speedup {value:.2f}x "
          f"(baseline {base:.2f}x, floor {floor:.2f}x, per-size "
          f"{per_size}) in {dt:.0f}s -> {verdict}", flush=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="import canary only (catches the round-4 class "
                    "of breakage in seconds)")
    args = ap.parse_args(argv)

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags +
                            " --xla_force_host_platform_device_count=8"
                            ).strip()
    # Arm the watchdog escalation ladder in every gate child (ISSUE-2 CI
    # satellite): a wedged step gets its stuck collectives cancelled and
    # attributed (`timeout(coll=...)`) instead of a bare gate TIMEOUT.
    # Soft/hard deadlines sized to land inside every step's own timeout
    # (shortest full-gate step: dryrun at 1200s) — an escalation armed
    # beyond the step kill would never run. No single collective in the
    # gate legitimately runs 100s.
    env.setdefault("UCC_WATCHDOG_TIMEOUT", "100")
    env.setdefault("UCC_WATCHDOG_ACTION", "cancel")
    env.setdefault("UCC_WATCHDOG_HARD_TIMEOUT", "200")
    env.setdefault("UCC_WATCHDOG_FILE", WATCHDOG_FILE)
    # flight-recorder dumps (always-on) out of the checkout: a watchdog
    # or rank-failure trigger in any gate child writes here
    env.setdefault("UCC_FLIGHT_FILE", "/tmp/ucc_gate_flight.json")

    ok = True
    if args.quick:
        ok &= _run("import canary",
                   [sys.executable, "-m", "pytest",
                    "tests/test_import_canary.py", "-q"],
                   timeout=300, env=env)
    else:
        ok &= _run("full suite",
                   [sys.executable, "-m", "pytest", "tests/", "-q"],
                   timeout=2700, env=env)
        ok &= _run("dryrun_multichip(8)",
                   [sys.executable, "-c",
                    "import __graft_entry__ as g; g.dryrun_multichip(8); "
                    "print('DRYRUN OK')"],
                   timeout=1200, env=env)
        # the rank-failure recovery pipeline (detect -> agree -> shrink
        # -> resume) must not silently rot: run the kill+shrink drill on
        # every gate pass (ISSUE-4 CI satellite; tier-1-safe, not slow)
        ok &= _run("kill+shrink soak",
                   [sys.executable, "-m", "ucc_tpu.fault.soak",
                    "--kill-shrink"],
                   timeout=600, env=env)
        # warn-only: surfaces perf regressions in-PR without making the
        # gate flaky on a noisy shared box (ISSUE 3 CI satellite)
        _perf_smoke(env)
        # warn-only: tuned allreduce >= default - tolerance through the
        # offline sweep -> cache -> reload round trip (ISSUE 5 satellite)
        _tuner_smoke(env)
        # warn-only: int8 allreduce beats exact on wire bytes and stays
        # inside the error budget on the wire-bound host path (ISSUE 6)
        _quant_smoke(env)
        # warn-only: the v2 native matcher holds its perf claims in both
        # thread modes — >= python under concurrent progress, within 5%
        # single-threaded (ISSUE 7). The kill+shrink soak above already
        # exercises native+FT: native is the default matcher now.
        _native_smoke(env)
        # warn-only: 512-rank simulated pod bootstraps through the tree
        # OOB with O(log n) rounds/fan-in, activates, passes the
        # collective matrix, and the N-level hier allreduce beats the
        # flat DCN default (ISSUE 8)
        _scale_smoke(env)
        # warn-only: flight-recorder diagnosis names a fault-injected
        # straggler rank and its stuck collective seq (ISSUE 9)
        _fr_smoke(env)
        # warn-only: generated DSL families compile + verify, run the
        # matrix, and tune end-to-end (ISSUE 10)
        _gen_smoke(env)
        # warn-only: a generated allreduce runs as a native execution
        # plan bitwise-identical to the interpreted path with ONE
        # data-path ffi crossing per collective (ISSUE 12)
        _plans_smoke(env)
        # warn-only: cost-model-guided program search fits, searches,
        # registers and dispatches a searched winner with sane
        # predicted-cost ordering (ISSUE 14)
        _search_smoke(env)
        # warn-only: device-side compiler backend lowers + verifies all
        # device families, runs the TPU-memtype matrix with a
        # generated-device allreduce pinned, and matches the host
        # interpreter bitwise (ISSUE 15)
        _devgen_smoke(env)
        # warn-only: continuous collector flags a fault-injected
        # straggler within 2 windows, RankBias moves selection off the
        # ring, and post-feedback p99 beats pre-feedback (ISSUE 16)
        _feedback_smoke(env)
        # warn-only: >= 2 kill->shrink->grow(rejoin) churn cycles with
        # collectives on every epoch, fences tripped both directions,
        # and the falsely-suspected survivor re-admitted (ISSUE 17)
        _churn_smoke(env)
        # warn-only: mixed-priority tenant teams share one progress
        # engine through kill -> shrink -> grow with coalesced bulk
        # traffic, and the priority-inversion / starvation counters
        # stay clean (ISSUE 18)
        _mt_smoke(env)
        # warn-only: wire crc32 detects 100% of a pinned corruptor's
        # storm rounds with sender attribution, the strike ledger
        # quarantines it, and the shrunk team runs a checked matrix —
        # classified silent-vs-detected-vs-hang (ISSUE 19)
        _integrity_smoke(env)
        # warn-only: the cross-process arena + pooled tier hold their
        # speedup over the socket TL on the 2-proc bench, classified
        # hang-vs-attach-failure-vs-regression (ISSUE 20)
        _ipc_smoke(env)
    print(f"[gate] {'PASS — safe to commit' if ok else 'FAIL — do NOT commit'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
