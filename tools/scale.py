#!/usr/bin/env python
"""Pod-scale simulation harness (repo-root entry).

Thin shim over the packaged CLI — the implementation lives in
ucc_tpu/tools/scale.py (installed as the `ucc_scale` console script).
Simulates a 512–2048-rank host-TL mesh bootstrapped through the
tree-structured OOB exchange with a synthetic multi-node/multi-pod
layout, runs the collective matrix, and measures N-level hier against
the flat DCN default per size cell.

    python tools/scale.py -n 512 --ppn 8 --npp 8 --json
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ucc_tpu.tools.scale import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
