"""Sequence-parallel attention (ring + Ulysses) — the long-context
first-class workload, validated exactly against unsharded attention."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ucc_tpu.examples.ring_attention import (  # noqa: E402
    make_ring_attention, make_ulysses_attention, reference_attention)


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return jax.make_mesh((8,), ("sp",))


def _inputs(heads, seq, d, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (heads, seq, d), jnp.float32)
    k = jax.random.normal(ks[1], (heads, seq, d), jnp.float32)
    v = jax.random.normal(ks[2], (heads, seq, d), jnp.float32)
    return q, k, v


class TestRingAttention:
    @pytest.mark.parametrize("seq", [64, 256])
    def test_exact_vs_reference(self, mesh, seq):
        heads, d = 4, 16
        q, k, v = _inputs(heads, seq, d)
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(mesh, P(None, "sp", None))
        qs, ks_, vs = (jax.device_put(x, sh) for x in (q, k, v))
        ring = make_ring_attention(mesh)
        out = np.asarray(jax.device_get(ring(qs, ks_, vs)))
        expect = np.asarray(reference_attention(q, k, v))
        np.testing.assert_allclose(out, expect, rtol=2e-4, atol=2e-5)

    def test_memory_scaling_shape(self, mesh):
        # each shard sees only seq/8 of K/V at a time: the jitted program
        # must accept a sequence too large to attend monolithically if
        # materialized as (seq, seq) scores on one shard boundary check
        heads, seq, d = 2, 512, 8
        q, k, v = _inputs(heads, seq, d, seed=3)
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(mesh, P(None, "sp", None))
        ring = make_ring_attention(mesh)
        out = ring(*(jax.device_put(x, sh) for x in (q, k, v)))
        assert out.shape == (heads, seq, d)
        expect = np.asarray(reference_attention(q, k, v))
        np.testing.assert_allclose(np.asarray(jax.device_get(out)), expect,
                                   rtol=2e-4, atol=2e-5)


class TestUlyssesAttention:
    def test_exact_vs_reference(self, mesh):
        heads, seq, d = 8, 128, 16   # heads % 8 == 0
        q, k, v = _inputs(heads, seq, d, seed=1)
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(mesh, P(None, "sp", None))
        qs, ks_, vs = (jax.device_put(x, sh) for x in (q, k, v))
        uly = make_ulysses_attention(mesh)
        out = np.asarray(jax.device_get(uly(qs, ks_, vs)))
        expect = np.asarray(reference_attention(q, k, v))
        np.testing.assert_allclose(out, expect, rtol=2e-4, atol=2e-5)
