"""Sequence-parallel attention (ring + Ulysses) — the long-context
first-class workload, validated exactly against unsharded attention."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ucc_tpu.examples.ring_attention import (  # noqa: E402
    make_ring_attention, make_ulysses_attention, reference_attention)


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return jax.make_mesh((8,), ("sp",))


def _inputs(heads, seq, d, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (heads, seq, d), jnp.float32)
    k = jax.random.normal(ks[1], (heads, seq, d), jnp.float32)
    v = jax.random.normal(ks[2], (heads, seq, d), jnp.float32)
    return q, k, v


class TestRingAttention:
    @pytest.mark.parametrize("seq", [64, 256])
    def test_exact_vs_reference(self, mesh, seq):
        heads, d = 4, 16
        q, k, v = _inputs(heads, seq, d)
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(mesh, P(None, "sp", None))
        qs, ks_, vs = (jax.device_put(x, sh) for x in (q, k, v))
        ring = make_ring_attention(mesh)
        out = np.asarray(jax.device_get(ring(qs, ks_, vs)))
        expect = np.asarray(reference_attention(q, k, v))
        np.testing.assert_allclose(out, expect, rtol=2e-4, atol=2e-5)

    def test_memory_scaling_shape(self, mesh):
        # each shard sees only seq/8 of K/V at a time: the jitted program
        # must accept a sequence too large to attend monolithically if
        # materialized as (seq, seq) scores on one shard boundary check
        heads, seq, d = 2, 512, 8
        q, k, v = _inputs(heads, seq, d, seed=3)
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(mesh, P(None, "sp", None))
        ring = make_ring_attention(mesh)
        out = ring(*(jax.device_put(x, sh) for x in (q, k, v)))
        assert out.shape == (heads, seq, d)
        expect = np.asarray(reference_attention(q, k, v))
        np.testing.assert_allclose(np.asarray(jax.device_get(out)), expect,
                                   rtol=2e-4, atol=2e-5)


class TestUlyssesAttention:
    def test_exact_vs_reference(self, mesh):
        heads, seq, d = 8, 128, 16   # heads % 8 == 0
        q, k, v = _inputs(heads, seq, d, seed=1)
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(mesh, P(None, "sp", None))
        qs, ks_, vs = (jax.device_put(x, sh) for x in (q, k, v))
        uly = make_ulysses_attention(mesh)
        out = np.asarray(jax.device_get(uly(qs, ks_, vs)))
        expect = np.asarray(reference_attention(q, k, v))
        np.testing.assert_allclose(out, expect, rtol=2e-4, atol=2e-5)


class TestFusedRingFlashAttention:
    """The Pallas-fused tier (ucc_tpu/fused_attention.py): K/V rotation
    as in-kernel remote DMAs overlapping the flash block update —
    validated exactly against full softmax(QK^T)V (interpret mode on the
    CPU mesh; the compiled ICI path shares ring_dma's hardware gate)."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_exact_vs_reference(self, mesh, causal):
        from ucc_tpu.fused_attention import make_ring_flash_attention
        heads, seq, d = 2, 64, 8
        q, k, v = _inputs(heads, seq, d, seed=5)
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(mesh, P(None, "sp", None))
        fn = make_ring_flash_attention(mesh, causal=causal, axis="sp")
        out = np.asarray(jax.device_get(
            fn(*(jax.device_put(x, sh) for x in (q, k, v)))))
        s = np.einsum("hqd,hkd->hqk", np.asarray(q), np.asarray(k)) \
            / np.sqrt(d)
        if causal:
            mask = np.tril(np.ones((seq, seq), bool))
            s = np.where(mask[None], s, -np.inf)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        expect = np.einsum("hqk,hkd->hqd", p, np.asarray(v))
        np.testing.assert_allclose(out, expect, rtol=2e-4, atol=2e-5)

    def test_matches_xla_tier(self, mesh):
        """Both context-parallel tiers must agree (same math, different
        schedules)."""
        from ucc_tpu.fused_attention import make_ring_flash_attention
        heads, seq, d = 4, 128, 16
        q, k, v = _inputs(heads, seq, d, seed=6)
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(mesh, P(None, "sp", None))
        args = tuple(jax.device_put(x, sh) for x in (q, k, v))
        fused = np.asarray(jax.device_get(
            make_ring_flash_attention(mesh, axis="sp")(*args)))
        xla = np.asarray(jax.device_get(make_ring_attention(mesh)(*args)))
        np.testing.assert_allclose(fused, xla, rtol=2e-4, atol=2e-5)

    def test_bf16_io_f32_accum(self, mesh):
        from ucc_tpu.fused_attention import make_ring_flash_attention
        heads, seq, d = 2, 64, 8
        q, k, v = _inputs(heads, seq, d, seed=7)
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(mesh, P(None, "sp", None))
        fn = make_ring_flash_attention(mesh, axis="sp")
        out = np.asarray(jax.device_get(
            fn(*(jax.device_put(x, sh) for x in (qb, kb, vb)))
            ).astype(np.float32))
        expect = np.asarray(reference_attention(q, k, v))
        # bf16 inputs, f32 accumulation: ~1e-2 tolerance
        np.testing.assert_allclose(out, expect, rtol=5e-2, atol=5e-2)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_vs_full_attention(self, mesh, causal):
        """custom_vjp: fused forward, lax ring-schedule backward — grads
        must match differentiating the full softmax(QK^T)V."""
        import contextlib
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ucc_tpu.fused_attention import ring_flash_attention
        from ucc_tpu.utils.jaxshim import shard_map_compat
        heads, seq, d = 2, 24, 4
        q, k, v = _inputs(heads, seq, d, seed=9)
        sh = NamedSharding(mesh, P(None, "sp", None))
        qs, ks_, vs = (jax.device_put(x, sh) for x in (q, k, v))

        def body(a, b, c):
            return ring_flash_attention(a, b, c, axis_name="sp",
                                        causal=causal)
        f = shard_map_compat(body, mesh, (P(None, "sp", None),) * 3,
                             P(None, "sp", None))

        @jax.jit
        def loss(a, b, c):
            return jnp.sum(f(a, b, c) ** 2)

        def loss_ref(a, b, c):
            s = jnp.einsum("hqd,hkd->hqk", a, b) / jnp.sqrt(jnp.float32(d))
            if causal:
                m = jnp.tril(jnp.ones((seq, seq), bool))
                s = jnp.where(m[None], s, -jnp.inf)
            p = jax.nn.softmax(s, -1)
            return jnp.sum(jnp.einsum("hqk,hkd->hqd", p, c) ** 2)

        ctx = jax.set_mesh(mesh) if hasattr(jax, "set_mesh") \
            else contextlib.nullcontext()
        with ctx:
            g1 = jax.grad(loss, argnums=(0, 1, 2))(qs, ks_, vs)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)


class TestLongContextTraining:
    """End-to-end long-context training step (examples/long_context.py):
    fused/sp attention inside a dp×sp jitted train step, gradients
    through the custom_vjp, DP sync via ops.allreduce."""

    def test_loss_decreases(self):
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        from ucc_tpu.examples.long_context import (init_params,
                                                   make_train_step)
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = jax.make_mesh((2, 4), ("dp", "sp"))
        params = init_params(heads=2, d=4)
        kx, ky = jax.random.split(jax.random.PRNGKey(3))
        x = jax.random.normal(kx, (4, 2, 32, 4), jnp.float32)
        y = jax.random.normal(ky, (4, 2, 32, 4), jnp.float32) * 0.1
        xs = NamedSharding(mesh, P("dp", None, "sp", None))
        x, y = jax.device_put(x, xs), jax.device_put(y, xs)
        step = make_train_step(mesh, lr=0.05)
        w = [params["wq"], params["wk"], params["wv"], params["wo"]]
        losses = []
        for _ in range(6):
            out = step(*w, x, y)
            losses.append(float(jax.device_get(out[0])))
            w = list(out[1:])
        assert losses[-1] < losses[0], losses

    def test_grads_match_dense(self):
        """The applied update must equal -lr * (gradient of the GLOBAL
        mean loss), identically on every device — pins the sp-axis
        weight-gradient reduction (weight grads are per-rank partials;
        the ring backward only aggregates dK/dV, so without the sp
        allreduce the 'replicated' params silently diverge)."""
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        from ucc_tpu.examples.long_context import (init_params,
                                                   make_train_step)
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = jax.make_mesh((2, 4), ("dp", "sp"))
        heads, d, batch, seq = 2, 4, 4, 32
        params = init_params(heads, d)
        kx, ky = jax.random.split(jax.random.PRNGKey(3))
        x = jax.random.normal(kx, (batch, heads, seq, d), jnp.float32)
        y = jax.random.normal(ky, (batch, heads, seq, d),
                              jnp.float32) * 0.1

        def dense_loss(wq, wk, wv, wo):
            q = jnp.einsum("bhsd,hde->bhse", x, wq)
            k = jnp.einsum("bhsd,hde->bhse", x, wk)
            v = jnp.einsum("bhsd,hde->bhse", x, wv)
            scores = jnp.einsum("bhse,bhte->bhst", q, k) / np.sqrt(d)
            mask = jnp.tril(jnp.ones((seq, seq), bool))
            p = jax.nn.softmax(jnp.where(mask, scores, -jnp.inf), -1)
            attn = jnp.einsum("bhst,bhte->bhse", p, v)
            out = jnp.einsum("bhse,hed->bhsd", attn, wo)
            return jnp.mean((out - y) ** 2)

        w = (params["wq"], params["wk"], params["wv"], params["wo"])
        ref = jax.grad(dense_loss, argnums=(0, 1, 2, 3))(*w)
        lr = 0.05
        xs = NamedSharding(mesh, P("dp", None, "sp", None))
        out = make_train_step(mesh, lr=lr)(
            *w, jax.device_put(x, xs), jax.device_put(y, xs))
        for name, new, old, g in zip(("wq", "wk", "wv", "wo"),
                                     out[1:], w, ref):
            shards = [np.asarray(s.data) for s in new.addressable_shards]
            for s in shards[1:]:       # truly replicated after update
                np.testing.assert_array_equal(s, shards[0], err_msg=name)
            np.testing.assert_allclose(
                shards[0], np.asarray(old - lr * g), rtol=1e-4,
                atol=1e-6, err_msg=name)

    def test_multi_axis_fallback_matches_fused(self, mesh):
        """ring_flash_attention under a multi-axis mesh silently takes
        the lax ring schedule; results must match the 1-axis fused path."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ucc_tpu.fused_attention import ring_flash_attention
        from ucc_tpu.utils.jaxshim import shard_map_compat
        heads, seq, d = 2, 32, 8
        q, k, v = _inputs(heads, seq, d, seed=12)
        # 1-axis fused
        sh1 = NamedSharding(mesh, P(None, "sp", None))
        f1 = shard_map_compat(
            lambda a, b, c: ring_flash_attention(a, b, c, axis_name="sp"),
            mesh, (P(None, "sp", None),) * 3, P(None, "sp", None))
        out1 = np.asarray(jax.device_get(jax.jit(f1)(
            *(jax.device_put(t, sh1) for t in (q, k, v)))))
        # 2-axis mesh (fallback path), sp size 4
        mesh2 = jax.make_mesh((2, 4), ("dp", "sp"))
        sh2 = NamedSharding(mesh2, P(None, "sp", None))
        f2 = shard_map_compat(
            lambda a, b, c: ring_flash_attention(a, b, c, axis_name="sp"),
            mesh2, (P(None, "sp", None),) * 3, P(None, "sp", None))
        out2 = np.asarray(jax.device_get(jax.jit(f2)(
            *(jax.device_put(t, sh2) for t in (q, k, v)))))
        np.testing.assert_allclose(out1, out2, rtol=2e-5, atol=2e-6)


class TestGroupedQueryAttention:
    """GQA: q heads grouped over fewer K/V heads — the ring rotates only
    the kv_heads blocks (heads/kv_heads less ICI traffic). Validated
    against dense attention with K/V heads repeated per group."""

    @staticmethod
    def _gqa_inputs(h, h_kv, seq, d, seed=21):
        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        q = jax.random.normal(ks[0], (h, seq, d), jnp.float32)
        k = jax.random.normal(ks[1], (h_kv, seq, d), jnp.float32)
        v = jax.random.normal(ks[2], (h_kv, seq, d), jnp.float32)
        return q, k, v

    @staticmethod
    def _dense(q, k, v, causal):
        h, seq, d = q.shape
        g = h // k.shape[0]
        kr = np.repeat(np.asarray(k), g, axis=0)
        vr = np.repeat(np.asarray(v), g, axis=0)
        s = np.einsum("hqd,hkd->hqk", np.asarray(q), kr) / np.sqrt(d)
        if causal:
            mask = np.tril(np.ones((seq, seq), bool))
            s = np.where(mask[None], s, -np.inf)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        return np.einsum("hqk,hkd->hqd", p, vr)

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("h,h_kv", [(4, 2), (8, 2), (6, 6)])
    def test_exact_vs_dense(self, mesh, causal, h, h_kv):
        from ucc_tpu.fused_attention import make_ring_flash_attention
        seq, d = 64, 8
        q, k, v = self._gqa_inputs(h, h_kv, seq, d)
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(mesh, P(None, "sp", None))
        fn = make_ring_flash_attention(mesh, causal=causal, axis="sp")
        out = np.asarray(jax.device_get(
            fn(*(jax.device_put(x, sh) for x in (q, k, v)))))
        np.testing.assert_allclose(out, self._dense(q, k, v, causal),
                                   rtol=2e-4, atol=2e-5)

    def test_mismatched_heads_rejected(self, mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ucc_tpu.fused_attention import ring_flash_attention
        from ucc_tpu.utils.jaxshim import shard_map_compat
        q, k, v = self._gqa_inputs(5, 2, 16, 4)   # 5 % 2 != 0
        sh = NamedSharding(mesh, P(None, "sp", None))

        def body(a, b, c):
            return ring_flash_attention(a, b, c, axis_name="sp")
        f = shard_map_compat(body, mesh, (P(None, "sp", None),) * 3,
                             P(None, "sp", None))
        with pytest.raises(ValueError, match="GQA"):
            f(*(jax.device_put(x, sh) for x in (q, k, v)))

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_vs_dense(self, mesh, causal):
        """Group-summed dK/dV: differentiating through jnp.repeat in the
        dense reference gives exactly the per-group gradient sums the
        ring backward must produce."""
        import contextlib
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ucc_tpu.fused_attention import ring_flash_attention
        from ucc_tpu.utils.jaxshim import shard_map_compat
        h, h_kv, seq, d = 4, 2, 24, 4
        q, k, v = self._gqa_inputs(h, h_kv, seq, d, seed=23)
        sh = NamedSharding(mesh, P(None, "sp", None))
        qs, ks_, vs = (jax.device_put(x, sh) for x in (q, k, v))

        def body(a, b, c):
            return ring_flash_attention(a, b, c, axis_name="sp",
                                        causal=causal)
        f = shard_map_compat(body, mesh, (P(None, "sp", None),) * 3,
                             P(None, "sp", None))

        @jax.jit
        def loss(a, b, c):
            return jnp.sum(f(a, b, c) ** 2)

        def loss_ref(a, b, c):
            g = h // h_kv
            kr = jnp.repeat(b, g, axis=0)
            vr = jnp.repeat(c, g, axis=0)
            s = jnp.einsum("hqd,hkd->hqk", a, kr) / jnp.sqrt(jnp.float32(d))
            if causal:
                m = jnp.tril(jnp.ones((seq, seq), bool))
                s = jnp.where(m[None], s, -jnp.inf)
            p = jax.nn.softmax(s, -1)
            return jnp.sum(jnp.einsum("hqk,hkd->hqd", p, vr) ** 2)

        ctx = jax.set_mesh(mesh) if hasattr(jax, "set_mesh") \
            else contextlib.nullcontext()
        with ctx:
            g1 = jax.grad(loss, argnums=(0, 1, 2))(qs, ks_, vs)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)


class TestGqaLongContextTraining:
    """GQA token-stream train step (examples/long_context.py round-5
    variant): 8 q heads over 2 kv heads on a dp x sp mesh — the ring
    rotates 4x less K/V; loss must decrease through the grouped
    custom_vjp backward + joint-axis weight sync."""

    def test_loss_decreases(self):
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        from ucc_tpu.examples.long_context import (init_gqa_params,
                                                   make_gqa_train_step)
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = jax.make_mesh((2, 4), ("dp", "sp"))
        heads, kv_heads, e, dm = 8, 2, 4, 16
        params = init_gqa_params(dm, heads, kv_heads, e)
        kx, ky = jax.random.split(jax.random.PRNGKey(5))
        x = jax.random.normal(kx, (4, 32, dm), jnp.float32)
        y = jax.random.normal(ky, (4, 32, dm), jnp.float32) * 0.1
        xs = NamedSharding(mesh, P("dp", "sp", None))
        x, y = jax.device_put(x, xs), jax.device_put(y, xs)
        step = make_gqa_train_step(mesh, heads, kv_heads, e, lr=0.05)
        w = [params["wq"], params["wk"], params["wv"], params["wo"]]
        losses = []
        for _ in range(6):
            out = step(*w, x, y)
            losses.append(float(jax.device_get(out[0])))
            w = list(out[1:])
        assert losses[-1] < losses[0], losses
