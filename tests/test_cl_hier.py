"""CL/HIER tests — hierarchical collectives over a simulated multi-node
topology (UCC_TOPO_FAKE_PPN groups in-process ranks into virtual nodes,
playing the role the reference's simulated-topology gtest fixtures play).
Covers RAB allreduce (incl. pipelined + AVG), split_rail, 2step bcast/
reduce, hierarchical barrier, and selection precedence over cl/basic."""
import os

import numpy as np
import pytest

import ucc_tpu
from ucc_tpu import (BufferInfo, CollArgs, CollArgsFlags, CollType, DataType,
                     ReductionOp, Status)
from ucc_tpu.topo.sbgp import SbgpType

from harness import UccJob


@pytest.fixture(scope="module")
def job():
    os.environ["UCC_TOPO_FAKE_PPN"] = "4"   # 8 ranks -> 2 nodes x 4
    j = UccJob(8)
    yield j
    j.cleanup()
    os.environ.pop("UCC_TOPO_FAKE_PPN", None)


@pytest.fixture(scope="module")
def teams(job):
    return job.create_team()


def hier_team_of(team):
    for clt in team.cl_teams:
        if clt.name == "hier":
            return clt
    return None


class TestHierTopology:
    def test_hier_team_created(self, teams):
        assert hier_team_of(teams[0]) is not None

    def test_sbgps(self, teams):
        ht = hier_team_of(teams[0])   # rank 0: leader of node 0
        assert ht.sbgp(SbgpType.NODE).sbgp.size == 4
        assert ht.sbgp(SbgpType.NODE_LEADERS) is not None
        assert ht.sbgp(SbgpType.NODE_LEADERS).sbgp.size == 2
        ht3 = hier_team_of(teams[3])  # rank 3: not a leader
        assert ht3.sbgp(SbgpType.NODE_LEADERS) is None
        # NET rails exist (equal ppn)
        assert ht.sbgp(SbgpType.NET) is not None

    def test_hier_wins_selection(self, teams):
        cands = teams[0].score_map.lookup(CollType.ALLREDUCE,
                                          ucc_tpu.MemoryType.HOST, 1 << 20)
        assert cands[0].alg_name in ("rab", "split_rail")


class TestHierAllreduce:
    @pytest.mark.parametrize("count", [1, 40, 4096])
    def test_rab_sum(self, job, teams, count):
        n = 8
        srcs = [np.full(count, r + 1.0, np.float32) for r in range(n)]
        dsts = [np.zeros(count, np.float32) for _ in range(n)]
        job.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(srcs[r], count, DataType.FLOAT32),
            dst=BufferInfo(dsts[r], count, DataType.FLOAT32),
            op=ReductionOp.SUM))
        for r in range(n):
            np.testing.assert_allclose(dsts[r], 36.0)

    def test_rab_avg(self, job, teams):
        n, count = 8, 33
        srcs = [np.full(count, float(r), np.float64) for r in range(n)]
        dsts = [np.zeros(count, np.float64) for _ in range(n)]
        job.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(srcs[r], count, DataType.FLOAT64),
            dst=BufferInfo(dsts[r], count, DataType.FLOAT64),
            op=ReductionOp.AVG))
        for r in range(n):
            np.testing.assert_allclose(dsts[r], 3.5)

    def test_rab_inplace(self, job, teams):
        n, count = 8, 16
        bufs = [np.full(count, r + 1.0, np.float32) for r in range(n)]
        job.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.ALLREDUCE,
            dst=BufferInfo(bufs[r], count, DataType.FLOAT32),
            op=ReductionOp.SUM, flags=CollArgsFlags.IN_PLACE))
        for r in range(n):
            np.testing.assert_allclose(bufs[r], 36.0)

    def test_split_rail_via_tune(self, monkeypatch):
        monkeypatch.setenv("UCC_TOPO_FAKE_PPN", "4")
        monkeypatch.setenv("UCC_CL_HIER_TUNE", "")  # reserved
        job = UccJob(8)
        try:
            teams = job.create_team()
            ht = hier_team_of(teams[0])
            count = 64
            srcs = [np.full(count, r + 1.0, np.float64) for r in range(8)]
            dsts = [np.zeros(count, np.float64) for _ in range(8)]
            # drive split_rail directly through the hier score entries
            from ucc_tpu.core.coll import InitArgs
            from ucc_tpu.cl.hier.algs import split_rail_init
            reqs = []
            for r in range(8):
                args = CollArgs(
                    coll_type=CollType.ALLREDUCE,
                    src=BufferInfo(srcs[r], count, DataType.FLOAT64),
                    dst=BufferInfo(dsts[r], count, DataType.FLOAT64),
                    op=ReductionOp.SUM)
                ia = InitArgs(args=args, team=teams[r],
                              mem_type=ucc_tpu.MemoryType.HOST,
                              msgsize=count * 8)
                task = split_rail_init(ia, hier_team_of(teams[r]))
                task.progress_queue = job.contexts[r].progress_queue
                reqs.append(task)
            for t in reqs:
                t.post()
            job.progress_until(lambda: all(t.is_completed() for t in reqs))
            for r in range(8):
                assert reqs[r].super_status == Status.OK
                np.testing.assert_allclose(dsts[r], 36.0)
        finally:
            job.cleanup()

    def test_rab_pipelined(self, monkeypatch):
        monkeypatch.setenv("UCC_TOPO_FAKE_PPN", "2")
        monkeypatch.setenv("UCC_CL_HIER_ALLREDUCE_RAB_PIPELINE",
                           "thresh=64:fragsize=256:nfrags=4:pdepth=2:sequential")
        job = UccJob(4)
        try:
            teams = job.create_team()
            count = 1000   # 4000 bytes -> ~16 fragments of 256B
            srcs = [np.arange(count, dtype=np.float32) * (r + 1)
                    for r in range(4)]
            dsts = [np.zeros(count, np.float32) for _ in range(4)]
            job.run_coll(teams, lambda r: CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(srcs[r], count, DataType.FLOAT32),
                dst=BufferInfo(dsts[r], count, DataType.FLOAT32),
                op=ReductionOp.SUM))
            expect = np.arange(count, dtype=np.float32) * 10
            for r in range(4):
                np.testing.assert_allclose(dsts[r], expect, rtol=1e-5)
        finally:
            job.cleanup()


class TestHierRootedAndBarrier:
    @pytest.mark.parametrize("root", [0, 5])   # leader and non-leader roots
    def test_bcast_2step(self, job, teams, root):
        n, count = 8, 50
        bufs = [(np.arange(count, dtype=np.int32) if r == root else
                 np.zeros(count, np.int32)) for r in range(n)]
        job.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.BCAST, root=root,
            src=BufferInfo(bufs[r], count, DataType.INT32)))
        for r in range(n):
            np.testing.assert_array_equal(bufs[r], np.arange(count))

    @pytest.mark.parametrize("root", [0, 6])
    def test_reduce_2step(self, job, teams, root):
        n, count = 8, 24
        srcs = [np.full(count, r + 1.0, np.float32) for r in range(n)]
        dst = np.zeros(count, np.float32)
        job.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.REDUCE, root=root,
            src=BufferInfo(srcs[r], count, DataType.FLOAT32),
            dst=BufferInfo(dst, count, DataType.FLOAT32) if r == root else None,
            op=ReductionOp.SUM))
        np.testing.assert_allclose(dst, 36.0)

    def test_barrier(self, job, teams):
        job.run_coll(teams, lambda r: CollArgs(coll_type=CollType.BARRIER))


class TestHierAllgatherv:
    def test_allgatherv_unpack(self, job, teams):
        """node gatherv -> leaders allgatherv -> node bcast -> unpack
        (cl_hier allgatherv w/ unpack step)."""
        n = 8
        counts = [2, 5, 1, 3, 4, 2, 6, 1]
        displs = list(np.cumsum([0] + counts[:-1]))
        total = sum(counts)
        srcs = [np.arange(counts[r], dtype=np.float32) + 100 * r
                for r in range(n)]
        dsts = [np.zeros(total, np.float32) for _ in range(n)]
        job.run_coll(teams, lambda r: ucc_tpu.CollArgs(
            coll_type=CollType.ALLGATHERV,
            src=BufferInfo(srcs[r], counts[r], DataType.FLOAT32),
            dst=ucc_tpu.BufferInfoV(dsts[r], counts, displs,
                                    DataType.FLOAT32)))
        expect = np.concatenate(srcs)
        for r in range(n):
            np.testing.assert_array_equal(dsts[r], expect)

    def test_allgatherv_selected_by_hier(self, teams):
        cands = teams[0].score_map.lookup(CollType.ALLGATHERV,
                                          ucc_tpu.MemoryType.HOST, 1 << 16)
        assert cands[0].alg_name == "unpack"

    def test_allgatherv_gapped_displacements(self, job, teams):
        """MPI-legal gaps between dst blocks must be preserved."""
        n = 8
        counts = [2] * n
        displs = [3 * r for r in range(n)]       # stride-3 gaps
        span = displs[-1] + counts[-1]
        srcs = [np.full(2, r + 1, np.int32) for r in range(n)]
        dsts = [np.full(span, -1, np.int32) for _ in range(n)]
        job.run_coll(teams, lambda r: ucc_tpu.CollArgs(
            coll_type=CollType.ALLGATHERV,
            src=BufferInfo(srcs[r], 2, DataType.INT32),
            dst=ucc_tpu.BufferInfoV(dsts[r], counts, displs,
                                    DataType.INT32)))
        for r in range(n):
            for p in range(n):
                np.testing.assert_array_equal(
                    dsts[r][displs[p]:displs[p] + 2], p + 1)
            # gap bytes untouched
            assert dsts[r][2] == -1


class TestTopoOrderedRing:
    def test_allreduce_ring_reorders_on_multinode(self, job, teams,
                                                  monkeypatch):
        """Ring allreduce over FULL_HOST_ORDERED: correctness unchanged,
        and the subset actually reorders when team ranks interleave
        hosts."""
        count = 4096    # large -> ring/sra range
        monkeypatch.setenv("UCC_TL_SHM_TUNE", "allreduce:@ring:inf")
        if True:
            # interleaved membership: team ranks alternate fake nodes
            sub2 = job.create_team([0, 4, 1, 5])
            srcs = [np.full(count, i + 1.0, np.float32) for i in range(4)]
            dsts = [np.zeros(count, np.float32) for _ in range(4)]
            job.run_coll(sub2, lambda r: CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(srcs[r], count, DataType.FLOAT32),
                dst=BufferInfo(dsts[r], count, DataType.FLOAT32),
                op=ReductionOp.SUM))
            for r in range(4):
                np.testing.assert_allclose(dsts[r], 10.0)
            # the reorder map is non-identity for this membership
            shm = None
            for clt in sub2[0].cl_teams:
                if clt.name == "basic":
                    for t in clt.tl_teams:
                        if t.name == "shm":
                            shm = t
            assert shm is not None
            ss = shm.topo_ordered_subset()
            assert ss is not None
            assert ss.map.to_array().tolist() != [0, 1, 2, 3]


class TestHierAlltoallNodeAgg:
    def test_alltoall_small_uses_node_agg(self, job, teams):
        cands = teams[0].score_map.lookup(CollType.ALLTOALL,
                                          ucc_tpu.MemoryType.HOST, 256)
        assert cands[0].alg_name == "node_agg"
        # above the threshold, flat algorithms win
        cands_big = teams[0].score_map.lookup(CollType.ALLTOALL,
                                              ucc_tpu.MemoryType.HOST,
                                              1 << 20)
        assert cands_big[0].alg_name != "node_agg"

    @pytest.mark.parametrize("blk", [1, 3])
    def test_alltoall_node_agg_correct(self, job, teams, blk):
        n = 8
        total = n * blk
        srcs = [np.arange(total, dtype=np.int32) + 1000 * r
                for r in range(n)]
        dsts = [np.zeros(total, np.int32) for _ in range(n)]
        job.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.ALLTOALL,
            src=BufferInfo(srcs[r], total, DataType.INT32),
            dst=BufferInfo(dsts[r], total, DataType.INT32)))
        for r in range(n):
            expect = np.concatenate(
                [srcs[p][r * blk:(r + 1) * blk] for p in range(n)])
            np.testing.assert_array_equal(dsts[r], expect)

    def test_alltoall_inplace_node_agg(self, job, teams):
        n, blk = 8, 2
        total = n * blk
        bufs = [np.arange(total, dtype=np.float32) + 100 * r
                for r in range(n)]
        origs = [b.copy() for b in bufs]
        job.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.ALLTOALL,
            dst=BufferInfo(bufs[r], total, DataType.FLOAT32),
            flags=CollArgsFlags.IN_PLACE))
        for r in range(n):
            expect = np.concatenate(
                [origs[p][r * blk:(r + 1) * blk] for p in range(n)])
            np.testing.assert_array_equal(bufs[r], expect)

    def test_alltoall_inplace_persistent_repost(self, job, teams):
        """Persistent in-place node-agg alltoall must snapshot per POST,
        not per init (re-posts read fresh data)."""
        n, blk = 8, 1
        total = n * blk
        bufs = [np.zeros(total, np.float32) for _ in range(n)]
        reqs = [teams[r].collective_init(CollArgs(
            coll_type=CollType.ALLTOALL,
            dst=BufferInfo(bufs[r], total, DataType.FLOAT32),
            flags=CollArgsFlags.IN_PLACE | CollArgsFlags.PERSISTENT))
            for r in range(n)]
        for it in (1, 2):
            for r in range(n):
                bufs[r][:] = np.arange(total) + 100 * r + 1000 * it
            origs = [b.copy() for b in bufs]
            for rq in reqs:
                rq.post()
            job.progress_until(lambda: all(
                rq.test() != Status.IN_PROGRESS for rq in reqs))
            for r in range(n):
                expect = np.concatenate(
                    [origs[p][r * blk:(r + 1) * blk] for p in range(n)])
                np.testing.assert_array_equal(bufs[r], expect)


class TestHierAlltoallvNodeAgg:
    def test_a2av_selected_by_hier(self, teams):
        cands = teams[0].score_map.lookup(CollType.ALLTOALLV,
                                          ucc_tpu.MemoryType.HOST, 256)
        assert cands[0].alg_name == "node_agg"

    @pytest.mark.parametrize("seed", [3, 11])
    def test_a2av_node_agg_correct(self, job, teams, seed):
        """Random per-pair counts matrix (incl zeros) through the full
        count-exchange -> gatherv -> leaders-a2av -> scatterv pipeline."""
        n = 8
        rng = np.random.default_rng(seed)
        m = rng.integers(0, 6, size=(n, n))
        from ucc_tpu import BufferInfoV
        srcs, dsts, argses = [], [], []
        for r in range(n):
            scounts = [int(c) for c in m[r]]
            rcounts = [int(m[p][r]) for p in range(n)]
            srcs.append(np.arange(sum(scounts), dtype=np.int64) + 1000 * r)
            dsts.append(np.zeros(sum(rcounts), np.int64))
            argses.append(CollArgs(
                coll_type=CollType.ALLTOALLV,
                src=BufferInfoV(srcs[r], scounts, None, DataType.INT64),
                dst=BufferInfoV(dsts[r], rcounts, None, DataType.INT64)))
        job.run_coll(teams, lambda r: argses[r])
        for r in range(n):
            off = 0
            for p in range(n):
                c = int(m[p][r])
                sd = int(np.sum(m[p][:r]))
                expect = (np.arange(int(np.sum(m[p])), dtype=np.int64)
                          + 1000 * p)[sd:sd + c]
                np.testing.assert_array_equal(dsts[r][off:off + c], expect)
                off += c

    def test_a2av_gapped_displacements(self, job, teams):
        """MPI-legal displacement gaps in dst."""
        n = 8
        from ucc_tpu import BufferInfoV
        scounts = [1] * n
        srcs = [np.arange(n, dtype=np.int32) + 10 * r for r in range(n)]
        # dst: blocks at stride 3 (gaps of 2)
        dsts = [np.full(3 * n, -1, np.int32) for _ in range(n)]
        rdispls = [3 * p for p in range(n)]
        argses = [CollArgs(
            coll_type=CollType.ALLTOALLV,
            src=BufferInfoV(srcs[r], scounts, None, DataType.INT32),
            dst=BufferInfoV(dsts[r], [1] * n, rdispls, DataType.INT32))
            for r in range(n)]
        job.run_coll(teams, lambda r: argses[r])
        for r in range(n):
            for p in range(n):
                assert dsts[r][3 * p] == 10 * p + r
                assert dsts[r][3 * p + 1] == -1      # gap untouched


class TestHierSplitRailPipelined:
    def test_split_rail_pipelined(self, monkeypatch):
        monkeypatch.setenv("UCC_TOPO_FAKE_PPN", "4")
        monkeypatch.setenv("UCC_CL_HIER_ALLREDUCE_SPLIT_RAIL_PIPELINE",
                           "thresh=0:fragsize=256:pdepth=2")
        monkeypatch.setenv("UCC_CL_HIER_TUNE", "allreduce:@split_rail:inf")
        job = UccJob(8)
        try:
            teams = job.create_team()
            # the tune must route to split_rail and the config must make
            # it a PipelinedSchedule (not the monolithic stage machine)
            from ucc_tpu.schedule.pipelined import PipelinedSchedule
            count = 1000       # several 256B fragments of f64
            srcs = [np.arange(count, dtype=np.float64) + r
                    for r in range(8)]
            dsts = [np.zeros(count, np.float64) for _ in range(8)]
            # collective_init allocates sub-collective tags, so it must be
            # called symmetrically on every rank (UCC init contract)
            reqs = [teams[r].collective_init(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(srcs[r], count, DataType.FLOAT64),
                dst=BufferInfo(dsts[r], count, DataType.FLOAT64),
                op=ReductionOp.SUM)) for r in range(8)]
            assert isinstance(reqs[0].task, PipelinedSchedule), \
                type(reqs[0].task)
            for rq in reqs:
                rq.post()
            job.progress_until(lambda: all(
                rq.test() != Status.IN_PROGRESS for rq in reqs))
            assert all(rq.test() == Status.OK for rq in reqs)
            expect = np.sum(srcs, axis=0)
            for r in range(8):
                np.testing.assert_allclose(dsts[r], expect)
        finally:
            job.cleanup()
