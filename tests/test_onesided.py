"""One-sided (mem_map remote access + global_work_buffer collectives).

Mirrors the reference's one-sided coverage: gtest core/test_mem_map.cc
(export/import/unmap), test/mpi onesided alltoall sweeps (main.cc -o flag),
and the sliding-window allreduce path (allreduce_sliding_window.c) — here
over the host RDMA-emulation transports (tl/host/onesided.py)."""
import numpy as np
import pytest

import ucc_tpu
from ucc_tpu import (BufferInfo, CollArgs, CollArgsFlags, CollType, DataType,
                     ReductionOp, Status)
from ucc_tpu.constants import dt_numpy
from ucc_tpu.tl.host.onesided import REGISTRY

from harness import UccJob


def _mkdata(rank, count, nd, seed=11):
    rng = np.random.default_rng(seed + rank)
    if np.issubdtype(nd, np.floating):
        return (rng.random(count) * 4 - 2).astype(nd)
    return rng.integers(1, 50, size=count).astype(nd)


@pytest.fixture()
def job4(monkeypatch, request):
    """Fresh 4-rank job; tests parametrize the TUNE env via markers."""
    tune = getattr(request, "param", "")
    if tune:
        monkeypatch.setenv("UCC_TL_SHM_TUNE", tune)
    j = UccJob(4)
    try:
        yield j
    finally:
        j.cleanup()


# ---------------------------------------------------------------------------
# sliding-window knob resolution (allreduce_sliding_window.h:36-38 analog)
# ---------------------------------------------------------------------------

class TestSwKnobs:
    """Pin sw_knobs auto outputs to the round-5 re-sweep table
    (BASELINE.md): the knobs are how the sweep's conclusions reach the
    collective, and round 4 shipped them broken (string-compared a
    parsed sentinel)."""

    @staticmethod
    def _default_cfg():
        from ucc_tpu.tl.shm import TL_SHM_CONFIG
        from ucc_tpu.utils.config import Config
        return Config(TL_SHM_CONFIG, env={})

    @pytest.mark.parametrize("msg,want_w,want_i", [
        (4 << 20, 256 << 10, 4),    # 4 MiB: 256K floor
        (16 << 20, 256 << 10, 4),   # 16 MiB: msg/64 = 256K (sweep best)
        (64 << 20, 1 << 20, 4),     # 64 MiB: 1M ceiling (sweep best)
    ])
    def test_auto_matches_sweep_table(self, msg, want_w, want_i):
        from ucc_tpu.tl.host.onesided import sw_knobs
        # the default config carries the PARSED 'auto' sentinel — the
        # exact value class the round-4 bug mishandled
        w, i = sw_knobs(self._default_cfg(), msg)
        assert (w, i) == (want_w, want_i)
        # no config at all resolves identically
        assert sw_knobs(None, msg) == (want_w, want_i)

    def test_explicit_values_win(self):
        from ucc_tpu.tl.shm import TL_SHM_CONFIG
        from ucc_tpu.tl.host.onesided import sw_knobs
        from ucc_tpu.utils.config import Config
        cfg = Config(TL_SHM_CONFIG, env={
            "UCC_TL_SHM_ALLREDUCE_SW_WINDOW": "512k",
            "UCC_TL_SHM_ALLREDUCE_SW_INFLIGHT": "2",
        })
        assert sw_knobs(cfg, 64 << 20) == (512 << 10, 2)

    def test_inf_sentinels_fall_back_to_auto(self):
        """'inf' parses to SIZE_INF/UINT_MAX — meaningless as scratch
        sizes; both must resolve like auto, not allocate from 2^64."""
        from ucc_tpu.tl.shm import TL_SHM_CONFIG
        from ucc_tpu.tl.host.onesided import sw_knobs, sw_max_work_buffer
        from ucc_tpu.utils.config import Config
        cfg = Config(TL_SHM_CONFIG, env={
            "UCC_TL_SHM_ALLREDUCE_SW_WINDOW": "inf",
            "UCC_TL_SHM_ALLREDUCE_SW_INFLIGHT": "inf",
        })
        assert sw_knobs(cfg, 64 << 20) == (1 << 20, 4)
        assert sw_max_work_buffer(cfg) == (1 << 20) * 4

    def test_max_work_buffer_auto_and_explicit(self):
        from ucc_tpu.tl.shm import TL_SHM_CONFIG
        from ucc_tpu.tl.host.onesided import sw_max_work_buffer
        from ucc_tpu.utils.config import Config
        assert sw_max_work_buffer(self._default_cfg()) == (1 << 20) * 4
        cfg = Config(TL_SHM_CONFIG, env={
            "UCC_TL_SHM_ALLREDUCE_SW_WINDOW": "1m",
            "UCC_TL_SHM_ALLREDUCE_SW_INFLIGHT": "2",
        })
        assert sw_max_work_buffer(cfg) == (1 << 20) * 2


# ---------------------------------------------------------------------------
# mem_map export/import/unmap (ucc.h:2265-2320)
# ---------------------------------------------------------------------------

class TestMemMap:
    def test_export_registers_segment(self, job4):
        ctx = job4.contexts[0]
        buf = np.arange(64, dtype=np.float64)
        h = ctx.mem_map(buf)
        desc = ctx.mem_import(h)
        assert desc["onesided"] is True
        assert desc["nbytes"] == buf.nbytes
        assert desc["buffer"] is buf           # same-process resolution
        key = (desc["ctx_uid"], desc["seg_id"])
        assert key in REGISTRY.segments
        ctx.mem_unmap(h)
        assert key not in REGISTRY.segments

    def test_import_remote_handle_is_metadata_only(self, job4):
        h = job4.contexts[1].mem_map(np.zeros(8, dtype=np.int32))
        desc = job4.contexts[0].mem_import(h)
        assert desc["buffer"] is None
        assert desc["seg_id"] >= 1

    def test_context_destroy_unregisters(self):
        job = UccJob(2)
        uid = job.contexts[0]._ctx_uid
        job.contexts[0].mem_map(np.zeros(16, dtype=np.uint8))
        assert any(k[0] == uid for k in REGISTRY.segments)
        job.cleanup()
        assert not any(k[0] == uid for k in REGISTRY.segments)

    def test_readonly_buffer_is_get_only(self, job4):
        ctx = job4.contexts[0]
        h = ctx.mem_map(b"\x01\x02\x03\x04")
        desc = ctx.mem_import(h)
        got = REGISTRY.read_get(desc["ctx_uid"], desc["seg_id"], 1, 2)
        assert got is not None and bytes(got) == b"\x02\x03"
        err = REGISTRY.apply_put(desc["ctx_uid"], desc["seg_id"], 0,
                                 np.zeros(2, dtype=np.uint8))
        assert err is not None and "read-only" in err

    def test_context_attr_work_buffer_size(self, job4):
        """ucc_context_get_attr parity (ucc.h:1177-1185): packed context
        address + the global_work_buffer scratch contract."""
        attr = job4.contexts[0].get_attr()
        assert attr.ctx_addr_len == len(attr.ctx_addr) > 0
        # auto sliding-window scratch bound: 1M window x 4 in-flight
        assert attr.global_work_buffer_size >= 4 * (1 << 20)

    def test_tpu_buffer_exports_metadata_only(self, job4):
        jax = pytest.importorskip("jax")
        ctx = job4.contexts[0]
        import jax.numpy as jnp
        h = ctx.mem_map(jnp.zeros(8, dtype=jnp.float32))
        desc = ctx.mem_import(h)
        assert desc["onesided"] is False


# ---------------------------------------------------------------------------
# onesided alltoall (tl_ucp alltoall_onesided.c)
# ---------------------------------------------------------------------------

def _a2a_expect(srcs, n, bsz):
    return [np.concatenate([srcs[p][r * bsz:(r + 1) * bsz]
                            for p in range(n)]) for r in range(n)]


class TestAlltoallOnesided:
    @pytest.mark.parametrize("job4", ["alltoall:@onesided"], indirect=True)
    @pytest.mark.parametrize("count_per", [1, 7, 1024])
    def test_put_variant(self, job4, count_per):
        n = 4
        count = count_per * n
        teams = job4.create_team()
        srcs = [_mkdata(r, count, np.float32) for r in range(n)]
        dsts = [np.zeros(count, dtype=np.float32) for _ in range(n)]
        handles = [job4.contexts[r].mem_map(dsts[r]) for r in range(n)]
        job4.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.ALLTOALL,
            src=BufferInfo(srcs[r], count, DataType.FLOAT32),
            dst=BufferInfo(dsts[r], count, DataType.FLOAT32),
            dst_memh=list(handles),
            flags=CollArgsFlags.MEM_MAP_DST_MEMH))
        for r, e in enumerate(_a2a_expect(srcs, n, count_per)):
            np.testing.assert_array_equal(dsts[r], e)
        # completion counters are deleted once consumed
        assert not any(isinstance(k, tuple) and k and k[0] == "__os_ctr__"
                       for k in REGISTRY.counters)

    @pytest.mark.parametrize("job4", ["alltoall:@onesided"], indirect=True)
    def test_get_variant(self, job4, monkeypatch):
        monkeypatch.setenv("UCC_TL_SHM_ALLTOALL_ONESIDED_ALG", "get")
        n = 4
        count = 8 * n
        teams = job4.create_team()
        srcs = [_mkdata(r, count, np.int64) for r in range(n)]
        dsts = [np.zeros(count, dtype=np.int64) for _ in range(n)]
        handles = [job4.contexts[r].mem_map(srcs[r]) for r in range(n)]
        job4.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.ALLTOALL,
            src=BufferInfo(srcs[r], count, DataType.INT64),
            dst=BufferInfo(dsts[r], count, DataType.INT64),
            src_memh=list(handles),
            flags=CollArgsFlags.MEM_MAP_SRC_MEMH))
        for r, e in enumerate(_a2a_expect(srcs, n, 8)):
            np.testing.assert_array_equal(dsts[r], e)

    @pytest.mark.parametrize("job4", ["alltoall:@onesided"], indirect=True)
    def test_missing_memh_self_bootstraps(self, job4):
        """TUNE selects onesided with NO memh args: the task mem_maps its
        own buffers and exchanges handles inline (round-3 bootstrap mode),
        then runs the one-sided protocol — no user rkey plumbing. The
        bootstrap segments are unmapped at completion."""
        n = 4
        count = 4 * n
        teams = job4.create_team()
        srcs = [_mkdata(r, count, np.float32) for r in range(n)]
        dsts = [np.zeros(count, dtype=np.float32) for _ in range(n)]
        before = len(REGISTRY.segments)
        job4.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.ALLTOALL,
            src=BufferInfo(srcs[r], count, DataType.FLOAT32),
            dst=BufferInfo(dsts[r], count, DataType.FLOAT32)))
        for r, e in enumerate(_a2a_expect(srcs, n, 4)):
            np.testing.assert_array_equal(dsts[r], e)
        assert len(REGISTRY.segments) == before   # bootstrap maps cleaned

    def test_memh_args_with_default_tune_run_twosided(self, job4):
        """Passing global memh without TUNE-selecting onesided keeps the
        default algorithm (reference parity: memh args enable, never
        force, the onesided path)."""
        n = 4
        count = 4 * n
        teams = job4.create_team()
        srcs = [_mkdata(r, count, np.float32) for r in range(n)]
        dsts = [np.zeros(count, dtype=np.float32) for _ in range(n)]
        handles = [job4.contexts[r].mem_map(dsts[r]) for r in range(n)]
        job4.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.ALLTOALL,
            src=BufferInfo(srcs[r], count, DataType.FLOAT32),
            dst=BufferInfo(dsts[r], count, DataType.FLOAT32),
            dst_memh=list(handles),
            flags=CollArgsFlags.MEM_MAP_DST_MEMH))
        for r, e in enumerate(_a2a_expect(srcs, n, 4)):
            np.testing.assert_array_equal(dsts[r], e)


class TestAlltoallvOnesided:
    """alltoallv_onesided.c semantics: initiator-side dst displacements
    are TARGET-relative (the transpose of the usual receive table)."""

    @pytest.mark.parametrize("job4", ["alltoallv:@onesided"], indirect=True)
    def test_uneven_blocks(self, job4):
        n = 4
        teams = job4.create_team()
        # m[r][p] = elements rank r sends to rank p
        m = [[(r + p) % 3 + 1 for p in range(n)] for r in range(n)]
        recv_counts = [[m[q][p] for q in range(n)] for p in range(n)]
        srcs, dsts, s_displ, d_displ_target = [], [], [], []
        for r in range(n):
            total = sum(m[r])
            srcs.append(np.arange(total, dtype=np.int32) + 1000 * r)
            dsts.append(np.full(sum(recv_counts[r]), -1, np.int32))
            s_displ.append(list(np.cumsum([0] + m[r][:-1])))
            # target-relative: my offset inside peer p's dst buffer
            d_displ_target.append(
                [sum(m[q][p] for q in range(r)) for p in range(n)])
        handles = [job4.contexts[r].mem_map(dsts[r]) for r in range(n)]
        from ucc_tpu import BufferInfoV
        job4.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.ALLTOALLV,
            src=BufferInfoV(srcs[r], m[r], s_displ[r], DataType.INT32),
            dst=BufferInfoV(dsts[r], recv_counts[r], d_displ_target[r],
                            DataType.INT32),
            dst_memh=list(handles),
            flags=CollArgsFlags.MEM_MAP_DST_MEMH))
        for p in range(n):
            expect = np.concatenate([
                srcs[q][s_displ[q][p]:s_displ[q][p] + m[q][p]]
                for q in range(n)])
            np.testing.assert_array_equal(dsts[p], expect)

    @pytest.mark.parametrize("job4", ["alltoallv:@onesided"], indirect=True)
    def test_bootstrap_mode_standard_semantics(self, job4):
        """Without memh the a2av bootstrap exchange carries each rank's
        receive displacements, so STANDARD MPI alltoallv args (usual
        receive-displacement table, no transpose) just work."""
        n = 4
        teams = job4.create_team()
        m = [[(r * 2 + p) % 3 + 1 for p in range(n)] for r in range(n)]
        recv_counts = [[m[q][p] for q in range(n)] for p in range(n)]
        srcs, dsts = [], []
        for r in range(n):
            srcs.append(np.arange(sum(m[r]), dtype=np.int32) + 1000 * r)
            dsts.append(np.full(sum(recv_counts[r]), -1, np.int32))
        from ucc_tpu import BufferInfoV
        job4.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.ALLTOALLV,
            src=BufferInfoV(srcs[r], m[r], None, DataType.INT32),
            dst=BufferInfoV(dsts[r], recv_counts[r], None, DataType.INT32)))
        for p in range(n):
            sdispl = {q: np.cumsum([0] + m[q][:-1]) for q in range(n)}
            expect = np.concatenate([
                srcs[q][sdispl[q][p]:sdispl[q][p] + m[q][p]]
                for q in range(n)])
            np.testing.assert_array_equal(dsts[p], expect)

    @pytest.mark.parametrize("job4", ["alltoallv:@onesided"], indirect=True)
    def test_zero_count_rank_still_notifies(self, job4):
        """An all-zero-count rank must not take the zero-size stub: its
        zero-byte puts carry the notifies peers' counters wait on."""
        n = 4
        teams = job4.create_team()
        # rank 0 sends nothing and receives nothing
        m = [[0] * n] + [[0 if p == 0 else 2 for p in range(n)]
                         for _ in range(1, n)]
        recv_counts = [[m[q][p] for q in range(n)] for p in range(n)]
        srcs, dsts, s_displ, d_displ_target = [], [], [], []
        for r in range(n):
            total = max(1, sum(m[r]))
            srcs.append(np.arange(total, dtype=np.float32) + 100 * r)
            dsts.append(np.zeros(max(1, sum(recv_counts[r])), np.float32))
            s_displ.append(list(np.cumsum([0] + m[r][:-1])))
            d_displ_target.append(
                [sum(m[q][p] for q in range(r)) for p in range(n)])
        handles = [job4.contexts[r].mem_map(dsts[r]) for r in range(n)]
        from ucc_tpu import BufferInfoV
        job4.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.ALLTOALLV,
            src=BufferInfoV(srcs[r], m[r], s_displ[r], DataType.FLOAT32),
            dst=BufferInfoV(dsts[r], recv_counts[r], d_displ_target[r],
                            DataType.FLOAT32),
            dst_memh=list(handles),
            flags=CollArgsFlags.MEM_MAP_DST_MEMH))
        for p in range(1, n):
            expect = np.concatenate([
                srcs[q][s_displ[q][p]:s_displ[q][p] + m[q][p]]
                for q in range(n) if m[q][p]])
            np.testing.assert_array_equal(dsts[p][:expect.size], expect)


# ---------------------------------------------------------------------------
# sliding-window one-sided allreduce (allreduce_sliding_window.{c,h})
# ---------------------------------------------------------------------------

def _sw_args(srcs, dsts, sh, dh, op, dt, count, inplace=False):
    flags = (CollArgsFlags.MEM_MAP_SRC_MEMH
             | CollArgsFlags.MEM_MAP_DST_MEMH)
    if inplace:
        flags |= CollArgsFlags.IN_PLACE

    def make(r):
        return CollArgs(coll_type=CollType.ALLREDUCE,
                        src=BufferInfo(srcs[r], count, dt),
                        dst=BufferInfo(dsts[r], count, dt),
                        op=op, src_memh=list(sh), dst_memh=list(dh),
                        flags=flags)
    return make


class TestAlltoallvOnesidedGet:
    """Beyond-reference GET variant (the reference alltoallv_onesided.c
    is put-only): readers pull blocks out of peers' source segments; a
    closing barrier keeps src segments readable (same protocol as the
    non-v alltoall get, tl_ucp.h:46-51)."""

    @staticmethod
    def _job_get(monkeypatch):
        monkeypatch.setenv("UCC_TL_SHM_TUNE", "alltoallv:@onesided")
        monkeypatch.setenv("UCC_TL_SHM_ALLTOALLV_ONESIDED_ALG", "get")
        return UccJob(4)

    def test_explicit_memh_target_relative(self, monkeypatch):
        """src.displacements are TARGET-relative in get mode: the offset
        inside PEER's source buffer of the block destined for me — the
        exact mirror of the put convention."""
        job = self._job_get(monkeypatch)
        try:
            n = 4
            teams = job.create_team()
            m = [[(r + p) % 3 + 1 for p in range(n)] for r in range(n)]
            recv_counts = [[m[q][p] for q in range(n)] for p in range(n)]
            srcs, dsts, s_displ_target = [], [], []
            for r in range(n):
                srcs.append(np.arange(sum(m[r]), dtype=np.int32) + 1000 * r)
                dsts.append(np.full(sum(recv_counts[r]), -1, np.int32))
                # target-relative: block-for-me's offset inside peer p's
                # SOURCE buffer = sum of p's sends to ranks before me
                s_displ_target.append(
                    [sum(m[p][q] for q in range(r)) for p in range(n)])
            handles = [job.contexts[r].mem_map(srcs[r]) for r in range(n)]
            from ucc_tpu import BufferInfoV
            job.run_coll(teams, lambda r: CollArgs(
                coll_type=CollType.ALLTOALLV,
                src=BufferInfoV(srcs[r], m[r], s_displ_target[r],
                                DataType.INT32),
                dst=BufferInfoV(dsts[r], recv_counts[r], None,
                                DataType.INT32),
                src_memh=list(handles),
                flags=CollArgsFlags.MEM_MAP_SRC_MEMH))
            for p in range(n):
                sdispl = {q: np.cumsum([0] + m[q][:-1]) for q in range(n)}
                expect = np.concatenate([
                    srcs[q][sdispl[q][p]:sdispl[q][p] + m[q][p]]
                    for q in range(n)])
                np.testing.assert_array_equal(dsts[p], expect)
        finally:
            job.cleanup()

    def test_bootstrap_mode_standard_semantics(self, monkeypatch):
        """Without memh the get-mode bootstrap exchange carries each
        rank's SEND displacements, so standard MPI alltoallv args just
        work."""
        job = self._job_get(monkeypatch)
        try:
            n = 4
            teams = job.create_team()
            m = [[(r * 2 + p) % 3 + 1 for p in range(n)] for r in range(n)]
            recv_counts = [[m[q][p] for q in range(n)] for p in range(n)]
            srcs, dsts = [], []
            for r in range(n):
                srcs.append(np.arange(sum(m[r]), dtype=np.int32) + 1000 * r)
                dsts.append(np.full(sum(recv_counts[r]), -1, np.int32))
            from ucc_tpu import BufferInfoV
            job.run_coll(teams, lambda r: CollArgs(
                coll_type=CollType.ALLTOALLV,
                src=BufferInfoV(srcs[r], m[r], None, DataType.INT32),
                dst=BufferInfoV(dsts[r], recv_counts[r], None,
                                DataType.INT32)))
            for p in range(n):
                sdispl = {q: np.cumsum([0] + m[q][:-1]) for q in range(n)}
                expect = np.concatenate([
                    srcs[q][sdispl[q][p]:sdispl[q][p] + m[q][p]]
                    for q in range(n)])
                np.testing.assert_array_equal(dsts[p], expect)
        finally:
            job.cleanup()

    def test_zero_count_peer(self, monkeypatch):
        """A peer that sends me nothing: zero-byte get + barrier still
        complete (the put path has the mirror-image test above)."""
        job = self._job_get(monkeypatch)
        try:
            n = 4
            teams = job.create_team()
            # rank 0 sends nothing to anyone; others send 2 elems each
            m = [[0] * n] + [[2] * n for _ in range(n - 1)]
            recv_counts = [[m[q][p] for q in range(n)] for p in range(n)]
            srcs, dsts = [], []
            for r in range(n):
                srcs.append(np.arange(max(sum(m[r]), 1),
                                      dtype=np.int32) + 1000 * r)
                dsts.append(np.full(max(sum(recv_counts[r]), 1), -1,
                                    np.int32))
            from ucc_tpu import BufferInfoV
            job.run_coll(teams, lambda r: CollArgs(
                coll_type=CollType.ALLTOALLV,
                src=BufferInfoV(srcs[r], m[r], None, DataType.INT32),
                dst=BufferInfoV(dsts[r], recv_counts[r], None,
                                DataType.INT32)))
            for p in range(n):
                got = dsts[p][:sum(recv_counts[p])]
                sdispl = {q: np.cumsum([0] + m[q][:-1]) for q in range(n)}
                expect = np.concatenate([
                    srcs[q][sdispl[q][p]:sdispl[q][p] + m[q][p]]
                    for q in range(n)]) if sum(recv_counts[p]) else \
                    np.empty(0, np.int32)
                np.testing.assert_array_equal(got, expect)
        finally:
            job.cleanup()


class TestSlidingWindowAllreduce:
    @pytest.mark.parametrize("job4", ["allreduce:@sliding_window"],
                             indirect=True)
    @pytest.mark.parametrize("count", [3, 64, 4097])
    def test_sum_multiwindow(self, job4, count, monkeypatch):
        # tiny window forces the multi-window pipeline incl. remainders
        monkeypatch.setenv("UCC_TL_SHM_ALLREDUCE_SW_WINDOW", "256")
        n = 4
        teams = job4.create_team()
        srcs = [_mkdata(r, count, np.float32) for r in range(n)]
        dsts = [np.zeros(count, dtype=np.float32) for _ in range(n)]
        sh = [job4.contexts[r].mem_map(srcs[r]) for r in range(n)]
        dh = [job4.contexts[r].mem_map(dsts[r]) for r in range(n)]
        job4.run_coll(teams, _sw_args(srcs, dsts, sh, dh, ReductionOp.SUM,
                                      DataType.FLOAT32, count))
        expect = np.sum(srcs, axis=0)
        for r in range(n):
            np.testing.assert_allclose(dsts[r], expect, rtol=1e-4,
                                       atol=1e-5)

    @pytest.mark.parametrize("job4", ["allreduce:@sliding_window"],
                             indirect=True)
    def test_avg_inplace(self, job4):
        n = 4
        count = 1000
        teams = job4.create_team()
        bufs = [_mkdata(r, count, np.float64) for r in range(n)]
        ref = [b.copy() for b in bufs]
        # in-place: src and dst memh map the same buffer
        h = [job4.contexts[r].mem_map(bufs[r]) for r in range(n)]
        job4.run_coll(teams, _sw_args(bufs, bufs, h, h, ReductionOp.AVG,
                                      DataType.FLOAT64, count, inplace=True))
        expect = np.mean(ref, axis=0)
        for r in range(n):
            np.testing.assert_allclose(bufs[r], expect, rtol=1e-9)

    @pytest.mark.parametrize("job4", ["allreduce:@sliding_window"],
                             indirect=True)
    @pytest.mark.parametrize("op,nd,dt", [
        (ReductionOp.MAX, np.int32, DataType.INT32),
        (ReductionOp.PROD, np.float32, DataType.FLOAT32),
    ])
    def test_ops_dtypes(self, job4, op, nd, dt):
        n = 4
        count = 37          # not divisible by team size: uneven partitions
        teams = job4.create_team()
        srcs = [_mkdata(r, count, nd) for r in range(n)]
        if op == ReductionOp.PROD:
            srcs = [np.clip(s, 0.5, 1.5).astype(nd) for s in srcs]
        dsts = [np.zeros(count, dtype=nd) for _ in range(n)]
        sh = [job4.contexts[r].mem_map(srcs[r]) for r in range(n)]
        dh = [job4.contexts[r].mem_map(dsts[r]) for r in range(n)]
        job4.run_coll(teams, _sw_args(srcs, dsts, sh, dh, op, dt, count))
        if op == ReductionOp.MAX:
            expect = np.max(srcs, axis=0)
            for r in range(n):
                np.testing.assert_array_equal(dsts[r], expect)
        else:
            expect = np.prod(srcs, axis=0)
            for r in range(n):
                np.testing.assert_allclose(dsts[r], expect, rtol=1e-4)

    @pytest.mark.parametrize("job4", ["allreduce:@sliding_window"],
                             indirect=True)
    def test_bootstrap_no_memh(self, job4, monkeypatch):
        """Plain TUNE selection with standard two-sided args: the task
        self-bootstraps its memh (mem_map + inline exchange) and the
        result matches; bootstrap segments unmapped at completion."""
        monkeypatch.setenv("UCC_TL_SHM_ALLREDUCE_SW_WINDOW", "128")
        n = 4
        count = 777
        teams = job4.create_team()
        srcs = [_mkdata(r, count, np.float32) for r in range(n)]
        dsts = [np.zeros(count, dtype=np.float32) for _ in range(n)]
        before = len(REGISTRY.segments)
        job4.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(srcs[r], count, DataType.FLOAT32),
            dst=BufferInfo(dsts[r], count, DataType.FLOAT32),
            op=ReductionOp.SUM))
        expect = np.sum(srcs, axis=0)
        for r in range(n):
            np.testing.assert_allclose(dsts[r], expect, rtol=1e-4,
                                       atol=1e-5)
        assert len(REGISTRY.segments) == before

    def test_hier_leaders_pick_sliding_window(self, monkeypatch):
        """The DCN-leader integration the bootstrap mode exists for:
        CL/HIER's RAB leader allreduce stage selects sliding_window via
        plain TL TUNE (no memh plumbing anywhere in hier)."""
        monkeypatch.setenv("UCC_TOPO_FAKE_PPN", "2")
        monkeypatch.setenv("UCC_TL_SHM_TUNE", "allreduce:@sliding_window")
        job = UccJob(4)
        try:
            teams = job.create_team()
            n, count = 4, 512
            srcs = [_mkdata(r, count, np.float64) for r in range(n)]
            dsts = [np.zeros(count, dtype=np.float64) for _ in range(n)]
            job.run_coll(teams, lambda r: CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(srcs[r], count, DataType.FLOAT64),
                dst=BufferInfo(dsts[r], count, DataType.FLOAT64),
                op=ReductionOp.SUM))
            expect = np.sum(srcs, axis=0)
            for r in range(n):
                np.testing.assert_allclose(dsts[r], expect, rtol=1e-12)
        finally:
            job.cleanup()

    @pytest.mark.parametrize("job4", ["allreduce:@sliding_window"],
                             indirect=True)
    def test_user_global_work_buffer_as_scratch(self, job4):
        """A user-provided global_work_buffer of at least the
        context-attr size backs the in-flight get buffers (ucc.h:1878)."""
        n = 4
        count = 600
        teams = job4.create_team()
        srcs = [_mkdata(r, count, np.float32) for r in range(n)]
        dsts = [np.zeros(count, dtype=np.float32) for _ in range(n)]
        sh = [job4.contexts[r].mem_map(srcs[r]) for r in range(n)]
        dh = [job4.contexts[r].mem_map(dsts[r]) for r in range(n)]
        wbs = job4.contexts[0].get_attr().global_work_buffer_size
        gwbs = [np.zeros(wbs, dtype=np.uint8) for _ in range(n)]
        make = _sw_args(srcs, dsts, sh, dh, ReductionOp.SUM,
                        DataType.FLOAT32, count)

        def with_gwb(r):
            a = make(r)
            a.global_work_buffer = gwbs[r]
            return a
        job4.run_coll(teams, with_gwb)
        expect = np.sum(srcs, axis=0)
        for r in range(n):
            np.testing.assert_allclose(dsts[r], expect, rtol=1e-4,
                                       atol=1e-5)
        # the scratch was actually written through the user buffer
        assert any(g.any() for g in gwbs)

    @pytest.mark.parametrize("job4", ["allreduce:@sliding_window"],
                             indirect=True)
    def test_persistent_repost(self, job4):
        n = 4
        count = 512
        teams = job4.create_team()
        srcs = [_mkdata(r, count, np.float32) for r in range(n)]
        dsts = [np.zeros(count, dtype=np.float32) for _ in range(n)]
        sh = [job4.contexts[r].mem_map(srcs[r]) for r in range(n)]
        dh = [job4.contexts[r].mem_map(dsts[r]) for r in range(n)]
        make = _sw_args(srcs, dsts, sh, dh, ReductionOp.SUM,
                        DataType.FLOAT32, count)

        def persistent(r):
            a = make(r)
            a.flags |= CollArgsFlags.PERSISTENT
            return a
        reqs = job4.run_coll(teams, persistent)
        expect = np.sum(srcs, axis=0)
        for r in range(n):
            np.testing.assert_allclose(dsts[r], expect, rtol=1e-4,
                                       atol=1e-5)
        # mutate sources and re-post the same requests
        for r in range(n):
            srcs[r] += r + 1
            dsts[r][:] = 0
        for rq in reqs:
            rq.post()
        job4.progress_until(lambda: all(
            rq.test() != Status.IN_PROGRESS for rq in reqs))
        for rq in reqs:
            assert rq.test() == Status.OK
        expect = np.sum(srcs, axis=0)
        for r in range(n):
            np.testing.assert_allclose(dsts[r], expect, rtol=1e-4,
                                       atol=1e-5)


# ---------------------------------------------------------------------------
# failure semantics + device-memory gating
# ---------------------------------------------------------------------------

class TestOneSidedFailure:
    @pytest.mark.parametrize("job4", ["alltoall:@onesided"], indirect=True)
    def test_unmapped_segment_fails_not_hangs(self, job4):
        """A put against an unmapped segment must fail the task (the
        initiator raises at apply; the target's notify counter is bumped
        AND poisoned so its wait completes with an error), never hang or
        complete with silent corruption."""
        n = 4
        count = 4 * n
        teams = job4.create_team()
        srcs = [_mkdata(r, count, np.float32) for r in range(n)]
        dsts = [np.zeros(count, dtype=np.float32) for _ in range(n)]
        handles = [job4.contexts[r].mem_map(dsts[r]) for r in range(n)]
        # rank 2 unmaps before the collective
        job4.contexts[2].mem_unmap(handles[2])
        reqs = [t.collective_init(CollArgs(
            coll_type=CollType.ALLTOALL,
            src=BufferInfo(srcs[r], count, DataType.FLOAT32),
            dst=BufferInfo(dsts[r], count, DataType.FLOAT32),
            dst_memh=list(handles),
            flags=CollArgsFlags.MEM_MAP_DST_MEMH))
            for r, t in enumerate(teams)]
        for rq in reqs:
            rq.post()
        import time
        deadline = time.monotonic() + 20
        sts = [Status.IN_PROGRESS] * n
        while time.monotonic() < deadline:
            for r in range(n):
                job4.contexts[r].progress()
            sts = [rq.test() for rq in reqs]
            if all(s != Status.IN_PROGRESS for s in sts):
                break
        assert any(s.is_error for s in sts if s != Status.IN_PROGRESS) or \
            any(s == Status.IN_PROGRESS for s in sts) is False
        # at least the ranks whose put hit the dead segment must error
        assert any(s.is_error for s in sts)

    def test_rejected_put_poisons_notify_counter(self):
        """Protocol invariant: a rejected put with a notify key bumps the
        counter (so the target's count completes) and records the error
        (so the target fails instead of consuming garbage)."""
        key = ("__os_ctr__", "test-uid", "tk", 1)
        err = REGISTRY.apply_put("no-such-ctx", 99, 0,
                                 np.zeros(4, np.uint8), notify=key)
        assert err is not None
        assert REGISTRY.counter_read(key) == 1
        assert REGISTRY.counter_errs(key) == [err]
        REGISTRY.counter_del(key)
        assert REGISTRY.counter_read(key) == 0
        assert REGISTRY.counter_errs(key) == []

    def test_socket_flush_fence_reports_rejections(self, job4):
        """os_flush over a real socket connection: the ack fences all
        prior puts on that path and reports rejections since the last
        flush (ucp_ep_flush error semantics), then resets."""
        # force the socket TL path between two in-process contexts
        ctx0 = job4.contexts[0].tl_contexts["socket"].obj
        ctx1_core = job4.contexts[1]
        buf = np.zeros(16, np.uint8)
        h = ctx1_core.mem_map(buf)
        desc = ctx1_core.mem_import(h)
        peer = 1
        # good put -> flush ack must be clean
        ctx0.os_put(peer, desc, 0, np.arange(4, dtype=np.uint8))
        fr = ctx0.os_flush(peer)
        job4.progress_until(lambda: fr.test(), timeout=10)
        assert fr.error is None
        assert buf[:4].tolist() == [0, 1, 2, 3]
        # out-of-bounds put -> flush reports it, next flush is clean again
        ctx0.os_put(peer, desc, 1000, np.zeros(64, np.uint8))
        fr2 = ctx0.os_flush(peer)
        job4.progress_until(lambda: fr2.test(), timeout=10)
        assert fr2.error is not None and "rejected" in fr2.error
        fr3 = ctx0.os_flush(peer)
        job4.progress_until(lambda: fr3.test(), timeout=10)
        assert fr3.error is None

    def test_tpu_memory_onesided_rejected(self, job4):
        jax = pytest.importorskip("jax")
        import jax.numpy as jnp
        teams = job4.create_team()
        x = jnp.zeros(8, dtype=jnp.float32)
        with pytest.raises(ucc_tpu.UccError) as ei:
            teams[0].collective_init(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(x, 8, DataType.FLOAT32),
                dst=BufferInfo(x, 8, DataType.FLOAT32),
                op=ReductionOp.SUM,
                global_work_buffer=np.zeros(8)))
        assert ei.value.status == Status.ERR_NOT_SUPPORTED
