"""Rank-failure recovery (UCC_FT=shrink; ISSUE 4): liveness detection
and attribution, fail-fast posts to dead ranks, fault-tolerant
agreement, ULFM-style Team.shrink, epoch fencing (PR-3 lease-buffer
interplay), and the half-created-team destroy regression."""
import os
import time

import numpy as np
import pytest

import ucc_tpu
from ucc_tpu import (BufferInfo, CollArgs, CollType, DataType,
                     RankFailedError, ReductionOp, Status, UccError)
from ucc_tpu.fault import health, inject
from ucc_tpu.obs import metrics
from ucc_tpu.tl.host.transport import (Mailbox, RecvReq, SendReq,
                                       _PendingSend)

from harness import UccJob


@pytest.fixture(autouse=True)
def _clean_ft():
    inject.reset()
    health.reset()
    yield
    inject.reset()
    health.reset()


#: heartbeat-timeout scale for loaded runs: with the tight 0.3s default
#: a full-suite machine (xdist neighbors, C++ rebuild, swap) can stall a
#: survivor's progress loop past the timeout and false-positive a
#: HEALTHY rank's death ~1-2 times/run (PR 19). Detection latency is
#: irrelevant to these assertions — _drive allows 5-15s — so scale the
#: timeout well clear of scheduler noise while keeping the beat interval
#: tight. Override with UCC_TEST_LOAD_FACTOR=1 for latency-sensitive
#: local profiling.
try:
    _LOAD = float(os.environ.get("UCC_TEST_LOAD_FACTOR", "") or 5.0)
except ValueError:
    _LOAD = 5.0


def _ft_on(interval=0.02, timeout=0.3):
    health.configure("shrink", interval=interval, timeout=timeout * _LOAD)


def _ar_args(rank, count=16):
    dst = np.zeros(count, np.float64)
    args = CollArgs(coll_type=CollType.ALLREDUCE,
                    src=BufferInfo(np.full(count, rank + 1.0), count,
                                   DataType.FLOAT64),
                    dst=BufferInfo(dst, count, DataType.FLOAT64),
                    op=ReductionOp.SUM)
    return args, dst


def _drive(ctxs, cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for c in ctxs:
            c.progress()
        if cond():
            return True
    return False


# ---------------------------------------------------------------------------
# detection + attribution
# ---------------------------------------------------------------------------

class TestDetection:
    def test_default_mode_is_cold(self):
        assert health.MODE == "none"
        assert not health.ENABLED
        job = UccJob(2)
        try:
            assert job.contexts[0].health is None
        finally:
            job.cleanup()

    def test_heartbeat_detects_killed_rank(self):
        """A rank that stops beating (kill injection) is detected by
        every survivor's registry within the heartbeat timeout, and
        in-flight collectives depending on it are cancelled with
        ERR_RANK_FAILED naming it."""
        _ft_on()
        job = UccJob(3)
        try:
            teams = job.create_team()
            # post BEFORE the kill so detection (not fail-fast) must
            # bound the in-flight collective
            reqs = [t.collective_init(_ar_args(i)[0]) for i, t in
                    enumerate(teams[:2])]
            killed_ctx = job.contexts[2].rank
            inject.configure(f"kill={killed_ctx}", seed=0)
            for rq in reqs:
                rq.post()
            assert _drive(job.contexts, lambda: all(
                rq.test() != Status.IN_PROGRESS for rq in reqs), 10)
            for rq in reqs:
                assert rq.test() == Status.ERR_RANK_FAILED, rq.test()
                assert killed_ctx in (rq.failed_ranks or [])
            for r in (0, 1):
                reg = job.contexts[r].health
                assert reg is not None and reg.is_dead(killed_ctx)
                assert reg.dead[killed_ctx]["source"] in (
                    "heartbeat", "send", "inject")
            for rq in reqs:
                rq.finalize()
        finally:
            job.cleanup()

    def test_fail_fast_post_to_dead_rank(self):
        """Satellite: a post targeting a known-dead rank fails fast with
        ERR_RANK_FAILED + attribution instead of black-holing until a
        watchdog timeout — and counts in rank_failures_detected. Runs
        with UCC_FT off: the kill drill alone must benefit."""
        metrics.reset()
        metrics.enable(file="/dev/null")
        job = UccJob(3)
        try:
            teams = job.create_team()
            killed_ctx = job.contexts[2].rank
            inject.configure(f"kill={killed_ctx}", seed=0)
            args, _ = _ar_args(0)
            rq = teams[0].collective_init(args)
            t0 = time.monotonic()
            rq.post()
            assert _drive(job.contexts, lambda:
                          rq.test() != Status.IN_PROGRESS, 5)
            assert time.monotonic() - t0 < 2.0   # fast, not watchdog-slow
            assert rq.test() == Status.ERR_RANK_FAILED
            assert killed_ctx in (rq.failed_ranks or [])
            snap = metrics.snapshot()
            hits = snap.get("counters", {}).get("rank_failures_detected", {})
            assert hits and sum(hits.values()) >= 1
            rq.finalize()
        finally:
            metrics.disable()
            metrics.reset()
            job.cleanup()


# ---------------------------------------------------------------------------
# agreement
# ---------------------------------------------------------------------------

class TestAgreement:
    def test_divergent_views_converge(self):
        """Survivors entering agreement with DIFFERENT local views (one
        detected the death, the others did not) converge on the union
        and an identical epoch — the other ranks learn the dead set
        mid-round and cancel their pending recv from the dead rank."""
        from ucc_tpu.fault.agree import FtAgreement
        _ft_on(timeout=10.0)   # heartbeats effectively off: views stay split
        job = UccJob(4)
        try:
            teams = job.create_team()
            tasks = {}
            for r, local in ((0, {2}), (1, set()), (3, set())):
                t = FtAgreement(teams[r].service_team, local, epoch=0,
                                round_timeout_s=8.0)
                t.progress_queue = job.contexts[r].progress_queue
                tasks[r] = t
                t.post()
            assert _drive(job.contexts, lambda: all(
                t.is_completed() for t in tasks.values()), 15)
            views = {(frozenset(t.result_dead), t.result_epoch)
                     for t in tasks.values()}
            assert views == {(frozenset({2}), 1)}, views
        finally:
            job.cleanup()


# ---------------------------------------------------------------------------
# the acceptance drill: kill -> detect -> agree -> shrink -> resume
# ---------------------------------------------------------------------------

class TestKillShrinkSoak:
    def test_kill_shrink_resume(self):
        """ISSUE-4 acceptance: with UCC_FAULT=kill and UCC_FT=shrink, a
        4-rank matrix survives the kill — every survivor observes
        ERR_RANK_FAILED naming the dead rank, all agree on the same
        (dead set, epoch), Team.shrink completes, and >= 50 subsequent
        collectives finish on the shrunk team with correct results and
        zero ranks IN_PROGRESS."""
        from ucc_tpu.fault.soak import run_kill_shrink_soak
        report = run_kill_shrink_soak(n_ranks=4, kill_rank=2,
                                      pre_iters=3, post_iters=54)
        assert report["violations"] == [], report
        assert report["post_iters"] >= 50
        views = {(tuple(v["dead"]), v["epoch"])
                 for v in report["agreed"].values()}
        assert len(views) == 1
        for v in report["detected"].values():
            assert v["status"] == "ERR_RANK_FAILED"
            assert report["killed"]["ctx_rank"] in v["ranks"]

    def test_old_team_rejects_posts_after_shrink(self):
        _ft_on()
        job = UccJob(3)
        try:
            teams = job.create_team()
            killed_ctx = job.contexts[2].rank
            inject.configure(f"kill={killed_ctx}", seed=0)
            # let the survivors detect the death first
            assert _drive(job.contexts, lambda: all(
                job.contexts[r].health.is_dead(killed_ctx)
                for r in (0, 1)), 5)
            shrinks = {r: teams[r].shrink_post() for r in (0, 1)}
            assert _drive(job.contexts, lambda: all(
                [s.test() != Status.IN_PROGRESS
                 for s in shrinks.values()]), 15)
            for s in shrinks.values():
                assert s.test() == Status.OK
                assert s.new_team.epoch == s.epoch
            with pytest.raises(RankFailedError):
                teams[0].collective_init(_ar_args(0)[0])
            # the successor works
            reqs = []
            for g, s in enumerate(shrinks.values()):
                args, dst = _ar_args(g)
                rq = s.new_team.collective_init(args)
                rq.post()
                reqs.append((rq, dst))
            assert _drive(job.contexts, lambda: all(
                rq.test() != Status.IN_PROGRESS for rq, _ in reqs), 10)
            for rq, dst in reqs:
                assert rq.test() == Status.OK
                assert np.allclose(dst, 1.0 + 2.0)
                rq.finalize()
            for s in shrinks.values():
                s.new_team.destroy()
        finally:
            job.cleanup()


# ---------------------------------------------------------------------------
# epoch fencing (PR-3 lease-buffer interplay)
# ---------------------------------------------------------------------------

TEAM_KEY = (("unit",), "cl")


class TestEpochFence:
    def test_fence_purges_parked_stale_state(self):
        """Fencing an epoch completes parked senders, errors stale
        posted recvs, and discards late stale arrivals — so a parked
        pre-shrink rndv send can no longer alias a buffer the pool
        reissues (its mailbox entry dies at the fence)."""
        mb = Mailbox()
        old_key = (TEAM_KEY, 0, 7, 0, 1)
        # a parked zero-copy rndv send (the PR-3 hazard shape) ...
        lease_buf = np.arange(64, dtype=np.uint8)
        ps = _PendingSend(lease_buf, SendReq(), copied=False)
        mb.push(old_key, ps)
        # ... and a stale posted recv
        stale_dst = np.zeros(64, np.uint8)
        stale_recv = RecvReq(stale_dst)
        mb.post_recv((TEAM_KEY, 0, 8, 0, 1), stale_recv)
        purged = mb.fence(TEAM_KEY, 1)
        assert purged == 2
        assert not mb.unexpected and not mb.posted
        assert ps.req.done           # sender stops waiting
        assert stale_recv.done and "fenced" in stale_recv.error

    def test_stale_send_cannot_match_post_shrink_recv(self):
        """Regression: a STALE pre-shrink send arriving after the fence
        is discarded at the matching boundary — it can never land in a
        recv posted under the new epoch (which would be a pool-reissued
        lease buffer in the PR-3 steady state)."""
        mb = Mailbox()
        mb.fence(TEAM_KEY, 1)
        new_dst = np.zeros(8, np.uint8)
        new_recv = RecvReq(new_dst)
        mb.post_recv((TEAM_KEY, 1, 1, 0, 0), new_recv)
        # same (coll_tag, slot, src) but old epoch: must NOT match
        sreq, kind = mb.send((TEAM_KEY, 0, 1, 0, 0),
                             np.full(8, 0xAB, np.uint8), 8192)
        assert kind == "fenced" and sreq.done
        assert not new_recv.done
        assert not new_dst.any()
        # the new-epoch send still matches normally
        sreq2, kind2 = mb.send((TEAM_KEY, 1, 1, 0, 0),
                               np.full(8, 0xCD, np.uint8), 8192)
        assert kind2 == "direct" and new_recv.done
        assert (new_dst == 0xCD).all()
        # posting a recv under the fenced epoch fails locally, loudly
        late = RecvReq(np.zeros(4, np.uint8))
        mb.post_recv((TEAM_KEY, 0, 2, 0, 0), late)
        assert late.done and "fenced" in late.error

    def test_shrink_fences_old_tl_teams(self):
        """Integration: after Team.shrink, a late message keyed to the
        OLD team's tag space is discarded by the survivor's transport
        (n_fenced), not delivered."""
        _ft_on()
        job = UccJob(3)
        try:
            teams = job.create_team()
            old_tl_keys = {r: teams[r]._tl_tag_spaces() for r in (0, 1)}
            assert all(old_tl_keys.values())
            killed_ctx = job.contexts[2].rank
            inject.configure(f"kill={killed_ctx}", seed=0)
            assert _drive(job.contexts, lambda: all(
                job.contexts[r].health.is_dead(killed_ctx)
                for r in (0, 1)), 5)
            shrinks = {r: teams[r].shrink_post() for r in (0, 1)}
            assert _drive(job.contexts, lambda: all(
                [s.test() != Status.IN_PROGRESS
                 for s in shrinks.values()]), 15)
            assert all(s.test() == Status.OK for s in shrinks.values())
            # replay a "delayed" pre-shrink send into survivor 1's
            # mailbox under the old cl-scope key at the old epoch
            tr1 = job.contexts[1].tl_contexts["shm"].obj.transport
            tk = old_tl_keys[1][0][0]
            before = tr1.n_fenced
            tr0 = job.contexts[0].tl_contexts["shm"].obj
            req = tr0.send_to(job.contexts[1].rank,
                              (tk, teams[1].epoch, 999, 0,
                               job.contexts[0].rank),
                              np.ones(8, np.float64))
            assert req.done                      # discarded, not parked
            assert tr1.mailbox.fences            # fence installed
            assert not any(k[0] == tk for k in tr1.mailbox.unexpected)
            for s in shrinks.values():
                s.new_team.destroy()
        finally:
            job.cleanup()


# ---------------------------------------------------------------------------
# half-created team destroy (satellite regression)
# ---------------------------------------------------------------------------

class TestHalfCreatedTeamDestroy:
    def test_destroy_after_mid_cl_create_failure(self, monkeypatch):
        """Team.fail()/destroy() on a team stuck mid _cl_create_step
        must tear down the already-created service team and the
        partially-created CL team without raising — even when a
        component's own destroy misbehaves."""
        from ucc_tpu.cl.basic import ClBasicTeam
        from ucc_tpu.core.team import TeamState

        monkeypatch.setattr(ClBasicTeam, "create_test",
                            lambda self: Status.IN_PROGRESS)
        destroyed = []
        orig_destroy = ClBasicTeam.destroy

        def raising_destroy(self):
            destroyed.append(self)
            orig_destroy(self)
            raise RuntimeError("component destroy bug")

        monkeypatch.setattr(ClBasicTeam, "destroy", raising_destroy)
        job = UccJob(2)
        try:
            from ucc_tpu import TeamParams, ThreadOobWorld
            world = ThreadOobWorld(2)
            teams = [job.contexts[r].create_team_post(
                TeamParams(oob=world.endpoint(r))) for r in range(2)]
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                sts = [t.create_test() for t in teams]
                for c in job.contexts:
                    c.progress()
                if all(t.state == TeamState.CL_CREATE for t in teams):
                    break
            assert all(t.state == TeamState.CL_CREATE for t in teams)
            for t in teams:
                t.fail(Status.ERR_TIMED_OUT, "test escalation")
                assert t.create_test() == Status.ERR_TIMED_OUT
            for t in teams:
                t.destroy()          # must not raise
                t.destroy()          # idempotent
            assert destroyed          # the half-created CL team was torn down
        finally:
            job.cleanup()
