"""Component-load failure must degrade in bounded time, not crawl.

Round 4 shipped two TL modules that failed to import; discovery skipped
them (correct) but the stack then burned the driver's entire multichip
timeout behind repeated CL/HIER fallback work. The reference treats a
team-create failure as a cheap bounded fallback (ucc_team.c:295-317):
destroy the half-made team, move to the next CL, done. These tests pin
that contract: with BOTH host TLs absent, an 8-rank 2-node job must
bootstrap, create a team, run collectives, and tear down within seconds
via the surviving TLs (xla/self/ring_dma).
"""
import logging
import time

import numpy as np
import pytest

import ucc_tpu
from ucc_tpu import (BufferInfo, CollArgs, CollType, DataType, ReductionOp,
                     Status)
from ucc_tpu.core import components

from harness import UccJob


@pytest.fixture()
def no_host_tls():
    """Simulate the round-4 failure: shm + socket never registered
    (import-time NameError makes discovery skip them)."""
    components.discover_components()
    saved = {k: components.TL_REGISTRY.pop(k)
             for k in ("shm", "socket") if k in components.TL_REGISTRY}
    assert saved, "host TLs were not registered to begin with"
    try:
        yield
    finally:
        components.TL_REGISTRY.update(saved)


def _allreduce_device(job, teams, n, count=1024):
    """Allreduce over jax device buffers — the TL/XLA path that must
    SURVIVE when the host TLs are gone."""
    import jax
    import jax.numpy as jnp
    from ucc_tpu import MemoryType

    argses = []
    for r in range(n):
        dev = job.contexts[r].tl_contexts["xla"].obj.device
        src = jax.device_put(
            jnp.asarray(np.arange(count, dtype=np.float32) * (r + 1)), dev)
        argses.append(CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(src, count, DataType.FLOAT32,
                           mem_type=MemoryType.TPU),
            dst=BufferInfo(None, count, DataType.FLOAT32,
                           mem_type=MemoryType.TPU),
            op=ReductionOp.SUM))
    job.run_coll(teams, lambda r: argses[r])
    want = np.arange(count, dtype=np.float32) * sum(range(1, n + 1))
    for r in range(n):
        np.testing.assert_allclose(np.asarray(argses[r].dst.buffer), want,
                                   rtol=1e-5)


def _host_allreduce_fails_fast(job, teams, n, budget_s=5.0):
    """With no host TL, a host-memory collective must fail immediately
    with NOT_SUPPORTED — not hang hunting for a provider."""
    t0 = time.monotonic()
    src = np.ones(64, dtype=np.float32)
    dst = np.zeros(64, dtype=np.float32)
    with pytest.raises(ucc_tpu.UccError) as ei:
        teams[0].collective_init(CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(src, 64, DataType.FLOAT32),
            dst=BufferInfo(dst, 64, DataType.FLOAT32),
            op=ReductionOp.SUM))
    assert ei.value.status == Status.ERR_NOT_SUPPORTED
    assert time.monotonic() - t0 < budget_s


class TestDegradedStack:
    BUDGET_S = 60.0   # generous CI bound; healthy path runs in seconds

    def test_multinode_job_completes_bounded(self, no_host_tls, monkeypatch):
        monkeypatch.setenv("UCC_TOPO_FAKE_PPN", "4")
        t0 = time.monotonic()
        job = UccJob(8)
        try:
            teams = job.create_team()
            for ctx in job.contexts:
                assert "shm" not in ctx.tl_contexts
                assert "socket" not in ctx.tl_contexts
            _host_allreduce_fails_fast(job, teams, 8)
            _allreduce_device(job, teams, 8)
        finally:
            job.cleanup()
        elapsed = time.monotonic() - t0
        assert elapsed < self.BUDGET_S, (
            f"degraded stack took {elapsed:.1f}s — component failure must "
            f"be a bounded fallback, not a crawl")

    def test_fallback_warned_once_per_team_not_per_coll(
            self, no_host_tls, monkeypatch):
        """The CL fallback decision is made at team create; posting many
        collectives afterwards must not re-attempt the failed CL.

        The ucc_tpu root logger does not propagate (utils/log.py), so
        caplog would capture NOTHING and pass vacuously — attach a list
        handler directly and prove it sees the team-create warnings
        (positive control) before asserting the collectives add none."""

        class _ListHandler(logging.Handler):
            def __init__(self):
                super().__init__(level=logging.WARNING)
                self.lines = []

            def emit(self, record):
                self.lines.append(record.getMessage())

        monkeypatch.setenv("UCC_TOPO_FAKE_PPN", "2")
        h = _ListHandler()
        job = None
        logging.getLogger("ucc_tpu").addHandler(h)
        try:
            job = UccJob(4)
            teams = job.create_team()
            # positive control: create-time fallback DID log through
            # this handler (hier fails on the leaders without host TLs)
            assert any("team create" in ln for ln in h.lines), \
                "handler saw no create-time warnings — capture is broken"
            n_create_warnings = len(h.lines)
            for _ in range(5):
                _allreduce_device(job, teams, 4, count=64)
            creates = [ln for ln in h.lines[n_create_warnings:]
                       if "team create" in ln]
            assert not creates, (
                "collective posts re-attempted CL team creation: "
                + "; ".join(creates))
        finally:
            logging.getLogger("ucc_tpu").removeHandler(h)
            if job is not None:
                job.cleanup()
