"""Multi-process TL/XLA: one team spanning TWO OS processes on a
multi-controller jax.distributed CPU mesh (2 procs x 2 virtual devices),
the pod shape exercised through the full stack (VERDICT r2 weak #5;
reference bar: tl_nccl multi-node bootstrap + test/mpi sweeps).

Coverage:
- allreduce / gather / scatter / allgatherv / bcast on device (jax.Array)
  buffers — the rooted colls pin the n_local gate: a spanning team must
  take the replicated shard_map program, NOT the explicit-placement
  fast path (which would silently truncate at root / KeyError);
- ALLTOALLV on the spanning team (uneven per-pair counts): the counts
  matrix is exchanged over the service team before launch so every
  controller compiles the identical program (tl/xla.py post_fn);
- hier-over-HBM mode (UCC_TOPO_FAKE_PPN=2): each process becomes a
  "node" — node stages run on-device through the NODE unit's XLA team,
  leaders run the DCN stage over the socket TL across processes
  (cl/hier/tpu.py; reference cl_hier RAB over tl_nccl+tl_ucp).

Each process runs two UCC contexts (rank == chip), bootstrapped by
TcpStoreOob; the XLA rendezvous deposits the two LOCAL shards and
launches the compiled program with the GLOBAL shape (gloo CPU
collectives).

Run as a worker:  python test_xla_multiprocess.py <proc_id> <base_port> [hier]
"""
import os
import subprocess
import sys

import pytest

HERE = os.path.abspath(__file__)


def _worker_main(proc_id: int, base_port: int, mode: str = "flat",
                 oob_ports=None) -> None:
    # three rendezvous ports: jax coordinator + the two TcpStoreOob
    # stores. Passed explicitly (probed SIMULTANEOUSLY by the parent):
    # deriving them as base+1/base+2 collided with the kernel's roughly
    # sequential ephemeral allocator — the next listeners any worker
    # opened landed exactly on base+1/base+2.
    p_ctx, p_team = (base_port + 1, base_port + 2) if oob_ports is None \
        else oob_ports
    sys.path.insert(0, os.path.dirname(os.path.dirname(HERE)))  # repo root
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
        " --xla_force_host_platform_device_count=2"
    if mode == "hier":
        # 4 ranks -> 2 fake nodes of 2; node boundary == process boundary
        os.environ["UCC_TOPO_FAKE_PPN"] = "2"
    if mode == "ring_dma":
        # the kernels' LOGICAL device ids and the rendezvous path had
        # only ever run single-controller (round-3 verdict next #6)
        os.environ["UCC_TL_RING_DMA_TUNE"] = "allreduce:@ring_dma:inf"
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 - older jax spells it differently
        pass
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{base_port}",
        num_processes=2, process_id=proc_id)
    assert len(jax.devices()) == 4 and len(jax.local_devices()) == 2

    import threading
    import time

    import jax.numpy as jnp
    import numpy as np

    import ucc_tpu
    from ucc_tpu import (BufferInfo, BufferInfoV, CollArgs, CollType,
                         ContextParams, DataType, MemoryType, ReductionOp,
                         Status, TcpStoreOob, TeamParams)

    n = 4
    my_ranks = [2 * proc_id, 2 * proc_id + 1]
    libs = {r: ucc_tpu.init() for r in my_ranks}
    ctxs = {}

    def mk(r):
        ctxs[r] = ucc_tpu.Context(libs[r], ContextParams(
            oob=TcpStoreOob(r, n, port=p_ctx)))

    ths = [threading.Thread(target=mk, args=(r,)) for r in my_ranks]
    for t in ths:
        t.start()
    for t in ths:
        t.join(timeout=120)
    for r in my_ranks:
        assert r in ctxs, f"context {r} failed"

    teams = {}

    def mkteam(r):
        teams[r] = ctxs[r].create_team_post(TeamParams(
            oob=TcpStoreOob(r, n, port=p_team)))

    ths = [threading.Thread(target=mkteam, args=(r,)) for r in my_ranks]
    for t in ths:
        t.start()
    for t in ths:
        t.join(timeout=120)
    deadline = time.monotonic() + 120
    while True:
        sts = [teams[r].create_test() for r in my_ranks]
        for r in my_ranks:
            ctxs[r].progress()
        if all(s == Status.OK for s in sts):
            break
        bad = [s for s in sts if s.is_error]
        assert not bad, f"team create failed: {bad}"
        assert time.monotonic() < deadline, "team create timed out"

    devs = {r: ctxs[r].tl_contexts["xla"].obj.device for r in my_ranks}

    def dev_buf(r, arr):
        a = jax.device_put(jnp.asarray(arr), devs[r])
        return BufferInfo(a, int(arr.size), DataType.FLOAT32,
                          mem_type=MemoryType.TPU)

    def run(make_args, check, timeout=120.0, label=""):
        argses = {r: make_args(r) for r in my_ranks}
        reqs = {r: teams[r].collective_init(argses[r]) for r in my_ranks}
        for r in my_ranks:
            reqs[r].post()
        end = time.monotonic() + timeout
        while any(reqs[r].test() == Status.IN_PROGRESS for r in my_ranks):
            for r in my_ranks:
                ctxs[r].progress()
            assert time.monotonic() < end, f"{label} timed out"
        for r in my_ranks:
            assert reqs[r].test() == Status.OK, \
                (label, r, reqs[r].test())
            check(r, argses[r])
        print(f"COLL-OK {label} {proc_id}", flush=True)

    count = 32

    if mode == "hier":
        # hier-over-HBM allreduce: node XLA stages + DCN leader stage.
        # Assert the topology actually split into 2 fake nodes and that
        # selection picked the hier TPU path, then verify the data.
        t0 = teams[my_ranks[0]]
        cands = t0.score_map.lookup(CollType.ALLREDUCE, MemoryType.TPU,
                                    1 << 12)
        assert cands and cands[0].alg_name == "rab_tpu", \
            [c.alg_name for c in cands]
        expect = n * (n + 1) / 2
        run(lambda r: CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=dev_buf(r, np.full(count, r + 1.0, np.float32)),
                dst=BufferInfo(None, count, DataType.FLOAT32,
                               mem_type=MemoryType.TPU),
                op=ReductionOp.SUM),
            lambda r, a: np.testing.assert_allclose(
                np.asarray(a.dst.buffer), expect),
            timeout=180, label="hier-allreduce")
        print(f"MULTIPROC-HIER-OK {proc_id}", flush=True)
        return

    if mode == "ring_dma":
        # 1) device-initiated ring allreduce through the full stack: the
        #    Pallas kernel (interpret on this CPU mesh) runs over the
        #    SPANNING 4-device mesh — interpret's remote-DMA discharge
        #    lowers to lax.all_gather, which rides the gloo backend
        #    across the two controllers
        t0 = teams[my_ranks[0]]
        cands = t0.score_map.lookup(CollType.ALLREDUCE, MemoryType.TPU,
                                    1 << 10)
        assert cands and cands[0].alg_name == "ring_dma", \
            [c.alg_name for c in cands]
        expect = n * (n + 1) / 2
        run(lambda r: CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=dev_buf(r, np.full(count, r + 1.0, np.float32)),
                dst=BufferInfo(None, count, DataType.FLOAT32,
                               mem_type=MemoryType.TPU),
                op=ReductionOp.SUM),
            lambda r, a: np.testing.assert_allclose(
                np.asarray(a.dst.buffer), expect),
            timeout=240, label="ring_dma-allreduce")

        # 2) fused ring flash-attention forward over the spanning mesh
        #    (jitted global-array entry; the K/V ring crosses the process
        #    boundary)
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ucc_tpu.fused_attention import make_ring_flash_attention
        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("sp",))
        prog = make_ring_flash_attention(mesh, axis="sp")
        h, s_loc, d = 2, 8, 4
        seq = n * s_loc
        rng = np.random.RandomState(7)
        qn, kn, vn = (rng.randn(h, seq, d).astype(np.float32)
                      for _ in range(3))
        sh = NamedSharding(mesh, P(None, "sp", None))
        all_devs = list(mesh.devices.flat)

        def garr(full):
            shards = [jax.device_put(
                jnp.asarray(full[:, i * s_loc:(i + 1) * s_loc, :]), dv)
                for i, dv in enumerate(all_devs) if dv.process_index ==
                jax.process_index()]
            return jax.make_array_from_single_device_arrays(
                (h, seq, d), sh, shards)

        out = jax.block_until_ready(prog(garr(qn), garr(kn), garr(vn)))
        # dense reference, checked on this process's addressable shards
        s = np.einsum("hqd,hkd->hqk", qn / np.sqrt(d), kn)
        p = np.exp(s - s.max(-1, keepdims=True))
        ref = np.einsum("hqk,hkd->hqd", p / p.sum(-1, keepdims=True), vn)
        for shard in out.addressable_shards:
            i = list(mesh.devices.flat).index(shard.device)
            np.testing.assert_allclose(
                np.asarray(shard.data),
                ref[:, i * s_loc:(i + 1) * s_loc, :], rtol=2e-5,
                atol=2e-6)
        print(f"COLL-OK fused-attention {proc_id}", flush=True)
        print(f"MULTIPROC-RINGDMA-OK {proc_id}", flush=True)
        return

    # ---- flat XLA team over 4 devices / 2 processes ----------------------
    # 1) allreduce
    expect = n * (n + 1) / 2
    run(lambda r: CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=dev_buf(r, np.full(count, r + 1.0, np.float32)),
            dst=BufferInfo(None, count, DataType.FLOAT32,
                           mem_type=MemoryType.TPU),
            op=ReductionOp.SUM),
        lambda r, a: np.testing.assert_allclose(
            np.asarray(a.dst.buffer), expect),
        label="allreduce")

    # 2) gather to root=1 — root lives in proc 0; proc 1's shards must
    #    arrive via the replicated program (the old fast path dropped them)
    root = 1
    full = np.concatenate([np.full(count, g + 1.0, np.float32)
                           for g in range(n)])

    def _check_gather(r, a):
        if r == root:
            np.testing.assert_allclose(np.asarray(a.dst.buffer), full)

    run(lambda r: CollArgs(
            coll_type=CollType.GATHER, root=root,
            src=dev_buf(r, np.full(count, r + 1.0, np.float32)),
            dst=BufferInfo(None, n * count, DataType.FLOAT32,
                           mem_type=MemoryType.TPU)),
        _check_gather, label="gather")

    # 3) scatter from root=2 (proc 1) — non-root proc must receive its block
    root = 2
    sdata = np.arange(n * count, dtype=np.float32)
    run(lambda r: CollArgs(
            coll_type=CollType.SCATTER, root=root,
            src=dev_buf(r, sdata if r == root
                        else np.zeros(n * count, np.float32)),
            dst=BufferInfo(None, count, DataType.FLOAT32,
                           mem_type=MemoryType.TPU)),
        lambda r, a: np.testing.assert_allclose(
            np.asarray(a.dst.buffer), sdata[r * count:(r + 1) * count]),
        label="scatter")

    # 4) allgatherv with per-rank counts
    vcounts = [8, 16, 24, 32]
    vfull = np.concatenate([np.full(vcounts[g], float(g), np.float32)
                            for g in range(n)])
    run(lambda r: CollArgs(
            coll_type=CollType.ALLGATHERV,
            src=dev_buf(r, np.full(vcounts[r], float(r), np.float32)),
            dst=BufferInfoV(None, vcounts, None, DataType.FLOAT32,
                            mem_type=MemoryType.TPU)),
        lambda r, a: np.testing.assert_allclose(
            np.asarray(a.dst.buffer), vfull),
        label="allgatherv")

    # 5) bcast from root=3
    root = 3
    bdata = np.arange(count, dtype=np.float32) * 3
    run(lambda r: CollArgs(
            coll_type=CollType.BCAST, root=root,
            src=dev_buf(r, bdata if r == root
                        else np.zeros(count, np.float32))),
        lambda r, a: np.testing.assert_allclose(
            np.asarray(a.src.buffer), bdata),
        label="bcast")

    # 6) ALLTOALLV on the spanning team: the counts matrix is exchanged
    #    over the service team before the launch, so every controller
    #    compiles the identical program (round-3 lift of the old
    #    n_local gate). Uneven per-pair counts exercise the index maps.
    m = [[(q + p) % 3 + 1 for p in range(n)] for q in range(n)]
    rcounts = [[m[q][p] for q in range(n)] for p in range(n)]
    vsrcs = {q: np.concatenate([np.full(m[q][p], 100.0 * q + p,
                                        np.float32) for p in range(n)])
             for q in range(n)}

    def _mk_a2av(r):
        a = jax.device_put(jnp.asarray(vsrcs[r]), devs[r])
        return CollArgs(
            coll_type=CollType.ALLTOALLV,
            src=BufferInfoV(a, m[r], None, DataType.FLOAT32,
                            mem_type=MemoryType.TPU),
            dst=BufferInfoV(None, rcounts[r], None, DataType.FLOAT32,
                            mem_type=MemoryType.TPU))

    def _check_a2av(r, a):
        sdispl = {q: np.cumsum([0] + m[q][:-1]) for q in range(n)}
        expect = np.concatenate([
            vsrcs[q][sdispl[q][r]:sdispl[q][r] + m[q][r]]
            for q in range(n)])
        np.testing.assert_allclose(np.asarray(a.dst.buffer), expect)

    run(_mk_a2av, _check_a2av, timeout=180, label="alltoallv-spanning")

    print(f"MULTIPROC-OK {proc_id}", flush=True)


def _gloo_available() -> bool:
    """Gate: multi-controller CPU collectives need the gloo backend."""
    probe = ("import jax; jax.config.update('jax_platforms','cpu'); "
             "jax.config.update('jax_cpu_collectives_implementation',"
             "'gloo'); print('y')")
    try:
        r = subprocess.run([sys.executable, "-c", probe],
                           capture_output=True, text=True, timeout=90)
        return "y" in r.stdout
    except Exception:  # noqa: BLE001
        return False


def _run_workers(mode: str, ok_marker: str, timeout: float = 900,
                 attempts: int = 2):
    # outer timeout must exceed the SUM of the workers' inner deadlines
    # (team create 120s + per-coll 120s budgets) so a stalled step fails
    # on its own precise inner assertion, not a truncated parent kill.
    # One retry on fresh ports: the coordinator/OOB listeners race other
    # tests' sockets (TIME_WAIT reuse) intermittently in full-suite runs;
    # a genuine correctness failure reproduces on the retry and still
    # fails the test.
    if not _gloo_available():
        pytest.skip("jax CPU gloo collectives unavailable in this "
                    "environment (multi-controller mesh needs them); "
                    "see PARITY.md distributed-backends note")
    import socket
    last_fail = ""
    for attempt in range(attempts):
        # hold THREE ephemeral listeners at once, then release: the
        # kernel's allocator moves past all three, so workers' own
        # ephemeral listeners cannot land on the rendezvous ports
        socks = []
        ports = []
        for _ in range(3):
            ps = socket.socket()
            ps.bind(("127.0.0.1", 0))
            ports.append(ps.getsockname()[1])
            socks.append(ps)
        for ps in socks:
            ps.close()
        base_port, p_ctx, p_team = ports
        env = dict(os.environ)
        env.pop("UCC_TLS", None)
        env.pop("UCC_TOPO_FAKE_PPN", None)
        procs = [subprocess.Popen(
            [sys.executable, HERE, str(i), str(base_port), mode,
             str(p_ctx), str(p_team)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env) for i in range(2)]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=timeout)
                outs.append(out)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            last_fail = "multi-process workers timed out:\n" + \
                "\n".join(outs)
            continue
        bad = [f"worker {i} (rc={p.returncode}):\n{out[-6000:]}"
               for i, (p, out) in enumerate(zip(procs, outs))
               if p.returncode != 0 or f"{ok_marker} {i}" not in out]
        if not bad:
            return
        last_fail = "\n".join(bad)
    pytest.fail(f"after {attempts} attempts:\n{last_fail}")


def test_two_process_xla_collectives():
    _run_workers("flat", "MULTIPROC-OK")


def test_two_process_hier_hbm_allreduce():
    _run_workers("hier", "MULTIPROC-HIER-OK")


def test_two_process_ring_dma_and_fused_attention():
    """ring_dma allreduce + fused ring attention across OS processes
    (round-3 verdict next #6): the kernels' logical device ids and the
    rendezvous path prove out on a genuine multi-controller mesh."""
    _run_workers("ring_dma", "MULTIPROC-RINGDMA-OK")


if __name__ == "__main__":
    _worker_main(int(sys.argv[1]), int(sys.argv[2]),
                 sys.argv[3] if len(sys.argv) > 3 else "flat",
                 (int(sys.argv[4]), int(sys.argv[5]))
                 if len(sys.argv) > 5 else None)
