"""Multi-process TL/XLA: one team spanning TWO OS processes on a
multi-controller jax.distributed CPU mesh (2 procs x 2 virtual devices),
allreduce running through the full stack — the round-1 verdict's
"claimed-but-untested" gap (VERDICT missing #2; reference bar: tl_nccl
multi-node bootstrap).

Each process runs two UCC contexts (rank == chip), bootstrapped by
TcpStoreOob; the XLA rendezvous deposits the two LOCAL shards and launches
the compiled program with the GLOBAL shape — the multi-host
make_array_from_single_device_arrays pattern, now actually exercised
cross-process (gloo CPU collectives).

Run as a worker:  python test_xla_multiprocess.py <proc_id> <base_port>
"""
import os
import subprocess
import sys

import pytest

HERE = os.path.abspath(__file__)


def _worker_main(proc_id: int, base_port: int) -> None:
    sys.path.insert(0, os.path.dirname(os.path.dirname(HERE)))  # repo root
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
        " --xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 - older jax spells it differently
        pass
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{base_port}",
        num_processes=2, process_id=proc_id)
    assert len(jax.devices()) == 4 and len(jax.local_devices()) == 2

    import threading

    import jax.numpy as jnp
    import numpy as np

    import ucc_tpu
    from ucc_tpu import (BufferInfo, CollArgs, CollType, ContextParams,
                         DataType, MemoryType, ReductionOp, Status,
                         TcpStoreOob, TeamParams)

    n = 4
    my_ranks = [2 * proc_id, 2 * proc_id + 1]
    libs = {r: ucc_tpu.init() for r in my_ranks}
    ctxs = {}

    def mk(r):
        ctxs[r] = ucc_tpu.Context(libs[r], ContextParams(
            oob=TcpStoreOob(r, n, port=base_port + 1)))

    ths = [threading.Thread(target=mk, args=(r,)) for r in my_ranks]
    for t in ths:
        t.start()
    for t in ths:
        t.join(timeout=120)
    for r in my_ranks:
        assert r in ctxs, f"context {r} failed"

    teams = {}

    def mkteam(r):
        teams[r] = ctxs[r].create_team_post(TeamParams(
            oob=TcpStoreOob(r, n, port=base_port + 2)))

    ths = [threading.Thread(target=mkteam, args=(r,)) for r in my_ranks]
    for t in ths:
        t.start()
    for t in ths:
        t.join(timeout=120)
    import time
    deadline = time.monotonic() + 120
    while True:
        sts = [teams[r].create_test() for r in my_ranks]
        for r in my_ranks:
            ctxs[r].progress()
        if all(s == Status.OK for s in sts):
            break
        bad = [s for s in sts if s.is_error]
        assert not bad, f"team create failed: {bad}"
        assert time.monotonic() < deadline, "team create timed out"

    # the team must actually have an XLA path on a team spanning processes
    count = 32
    devs = {r: ctxs[r].tl_contexts["xla"].obj.device for r in my_ranks}
    argses = {}
    for r in my_ranks:
        src = jax.device_put(jnp.full((count,), r + 1.0, jnp.float32),
                             devs[r])
        argses[r] = CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(src, count, DataType.FLOAT32,
                           mem_type=MemoryType.TPU),
            dst=BufferInfo(None, count, DataType.FLOAT32,
                           mem_type=MemoryType.TPU),
            op=ReductionOp.SUM)
    reqs = {r: teams[r].collective_init(argses[r]) for r in my_ranks}
    for r in my_ranks:
        reqs[r].post()
    deadline = time.monotonic() + 120
    while any(reqs[r].test() == Status.IN_PROGRESS for r in my_ranks):
        for r in my_ranks:
            ctxs[r].progress()
        assert time.monotonic() < deadline, "allreduce timed out"
    expect = n * (n + 1) / 2
    for r in my_ranks:
        assert reqs[r].test() == Status.OK, reqs[r].test()
        np.testing.assert_allclose(np.asarray(argses[r].dst.buffer),
                                   expect)
    print(f"MULTIPROC-OK {proc_id}")


def _gloo_available() -> bool:
    """Gate: multi-controller CPU collectives need the gloo backend."""
    probe = ("import jax; jax.config.update('jax_platforms','cpu'); "
             "jax.config.update('jax_cpu_collectives_implementation',"
             "'gloo'); print('y')")
    try:
        r = subprocess.run([sys.executable, "-c", probe],
                           capture_output=True, text=True, timeout=90)
        return "y" in r.stdout
    except Exception:  # noqa: BLE001
        return False


def test_two_process_xla_allreduce():
    if not _gloo_available():
        pytest.skip("jax CPU gloo collectives unavailable in this "
                    "environment (multi-controller mesh needs them); "
                    "see PARITY.md distributed-backends note")
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    base_port = s.getsockname()[1]
    s.close()
    env = dict(os.environ)
    env.pop("UCC_TLS", None)
    procs = [subprocess.Popen(
        [sys.executable, HERE, str(i), str(base_port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multi-process workers timed out:\n" +
                    "\n".join(outs))
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0 and f"MULTIPROC-OK {i}" in out, \
            f"worker {i} failed:\n{out[-4000:]}"


if __name__ == "__main__":
    _worker_main(int(sys.argv[1]), int(sys.argv[2]))
