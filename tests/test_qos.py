"""Multi-tenant service tests: priority-lane progress queue,
small-collective coalescing, per-team QoS accounting.

Queue-level tests drive a bare ProgressQueue with counter tasks owned
by fake teams (only ``priority`` matters for lane placement).
Harness-level tests run real in-process jobs with UCC_COALESCE on and
check the fused batches bitwise against independent posts.
"""
import time

import numpy as np
import pytest

import ucc_tpu
from ucc_tpu import BufferInfo, CollArgs, Status, TeamParams, ThreadOobWorld
from ucc_tpu.constants import CollArgsFlags, CollType, DataType, ReductionOp
from ucc_tpu.core import coalesce
from ucc_tpu.schedule import progress as pg
from ucc_tpu.schedule.progress import ProgressQueue

from harness import UccJob


class _FakeTeam:
    def __init__(self, priority, tid=7):
        self.priority = priority
        self.id = tid
        self.context = None


class LaneTask(pg.CollTask):
    """Counts service passes; completes after n_steps."""

    def __init__(self, priority, trace=None, n_steps=1, name=""):
        super().__init__(team=_FakeTeam(priority))
        self.trace = trace if trace is not None else []
        self.n_steps = n_steps
        self.name = name
        self.steps = 0

    def post_fn(self):
        return Status.OK

    def progress_fn(self):
        self.steps += 1
        self.trace.append(self.name)
        if self.steps >= self.n_steps:
            self.status = Status.OK


def _enqueue(pq, *tasks):
    for t in tasks:
        t.status = t.super_status = Status.IN_PROGRESS
        t.steps = 0
        pq._lanes[pg._task_lane(t)].append(t)
        t._pq_enq = t._pq_last = time.monotonic()
        t._pq_low_snap = sum(pq._svc_count[:pg._task_lane(t)])
        t.progress_queue = pq


@pytest.fixture
def qos_knobs():
    """Restore module QoS/coalescing knobs mutated by a test."""
    w, a = pg._WEIGHTS, pg._AGE_S
    c = (coalesce.ENABLED, coalesce.LIMIT_BYTES, coalesce.WINDOW_S,
         coalesce.MAX_BATCH)
    yield
    pg._WEIGHTS, pg._AGE_S = w, a
    (coalesce.ENABLED, coalesce.LIMIT_BYTES, coalesce.WINDOW_S,
     coalesce.MAX_BATCH) = c


class TestPriorityLanes:
    def test_high_lane_served_first_and_bulk_capped(self, qos_knobs):
        pg.configure(weights="1,2,4,8", age_ms=10_000)
        pq = ProgressQueue()
        trace = []
        bulk = [LaneTask(0, trace, n_steps=99, name=f"b{i}")
                for i in range(4)]
        hot = LaneTask(3, trace, n_steps=99, name="hot")
        _enqueue(pq, *bulk, hot)
        pq.progress()
        # latency lane first; bulk lane capped to weight 1 while a
        # higher lane is non-empty
        assert trace[0] == "hot"
        assert sum(1 for n in trace if n.startswith("b")) == 1

    def test_single_lane_drains_uncapped(self, qos_knobs):
        pg.configure(weights="1,2,4,8", age_ms=10_000)
        pq = ProgressQueue()
        trace = []
        tasks = [LaneTask(1, trace, n_steps=99, name=f"t{i}")
                 for i in range(8)]
        _enqueue(pq, *tasks)
        pq.progress()
        # no higher lane occupied -> the WRR cap never engages and the
        # pass services every queued task (pre-lane behavior)
        assert len(trace) == 8

    def test_starved_task_ages_into_service(self, qos_knobs):
        # the progress-fairness regression: a bulk task beyond the WRR
        # cap must be serviced once it waits past the aging bound, even
        # under a saturating latency-lane stream
        pg.configure(weights="1,2,4,8", age_ms=5)
        pq = ProgressQueue()
        hot = LaneTask(3, n_steps=10**9, name="hot")
        bulk = [LaneTask(0, n_steps=10**9, name=f"b{i}") for i in range(3)]
        _enqueue(pq, hot, *bulk)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and \
                not all(b.steps > 0 for b in bulk):
            pq.progress()
            time.sleep(0.002)
        assert all(b.steps > 0 for b in bulk), \
            "bulk tasks starved behind the latency lane"
        # the aging promotion is what served them, and it was measured
        assert pq.starvation_max_s > 0.0
        snap = pq.qos_snapshot()
        assert snap["starvation_max_ms"] > 0.0
        assert pq.starvation_max_s == 0.0  # reset=True

    def test_priority_inversion_counter(self, qos_knobs):
        pg.configure(weights="1,2,4,8", age_ms=1)
        pq = ProgressQueue()
        hot = LaneTask(2, n_steps=1, name="hot")
        _enqueue(pq, hot)
        # lower-lane services advance after hot's enqueue snapshot,
        # while hot waits past the aging bound
        pq._svc_count[0] += 5
        hot._pq_enq -= 0.05
        pq.progress()
        assert pq.inversions == 1
        assert pq.qos_snapshot()["inversions"] == 1

    def test_flat_q_compat_surface(self, qos_knobs):
        # watchdog dumps and the FT cancel sweep duck-type on queue._q
        pq = ProgressQueue()
        b = LaneTask(0, n_steps=99, name="b")
        h = LaneTask(3, n_steps=99, name="h")
        _enqueue(pq, b, h)
        flat = pq._q
        assert flat == (h, b)      # highest lane first
        assert len(pq) == 2

    def test_qos_snapshot_team_wait(self, qos_knobs):
        pg.configure(weights="1,2,4,8", age_ms=10_000)
        pq = ProgressQueue()
        t = LaneTask(1, n_steps=2, name="t")
        t.team.id = 42
        _enqueue(pq, t)
        t._pq_enq -= 0.010
        pq.progress()
        snap = pq.qos_snapshot()
        assert 42 in snap["team_wait_ms"]
        w = snap["team_wait_ms"][42]
        assert w["n"] == 1 and w["max"] >= 10.0
        assert pq.qos_snapshot()["team_wait_ms"] == {}  # reset

    def test_clamp_priority(self):
        assert pg.clamp_priority(-3) == 0
        assert pg.clamp_priority(99) == pg.NUM_LANES - 1
        assert pg.clamp_priority("2") == 2
        assert pg.clamp_priority("bogus") == pg.DEFAULT_PRIORITY
        assert pg.clamp_priority(None) == pg.DEFAULT_PRIORITY


# ---------------------------------------------------------------------------
def _team_with_priority(job, priority):
    """Create one full team with an explicit TeamParams.priority."""
    world = ThreadOobWorld(job.n)
    teams = [job.contexts[r].create_team_post(
        TeamParams(oob=world.endpoint(r), priority=priority))
        for r in range(job.n)]
    # create_test must be called on EVERY member each round (no
    # short-circuit) or the laggards' state machines never step
    job.progress_until(lambda: all(
        [t.create_test() == Status.OK for t in teams]), 30)
    job.teams.append(teams)
    return teams


def _ar_args(src, dst, op=ReductionOp.SUM, dt=DataType.FLOAT32,
             inplace=False):
    cnt = dst.size
    flags = CollArgsFlags.IN_PLACE if inplace else CollArgsFlags(0)
    return CollArgs(coll_type=CollType.ALLREDUCE,
                    src=None if inplace else BufferInfo(src, cnt, dt),
                    dst=BufferInfo(dst, cnt, dt), op=op, flags=flags)


def _wait_reqs(job, reqs, timeout=30.0):
    job.progress_until(lambda: all(
        rq.test() != Status.IN_PROGRESS for per in reqs for rq in per),
        timeout)


class TestCoalescing:
    N = 4

    def _job(self, **knobs):
        coalesce.configure(**knobs)
        return UccJob(self.N)

    def test_team_priority_resolution(self, qos_knobs, monkeypatch):
        job = UccJob(2)
        try:
            teams = _team_with_priority(job, 3)
            assert all(t.priority == 3 for t in teams)
            monkeypatch.setenv("UCC_TEAM_PRIORITY", "2")
            teams2 = job.create_team()
            assert all(t.priority == 2 for t in teams2)
        finally:
            job.cleanup()

    def test_coalesced_bitwise_vs_independent(self, qos_knobs):
        """The acceptance bitwise claim: a coalesced batch delivers
        byte-identical results to the same collectives posted
        independently with coalescing off. Integer-valued payloads so
        every reduction order is exact; AVG over a power-of-two team is
        exact too. Covers SUM, AVG, an inplace member, and bf16."""
        N = self.N
        cases = [  # (op, dtype, inplace)
            (ReductionOp.SUM, DataType.FLOAT32, False),
            (ReductionOp.SUM, DataType.FLOAT32, True),
            (ReductionOp.AVG, DataType.FLOAT32, False),
            (ReductionOp.SUM, DataType.BFLOAT16, False),
        ]
        cnt = 16

        def payload(r, k, np_dt):
            return (np.arange(cnt) % 5 + r + k).astype(np_dt)

        results = {}
        for enabled in (False, True):
            coalesce.configure(enabled=enabled, limit=8192, window_us=5e4,
                               max_batch=16)
            job = UccJob(N)
            try:
                teams = job.create_team()
                if enabled:
                    assert all(t.coalescer is not None for t in teams)
                else:
                    assert all(t.coalescer is None for t in teams)
                from ucc_tpu.constants import dt_numpy
                dsts = []
                reqs = [[] for _ in range(N)]
                # two members per signature so every sealed batch
                # actually fuses (>= 2 members)
                for ci, (op, dt, inplace) in enumerate(cases):
                    np_dt = dt_numpy(dt)
                    for j in range(2):
                        k = 2 * ci + j
                        per = []
                        for r, t in enumerate(teams):
                            if inplace:
                                dst = payload(r, k, np_dt)
                                args = _ar_args(None, dst, op, dt,
                                                inplace=True)
                            else:
                                src = payload(r, k, np_dt)
                                dst = np.zeros(cnt, dtype=np_dt)
                                args = _ar_args(src, dst, op, dt)
                            rq = t.collective_init(args)
                            rq.post()
                            reqs[r].append(rq)
                            per.append(dst)
                        dsts.append(per)
                if enabled:
                    held = [len(t.coalescer.pending) for t in teams]
                    assert all(h == 2 for h in held), held
                _wait_reqs(job, reqs)
                for per in reqs:
                    for rq in per:
                        assert rq.test() == Status.OK
                if enabled:
                    # cases 0+1 share a signature (one 4-member batch),
                    # AVG and bf16 sealed their own pair batches
                    assert all(t.coalescer._fused_seq >= 3 for t in teams)
                results[enabled] = [[d.copy() for d in per] for per in dsts]
            finally:
                job.cleanup()
        for k in range(2 * len(cases)):
            for r in range(N):
                a, b = results[False][k][r], results[True][k][r]
                assert a.dtype == b.dtype
                assert np.array_equal(a, b), \
                    f"case {k} rank {r}: {a} != {b}"

    def test_mixed_signature_seals_batch(self, qos_knobs):
        # a post with a different (op, dtype) signature is a
        # program-order closure point: the open batch seals, both
        # batches complete correctly
        coalesce.configure(enabled=True, limit=8192, window_us=5e4,
                           max_batch=16)
        job = UccJob(self.N)
        try:
            teams = job.create_team()
            cnt = 8
            srcs, dsts, reqs = [], [], [[] for _ in range(self.N)]
            for k, op in enumerate((ReductionOp.SUM, ReductionOp.SUM,
                                    ReductionOp.MAX)):
                per_d = []
                for r, t in enumerate(teams):
                    src = (np.arange(cnt) + r + k).astype(np.float32)
                    dst = np.zeros(cnt, dtype=np.float32)
                    rq = t.collective_init(_ar_args(src, dst, op))
                    rq.post()
                    reqs[r].append(rq)
                    per_d.append(dst)
                dsts.append(per_d)
            # MAX arrived with a different signature -> SUM batch sealed
            assert all(len(t.coalescer.pending) == 1 for t in teams)
            _wait_reqs(job, reqs)
            base = np.arange(cnt).astype(np.float32)
            for r in range(self.N):
                assert np.array_equal(
                    dsts[0][r], sum(base + q for q in range(self.N)))
                assert np.array_equal(dsts[2][r], base + self.N - 1 + 2)
        finally:
            job.cleanup()

    def test_cancel_one_of_batch(self, qos_knobs):
        # cancelling one held member is rank-local: its segment stays in
        # the sealed batch (membership symmetry) but delivery and
        # completion are skipped for it alone
        coalesce.configure(enabled=True, limit=8192, window_us=5e4,
                           max_batch=16)
        job = UccJob(self.N)
        try:
            teams = job.create_team()
            cnt = 8
            dsts, reqs = [], [[] for _ in range(self.N)]
            for k in range(3):
                per_d = []
                for r, t in enumerate(teams):
                    src = (np.arange(cnt) + r + 10 * k).astype(np.float32)
                    dst = np.full(cnt, -1.0, dtype=np.float32)
                    rq = t.collective_init(_ar_args(src, dst))
                    rq.post()
                    reqs[r].append(rq)
                    per_d.append(dst)
                dsts.append(per_d)
            # rank 0 cancels its member k=1 while held
            reqs[0][1].task.cancel()
            assert reqs[0][1].test() == Status.ERR_CANCELED
            others = [[rq for i, rq in enumerate(per) if (r, i) != (0, 1)]
                      for r, per in enumerate(reqs)]
            _wait_reqs(job, others)
            base = np.arange(cnt).astype(np.float32)
            for k in (0, 1, 2):
                expect = sum(base + q + 10 * k for q in range(self.N))
                for r in range(self.N):
                    if (r, k) == (0, 1):
                        # no delivery into a cancelled member's dst
                        assert np.all(dsts[k][r] == -1.0)
                        continue
                    assert reqs[r][k].test() == Status.OK
                    # rank 0's contribution still participated
                    assert np.array_equal(dsts[k][r], expect)
        finally:
            job.cleanup()

    def test_destroy_mid_batch_aborts_members(self, qos_knobs):
        # fence/epoch contract: team teardown with a held batch fails
        # the members terminally instead of leaking them
        coalesce.configure(enabled=True, limit=8192, window_us=1e6,
                           max_batch=16)
        job = UccJob(2)
        try:
            teams = job.create_team()
            cnt = 8
            reqs = []
            for r, t in enumerate(teams):
                src = np.ones(cnt, dtype=np.float32)
                dst = np.zeros(cnt, dtype=np.float32)
                rq = t.collective_init(_ar_args(src, dst))
                rq.post()
                reqs.append(rq)
            assert all(len(t.coalescer.pending) == 1 for t in teams)
            for t in teams:
                t.destroy()
            for rq in reqs:
                st = rq.task.super_status
                assert st == Status.ERR_CANCELED, st
        finally:
            job.cleanup()

    def test_window_flush_without_test(self, qos_knobs):
        # quiescent-rank valve: nobody tests the requests; the window
        # expiry (driven from Context.progress) seals and completes them
        coalesce.configure(enabled=True, limit=8192, window_us=2e3,
                           max_batch=16)
        job = UccJob(self.N)
        try:
            teams = job.create_team()
            cnt = 8
            reqs, dsts = [], []
            for r, t in enumerate(teams):
                src = (np.arange(cnt) + r).astype(np.float32)
                dst = np.zeros(cnt, dtype=np.float32)
                rq = t.collective_init(_ar_args(src, dst))
                rq.post()
                reqs.append(rq)
                dsts.append(dst)
            # progress WITHOUT touching req.test (which would flush)
            deadline = time.monotonic() + 10.0
            while not all(rq.task.is_completed() for rq in reqs):
                for ctx in job.contexts:
                    ctx.progress()
                assert time.monotonic() < deadline, "window never flushed"
            expect = sum(np.arange(cnt).astype(np.float32) + q
                         for q in range(self.N))
            for dst in dsts:
                assert np.array_equal(dst, expect)
        finally:
            job.cleanup()

    def test_priority_post_flushes_bulk_window(self, qos_knobs):
        # the cross-team latency valve: a latency-class team's post
        # seals every open bulk batch in the context immediately
        coalesce.configure(enabled=True, limit=8192, window_us=1e6,
                           max_batch=16)
        job = UccJob(2)
        try:
            bulk = job.create_team()
            hot = _team_with_priority(job, 3)
            assert all(t.coalescer is None for t in hot)
            cnt = 8
            held = []
            for r, t in enumerate(bulk):
                src = np.ones(cnt, dtype=np.float32)
                dst = np.zeros(cnt, dtype=np.float32)
                rq = t.collective_init(_ar_args(src, dst))
                rq.post()
                held.append(rq)
            assert all(len(t.coalescer.pending) == 1 for t in bulk)
            hot_reqs = [t.collective_init(CollArgs(
                coll_type=CollType.BARRIER)) for t in hot]
            for rq in hot_reqs:
                rq.post()
            # the priority post flushed the bulk batches at post time
            assert all(len(t.coalescer.pending) == 0 for t in bulk)
            _wait_reqs(job, [held + hot_reqs])
        finally:
            job.cleanup()

    def test_disabled_dispatch_identical(self, qos_knobs):
        # UCC_COALESCE off (the default): no coalescer attached, no
        # request binding, and the candidate walk picks the same
        # algorithm it always picked
        coalesce.configure(enabled=True, limit=8192, window_us=5e4,
                           max_batch=16)
        job_on = UccJob(2)
        t_on = job_on.create_team()   # attach happens at activation
        coalesce.configure(enabled=False)
        job_off = UccJob(2)
        try:
            t_off = job_off.create_team()
            assert all(t.coalescer is not None for t in t_on)
            assert all(t.coalescer is None for t in t_off)
            cnt = 8
            algs = {}
            for label, job, teams in (("on", job_on, t_on),
                                      ("off", job_off, t_off)):
                reqs = []
                for r, t in enumerate(teams):
                    src = np.ones(cnt, dtype=np.float32)
                    dst = np.zeros(cnt, dtype=np.float32)
                    rq = t.collective_init(_ar_args(src, dst))
                    reqs.append(rq)
                algs[label] = [rq.task.alg_name for rq in reqs]
                from ucc_tpu.constants import MemoryType
                cands = teams[0].score_map.lookup(
                    CollType.ALLREDUCE, MemoryType.HOST, cnt * 4)
                algs[label + "_cands"] = [str(c.alg_name) for c in cands]
                if label == "off":
                    assert all(rq._coalesce is None for rq in reqs)
                else:
                    assert all(rq._coalesce is not None for rq in reqs)
                for rq in reqs:
                    rq.post()
                job.progress_until(lambda: all(
                    rq.test() != Status.IN_PROGRESS for rq in reqs), 30)
            assert algs["on"] == algs["off"]
            assert algs["on_cands"] == algs["off_cands"]
        finally:
            job_on.cleanup()
            job_off.cleanup()
