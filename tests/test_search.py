"""Search-driven algorithm synthesis (ISSUE 14): the alpha-beta cost
model (fit, pricing, link classification, persistence), the joint-space
proposer + cost pruning, the search cache round trip with
origin="searched" provenance, the tuner-cache staleness guard, the
verified-program disk cache, hierarchical program composition on an
asymmetric simulated pod layout (incl. quantized DCN edges), and the
budgeted end-to-end search loop.
"""
from __future__ import annotations

import json
import os
import zlib

import numpy as np
import pytest

from ucc_tpu import BufferInfo, CollArgs, Status
from ucc_tpu.constants import (CollType, DataType, MemoryType,
                               ReductionOp)
from ucc_tpu.dsl import families as fam
from ucc_tpu.dsl import registry as genreg
from ucc_tpu.dsl import search as gensearch
from ucc_tpu.dsl.verify import verify
from ucc_tpu.score import cost
from ucc_tpu.score.tuner import (apply_entries, cand_label,
                                 forced_request, sweep_candidates)

from harness import UccJob


def _paths(node_of, pod_of=None):
    out = []
    for nd in node_of:
        hh = zlib.crc32(f"n{nd}".encode())
        if pod_of is None:
            out.append((hh,))
        else:
            out.append((zlib.crc32(f"p{pod_of[nd]}".encode()), hh))
    return out


# asymmetric 3-level pod layout: nodes of 2,1,3,2 ranks over 2 pods
ASYM_PATHS = _paths([0, 0, 1, 2, 2, 2, 3, 3], [0, 0, 1, 1])


# ---------------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------------

class TestCostModel:
    def test_seed_model_orders_latency_vs_bandwidth(self):
        """Direct exchange (2 rounds) must beat ring (2n-2 rounds) at
        tiny sizes; the crossover must flip somewhere as bytes grow —
        the alpha/beta separation the search prunes with."""
        m = cost.CostModel()
        n = 8
        ring = fam.gen_ring(n, 1)
        direct = fam.gen_rhd(n, radix=n)
        assert m.predict_us(direct, 256) < m.predict_us(ring, 256)
        # both are bandwidth-optimal (~2(n-1)/n of the vector on the
        # critical path); what separates them is ROUND count, which is
        # exactly the alpha term the model prices
        big = 8 << 20
        ring_feats = m.features(ring, big)
        direct_feats = m.features(direct, big)
        assert ring_feats["shm"][1] <= direct_feats["shm"][1]
        assert ring_feats["shm"][0] > direct_feats["shm"][0]

    def test_quantized_edges_priced_at_wire_bytes(self):
        m = cost.CostModel()
        n, size = 8, 1 << 20
        exact = fam.gen_rhd(n, radix=n)
        q = fam.gen_rhd(n, radix=n, wire="int8")
        fe = m.features(exact, size)["shm"]
        fq = m.features(q, size)["shm"]
        assert fq[0] == fe[0]                  # same rounds
        assert fq[1] < fe[1] * 0.30            # ~4x fewer wire bytes

    def test_hier_program_prices_dcn_edges_separately(self):
        prog = fam.gen_hier(ASYM_PATHS, top=0)
        m = cost.CostModel()
        link_of = cost.link_of_paths(ASYM_PATHS)
        feats = m.features(prog, 64 << 10, link_of)
        assert "shm" in feats and "dcn" in feats
        # quantizing the DCN edges shrinks ONLY the dcn byte feature
        qprog = fam.gen_hier(ASYM_PATHS, top=0, wire="int8")
        qfeats = m.features(qprog, 64 << 10, link_of)
        assert qfeats["dcn"][1] < feats["dcn"][1] * 0.30
        assert qfeats["shm"][1] == feats["shm"][1]

    def test_fit_recovers_synthetic_coefficients(self):
        """Records generated FROM the model must fit back to (close to)
        the same coefficients."""
        true = cost.CostModel()
        true.links["shm"] = cost.LinkCoeffs(12.0, 2.0e-3)
        n = 8
        recs = []
        for gen, size in (("ring(chunks=1)", 65536),
                          ("rhd(radix=8)", 65536),
                          ("rhd(radix=2)", 65536),
                          ("ring(chunks=1)", 8192),
                          ("rhd(radix=8)", 8192)):
            famname, params, wire = cost.parse_param_str(gen)
            prog = genreg.build_named(famname, params, n, wire=wire)
            us = true.predict_us(prog, size)
            recs.append({"gen": gen, "ranks": n, "size_bytes": size,
                         "p50_us": round(us, 3)})
        m = cost.fit_records(recs)
        assert m is not None and m.fitted
        got = m.links["shm"]
        assert got.fitted
        assert abs(got.alpha_us - 12.0) / 12.0 < 0.05
        assert abs(got.beta_us_per_byte - 2.0e-3) / 2.0e-3 < 0.05
        # the other classes are derived (rescaled), not fitted
        assert not m.links["dcn"].fitted

    def test_parse_param_str_roundtrip(self):
        assert cost.parse_param_str("ring(chunks=4)") == \
            ("ring", {"chunks": 4}, "")
        assert cost.parse_param_str("hier(top=2,wire=int8)") == \
            ("hier", {"top": 2}, "int8")
        assert cost.parse_param_str("qdirect(int8,radix=8)") == \
            ("qdirect", {"radix": 8}, "int8")
        assert cost.parse_param_str("sra_pipe(depth=4,radix=2)") == \
            ("sra_pipe", {"depth": 4, "radix": 2}, "")
        assert cost.parse_param_str("garbage")[0] == ""

    def test_save_load_roundtrip(self, tmp_path):
        m = cost.fit_records([
            {"gen": "ring(chunks=1)", "ranks": 4, "size_bytes": 4096,
             "p50_us": 100.0},
            {"gen": "rhd(radix=4)", "ranks": 4, "size_bytes": 4096,
             "p50_us": 60.0},
            {"gen": "rhd(radix=2)", "ranks": 4, "size_bytes": 4096,
             "p50_us": 80.0}])
        assert m is not None
        p = str(tmp_path / "cost.json")
        cost.save_model(m, p)
        m2 = cost.load_model(p)
        assert m2 is not None and m2.fitted
        assert m2.links["shm"].alpha_us == \
            pytest.approx(m.links["shm"].alpha_us)
        # a never-fitted model is not worth a predicted_us column
        cost.save_model(cost.CostModel(), p)
        assert cost.load_model(p) is None

    def test_link_of_paths(self):
        link = cost.link_of_paths(ASYM_PATHS)
        assert link(0, 1) == "shm"        # same node
        assert link(0, 2) == "socket"     # same pod, different node
        assert link(0, 3) == "dcn"        # different pod
        flat = cost.link_of_paths(None)
        assert flat(0, 5) == "shm"


# ---------------------------------------------------------------------------
# joint-space proposer + pruning
# ---------------------------------------------------------------------------

class TestPropose:
    def test_space_exceeds_the_fixed_grids(self):
        n = 8
        grid = gensearch.grid_program_names(CollType.ALLREDUCE, n)
        space = gensearch.propose(CollType.ALLREDUCE, n,
                                  grid_names=grid)
        names = {c.name for c in space}
        beyond = {c.name for c in space if not c.from_grid}
        assert "gen_ring_c3" in beyond        # chunking outside grid
        assert "gen_sra_pipe_d3" in beyond    # depth outside grid
        assert any(c.params.get("radix") and c.family == "sra_pipe"
                   for c in space)            # JOINT depth x radix
        assert grid <= names                  # grids are a subspace

    def test_hier_points_need_paths(self):
        n = len(ASYM_PATHS)
        flat = gensearch.propose(CollType.ALLREDUCE, n)
        assert not any(c.hier for c in flat)
        topo = gensearch.propose(CollType.ALLREDUCE, n,
                                 paths=ASYM_PATHS, quant_mode="int8")
        hier = [c for c in topo if c.hier]
        assert any(c.wire == "int8" for c in hier)
        assert any(not c.wire for c in hier)

    def test_shortlist_budget_and_per_size_predictions(self):
        n = 8
        space = gensearch.propose(CollType.ALLREDUCE, n)
        m = cost.CostModel()
        small = gensearch.shortlist(space, m, 256, 4)
        big = gensearch.shortlist(space, m, 4 << 20, 4)
        assert len(small) == 4 and len(big) == 4
        # per-size copies: predictions must not clobber across sizes
        by_name_small = {c.name: c.predicted_us for c in small}
        for c in big:
            if c.name in by_name_small:
                assert c.predicted_us != by_name_small[c.name]
        # ordering sane: a latency algorithm leads the small shortlist
        assert small[0].predicted_us <= small[-1].predicted_us


# ---------------------------------------------------------------------------
# search cache + registration round trip
# ---------------------------------------------------------------------------

class TestSearchCache:
    def test_store_replace_scope_and_load(self, tmp_path):
        p = str(tmp_path / "search.json")
        e1 = {"coll": "allreduce", "n": 4, "family": "ring",
              "params": {"chunks": 3}, "wire": "", "name": "gen_ring_c3",
              "gen": "ring(chunks=3)", "paths_digest": ""}
        e2 = dict(e1, name="gen_ring_c6", params={"chunks": 6},
                  gen="ring(chunks=6)")
        gensearch.store_search_entries(p, [e1, e2])
        assert len(gensearch.load_search_cache(p)["entries"]) == 2
        # scope replace drops both, keeps the new winner only
        gensearch.store_search_entries(
            p, [e1], replace_scopes=[("allreduce", 4, "")])
        entries = gensearch.load_search_cache(p)["entries"]
        assert [e["name"] for e in entries] == ["gen_ring_c3"]
        # a different scope is untouched
        e8 = dict(e1, n=8)
        gensearch.store_search_entries(p, [e8])
        gensearch.store_search_entries(
            p, [], replace_scopes=[("allreduce", 4, "")])
        entries = gensearch.load_search_cache(p)["entries"]
        assert [e["n"] for e in entries] == [8]

    def test_searched_programs_rebuild_and_skip_stale(self, tmp_path,
                                                      monkeypatch):
        p = str(tmp_path / "search.json")
        monkeypatch.setenv("UCC_GEN_SEARCH_CACHE", p)
        gensearch.store_search_entries(p, [
            {"coll": "allreduce", "n": 4, "family": "ring",
             "params": {"chunks": 3}, "wire": "", "name": "gen_ring_c3",
             "gen": "ring(chunks=3)", "paths_digest": ""},
            # stale: unknown family no longer builds
            {"coll": "allreduce", "n": 4, "family": "warp",
             "params": {}, "wire": "", "name": "gen_warp",
             "gen": "warp()", "paths_digest": ""},
            # different team size: not applicable here
            {"coll": "allreduce", "n": 8, "family": "ring",
             "params": {"chunks": 6}, "wire": "", "name": "gen_ring_c6",
             "gen": "ring(chunks=6)", "paths_digest": ""}])
        progs = gensearch.searched_programs(None, 4)
        assert [pr.name for pr in progs] == ["gen_ring_c3"]
        for pr in progs:
            verify(pr)                # registration-grade

    def test_searched_candidate_registers_and_dispatches(
            self, tmp_path, monkeypatch):
        """The acceptance round trip: search cache -> registration
        (origin 'searched') -> tuner promotion -> dispatch, with the
        provenance visible in the score dump."""
        p = str(tmp_path / "search.json")
        monkeypatch.setenv("UCC_GEN_SEARCH_CACHE", p)
        gensearch.store_search_entries(p, [
            {"coll": "allreduce", "n": 2, "family": "ring",
             "params": {"chunks": 3}, "wire": "", "name": "gen_ring_c3",
             "gen": "ring(chunks=3)", "paths_digest": "",
             "predicted_us": 42.0, "measured_us": 40.0}])
        job = UccJob(2, lib_overrides={"GEN": "y", "GEN_SEARCH": "y"})
        try:
            teams = job.create_team()
            cands = sweep_candidates(teams[0], CollType.ALLREDUCE,
                                     MemoryType.HOST, 65536)
            searched = [c for c in cands if c.origin == "searched"]
            assert searched and searched[0].alg_name == "gen_ring_c3"
            assert searched[0].gen == "ring(chunks=3)"
            # tuner-cache promotion with origin=searched (every rank:
            # diverging score maps would deadlock the dispatch)
            for t in teams:
                ok = t.score_map.apply_learned(
                    CollType.ALLREDUCE, MemoryType.HOST, 0, 1 << 20,
                    "gen_ring_c3", origin="searched")
                assert ok
            info = teams[0].score_map.print_info("t")
            assert "searched gen:ring(chunks=3)" in info
            # dispatch actually runs the searched program
            count = 999
            srcs = [np.full(count, r + 1.0, np.float32)
                    for r in range(2)]
            dsts = [np.zeros(count, np.float32) for _ in range(2)]
            reqs = job.run_coll(teams, lambda i: CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(srcs[i], count, DataType.FLOAT32),
                dst=BufferInfo(dsts[i], count, DataType.FLOAT32),
                op=ReductionOp.SUM))
            assert reqs[0].task.alg_name == "gen_ring_c3"
            for rq in reqs:
                rq.finalize()
            np.testing.assert_allclose(dsts[0], np.full(count, 3.0))
        finally:
            job.cleanup()

    def test_gen_search_off_keeps_candidates_clean(self, tmp_path,
                                                   monkeypatch):
        p = str(tmp_path / "search.json")
        monkeypatch.setenv("UCC_GEN_SEARCH_CACHE", p)
        gensearch.store_search_entries(p, [
            {"coll": "allreduce", "n": 2, "family": "ring",
             "params": {"chunks": 3}, "wire": "", "name": "gen_ring_c3",
             "gen": "ring(chunks=3)", "paths_digest": ""}])
        job = UccJob(2, lib_overrides={"GEN": "y", "GEN_SEARCH": "n"})
        try:
            teams = job.create_team()
            cands = sweep_candidates(teams[0], CollType.ALLREDUCE,
                                     MemoryType.HOST, 65536)
            assert not any(c.origin == "searched" for c in cands)
        finally:
            job.cleanup()


# ---------------------------------------------------------------------------
# tuner-cache staleness guard (ISSUE 14 satellite)
# ---------------------------------------------------------------------------

class TestStalenessGuard:
    def test_stale_generated_entry_dropped_with_metric(self,
                                                       monkeypatch):
        """A cache entry naming a generated algorithm that no longer
        registers (UCC_GEN off here) must be DROPPED with a warning +
        metric — never compiled into the score map — while plain
        hand-written entries still apply."""
        from ucc_tpu.obs import metrics
        monkeypatch.setattr(metrics, "ENABLED", True)
        key = metrics._key("tuner_stale_entries_dropped", "tuner",
                           "allreduce", "gen_ring_c3")
        job = UccJob(2)               # UCC_GEN off: no gen_* candidates
        try:
            teams = job.create_team()
            sm = teams[0].score_map
            before = sm.lookup(CollType.ALLREDUCE, MemoryType.HOST,
                               4096)
            n0 = metrics._counters.get(key, 0)
            covered = apply_entries(sm, [
                {"coll": "allreduce", "mem": "host", "start": 0,
                 "end": 1 << 20, "alg": "gen_ring_c3",
                 "gen": "ring(chunks=3)", "origin": "searched"},
                {"coll": "allreduce", "mem": "host", "start": 0,
                 "end": 4096, "alg": "sra_knomial"}])
            # only the hand-written entry applied
            assert covered == [(CollType.ALLREDUCE, MemoryType.HOST,
                                0, 4096)]
            after = sm.lookup(CollType.ALLREDUCE, MemoryType.HOST,
                              8192)
            assert not any(c.alg_name == "gen_ring_c3" for c in after)
            assert len(after) == len(before)
            assert metrics._counters.get(key, 0) == n0 + 1
        finally:
            job.cleanup()


# ---------------------------------------------------------------------------
# verified-program disk cache (ISSUE 14 satellite)
# ---------------------------------------------------------------------------

class TestProgramDiskCache:
    def _reset(self, path):
        genreg._CACHE.clear()
        genreg._PENDING.clear()
        genreg._DISK["path"] = False
        genreg._DISK["programs"] = None
        os.environ["UCC_GEN_PROG_CACHE"] = path

    def test_roundtrip_skips_verification(self, tmp_path, monkeypatch):
        path = str(tmp_path / "programs.pkl")
        self._reset(path)
        try:
            p1 = genreg.build_program("ring", 2, 6)
            assert p1 is not None
            genreg.flush_program_cache()   # writes batch (atexit flush)
            assert os.path.exists(path)
            # fresh process simulation: memory cache cleared, verifier
            # booby-trapped — a disk hit must NOT re-verify
            self._reset(path)

            def boom(prog):
                raise AssertionError("disk hit must skip verification")
            monkeypatch.setattr(genreg, "verify", boom)
            p2 = genreg.build_program("ring", 2, 6)
            assert p2 is not None and p2.name == p1.name
            assert p2.n_rounds == p1.n_rounds
        finally:
            self._reset("0")

    def test_version_bump_invalidates(self, tmp_path, monkeypatch):
        path = str(tmp_path / "programs.pkl")
        self._reset(path)
        try:
            assert genreg.build_program("ring", 1, 4) is not None
            genreg.flush_program_cache()
            # stamp the file with a stale DSL version
            import pickle
            with open(path, "rb") as fh:
                data = pickle.load(fh)
            data["version"] = -1
            with open(path, "wb") as fh:
                pickle.dump(data, fh)
            self._reset(path)
            calls = []
            real = genreg.verify

            def spy(prog):
                calls.append(prog.name)
                return real(prog)
            monkeypatch.setattr(genreg, "verify", spy)
            assert genreg.build_program("ring", 1, 4) is not None
            assert calls, "stale-version cache must force re-verify"
        finally:
            self._reset("0")

    def test_disabled_by_knob(self, tmp_path):
        path = str(tmp_path / "programs.pkl")
        self._reset("0")
        try:
            assert genreg.build_program("ring", 1, 4) is not None
            genreg.flush_program_cache()
            assert not os.path.exists(path)
        finally:
            self._reset("0")

    def test_corrupt_cache_rebuilds(self, tmp_path):
        path = str(tmp_path / "programs.pkl")
        with open(path, "wb") as fh:
            fh.write(b"not a pickle")
        self._reset(path)
        try:
            assert genreg.build_program("ring", 1, 4) is not None
        finally:
            self._reset("0")


# ---------------------------------------------------------------------------
# hierarchical composition (acceptance: >= 3-level tree, quantized DCN
# edges, asymmetric simulated pod layout)
# ---------------------------------------------------------------------------

class TestHierPrograms:
    def test_three_level_asymmetric_verifies_with_quant_dcn(self):
        for top in (0, 1, 2, 4):
            for wire in ("", "int8", "fp8"):
                prog = fam.gen_hier(ASYM_PATHS, top=top, wire=wire)
                verify(prog)
                assert prog.nranks == 8
                assert prog.edge_wire_mode == wire
                if wire:
                    # ONLY cross-pod edges quantize
                    from ucc_tpu.dsl.ir import OpKind
                    for r, rp in enumerate(prog.ranks):
                        for ops in rp.rounds:
                            for op in ops:
                                if op.kind == OpKind.COPY:
                                    continue
                                crosses = ASYM_PATHS[r][0] != \
                                    ASYM_PATHS[op.peer][0]
                                assert bool(op.wire) == crosses, \
                                    (r, op)

    def test_single_node_layout_inapplicable(self):
        with pytest.raises(fam.Inapplicable):
            fam.gen_hier(_paths([0, 0, 0, 0]), top=0)

    def test_hier_matches_numpy_on_simulated_pod(self, monkeypatch):
        """End-to-end on the fake 2,1,3-nodes x 2-pods topology: every
        hier variant (exact + quantized-DCN) matches numpy cross-rank
        and all ranks agree bitwise."""
        monkeypatch.setenv("UCC_TOPO_FAKE_PPN", "2,1,3")
        monkeypatch.setenv("UCC_TOPO_FAKE_NODES_PER_POD", "2")
        from ucc_tpu.quant import default_budget
        n, count = 8, 8 << 10
        msgsize = count * 4
        job = UccJob(n, lib_overrides={"GEN": "y", "QUANT": "int8"})
        try:
            teams = job.create_team()
            cands = sweep_candidates(teams[0], CollType.ALLREDUCE,
                                     MemoryType.HOST, msgsize)
            idxs = {c.alg_name: i for i, c in enumerate(cands)
                    if c.origin == "generated" and
                    cand_label(c)[0] == "shm" and
                    c.alg_name.startswith("gen_hier")}
            assert any("qint8" in k for k in idxs)
            assert any("qint8" not in k for k in idxs)
            rng = np.random.default_rng(3)
            srcs = [(rng.random(count).astype(np.float32) - 0.5) * 4
                    for _ in range(n)]
            exact = np.sum(np.stack(srcs).astype(np.float64), axis=0)
            peak = np.max(np.abs(exact))
            from test_dsl import _force_coll
            for name, i in sorted(idxs.items()):
                dsts = [np.zeros(count, np.float32) for _ in range(n)]
                argses = [CollArgs(
                    coll_type=CollType.ALLREDUCE,
                    src=BufferInfo(srcs[r].copy(), count,
                                   DataType.FLOAT32),
                    dst=BufferInfo(dsts[r], count, DataType.FLOAT32),
                    op=ReductionOp.SUM) for r in range(n)]
                sts = _force_coll(job, teams, argses,
                                  CollType.ALLREDUCE, i, msgsize)
                assert all(s == Status.OK for s in sts), (name, sts)
                tol = default_budget("int8") if "qint8" in name \
                    else 1e-5
                for d in dsts:
                    assert np.max(np.abs(d - exact)) / peak <= tol, name
                for d in dsts[1:]:
                    np.testing.assert_array_equal(dsts[0], d,
                                                  err_msg=name)
        finally:
            job.cleanup()


# ---------------------------------------------------------------------------
# end-to-end budgeted search (small mesh; the CI smoke runs the big one)
# ---------------------------------------------------------------------------

class TestSearchEndToEnd:
    def test_budgeted_search_produces_persisted_winner(self, tmp_path,
                                                       monkeypatch):
        search_cache = str(tmp_path / "search.json")
        tuner_cache = str(tmp_path / "tune.json")
        monkeypatch.setenv("UCC_GEN_SEARCH_CACHE", search_cache)
        model = cost.CostModel()     # seed model: no probe job needed
        rep = gensearch.run_search(
            2, ["allreduce"], [8192], iters=2, budget=4,
            search_cache=search_cache, tuner_cache=tuner_cache,
            model=model, verbose=False)
        res = rep["results"][0]
        assert res.get("winner"), rep
        finalists = res["finalists"]
        assert finalists and all("measured_us" in f for f in finalists)
        # searched shortlist rows carry predicted cost provenance
        assert any(f.get("predicted_us") is not None
                   for f in finalists)
        if rep.get("winners"):
            cachef = gensearch.load_search_cache(search_cache)
            names = {e["name"] for e in cachef["entries"]}
            assert set(rep["winners"]) <= names
        if rep.get("tuner_entries"):
            with open(tuner_cache) as fh:
                tc = json.load(fh)
            entries = next(iter(tc["signatures"].values()))["entries"]
            assert all(e.get("origin") == "searched" for e in entries)
            assert all(e.get("measured_us") is not None
                       for e in entries)
