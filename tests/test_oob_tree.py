"""Logarithmic bootstrap (ISSUE 8): tree-structured OOB store exchange —
layout construction, thread/TCP tree allgather correctness, O(log n)
round/fan-in scaling, subset-capable SubsetOob rounds, and the k-ary
TransportOob exchange surviving its rewrite."""
import socket
import threading

import pytest

from ucc_tpu.core.oob import (SubsetOob, TcpTreeOob, ThreadOobWorld,
                              ThreadTreeOobWorld, tree_layout)


class TestTreeLayout:
    def test_symmetric(self):
        lay = tree_layout(64, ppn=8, radix=4)
        assert [len(groups) for groups in lay] == [8, 2, 1]
        assert lay[0][0] == list(range(8))
        assert lay[1][0] == [0, 8, 16, 24]          # node leaders
        assert lay[2][0] == [0, 32]                 # chunk leaders

    def test_asymmetric_cyclic(self):
        lay = tree_layout(5, ppn="2,1", radix=2)
        assert lay[0] == [[0, 1], [2], [3, 4]]
        assert lay[1] == [[0, 2], [3]]
        assert lay[2] == [[0, 3]]

    def test_single_rank(self):
        assert tree_layout(1) == [[[0]]]

    def test_single_node(self):
        assert tree_layout(4, ppn=8) == [[[0, 1, 2, 3]]]

    def test_no_ppn_uses_radix_blocks(self):
        lay = tree_layout(16, radix=4)
        assert [len(g) for g in lay[0]] == [4, 4, 4, 4]
        assert len(lay) == 2

    def test_every_level_partitions_leaders(self):
        lay = tree_layout(100, ppn="3,1,5", radix=3)
        # level 0 partitions ALL ranks
        flat = sorted(r for g in lay[0] for r in g)
        assert flat == list(range(100))
        # each level's members are exactly the previous level's leaders
        for lvl in range(1, len(lay)):
            members = sorted(r for g in lay[lvl] for r in g)
            leaders = sorted(g[0] for g in lay[lvl - 1])
            assert members == leaders
        assert len(lay[-1]) == 1


def _run_threads(n, fn):
    errs = []

    def wrap(r):
        try:
            fn(r)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errs.append((r, e))

    ths = [threading.Thread(target=wrap, args=(r,)) for r in range(n)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(60)
    assert not errs, errs


class TestThreadTreeOob:
    def test_allgather_matches_world(self):
        n = 24
        w = ThreadTreeOobWorld(n, ppn=3, radix=2)
        eps = w.endpoints()
        out = [None] * n

        def run(r):
            out[r] = eps[r].allgather(f"blob-{r}".encode()).result

        _run_threads(n, run)
        expect = [f"blob-{r}".encode() for r in range(n)]
        assert all(o == expect for o in out)

    def test_pipelined_rounds_stay_ordered(self):
        n = 12
        w = ThreadTreeOobWorld(n, ppn=4, radix=2)
        eps = w.endpoints()
        out = [None] * n

        def run(r):
            reqs = [eps[r].allgather(f"{r}.{i}".encode()) for i in range(4)]
            out[r] = [rq.result for rq in reqs]

        _run_threads(n, run)
        for r in range(n):
            for i in range(4):
                assert out[r][i] == [f"{x}.{i}".encode() for x in range(n)]

    def test_empty_and_large_payloads(self):
        n = 9
        w = ThreadTreeOobWorld(n, ppn=3, radix=3)
        eps = w.endpoints()
        payloads = [b"" if r % 2 else bytes([r]) * (10_000 + r)
                    for r in range(n)]
        out = [None] * n

        def run(r):
            out[r] = eps[r].allgather(payloads[r]).result

        _run_threads(n, run)
        assert all(o == payloads for o in out)

    def test_rounds_scale_logarithmically(self):
        """The tentpole claim, at the OOB layer: per-allgather store
        rounds grow with tree DEPTH, per-store fan-in stays bounded by
        max(ppn, radix) — both << n, where the flat store funnels n
        connections into one server."""
        for n in (64, 512):
            w = ThreadTreeOobWorld(n, ppn=8, radix=8)
            eps = w.endpoints()
            out = [None] * n

            def run(r):
                out[r] = eps[r].allgather(str(r).encode()).result

            _run_threads(n, run)
            assert all(o == [str(x).encode() for x in range(n)]
                       for o in out)
            levels = eps[0].stats["levels"]
            assert levels <= 3
            assert max(e.stats["max_fanin"] for e in eps) == 8 < n
            assert max(e.stats["rounds"] for e in eps) <= 2 * levels

    def test_single_rank_world(self):
        w = ThreadTreeOobWorld(1)
        ep = w.endpoint(0)
        assert ep.allgather(b"solo").result == [b"solo"]
        assert ep.stats["rounds"] == 0


class TestTcpTreeOob:
    def test_allgather_over_sockets(self):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        base = s.getsockname()[1]
        s.close()
        n = 8
        assert TcpTreeOob.ports_needed(n, ppn=2, radix=2) == 7
        ends = [None] * n

        def mk(r):
            ends[r] = TcpTreeOob(r, n, base_port=base + 1, key="t",
                                 ppn=2, radix=2, timeout_s=20)

        _run_threads(n, mk)
        out = [None] * n

        def ag(r):
            out[r] = ends[r].allgather(f"tcp{r}".encode()).result

        _run_threads(n, ag)
        try:
            expect = [f"tcp{r}".encode() for r in range(n)]
            assert all(o == expect for o in out)
            # no store saw more than max(ppn, radix)=2 members
            assert ends[0].stats["max_fanin"] == 2
        finally:
            for e in ends:
                e.close()


class TestSubsetCapability:
    """ISSUE 8 satellite: subset bootstrap over a capable parent runs
    members-only rounds — non-members skip entirely, so a nested
    subgroup create no longer costs a whole-team round per level."""

    def test_members_only_round(self):
        w = ThreadOobWorld(6)
        subs = [SubsetOob(w.endpoint(r), [1, 2, 4]) for r in (1, 2, 4)]
        reqs = [s.allgather(f"m{s.oob_ep}".encode()) for s in subs]
        for rq in reqs:
            assert rq.result == [b"m0", b"m1", b"m2"]
        # the parent's main round space was never touched: ranks 0/3/5
        # did not participate and no main round was consumed
        assert w.next_round == [0] * 6
        assert not w.rounds

    def test_participate_is_noop_on_capable_parent(self):
        w = ThreadOobWorld(4)
        ep = w.endpoint(3)
        from ucc_tpu.status import Status
        rq = SubsetOob.participate(ep)
        assert rq.test() == Status.OK
        assert w.next_round == [0] * 4

    def test_nested_subsets(self):
        w = ThreadOobWorld(8)
        outer_ranks = [1, 3, 5, 7]
        outers = [SubsetOob(w.endpoint(r), outer_ranks)
                  for r in outer_ranks]
        assert all(o.SUBSET_CAPABLE for o in outers)
        # inner subset {3, 7} = outer indices {1, 3}
        inners = [SubsetOob(outers[1], [1, 3]), SubsetOob(outers[3], [1, 3])]
        reqs = [i.allgather(f"n{i.oob_ep}".encode()) for i in inners]
        for rq in reqs:
            assert rq.result == [b"n0", b"n1"]
        assert w.next_round == [0] * 8

    def test_legacy_parent_keeps_full_round_contract(self):
        """A non-capable parent (no subset_allgather) still needs the
        whole-team participate round."""

        class Legacy(ThreadOobWorld):
            pass

        w = Legacy(3)
        eps = w.endpoints()
        for ep in eps:
            ep.SUBSET_CAPABLE = False      # simulate a flat TCP store
            ep.subset_allgather = None
        sub = SubsetOob(eps[1], [1, 2])
        sub2 = SubsetOob(eps[2], [1, 2])
        assert not sub.SUBSET_CAPABLE
        r1 = sub.allgather(b"a")
        r2 = sub2.allgather(b"b")
        SubsetOob.participate(eps[0])      # rank 0 must ride along
        assert r1.result == [b"a", b"b"] == r2.result

    def test_create_from_parent_nonmember_skips(self):
        """Team.create_from_parent over a capable OOB: non-members
        return immediately without consuming any parent round."""
        import sys
        sys.path.insert(0, __file__.rsplit("/", 1)[0])
        from harness import UccJob
        job = UccJob(4)
        try:
            teams = job.create_team()
            world = job.teams and None
            from ucc_tpu.core.team import Team
            subs = {}

            def split(i):
                subs[i] = Team.create_from_parent(teams[i], [0, 2])

            # cooperative: members' create must not need non-members
            for i in (1, 3):
                split(i)
                assert subs[i] is None
            for i in (0, 2):
                split(i)
            import time
            from ucc_tpu import Status
            deadline = time.monotonic() + 30
            while True:
                sts = [subs[i].create_test() for i in (0, 2)]
                if all(s == Status.OK for s in sts):
                    break
                assert not any(s.is_error for s in sts), sts
                for c in job.contexts:
                    c.progress()
                assert time.monotonic() < deadline
            assert subs[0].size == 2 and subs[2].rank == 1
            subs[0].destroy()
            subs[2].destroy()
        finally:
            job.cleanup()


class TestTransportOobTree:
    """The k-ary rewrite of the fault-tolerant transport OOB: correctness
    over a live service-team transport, batched tree fan-in."""

    def _mk_oob(self, job, teams, r, epoch=7):
        from ucc_tpu.core.oob import TransportOob
        svc = teams[r].service_team
        members = [int(teams[r].ctx_map.eval(i))
                   for i in range(teams[r].size)]
        return TransportOob(svc.comp_context, svc.transport, members,
                            teams[r].context.rank,
                            ("test", teams[r].team_key), epoch)

    @pytest.mark.parametrize("n", [2, 5, 9])
    def test_allgather(self, n):
        import sys
        sys.path.insert(0, __file__.rsplit("/", 1)[0])
        from harness import UccJob
        from ucc_tpu import Status
        job = UccJob(n)
        try:
            teams = job.create_team()
            oobs = [self._mk_oob(job, teams, r) for r in range(n)]
            payloads = [b"" if r == 1 else f"tp-{r}".encode() * (r + 1)
                        for r in range(n)]
            reqs = [oobs[r].allgather(payloads[r]) for r in range(n)]
            # list comprehension, NOT a short-circuiting generator:
            # interior tree members forward inside test(), so every
            # member must be polled (the shrink drivers' contract)
            job.progress_until(lambda: all(
                [rq.test() != Status.IN_PROGRESS for rq in reqs]))
            for rq in reqs:
                assert rq.result == payloads
            # second round on the same oob instances (round_idx keying)
            reqs = [oobs[r].allgather(f"r2-{r}".encode())
                    for r in range(n)]
            job.progress_until(lambda: all(
                [rq.test() != Status.IN_PROGRESS for rq in reqs]))
            for rq in reqs:
                assert rq.result == [f"r2-{x}".encode() for x in range(n)]
        finally:
            job.cleanup()
