"""End-to-end data integrity (UCC_INTEGRITY; ISSUE 19).

Wire crc32 at the match boundary in BOTH matchers and BOTH match
orders (posted-recv-first direct delivery, unexpected eager and rndv),
end-to-end detection with sender attribution through the collective
stack (classic algorithms and native execution plans), sampled result
attestation with minority attribution on 4- and 8-rank teams, strike
escalation into quarantine + shrink via the corruption-storm drill,
rejoin-after-quarantine with a clean strike slate, the off-mode
zero-cost contract, and UCC_QUANT composition.
"""
import time
import zlib

import numpy as np
import pytest

from ucc_tpu import (BufferInfo, CollArgs, CollArgsFlags, CollType,
                     DataType, MemoryType, ReductionOp, Status)
from ucc_tpu import integrity
from ucc_tpu.fault import health, inject
from ucc_tpu.status import DataCorruptedError
from ucc_tpu.tl.host.transport import Mailbox, RecvReq

from harness import UccJob

native_available = False
try:
    from ucc_tpu.native import NativeMailbox, available
    native_available = available()
except Exception:  # noqa: BLE001 - toolchain-less machines
    pass

needs_native = pytest.mark.skipif(not native_available,
                                  reason="native core unavailable")


@pytest.fixture(autouse=True)
def _clean():
    inject.reset()
    integrity.reset()
    yield
    inject.reset()
    integrity.reset()
    health.reset()


def _key(src=3, tag=7):
    # (team_key, epoch, tag, slot, sender ctx rank) — the 5-tuple both
    # matchers key on; key[4] is the attribution the verifier reads
    return ("itest", 0, (1 << 20) + tag, 5, src)


def _corrupted(n=64):
    clean = np.arange(n, dtype=np.uint8)
    crc = zlib.crc32(clean) & 0xFFFFFFFF
    bad = clean.copy()
    bad[n // 2] ^= 0xFF
    return bad, crc


# ---------------------------------------------------------------------------
# wire checksum at the match boundary: python matcher, both orders
# ---------------------------------------------------------------------------

class TestWireMatchBoundaryPython:
    def test_recv_first_direct_delivery(self):
        integrity.configure(mode="wire")
        mb = Mailbox()
        rq = RecvReq(np.zeros(64, np.uint8))
        mb.post_recv(_key(), rq)
        bad, crc = _corrupted()
        sreq, kind = mb.send(_key(), bad, 8192, crc=crc)
        assert kind == "direct" and rq.done
        assert "crc32 mismatch" in rq.error
        assert rq.corrupt_src == 3

    def test_send_first_unexpected_eager(self):
        integrity.configure(mode="wire")
        mb = Mailbox()
        bad, crc = _corrupted()
        sreq, kind = mb.send(_key(src=2), bad, 8192, crc=crc)
        assert kind == "eager"
        rq = RecvReq(np.zeros(64, np.uint8))
        mb.post_recv(_key(src=2), rq)
        assert rq.done and "crc32 mismatch" in rq.error
        assert rq.corrupt_src == 2

    def test_send_first_unexpected_rndv(self):
        integrity.configure(mode="wire")
        mb = Mailbox()
        bad, crc = _corrupted(4096)
        sreq, kind = mb.send(_key(src=1), bad, 64, crc=crc)  # > eager cap
        assert kind == "rndv"
        rq = RecvReq(np.zeros(4096, np.uint8))
        mb.post_recv(_key(src=1), rq)
        assert rq.done and "crc32 mismatch" in rq.error
        assert rq.corrupt_src == 1

    def test_clean_payload_passes(self):
        # wire mode computes the crc at send when the caller passes none
        integrity.configure(mode="wire")
        mb = Mailbox()
        rq = RecvReq(np.zeros(64, np.uint8))
        mb.post_recv(_key(), rq)
        mb.send(_key(), np.arange(64, dtype=np.uint8), 8192)
        assert rq.done and rq.error is None and rq.corrupt_src is None

    def test_off_mode_unchecked_and_uncosted(self):
        # the off-mode contract: no checksum is computed (the parked
        # metadata stays None) and a corrupted frame is NOT flagged —
        # zero cost means zero checking, by design
        assert not integrity.ENABLED
        mb = Mailbox()
        bad, _ = _corrupted()
        mb.send(_key(src=9), bad, 8192)
        assert mb.unexpected[_key(src=9)][0].crc is None
        rq = RecvReq(np.zeros(64, np.uint8))
        mb.post_recv(_key(src=9), rq)
        assert rq.done and rq.error is None


# ---------------------------------------------------------------------------
# wire checksum at the match boundary: native (C) matcher, both orders
# ---------------------------------------------------------------------------

@needs_native
class TestWireMatchBoundaryNative:
    def _mb(self):
        mb = NativeMailbox()
        return mb

    def test_recv_first_direct_delivery(self):
        integrity.configure(mode="wire")
        mb = self._mb()
        try:
            rq = mb.post_recv_native(_key(), np.zeros(64, np.uint8))
            bad, crc = _corrupted()
            mb.push_native(_key(), bad, crc=crc)
            assert rq.test()
            assert rq.error and "crc32 mismatch" in rq.error
            assert rq.corrupt_src == 3
        finally:
            mb.destroy()

    def test_send_first_unexpected_eager(self):
        integrity.configure(mode="wire")
        mb = self._mb()
        try:
            bad, crc = _corrupted()
            mb.push_native(_key(src=2), bad, crc=crc)
            rq = mb.post_recv_native(_key(src=2), np.zeros(64, np.uint8))
            assert rq.test()
            assert rq.error and "crc32 mismatch" in rq.error
            assert rq.corrupt_src == 2
        finally:
            mb.destroy()

    def test_send_first_unexpected_rndv(self):
        integrity.configure(mode="wire")
        mb = self._mb()
        try:
            bad, crc = _corrupted(1 << 16)   # > eager cap: rndv park
            mb.push_native(_key(src=1), bad, crc=crc)
            rq = mb.post_recv_native(_key(src=1),
                                     np.zeros(1 << 16, np.uint8))
            assert rq.test()
            assert rq.error and "crc32 mismatch" in rq.error
            assert rq.corrupt_src == 1
        finally:
            mb.destroy()

    def test_clean_payload_computed_c_side(self):
        # armed mailbox + no caller crc: the C push computes the
        # checksum itself and the verify at delivery passes
        integrity.configure(mode="wire")
        mb = self._mb()
        try:
            rq = mb.post_recv_native(_key(), np.zeros(64, np.uint8))
            mb.push_native(_key(), np.arange(64, dtype=np.uint8))
            assert rq.test() and rq.error is None
            assert rq.corrupt_src is None
        finally:
            mb.destroy()

    def test_off_mode_unchecked(self):
        assert not integrity.ENABLED
        mb = self._mb()   # created with integrity off: never armed
        try:
            bad, _ = _corrupted()
            mb.push_native(_key(src=9), bad)
            rq = mb.post_recv_native(_key(src=9), np.zeros(64, np.uint8))
            assert rq.test() and rq.error is None
        finally:
            mb.destroy()

    def test_python_and_c_crc_agree(self):
        # the C table must be bit-identical to zlib.crc32, or mixed
        # python-sender/native-receiver paths would false-positive
        integrity.configure(mode="wire")
        mb = self._mb()
        try:
            data = np.frombuffer(bytes(range(256)) * 5, dtype=np.uint8)
            rq = mb.post_recv_native(_key(), np.zeros(data.size, np.uint8))
            mb.push_native(_key(), data.copy(),
                           crc=zlib.crc32(data) & 0xFFFFFFFF)
            assert rq.test() and rq.error is None
        finally:
            mb.destroy()


# ---------------------------------------------------------------------------
# end-to-end: corrupted collective fails with attribution, both matchers
# ---------------------------------------------------------------------------

def _drive_classify(job, rqs, deadline_s=10.0):
    """Drive requests to terminal; returns per-rank (status, ranks)
    where ranks is the corruption attribution (wire errors RETURN the
    status with task.corrupt_ranks set; attestation RAISES)."""
    done = [None] * len(rqs)
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline and any(d is None for d in done):
        for c in job.contexts:
            c.progress()
        for i, rq in enumerate(rqs):
            if done[i] is not None:
                continue
            try:
                st = rq.test()
            except DataCorruptedError as e:
                done[i] = (Status.ERR_DATA_CORRUPTED, sorted(e.ranks))
                continue
            if st != Status.IN_PROGRESS:
                done[i] = (st, sorted(getattr(rq.task, "corrupt_ranks",
                                              ()) or ()))
    for i, rq in enumerate(rqs):
        if done[i] is None:
            rq.task.cancel(Status.ERR_TIMED_OUT)
            done[i] = (Status.IN_PROGRESS, [])
    return done


def _allreduce_args(rank, count, src, dst, timeout=2.0):
    return CollArgs(coll_type=CollType.ALLREDUCE,
                    src=BufferInfo(src, count, DataType.FLOAT32,
                                   MemoryType.HOST),
                    dst=BufferInfo(dst, count, DataType.FLOAT32,
                                   MemoryType.HOST),
                    op=ReductionOp.SUM, flags=CollArgsFlags.TIMEOUT,
                    timeout=timeout)


class TestWireCollective:
    @pytest.mark.parametrize("matcher", [
        pytest.param("native", marks=needs_native), "python"])
    def test_corruptor_detected_and_attributed(self, matcher, monkeypatch):
        if matcher == "python":
            monkeypatch.setenv("UCC_TL_SHM_NATIVE", "0")
        integrity.configure(mode="wire")
        n, count = 4, 1003
        job = UccJob(n)
        rqs = []
        try:
            teams = job.create_team()
            # armed only after team create: service colls stay clean
            inject.configure("corrupt=1.0,corrupt_rank=1", seed=3)
            ins = [np.full(count, i + 1.0, np.float32) for i in range(n)]
            outs = [np.zeros(count, np.float32) for _ in range(n)]
            for i, t in enumerate(teams):
                rq = t.collective_init(
                    _allreduce_args(i, count, ins[i], outs[i]))
                rq.post()
                rqs.append(rq)
            done = _drive_classify(job, rqs)
            hits = [d for d in done if d[0] == Status.ERR_DATA_CORRUPTED]
            assert hits, f"no rank detected the corruption: {done}"
            assert all(d[1] == [1] for d in hits), done
            # nobody may park: timeouts are acceptable collateral for
            # ranks starved of the corrupted contribution, hangs are not
            assert all(d[0] != Status.IN_PROGRESS for d in done), done
        finally:
            for rq in rqs:
                try:
                    rq.task.cancel()
                except Exception:  # noqa: BLE001
                    pass
            inject.reset()
            job.cleanup()


@needs_native
class TestPlanWireDetection:
    def test_native_plan_round_carries_checksums(self, monkeypatch):
        """The C executor's rounds never re-enter python — the entry-
        header checksum word must cover them: peers keep NATIVE PLANS
        (the pinned corruptor interprets, which is wire-compatible),
        the plan terminates ST_CORRUPT, and the harvested counter
        attributes the sender."""
        monkeypatch.setenv("UCC_GEN_NATIVE", "y")
        monkeypatch.setenv("UCC_TL_SHM_TUNE", "allreduce:@ring:inf")
        integrity.configure(mode="wire")
        n, count = 4, 1003
        job = UccJob(n)
        rqs = []
        try:
            teams = job.create_team()
            inject.configure("corrupt=1.0,corrupt_rank=1", seed=7)
            ins = [np.full(count, i + 1.0, np.float32) for i in range(n)]
            outs = [np.zeros(count, np.float32) for _ in range(n)]
            for i, t in enumerate(teams):
                rq = t.collective_init(
                    _allreduce_args(i, count, ins[i], outs[i]))
                rq.post()
                rqs.append(rq)
            done = _drive_classify(job, rqs)
            # probe BEFORE finalize releases the plans
            plans = [getattr(rq.task, "_plan", None) is not None
                     for rq in rqs]
            hits = [d for d in done if d[0] == Status.ERR_DATA_CORRUPTED]
            assert hits and all(d[1] == [1] for d in hits), done
            # candidate selection stayed rank-invariant: the corruptor
            # interpreted, at least one detector ran the C plan
            assert plans[1] is False
            assert any(plans[i] for i in (0, 2, 3)), plans
        finally:
            for rq in rqs:
                try:
                    rq.task.cancel()
                except Exception:  # noqa: BLE001
                    pass
            inject.reset()
            job.cleanup()


# ---------------------------------------------------------------------------
# verify mode: sampled cross-rank result attestation
# ---------------------------------------------------------------------------

def _complete_then_scribble(job, teams, n, count, victim):
    """Run an allreduce to task completion WITHOUT calling test() (so
    attestation has not started), then scribble *victim*'s result —
    modeling corruption past the wire (local reduce / memory)."""
    ins = [np.full(count, i + 1.0, np.float32) for i in range(n)]
    outs = [np.zeros(count, np.float32) for _ in range(n)]
    rqs = []
    for i, t in enumerate(teams):
        rq = t.collective_init(_allreduce_args(i, count, ins[i], outs[i],
                                               timeout=10.0))
        rq.post()
        rqs.append(rq)
    job.progress_until(lambda: all(
        rq.task.super_status != Status.IN_PROGRESS for rq in rqs))
    assert all(rq.task.super_status == Status.OK for rq in rqs)
    outs[victim][count // 2] = 999.0
    return rqs


class TestAttestation:
    @pytest.mark.parametrize("n", [4, 8])
    def test_minority_digest_names_corruptor(self, n):
        integrity.configure(mode="verify", sample=1, strikes=99)
        count = 256
        victim = n - 2
        job = UccJob(n)
        rqs = []
        try:
            teams = job.create_team()
            victim_ctx = teams[victim].context.rank
            rqs = _complete_then_scribble(job, teams, n, count, victim)
            done = _drive_classify(job, rqs)
            hits = [d for d in done if d[0] == Status.ERR_DATA_CORRUPTED]
            # every member compares digests; the minority (1 vs n-1)
            # names the corruptor on all of them, including itself
            assert len(hits) == n, done
            assert all(d[1] == [victim_ctx] for d in hits), done
            # each context charged one strike against the offender
            for t in teams:
                assert integrity.strikes(t.context, victim_ctx) == 1
        finally:
            for rq in rqs:
                try:
                    rq.task.cancel()
                except Exception:  # noqa: BLE001
                    pass
            job.cleanup()

    def test_strikes_escalate_to_quarantine(self):
        # strike budget 1: the first attested mismatch quarantines the
        # offender in every member's health registry
        health.configure("shrink", interval=0.05, timeout=2.0)
        integrity.configure(mode="verify", sample=1, strikes=1)
        n, count, victim = 4, 256, 2
        job = UccJob(n)
        rqs = []
        try:
            teams = job.create_team()
            victim_ctx = teams[victim].context.rank
            rqs = _complete_then_scribble(job, teams, n, count, victim)
            _drive_classify(job, rqs)
            for i, t in enumerate(teams):
                if i == victim:
                    continue   # the corruptor never quarantines itself
                assert victim_ctx in t.context.health.dead_set(), \
                    f"rank {i} did not quarantine ctx {victim_ctx}"
        finally:
            for rq in rqs:
                try:
                    rq.task.cancel()
                except Exception:  # noqa: BLE001
                    pass
            job.cleanup()
            health.configure("none")

    def test_clean_results_attest_ok(self):
        # the happy path: digests agree, every rank reaches OK through
        # the attestation hook (poll-every-request exchange drives it)
        integrity.configure(mode="verify", sample=1, strikes=3)
        n, count = 4, 256
        job = UccJob(n)
        try:
            teams = job.create_team()
            ins = [np.full(count, i + 1.0, np.float32) for i in range(n)]
            outs = [np.zeros(count, np.float32) for _ in range(n)]
            rqs = []
            for i, t in enumerate(teams):
                rq = t.collective_init(
                    _allreduce_args(i, count, ins[i], outs[i],
                                    timeout=10.0))
                rq.post()
                rqs.append(rq)
            done = _drive_classify(job, rqs)
            assert all(d[0] == Status.OK for d in done), done
            expected = sum(i + 1.0 for i in range(n))
            for o in outs:
                assert np.allclose(o, expected)
        finally:
            job.cleanup()


# ---------------------------------------------------------------------------
# the full pipeline: storm -> strikes -> quarantine -> shrink -> resume
# ---------------------------------------------------------------------------

@needs_native
class TestCorruptionStormDrill:
    def test_drill_report_clean(self):
        from ucc_tpu.fault.soak import run_corrupt_soak
        report = run_corrupt_soak(n_ranks=4, corrupt_rank=1, strikes=2,
                                  pre_iters=2, post_iters=8,
                                  storm_rounds_max=6, count=128)
        assert report["violations"] == [], report
        assert report["quarantined"]
        assert report["rounds_to_quarantine"] == 2
        assert report["detections"] == report["storm_rounds"]
        assert report["plan_mode"]
        assert report["post_iters"] == 8
        # survivors converged on the corruptor as the dead set
        deads = {tuple(v["dead"]) for v in report["agreed"].values()}
        assert deads == {(report["corruptor"]["ctx_rank"],)}


# ---------------------------------------------------------------------------
# rejoin after quarantine (PR-17 membership path)
# ---------------------------------------------------------------------------

class TestRejoinAfterQuarantine:
    def test_quarantined_rank_rejoins_with_clean_slate(self):
        from ucc_tpu.core.team import Team
        health.configure("shrink", interval=0.05, timeout=2.0)
        integrity.configure(mode="verify", sample=1, strikes=2)
        n, count = 4, 64
        offender = 1
        job = UccJob(n)
        try:
            teams = job.create_team()
            offender_ctx = teams[offender].context.rank
            # trip the quarantine from rank 0's evidence (two wire
            # strikes at the verify-mode budget)
            ctx0 = teams[0].context
            integrity.note_wire_mismatch(ctx0, offender_ctx, "drill")
            integrity.note_wire_mismatch(ctx0, offender_ctx, "drill")
            assert offender_ctx in ctx0.health.dead_set()
            assert integrity.strikes(ctx0, offender_ctx) == 2

            # shrink it out (agreement floods rank 0's view)
            survivors = [r for r in range(n) if r != offender]
            shrinks = {r: teams[r].shrink_post() for r in survivors}
            # poll EVERY request each pass (membership test() drives
            # the OOB rebuild rounds; a short-circuit would deadlock)
            job.progress_until(lambda: all(
                st != Status.IN_PROGRESS
                for st in [shrinks[r].test() for r in survivors]),
                timeout=20.0)
            assert all(shrinks[r].test() == Status.OK for r in survivors)
            shrunk = {r: shrinks[r].new_team for r in survivors}

            # re-admit through grow + join; revive clears the ledger
            grows = {r: shrunk[r].grow_post([offender_ctx])
                     for r in survivors}
            join = Team.join_post(job.contexts[offender])
            reqs = list(grows.values()) + [join]
            job.progress_until(lambda: all(
                st != Status.IN_PROGRESS
                for st in [rq.test() for rq in reqs]), timeout=30.0)
            assert all(rq.test() == Status.OK for rq in reqs)
            assert offender_ctx not in ctx0.health.dead_set()
            assert integrity.strikes(ctx0, offender_ctx) == 0

            # the rebuilt full team passes a checked allreduce
            grown = [grows[r].new_team for r in survivors]
            order = sorted(survivors) + [offender]
            full = {r: (grown[survivors.index(r)] if r in survivors
                        else join.new_team) for r in order}
            ins = [np.full(count, r + 1.0, np.float32) for r in range(n)]
            outs = [np.zeros(count, np.float32) for _ in range(n)]
            rqs = []
            for r in order:
                rq = full[r].collective_init(_allreduce_args(
                    full[r].rank, count, ins[r], outs[r], timeout=10.0))
                rq.post()
                rqs.append(rq)
            done = _drive_classify(job, rqs)
            assert all(d[0] == Status.OK for d in done), done
            expected = sum(r + 1.0 for r in range(n))
            for o in outs:
                assert np.allclose(o, expected)
            for t in list(full.values()):
                t.destroy()
            for t in shrunk.values():
                t.destroy()
        finally:
            job.cleanup()
            health.configure("none")


# ---------------------------------------------------------------------------
# composition: UCC_QUANT + UCC_INTEGRITY
# ---------------------------------------------------------------------------

class TestQuantCompose:
    def test_quantized_allreduce_under_verify(self, monkeypatch):
        """Quantized wire traffic checksums the ENCODED bytes and the
        deterministic codec yields bit-identical dequantized results on
        every rank — so verify-mode attestation agrees and the
        collective lands OK within the quant error budget."""
        monkeypatch.setenv("UCC_QUANT", "int8")
        integrity.configure(mode="verify", sample=1, strikes=3)
        n, count = 4, 32 << 10   # >=64k payload range: quant engages
        job = UccJob(n)
        try:
            teams = job.create_team()
            rng = np.random.default_rng(5)
            ins = [rng.standard_normal(count).astype(np.float32)
                   for _ in range(n)]
            outs = [np.zeros(count, np.float32) for _ in range(n)]
            rqs = []
            for i, t in enumerate(teams):
                rq = t.collective_init(
                    _allreduce_args(i, count, ins[i], outs[i],
                                    timeout=20.0))
                rq.post()
                rqs.append(rq)
            done = _drive_classify(job, rqs, deadline_s=30.0)
            assert all(d[0] == Status.OK for d in done), done
            exact = np.sum(ins, axis=0)
            scale = np.max(np.abs(exact)) or 1.0
            for o in outs:
                assert np.max(np.abs(o - exact)) / scale < 0.05
        finally:
            job.cleanup()
