"""Pipeline-parallel example: microbatches stream through per-device
stages via ops.ring_shift inside one jitted schedule."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from ucc_tpu.examples.pipeline_parallel import (make_pipeline,
                                                reference_pipeline)


@pytest.mark.parametrize("n_micro", [1, 3, 6])
def test_pipeline_matches_sequential(n_micro):
    n = 4
    if len(jax.devices()) < n:
        pytest.skip("needs >= 4 devices")
    mesh = jax.make_mesh((n,), ("pp",))
    b, d = 2, 8
    k1, k2 = jax.random.split(jax.random.PRNGKey(3))
    x = jax.random.normal(k1, (n_micro, b, d), jnp.float32)
    w = jax.random.normal(k2, (n, d, d), jnp.float32) * 0.3
    pipe = make_pipeline(mesh, n_micro)
    y = pipe(jax.device_put(x, NamedSharding(mesh, P(None))),
             jax.device_put(w, NamedSharding(mesh, P("pp"))))
    expect = reference_pipeline(x, w)
    np.testing.assert_allclose(np.asarray(y), expect, rtol=2e-4, atol=2e-5)
