"""Fault-tolerance layer: injection determinism, cancellation/abort
semantics, runtime score-map fallback, watchdog escalation, and the
no-hang soak (ISSUE 2 acceptance: >= 200 iterations of the collective
matrix under drop+delay+error injection with every rank reaching a
terminal status, and a ucc_stats dump with nonzero coll_cancelled /
coll_fallback_runtime counters)."""
import json
import time

import numpy as np
import pytest

import ucc_tpu
from ucc_tpu import (BufferInfo, CollArgs, CollArgsFlags, CollType,
                     DataType, ReductionOp, Status, UccError)
from ucc_tpu.fault import inject
from ucc_tpu.fault.soak import run_soak
from ucc_tpu.obs import metrics, watchdog
from ucc_tpu.schedule.progress import ProgressQueue
from ucc_tpu.schedule.schedule import Schedule
from ucc_tpu.schedule.task import CollTask

from harness import UccJob


@pytest.fixture(autouse=True)
def _clean_fault():
    inject.reset()
    yield
    inject.reset()


# ---------------------------------------------------------------------------
# spec parsing / zero-cost guarantees
# ---------------------------------------------------------------------------

class TestSpec:
    def test_disabled_by_default(self):
        assert not inject.ENABLED

    def test_parse_full(self):
        s = inject.parse_spec("drop=0.1,delay=0.2:0.005,error=0.3,"
                              "post_error=0.05,kill=2+5")
        assert s.drop == 0.1 and s.delay == 0.2 and s.delay_s == 0.005
        assert s.error == 0.3 and s.post_error == 0.05
        assert s.kill == {2, 5}
        assert s.active

    def test_parse_off(self):
        for spec in ("", "n", "off", "0"):
            assert not inject.parse_spec(spec).active

    def test_unknown_key_raises(self):
        with pytest.raises(ValueError):
            inject.parse_spec("dorp=0.1")

    def test_bad_probability_raises(self):
        with pytest.raises(ValueError):
            inject.parse_spec("drop=1.5")

    def test_configure_enables_and_reset_disables(self):
        inject.configure("drop=0.5", seed=1)
        assert inject.ENABLED
        inject.reset()
        assert not inject.ENABLED

    def test_determinism(self):
        inject.configure("drop=0.3,error=0.2", seed=42)
        a = [inject.send_action() for _ in range(200)]
        inject.configure("drop=0.3,error=0.2", seed=42)
        b = [inject.send_action() for _ in range(200)]
        assert a == b
        assert "drop" in a and "error" in a


# ---------------------------------------------------------------------------
# cancellation semantics
# ---------------------------------------------------------------------------

class _HangTask(CollTask):
    """Never completes on its own; records cancel_fn calls."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.cancel_fn_calls = 0

    def post_fn(self):
        return Status.OK

    def progress_fn(self):
        pass

    def cancel_fn(self):
        self.cancel_fn_calls += 1


class TestCancel:
    def test_cancel_completes_with_status(self):
        t = _HangTask()
        t.post()
        assert t.super_status == Status.IN_PROGRESS
        t.cancel()
        assert t.super_status == Status.ERR_CANCELED
        assert t.cancel_fn_calls == 1

    def test_cancel_idempotent(self):
        t = _HangTask()
        t.post()
        t.cancel(Status.ERR_TIMED_OUT)
        t.cancel()
        assert t.super_status == Status.ERR_TIMED_OUT
        assert t.cancel_fn_calls == 1

    def test_cancel_after_complete_is_noop(self):
        t = _HangTask()
        t.post()
        t.complete(Status.OK)
        t.cancel()
        assert t.super_status == Status.OK
        assert t.cancel_fn_calls == 0

    def test_schedule_cancel_propagates_status_to_children(self):
        sched = Schedule()
        kids = [_HangTask(), _HangTask()]
        for k in kids:
            sched.add_task(k)
        sched.post()
        for k in kids:
            k.post()
        sched.cancel(Status.ERR_TIMED_OUT)
        assert sched.super_status == Status.ERR_TIMED_OUT
        for k in kids:
            assert k.super_status == Status.ERR_TIMED_OUT
            assert k.cancel_fn_calls == 1

    def test_progress_queue_timeout_cancels(self):
        q = ProgressQueue()
        t = _HangTask()
        t.timeout = 0.01
        t.progress_queue = q
        t.post()
        time.sleep(0.02)
        q.progress()
        assert t.super_status == Status.ERR_TIMED_OUT
        assert t.cancel_fn_calls == 1
        assert len(q) == 0

    def test_host_task_cancel_unwinds_posted_ops(self):
        """Cancelling rank 0's collective withdraws its posted recvs
        (mailbox skips cancelled entries) and closes the generator."""
        job = UccJob(2)
        try:
            teams = job.create_team()
            count = 8
            dst = np.zeros(count, np.float64)
            # only rank 0 posts: its recv from rank 1 can never match
            req = teams[0].collective_init(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(np.ones(count), count, DataType.FLOAT64),
                dst=BufferInfo(dst, count, DataType.FLOAT64),
                op=ReductionOp.SUM))
            req.post()
            for _ in range(10):
                job.contexts[0].progress()
            assert req.test() == Status.IN_PROGRESS
            req.task.cancel()
            assert req.test() == Status.ERR_CANCELED
            req.finalize()
        finally:
            job.cleanup()


# ---------------------------------------------------------------------------
# watchdog escalation ladder
# ---------------------------------------------------------------------------

class TestWatchdogEscalation:
    @pytest.fixture(autouse=True)
    def _wd(self, tmp_path):
        watchdog.reset()
        watchdog.configure(0.03, file=str(tmp_path / "wd.json"),
                           action="cancel", hard_timeout=0.06)
        yield
        watchdog.configure(0, action="dump")
        watchdog.reset()

    def test_bad_action_rejected(self):
        with pytest.raises(ValueError):
            watchdog.configure(1, action="explode")

    def test_cancel_at_hard_deadline(self):
        q = ProgressQueue()
        t = _HangTask()
        t.progress_queue = q
        t.post()
        deadline = time.monotonic() + 5
        while not t.is_completed():
            q.progress()
            watchdog._last_scan = 0.0   # defeat the 1s scan throttle
            assert time.monotonic() < deadline, "escalation never fired"
            time.sleep(0.005)
        assert t.super_status == Status.ERR_TIMED_OUT
        assert t.cancel_fn_calls == 1

    def test_abort_cancels_all_in_flight(self):
        watchdog.configure(0.03, action="abort", hard_timeout=0.06)
        q = ProgressQueue()
        old = _HangTask()
        old.progress_queue = q
        old.post()
        time.sleep(0.08)
        fresh = _HangTask()          # NOT past the hard deadline
        fresh.progress_queue = q
        fresh.post()
        watchdog._last_scan = 0.0
        q.progress()
        assert old.super_status == Status.ERR_TIMED_OUT
        assert fresh.super_status == Status.ERR_TIMED_OUT

    def test_dump_action_never_cancels(self):
        watchdog.configure(0.02, action="dump")
        q = ProgressQueue()
        t = _HangTask()
        t.progress_queue = q
        t.post()
        time.sleep(0.08)
        watchdog._last_scan = 0.0
        q.progress()
        assert t.super_status == Status.IN_PROGRESS
        t.cancel()


# ---------------------------------------------------------------------------
# runtime score-map fallback
# ---------------------------------------------------------------------------

class TestRuntimeFallback:
    def test_precommit_failure_retries_next_candidate(self):
        """Force the winning algorithm to fail before any send: the
        request must swap to the next candidate invisibly and the
        collective must still produce the right answer."""
        job = UccJob(4)
        inject.reset()
        try:
            teams = job.create_team()
            count = 16
            srcs = [np.full(count, r + 1.0, np.float64) for r in range(4)]
            dsts = [np.zeros(count, np.float64) for _ in range(4)]
            reqs = [teams[r].collective_init(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(srcs[r], count, DataType.FLOAT64),
                dst=BufferInfo(dsts[r], count, DataType.FLOAT64),
                op=ReductionOp.SUM)) for r in range(4)]
            assert all(rq._fallback for rq in reqs), \
                "allreduce should have fallback candidates"
            # fail every first-chosen task before it commits data
            for rq in reqs:
                rq.task.post_fn = lambda: Status.ERR_NO_RESOURCE
            first_algs = [rq.task.alg_name for rq in reqs]
            for rq in reqs:
                rq.post()
            # list, not generator: test() is what performs the fallback
            # re-post, so every rank must be polled each pass
            job.progress_until(lambda: all(
                [rq.test() != Status.IN_PROGRESS for rq in reqs]))
            for r, rq in enumerate(reqs):
                assert rq.test() == Status.OK, rq.test()
                assert rq._fb_used
                assert rq.task.alg_name != first_algs[r]
                np.testing.assert_allclose(dsts[r], 10.0)
        finally:
            job.cleanup()

    def test_committed_failure_does_not_retry(self):
        t = _HangTask()
        t.data_committed = True
        from ucc_tpu.core.coll import CollRequest
        req = CollRequest.__new__(CollRequest)
        req.task = t
        req._posted = True
        req._persistent = False
        req._fallback = (None, [object()])
        req._fb_used = False
        t.post()
        t.complete(Status.ERR_NO_RESOURCE)
        assert not req._try_runtime_fallback()

    def test_timed_out_failure_does_not_retry(self):
        t = _HangTask()
        t.data_committed = False
        from ucc_tpu.core.coll import CollRequest
        req = CollRequest.__new__(CollRequest)
        req.task = t
        req._posted = True
        req._persistent = False
        req._fallback = (None, [object()])
        req._fb_used = False
        t.post()
        t.complete(Status.ERR_TIMED_OUT)
        assert not req._try_runtime_fallback()


# ---------------------------------------------------------------------------
# no-hang invariant: rank kill
# ---------------------------------------------------------------------------

class TestNoHangOnRankKill:
    def test_killed_rank_leaves_peers_terminal(self):
        """A rank killed mid-collective (all its sends dropped, its
        posts failing) must leave every peer at a terminal status within
        the collective deadline — nobody parks IN_PROGRESS forever."""
        job = UccJob(3)
        try:
            teams = job.create_team()
            killed_ctx_rank = job.contexts[2].rank
            inject.configure(f"kill={killed_ctx_rank}", seed=0)
            count = 8
            dsts = [np.zeros(count, np.float64) for _ in range(3)]
            reqs = [teams[r].collective_init(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(np.ones(count), count, DataType.FLOAT64),
                dst=BufferInfo(dsts[r], count, DataType.FLOAT64),
                op=ReductionOp.SUM, flags=CollArgsFlags.TIMEOUT,
                timeout=0.5)) for r in range(3)]
            for rq in reqs:
                rq.post()
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                for c in job.contexts:
                    c.progress()
                if all(rq.test() != Status.IN_PROGRESS for rq in reqs):
                    break
            sts = [rq.test() for rq in reqs]
            assert all(s != Status.IN_PROGRESS for s in sts), sts
            assert all(s.is_error for s in sts), sts
            inject.reset()
            for rq in reqs:
                rq.finalize()
        finally:
            inject.reset()
            job.cleanup()


# ---------------------------------------------------------------------------
# the acceptance soak
# ---------------------------------------------------------------------------

class TestSoak:
    def test_soak_no_hang_with_stats(self, tmp_path):
        """ISSUE-2 acceptance: >= 200 iterations of the collective
        matrix under drop+delay+error (+post_error for the runtime-
        fallback path) with zero ranks left IN_PROGRESS, plus a
        ucc_stats dump whose coll_cancelled and coll_fallback_runtime
        counters are nonzero."""
        stats_file = tmp_path / "soak_stats.json"
        metrics.reset()
        metrics.enable(file=str(stats_file))
        try:
            report = run_soak(
                n_ranks=4, iterations=200,
                spec="drop=0.01,delay=0.05:0.003,error=0.02,"
                     "post_error=0.01",
                seed=7, coll_timeout_s=0.4, iter_deadline_s=10.0)
            assert report["hangs"] == [], report["hangs"]
            assert report["iterations"] == 200
            # the drill actually injected every armed fault kind
            for kind in ("drop", "delay", "error", "post_error"):
                assert report["injected"][kind] > 0, report["injected"]
            metrics.dump(str(stats_file), reason="soak")
        finally:
            metrics.disable()
        snap = json.loads(stats_file.read_text().strip().splitlines()[-1])
        counters = snap["counters"]
        assert sum(counters.get("coll_cancelled", {}).values()) > 0, \
            "no cancellations recorded — drops did not exercise the " \
            "timeout->cancel ladder"
        assert sum(counters.get("coll_fallback_runtime", {}).values()) > 0, \
            "no runtime fallbacks recorded"
        metrics.reset()

    def test_soak_deterministic(self):
        kw = dict(n_ranks=2, iterations=12, spec="drop=0.05,error=0.05",
                  seed=3, coll_timeout_s=0.3, iter_deadline_s=6.0)
        a = run_soak(**kw)
        b = run_soak(**kw)
        assert a["injected"] == b["injected"]
        assert a["outcomes"] == b["outcomes"]
        assert a["hangs"] == b["hangs"] == []
