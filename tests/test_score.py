"""Selection-engine tests — mirrors reference gtest coll_score suites
(test/gtest/coll_score/test_score.cc, test_score_update.cc)."""
import pytest

from ucc_tpu.constants import CollType, MemoryType
from ucc_tpu.score import (CollScore, ScoreMap, SCORE_MAX, parse_tune_str)
from ucc_tpu.status import Status, UccError
from ucc_tpu.utils.config import SIZE_INF


def mkinit(tag):
    def init(args, team):
        return (tag, args, team)
    return init


class TestCollScore:
    def test_add_and_lookup(self):
        s = CollScore()
        assert s.add_range(CollType.ALLREDUCE, MemoryType.HOST, 0, 4096, 10,
                           mkinit("kn"), "teamA", "knomial") == Status.OK
        s.add_range(CollType.ALLREDUCE, MemoryType.HOST, 4096, SIZE_INF, 20,
                    mkinit("ring"), "teamA", "ring")
        m = ScoreMap(s)
        assert m.lookup(CollType.ALLREDUCE, MemoryType.HOST, 100)[0].alg_name == "knomial"
        assert m.lookup(CollType.ALLREDUCE, MemoryType.HOST, 1 << 20)[0].alg_name == "ring"
        assert m.lookup(CollType.BCAST, MemoryType.HOST, 100) == []

    def test_invalid_range(self):
        s = CollScore()
        assert s.add_range(CollType.BCAST, MemoryType.HOST, 10, 10, 5) == \
            Status.ERR_INVALID_PARAM

    def test_merge_max_score_wins(self):
        a = CollScore.build_default("tl_a", 10, [CollType.ALLREDUCE],
                                    [MemoryType.HOST], mkinit("a"), "alg_a")
        b = CollScore.build_default("tl_b", 40, [CollType.ALLREDUCE],
                                    [MemoryType.HOST], mkinit("b"), "alg_b")
        m = ScoreMap(a.merge(b))
        cands = m.lookup(CollType.ALLREDUCE, MemoryType.HOST, 123)
        assert [c.alg_name for c in cands] == ["alg_b", "alg_a"]

    def test_fallback_walk(self):
        def unsupported_init(args, team):
            raise UccError(Status.ERR_NOT_SUPPORTED)

        a = CollScore.build_default("tl_a", 10, [CollType.ALLREDUCE],
                                    [MemoryType.HOST], mkinit("a"), "alg_a")
        b = CollScore.build_default("tl_b", 40, [CollType.ALLREDUCE],
                                    [MemoryType.HOST], unsupported_init, "alg_b")
        m = ScoreMap(a.merge(b))
        task, rng = m.init_coll(CollType.ALLREDUCE, MemoryType.HOST, 8, "args")
        assert task[0] == "a" and rng.alg_name == "alg_a"

    def test_no_candidates_raises(self):
        m = ScoreMap(CollScore())
        with pytest.raises(UccError) as ei:
            m.init_coll(CollType.BARRIER, MemoryType.HOST, 0, None)
        assert ei.value.status == Status.ERR_NOT_SUPPORTED


class TestTuneParser:
    def test_full_section(self):
        secs = parse_tune_str("allreduce:0-4k:@knomial:inf#bcast:host:50")
        assert len(secs) == 2
        s0, s1 = secs
        assert s0.colls == [CollType.ALLREDUCE]
        assert s0.msg_ranges == [(0, 4096)]
        assert s0.alg == "knomial" and s0.score == SCORE_MAX
        assert s1.colls == [CollType.BCAST]
        assert s1.mems == [MemoryType.HOST]
        assert s1.score == 50

    def test_coll_list_and_ranges(self):
        secs = parse_tune_str("allreduce,bcast:4k-inf:30")
        assert secs[0].colls == [CollType.ALLREDUCE, CollType.BCAST]
        assert secs[0].msg_ranges == [(4096, SIZE_INF)]

    def test_numeric_alg_id(self):
        secs = parse_tune_str("allreduce:0-4k:@1")
        assert secs[0].alg == "1"

    def test_bad_token(self):
        with pytest.raises(ValueError):
            parse_tune_str("allreduce:whatever_this_is")

    def test_cuda_memtype_aliases_to_tpu(self):
        secs = parse_tune_str("allreduce:cuda:10")
        assert secs[0].mems == [MemoryType.TPU]


class TestUpdateFromStr:
    def _score(self):
        s = CollScore()
        s.add_range(CollType.ALLREDUCE, MemoryType.HOST, 0, SIZE_INF, 10,
                    mkinit("kn"), "tl_x", "knomial")
        return s

    def test_score_override_splits_range(self):
        s = self._score()
        assert s.update_from_str("allreduce:0-4k:inf") == Status.OK
        m = ScoreMap(s)
        assert m.lookup(CollType.ALLREDUCE, MemoryType.HOST, 100)[0].score == SCORE_MAX
        assert m.lookup(CollType.ALLREDUCE, MemoryType.HOST, 1 << 20)[0].score == 10

    def test_disable_with_zero(self):
        # reference idiom: UCC_TL_X_TUNE=allreduce:0 disables the coll
        s = self._score()
        s.update_from_str("allreduce:0")
        m = ScoreMap(s)
        assert m.lookup(CollType.ALLREDUCE, MemoryType.HOST, 100) == []

    def test_alg_switch(self):
        s = self._score()

        def resolver(coll, alg):
            assert coll == CollType.ALLREDUCE
            return mkinit("ring") if alg == "ring" else None

        assert s.update_from_str("allreduce:4k-inf:@ring", resolver) == Status.OK
        m = ScoreMap(s)
        lo = m.lookup(CollType.ALLREDUCE, MemoryType.HOST, 8)[0]
        hi = m.lookup(CollType.ALLREDUCE, MemoryType.HOST, 1 << 20)[0]
        assert lo.alg_name == "knomial" and hi.alg_name == "ring"
        task, _ = m.init_coll(CollType.ALLREDUCE, MemoryType.HOST, 1 << 20, "a")
        assert task[0] == "ring"

    def test_unknown_alg_is_error(self):
        s = self._score()
        assert s.update_from_str("allreduce:@nope", lambda c, a: None) == \
            Status.ERR_INVALID_PARAM

    def test_malformed_is_error(self):
        s = self._score()
        assert s.update_from_str("allreduce:gibber ish") == \
            Status.ERR_INVALID_PARAM

    def test_untouched_colls_unaffected(self):
        s = self._score()
        s.add_range(CollType.BCAST, MemoryType.HOST, 0, SIZE_INF, 7,
                    mkinit("b"), "tl_x", "bkn")
        s.update_from_str("allreduce:0")
        m = ScoreMap(s)
        assert m.lookup(CollType.BCAST, MemoryType.HOST, 100)[0].score == 7

    def test_print_info(self):
        m = ScoreMap(self._score())
        info = m.print_info("t0")
        assert "allreduce/host" in info and "knomial:10" in info


class TestDeterministicTieBreak:
    """ISSUE 5 satellite: equal-score candidates must order by content
    (score desc, then alg name, then component, then registration), not
    construction history — a cross-rank divergence in that order makes
    ranks pick different algorithms for one collective and deadlocks."""

    def _map_with_insertion(self, names):
        s = CollScore()
        for nm in names:
            s.add_range(CollType.ALLREDUCE, MemoryType.HOST, 0, SIZE_INF,
                        10, mkinit(nm), "tl_x", nm)
        return ScoreMap(s)

    def test_equal_score_orders_by_name_not_insertion(self):
        m1 = self._map_with_insertion(["zeta", "alpha"])
        m2 = self._map_with_insertion(["alpha", "zeta"])
        l1 = [c.alg_name for c in
              m1.lookup(CollType.ALLREDUCE, MemoryType.HOST, 100)]
        l2 = [c.alg_name for c in
              m2.lookup(CollType.ALLREDUCE, MemoryType.HOST, 100)]
        assert l1 == l2 == ["alpha", "zeta"]

    def test_two_equal_score_ranges_regression(self):
        # the satellite's regression shape: two candidates carrying two
        # equal-score ranges each, inserted in opposite orders — every
        # lookup point must agree on the full candidate order
        def build(order):
            s = CollScore()
            for nm in order:
                s.add_range(CollType.BCAST, MemoryType.HOST, 0, 4096, 7,
                            mkinit(nm), "tl_x", nm)
                s.add_range(CollType.BCAST, MemoryType.HOST, 4096,
                            SIZE_INF, 7, mkinit(nm), "tl_x", nm)
            return ScoreMap(s)

        a = build(["ring", "knomial"])
        b = build(["knomial", "ring"])
        for msg in (128, 1 << 20):
            la = [c.alg_name for c in
                  a.lookup(CollType.BCAST, MemoryType.HOST, msg)]
            lb = [c.alg_name for c in
                  b.lookup(CollType.BCAST, MemoryType.HOST, msg)]
            assert la == lb == ["knomial", "ring"]

    def test_score_still_dominates_name(self):
        s = CollScore()
        s.add_range(CollType.ALLREDUCE, MemoryType.HOST, 0, SIZE_INF, 5,
                    mkinit("alpha"), "tl_x", "alpha")
        s.add_range(CollType.ALLREDUCE, MemoryType.HOST, 0, SIZE_INF, 50,
                    mkinit("zeta"), "tl_x", "zeta")
        m = ScoreMap(s)
        assert m.lookup(CollType.ALLREDUCE, MemoryType.HOST,
                        10)[0].alg_name == "zeta"


class TestTuneDslEdges:
    """ISSUE 5 satellite: parse_tune_str / update_from_str edge cases."""

    def _score(self):
        s = CollScore()
        s.add_range(CollType.ALLREDUCE, MemoryType.HOST, 0, SIZE_INF, 10,
                    mkinit("kn"), "tl_x", "knomial")
        return s

    def test_overlapping_updates_split_at_boundaries(self):
        s = self._score()
        assert s.update_from_str("allreduce:0-8k:20") == Status.OK
        assert s.update_from_str("allreduce:4k-16k:30") == Status.OK
        m = ScoreMap(s)

        def score_at(msg):
            return m.lookup(CollType.ALLREDUCE, MemoryType.HOST, msg)[0].score

        assert score_at(2 << 10) == 20       # [0,4k) keeps first overlay
        assert score_at(6 << 10) == 30       # [4k,8k) split by second
        assert score_at(12 << 10) == 30      # [8k,16k)
        assert score_at(1 << 20) == 10       # untouched tail

    def test_multiple_ranges_one_section(self):
        s = self._score()
        assert s.update_from_str("allreduce:0-1k:4k-8k:99") == Status.OK
        m = ScoreMap(s)
        assert m.lookup(CollType.ALLREDUCE, MemoryType.HOST, 512)[0].score == 99
        assert m.lookup(CollType.ALLREDUCE, MemoryType.HOST, 2048)[0].score == 10
        assert m.lookup(CollType.ALLREDUCE, MemoryType.HOST, 6144)[0].score == 99

    def test_inf_forces_over_higher_default(self):
        s = self._score()
        s.add_range(CollType.ALLREDUCE, MemoryType.HOST, 0, SIZE_INF, 90,
                    mkinit("ring"), "tl_x", "ring")

        def resolver(coll, alg):
            return mkinit("kn2") if alg == "knomial" else None

        assert s.update_from_str("allreduce:0-4k:@knomial:inf",
                                 resolver) == Status.OK
        m = ScoreMap(s)
        lo = m.lookup(CollType.ALLREDUCE, MemoryType.HOST, 100)
        assert lo[0].score == SCORE_MAX
        task, _ = m.init_coll(CollType.ALLREDUCE, MemoryType.HOST, 100, "a")
        assert task[0] == "kn2"
        hi = m.lookup(CollType.ALLREDUCE, MemoryType.HOST, 1 << 20)
        assert hi[0].alg_name == "ring"      # outside the forced window

    def test_score_zero_disables_subrange_only(self):
        s = self._score()
        assert s.update_from_str("allreduce:4k-inf:0") == Status.OK
        m = ScoreMap(s)
        assert m.lookup(CollType.ALLREDUCE, MemoryType.HOST, 100) != []
        assert m.lookup(CollType.ALLREDUCE, MemoryType.HOST, 1 << 20) == []

    @pytest.mark.parametrize("bad", [
        "allreduce:@a:@b",            # duplicate alg token
        "allreduce:-5",               # negative score
        "allreduce:4k-x1",            # unparseable range bound
        "allreduce:not a token",      # garbage
    ])
    def test_malformed_tokens_raise_and_error(self, bad):
        with pytest.raises(ValueError):
            parse_tune_str(bad)
        s = self._score()
        assert s.update_from_str(bad) == Status.ERR_INVALID_PARAM

    def test_empty_sections_are_skipped(self):
        assert parse_tune_str("##  #") == []


class TestProvenance:
    """ISSUE 5 satellite: print_info marks every range with why it won
    (default | tune-str | learned), surfaced via team logs/ucc_info -s."""

    def test_origins_tracked_and_printed(self):
        s = CollScore()
        s.add_range(CollType.ALLREDUCE, MemoryType.HOST, 0, SIZE_INF, 10,
                    mkinit("kn"), "tl_x", "knomial")
        s.add_range(CollType.ALLREDUCE, MemoryType.HOST, 0, SIZE_INF, 5,
                    mkinit("ring"), "tl_x", "ring")
        assert s.update_from_str("allreduce:0-4k:20") == Status.OK
        m = ScoreMap(s)
        assert m.apply_learned(CollType.ALLREDUCE, MemoryType.HOST,
                               4096, 1 << 20, "ring")
        info = m.print_info("t0")
        assert "(default)" in info
        assert "(tune-str)" in info
        assert "(learned)" in info
        # and the learned promotion actually wins inside its window only
        win = m.lookup(CollType.ALLREDUCE, MemoryType.HOST, 64 << 10)[0]
        assert win.alg_name == "ring" and win.origin == "learned"
        out = m.lookup(CollType.ALLREDUCE, MemoryType.HOST, 2 << 20)[0]
        assert out.alg_name == "knomial"

    def test_apply_learned_unknown_alg_is_noop(self):
        s = CollScore()
        s.add_range(CollType.ALLREDUCE, MemoryType.HOST, 0, SIZE_INF, 10,
                    mkinit("kn"), "tl_x", "knomial")
        m = ScoreMap(s)
        assert not m.apply_learned(CollType.ALLREDUCE, MemoryType.HOST,
                                   0, 4096, "no_such_alg")
        assert m.lookup(CollType.ALLREDUCE, MemoryType.HOST,
                        100)[0].origin == "default"


class TestTopologyAwareAllgatherDefault:
    """The large-message allgather winner is topology-dependent, like
    the reference's dynamic score string (allgather.c:55-100)."""

    @staticmethod
    def _selected(teams, n, count):
        """Which algorithm the score map picks for a host allgather of
        ``count`` elements per rank (peek, no run)."""
        sm = teams[0].score_map
        cands = sm.lookup(CollType.ALLGATHER, MemoryType.HOST,
                          count * 8 * n)
        return cands[0].alg_name if cands else None

    def test_even_single_node_prefers_neighbor(self):
        from harness import UccJob
        job = UccJob(4)
        try:
            teams = job.create_team()
            assert self._selected(teams, 4, 64 << 10) == "neighbor"
        finally:
            job.cleanup()

    def test_odd_team_prefers_ring(self):
        from harness import UccJob
        job = UccJob(5)
        try:
            teams = job.create_team()
            assert self._selected(teams, 5, 64 << 10) == "ring"
        finally:
            job.cleanup()

    def test_multinode_reordered_prefers_ring(self, monkeypatch):
        """Even size BUT multi-node with a non-identity host-ordered
        map: ring keeps n-1 of n hops intra-node (use_reordering
        branch)."""
        from harness import UccJob
        monkeypatch.setenv("UCC_TOPO_FAKE_PPN", "2")
        job = UccJob(4)
        try:
            teams = job.create_team()
            assert self._selected(teams, 4, 64 << 10) == "ring"
        finally:
            job.cleanup()


class TestRanksReorderingKnob:
    """RANKS_REORDERING=n disables the FULL_HOST_ORDERED reorder — the
    multinode allgather default flips back to neighbor (even team), and
    ring algorithms run in natural rank order."""

    def test_knob_off_restores_neighbor_default(self, monkeypatch):
        from harness import UccJob
        monkeypatch.setenv("UCC_TOPO_FAKE_PPN", "2")
        monkeypatch.setenv("UCC_TL_SHM_RANKS_REORDERING", "n")
        job = UccJob(4)
        try:
            teams = job.create_team()
            sm = teams[0].score_map
            cands = sm.lookup(CollType.ALLGATHER, MemoryType.HOST,
                              64 << 13)
            assert cands[0].alg_name == "neighbor", cands[0].alg_name
        finally:
            job.cleanup()
