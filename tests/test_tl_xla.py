"""TL/XLA collective correctness on the virtual 8-device CPU mesh —
the TPU compute path (BASELINE configs[1-2]: allreduce/allgather/bcast/
barrier over the device mesh). Each UCC rank owns one jax device; buffers
are jax.Arrays (MemoryType.TPU convention: dst.buffer is rebound to the
result array)."""
import numpy as np
import pytest

import ucc_tpu
from ucc_tpu import (BufferInfo, BufferInfoV, CollArgs, CollArgsFlags,
                     CollType, DataType, MemoryType, ReductionOp, Status)

from harness import UccJob

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402


@pytest.fixture(scope="module")
def job():
    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 virtual devices")
    j = UccJob(4)
    yield j
    j.cleanup()


@pytest.fixture(scope="module")
def teams(job):
    return job.create_team()


def run_xla(job, teams, make_args):
    reqs = [t.collective_init(make_args(i)) for i, t in enumerate(teams)]
    for rq in reqs:
        rq.post()
    job.progress_until(lambda: all(
        rq.test() != Status.IN_PROGRESS for rq in reqs))
    for rq in reqs:
        assert rq.test() == Status.OK, rq.test()
    return reqs


def dev_array(job, rank, np_arr):
    dev = job.contexts[rank].tl_contexts["xla"].obj.device
    return jax.device_put(jnp.asarray(np_arr), dev)


def tpu_buf(job, rank, np_arr, dt):
    arr = dev_array(job, rank, np_arr)
    return BufferInfo(arr, int(np.prod(np_arr.shape)), dt,
                      mem_type=MemoryType.TPU)


class TestXlaAllreduce:
    @pytest.mark.parametrize("count", [8, 1000])
    def test_sum(self, job, teams, count):
        n = 4
        srcs = [np.full(count, r + 1.0, np.float32) for r in range(n)]
        argses = []
        for r in range(n):
            argses.append(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=tpu_buf(job, r, srcs[r], DataType.FLOAT32),
                dst=BufferInfo(None, count, DataType.FLOAT32,
                               mem_type=MemoryType.TPU),
                op=ReductionOp.SUM))
        run_xla(job, teams, lambda r: argses[r])
        for r in range(n):
            out = np.asarray(argses[r].dst.buffer)
            np.testing.assert_allclose(out, np.full(count, 10.0))

    def test_avg_bf16(self, job, teams):
        n = 4
        count = 64
        argses = []
        for r in range(n):
            src = (np.ones(count) * (r + 1)).astype(jnp.bfloat16)
            argses.append(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=tpu_buf(job, r, src, DataType.BFLOAT16),
                dst=BufferInfo(None, count, DataType.BFLOAT16,
                               mem_type=MemoryType.TPU),
                op=ReductionOp.AVG))
        run_xla(job, teams, lambda r: argses[r])
        for r in range(n):
            out = np.asarray(argses[r].dst.buffer).astype(np.float32)
            np.testing.assert_allclose(out, 2.5)

    @pytest.mark.parametrize("op,expect_fn", [
        (ReductionOp.MAX, lambda s: np.maximum.reduce(s)),
        (ReductionOp.PROD, lambda s: np.prod(np.stack(s), axis=0)),
        (ReductionOp.BOR, lambda s: np.bitwise_or.reduce(s)),
    ])
    def test_exotic_ops(self, job, teams, op, expect_fn):
        n = 4
        count = 16
        nd = np.int32
        srcs = [(np.arange(count) % 5 + r + 1).astype(nd) for r in range(n)]
        argses = [CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=tpu_buf(job, r, srcs[r], DataType.INT32),
            dst=BufferInfo(None, count, DataType.INT32,
                           mem_type=MemoryType.TPU),
            op=op) for r in range(n)]
        run_xla(job, teams, lambda r: argses[r])
        expect = expect_fn(srcs)
        for r in range(n):
            np.testing.assert_array_equal(np.asarray(argses[r].dst.buffer),
                                          expect)

    def test_ring_alg_via_tune(self, monkeypatch):
        monkeypatch.setenv("UCC_TL_XLA_TUNE", "allreduce:@ring:inf")
        job = UccJob(4)
        try:
            teams = job.create_team()
            count = 16   # divisible by 4 for the ring
            argses = [CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=tpu_buf(job, r, np.full(count, r + 1.0, np.float32),
                            DataType.FLOAT32),
                dst=BufferInfo(None, count, DataType.FLOAT32,
                               mem_type=MemoryType.TPU),
                op=ReductionOp.SUM) for r in range(4)]
            run_xla(job, teams, lambda r: argses[r])
            for r in range(4):
                np.testing.assert_allclose(
                    np.asarray(argses[r].dst.buffer), 10.0)
        finally:
            job.cleanup()


class TestXlaOtherColls:
    def test_allgather(self, job, teams):
        n, per = 4, 5
        srcs = [np.arange(per, dtype=np.float32) + 10 * r for r in range(n)]
        argses = [CollArgs(
            coll_type=CollType.ALLGATHER,
            src=tpu_buf(job, r, srcs[r], DataType.FLOAT32),
            dst=BufferInfo(None, per * n, DataType.FLOAT32,
                           mem_type=MemoryType.TPU)) for r in range(n)]
        run_xla(job, teams, lambda r: argses[r])
        expect = np.concatenate(srcs)
        for r in range(n):
            np.testing.assert_array_equal(np.asarray(argses[r].dst.buffer),
                                          expect)

    def test_allgatherv(self, job, teams):
        n = 4
        counts = [2, 5, 1, 3]
        srcs = [np.arange(counts[r], dtype=np.int32) + 100 * r
                for r in range(n)]
        argses = [CollArgs(
            coll_type=CollType.ALLGATHERV,
            src=tpu_buf(job, r, srcs[r], DataType.INT32),
            dst=BufferInfoV(None, counts, None, DataType.INT32,
                            mem_type=MemoryType.TPU)) for r in range(n)]
        run_xla(job, teams, lambda r: argses[r])
        expect = np.concatenate(srcs)
        for r in range(n):
            np.testing.assert_array_equal(np.asarray(argses[r].dst.buffer),
                                          expect)

    def test_bcast(self, job, teams):
        n, count, root = 4, 12, 2
        argses = []
        for r in range(n):
            data = np.full(count, 7.5, np.float32) if r == root else \
                np.zeros(count, np.float32)
            argses.append(CollArgs(
                coll_type=CollType.BCAST, root=root,
                src=tpu_buf(job, r, data, DataType.FLOAT32)))
        run_xla(job, teams, lambda r: argses[r])
        for r in range(n):
            np.testing.assert_array_equal(np.asarray(argses[r].src.buffer),
                                          np.full(count, 7.5, np.float32))

    def test_reduce(self, job, teams):
        n, count, root = 4, 9, 1
        srcs = [np.full(count, r + 1.0, np.float64) for r in range(n)]
        argses = [CollArgs(
            coll_type=CollType.REDUCE, root=root,
            src=tpu_buf(job, r, srcs[r], DataType.FLOAT64),
            dst=BufferInfo(None, count, DataType.FLOAT64,
                           mem_type=MemoryType.TPU) if r == root else None,
            op=ReductionOp.SUM) for r in range(n)]
        run_xla(job, teams, lambda r: argses[r])
        np.testing.assert_allclose(np.asarray(argses[root].dst.buffer), 10.0)

    def test_alltoall(self, job, teams):
        n, blk = 4, 3
        total = n * blk
        srcs = [np.arange(total, dtype=np.int32) + 100 * r for r in range(n)]
        argses = [CollArgs(
            coll_type=CollType.ALLTOALL,
            src=tpu_buf(job, r, srcs[r], DataType.INT32),
            dst=BufferInfo(None, total, DataType.INT32,
                           mem_type=MemoryType.TPU)) for r in range(n)]
        run_xla(job, teams, lambda r: argses[r])
        for r in range(n):
            expect = np.concatenate(
                [srcs[p][r * blk:(r + 1) * blk] for p in range(n)])
            np.testing.assert_array_equal(np.asarray(argses[r].dst.buffer),
                                          expect)

    def test_reduce_scatter(self, job, teams):
        n, per = 4, 4
        total = n * per
        srcs = [np.arange(total, dtype=np.float32) * (r + 1)
                for r in range(n)]
        argses = [CollArgs(
            coll_type=CollType.REDUCE_SCATTER,
            src=tpu_buf(job, r, srcs[r], DataType.FLOAT32),
            dst=BufferInfo(None, per, DataType.FLOAT32,
                           mem_type=MemoryType.TPU),
            op=ReductionOp.SUM) for r in range(n)]
        run_xla(job, teams, lambda r: argses[r])
        expect = np.sum(srcs, axis=0)
        for r in range(n):
            np.testing.assert_allclose(np.asarray(argses[r].dst.buffer),
                                       expect[r * per:(r + 1) * per])

    def test_scatter(self, job, teams):
        n, per, root = 4, 3, 0
        src = np.arange(per * n, dtype=np.float32)
        argses = [CollArgs(
            coll_type=CollType.SCATTER, root=root,
            src=tpu_buf(job, r, src, DataType.FLOAT32) if r == root else None,
            dst=BufferInfo(None, per, DataType.FLOAT32,
                           mem_type=MemoryType.TPU)) for r in range(n)]
        run_xla(job, teams, lambda r: argses[r])
        for r in range(n):
            np.testing.assert_array_equal(np.asarray(argses[r].dst.buffer),
                                          src[r * per:(r + 1) * per])

    def test_barrier(self, job, teams):
        argses = [CollArgs(coll_type=CollType.BARRIER,
                           src=BufferInfo(None, 0, DataType.UINT8,
                                          mem_type=MemoryType.TPU))
                  for _ in range(4)]
        run_xla(job, teams, lambda r: argses[r])


class TestXlaProgramCache:
    def test_second_call_uses_cache(self, job, teams):
        n, count = 4, 32
        shared = teams[0].cl_teams[0].tl_teams
        # find the xla TL team and snapshot cache size after one coll
        def one_round(val):
            argses = [CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=tpu_buf(job, r, np.full(count, val, np.float32),
                            DataType.FLOAT32),
                dst=BufferInfo(None, count, DataType.FLOAT32,
                               mem_type=MemoryType.TPU),
                op=ReductionOp.SUM) for r in range(n)]
            run_xla(job, teams, lambda r: argses[r])
            return argses

        one_round(1.0)
        xla_team = next(t for t in teams[0].cl_teams[0].tl_teams
                        if t.name == "xla")
        size_after_first = len(xla_team.shared.programs)
        argses = one_round(2.0)
        assert len(xla_team.shared.programs) == size_after_first
        np.testing.assert_allclose(np.asarray(argses[0].dst.buffer), 8.0)


class TestXlaAlltoallv:
    def test_alltoallv_tpu_mem(self, job, teams):
        """Per-pair counts matrix assembled from the rendezvous slot;
        padded all_to_all + unpack on device."""
        n = 4
        m = np.array([[1, 2, 0, 3],
                      [2, 1, 4, 0],
                      [0, 3, 1, 2],
                      [1, 0, 2, 1]])
        argses = []
        for r in range(n):
            scounts = [int(c) for c in m[r]]
            rcounts = [int(m[p][r]) for p in range(n)]
            sdispl = list(np.cumsum([0] + scounts[:-1]))
            rdispl = list(np.cumsum([0] + rcounts[:-1]))
            src = np.arange(sum(scounts), dtype=np.float32) + 100 * r
            argses.append(CollArgs(
                coll_type=CollType.ALLTOALLV,
                src=BufferInfoV(dev_array(job, r, src), scounts, sdispl,
                                DataType.FLOAT32,
                                mem_type=MemoryType.TPU),
                dst=BufferInfoV(None, rcounts, rdispl, DataType.FLOAT32,
                                mem_type=MemoryType.TPU)))
        run_xla(job, teams, lambda r: argses[r])
        for r in range(n):
            out = np.asarray(argses[r].dst.buffer)
            off = 0
            for p in range(n):
                c = int(m[p][r])
                sd = int(np.cumsum([0] + [int(x) for x in m[p][:-1]])[r])
                expect = np.arange(sum(int(x) for x in m[p]),
                                   dtype=np.float32)[sd:sd + c] + 100 * p
                np.testing.assert_array_equal(out[off:off + c], expect)
                off += c

    def test_alltoallv_host_mem_via_xla_disabled(self, job, teams):
        """HOST memtype a2av still routes to the host TLs (higher score)."""
        n = 4
        counts = [[2] * n for _ in range(n)]
        srcs = [np.arange(2 * n, dtype=np.int32) + 10 * r for r in range(n)]
        dsts = [np.zeros(2 * n, np.int32) for _ in range(n)]
        job.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.ALLTOALLV,
            src=BufferInfoV(srcs[r], counts[r], None, DataType.INT32),
            dst=BufferInfoV(dsts[r], counts[r], None, DataType.INT32)))
        for r in range(n):
            expect = np.concatenate(
                [srcs[p][r * 2:(r + 1) * 2] for p in range(n)])
            np.testing.assert_array_equal(dsts[r], expect)


class TestXlaRemainderConventions:
    """ADVICE r1 (high): non-divisible reduce_scatter must follow the
    near-equal split convention (remainder in the FIRST blocks,
    ucc_buffer_block_count), not equal padded blocks."""

    def test_reduce_scatter_remainder(self, job, teams):
        from ucc_tpu.utils.mathutils import block_count, block_offset
        n, total = 4, 10           # blocks 3,3,2,2
        srcs = [np.arange(total, dtype=np.float32) * 10.0 * (r + 1)
                for r in range(n)]
        argses = [CollArgs(
            coll_type=CollType.REDUCE_SCATTER,
            src=tpu_buf(job, r, srcs[r], DataType.FLOAT32),
            dst=BufferInfo(None, block_count(total, n, r), DataType.FLOAT32,
                           mem_type=MemoryType.TPU),
            op=ReductionOp.SUM) for r in range(n)]
        run_xla(job, teams, lambda r: argses[r])
        expect = np.sum(srcs, axis=0)
        for r in range(n):
            off = block_offset(total, n, r)
            cnt = block_count(total, n, r)
            np.testing.assert_allclose(np.asarray(argses[r].dst.buffer),
                                       expect[off:off + cnt])

    def test_scatter_non_divisible_rejected(self, job, teams):
        from ucc_tpu import UccError
        src = np.arange(10, dtype=np.float32)    # 10 % 4 != 0
        args = CollArgs(
            coll_type=CollType.SCATTER, root=0,
            src=tpu_buf(job, 0, src, DataType.FLOAT32),
            dst=BufferInfo(None, 3, DataType.FLOAT32,
                           mem_type=MemoryType.TPU))
        with pytest.raises(UccError):
            teams[0].collective_init(args)


class TestXlaPersistent:
    """Persistent collectives (ucc.h:1674): init once, post many. The TL
    reuses its cached global array + AOT program when the buffers are
    unchanged; rebinding src changes the buffers and must recompute."""

    def test_repost_unchanged_buffers(self, job, teams):
        # count above SHORT_MSG_MAX: the launch cache belongs to the
        # compiled-program path (short messages go host-staged eager and
        # have nothing to cache — TestXlaShortMsg covers them)
        from ucc_tpu import CollArgsFlags
        n, count = 4, 64 << 10
        srcs = [dev_array(job, r, np.full(count, r + 1.0, np.float32))
                for r in range(n)]
        argses = [CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(srcs[r], count, DataType.FLOAT32,
                           mem_type=MemoryType.TPU),
            dst=BufferInfo(None, count, DataType.FLOAT32,
                           mem_type=MemoryType.TPU),
            op=ReductionOp.SUM,
            flags=CollArgsFlags.PERSISTENT) for r in range(n)]
        reqs = [teams[r].collective_init(argses[r]) for r in range(n)]
        xla_team = next(t for t in teams[0].cl_teams[0].tl_teams
                        if t.name == "xla")
        for _ in range(3):
            for rq in reqs:
                rq.post()
            job.progress_until(lambda: all(
                rq.test() != Status.IN_PROGRESS for rq in reqs))
            for r in range(n):
                assert reqs[r].test() == Status.OK
                np.testing.assert_allclose(
                    np.asarray(argses[r].dst.buffer), 10.0)
        assert len(xla_team.shared.launch_cache) >= 1
        for rq in reqs:
            rq.finalize()
        # finalize drops the cache entries
        assert len(xla_team.shared.launch_cache) == 0

    def test_repost_rebound_src(self, job, teams):
        """Rebinding src between posts must produce the new result (the
        identity check rejects the cached launch)."""
        from ucc_tpu import CollArgsFlags
        n, count = 4, 16
        argses = [CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(dev_array(job, r, np.full(count, 1.0, np.float32)),
                           count, DataType.FLOAT32, mem_type=MemoryType.TPU),
            dst=BufferInfo(None, count, DataType.FLOAT32,
                           mem_type=MemoryType.TPU),
            op=ReductionOp.SUM,
            flags=CollArgsFlags.PERSISTENT) for r in range(n)]
        reqs = [teams[r].collective_init(argses[r]) for r in range(n)]
        for rq in reqs:
            rq.post()
        job.progress_until(lambda: all(
            rq.test() != Status.IN_PROGRESS for rq in reqs))
        np.testing.assert_allclose(np.asarray(argses[0].dst.buffer), 4.0)
        for r in range(n):
            argses[r].src.buffer = dev_array(
                job, r, np.full(count, 2.0, np.float32))
        for rq in reqs:
            rq.post()
        job.progress_until(lambda: all(
            rq.test() != Status.IN_PROGRESS for rq in reqs))
        for r in range(n):
            np.testing.assert_allclose(np.asarray(argses[r].dst.buffer), 8.0)
        for rq in reqs:
            rq.finalize()


class TestXlaRootedPlacement:
    """Rooted colls are explicit data placement (round-2 redesign): the
    result lives ONLY where UCC semantics need it — no replicated
    allgather/bcast inflation (VERDICT r1 weak #3)."""

    def test_gather_lands_on_root_only(self, job, teams):
        n, per, root = 4, 6, 2
        srcs = [np.arange(per, dtype=np.float32) + 10 * r for r in range(n)]
        argses = [CollArgs(
            coll_type=CollType.GATHER, root=root,
            src=tpu_buf(job, r, srcs[r], DataType.FLOAT32),
            dst=BufferInfo(None, per * n, DataType.FLOAT32,
                           mem_type=MemoryType.TPU) if r == root else None)
            for r in range(n)]
        run_xla(job, teams, lambda r: argses[r])
        out = argses[root].dst.buffer
        np.testing.assert_array_equal(np.asarray(out), np.concatenate(srcs))
        root_dev = job.contexts[root].tl_contexts["xla"].obj.device
        assert set(out.devices()) == {root_dev}

    def test_scatter_no_replicated_program(self, job, teams):
        n, per, root = 4, 5, 1
        src = np.arange(per * n, dtype=np.float32)
        argses = [CollArgs(
            coll_type=CollType.SCATTER, root=root,
            src=tpu_buf(job, r, src, DataType.FLOAT32) if r == root else None,
            dst=BufferInfo(None, per, DataType.FLOAT32,
                           mem_type=MemoryType.TPU)) for r in range(n)]
        run_xla(job, teams, lambda r: argses[r])
        for r in range(n):
            out = argses[r].dst.buffer
            np.testing.assert_array_equal(np.asarray(out),
                                          src[r * per:(r + 1) * per])
            dev_r = job.contexts[r].tl_contexts["xla"].obj.device
            assert set(out.devices()) == {dev_r}
        # mechanism: no shard_map program was compiled for scatter at all
        # (blocks move by direct device placement)
        xla_team = next(t for t in teams[0].cl_teams[0].tl_teams
                        if t.name == "xla")
        assert not any(k[0] == CollType.SCATTER
                       for k in xla_team.shared.programs
                       if isinstance(k, tuple) and len(k) > 0)

    def test_reduce_lands_on_root_only(self, job, teams):
        n, count, root = 4, 10, 3     # non-divisible: exercises padding
        srcs = [np.arange(count, dtype=np.float32) * (r + 1)
                for r in range(n)]
        argses = [CollArgs(
            coll_type=CollType.REDUCE, root=root,
            src=tpu_buf(job, r, srcs[r], DataType.FLOAT32),
            dst=BufferInfo(None, count, DataType.FLOAT32,
                           mem_type=MemoryType.TPU) if r == root else None,
            op=ReductionOp.SUM) for r in range(n)]
        run_xla(job, teams, lambda r: argses[r])
        out = argses[root].dst.buffer
        np.testing.assert_allclose(np.asarray(out), np.sum(srcs, axis=0))
        root_dev = job.contexts[root].tl_contexts["xla"].obj.device
        assert set(out.devices()) == {root_dev}

    def test_gatherv_lands_on_root_only(self, job, teams):
        n, root = 4, 0
        counts = [3, 1, 4, 2]
        srcs = [np.arange(counts[r], dtype=np.int32) + 100 * r
                for r in range(n)]
        argses = [CollArgs(
            coll_type=CollType.GATHERV, root=root,
            src=tpu_buf(job, r, srcs[r], DataType.INT32),
            dst=BufferInfoV(None, counts, None, DataType.INT32,
                            mem_type=MemoryType.TPU)) for r in range(n)]
        run_xla(job, teams, lambda r: argses[r])
        out = argses[root].dst.buffer
        np.testing.assert_array_equal(np.asarray(out), np.concatenate(srcs))
        assert len(set(out.devices())) == 1


class TestXlaActiveSet:
    def test_active_set_rejected_at_init(self, job, teams):
        """Active-set colls post on a subset only; the full-team
        rendezvous would hang waiting for the rest — TL/XLA must refuse
        at init so selection falls through to subset-capable TLs."""
        from ucc_tpu import ActiveSet, UccError
        args = CollArgs(
            coll_type=CollType.BCAST, root=0,
            src=tpu_buf(job, 0, np.zeros(8, np.float32), DataType.FLOAT32),
            active_set=ActiveSet(start=0, stride=1, size=2))
        # TPU memtype has no subset-capable TL -> clean error, not a hang
        with pytest.raises(UccError):
            teams[0].collective_init(args)


class TestXlaLaunchFailure:
    def test_inconsistent_counts_fail_cleanly(self, job, teams):
        """A user error (per-rank counts disagree) must fail every local
        task with an error status — never wedge the rendezvous or raise
        out of the progress loop."""
        n = 4
        counts = [16, 16, 16, 32]        # rank 3 lies
        argses = [CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=tpu_buf(job, r, np.ones(counts[r], np.float32),
                        DataType.FLOAT32),
            dst=BufferInfo(None, counts[r], DataType.FLOAT32,
                           mem_type=MemoryType.TPU),
            op=ReductionOp.SUM) for r in range(n)]
        reqs = [teams[r].collective_init(argses[r]) for r in range(n)]
        for rq in reqs:
            rq.post()
        job.progress_until(lambda: all(
            rq.test() != Status.IN_PROGRESS for rq in reqs), timeout=20)
        assert any(rq.test().is_error for rq in reqs)
        # the team must still be usable afterwards
        good = [CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=tpu_buf(job, r, np.ones(8, np.float32), DataType.FLOAT32),
            dst=BufferInfo(None, 8, DataType.FLOAT32,
                           mem_type=MemoryType.TPU),
            op=ReductionOp.SUM) for r in range(n)]
        run_xla(job, teams, lambda r: good[r])
        np.testing.assert_allclose(np.asarray(good[0].dst.buffer), 4.0)


class TestXlaGenericDt:
    def test_generic_dtype_rejected_cleanly(self, job, teams):
        """User-defined datatypes have no numeric compute type for a
        compiled program: clean NOT_SUPPORTED, not a raw ValueError
        (reference device TLs reject the same way)."""
        from ucc_tpu import UccError
        from ucc_tpu.constants import GenericDataType
        gdt = GenericDataType(8, name="opaque")
        arr = dev_array(job, 0, np.zeros(8, np.uint8))
        with pytest.raises(UccError):
            teams[0].collective_init(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(arr, 8, gdt, mem_type=MemoryType.TPU),
                dst=BufferInfo(None, 8, gdt, mem_type=MemoryType.TPU)))


class TestXlaShortMsg:
    """The latency-optimized short-message algorithm (tl/xla 'short'):
    host-staged eager reduce + ONE replicated jax.device_put instead of a
    compiled collective program — the tl_ucp short-protocol analog
    (reference: tl_ucp short vs long protocol split). Selected by score
    range below UCC_TL_XLA_SHORT_MSG_MAX on fully process-local teams."""

    def test_selected_below_threshold(self, teams):
        cands = teams[0].score_map.lookup(CollType.ALLREDUCE,
                                          MemoryType.TPU, 64)
        assert cands[0].alg_name == "short"
        big = teams[0].score_map.lookup(CollType.ALLREDUCE,
                                        MemoryType.TPU, 1 << 20)
        assert big[0].alg_name != "short"

    @pytest.mark.parametrize("op,expect", [
        (ReductionOp.SUM, 10.0), (ReductionOp.MAX, 4.0),
        (ReductionOp.MIN, 1.0), (ReductionOp.AVG, 2.5),
    ])
    def test_allreduce_ops(self, job, teams, op, expect):
        n, count = 4, 16
        argses = [CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=tpu_buf(job, r, np.full(count, r + 1.0, np.float32),
                        DataType.FLOAT32),
            dst=BufferInfo(None, count, DataType.FLOAT32,
                           mem_type=MemoryType.TPU),
            op=op) for r in range(n)]
        run_xla(job, teams, lambda r: argses[r])
        for r in range(n):
            np.testing.assert_allclose(np.asarray(argses[r].dst.buffer),
                                       expect)

    def test_persistent_repost_no_program(self, job, teams):
        """Persistent short re-posts go through the eager path every round
        (nothing to launch-cache) and the fast re-post lane keeps them
        correct across rounds."""
        n, count = 4, 8
        xla_team = next(t for t in teams[0].cl_teams[0].tl_teams
                        if t.name == "xla")
        cache_before = len(xla_team.shared.launch_cache)
        argses = [CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=tpu_buf(job, r, np.full(count, r + 1.0, np.float32),
                        DataType.FLOAT32),
            dst=BufferInfo(None, count, DataType.FLOAT32,
                           mem_type=MemoryType.TPU),
            op=ReductionOp.SUM,
            flags=CollArgsFlags.PERSISTENT) for r in range(n)]
        reqs = [teams[r].collective_init(argses[r]) for r in range(n)]
        for _ in range(3):
            for rq in reqs:
                rq.post()
            job.progress_until(lambda: all(
                rq.test() != Status.IN_PROGRESS for rq in reqs))
            for r in range(n):
                assert reqs[r].test() == Status.OK
                np.testing.assert_allclose(
                    np.asarray(argses[r].dst.buffer), 10.0)
        assert len(xla_team.shared.launch_cache) == cache_before
        for rq in reqs:
            rq.finalize()

    def test_bcast_reduce_allgather(self, job, teams):
        n, count = 4, 12
        data = np.arange(count, dtype=np.float32) * 3
        argses = []
        for r in range(n):
            src = data if r == 1 else np.zeros(count, np.float32)
            argses.append(CollArgs(coll_type=CollType.BCAST, root=1,
                                   src=tpu_buf(job, r, src,
                                               DataType.FLOAT32)))
        run_xla(job, teams, lambda r: argses[r])
        for r in range(n):
            np.testing.assert_allclose(np.asarray(argses[r].src.buffer),
                                       data)
        argses = [CollArgs(
            coll_type=CollType.REDUCE, root=2, op=ReductionOp.SUM,
            src=tpu_buf(job, r, np.full(count, r + 1.0, np.float32),
                        DataType.FLOAT32),
            dst=BufferInfo(None, count, DataType.FLOAT32,
                           mem_type=MemoryType.TPU)) for r in range(n)]
        run_xla(job, teams, lambda r: argses[r])
        np.testing.assert_allclose(np.asarray(argses[2].dst.buffer), 10.0)
        argses = [CollArgs(
            coll_type=CollType.ALLGATHER,
            src=tpu_buf(job, r, np.full(count, float(r), np.float32),
                        DataType.FLOAT32),
            dst=BufferInfo(None, n * count, DataType.FLOAT32,
                           mem_type=MemoryType.TPU)) for r in range(n)]
        run_xla(job, teams, lambda r: argses[r])
        full = np.concatenate([np.full(count, float(g), np.float32)
                               for g in range(n)])
        for r in range(n):
            np.testing.assert_allclose(np.asarray(argses[r].dst.buffer),
                                       full)

    def test_barrier_rendezvous(self, job, teams):
        argses = [CollArgs(coll_type=CollType.BARRIER) for _ in range(4)]
        run_xla(job, teams, lambda r: argses[r])

    def test_unmapped_op_falls_through_to_program(self, job, teams):
        """Ops without a host ufunc (LAND) at short sizes must fall back
        to the compiled-program path inside the same launch, not fail."""
        n, count = 4, 8
        argses = [CollArgs(
            coll_type=CollType.ALLREDUCE, op=ReductionOp.LAND,
            src=tpu_buf(job, r, np.full(count, float(r % 2), np.float32),
                        DataType.FLOAT32),
            dst=BufferInfo(None, count, DataType.FLOAT32,
                           mem_type=MemoryType.TPU)) for r in range(n)]
        run_xla(job, teams, lambda r: argses[r])
        for r in range(n):
            np.testing.assert_allclose(np.asarray(argses[r].dst.buffer),
                                       0.0)

    def test_threshold_disable(self, monkeypatch):
        monkeypatch.setenv("UCC_TL_XLA_SHORT_MSG_MAX", "0")
        j = UccJob(2)
        try:
            teams = j.create_team()
            cands = teams[0].score_map.lookup(CollType.ALLREDUCE,
                                              MemoryType.TPU, 64)
            assert all(c.alg_name != "short" for c in cands)
        finally:
            j.cleanup()


class TestXlaScatterv:
    """SCATTERV on device memory via explicit per-block placement
    (VERDICT r2 missing #2; reference: tl_ucp scatterv.c linear).
    Uneven blocks, non-zero root, and a zero-count rank."""

    @pytest.mark.parametrize("root", [0, 2])
    def test_uneven_blocks(self, job, teams, root):
        n = 4
        counts = [3, 7, 0, 5]
        total = sum(counts)
        displs = list(np.cumsum([0] + counts[:-1]))
        data = np.arange(total, dtype=np.float32) * 2
        argses = []
        for r in range(n):
            if r == root:
                src = BufferInfoV(dev_array(job, r, data), counts, displs,
                                  DataType.FLOAT32,
                                  mem_type=MemoryType.TPU)
            else:
                src = None
            argses.append(CollArgs(
                coll_type=CollType.SCATTERV, root=root, src=src,
                dst=BufferInfo(None, counts[r], DataType.FLOAT32,
                               mem_type=MemoryType.TPU)))
        run_xla(job, teams, lambda r: argses[r])
        for r in range(n):
            got = np.asarray(argses[r].dst.buffer)
            np.testing.assert_allclose(
                got, data[displs[r]:displs[r] + counts[r]])

    def test_root_missing_counts_rejected(self, job, teams):
        from ucc_tpu import UccError
        with pytest.raises(UccError):
            teams[0].collective_init(CollArgs(
                coll_type=CollType.SCATTERV, root=0,
                src=tpu_buf(job, 0, np.zeros(8, np.float32),
                            DataType.FLOAT32),
                dst=BufferInfo(None, 2, DataType.FLOAT32,
                               mem_type=MemoryType.TPU)))


class TestXlaAsyncFailure:
    """Eager-completion failure contract (VERDICT r2 weak #7; reference:
    ucc_schedule.h error propagation :258):

    - a failure DURING launch (build/dispatch raises) fails every local
      task with an error status — TestXlaLaunchFailure pins that;
    - a failure AFTER dispatch (the device program fails asynchronously,
      only possible on a real accelerator — the CPU backend executes
      inline) CANNOT be reported by test(): eager completion already
      returned OK at dispatch, per stream-ordered semantics. The
      contract is that the error surfaces at the CONSUMPTION point —
      jax.block_until_ready(dst.buffer) / np.asarray(dst.buffer) raises
      — exactly like work queued behind a faulted CUDA stream. This test
      simulates the poisoned future the TPU runtime would return and
      pins that our plumbing (a) still reports OK, (b) delivers the
      poisoned result through dst.buffer rather than swallowing it."""

    class _PoisonShardData:
        def __init__(self, shape):
            self.shape = shape
            self.ndim = len(shape)

        def __array__(self, *a, **k):
            raise RuntimeError("injected async device failure")

    def test_poisoned_future_surfaces_at_consumption(self, job, teams):
        n, count = 4, 40000  # above SHORT_MSG_MAX: the program path
        xla_team = next(t for t in teams[0].cl_teams[0].tl_teams
                        if t.name == "xla")
        shared = xla_team.shared
        outer = self

        class _PoisonShard:
            def __init__(self, dev, shape):
                self.device = dev
                self.data = outer._PoisonShardData(shape)

        class _PoisonOut:
            def __init__(self, devs, per_rank):
                self.shape = (len(devs) * per_rank,)
                self.addressable_shards = [
                    _PoisonShard(d, (per_rank,)) for d in devs]

        def poison_program(garr):
            return _PoisonOut(shared.devices, count)

        from ucc_tpu.constants import ReductionOp as R
        key = (CollType.ALLREDUCE, R.SUM, np.dtype(np.float32).str,
               count, "xla", 0, None)
        assert key not in shared.programs
        shared.programs[key] = (poison_program, count)
        try:
            argses = [CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=tpu_buf(job, r, np.ones(count, np.float32),
                            DataType.FLOAT32),
                dst=BufferInfo(None, count, DataType.FLOAT32,
                               mem_type=MemoryType.TPU),
                op=ReductionOp.SUM) for r in range(n)]
            reqs = [teams[r].collective_init(argses[r]) for r in range(n)]
            for rq in reqs:
                rq.post()
            job.progress_until(lambda: all(
                rq.test() != Status.IN_PROGRESS for rq in reqs),
                timeout=20)
            # (a) stream-ordered: the request itself reports OK
            for rq in reqs:
                assert rq.test() == Status.OK
            # (b) the poisoned result is DELIVERED, and consumption raises
            for r in range(n):
                assert argses[r].dst.buffer is not None
                with pytest.raises(RuntimeError, match="injected async"):
                    np.asarray(argses[r].dst.buffer)
        finally:
            shared.programs.pop(key, None)


class TestXlaShortAlltoall:
    """ALLTOALL through the short path: host transpose + one row-sharded
    device_put (each rank's receive layout is its row of the global)."""

    def test_alltoall_short(self, job, teams):
        n, blk = 4, 8
        total = n * blk
        cands = teams[0].score_map.lookup(CollType.ALLTOALL,
                                          MemoryType.TPU, total * 4)
        assert cands[0].alg_name == "short"
        srcs = [np.arange(total, dtype=np.float32) + 1000 * r
                for r in range(n)]
        argses = [CollArgs(
            coll_type=CollType.ALLTOALL,
            src=tpu_buf(job, r, srcs[r], DataType.FLOAT32),
            dst=BufferInfo(None, total, DataType.FLOAT32,
                           mem_type=MemoryType.TPU)) for r in range(n)]
        run_xla(job, teams, lambda r: argses[r])
        for r in range(n):
            expect = np.concatenate(
                [srcs[p][r * blk:(r + 1) * blk] for p in range(n)])
            np.testing.assert_allclose(np.asarray(argses[r].dst.buffer),
                                       expect)

    def test_alltoall_non_divisible_falls_through(self, job, teams):
        """count % n != 0: the short path defers to the padded program,
        whose ceil-block exchange semantics must hold (content-checked,
        not just shape — the fallback itself is what's under test)."""
        n, total = 4, 10
        padded = 12                      # ceil to n-divisible, blk=3
        blk = padded // n
        srcs = [np.arange(total, dtype=np.float32) + 100 * r
                for r in range(n)]
        argses = [CollArgs(
            coll_type=CollType.ALLTOALL,
            src=tpu_buf(job, r, srcs[r], DataType.FLOAT32),
            dst=BufferInfo(None, total, DataType.FLOAT32,
                           mem_type=MemoryType.TPU)) for r in range(n)]
        run_xla(job, teams, lambda r: argses[r])
        srcs_p = [np.pad(s, (0, padded - total)) for s in srcs]
        for r in range(n):
            expect = np.concatenate(
                [srcs_p[p][r * blk:(r + 1) * blk] for p in range(n)])
            got = np.asarray(argses[r].dst.buffer)
            np.testing.assert_allclose(got[:total], expect[:total])


class TestXlaShortDtypes:
    """Short-path dtype breadth: the host staging must honor the same
    dtype matrix the compiled programs serve (bf16 rides ml_dtypes in
    numpy; AVG on non-float kinds falls back to the program)."""

    @pytest.mark.parametrize("dt,np_dt", [
        (DataType.BFLOAT16, "bfloat16"), (DataType.FLOAT16, np.float16),
        (DataType.INT8, np.int8), (DataType.UINT64, np.uint64),
        (DataType.FLOAT64, np.float64),
    ])
    def test_short_allreduce_dtypes(self, job, teams, dt, np_dt):
        n, count = 4, 16
        if np_dt == "bfloat16":
            import ml_dtypes
            np_dt = ml_dtypes.bfloat16
        srcs = [(np.arange(count) % 3 + r + 1).astype(np_dt)
                for r in range(n)]
        argses = [CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=tpu_buf(job, r, srcs[r], dt),
            dst=BufferInfo(None, count, dt, mem_type=MemoryType.TPU),
            op=ReductionOp.SUM) for r in range(n)]
        run_xla(job, teams, lambda r: argses[r])
        expect = np.sum([s.astype(np.float64) for s in srcs], axis=0)
        for r in range(n):
            got = np.asarray(argses[r].dst.buffer).astype(np.float64)
            np.testing.assert_allclose(got, expect, rtol=1e-2)

    def test_short_avg_int_falls_back_to_program(self, job, teams):
        """AVG on an integer dtype has no exact host ufunc ladder; the
        short path defers to the compiled program, which must still
        produce the (truncated) integer mean."""
        n, count = 4, 8
        argses = [CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=tpu_buf(job, r, np.full(count, (r + 1) * 2, np.int32),
                        DataType.INT32),
            dst=BufferInfo(None, count, DataType.INT32,
                           mem_type=MemoryType.TPU),
            op=ReductionOp.AVG) for r in range(n)]
        run_xla(job, teams, lambda r: argses[r])
        for r in range(n):
            got = np.asarray(argses[r].dst.buffer)
            assert got[0] in (5, 5.0), got[0]   # (2+4+6+8)/4
