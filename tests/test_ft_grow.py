"""Elastic membership (ISSUE 17): Team.grow / Team.join, the grow-side
epoch fence, rollback when a joiner never arrives, the fresh-heartbeat
agreement race fix, re-admission of a falsely-suspected survivor, and
collector/flight continuity across growth."""
import time

import numpy as np
import pytest

from ucc_tpu import (BufferInfo, CollArgs, CollType, DataType,
                     RankFailedError, ReductionOp, Status)
from ucc_tpu.core.team import Team
from ucc_tpu.fault import health, inject
from ucc_tpu.tl.host.transport import Mailbox, RecvReq

from harness import UccJob


@pytest.fixture(autouse=True)
def _clean_ft():
    inject.reset()
    health.reset()
    yield
    inject.reset()
    health.reset()


def _ft_on(interval=0.02, timeout=0.3):
    health.configure("shrink", interval=interval, timeout=timeout)


def _ar_args(rank, count=16):
    dst = np.zeros(count, np.float64)
    args = CollArgs(coll_type=CollType.ALLREDUCE,
                    src=BufferInfo(np.full(count, rank + 1.0), count,
                                   DataType.FLOAT64),
                    dst=BufferInfo(dst, count, DataType.FLOAT64),
                    op=ReductionOp.SUM)
    return args, dst


def _drive(ctxs, cond, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for c in ctxs:
            c.progress()
        if cond():
            return True
    return False


def _grow_to_full(job, teams, joiner_idx, timeout=20.0):
    """Drive grow_post on every *teams* member + join_post on the
    joiner; returns (grows dict, join request). NOTE the list
    comprehension in the condition: every membership request must be
    polled each pass — test() drives the rebuild rounds."""
    joiner_ctx = job.contexts[joiner_idx].rank
    grows = {r: t.grow_post([joiner_ctx]) for r, t in teams.items()}
    jn = Team.join_post(job.contexts[joiner_idx])
    assert _drive(job.contexts, lambda: all(
        [g.test() != Status.IN_PROGRESS for g in grows.values()]
        + [jn.test() != Status.IN_PROGRESS]), timeout)
    return grows, jn


# ---------------------------------------------------------------------------
# grow basics
# ---------------------------------------------------------------------------

class TestGrowBasic:
    def test_grow_admits_rank_and_retires_old_team(self):
        """Survivors grow_post + the joiner join_post converge on one
        epoch; the old team refuses new posts (naming the grow) and the
        grown team serves a correct allreduce including the joiner."""
        job = UccJob(4)
        try:
            teams = dict(enumerate(job.create_team(ranks=[0, 1, 2])))
            grows, jn = _grow_to_full(job, teams, 3)
            for g in grows.values():
                assert g.test() == Status.OK, g.test()
            assert jn.test() == Status.OK
            epochs = {g.epoch for g in grows.values()} | {jn.epoch}
            assert epochs == {1}, epochs
            new_teams = [grows[r].new_team for r in sorted(grows)] \
                + [jn.new_team]
            for t in new_teams:
                assert t.size == 4 and t.epoch == 1
            with pytest.raises(RankFailedError, match="grow"):
                teams[0].collective_init(_ar_args(0)[0])
            reqs = []
            for g, t in enumerate(new_teams):
                args, dst = _ar_args(g)
                rq = t.collective_init(args)
                rq.post()
                reqs.append((rq, dst))
            assert _drive(job.contexts, lambda: all(
                rq.test() != Status.IN_PROGRESS for rq, _ in reqs), 10)
            for rq, dst in reqs:
                assert rq.test() == Status.OK, rq.test()
                assert np.allclose(dst, sum(g + 1.0 for g in range(4)))
                rq.finalize()
            for t in new_teams:
                t.destroy()
        finally:
            job.cleanup()

    def test_grow_validates_inputs(self):
        job = UccJob(3)
        try:
            teams = job.create_team()
            # admitting a current member is a caller error
            with pytest.raises(Exception):
                teams[0].grow_post([job.contexts[1].rank])
            with pytest.raises(Exception):
                teams[0].grow_post([])
        finally:
            job.cleanup()


# ---------------------------------------------------------------------------
# the grow-side epoch fence (satellite c)
# ---------------------------------------------------------------------------

TEAM_KEY = (("unit",), "cl")


class TestGrowFence:
    def test_stale_pre_grow_send_cannot_match_post_grow_recv(self):
        """Mailbox unit: after the grow fence (epoch 1 -> 2), a send
        still keyed to the pre-grow epoch is discarded at the matching
        boundary; it can never land in a recv posted under the grown
        epoch."""
        mb = Mailbox()
        mb.fence(TEAM_KEY, 2)        # team grew: epochs < 2 are dead
        new_dst = np.zeros(8, np.uint8)
        new_recv = RecvReq(new_dst)
        mb.post_recv((TEAM_KEY, 2, 1, 0, 0), new_recv)
        # identical (tag, slot, src) but the pre-grow epoch: no match
        sreq, kind = mb.send((TEAM_KEY, 1, 1, 0, 0),
                             np.full(8, 0xAB, np.uint8), 8192)
        assert kind == "fenced" and sreq.done
        assert not new_recv.done and not new_dst.any()
        sreq2, kind2 = mb.send((TEAM_KEY, 2, 1, 0, 0),
                               np.full(8, 0xCD, np.uint8), 8192)
        assert kind2 == "direct" and new_recv.done
        assert (new_dst == 0xCD).all()

    def test_grow_fences_old_tl_teams(self):
        """Integration: after Team.grow, a late send keyed to the OLD
        team's tag space is discarded by the transport (n_fenced ticks)
        on whichever matcher the endpoint uses — native included."""
        from ucc_tpu.tl.host.transport import InProcTransport
        job = UccJob(4)
        try:
            teams = dict(enumerate(job.create_team(ranks=[0, 1, 2])))
            grows, jn = _grow_to_full(job, teams, 3)
            assert all(g.test() == Status.OK for g in grows.values())
            assert jn.test() == Status.OK
            probed = False
            for team_key, tr in teams[0]._tl_tag_spaces():
                if not isinstance(tr, InProcTransport):
                    continue
                before = tr.n_fenced
                key = (team_key, 0, (1 << 20) + 1, 999, 0)
                req = tr.send_nb(tr, key, np.ones(8, np.uint8))
                assert req.test()          # sender never parks
                assert tr.n_fenced == before + 1
                probed = True
                break
            assert probed, "no loopback transport to probe"
            for t in [g.new_team for g in grows.values()] + [jn.new_team]:
                t.destroy()
        finally:
            job.cleanup()


# ---------------------------------------------------------------------------
# rollback: a joiner that never shows up (satellite b)
# ---------------------------------------------------------------------------

class TestGrowRollback:
    def test_absent_joiner_times_out_and_old_team_survives(self):
        """A grow whose joiner never bootstraps fails ERR_TIMED_OUT
        naming the absent joiner; the pre-grow team stays fully usable,
        and a retried grow (joiner present this time) succeeds."""
        job = UccJob(4)
        try:
            teams = dict(enumerate(job.create_team(ranks=[0, 1, 2])))
            joiner_ctx = job.contexts[3].rank
            grows = {r: t.grow_post([joiner_ctx], timeout_s=2.0)
                     for r, t in teams.items()}
            assert _drive(job.contexts, lambda: all(
                [g.test() != Status.IN_PROGRESS
                 for g in grows.values()]), 20)
            for g in grows.values():
                assert g.test() == Status.ERR_TIMED_OUT, g.test()
                assert g.absent_joiners == [joiner_ctx]
                assert g.new_team is None
            assert not teams[0]._shrunk
            # the old team still serves correct collectives
            reqs = []
            for g, t in teams.items():
                args, dst = _ar_args(g)
                rq = t.collective_init(args)
                rq.post()
                reqs.append((rq, dst))
            assert _drive(job.contexts, lambda: all(
                rq.test() != Status.IN_PROGRESS for rq, _ in reqs), 10)
            for rq, dst in reqs:
                assert rq.test() == Status.OK, rq.test()
                assert np.allclose(dst, 1.0 + 2.0 + 3.0)
                rq.finalize()
            # retry with the joiner present: the per-attempt agreement
            # tag and the invite-supersede join protocol make the stale
            # first-attempt invite harmless
            grows2, jn = _grow_to_full(job, teams, 3)
            sts = [g.test() for g in grows2.values()] + [jn.test()]
            assert all(s == Status.OK for s in sts), sts
            for t in [g.new_team for g in grows2.values()] \
                    + [jn.new_team]:
                t.destroy()
        finally:
            job.cleanup()


# ---------------------------------------------------------------------------
# the PR-4 agreement race (satellite a)
# ---------------------------------------------------------------------------

class TestAgreeRace:
    def _run_agreement(self, job, round_timeout_s):
        """All ranks enter agreement with EMPTY views while ctx rank 1's
        sends are deterministically delayed past the round timeout."""
        from ucc_tpu.fault.agree import FtAgreement
        teams = job.create_team()
        delayed_ctx = job.contexts[1].rank
        inject.configure(f"delay=1.0:0.6,delay_rank={delayed_ctx}",
                         seed=0)
        tasks = {}
        for r in range(len(teams)):
            t = FtAgreement(teams[r].service_team, set(), epoch=0,
                            round_timeout_s=round_timeout_s)
            t.progress_queue = job.contexts[r].progress_queue
            tasks[r] = t
            t.post()
        assert _drive(job.contexts, lambda: all(
            t.is_completed() for t in tasks.values()), 20)
        return tasks

    def test_fresh_heartbeat_rank_survives_slow_agreement(self):
        """Regression (PR-4 race): a live rank whose agreement messages
        are slower than the round timeout but whose heartbeat is FRESH
        must NOT be suspected — the deadline folds against health
        evidence and extends instead of condemning."""
        _ft_on(interval=0.02, timeout=5.0)
        job = UccJob(3)
        try:
            # round timeout 0.25s < the 0.6s send delay: without the
            # freshness fold every peer would condemn rank 1 at the
            # first deadline
            tasks = self._run_agreement(job, round_timeout_s=0.25)
            views = {(frozenset(t.result_dead), t.result_epoch)
                     for t in tasks.values()}
            assert views == {(frozenset(), 1)}, views
        finally:
            job.cleanup()

    def test_grace_zero_documents_the_old_race(self, monkeypatch):
        """Control: with the freshness grace disabled the identical
        drill condemns the slow-but-alive rank — the behaviour the
        UCC_FT_AGREE_GRACE fold exists to prevent."""
        monkeypatch.setenv("UCC_FT_AGREE_GRACE", "0")
        _ft_on(interval=0.02, timeout=5.0)
        job = UccJob(3)
        try:
            tasks = self._run_agreement(job, round_timeout_s=0.25)
            dead_views = [t.result_dead for r, t in tasks.items()
                          if r != 1]
            assert any(1 in d for d in dead_views), dead_views
        finally:
            job.cleanup()


# ---------------------------------------------------------------------------
# re-admission after false suspicion (closes the PR-4 limit)
# ---------------------------------------------------------------------------

class TestRejoinAfterFalseExclusion:
    def test_falsely_excluded_live_rank_rejoins(self):
        """Survivors shrink a LIVE rank out (bad hint); the victim —
        which never took part — tears down its stale team and re-enters
        through the join path: revived out of every survivor's dead
        set, serving correct collectives on the new epoch."""
        _ft_on()
        job = UccJob(4)
        try:
            teams = job.create_team()
            victim = 3
            victim_ctx = job.contexts[victim].rank
            shrinks = {r: teams[r].shrink_post(dead_hint=[victim])
                       for r in range(4) if r != victim}
            assert _drive(job.contexts, lambda: all(
                [s.test() != Status.IN_PROGRESS
                 for s in shrinks.values()]), 20)
            for s in shrinks.values():
                assert s.test() == Status.OK, s.test()
            for r in shrinks:
                assert victim_ctx in job.contexts[r].health.dead_set()
            teams[victim].destroy()
            small = {r: shrinks[r].new_team for r in shrinks}
            grows, jn = _grow_to_full(job, small, victim)
            assert all(g.test() == Status.OK for g in grows.values())
            assert jn.test() == Status.OK
            # demonstrably re-admitted: revived everywhere ...
            for r in shrinks:
                assert victim_ctx not in job.contexts[r].health.dead_set()
            # ... and serving collectives on the post-rejoin epoch
            new_teams = [grows[r].new_team for r in sorted(grows)] \
                + [jn.new_team]
            assert {t.epoch for t in new_teams} == {2}
            reqs = []
            for g, t in enumerate(new_teams):
                args, dst = _ar_args(g)
                rq = t.collective_init(args)
                rq.post()
                reqs.append((rq, dst))
            assert _drive(job.contexts, lambda: all(
                rq.test() != Status.IN_PROGRESS for rq, _ in reqs), 10)
            for rq, dst in reqs:
                assert rq.test() == Status.OK, rq.test()
                assert np.allclose(dst, sum(g + 1.0 for g in range(4)))
                rq.finalize()
            for t in new_teams:
                t.destroy()
        finally:
            job.cleanup()


# ---------------------------------------------------------------------------
# collector / flight continuity across growth (satellite f)
# ---------------------------------------------------------------------------

class TestObsContinuity:
    def test_collector_state_survives_grow(self):
        """The straggler scorer's learned state rides the handoff into
        the grown team's watch (remapped through ctx ranks — the rank
        set is not monotone under growth), the retired team stops being
        watched, and the joiner's boot:* flight spans exist under the
        new epoch for the merged trace."""
        from ucc_tpu.obs import collector as obs_collector
        from ucc_tpu.obs import flight as obs_flight
        prev = (obs_collector.KNOBS.enabled, obs_collector.KNOBS.interval,
                obs_collector.KNOBS.dir, obs_flight.ENABLED)
        obs_flight.configure(enabled=True)
        obs_collector.configure(enabled=True, interval=0.25, dir="")
        job = UccJob(4)
        try:
            teams = dict(enumerate(job.create_team(ranks=[0, 1, 2])))
            col = job.contexts[0].collector
            assert col is not None
            old_w = col.watch_for(teams[0])
            assert old_w is not None
            # learned straggler state on the pre-grow watch
            old_w.scorer.scores = {1: 2.5}
            old_w.scorer.streaks = {1: 3}
            old_w.scorer.flagged = {1}
            old_w.scorer.windows_seen = 7
            grows, jn = _grow_to_full(job, teams, 3)
            assert all(g.test() == Status.OK for g in grows.values())
            assert jn.test() == Status.OK
            new_team = grows[0].new_team
            assert col.watch_for(teams[0]) is None   # retired: unwatched
            new_w = col.watch_for(new_team)
            assert new_w is not None
            # ctx 1 was old rank 1 and is new rank 1 (joiner appended)
            assert new_w.scorer.scores == {1: 2.5}
            assert new_w.scorer.streaks == {1: 3}
            assert new_w.scorer.flagged == {1}
            assert new_w.scorer.windows_seen == 7
            assert new_w.window == 0   # window index restarts by design
            # the joiner's flight ring carries boot spans for the grown
            # team under the new epoch — they land in a merged trace
            jfr = job.contexts[3].flight
            assert jfr is not None
            evs = jfr.snapshot()["events"]
            boots = [e for e in evs
                     if str(e.get("stage", "")).startswith("boot:")
                     and e.get("epoch") == 1]
            assert boots, evs
            # survivors recorded the grow membership marker inline
            sfr = job.contexts[0].flight
            marks = [e for e in sfr.snapshot()["events"]
                     if e.get("coll") == "membership"]
            assert any(e.get("alg") == "grow" for e in marks), marks
            for t in [g.new_team for g in grows.values()] + [jn.new_team]:
                t.destroy()
        finally:
            job.cleanup()
            obs_collector.configure(enabled=prev[0], interval=prev[1],
                                    dir=prev[2])
            obs_flight.configure(enabled=prev[3])


# ---------------------------------------------------------------------------
# the acceptance drill: churn (kill -> shrink -> grow cycles)
# ---------------------------------------------------------------------------

class TestChurn:
    def test_mini_churn_cycle(self):
        """One full kill -> shrink -> grow(rejoin) cycle plus the
        false-suspicion round, collectives in flight on every epoch,
        fences tripped in both directions."""
        from ucc_tpu.fault.soak import run_churn_soak
        report = run_churn_soak(n_ranks=4, cycles=1, iters_per_epoch=2,
                                post_iters=6)
        assert report["violations"] == [], report
        assert report["cycles"] == 1
        assert report["fenced"]["shrink"] > 0
        assert report["fenced"]["grow"] > 0
        assert report["readmitted"] is True
        assert report["post_churn_ok"] == 6

    @pytest.mark.slow
    def test_churn_acceptance(self):
        """ISSUE-17 acceptance: >= 2 interleaved cycles, no hang,
        n_fenced > 0 both directions, >= 50 correct post-churn
        collectives, the falsely-excluded survivor re-admitted and
        serving on the new epoch — on the native matcher."""
        from ucc_tpu.fault.soak import run_churn_soak
        report = run_churn_soak(n_ranks=4, cycles=2, post_iters=54,
                                plans=True)
        assert report["violations"] == [], report
        assert report["cycles"] >= 2
        assert report["fenced"]["shrink"] >= 2
        assert report["fenced"]["grow"] >= 2
        assert report["post_churn_ok"] >= 50
        assert report["readmitted"] is True
        assert report["matcher"] == "native"
