"""TL coll-plugin sub-framework (VERDICT r2 missing #4 / next #9;
reference: ucc_tl.h:64-69 tlcp iface, tl/ucp/coll_plugins/): an
out-of-tree module injects AlgSpecs into an existing TL's algorithm
table via UCC_TL_<NAME>_COLL_PLUGINS, gets default score ranges, and is
selectable by name through the TL's TUNE string like any built-in."""
import numpy as np
import pytest

import ucc_tpu
from ucc_tpu import (BufferInfo, CollArgs, CollType, DataType, MemoryType,
                     ReductionOp, UccError)

from harness import UccJob


class TestCollPlugin:
    def test_plugin_alg_selectable_via_tune(self, monkeypatch):
        import dummy_coll_plugin
        monkeypatch.setenv("UCC_TL_SHM_COLL_PLUGINS", "dummy_coll_plugin")
        monkeypatch.setenv("UCC_TL_SHM_TUNE", "allreduce:@dummy:inf")
        before = dummy_coll_plugin.INIT_CALLS
        job = UccJob(4)
        try:
            teams = job.create_team()
            cands = teams[0].score_map.lookup(CollType.ALLREDUCE,
                                              MemoryType.HOST, 1 << 10)
            assert cands[0].alg_name == "dummy"
            count = 32
            dsts = [np.zeros(count, np.float32) for _ in range(4)]
            job.run_coll(teams, lambda r: CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(np.full(count, r + 1.0, np.float32),
                               count, DataType.FLOAT32),
                dst=BufferInfo(dsts[r], count, DataType.FLOAT32),
                op=ReductionOp.SUM))
            for r in range(4):
                np.testing.assert_allclose(dsts[r], 10.0)
            assert dummy_coll_plugin.INIT_CALLS > before, \
                "plugin init never ran"
        finally:
            job.cleanup()

    def test_plugin_registered_without_tune_keeps_defaults(self,
                                                           monkeypatch):
        """Without a TUNE boost the plugin alg is present in the table
        but the built-in default ranges still win selection."""
        monkeypatch.setenv("UCC_TL_SHM_COLL_PLUGINS", "dummy_coll_plugin")
        job = UccJob(2)
        try:
            teams = job.create_team()
            cands = teams[0].score_map.lookup(CollType.ALLREDUCE,
                                              MemoryType.HOST, 64)
            assert cands[0].alg_name != "dummy"
        finally:
            job.cleanup()

    def test_broken_plugin_is_a_hard_config_error(self, monkeypatch):
        from ucc_tpu import Status
        from ucc_tpu.tl.base import load_coll_plugins
        monkeypatch.setenv("UCC_TL_SHM_COLL_PLUGINS",
                           "no_such_module_xyz")
        # the loader itself names the broken plugin...
        with pytest.raises(UccError, match="coll plugin"):
            load_coll_plugins("shm")
        # ...and through the full stack team create fails INVALID_PARAM
        # (the state machine wraps the message; the status carries)
        with pytest.raises(UccError) as ei:
            job = UccJob(2)
            try:
                job.create_team()
            finally:
                job.cleanup()
        assert ei.value.status == Status.ERR_INVALID_PARAM
