"""Regressions for review findings: non-power-of-radix sizes, OOB GC,
team split, contiguity, algorithm exception surfacing."""
import numpy as np
import pytest

import ucc_tpu
from ucc_tpu import (BufferInfo, CollArgs, CollType, DataType, ReductionOp,
                     Status, Team, ThreadOobWorld, UccError)

from harness import UccJob


class TestAwkwardTeamSizes:
    """Sizes where n_extra > full for radix 4 (9..15) deadlocked the
    knomial extra/proxy fold — and team create with it (service allreduce
    uses the same algorithm)."""

    @pytest.mark.parametrize("n", [6, 7, 9, 11, 13, 15])
    def test_allreduce(self, n):
        job = UccJob(n)
        try:
            teams = job.create_team()
            count = 21
            srcs = [np.full(count, r + 1.0, np.float64) for r in range(n)]
            dsts = [np.zeros(count, np.float64) for _ in range(n)]
            job.run_coll(teams, lambda r: CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(srcs[r], count, DataType.FLOAT64),
                dst=BufferInfo(dsts[r], count, DataType.FLOAT64),
                op=ReductionOp.SUM))
            expect = n * (n + 1) / 2
            for r in range(n):
                np.testing.assert_allclose(dsts[r], expect)
        finally:
            job.cleanup()


class TestOobRounds:
    def test_pipelined_rounds_before_reads(self):
        # 3 allgathers posted before any result is read: GC must not free
        # a round whose request is still live
        world = ThreadOobWorld(2)
        eps = world.endpoints()
        reqs = [[ep.allgather(bytes([ep.oob_ep, i])) for i in range(3)]
                for ep in eps]
        for i in range(3):
            for r in range(2):
                assert reqs[r][i].wait() == [bytes([0, i]), bytes([1, i])]

    def test_result_idempotent(self):
        world = ThreadOobWorld(2)
        eps = world.endpoints()
        r0 = eps[0].allgather(b"a")
        r1 = eps[1].allgather(b"b")
        assert r0.wait() == [b"a", b"b"]
        assert r0.result == [b"a", b"b"]  # re-read after GC-eligible
        assert r1.wait() == [b"a", b"b"]


class TestTeamSplit:
    def test_create_from_parent(self):
        job = UccJob(4)
        try:
            parents = job.create_team()
            subs = [Team.create_from_parent(parents[r], [0, 2])
                    for r in range(4)]
            assert subs[1] is None and subs[3] is None
            members = [subs[0], subs[2]]
            # NB: create_test actively drives the state machine, so every
            # member must be polled each pass (list, not short-circuit)
            job.progress_until(lambda: all(
                [t.create_test() != Status.IN_PROGRESS for t in members]))
            assert all(t.create_test() == Status.OK for t in members)
            count = 4
            dsts = [np.zeros(count, np.int32) for _ in range(2)]
            reqs = [members[i].collective_init(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(np.full(count, i + 1, np.int32), count,
                               DataType.INT32),
                dst=BufferInfo(dsts[i], count, DataType.INT32),
                op=ReductionOp.SUM)) for i in range(2)]
            for rq in reqs:
                rq.post()
            job.progress_until(lambda: all(
                rq.test() != Status.IN_PROGRESS for rq in reqs))
            for i in range(2):
                np.testing.assert_array_equal(dsts[i], np.full(count, 3))
        finally:
            job.cleanup()


class TestBadInput:
    def test_noncontiguous_buffer_rejected(self):
        job = UccJob(2)
        try:
            teams = job.create_team()
            from ucc_tpu import CollArgsFlags
            bad = np.zeros((8, 2), np.float32)[:, 0]   # non-contiguous view
            good = np.zeros(8, np.float32)
            reqs = []
            for r in range(2):
                reqs.append(teams[r].collective_init(CollArgs(
                    coll_type=CollType.ALLREDUCE,
                    src=BufferInfo(bad if r == 0 else good, 8,
                                   DataType.FLOAT32),
                    dst=BufferInfo(np.zeros(8, np.float32), 8,
                                   DataType.FLOAT32),
                    op=ReductionOp.SUM,
                    flags=CollArgsFlags.TIMEOUT, timeout=0.5)))
            for rq in reqs:
                rq.post()
            job.progress_until(lambda: all(
                rq.test() != Status.IN_PROGRESS for rq in reqs), timeout=10)
            # rank 0 fails cleanly with invalid-param; rank 1's peer never
            # arrives so its per-coll timeout fires (reference timeout
            # semantics, ucc_progress_queue_st.c:35-45)
            assert reqs[0].test().is_error
            assert reqs[1].test() == Status.ERR_TIMED_OUT
        finally:
            job.cleanup()


class TestTransportTruncation:
    """ADVICE r1: a send larger than the posted recv buffer must surface
    as an error on the recv request (and fail the task via wait()), not
    silently truncate."""

    def test_deliver_flags_truncation(self):
        from ucc_tpu.tl.host.transport import (Mailbox, RecvReq, SendReq,
                                               _PendingSend)
        mb = Mailbox()
        key = ("t", 1, 0, 0)
        req = RecvReq(np.zeros(4, np.float32))
        mb.post_recv(key, req)
        ps = _PendingSend(np.arange(10, dtype=np.float32), SendReq(), False)
        mb.push(key, ps)
        assert req.done and ps.req.done
        assert req.error is not None and "truncated" in req.error

    def test_smaller_send_is_fine(self):
        from ucc_tpu.tl.host.transport import (Mailbox, RecvReq, SendReq,
                                               _PendingSend)
        mb = Mailbox()
        key = ("t", 2, 0, 0)
        req = RecvReq(np.zeros(8, np.float32))
        mb.post_recv(key, req)
        mb.push(key, _PendingSend(np.ones(3, np.float32), SendReq(), False))
        assert req.done and req.error is None and req.nbytes == 3

    def test_wait_raises_on_truncation(self):
        from ucc_tpu.tl.host.task import HostCollTask
        from ucc_tpu.tl.host.transport import RecvReq
        req = RecvReq(np.zeros(2, np.float32))
        req.done = True
        req.error = "message truncated: test"
        task = object.__new__(HostCollTask)
        with pytest.raises(UccError):
            list(task.wait(req))
