"""Regressions for review findings: non-power-of-radix sizes, OOB GC,
team split, contiguity, algorithm exception surfacing."""
import numpy as np
import pytest

import ucc_tpu
from ucc_tpu import (BufferInfo, CollArgs, CollType, DataType, ReductionOp,
                     Status, Team, ThreadOobWorld, UccError)

from harness import UccJob


class TestAwkwardTeamSizes:
    """Sizes where n_extra > full for radix 4 (9..15) deadlocked the
    knomial extra/proxy fold — and team create with it (service allreduce
    uses the same algorithm)."""

    @pytest.mark.parametrize("n", [6, 7, 9, 11, 13, 15])
    def test_allreduce(self, n):
        job = UccJob(n)
        try:
            teams = job.create_team()
            count = 21
            srcs = [np.full(count, r + 1.0, np.float64) for r in range(n)]
            dsts = [np.zeros(count, np.float64) for _ in range(n)]
            job.run_coll(teams, lambda r: CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(srcs[r], count, DataType.FLOAT64),
                dst=BufferInfo(dsts[r], count, DataType.FLOAT64),
                op=ReductionOp.SUM))
            expect = n * (n + 1) / 2
            for r in range(n):
                np.testing.assert_allclose(dsts[r], expect)
        finally:
            job.cleanup()


class TestOobRounds:
    def test_pipelined_rounds_before_reads(self):
        # 3 allgathers posted before any result is read: GC must not free
        # a round whose request is still live
        world = ThreadOobWorld(2)
        eps = world.endpoints()
        reqs = [[ep.allgather(bytes([ep.oob_ep, i])) for i in range(3)]
                for ep in eps]
        for i in range(3):
            for r in range(2):
                assert reqs[r][i].wait() == [bytes([0, i]), bytes([1, i])]

    def test_result_idempotent(self):
        world = ThreadOobWorld(2)
        eps = world.endpoints()
        r0 = eps[0].allgather(b"a")
        r1 = eps[1].allgather(b"b")
        assert r0.wait() == [b"a", b"b"]
        assert r0.result == [b"a", b"b"]  # re-read after GC-eligible
        assert r1.wait() == [b"a", b"b"]


class TestTeamSplit:
    def test_create_from_parent(self):
        job = UccJob(4)
        try:
            parents = job.create_team()
            subs = [Team.create_from_parent(parents[r], [0, 2])
                    for r in range(4)]
            assert subs[1] is None and subs[3] is None
            members = [subs[0], subs[2]]
            # NB: create_test actively drives the state machine, so every
            # member must be polled each pass (list, not short-circuit)
            job.progress_until(lambda: all(
                [t.create_test() != Status.IN_PROGRESS for t in members]))
            assert all(t.create_test() == Status.OK for t in members)
            count = 4
            dsts = [np.zeros(count, np.int32) for _ in range(2)]
            reqs = [members[i].collective_init(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(np.full(count, i + 1, np.int32), count,
                               DataType.INT32),
                dst=BufferInfo(dsts[i], count, DataType.INT32),
                op=ReductionOp.SUM)) for i in range(2)]
            for rq in reqs:
                rq.post()
            job.progress_until(lambda: all(
                rq.test() != Status.IN_PROGRESS for rq in reqs))
            for i in range(2):
                np.testing.assert_array_equal(dsts[i], np.full(count, 3))
        finally:
            job.cleanup()


class TestBadInput:
    def test_noncontiguous_buffer_rejected(self):
        job = UccJob(2)
        try:
            teams = job.create_team()
            from ucc_tpu import CollArgsFlags
            bad = np.zeros((8, 2), np.float32)[:, 0]   # non-contiguous view
            good = np.zeros(8, np.float32)
            reqs = []
            for r in range(2):
                reqs.append(teams[r].collective_init(CollArgs(
                    coll_type=CollType.ALLREDUCE,
                    src=BufferInfo(bad if r == 0 else good, 8,
                                   DataType.FLOAT32),
                    dst=BufferInfo(np.zeros(8, np.float32), 8,
                                   DataType.FLOAT32),
                    op=ReductionOp.SUM,
                    flags=CollArgsFlags.TIMEOUT, timeout=0.5)))
            for rq in reqs:
                rq.post()
            job.progress_until(lambda: all(
                rq.test() != Status.IN_PROGRESS for rq in reqs), timeout=10)
            # rank 0 fails cleanly with invalid-param; rank 1's peer never
            # arrives so its per-coll timeout fires (reference timeout
            # semantics, ucc_progress_queue_st.c:35-45)
            assert reqs[0].test().is_error
            assert reqs[1].test() == Status.ERR_TIMED_OUT
        finally:
            job.cleanup()


class TestTransportTruncation:
    """ADVICE r1: a send larger than the posted recv buffer must surface
    as an error on the recv request (and fail the task via wait()), not
    silently truncate."""

    def test_deliver_flags_truncation(self):
        from ucc_tpu.tl.host.transport import (Mailbox, RecvReq, SendReq,
                                               _PendingSend)
        mb = Mailbox()
        key = ("t", 1, 0, 0)
        req = RecvReq(np.zeros(4, np.float32))
        mb.post_recv(key, req)
        ps = _PendingSend(np.arange(10, dtype=np.float32), SendReq(), False)
        mb.push(key, ps)
        assert req.done and ps.req.done
        assert req.error is not None and "truncated" in req.error

    def test_smaller_send_is_fine(self):
        from ucc_tpu.tl.host.transport import (Mailbox, RecvReq, SendReq,
                                               _PendingSend)
        mb = Mailbox()
        key = ("t", 2, 0, 0)
        req = RecvReq(np.zeros(8, np.float32))
        mb.post_recv(key, req)
        mb.push(key, _PendingSend(np.ones(3, np.float32), SendReq(), False))
        assert req.done and req.error is None and req.nbytes == 3

    def test_wait_raises_on_truncation(self):
        from ucc_tpu.tl.host.task import HostCollTask
        from ucc_tpu.tl.host.transport import RecvReq
        req = RecvReq(np.zeros(2, np.float32))
        req.done = True
        req.error = "message truncated: test"
        task = object.__new__(HostCollTask)
        with pytest.raises(UccError):
            list(task.wait(req))


class _FakeReq:
    def __init__(self, done=True, error=None):
        self.done = done
        self.error = error

    def test(self):
        return self.done


class TestBatchedAllgatherSendErrors:
    """tl/host/allgather.py linear_batched: completed sends were dropped
    from the window without checking r.error — an errored send left the
    collective spinning (recvs never matched) instead of failing it."""

    def _task(self):
        from ucc_tpu.tl.host.allgather import AllgatherLinearBatched
        t = object.__new__(AllgatherLinearBatched)
        count = 8
        src = np.arange(4, dtype=np.float64)
        dst = np.zeros(count)
        t.args = CollArgs(
            coll_type=CollType.ALLGATHER,
            src=BufferInfo(src, 4, DataType.FLOAT64),
            dst=BufferInfo(dst, count, DataType.FLOAT64))
        t.gsize, t.grank, t.nreqs = 2, 0, 1
        t.recv_nb = lambda peer, buf, slot=0: _FakeReq(done=False)
        return t

    def test_errored_send_fails_the_collective(self):
        t = self._task()
        t.send_nb = lambda peer, data, slot=0: _FakeReq(
            done=True, error="connection reset by peer")
        gen = t.run()
        with pytest.raises(UccError):
            # bounded drive: pre-fix the errored send vanished and the
            # generator yielded forever waiting on the dead recvs
            for _ in range(50):
                next(gen)
            pytest.fail("errored send was dropped without failing")

    def test_errored_send_bumps_coll_errors(self, tmp_path):
        from ucc_tpu.obs import metrics
        metrics.reset()
        metrics.enable(file=str(tmp_path / "s.json"))
        try:
            t = self._task()
            t.send_nb = lambda peer, data, slot=0: _FakeReq(
                done=True, error="boom")
            gen = t.run()
            with pytest.raises(UccError):
                for _ in range(50):
                    next(gen)
            snap = metrics.snapshot()
            errs = snap["counters"].get("coll_errors", {})
            assert sum(v for k, v in errs.items()
                       if "tl/host|allgather" in k) == 1
        finally:
            metrics.disable()
            metrics.reset()


class TestClAgreeConvergence:
    """core/team.py: a rank whose every CL create fails used to raise in
    CL_CREATE without posting the CL_AGREE allgather — peers that DID
    create a CL then parked in CL_AGREE forever. The agreement round is
    now posted with an empty set so everyone converges to
    ERR_NO_RESOURCE."""

    def test_peers_converge_instead_of_hanging(self):
        import time as _time
        from ucc_tpu import TeamParams
        job = UccJob(2)
        teams = []
        try:
            # rank 1 loses every CL before team create (the asymmetric
            # component-load failure cl_hier can hit for real)
            job.contexts[1].cl_contexts.clear()
            sub_world = ThreadOobWorld(2)
            teams = [job.contexts[r].create_team_post(
                TeamParams(oob=sub_world.endpoint(r))) for r in range(2)]
            deadline = _time.monotonic() + 20.0
            while True:
                sts = [t.create_test() for t in teams]
                for ctx in job.contexts:
                    ctx.progress()
                if all(s != Status.IN_PROGRESS for s in sts):
                    break
                assert _time.monotonic() < deadline, \
                    f"peers hung instead of converging: {sts}"
            assert sts == [Status.ERR_NO_RESOURCE, Status.ERR_NO_RESOURCE]
        finally:
            for t in teams:
                t.destroy()
            job.cleanup()


class TestStoreServerDuplicateRanks:
    """core/oob.py _StoreServer: duplicate rank registrations counted
    toward the quota, so a retrying/misconfigured client could eat a
    genuine member's slot and wedge the whole rendezvous."""

    def test_duplicate_rank_rejected(self):
        import socket
        import struct
        import threading
        from ucc_tpu.core.oob import (TcpStoreOob, _recv_exact,
                                      _store_cookie)
        with socket.socket() as probe:
            probe.bind(("127.0.0.1", 0))
            port = probe.getsockname()[1]
        oob0 = TcpStoreOob(0, 2, port=port, key="duptest", timeout_s=10)
        rogue = None
        oob1 = None
        try:
            # rogue duplicate claim of rank 0 BEFORE rank 1 registers
            cookie = _store_cookie("duptest", 2)
            rogue = socket.create_connection(("127.0.0.1", port), 5)
            rogue.settimeout(5)
            assert _recv_exact(rogue, len(cookie)) == cookie
            rogue.sendall(cookie + struct.pack("!I", 0))
            # pre-fix: the dup filled the quota and this ctor timed out
            oob1 = TcpStoreOob(1, 2, port=port, key="duptest",
                               timeout_s=10)
            results = {}

            def gather(rank, oob, payload):
                results[rank] = oob.allgather(payload).result

            th = [threading.Thread(target=gather, args=(0, oob0, b"a")),
                  threading.Thread(target=gather, args=(1, oob1, b"b"))]
            for t in th:
                t.start()
            for t in th:
                t.join(timeout=10)
            assert results.get(0) == [b"a", b"b"]
            assert results.get(1) == [b"a", b"b"]
        finally:
            if rogue is not None:
                rogue.close()
            if oob1 is not None:
                oob1.close()
            oob0.close()


class TestSrgGatherSlots:
    """tl/host/sra.py: the SRG gather slot 190 collided with
    scatter-reduce round slots 172+rnd at round 18 (190 = 172+18); the
    gather/forward slots now live at a base no round counter reaches."""

    def test_slots_clear_of_round_space(self):
        from ucc_tpu.tl.host.sra import (_SRG_FORWARD_SLOT,
                                         _SRG_GATHER_SLOT)
        # scatter-reduce uses 172+rnd with rnd <= log2(team size); even
        # a 2**64-rank team stays under 172+64
        assert _SRG_GATHER_SLOT >= 172 + 64
        assert _SRG_FORWARD_SLOT >= 172 + 64
        assert _SRG_GATHER_SLOT != _SRG_FORWARD_SLOT

    def test_srg_reduce_with_extra_root(self, monkeypatch):
        # root >= full exercises BOTH moved slots (gather + forward to
        # the extra root)
        monkeypatch.setenv("UCC_TL_SHM_TUNE", "reduce:@srg_knomial:100")
        n, count, root = 3, 12, 2
        job = UccJob(n)
        try:
            teams = job.create_team()
            srcs = [np.full(count, r + 1.0) for r in range(n)]
            dsts = [np.zeros(count) if r == root else None
                    for r in range(n)]
            job.run_coll(teams, lambda r: CollArgs(
                coll_type=CollType.REDUCE, root=root,
                src=BufferInfo(srcs[r], count, DataType.FLOAT64),
                dst=(BufferInfo(dsts[r], count, DataType.FLOAT64)
                     if r == root else None),
                op=ReductionOp.SUM))
            np.testing.assert_allclose(dsts[root], 6.0)
        finally:
            job.cleanup()


class TestWaitTimeoutCancels:
    """core/coll.py: CollRequest.wait used to raise on deadline but
    leave the task IN_PROGRESS in the progress queue — finalize then
    raised forever and the posted ops were orphaned. wait now cancels
    the task (ERR_TIMED_OUT) before raising, so finalize works and the
    queue drains."""

    def test_wait_timeout_leaves_finalizable_request(self):
        job = UccJob(2)
        try:
            teams = job.create_team()
            count = 8
            dst = np.zeros(count, np.float64)
            # only rank 0 posts: the collective can never complete
            req = teams[0].collective_init(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(np.ones(count), count, DataType.FLOAT64),
                dst=BufferInfo(dst, count, DataType.FLOAT64),
                op=ReductionOp.SUM))
            req.post()
            with pytest.raises(UccError) as ei:
                req.wait(timeout=0.2)
            assert ei.value.status == Status.ERR_TIMED_OUT
            # the fix: task is terminal, finalize no longer raises
            assert req.test() == Status.ERR_TIMED_OUT
            req.finalize()
            # the queue drains the cancelled task instead of spinning it
            for _ in range(3):
                job.contexts[0].progress()
            assert len(job.contexts[0].progress_queue) == 0
        finally:
            job.cleanup()


class TestProgressExceptionSurfaced:
    """schedule/progress.py: a progress_fn crash was masked as a bare
    ERR_NO_MESSAGE with no traceback. The queue now logs the exception
    once with the task identity, keeps it on task.exc, and bumps
    coll_errors."""

    def test_exception_kept_on_task(self):
        import logging
        from ucc_tpu.obs import metrics
        from ucc_tpu.schedule.progress import ProgressQueue
        from ucc_tpu.schedule.task import CollTask

        class _Boom(CollTask):
            def __init__(self):
                super().__init__()
                self.calls = 0
                self.coll_name = "allreduce"
                self.alg_name = "boom"

            def post_fn(self):
                return Status.OK

            def progress_fn(self):
                self.calls += 1
                if self.calls > 1:   # survive the enqueue-time pass
                    raise RuntimeError("boom")

        # the ucc root logger is propagate=False, so capture with our
        # own handler instead of caplog
        records = []

        class _Capture(logging.Handler):
            def emit(self, record):
                records.append(record)

        from ucc_tpu.utils.log import get_logger
        sched_logger = get_logger("schedule")
        cap = _Capture(level=logging.ERROR)
        sched_logger.addHandler(cap)
        metrics.reset()
        metrics.enable()
        try:
            q = ProgressQueue()
            t = _Boom()
            t.progress_queue = q
            t.post()
            q.progress()
            assert t.super_status == Status.ERR_NO_MESSAGE
            assert isinstance(t.exc, RuntimeError)
            assert "boom" in str(t.exc)
            # logged once, naming the task
            msgs = [r for r in records if "failing with" in r.getMessage()]
            assert len(msgs) == 1
            assert "_Boom" in msgs[0].getMessage()
            snap = metrics.snapshot()
            errs = snap["counters"].get("coll_errors", {})
            assert sum(errs.values()) >= 1
        finally:
            sched_logger.removeHandler(cap)
            metrics.disable()
            metrics.reset()


class TestStoreServerBootstrapDeadline:
    """core/oob.py: _StoreServer waited for stragglers forever — one
    crashed rank hung the whole job's bootstrap. After the bootstrap
    deadline, registered clients now get ERR_TIMED_OUT naming the
    absent ranks."""

    def test_absent_ranks_named(self):
        from ucc_tpu.core.oob import TcpStoreOob
        import socket as pysock

        probe = pysock.socket()
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()
        # rank 0 of a 3-rank job; ranks 1 and 2 never arrive
        oob = TcpStoreOob(0, 3, port=port, timeout_s=5,
                          bootstrap_timeout_s=0.5)
        try:
            req = oob.allgather(b"hello")
            with pytest.raises(UccError) as ei:
                req.wait()
            assert ei.value.status == Status.ERR_TIMED_OUT
            assert "[1, 2]" in str(ei.value)
        finally:
            oob.close()

    def test_no_deadline_waits(self):
        """bootstrap_timeout_s <= 0 preserves the wait-forever contract
        (in-process servers constructed directly by older tests)."""
        from ucc_tpu.core.oob import _StoreServer, _store_cookie
        srv = _StoreServer(2, ("127.0.0.1", 0), _store_cookie("j", 2),
                           bootstrap_timeout_s=0.0)
        try:
            import time as _t
            _t.sleep(0.3)
            assert srv.thread.is_alive()   # still patiently listening
        finally:
            srv.close()


class TestPeerTimeoutTerminal:
    """The no-hang invariant, minimal form: when one rank never posts,
    every OTHER rank's collective must reach a terminal status within
    its timeout — cancelled with posted ops unwound, not parked
    IN_PROGRESS (the round-5 probe-log `hang` wall)."""

    def test_peers_reach_terminal_status(self):
        from ucc_tpu.constants import CollArgsFlags
        n = 3
        job = UccJob(n)
        try:
            teams = job.create_team()
            count = 8
            dsts = [np.zeros(count, np.float64) for _ in range(n)]
            # rank 2 never posts (simulated silent death)
            reqs = [teams[r].collective_init(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(np.ones(count), count, DataType.FLOAT64),
                dst=BufferInfo(dsts[r], count, DataType.FLOAT64),
                op=ReductionOp.SUM, flags=CollArgsFlags.TIMEOUT,
                timeout=0.3)) for r in range(n - 1)]
            for rq in reqs:
                rq.post()
            import time as _t
            deadline = _t.monotonic() + 5.0
            while _t.monotonic() < deadline:
                for c in job.contexts:
                    c.progress()
                if all([rq.test() != Status.IN_PROGRESS for rq in reqs]):
                    break
            sts = [rq.test() for rq in reqs]
            assert all(s == Status.ERR_TIMED_OUT for s in sts), sts
            for rq in reqs:
                rq.finalize()       # terminal => finalizable
        finally:
            job.cleanup()


class TestIntegrityOffModeFree:
    """UCC_INTEGRITY=off must be measurably free: the send path computes
    NO checksum (the parked match metadata stays None, no zlib.crc32
    call) and collective_init binds no attestation state — the hot
    paths are byte-identical to a build without the subsystem."""

    def test_send_path_computes_no_checksum(self, monkeypatch):
        from ucc_tpu import integrity
        from ucc_tpu.tl.host import transport as tmod
        integrity.reset()
        assert not integrity.ENABLED
        calls = []
        real = tmod.zlib.crc32

        class _Probe:
            crc32 = staticmethod(lambda *a: calls.append(1) or real(*a))

        monkeypatch.setattr(tmod, "zlib", _Probe)
        mb = tmod.Mailbox()
        key = ("off", 0, (1 << 20) + 1, 0, 0)
        mb.send(key, np.arange(64, dtype=np.uint8), 8192)
        assert not calls, "off-mode send computed a checksum"
        assert mb.unexpected[key][0].crc is None

    def test_no_attest_bound_when_off(self):
        from ucc_tpu import integrity
        integrity.reset()
        n = 2
        job = UccJob(n)
        try:
            teams = job.create_team()
            dsts = [np.zeros(8, np.float64) for _ in range(n)]
            reqs = [teams[r].collective_init(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(np.ones(8), 8, DataType.FLOAT64),
                dst=BufferInfo(dsts[r], 8, DataType.FLOAT64),
                op=ReductionOp.SUM)) for r in range(n)]
            assert all(rq._attest is None for rq in reqs)
            for rq in reqs:
                rq.post()
            job.progress_until(lambda: all(
                rq.test() != Status.IN_PROGRESS for rq in reqs))
        finally:
            job.cleanup()


class TestCorruptPinnedPlanEligibility:
    """A pinned UCC_FAULT=corrupt spec makes plan-engagement rank-
    variant (only the corruptor interprets) — but CANDIDATE selection
    must stay rank-invariant, or the corruptor falls back to a classic
    algorithm with a different slot scheme and deadlocks the team (the
    interpreted plan IR is wire-compatible with peer plans; a classic
    algorithm is not)."""

    def test_candidate_selection_is_rank_invariant(self):
        from ucc_tpu.dsl.plan import _fault_blocks_plans
        from ucc_tpu.fault import inject
        inject.reset()
        try:
            inject.configure("corrupt=0.5,corrupt_rank=1", seed=0)
            # invariant probe (candidate selection): same answer on
            # every rank — the generated task survives everywhere
            assert _fault_blocks_plans(None, invariant=True) is False
            # rank-variant probe (plan engage): with the team unknown,
            # conservatively interpret
            assert _fault_blocks_plans(None) is True
            # an UNPINNED corrupt spec can strike any sender: plans
            # off everywhere, invariantly
            inject.configure("corrupt=0.5", seed=0)
            assert _fault_blocks_plans(None, invariant=True) is True
            assert _fault_blocks_plans(None) is True
        finally:
            inject.reset()
