"""Out-of-tree TL coll plugin used by tests/test_coll_plugin.py — the
ucc_tl.h:64-69 / tl/ucp/coll_plugins analog: injects an extra allreduce
algorithm ("dummy") into tl/shm via UCC_TL_SHM_COLL_PLUGINS, selectable
through the normal TUNE string. Delegates the actual work to the
knomial task (plugins compose framework algorithms freely) and counts
invocations so the test can prove the plugin path ran."""

from ucc_tpu.constants import CollType
from ucc_tpu.tl.base import AlgSpec
from ucc_tpu.tl.host.knomial import AllreduceKnomial

INIT_CALLS = 0


def ucc_coll_plugin(tl_team):
    def init(ia, team):
        global INIT_CALLS
        INIT_CALLS += 1
        return AllreduceKnomial(ia, team)

    return {CollType.ALLREDUCE: [AlgSpec(100, "dummy", init)]}
