"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform (the reference tests a
16-rank in-process job the same way — test/gtest/common/test_ucc.h:209; we
mirror it with 8 virtual chips so multi-chip sharding paths compile and
execute without TPU hardware). Must run before jax is first imported.
"""
import os

# FORCE (not setdefault): this environment presets JAX_PLATFORMS=axon, and
# an inherited accelerator platform makes ensure_live_backend probe the
# (possibly wedged) tunnel for its full timeout inside the test run.
# UCC_TPU_REAL_CHIP=1 (set by tools/tpu_probe.py during a live chip
# window) disables the forcing so the real-chip compile tests actually
# see the accelerator instead of a virtual CPU mesh.
_REAL_CHIP = os.environ.get("UCC_TPU_REAL_CHIP") == "1"
if not _REAL_CHIP:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

# the always-on flight recorder (obs/flight.py) dumps to ucc_flight.json
# in the CWD by default; tests that trigger collection (watchdog dumps,
# rank-failure drills) must not litter the repo checkout — route the
# default to a per-session temp file (read at ucc_tpu import, so this
# must run before the first test import)
if "UCC_FLIGHT_FILE" not in os.environ:
    import tempfile
    os.environ["UCC_FLIGHT_FILE"] = os.path.join(
        tempfile.gettempdir(), f"ucc_flight_test_{os.getpid()}.json")

# the DSL program/search/cost caches (ucc_tpu/dsl, ISSUE 14) default to
# ~/.cache/ucc_tpu — tests must neither read a developer's real caches
# (stale searched winners would change candidate lists under test) nor
# write into them; route all three to per-session temp files
import tempfile as _tf
for _var, _name in (("UCC_GEN_PROG_CACHE", "programs.pkl"),
                    ("UCC_GEN_SEARCH_CACHE", "search.json"),
                    ("UCC_GEN_COST_CACHE", "cost.json")):
    if _var not in os.environ:
        os.environ[_var] = os.path.join(
            _tf.gettempdir(), f"ucc_test_{os.getpid()}_{_name}")

# this environment preloads jax at interpreter startup, so the env vars
# above may arrive too late for jax's import-time config read — force the
# platform through the runtime config as well (backends init lazily)
import sys
if not _REAL_CHIP and "jax" in sys.modules:
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 - backend already initialized
        pass


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`; long-running acceptance drills (the
    # churn soak) opt out of it with this marker
    config.addinivalue_line(
        "markers", "slow: long-running acceptance drill, excluded from "
        "the tier-1 sweep")
