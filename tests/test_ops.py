"""ucc_tpu.ops — traceable collectives inside user shard_map/jit programs
(the TPU-native triggered-post execution model, reference ucc.h:2050-2260)."""
import numpy as np
import pytest

from ucc_tpu.constants import ReductionOp
from ucc_tpu import ops

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402


def get_shard_map():
    if hasattr(jax, "shard_map"):
        return jax.shard_map
    from jax.experimental.shard_map import shard_map
    return shard_map


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return jax.make_mesh((8,), ("r",))


def run_sm(mesh, fn, x, out_specs=P("r", None)):
    sm = get_shard_map()
    try:
        wrapped = sm(fn, mesh=mesh, in_specs=P("r", None),
                     out_specs=out_specs, check_vma=False)
    except TypeError:
        wrapped = sm(fn, mesh=mesh, in_specs=P("r", None),
                     out_specs=out_specs, check_rep=False)
    return jax.jit(wrapped)(x)


class TestOpsInJit:
    def test_allreduce_sum(self, mesh):
        x = jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4)
        out = np.asarray(jax.device_get(
            run_sm(mesh, lambda v: ops.allreduce(v, ReductionOp.SUM), x)))
        expect = np.sum(np.asarray(x), axis=0)
        for r in range(8):
            np.testing.assert_allclose(out[r], expect)

    def test_allreduce_ring_matches_psum(self, mesh):
        x = jnp.arange(8 * 16, dtype=jnp.float32).reshape(8, 16)
        ring = run_sm(mesh, lambda v: ops.allreduce_ring(v, ReductionOp.SUM), x)
        psum = run_sm(mesh, lambda v: ops.allreduce(v, ReductionOp.SUM), x)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(psum),
                                   rtol=1e-5)

    def test_reduce_scatter(self, mesh):
        x = jnp.ones((8, 16), jnp.float32)
        out = run_sm(mesh, lambda v: ops.reduce_scatter(v, ReductionOp.SUM), x)
        assert out.shape == (8, 2)
        np.testing.assert_allclose(np.asarray(out), 8.0)

    def test_allgather(self, mesh):
        x = jnp.arange(8 * 2, dtype=jnp.int32).reshape(8, 2)
        out = np.asarray(jax.device_get(run_sm(mesh, ops.allgather, x)))
        assert out.shape == (8, 16)
        for r in range(8):
            np.testing.assert_array_equal(out[r], np.arange(16))

    def test_alltoall(self, mesh):
        n, blk = 8, 2
        x = jnp.arange(n * n * blk, dtype=jnp.int32).reshape(n, n * blk)
        out = np.asarray(jax.device_get(run_sm(mesh, ops.alltoall, x)))
        xin = np.asarray(x)
        for r in range(n):
            expect = np.concatenate(
                [xin[p, r * blk:(r + 1) * blk] for p in range(n)])
            np.testing.assert_array_equal(out[r], expect)

    def test_bcast(self, mesh):
        x = jnp.stack([jnp.full(4, float(r + 1)) for r in range(8)])
        out = run_sm(mesh, lambda v: ops.bcast(v, root=3), x)
        np.testing.assert_allclose(np.asarray(out), 4.0)

    def test_minloc(self, mesh):
        vals = np.random.default_rng(0).random((8, 6)).astype(np.float32)
        pairs = np.empty((8, 12), np.float32)
        pairs[:, 0::2] = vals
        pairs[:, 1::2] = np.arange(8)[:, None]
        out = np.asarray(jax.device_get(
            run_sm(mesh, lambda v: ops.allreduce(v, ReductionOp.MINLOC),
                   jnp.asarray(pairs))))
        np.testing.assert_allclose(out[0][0::2], vals.min(axis=0))
        np.testing.assert_array_equal(out[0][1::2].astype(np.int64),
                                      vals.argmin(axis=0))

    def test_composes_with_grad(self, mesh):
        """ops inside a differentiated program — the data-parallel
        gradient-sync use case (psum is linear, grad flows)."""
        sm = get_shard_map()

        def loss(w, x):
            def shard_fn(w, x):
                local = jnp.sum((x @ w) ** 2, keepdims=True)[None]
                return ops.allreduce(local, ReductionOp.SUM)
            try:
                f = sm(shard_fn, mesh=mesh, in_specs=(P(), P("r", None)),
                       out_specs=P(None, None), check_vma=False)
            except TypeError:
                f = sm(shard_fn, mesh=mesh, in_specs=(P(), P("r", None)),
                       out_specs=P(None, None), check_rep=False)
            return f(w, x)[0, 0]

        w = jnp.ones((4,), jnp.float32)
        x = jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4) / 10
        g = jax.jit(jax.grad(loss))(w, x)
        assert g.shape == (4,) and bool(jnp.all(jnp.isfinite(g)))


class TestOpsAlltoallv:
    """In-jit alltoallv with a static counts matrix (packed layout)."""

    @pytest.mark.parametrize("seed", [0, 9])
    def test_matches_numpy(self, seed):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ucc_tpu.utils.jaxshim import shard_map_compat
        n = min(8, len(jax.devices()))
        if n < 2:
            pytest.skip("needs >= 2 devices")
        rng = np.random.default_rng(seed)
        m = rng.integers(0, 5, size=(n, n))
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:n]), ("r",))
        max_src = max(1, int(m.sum(axis=1).max()))
        max_dst = max(1, int(m.sum(axis=0).max()))
        srcs = []
        for i in range(n):
            tot = int(m[i].sum())
            s = np.zeros(max_src, np.float32)
            s[:tot] = np.arange(tot) + 100 * i
            srcs.append(s)
        garr = jax.make_array_from_single_device_arrays(
            (n * max_src,), NamedSharding(mesh, P("r")),
            [jax.device_put(jnp.asarray(srcs[i]), mesh.devices.reshape(-1)[i])
             for i in range(n)])

        prog = jax.jit(shard_map_compat(
            lambda x: ops.alltoallv(x, m), mesh, P("r"), P("r")))
        out = prog(garr)
        shards = {s.device: np.asarray(s.data)
                  for s in out.addressable_shards}
        devs = mesh.devices.reshape(-1)
        for i in range(n):
            got = shards[devs[i]]
            off = 0
            for p in range(n):
                c = int(m[p, i])
                sd = int(np.sum(m[p, :i]))
                expect = (np.arange(int(m[p].sum())) + 100 * p)[sd:sd + c]
                np.testing.assert_array_equal(got[off:off + c], expect)
                off += c
            np.testing.assert_array_equal(got[off:max_dst], 0)

    def test_allgatherv_matches_numpy(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ucc_tpu.utils.jaxshim import shard_map_compat
        n = min(8, len(jax.devices()))
        if n < 2:
            pytest.skip("needs >= 2 devices")
        counts = [(i % 4) for i in range(n)]      # includes zeros
        maxc = max(1, max(counts))
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:n]), ("r",))
        srcs = []
        for i in range(n):
            s = np.zeros(maxc, np.int32)
            s[:counts[i]] = np.arange(counts[i]) + 10 * i
            srcs.append(s)
        garr = jax.make_array_from_single_device_arrays(
            (n * maxc,), NamedSharding(mesh, P("r")),
            [jax.device_put(jnp.asarray(srcs[i]),
                            mesh.devices.reshape(-1)[i])
             for i in range(n)])
        prog = jax.jit(shard_map_compat(
            lambda x: ops.allgatherv(x, counts), mesh, P("r"), P(None)))
        out = np.asarray(prog(garr))
        expect = np.concatenate(
            [np.arange(counts[i], dtype=np.int32) + 10 * i
             for i in range(n)])
        np.testing.assert_array_equal(out, expect)
