"""Per-algorithm correctness sweep via the TUNE DSL — mirrors the
reference's alg-variant coverage (each tl_ucp alg id tested across team
sizes): every algorithm forced via UCC_TL_SHM_TUNE and validated against
numpy expectations, including NOT_SUPPORTED fallback behavior."""
import numpy as np
import pytest

import ucc_tpu
from ucc_tpu import (BufferInfo, CollArgs, CollType, DataType, ReductionOp,
                     Status)

from harness import UccJob


def run_with_tune(tune, n, make_args, check, monkeypatch):
    monkeypatch.setenv("UCC_TL_SHM_TUNE", tune)
    job = UccJob(n)
    try:
        teams = job.create_team()
        reqs = job.run_coll(teams, make_args)
        check()
    finally:
        job.cleanup()


class TestAllgatherAlgs:
    @pytest.mark.parametrize("alg", ["ring", "bruck", "neighbor", "linear", "sparbit", "knomial"])
    @pytest.mark.parametrize("n", [2, 4, 6, 8])
    def test_allgather(self, alg, n, monkeypatch):
        per = 7
        srcs = [np.arange(per, dtype=np.int64) + 100 * r for r in range(n)]
        dsts = [np.zeros(per * n, dtype=np.int64) for _ in range(n)]
        expect = np.concatenate(srcs)

        def check():
            for r in range(n):
                np.testing.assert_array_equal(dsts[r], expect)

        run_with_tune(f"allgather:@{alg}:inf", n, lambda r: CollArgs(
            coll_type=CollType.ALLGATHER,
            src=BufferInfo(srcs[r], per, DataType.INT64),
            dst=BufferInfo(dsts[r], per * n, DataType.INT64)),
            check, monkeypatch)

    def test_neighbor_odd_falls_back(self, monkeypatch):
        """Odd team size: neighbor raises NOT_SUPPORTED, fallback chain
        must pick another algorithm and still complete correctly."""
        n, per = 5, 4
        srcs = [np.full(per, r, np.int32) for r in range(n)]
        dsts = [np.zeros(per * n, np.int32) for _ in range(n)]

        def check():
            expect = np.concatenate(srcs)
            for r in range(n):
                np.testing.assert_array_equal(dsts[r], expect)

        run_with_tune("allgather:@neighbor:inf", n, lambda r: CollArgs(
            coll_type=CollType.ALLGATHER,
            src=BufferInfo(srcs[r], per, DataType.INT32),
            dst=BufferInfo(dsts[r], per * n, DataType.INT32)),
            check, monkeypatch)


class TestBcastAlgs:
    @pytest.mark.parametrize("alg", ["knomial", "sag_knomial", "dbt"])
    @pytest.mark.parametrize("n", [2, 3, 5, 8])
    @pytest.mark.parametrize("root", [0, 1])
    def test_bcast(self, alg, n, root, monkeypatch):
        if root >= n:
            pytest.skip("root out of range")
        count = 64
        bufs = [(np.arange(count, dtype=np.float32) * 3 if r == root else
                 np.zeros(count, np.float32)) for r in range(n)]
        expect = np.arange(count, dtype=np.float32) * 3

        def check():
            for r in range(n):
                np.testing.assert_array_equal(bufs[r], expect)

        run_with_tune(f"bcast:@{alg}:inf", n, lambda r: CollArgs(
            coll_type=CollType.BCAST, root=root,
            src=BufferInfo(bufs[r], count, DataType.FLOAT32)),
            check, monkeypatch)

    def test_sag_small_count_falls_back(self, monkeypatch):
        # count < team size: sag raises NOT_SUPPORTED; knomial serves
        n = 4
        bufs = [(np.ones(2, np.int32) * 9 if r == 0 else
                 np.zeros(2, np.int32)) for r in range(n)]

        def check():
            for r in range(n):
                np.testing.assert_array_equal(bufs[r], 9)

        run_with_tune("bcast:@sag_knomial:inf", n, lambda r: CollArgs(
            coll_type=CollType.BCAST, root=0,
            src=BufferInfo(bufs[r], 2, DataType.INT32)),
            check, monkeypatch)


class TestReduceAlgs:
    @pytest.mark.parametrize("alg", ["knomial", "dbt", "srg_knomial"])
    @pytest.mark.parametrize("n", [2, 3, 5, 8])
    def test_reduce(self, alg, n, monkeypatch):
        count = 50
        root = n - 1
        srcs = [np.full(count, r + 1.0, np.float64) for r in range(n)]
        dst = np.zeros(count, np.float64)

        def check():
            np.testing.assert_allclose(dst, n * (n + 1) / 2)

        run_with_tune(f"reduce:@{alg}:inf", n, lambda r: CollArgs(
            coll_type=CollType.REDUCE, root=root,
            src=BufferInfo(srcs[r], count, DataType.FLOAT64),
            dst=BufferInfo(dst, count, DataType.FLOAT64) if r == root
            else None, op=ReductionOp.SUM), check, monkeypatch)

    def test_reduce_dbt_avg(self, monkeypatch):
        n, count = 4, 33
        srcs = [np.full(count, float(r), np.float64) for r in range(n)]
        dst = np.zeros(count, np.float64)

        def check():
            np.testing.assert_allclose(dst, 1.5)

        run_with_tune("reduce:@dbt:inf", n, lambda r: CollArgs(
            coll_type=CollType.REDUCE, root=0,
            src=BufferInfo(srcs[r], count, DataType.FLOAT64),
            dst=BufferInfo(dst, count, DataType.FLOAT64) if r == 0
            else None, op=ReductionOp.AVG), check, monkeypatch)


class TestGatherScatterKnomial:
    @pytest.mark.parametrize("coll,alg", [(CollType.GATHER, "knomial"),
                                          (CollType.SCATTER, "knomial")])
    @pytest.mark.parametrize("n", [2, 3, 5, 8])
    @pytest.mark.parametrize("root", [0, 2])
    def test_tree(self, coll, alg, n, root, monkeypatch):
        root = root % n       # test a valid equivalent, never skip
        per = 6
        name = "gather" if coll == CollType.GATHER else "scatter"
        if coll == CollType.GATHER:
            srcs = [np.arange(per, dtype=np.int32) + 10 * r
                    for r in range(n)]
            dst = np.zeros(per * n, np.int32)

            def make(r):
                return CollArgs(coll_type=coll, root=root,
                                src=BufferInfo(srcs[r], per, DataType.INT32),
                                dst=BufferInfo(dst, per * n, DataType.INT32)
                                if r == root else None)

            def check():
                np.testing.assert_array_equal(dst, np.concatenate(srcs))
        else:
            src = np.arange(per * n, dtype=np.int32)
            dsts = [np.zeros(per, np.int32) for _ in range(n)]

            def make(r):
                return CollArgs(coll_type=coll, root=root,
                                src=BufferInfo(src, per * n, DataType.INT32)
                                if r == root else None,
                                dst=BufferInfo(dsts[r], per, DataType.INT32))

            def check():
                for r in range(n):
                    np.testing.assert_array_equal(
                        dsts[r], src[r * per:(r + 1) * per])

        run_with_tune(f"{name}:@{alg}:inf", n, make, check, monkeypatch)


class TestReduceScatterKnomial:
    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_pow2(self, n, monkeypatch):
        per = 8
        total = per * n
        srcs = [np.arange(total, dtype=np.float32) * (r + 1)
                for r in range(n)]
        dsts = [np.zeros(per, np.float32) for _ in range(n)]
        expect = np.sum(srcs, axis=0)

        def check():
            for r in range(n):
                np.testing.assert_allclose(dsts[r],
                                           expect[r * per:(r + 1) * per])

        run_with_tune("reduce_scatter:@knomial:inf", n, lambda r: CollArgs(
            coll_type=CollType.REDUCE_SCATTER,
            src=BufferInfo(srcs[r], total, DataType.FLOAT32),
            dst=BufferInfo(dsts[r], per, DataType.FLOAT32),
            op=ReductionOp.SUM), check, monkeypatch)

    def test_non_pow2_falls_back(self, monkeypatch):
        n, per = 3, 5
        total = per * n
        srcs = [np.ones(total, np.float32) * (r + 1) for r in range(n)]
        dsts = [np.zeros(per, np.float32) for _ in range(n)]

        def check():
            for r in range(n):
                np.testing.assert_allclose(dsts[r], 6.0)

        run_with_tune("reduce_scatter:@knomial:inf", n, lambda r: CollArgs(
            coll_type=CollType.REDUCE_SCATTER,
            src=BufferInfo(srcs[r], total, DataType.FLOAT32),
            dst=BufferInfo(dsts[r], per, DataType.FLOAT32),
            op=ReductionOp.SUM), check, monkeypatch)


class TestNewRound2Algs:
    """Round-2 algorithm gap closures (VERDICT missing #5): knomial
    allgatherv, bidirectional reduce_scatter ring, hybrid alltoallv."""

    @pytest.mark.parametrize("n", [2, 3, 5, 8])
    def test_allgatherv_knomial(self, n, monkeypatch):
        from ucc_tpu import BufferInfoV
        counts = [(r % 3) + 1 for r in range(n)]
        srcs = [np.arange(counts[r], dtype=np.int32) + 100 * r
                for r in range(n)]
        dsts = [np.zeros(sum(counts), np.int32) for _ in range(n)]

        def check():
            expect = np.concatenate(srcs)
            for r in range(n):
                np.testing.assert_array_equal(dsts[r], expect)

        run_with_tune("allgatherv:@knomial:inf", n, lambda r: CollArgs(
            coll_type=CollType.ALLGATHERV,
            src=BufferInfo(srcs[r], counts[r], DataType.INT32),
            dst=BufferInfoV(dsts[r], counts, None, DataType.INT32)),
            check, monkeypatch)

    @pytest.mark.parametrize("n", [2, 3, 4, 7, 8])
    @pytest.mark.parametrize("count", [16, 37])
    def test_reduce_scatter_ring_bidirectional(self, n, count, monkeypatch):
        from ucc_tpu.utils.mathutils import block_count, block_offset
        if count < n:
            pytest.skip("count < team size")
        srcs = [np.arange(count, dtype=np.float64) * (r + 1)
                for r in range(n)]
        dsts = [np.zeros(block_count(count, n, r), np.float64)
                for r in range(n)]

        def check():
            expect = np.sum(srcs, axis=0)
            for r in range(n):
                off = block_offset(count, n, r)
                np.testing.assert_allclose(
                    dsts[r], expect[off:off + block_count(count, n, r)])

        run_with_tune("reduce_scatter:@ring_bidirectional:inf", n,
                      lambda r: CollArgs(
                          coll_type=CollType.REDUCE_SCATTER,
                          src=BufferInfo(srcs[r], count, DataType.FLOAT64),
                          dst=BufferInfo(dsts[r], dsts[r].size,
                                         DataType.FLOAT64),
                          op=ReductionOp.SUM), check, monkeypatch)

    def test_reduce_scatter_bidir_avg(self, monkeypatch):
        n, count = 4, 24
        srcs = [np.full(count, r + 1.0, np.float64) for r in range(n)]
        dsts = [np.zeros(count // n, np.float64) for _ in range(n)]

        def check():
            for r in range(n):
                np.testing.assert_allclose(dsts[r], 2.5)

        run_with_tune("reduce_scatter:@ring_bidirectional:inf", n,
                      lambda r: CollArgs(
                          coll_type=CollType.REDUCE_SCATTER,
                          src=BufferInfo(srcs[r], count, DataType.FLOAT64),
                          dst=BufferInfo(dsts[r], count // n,
                                         DataType.FLOAT64),
                          op=ReductionOp.AVG), check, monkeypatch)

    @pytest.mark.parametrize("n", [2, 3, 5, 8])
    def test_alltoallv_hybrid(self, n, monkeypatch):
        """Mixed small/large pairs: larges go pairwise, smalls via the
        Bruck forwarding phase."""
        from ucc_tpu import BufferInfoV
        from ucc_tpu.tl.host.alltoall import AlltoallvHybrid
        rng = np.random.default_rng(7)
        thresh = AlltoallvHybrid.SMALL_THRESH
        # counts[s][d]: small (<= thresh) and large (> thresh) mixed
        m = np.zeros((n, n), dtype=int)
        for s_ in range(n):
            for d in range(n):
                m[s_][d] = int(rng.integers(0, 5)) if (s_ + d) % 2 == 0 \
                    else thresh + int(rng.integers(1, 50))
        srcs, dsts = [], []
        for r in range(n):
            scounts = [int(c) for c in m[r]]
            rcounts = [int(m[p][r]) for p in range(n)]
            srcs.append(np.arange(sum(scounts), dtype=np.int64) + 1000 * r)
            dsts.append(np.zeros(sum(rcounts), np.int64))

        def make(r):
            scounts = [int(c) for c in m[r]]
            rcounts = [int(m[p][r]) for p in range(n)]
            return CollArgs(
                coll_type=CollType.ALLTOALLV,
                src=BufferInfoV(srcs[r], scounts, None, DataType.INT64),
                dst=BufferInfoV(dsts[r], rcounts, None, DataType.INT64))

        def check():
            for r in range(n):
                off = 0
                for p in range(n):
                    c = int(m[p][r])
                    sd = int(np.sum(m[p][:r]))
                    expect = (np.arange(int(np.sum(m[p])), dtype=np.int64)
                              + 1000 * p)[sd:sd + c]
                    np.testing.assert_array_equal(dsts[r][off:off + c],
                                                  expect)
                    off += c

        run_with_tune("alltoallv:@hybrid:inf", n, make, check, monkeypatch)


class TestAllreduceDbt:
    """Fused allreduce-DBT: both halves flow concurrently, each tree's
    bcast starting when its half reaches the virtual root."""

    @pytest.mark.parametrize("n", [2, 3, 5, 8])
    @pytest.mark.parametrize("count", [10, 33, 257])
    def test_sum(self, n, count, monkeypatch):
        srcs = [np.arange(count, dtype=np.float64) * (r + 1)
                for r in range(n)]
        dsts = [np.zeros(count, np.float64) for _ in range(n)]

        def check():
            expect = np.sum(srcs, axis=0)
            for r in range(n):
                np.testing.assert_allclose(dsts[r], expect)

        run_with_tune("allreduce:@dbt:inf", n, lambda r: CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(srcs[r], count, DataType.FLOAT64),
            dst=BufferInfo(dsts[r], count, DataType.FLOAT64),
            op=ReductionOp.SUM), check, monkeypatch)

    def test_avg_inplace(self, monkeypatch):
        n, count = 6, 48
        bufs = [np.full(count, r + 1.0, np.float64) for r in range(n)]

        def check():
            for r in range(n):
                np.testing.assert_allclose(bufs[r], 3.5)

        from ucc_tpu import CollArgsFlags
        run_with_tune("allreduce:@dbt:inf", n, lambda r: CollArgs(
            coll_type=CollType.ALLREDUCE,
            dst=BufferInfo(bufs[r], count, DataType.FLOAT64),
            op=ReductionOp.AVG,
            flags=CollArgsFlags.IN_PLACE), check, monkeypatch)


class TestSraSrgRadix:
    """Arbitrary-radix SRA/SRG (sra_knomial.h generalizes the halving to
    radix r): radices {2,3,4} x pow2/non-pow2 team sizes, with the
    mrange radix knob steering selection."""

    @pytest.mark.parametrize("radix", [2, 3, 4])
    @pytest.mark.parametrize("n", [4, 5, 8, 9])
    @pytest.mark.parametrize("count", [1, 17, 4096])
    def test_sra_allreduce(self, radix, n, count, monkeypatch):
        monkeypatch.setenv("UCC_TL_SHM_ALLREDUCE_SRA_RADIX",
                           f"0-inf:{radix}")
        rng = np.random.default_rng(7 + radix)
        srcs = [(rng.random(count) * 4 - 2).astype(np.float32)
                for _ in range(n)]
        dsts = [np.zeros(count, np.float32) for _ in range(n)]
        expect = np.sum(srcs, axis=0)

        def check():
            for r in range(n):
                np.testing.assert_allclose(dsts[r], expect, rtol=1e-4,
                                           atol=1e-5)

        run_with_tune("allreduce:@sra_knomial:inf", n, lambda r: CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(srcs[r], count, DataType.FLOAT32),
            dst=BufferInfo(dsts[r], count, DataType.FLOAT32),
            op=ReductionOp.SUM), check, monkeypatch)

    @pytest.mark.parametrize("radix", [2, 3, 4])
    @pytest.mark.parametrize("n", [4, 9])
    def test_sra_allreduce_avg(self, radix, n, monkeypatch):
        monkeypatch.setenv("UCC_TL_SHM_ALLREDUCE_SRA_RADIX",
                           f"0-inf:{radix}")
        count = 333
        srcs = [np.full(count, float(r + 1), np.float64) for r in range(n)]
        dsts = [np.zeros(count, np.float64) for _ in range(n)]
        expect = np.mean(srcs, axis=0)

        def check():
            for r in range(n):
                np.testing.assert_allclose(dsts[r], expect, rtol=1e-12)

        run_with_tune("allreduce:@sra_knomial:inf", n, lambda r: CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(srcs[r], count, DataType.FLOAT64),
            dst=BufferInfo(dsts[r], count, DataType.FLOAT64),
            op=ReductionOp.AVG), check, monkeypatch)

    @pytest.mark.parametrize("radix", [2, 3, 4])
    @pytest.mark.parametrize("n", [4, 5, 9])
    @pytest.mark.parametrize("root", [0, 1])
    def test_srg_reduce(self, radix, n, root, monkeypatch):
        monkeypatch.setenv("UCC_TL_SHM_REDUCE_SRG_RADIX",
                           f"0-inf:{radix}")
        count = 1025
        srcs = [np.arange(count, dtype=np.int64) + r for r in range(n)]
        dsts = [np.zeros(count, np.int64) for _ in range(n)]
        expect = np.sum(srcs, axis=0)

        def check():
            np.testing.assert_array_equal(dsts[root], expect)

        run_with_tune("reduce:@srg_knomial:inf", n, lambda r: CollArgs(
            coll_type=CollType.REDUCE,
            src=BufferInfo(srcs[r], count, DataType.INT64),
            dst=BufferInfo(dsts[r], count, DataType.INT64),
            op=ReductionOp.SUM, root=root), check, monkeypatch)

    def test_srg_reduce_extra_root(self, monkeypatch):
        """Root beyond the power-of-radix boundary (an EXTRA rank): the
        proxy must forward the gathered result to it."""
        monkeypatch.setenv("UCC_TL_SHM_REDUCE_SRG_RADIX", "0-inf:3")
        n, count, root = 5, 257, 4     # full=3, ranks 3,4 are extras
        srcs = [np.full(count, r + 1.0, np.float32) for r in range(n)]
        dsts = [np.zeros(count, np.float32) for _ in range(n)]

        def check():
            np.testing.assert_allclose(dsts[root],
                                       np.full(count, 15.0), rtol=1e-5)

        run_with_tune("reduce:@srg_knomial:inf", n, lambda r: CollArgs(
            coll_type=CollType.REDUCE,
            src=BufferInfo(srcs[r], count, DataType.FLOAT32),
            dst=BufferInfo(dsts[r], count, DataType.FLOAT32),
            op=ReductionOp.SUM, root=root), check, monkeypatch)

    def test_mrange_knob_steers_radix_per_size(self, monkeypatch):
        """The per-msg-range knob surface: small msgs radix 4, large
        radix 2 — both must select and complete."""
        monkeypatch.setenv("UCC_TL_SHM_ALLREDUCE_SRA_RADIX",
                           "0-4k:4,4k-inf:2")
        n = 8
        for count in (64, 4096):
            srcs = [np.full(count, r + 1.0, np.float32) for r in range(n)]
            dsts = [np.zeros(count, np.float32) for _ in range(n)]
            expect = np.sum(srcs, axis=0)

            def check():
                for r in range(n):
                    np.testing.assert_allclose(dsts[r], expect, rtol=1e-4)

            run_with_tune("allreduce:@sra_knomial:inf", n,
                          lambda r: CollArgs(
                              coll_type=CollType.ALLREDUCE,
                              src=BufferInfo(srcs[r], count,
                                             DataType.FLOAT32),
                              dst=BufferInfo(dsts[r], count,
                                             DataType.FLOAT32),
                              op=ReductionOp.SUM), check, monkeypatch)


class TestAllgatherLinearBatched:
    """Bounded-in-flight linear allgather (allgather_linear.c batched
    init): correctness at every window depth incl. nreqs=1 (fully
    serialized) and the auto one-shot clamp."""

    @pytest.mark.parametrize("n", [2, 4, 7])
    @pytest.mark.parametrize("posts", ["1", "2", "auto"])
    def test_allgather(self, n, posts, monkeypatch):
        monkeypatch.setenv("UCC_TL_SHM_ALLGATHER_BATCHED_NUM_POSTS", posts)
        per = 9
        srcs = [np.arange(per, dtype=np.int64) + 100 * r for r in range(n)]
        dsts = [np.zeros(per * n, dtype=np.int64) for _ in range(n)]
        expect = np.concatenate(srcs)

        def check():
            for r in range(n):
                np.testing.assert_array_equal(dsts[r], expect)

        run_with_tune(f"allgather:@linear_batched:inf", n,
                      lambda r: CollArgs(
                          coll_type=CollType.ALLGATHER,
                          src=BufferInfo(srcs[r], per, DataType.INT64),
                          dst=BufferInfo(dsts[r], per * n, DataType.INT64)),
                      check, monkeypatch)

    def test_inplace(self, monkeypatch):
        n, per = 4, 5
        monkeypatch.setenv("UCC_TL_SHM_ALLGATHER_BATCHED_NUM_POSTS", "2")
        from ucc_tpu import CollArgsFlags
        bufs = [np.zeros(per * n, np.float32) for _ in range(n)]
        for r in range(n):
            bufs[r][r * per:(r + 1) * per] = np.arange(per) + 10.0 * r
        expect = np.concatenate([np.arange(per) + 10.0 * r
                                 for r in range(n)]).astype(np.float32)

        def check():
            for r in range(n):
                np.testing.assert_allclose(bufs[r], expect)

        run_with_tune("allgather:@linear_batched:inf", n,
                      lambda r: CollArgs(
                          coll_type=CollType.ALLGATHER,
                          dst=BufferInfo(bufs[r], per * n,
                                         DataType.FLOAT32),
                          flags=CollArgsFlags.IN_PLACE), check, monkeypatch)


class TestPairwiseNumPosts:
    """ALLTOALL(V)_PAIRWISE_NUM_POSTS (alltoall_pairwise.c get_num_posts):
    every window depth must stay correct; auto resolves by msg/team size."""

    @pytest.mark.parametrize("posts", ["1", "2", "0", "auto"])
    def test_alltoall(self, posts, monkeypatch):
        n, per = 5, 6
        monkeypatch.setenv("UCC_TL_SHM_ALLTOALL_PAIRWISE_NUM_POSTS", posts)
        srcs = [np.arange(per * n, dtype=np.int32) + 1000 * r
                for r in range(n)]
        dsts = [np.zeros(per * n, np.int32) for _ in range(n)]

        def check():
            for r in range(n):
                expect = np.concatenate(
                    [srcs[q][r * per:(r + 1) * per] for q in range(n)])
                np.testing.assert_array_equal(dsts[r], expect)

        run_with_tune("alltoall:@pairwise:inf", n, lambda r: CollArgs(
            coll_type=CollType.ALLTOALL,
            src=BufferInfo(srcs[r], per * n, DataType.INT32),
            dst=BufferInfo(dsts[r], per * n, DataType.INT32)),
            check, monkeypatch)

    def test_resolution_rules(self):
        """Pin the auto/0/clamp rules to the reference's get_num_posts."""
        from ucc_tpu.tl.host.alltoall import _pairwise_num_posts
        from ucc_tpu.utils.config import SIZE_AUTO

        class _Cfg:
            def __init__(self, v):
                self.v = v

            def get(self, k):
                return self.v

        class _Team:
            def __init__(self, v):
                self.comp_context = type("C", (), {"config": _Cfg(v)})()

        # alltoall auto: big msg + big team -> 1; else all (= tsize)
        assert _pairwise_num_posts(_Team(SIZE_AUTO), "k", 100_000, 64, 4) == 1
        assert _pairwise_num_posts(_Team(SIZE_AUTO), "k", 100_000, 8, 4) == 8
        assert _pairwise_num_posts(_Team(SIZE_AUTO), "k", 1024, 64, 4) == 64
        # alltoallv auto (data_size None): team-size-only
        assert _pairwise_num_posts(_Team(SIZE_AUTO), "k", None, 64, 4) == 1
        assert _pairwise_num_posts(_Team(SIZE_AUTO), "k", None, 8, 4) == 8
        # explicit 0 / inf / oversize clamp to tsize; in-range passes
        from ucc_tpu.utils.config import UINT_MAX
        assert _pairwise_num_posts(_Team(0), "k", 1024, 8, 4) == 8
        assert _pairwise_num_posts(_Team(UINT_MAX), "k", 100_000, 64, 4) == 64
        assert _pairwise_num_posts(_Team(99), "k", 1024, 8, 4) == 8
        assert _pairwise_num_posts(_Team(3), "k", 1024, 8, 4) == 3


class TestSraPipelined:
    """ALLREDUCE_SRA_PIPELINE (the reference ALLREDUCE_SRA_KN_PIPELINE
    role): above the threshold the vector fragments through the
    PipelinedSchedule engine; below it the plain task runs."""

    @pytest.mark.parametrize("n", [4, 5])
    @pytest.mark.parametrize("count", [4096, 10001])
    @pytest.mark.parametrize("order", ["ordered", "parallel"])
    def test_fragmented_correct(self, n, count, order, monkeypatch):
        monkeypatch.setenv(
            "UCC_TL_SHM_ALLREDUCE_SRA_PIPELINE",
            f"thresh=1K:fragsize=8K:nfrags=4:pdepth=2:{order}")
        rng = np.random.default_rng(33)
        srcs = [(rng.random(count) * 4 - 2).astype(np.float32)
                for _ in range(n)]
        dsts = [np.zeros(count, np.float32) for _ in range(n)]
        expect = np.sum(srcs, axis=0)

        def check():
            for r in range(n):
                np.testing.assert_allclose(dsts[r], expect, rtol=1e-4,
                                           atol=1e-5)

        run_with_tune("allreduce:@sra_knomial:inf", n, lambda r: CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(srcs[r], count, DataType.FLOAT32),
            dst=BufferInfo(dsts[r], count, DataType.FLOAT32),
            op=ReductionOp.SUM), check, monkeypatch)

    def test_below_thresh_runs_plain(self, monkeypatch):
        """Under the threshold the init returns the plain task (no
        schedule wrapping) — pin via the returned type. Since PR 12 the
        plain task may be the NATIVE-PLAN bridge (a GeneratedCollTask
        running the verified gen_sra program) when UCC_GEN_NATIVE
        resolves on — still plain, still the SRA structure."""
        monkeypatch.setenv("UCC_TL_SHM_ALLREDUCE_SRA_PIPELINE",
                           "thresh=1M:fragsize=1M:nfrags=4")
        monkeypatch.setenv("UCC_TL_SHM_TUNE", "allreduce:@sra_knomial:inf")
        from harness import UccJob
        from ucc_tpu.tl.host.sra import AllreduceSraKnomial
        job = UccJob(2)
        try:
            teams = job.create_team()
            src = np.ones(64, np.float32)
            dst = np.zeros(64, np.float32)
            req = teams[0].collective_init(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(src, 64, DataType.FLOAT32),
                dst=BufferInfo(dst, 64, DataType.FLOAT32),
                op=ReductionOp.SUM))
            task = getattr(req, "task", req)
            is_plan_bridge = getattr(getattr(task, "prog", None),
                                     "family", "") == "sra"
            assert isinstance(task, AllreduceSraKnomial) or \
                "Sra" in type(task).__name__ or is_plan_bridge
            from ucc_tpu.schedule.pipelined import PipelinedSchedule
            assert not isinstance(task, PipelinedSchedule)
        finally:
            job.cleanup()

    def test_avg_fragmented(self, monkeypatch):
        monkeypatch.setenv("UCC_TL_SHM_ALLREDUCE_SRA_PIPELINE",
                           "thresh=1K:fragsize=4K:nfrags=3")
        n, count = 4, 5000
        srcs = [np.full(count, float(r + 1), np.float64) for r in range(n)]
        dsts = [np.zeros(count, np.float64) for _ in range(n)]
        expect = np.mean(srcs, axis=0)

        def check():
            for r in range(n):
                np.testing.assert_allclose(dsts[r], expect, rtol=1e-12)

        run_with_tune("allreduce:@sra_knomial:inf", n, lambda r: CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(srcs[r], count, DataType.FLOAT64),
            dst=BufferInfo(dsts[r], count, DataType.FLOAT64),
            op=ReductionOp.AVG), check, monkeypatch)


class TestSrgPipelined:
    """REDUCE_SRG_PIPELINE: rooted reduce fragments through the same
    engine; root and non-root (dst=None) shapes both retarget."""

    @pytest.mark.parametrize("n,root", [(4, 0), (5, 2)])
    def test_fragmented_correct(self, n, root, monkeypatch):
        monkeypatch.setenv("UCC_TL_SHM_REDUCE_SRG_PIPELINE",
                           "thresh=1K:fragsize=8K:nfrags=3:pdepth=2")
        count = 6000
        srcs = [np.arange(count, dtype=np.int64) + r for r in range(n)]
        dsts = [np.zeros(count, np.int64) for _ in range(n)]
        expect = np.sum(srcs, axis=0)

        def check():
            np.testing.assert_array_equal(dsts[root], expect)

        run_with_tune("reduce:@srg_knomial:inf", n, lambda r: CollArgs(
            coll_type=CollType.REDUCE,
            src=BufferInfo(srcs[r], count, DataType.INT64),
            dst=BufferInfo(dsts[r], count, DataType.INT64),
            op=ReductionOp.SUM, root=root), check, monkeypatch)

    def test_avg_fragmented(self, monkeypatch):
        monkeypatch.setenv("UCC_TL_SHM_REDUCE_SRG_PIPELINE",
                           "thresh=1K:fragsize=4K:nfrags=4")
        n, count, root = 4, 3000, 1
        srcs = [np.full(count, float(r + 1), np.float64) for r in range(n)]
        dsts = [np.zeros(count, np.float64) for _ in range(n)]

        def check():
            np.testing.assert_allclose(dsts[root],
                                       np.full(count, 2.5), rtol=1e-12)

        run_with_tune("reduce:@srg_knomial:inf", n, lambda r: CollArgs(
            coll_type=CollType.REDUCE,
            src=BufferInfo(srcs[r], count, DataType.FLOAT64),
            dst=BufferInfo(dsts[r], count, DataType.FLOAT64),
            op=ReductionOp.AVG, root=root), check, monkeypatch)


class TestLinearNumPosts:
    """GATHERV/SCATTERV_LINEAR_NUM_POSTS: the root's request window is
    bounded; every depth stays correct (incl. 1 = fully serialized)."""

    @pytest.mark.parametrize("posts", ["1", "2", "0"])
    @pytest.mark.parametrize("coll,alg", [
        (CollType.GATHERV, "gatherv:@linear"),
        (CollType.SCATTERV, "scatterv:@linear"),
    ])
    def test_v_colls(self, posts, coll, alg, monkeypatch):
        from ucc_tpu import BufferInfoV
        n, root = 5, 1
        knob = "GATHERV_LINEAR_NUM_POSTS" if coll == CollType.GATHERV \
            else "SCATTERV_LINEAR_NUM_POSTS"
        monkeypatch.setenv(f"UCC_TL_SHM_{knob}", posts)
        counts = [(r % 3) + 1 for r in range(n)]
        total = sum(counts)
        if coll == CollType.GATHERV:
            srcs = [np.full(counts[r], float(r + 1), np.float32)
                    for r in range(n)]
            dsts = [np.zeros(total, np.float32) for _ in range(n)]

            def check():
                np.testing.assert_allclose(
                    dsts[root], np.concatenate(srcs))

            run_with_tune(f"{alg}:inf", n, lambda r: CollArgs(
                coll_type=coll, root=root,
                src=BufferInfo(srcs[r], counts[r], DataType.FLOAT32),
                dst=BufferInfoV(dsts[r], counts, None, DataType.FLOAT32)
                if r == root else None), check, monkeypatch)
        else:
            src_all = np.arange(total, dtype=np.float32)
            dsts = [np.zeros(counts[r], np.float32) for r in range(n)]

            def check():
                off = 0
                for r in range(n):
                    np.testing.assert_allclose(
                        dsts[r], src_all[off:off + counts[r]])
                    off += counts[r]

            run_with_tune(f"{alg}:inf", n, lambda r: CollArgs(
                coll_type=coll, root=root,
                src=BufferInfoV(src_all, counts, None, DataType.FLOAT32)
                if r == root else None,
                dst=BufferInfo(dsts[r], counts[r], DataType.FLOAT32)),
                check, monkeypatch)


class TestHybridKnobs:
    """ALLTOALLV_HYBRID_CHUNK_BYTE_LIMIT / _PAIRWISE_NUM_POSTS: routing
    split and phase-1 window are knob-driven; correctness at both
    extremes (everything direct / everything forwarded)."""

    @pytest.mark.parametrize("limit", ["1", "12k", "1m"])
    def test_routing_split_extremes(self, limit, monkeypatch):
        from ucc_tpu import BufferInfoV
        monkeypatch.setenv("UCC_TL_SHM_ALLTOALLV_HYBRID_CHUNK_BYTE_LIMIT",
                           limit)
        monkeypatch.setenv(
            "UCC_TL_SHM_ALLTOALLV_HYBRID_PAIRWISE_NUM_POSTS", "1")
        n = 5
        m = [[(r * 3 + p) % 5 + 1 for p in range(n)] for r in range(n)]
        recv_counts = [[m[q][p] for q in range(n)] for p in range(n)]
        srcs, dsts = [], []
        for r in range(n):
            srcs.append(np.arange(sum(m[r]), dtype=np.int64) + 1000 * r)
            dsts.append(np.full(sum(recv_counts[r]), -1, np.int64))

        def check():
            for p in range(n):
                sdispl = {q: np.cumsum([0] + m[q][:-1]) for q in range(n)}
                expect = np.concatenate([
                    srcs[q][sdispl[q][p]:sdispl[q][p] + m[q][p]]
                    for q in range(n)])
                np.testing.assert_array_equal(dsts[p], expect)

        run_with_tune("alltoallv:@hybrid:inf", n, lambda r: CollArgs(
            coll_type=CollType.ALLTOALLV,
            src=BufferInfoV(srcs[r], m[r], None, DataType.INT64),
            dst=BufferInfoV(dsts[r], recv_counts[r], None,
                            DataType.INT64)), check, monkeypatch)


class TestGlobalKnRadix:
    """KN_RADIX (tl_ucp_lib.c:30-37): a positive value supersedes the
    barrier/bcast/reduce KN radixes; allreduce keeps its own knob (the
    reference does NOT copy into it); 0 and the auto/inf sentinels
    defer. Unlike the reference's six-knob list, the set is trimmed to
    radixes that exist: reduce_scatter/scatter/gather trees here are
    binomial (radix-2 hardwired) and have no radix knob to override."""

    def test_global_set_matches_reachable_knobs(self):
        from ucc_tpu.tl.host.team import _KN_RADIX_GLOBAL
        # exactly the knobs cfg_radix is ever called with (knomial.py);
        # phantom entries would advertise a knob with no effect
        assert _KN_RADIX_GLOBAL == {"barrier_kn_radix", "bcast_kn_radix",
                                    "reduce_kn_radix"}

    @staticmethod
    def _host_team(job):
        t = job.create_team()[0]
        return [tl for cl in t.cl_teams
                for tl in getattr(cl, "tl_teams", [])
                if tl.NAME == "shm"][0]

    def test_override_scope(self, monkeypatch):
        from harness import UccJob
        monkeypatch.setenv("UCC_TL_SHM_KN_RADIX", "3")
        monkeypatch.setenv("UCC_TL_SHM_ALLREDUCE_KN_RADIX", "0-inf:8")
        monkeypatch.setenv("UCC_TL_SHM_BCAST_KN_RADIX", "0-inf:8")
        job = UccJob(2)
        try:
            host = self._host_team(job)
            # copied-into set IS overridden
            assert host.cfg_radix("bcast_kn_radix", 1024) == 3
            assert host.cfg_radix("barrier_kn_radix", 1024) == 3
            # allreduce is NOT (tl_ucp_lib.c copies selectively)
            assert host.cfg_radix("allreduce_kn_radix", 1024) == 8
            # non-kn knobs are NOT
            assert host.cfg_radix("allreduce_sra_radix", 1024,
                                  default=2) == 2
        finally:
            job.cleanup()

    @pytest.mark.parametrize("val", ["0", "auto", "inf"])
    def test_non_positive_and_sentinels_defer(self, val, monkeypatch):
        from harness import UccJob
        monkeypatch.setenv("UCC_TL_SHM_KN_RADIX", val)
        monkeypatch.setenv("UCC_TL_SHM_BCAST_KN_RADIX", "0-inf:8")
        job = UccJob(2)
        try:
            host = self._host_team(job)
            assert host.cfg_radix("bcast_kn_radix", 1024) == 8
        finally:
            job.cleanup()

    def test_collectives_run_under_override(self, monkeypatch):
        monkeypatch.setenv("UCC_TL_SHM_KN_RADIX", "3")
        n, count = 5, 257
        srcs = [np.full(count, r + 1.0, np.float32) for r in range(n)]
        dsts = [np.zeros(count, np.float32) for _ in range(n)]

        def check():
            for r in range(n):
                np.testing.assert_allclose(dsts[r],
                                           np.full(count, 15.0), rtol=1e-5)

        run_with_tune("allreduce:@knomial:inf", n, lambda r: CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(srcs[r], count, DataType.FLOAT32),
            dst=BufferInfo(dsts[r], count, DataType.FLOAT32),
            op=ReductionOp.SUM), check, monkeypatch)
