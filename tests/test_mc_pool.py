"""Hot-path memory overhaul (ISSUE 3): mc mpool, scratch leases, and the
transport's copy-free matching fast path.

Covers the acceptance criteria: a persistent allreduce loop shows ZERO
pool-miss growth after warmup (no per-iteration scratch allocation),
and the zero-copy send path is exercised in both match orders with the
truncation and cancel-under-lock contracts from PR 2 preserved.
"""
from __future__ import annotations

import numpy as np
import pytest

from ucc_tpu import (BufferInfo, CollArgs, CollArgsFlags, CollType, DataType,
                     ReductionOp, Status)
from ucc_tpu.mc.pool import HostMemPool, ScratchLease, host_pool
from ucc_tpu.tl.host.transport import InProcTransport, Mailbox, RecvReq

from harness import UccJob


# ---------------------------------------------------------------------------
# pool unit behavior
# ---------------------------------------------------------------------------

class TestHostMemPool:
    def test_miss_then_hit_same_class(self):
        p = HostMemPool()
        a = p.get(1000)
        assert a.nbytes == 1024          # power-of-two bucket
        p.put(a)
        b = p.get(900)                   # same class -> cache hit
        assert b is a
        assert p.stats()["hits"] == 1 and p.stats()["misses"] == 1

    def test_distinct_classes_do_not_alias(self):
        p = HostMemPool()
        a = p.get(100)
        p.put(a)
        b = p.get(100000)
        assert b is not a and b.nbytes >= 100000

    def test_max_elems_cap(self):
        p = HostMemPool(max_elems=1)
        a, b = p.get(512), p.get(512)
        p.put(a)
        p.put(b)                         # beyond cap: dropped
        assert p.stats()["cached_elems"] == 1

    def test_max_bytes_cap(self):
        p = HostMemPool(max_bytes=2048)
        a, b, c = p.get(1024), p.get(1024), p.get(1024)
        for buf in (a, b, c):
            p.put(buf)
        assert p.stats()["cached_bytes"] <= 2048

    def test_oversize_bypasses_pool(self):
        p = HostMemPool(max_elem_size=4096)
        a = p.get(10000)
        assert a.nbytes == 10000         # exact, unbucketed
        p.put(a)
        assert p.stats()["cached_elems"] == 0

    def test_disabled_pool_always_misses(self):
        p = HostMemPool(enable=False)
        a = p.get(512)
        p.put(a)
        p.get(512)
        st = p.stats()
        assert st["hits"] == 0 and st["misses"] == 2

    def test_bucket_overflow_of_max_elem_size_goes_direct(self):
        # admission is by bucket capacity: with a non-pow2 cap, sizes
        # whose bucket rounds past it must bypass the pool entirely
        # (get/put agree), not miss forever on an uncacheable bucket
        p = HostMemPool(max_elem_size=100 << 20)
        a = p.get(70 << 20)              # bucket would be 128M > 100M
        assert a.nbytes == 70 << 20      # direct: exact, unbucketed
        p.put(a)
        assert p.stats()["cached_elems"] == 0
        b = p.get(50 << 20)              # bucket 64M <= 100M: pooled
        assert b.nbytes == 64 << 20
        p.put(b)
        assert p.stats()["cached_elems"] == 1

    def test_env_config(self, monkeypatch):
        from ucc_tpu.mc.pool import _pool_from_env
        monkeypatch.setenv("UCC_MC_POOL_MAX_ELEMS", "3")
        monkeypatch.setenv("UCC_MC_POOL_MAX_ELEM_SIZE", "1M")
        monkeypatch.setenv("UCC_MC_POOL", "n")   # shorthand disable
        p = _pool_from_env()
        assert p.max_elems == 3 and p.max_elem_size == (1 << 20)
        assert not p.enable


class TestScratchLease:
    def test_same_key_reuses_without_pool_traffic(self):
        p = HostMemPool()
        lease = ScratchLease(p)
        a = lease.get("x", 100, np.float32)
        before = p.stats()
        b = lease.get("x", 100, np.float32)
        assert b.base is a.base or b is a
        after = p.stats()
        assert after["hits"] == before["hits"]
        assert after["misses"] == before["misses"]

    def test_growth_releases_old_and_refits(self):
        p = HostMemPool()
        lease = ScratchLease(p)
        lease.get("x", 100, np.float32)
        big = lease.get("x", 100000, np.float32)
        assert big.size == 100000
        # old buffer went back to the pool
        assert p.stats()["cached_elems"] == 1

    def test_shape_and_dtype_views(self):
        lease = ScratchLease(HostMemPool())
        m = lease.get("m", (3, 5), np.int64)
        assert m.shape == (3, 5) and m.dtype == np.int64
        m[2, 4] = 7          # writable
        assert m[2, 4] == 7

    def test_release_returns_everything(self):
        p = HostMemPool()
        lease = ScratchLease(p)
        lease.get("a", 128, np.uint8)
        lease.get("b", 4096, np.float64)
        lease.release()
        st = p.stats()
        assert st["cached_elems"] == 2 and st["leased"] == 0
        lease.release()      # idempotent
        assert p.stats()["cached_elems"] == 2


# ---------------------------------------------------------------------------
# zero-copy / copy-free transport fast path
# ---------------------------------------------------------------------------

def _pair():
    a = InProcTransport(use_native=False)
    b = InProcTransport(use_native=False)
    return a, b


KEY = ("t", 1, 0, 0)


class TestCopyFreeFastPath:
    def test_posted_recv_first_is_copy_free(self):
        a, b = _pair()
        dst = np.zeros(4, np.float32)
        rreq = b.recv_nb(KEY, dst)
        payload = np.arange(4, dtype=np.float32)
        sreq = a.send_nb(b, KEY, payload)
        assert sreq.test() and rreq.test()
        assert np.array_equal(dst, payload)
        # matched a posted recv: delivered straight from the sender's
        # buffer, no eager staging copy even though it's a small message
        assert a.n_direct == 1 and a.n_eager == 0 and a.n_rndv == 0
        a.close(), b.close()

    def test_unexpected_small_pays_eager_copy(self):
        a, b = _pair()
        payload = np.arange(4, dtype=np.float32)
        sreq = a.send_nb(b, KEY, payload)
        assert sreq.test()               # eager: sender free immediately
        assert a.n_eager == 1 and a.n_direct == 0
        payload[:] = -1                  # sender reuses its buffer...
        dst = np.zeros(4, np.float32)
        rreq = b.recv_nb(KEY, dst)
        assert rreq.test()
        # ...and the receiver still sees the ORIGINAL data (it was copied)
        assert np.array_equal(dst, np.arange(4, dtype=np.float32))
        a.close(), b.close()

    def test_unexpected_large_is_rendezvous(self):
        a, b = _pair()
        payload = np.ones(b.EAGER_THRESHOLD + 64, np.uint8)
        sreq = a.send_nb(b, KEY, payload)
        assert not sreq.test()           # zero-copy: completes on match
        assert a.n_rndv == 1
        dst = np.zeros_like(payload)
        rreq = b.recv_nb(KEY, dst)
        assert sreq.test() and rreq.test()
        assert np.array_equal(dst, payload)
        a.close(), b.close()

    def test_truncation_error_preserved_both_orders(self):
        # posted-recv-first (the new direct path)
        a, b = _pair()
        dst = np.zeros(2, np.float32)
        rreq = b.recv_nb(KEY, dst)
        a.send_nb(b, KEY, np.arange(8, dtype=np.float32))
        assert rreq.test() and rreq.error and "truncated" in rreq.error
        # unexpected-first (classic queue path)
        dst2 = np.zeros(2, np.float32)
        a.send_nb(b, ("t", 2, 0, 0), np.arange(8, dtype=np.float32))
        rreq2 = b.recv_nb(("t", 2, 0, 0), dst2)
        assert rreq2.test() and rreq2.error and "truncated" in rreq2.error
        a.close(), b.close()

    def test_cancelled_recv_not_scribbled_by_direct_path(self):
        # the PR 2 cancel-under-lock contract must survive the fast path:
        # a cancelled recv is skipped at match time, the send parks as
        # unexpected instead of writing into the withdrawn buffer
        a, b = _pair()
        dst = np.zeros(4, np.float32)
        rreq = b.recv_nb(KEY, dst)
        rreq.cancel()
        sreq = a.send_nb(b, KEY, np.arange(4, dtype=np.float32))
        assert np.array_equal(dst, np.zeros(4, np.float32))
        assert a.n_direct == 0           # did NOT match the cancelled recv
        # a fresh recv still gets the parked message
        dst2 = np.zeros(4, np.float32)
        rreq2 = b.recv_nb(KEY, dst2)
        assert rreq2.test() and sreq.test()
        assert np.array_equal(dst2, np.arange(4, dtype=np.float32))
        a.close(), b.close()

    def test_fifo_across_mixed_paths(self):
        # two unexpected sends then two recvs: order preserved
        a, b = _pair()
        a.send_nb(b, KEY, np.array([1.0], np.float32))
        a.send_nb(b, KEY, np.array([2.0], np.float32))
        d1, d2 = np.zeros(1, np.float32), np.zeros(1, np.float32)
        b.recv_nb(KEY, d1)
        b.recv_nb(KEY, d2)
        assert d1[0] == 1.0 and d2[0] == 2.0
        a.close(), b.close()

    def test_eager_limit_env_knob(self, monkeypatch):
        monkeypatch.setenv("UCC_HOST_EAGER_LIMIT", "64k")
        t = InProcTransport(use_native=False)
        assert t.EAGER_THRESHOLD == 64 << 10
        t.close()
        monkeypatch.delenv("UCC_HOST_EAGER_LIMIT")
        t2 = InProcTransport(use_native=False)
        assert t2.EAGER_THRESHOLD == 8192
        t2.close()

    def test_mailbox_push_contract_unchanged(self):
        # the socket reader thread still delivers via push(); same
        # matching semantics as send()
        from ucc_tpu.tl.host.transport import SendReq, _PendingSend
        mb = Mailbox()
        req = RecvReq(np.zeros(4, np.float32))
        mb.post_recv(KEY, req)
        mb.push(KEY, _PendingSend(np.ones(4, np.float32), SendReq(), False))
        assert req.test() and req.error is None


# ---------------------------------------------------------------------------
# allocation-regression acceptance: steady-state persistent loop
# ---------------------------------------------------------------------------

def _persistent_allreduce_reqs(job, teams, count):
    def mk(r):
        src = np.full(count, float(r + 1), np.float32)
        return CollArgs(coll_type=CollType.ALLREDUCE,
                        src=BufferInfo(src, count, DataType.FLOAT32),
                        dst=BufferInfo(np.zeros(count, np.float32), count,
                                       DataType.FLOAT32),
                        op=ReductionOp.SUM,
                        flags=CollArgsFlags.PERSISTENT)
    argses = [mk(r) for r in range(len(teams))]
    reqs = [t.collective_init(argses[r]) for r, t in enumerate(teams)]
    return argses, reqs


def _post_and_wait(job, reqs):
    for rq in reqs:
        rq.post()
    job.progress_until(lambda: all(rq.test() != Status.IN_PROGRESS
                                   for rq in reqs))
    for rq in reqs:
        assert rq.test() == Status.OK


class TestSteadyStateZeroAlloc:
    N = 4

    def _run_loop(self, count, warmup=3, iters=10, env=None, monkeypatch=None):
        if env:
            for k, v in env.items():
                monkeypatch.setenv(k, v)
        job = UccJob(self.N)
        try:
            teams = job.create_team()
            argses, reqs = _persistent_allreduce_reqs(job, teams, count)
            for _ in range(warmup):
                _post_and_wait(job, reqs)
            pool0 = host_pool().stats()
            for _ in range(iters):
                _post_and_wait(job, reqs)
            pool1 = host_pool().stats()
            expected = np.full(count, sum(range(1, self.N + 1)), np.float32)
            np.testing.assert_allclose(argses[0].dst.buffer, expected)
            for rq in reqs:
                rq.finalize()
            return pool0, pool1
        finally:
            job.cleanup()

    def test_small_allreduce_zero_miss_growth(self):
        # small message -> knomial (latency alg)
        pool0, pool1 = self._run_loop(count=64)
        assert pool1["misses"] == pool0["misses"], \
            "steady-state persistent allreduce allocated scratch per post"

    def test_large_allreduce_zero_miss_growth(self):
        # large message -> sra_knomial / ring (bandwidth algs)
        pool0, pool1 = self._run_loop(count=64 << 10)
        assert pool1["misses"] == pool0["misses"]

    def test_pipelined_window_reuses_scratch(self, monkeypatch):
        # fragmentation pipeline: window entries must reuse ONE scratch
        # set across all fragments (tentpole item 2)
        pool0, pool1 = self._run_loop(
            count=64 << 10,
            env={"UCC_TL_SHM_ALLREDUCE_SRA_PIPELINE":
                 "thresh=1k:fragsize=64k:nfrags=4:pdepth=2"},
            monkeypatch=monkeypatch)
        assert pool1["misses"] == pool0["misses"]

    def test_errored_task_lease_not_recycled(self):
        # a task that ended in error may have parked zero-copy rendezvous
        # sends referencing its lease in a peer's unexpected queue; its
        # finalize must DROP the lease, not file the buffers back into
        # the pool where another collective would overwrite them
        from ucc_tpu.mc.pool import reset_host_pool
        from ucc_tpu.tl.host.task import HostCollTask
        pool = HostMemPool()
        reset_host_pool(pool)
        try:
            t = object.__new__(HostCollTask)
            t.scratch("work", 1 << 20, np.float32)
            t.status = t.super_status = Status.ERR_TIMED_OUT
            t.finalize_fn()
            assert pool.stats()["cached_elems"] == 0   # dropped, not pooled
            # a clean task's lease DOES return
            t2 = object.__new__(HostCollTask)
            t2.scratch("work", 1 << 20, np.float32)
            t2.status = t2.super_status = Status.OK
            t2.finalize_fn()
            assert pool.stats()["cached_elems"] == 1
        finally:
            reset_host_pool(None)

    def test_errored_then_reset_persistent_lease_stays_tainted(self):
        # the taint must be captured BEFORE reset() clears the status: an
        # errored post of a persistent collective parks rndv sends, the
        # user re-posts, the re-post completes OK — finalize must STILL
        # drop the lease (the stale parked views reference it)
        from ucc_tpu.mc.pool import reset_host_pool
        from ucc_tpu.tl.host.task import HostCollTask
        pool = HostMemPool()
        reset_host_pool(pool)
        try:
            t = object.__new__(HostCollTask)
            t.tag = ("svc", 1)           # tuple tag: reset skips the team
            t.scratch("work", 1 << 16, np.float32)
            t.status = t.super_status = Status.ERR_TIMED_OUT
            t.exc = None
            t.n_deps = t.n_deps_base = t.n_deps_satisfied = 0
            t.reset()                    # clears status -> must taint first
            t.status = t.super_status = Status.OK
            t.finalize_fn()
            assert pool.stats()["cached_elems"] == 0, \
                "tainted lease was recycled into the pool"
        finally:
            reset_host_pool(None)

    def test_lease_released_on_finalize(self, monkeypatch):
        # task-lease lifetime is what this pins; the native-plan path
        # holds a PLAN-lifetime lease in the team cache instead (its
        # release-at-team-destroy twin lives in test_plan.py)
        monkeypatch.setenv("UCC_GEN_NATIVE", "n")
        job = UccJob(2)
        try:
            teams = job.create_team()
            argses, reqs = _persistent_allreduce_reqs(job, teams, 1 << 10)
            _post_and_wait(job, reqs)
            leased_before = host_pool().stats()["leased"]
            for rq in reqs:
                rq.finalize()
            assert host_pool().stats()["leased"] < leased_before or \
                leased_before == 0
        finally:
            job.cleanup()


class TestColdHookBinding:
    """Per-message obs/fault hooks bind at post time: with everything
    disabled the fast path is taken, and enabling metrics between posts
    of a persistent collective takes effect on the next post."""

    def test_metrics_enabled_between_posts_still_counted(self, tmp_path):
        from ucc_tpu.obs import metrics
        job = UccJob(2)
        try:
            teams = job.create_team()
            argses, reqs = _persistent_allreduce_reqs(job, teams, 32)
            _post_and_wait(job, reqs)        # cold post: no metrics
            metrics.reset()
            metrics.enable(file=str(tmp_path / "s.json"))
            try:
                _post_and_wait(job, reqs)    # re-bound at this post
                snap = metrics.snapshot()
                sent = snap["counters"].get("msgs_sent", {})
                assert sum(v for k, v in sent.items()
                           if "tl/host|allreduce" in k) > 0
            finally:
                metrics.disable()
                metrics.reset()
            for rq in reqs:
                rq.finalize()
        finally:
            job.cleanup()
