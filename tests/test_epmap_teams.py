"""Teams created from ep_maps alone (no per-team OOB) — the reference's
ep_map FULL/STRIDED/ARRAY team creation (ucc.h:1337-1357) riding internal
service collectives instead of a user OOB round."""
import numpy as np
import pytest

import ucc_tpu
from ucc_tpu import (BufferInfo, CollArgs, CollType, DataType, ReductionOp,
                     Status, TeamParams)
from ucc_tpu.utils.ep_map import EpMap

from harness import UccJob


@pytest.fixture(scope="module")
def job():
    j = UccJob(6)
    yield j
    j.cleanup()


def create_epmap_teams(job, ranks):
    emap = EpMap.from_array(ranks)
    teams = [job.contexts[r].create_team_post(TeamParams(ep_map=emap))
             for r in ranks]
    job.progress_until(lambda: all(
        [t.create_test() != Status.IN_PROGRESS for t in teams]))
    for t in teams:
        assert t.create_test() == Status.OK
    return teams


class TestEpMapTeams:
    def test_full_world(self, job):
        teams = create_epmap_teams(job, list(range(6)))
        count = 10
        dsts = [np.zeros(count, np.float32) for _ in range(6)]
        reqs = [teams[i].collective_init(CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(np.full(count, i + 1.0, np.float32), count,
                           DataType.FLOAT32),
            dst=BufferInfo(dsts[i], count, DataType.FLOAT32),
            op=ReductionOp.SUM)) for i in range(6)]
        for rq in reqs:
            rq.post()
        job.progress_until(lambda: all(
            rq.test() != Status.IN_PROGRESS for rq in reqs))
        for i in range(6):
            np.testing.assert_allclose(dsts[i], 21.0)

    def test_strided_subset(self, job):
        ranks = [1, 3, 5]
        teams = create_epmap_teams(job, ranks)
        assert [t.rank for t in teams] == [0, 1, 2]
        assert len({t.id for t in teams}) == 1
        count = 4
        dsts = [np.zeros(count, np.int32) for _ in range(3)]
        reqs = [teams[i].collective_init(CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(np.full(count, 10 * (i + 1), np.int32), count,
                           DataType.INT32),
            dst=BufferInfo(dsts[i], count, DataType.INT32),
            op=ReductionOp.SUM)) for i in range(3)]
        for rq in reqs:
            rq.post()
        job.progress_until(lambda: all(
            rq.test() != Status.IN_PROGRESS for rq in reqs))
        for i in range(3):
            np.testing.assert_array_equal(dsts[i], 60)

    def test_two_identical_membership_teams_isolated(self, job):
        """The per-membership counter must keep two same-member teams'
        traffic separate."""
        ranks = [0, 2]
        t_a = create_epmap_teams(job, ranks)
        t_b = create_epmap_teams(job, ranks)
        assert t_a[0].team_key != t_b[0].team_key
        count = 4
        a_dst = [np.zeros(count, np.int32) for _ in range(2)]
        b_dst = [np.zeros(count, np.int32) for _ in range(2)]
        reqs = []
        for i in range(2):
            reqs.append(t_a[i].collective_init(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(np.full(count, 1, np.int32), count,
                               DataType.INT32),
                dst=BufferInfo(a_dst[i], count, DataType.INT32),
                op=ReductionOp.SUM)))
            reqs.append(t_b[i].collective_init(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(np.full(count, 100, np.int32), count,
                               DataType.INT32),
                dst=BufferInfo(b_dst[i], count, DataType.INT32),
                op=ReductionOp.SUM)))
        for rq in reqs:
            rq.post()
        job.progress_until(lambda: all(
            rq.test() != Status.IN_PROGRESS for rq in reqs))
        for i in range(2):
            np.testing.assert_array_equal(a_dst[i], 2)
            np.testing.assert_array_equal(b_dst[i], 200)
