"""N-level hierarchy topology tests (ISSUE 8 satellite): HierTree /
TeamTopo.node_layout / sbgp construction on ASYMMETRIC layouts (unequal
ranks-per-node, single-rank nodes, one-node pods) — previously only the
symmetric two-level case was exercised — plus end-to-end nlvl
collectives over an asymmetric 3-level (chip/node/pod) simulated team.
"""
import os

import numpy as np
import pytest

import ucc_tpu
from ucc_tpu import (BufferInfo, CollArgs, CollArgsFlags, CollType, DataType,
                     ReductionOp)
from ucc_tpu.topo.proc_info import ProcInfo, fake_topology
from ucc_tpu.topo.sbgp import SbgpStatus, SbgpType
from ucc_tpu.topo.topo import ContextTopo, HierTree, TeamTopo
from ucc_tpu.utils.ep_map import EpMap

from harness import UccJob


def _paths(node_of, pod_of=None):
    """Per-rank attribute paths from a rank->node map (and optional
    node->pod map), hashed the way the context fake-topology hook does."""
    import zlib
    out = []
    for r, node in enumerate(node_of):
        hh = zlib.crc32(f"fake-node-{node}".encode())
        if pod_of is None:
            out.append((hh,))
        else:
            out.append((zlib.crc32(f"fake-pod-{pod_of[node]}".encode()), hh))
    return out


class TestHierTreePaths:
    """HierTree from raw paths: arbitrary asymmetric layouts without a
    context."""

    def test_two_level_asymmetric(self):
        # nodes of 2,1,3: a single-rank node in the middle
        tree = HierTree(_paths([0, 0, 1, 2, 2, 2]), my_rank=0)
        assert tree.n_levels == 2
        assert tree.level(0).groups == [[0, 1], [2], [3, 4, 5]]
        assert tree.level(1).groups == [[0, 2, 3]]     # node leaders
        assert tree.tree_order == [0, 1, 2, 3, 4, 5]

    def test_three_level_with_one_node_pod(self):
        # pods: nodes {0,1} -> pod 0, node {2} -> pod 1 (one-node pod)
        tree = HierTree(_paths([0, 0, 1, 2, 2, 2], pod_of=[0, 0, 1]),
                        my_rank=0)
        assert tree.n_levels == 3
        assert tree.level(0).groups == [[0, 1], [2], [3, 4, 5]]
        assert tree.level(1).groups == [[0, 2], [3]]   # per-pod leaders
        assert tree.level(2).groups == [[0, 3]]        # pod leaders
        # rank 4's representative chain: itself -> node leader 3 -> 3
        assert tree.rep(0, 4) == 4
        assert tree.rep(1, 4) == 3
        assert tree.rep(2, 4) == 3
        assert not tree.is_member(1, 4)
        assert tree.is_member(1, 3) and tree.is_member(2, 3)

    def test_all_single_rank_nodes(self):
        tree = HierTree(_paths([0, 1, 2, 3]), my_rank=2)
        assert tree.n_levels == 2
        assert all(len(g) == 1 for g in tree.level(0).groups)
        assert tree.level(1).groups == [[0, 1, 2, 3]]
        # every rank is its own node leader
        assert all(tree.is_member(1, r) for r in range(4))

    def test_interleaved_ranks_stay_grouped(self):
        # node membership need not be rank-contiguous
        tree = HierTree(_paths([0, 1, 0, 1]), my_rank=0)
        assert tree.level(0).groups == [[0, 2], [1, 3]]
        assert tree.level(1).groups == [[0, 1]]
        # subtrees contiguous in tree order
        assert tree.tree_order == [0, 2, 1, 3]

    def test_invariants_on_lopsided_layout(self):
        # 11 ranks: pods of very different shapes incl. single-rank ones
        node_of = [0, 0, 0, 0, 1, 2, 2, 3, 4, 4, 4]
        pod_of = [0, 0, 0, 1, 2]
        tree = HierTree(_paths(node_of, pod_of), my_rank=5)
        n = len(node_of)
        for lvl in range(tree.n_levels):
            groups = tree.level(lvl).groups
            members = sorted(r for g in groups for r in g)
            if lvl == 0:
                assert members == list(range(n))
            else:
                prev = sorted(g[0] for g in tree.level(lvl - 1).groups)
                assert members == prev
            for g in groups:
                assert g == sorted(g)          # leader = lowest rank
        assert len(tree.level(tree.n_levels - 1).groups) == 1
        for r in range(n):
            for lvl in range(tree.n_levels):
                rep = tree.rep(lvl, r)
                assert rep in tree.group(lvl, r)
                assert tree.is_member(lvl, r) == (rep == r)
                assert tree.group(lvl, r)[tree.rep_group_rank(lvl, r)] == rep

    def test_describe_names_levels(self):
        tree = HierTree(_paths([0, 0, 1, 1], pod_of=[0, 1]), my_rank=0)
        text = tree.describe()
        assert "3 levels" in text and "node" in text and "top" in text


class TestFakeTopology:
    def test_cyclic_ppn(self):
        env = {"UCC_TOPO_FAKE_PPN": "2,1,3"}
        nodes = [fake_topology(r, env)[0] for r in range(8)]
        assert nodes == [0, 0, 1, 2, 2, 2, 3, 3]

    def test_pods(self):
        env = {"UCC_TOPO_FAKE_PPN": "2", "UCC_TOPO_FAKE_NODES_PER_POD": "2"}
        pods = [fake_topology(r, env)[1] for r in range(8)]
        assert pods == [0, 0, 0, 0, 1, 1, 1, 1]

    def test_unset(self):
        assert fake_topology(3, {}) == (None, None)


def _topo(procs, my_rank=0):
    return TeamTopo(ContextTopo(procs), EpMap.full(len(procs)), my_rank)


def _procs(node_of, pod_of=None):
    import zlib
    out = []
    for r, node in enumerate(node_of):
        hh = zlib.crc32(f"fake-node-{node}".encode())
        ph = -1 if pod_of is None else \
            zlib.crc32(f"fake-pod-{pod_of[node]}".encode())
        out.append(ProcInfo(host_hash=hh, pid=1000 + r, real_host_hash=hh,
                            pod_hash=ph))
    return out


class TestTeamTopoAsymmetric:
    """TeamTopo.node_layout / sbgp construction beyond the symmetric
    two-level case."""

    def test_node_layout_sorted_counts(self):
        topo = _topo(_procs([0, 0, 1, 2, 2, 2]))
        assert topo.node_layout() == (1, 2, 3)

    def test_node_layout_single_rank_nodes(self):
        topo = _topo(_procs([0, 1, 2]))
        assert topo.node_layout() == (1, 1, 1)

    def test_node_sbgp_on_single_rank_node(self):
        topo = _topo(_procs([0, 0, 1, 2, 2, 2]), my_rank=2)
        node = topo.get_sbgp(SbgpType.NODE)
        assert node.status == SbgpStatus.ENABLED
        assert node.size == 1 and node.group_rank == 0

    def test_leaders_sbgp_asymmetric(self):
        topo = _topo(_procs([0, 0, 1, 2, 2, 2]), my_rank=3)
        leaders = topo.get_sbgp(SbgpType.NODE_LEADERS)
        assert leaders.status == SbgpStatus.ENABLED
        assert [int(leaders.map.eval(i)) for i in range(leaders.size)] \
            == [0, 2, 3]

    def test_net_not_exists_on_unequal_ppn(self):
        topo = _topo(_procs([0, 0, 1]))
        assert topo.get_sbgp(SbgpType.NET).status == SbgpStatus.NOT_EXISTS

    def test_hier_tree_depth_and_cap(self):
        procs = _procs([0, 0, 1, 1], pod_of=[0, 1])
        topo = _topo(procs)
        assert topo.pods_active()
        assert topo.hier_tree().n_levels == 3
        # a 2-level cap collapses the pod attribute (classic split)
        capped = topo.hier_tree(max_levels=2)
        assert capped.n_levels == 2
        assert capped.level(1).groups == [[0, 2]]

    def test_unknown_pods_degrade_to_two_levels(self):
        topo = _topo(_procs([0, 0, 1, 1]))   # pod_hash = -1 everywhere
        assert not topo.pods_active()
        assert topo.hier_tree().n_levels == 2


@pytest.fixture(scope="module")
def job():
    # 8 ranks -> nodes of 2,1,3,2 (cyclic "2,1,3"); nodes per pod 2 ->
    # pods {node0,node1} {node2,node3}: asymmetric everything, incl. a
    # single-rank node whose leader serves two tree levels
    os.environ["UCC_TOPO_FAKE_PPN"] = "2,1,3"
    os.environ["UCC_TOPO_FAKE_NODES_PER_POD"] = "2"
    j = UccJob(8)
    yield j
    j.cleanup()
    os.environ.pop("UCC_TOPO_FAKE_PPN", None)
    os.environ.pop("UCC_TOPO_FAKE_NODES_PER_POD", None)


@pytest.fixture(scope="module")
def teams(job):
    return job.create_team()


def hier_team_of(team):
    for clt in team.cl_teams:
        if clt.name == "hier":
            return clt
    return None


class TestNlvlEndToEnd:
    """Collectives composed over the asymmetric 3-level tree."""

    def test_tree_resolved(self, teams):
        ht = hier_team_of(teams[0])
        assert ht is not None
        assert ht.n_levels == 3
        assert ht.tree.level(0).groups == [[0, 1], [2], [3, 4, 5], [6, 7]]
        assert ht.tree.level(1).groups == [[0, 2], [3, 6]]
        assert ht.tree.level(2).groups == [[0, 3]]
        # units exist exactly where this rank is a member
        assert all(ht.level_unit(l) is not None for l in range(3))
        ht4 = hier_team_of(teams[4])
        assert ht4.level_unit(0) is not None
        assert ht4.level_unit(1) is None and ht4.level_unit(2) is None
        text = ht.describe_topology()
        assert "3 levels" in text and "not a participant" not in text

    def test_nlvl_is_default_on_pods(self, teams):
        cands = teams[0].score_map.lookup(
            CollType.ALLREDUCE, ucc_tpu.MemoryType.HOST, 1 << 16)
        assert cands[0].alg_name == "nrab"
        bc = teams[0].score_map.lookup(
            CollType.BCAST, ucc_tpu.MemoryType.HOST, 1 << 16)
        assert bc[0].alg_name == "nstep"

    @pytest.mark.parametrize("count", [1, 37, 4096])
    def test_allreduce(self, job, teams, count):
        n = 8
        srcs = [np.full(count, r + 1.0, np.float32) for r in range(n)]
        dsts = [np.zeros(count, np.float32) for _ in range(n)]
        job.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(srcs[r], count, DataType.FLOAT32),
            dst=BufferInfo(dsts[r], count, DataType.FLOAT32),
            op=ReductionOp.SUM))
        for r in range(n):
            np.testing.assert_allclose(dsts[r], 36.0)

    def test_allreduce_avg_inplace(self, job, teams):
        n, count = 8, 65
        bufs = [np.full(count, float(r), np.float64) for r in range(n)]
        job.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.ALLREDUCE, op=ReductionOp.AVG,
            src=None, dst=BufferInfo(bufs[r], count, DataType.FLOAT64),
            flags=CollArgsFlags.IN_PLACE))
        for r in range(n):
            np.testing.assert_allclose(bufs[r], 3.5)

    # roots chosen to hit every tree position: a pod/global leader, a
    # node leader that is not a pod leader, a plain member, and the
    # single-rank-node rank that serves two upper levels
    @pytest.mark.parametrize("root", [0, 2, 4, 6, 7])
    def test_bcast(self, job, teams, root):
        n, count = 8, 50
        bufs = [(np.arange(count, dtype=np.float32) if r == root
                 else np.zeros(count, np.float32)) for r in range(n)]
        job.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.BCAST, root=root,
            src=BufferInfo(bufs[r], count, DataType.FLOAT32)))
        for r in range(n):
            np.testing.assert_allclose(bufs[r],
                                       np.arange(count, dtype=np.float32))

    @pytest.mark.parametrize("root", [0, 2, 5])
    def test_reduce(self, job, teams, root):
        n, count = 8, 29
        srcs = [np.full(count, float(r + 1), np.float32) for r in range(n)]
        dst = np.zeros(count, np.float32)
        job.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.REDUCE, root=root, op=ReductionOp.SUM,
            src=BufferInfo(srcs[r], count, DataType.FLOAT32),
            dst=BufferInfo(dst, count, DataType.FLOAT32)
            if r == root else None))
        np.testing.assert_allclose(dst, 36.0)

    def test_barrier(self, job, teams):
        job.run_coll(teams, lambda r: CollArgs(coll_type=CollType.BARRIER))

    def test_allgather(self, job, teams):
        n, blk = 8, 3
        srcs = [np.full(blk, r + 1.0, np.float32) for r in range(n)]
        dsts = [np.zeros(blk * n, np.float32) for _ in range(n)]
        job.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.ALLGATHER,
            src=BufferInfo(srcs[r], blk, DataType.FLOAT32),
            dst=BufferInfo(dsts[r], blk * n, DataType.FLOAT32)))
        exp = np.repeat(np.arange(1, n + 1, dtype=np.float32), blk)
        for r in range(n):
            np.testing.assert_allclose(dsts[r], exp)

    def test_allgatherv_uneven(self, job, teams):
        from ucc_tpu.api.types import BufferInfoV
        n = 8
        counts = [r + 1 for r in range(n)]
        total = sum(counts)
        displs = list(np.cumsum([0] + counts[:-1]))
        srcs = [np.full(counts[r], r + 1.0, np.float32) for r in range(n)]
        dsts = [np.zeros(total, np.float32) for _ in range(n)]
        job.run_coll(teams, lambda r: CollArgs(
            coll_type=CollType.ALLGATHERV,
            src=BufferInfo(srcs[r], counts[r], DataType.FLOAT32),
            dst=BufferInfoV(dsts[r], counts, displs, DataType.FLOAT32)))
        exp = np.concatenate([np.full(c, i + 1.0, np.float32)
                              for i, c in enumerate(counts)])
        for r in range(n):
            np.testing.assert_allclose(dsts[r], exp)
