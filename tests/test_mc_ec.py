"""MC/EC component tests — mirrors reference gtest core/test_mc.cc and
ec tests: reductions across ops × dtypes, strided/multi-dst/copy task
types, alpha scaling, executor semantics. EC/TPU pallas kernels run in
interpret mode on the CPU backend."""
import numpy as np
import pytest

from ucc_tpu.constants import DataType, MemoryType, ReductionOp
from ucc_tpu.ec.base import EXECUTOR_NUM_BUFS, create_executor
from ucc_tpu.ec.cpu import EcCpu
from ucc_tpu.mc.base import detect_mem_type, get_mc
from ucc_tpu.status import Status, UccError


class TestMcCpu:
    def test_alloc_memcpy_memset(self):
        mc = get_mc(MemoryType.HOST)
        buf = mc.alloc(64)
        mc.memset(buf, 7, 64)
        assert (buf == 7).all()
        dst = mc.alloc(64)
        mc.memcpy(dst, buf, 64)
        assert (dst == 7).all()

    def test_detect(self):
        assert detect_mem_type(np.zeros(4)) == MemoryType.HOST
        assert detect_mem_type(b"abc") == MemoryType.HOST


class TestMcTpu:
    def test_query_and_staging(self):
        jax = pytest.importorskip("jax")
        import jax.numpy as jnp
        from ucc_tpu.mc.tpu import McTpu
        mc = McTpu()
        arr = jnp.arange(8, dtype=jnp.float32)
        attr = mc.mem_query(arr)
        assert attr is not None and attr.mem_type == MemoryType.TPU
        assert detect_mem_type(arr) == MemoryType.TPU
        # HBM alloc + pool recycle
        a = mc.alloc(1024, dtype=np.float32)
        assert a.shape == (256,)
        mc.free(a)
        b = mc.alloc(1024, dtype=np.float32)
        assert b is a   # recycled
        # host <- device staging
        host = np.zeros(8, np.float32)
        mc.memcpy(host, arr, 32)
        np.testing.assert_array_equal(host, np.arange(8, dtype=np.float32))


class TestEcCpu:
    @pytest.mark.parametrize("op,ref", [
        (ReductionOp.SUM, lambda a: np.sum(a, axis=0)),
        (ReductionOp.PROD, lambda a: np.prod(np.stack(a), axis=0)),
        (ReductionOp.MAX, lambda a: np.maximum.reduce(a)),
        (ReductionOp.MIN, lambda a: np.minimum.reduce(a)),
        (ReductionOp.BAND, lambda a: np.bitwise_and.reduce(a)),
        (ReductionOp.BXOR, lambda a: np.bitwise_xor.reduce(a)),
        (ReductionOp.LAND, lambda a: np.logical_and.reduce(a).astype(a[0].dtype)),
    ])
    def test_reduce_int(self, op, ref):
        ec = EcCpu()
        srcs = [np.arange(1, 33, dtype=np.int32) + i for i in range(3)]
        dst = np.zeros(32, np.int32)
        ec.reduce(dst, srcs, 32, DataType.INT32, op)
        np.testing.assert_array_equal(dst, ref(srcs))

    def test_avg_alpha(self):
        ec = EcCpu()
        srcs = [np.ones(8, np.float32) * (i + 1) for i in range(4)]
        dst = np.zeros(8, np.float32)
        ec.reduce(dst, srcs, 8, DataType.FLOAT32, ReductionOp.AVG, alpha=0.25)
        np.testing.assert_allclose(dst, 2.5)

    def test_reduce_strided(self):
        ec = EcCpu()
        src1 = np.ones(4, np.float32)
        base = np.arange(12, dtype=np.float32)   # 3 strided srcs of 4
        dst = np.zeros(4, np.float32)
        ec.reduce_strided(dst, src1, base, 16, 3, 4, DataType.FLOAT32,
                          ReductionOp.SUM)
        np.testing.assert_allclose(dst, 1 + base[0:4] + base[4:8] + base[8:12])

    def test_num_bufs_cap(self):
        ec = EcCpu()
        srcs = [np.ones(2, np.float32)] * (EXECUTOR_NUM_BUFS + 1)
        with pytest.raises(UccError):
            ec.reduce(np.zeros(2, np.float32), srcs, 2, DataType.FLOAT32,
                      ReductionOp.SUM)

    def test_band_on_float_rejected(self):
        ec = EcCpu()
        with pytest.raises(UccError):
            ec.reduce(np.zeros(2, np.float32), [np.ones(2, np.float32)] * 2,
                      2, DataType.FLOAT32, ReductionOp.BAND)


class TestEcTpu:
    @pytest.fixture(scope="class")
    def ec(self):
        pytest.importorskip("jax")
        return create_executor(MemoryType.TPU)

    @pytest.mark.parametrize("op,ref", [
        (ReductionOp.SUM, lambda a: np.sum(a, axis=0)),
        (ReductionOp.PROD, lambda a: np.prod(np.stack(a), axis=0)),
        (ReductionOp.MAX, lambda a: np.maximum.reduce(a)),
        (ReductionOp.MIN, lambda a: np.minimum.reduce(a)),
    ])
    @pytest.mark.parametrize("count", [7, 128, 1000])
    def test_reduce_f32(self, ec, op, ref, count):
        srcs = [np.random.default_rng(i).random(count).astype(np.float32) + 1
                for i in range(4)]
        t = ec.reduce(None, srcs, count, DataType.FLOAT32, op)
        while ec.task_test(t) == Status.IN_PROGRESS:
            pass
        np.testing.assert_allclose(np.asarray(t.array), ref(srcs), rtol=1e-5)

    def test_reduce_bitwise_int(self, ec):
        srcs = [(np.arange(64) + i * 3).astype(np.int32) for i in range(3)]
        t = ec.reduce(None, srcs, 64, DataType.INT32, ReductionOp.BXOR)
        while ec.task_test(t) == Status.IN_PROGRESS:
            pass
        np.testing.assert_array_equal(np.asarray(t.array),
                                      np.bitwise_xor.reduce(srcs))

    def test_bf16_accumulates_f32(self, ec):
        import ml_dtypes
        nd = np.dtype(ml_dtypes.bfloat16)
        srcs = [np.full(256, 0.1, dtype=nd) for _ in range(8)]
        t = ec.reduce(None, srcs, 256, DataType.BFLOAT16, ReductionOp.SUM)
        while ec.task_test(t) == Status.IN_PROGRESS:
            pass
        out = np.asarray(t.array).astype(np.float32)
        # bf16-accumulated would drift much further than f32-accumulated
        np.testing.assert_allclose(out, 0.80078, rtol=3e-3)

    def test_avg_with_alpha(self, ec):
        srcs = [np.full(64, float(i + 1), np.float32) for i in range(4)]
        t = ec.reduce(None, srcs, 64, DataType.FLOAT32, ReductionOp.AVG,
                      alpha=0.25)
        while ec.task_test(t) == Status.IN_PROGRESS:
            pass
        np.testing.assert_allclose(np.asarray(t.array), 2.5)

    def test_minloc(self, ec):
        pairs = 8
        srcs = []
        for r in range(3):
            arr = np.empty(pairs * 2, np.float32)
            arr[0::2] = np.random.default_rng(r).random(pairs)
            arr[1::2] = r
            srcs.append(arr)
        t = ec.reduce(None, srcs, pairs * 2, DataType.FLOAT32,
                      ReductionOp.MINLOC)
        while ec.task_test(t) == Status.IN_PROGRESS:
            pass
        out = np.asarray(t.array)
        vals = np.stack([s[0::2] for s in srcs])
        np.testing.assert_allclose(out[0::2], vals.min(axis=0))
        np.testing.assert_array_equal(out[1::2].astype(int),
                                      vals.argmin(axis=0))

    def test_reduce_strided(self, ec):
        src1 = np.ones(16, np.float32)
        base = np.arange(48, dtype=np.float32)
        t = ec.reduce_strided(None, src1, base, 64, 3, 16, DataType.FLOAT32,
                              ReductionOp.SUM)
        while ec.task_test(t) == Status.IN_PROGRESS:
            pass
        np.testing.assert_allclose(
            np.asarray(t.array),
            1 + base[:16] + base[16:32] + base[32:48])

    def test_copy(self, ec):
        src = np.arange(32, dtype=np.int64)
        t = ec.copy(None, src, 32 * 8)
        while ec.task_test(t) == Status.IN_PROGRESS:
            pass
        np.testing.assert_array_equal(np.asarray(t.array), src)


class TestMcTpuD2D:
    """Round-2: device<->device copies must not round-trip the
    DESTINATION through host numpy (VERDICT r1 weak #4)."""

    def test_full_copy_lands_on_dst_device(self):
        import jax
        import jax.numpy as jnp
        from ucc_tpu.mc.tpu import McTpu
        devs = jax.devices()
        if len(devs) < 2:
            pytest.skip("needs >= 2 devices")
        mc = McTpu()
        src = jax.device_put(jnp.arange(16, dtype=jnp.float32), devs[0])
        dst = jax.device_put(jnp.zeros(16, jnp.float32), devs[1])
        out = mc.memcpy(dst, src, 16 * 4)
        assert set(out.devices()) == {devs[1]}
        np.testing.assert_array_equal(np.asarray(out),
                                      np.arange(16, dtype=np.float32))

    def test_partial_copy_preserves_tail_on_device(self):
        import jax
        import jax.numpy as jnp
        from ucc_tpu.mc.tpu import McTpu
        mc = McTpu()
        dev = jax.devices()[0]
        src = jax.device_put(jnp.full(8, 7.0, jnp.float32), dev)
        dst = jax.device_put(jnp.arange(8, dtype=jnp.float32), dev)
        out = mc.memcpy(dst, src, 4 * 4)     # first 4 elements only
        np.testing.assert_array_equal(
            np.asarray(out), [7, 7, 7, 7, 4, 5, 6, 7])

    def test_memset_on_device(self):
        import jax
        import jax.numpy as jnp
        from ucc_tpu.mc.tpu import McTpu
        mc = McTpu()
        dev = jax.devices()[0]
        buf = jax.device_put(jnp.arange(6, dtype=jnp.int32), dev)
        out = mc.memset(buf, 0, 3 * 4)
        np.testing.assert_array_equal(np.asarray(out), [0, 0, 0, 3, 4, 5])


class TestEcTpuCopyContract:
    def test_copy_lands_on_dst_device(self):
        import jax
        import jax.numpy as jnp
        from ucc_tpu.ec.tpu import EcTpu
        devs = jax.devices()
        if len(devs) < 2:
            pytest.skip("needs >= 2 devices")
        ec = EcTpu()
        src = jax.device_put(jnp.arange(8, dtype=jnp.float32), devs[0])
        dst = jax.device_put(jnp.zeros(8, jnp.float32), devs[1])
        from ucc_tpu import Status
        t = ec.copy(dst, src, 8 * 4)
        while ec.task_test(t) == Status.IN_PROGRESS:
            pass
        assert set(t.array.devices()) == {devs[1]}

    def test_copy_overflow_asserts(self):
        import jax.numpy as jnp
        from ucc_tpu.ec.tpu import EcTpu
        from ucc_tpu import UccError
        ec = EcTpu()
        src = jnp.arange(8, dtype=jnp.float32)
        dst = jnp.zeros(2, jnp.float32)
        with pytest.raises(UccError):
            ec.copy(dst, src, 8 * 4)
