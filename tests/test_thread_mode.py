"""Thread modes (ucc.h:493-497): MULTIPLE-mode world where every rank is
driven concurrently from its own OS thread (the deployment shape of a
one-process-per-host pod runner) over the MT progress queue."""
import threading

import numpy as np
import pytest

import ucc_tpu
from ucc_tpu import (BufferInfo, CollArgs, CollType, Context, ContextParams,
                     DataType, LibParams, ReductionOp, Status, TeamParams,
                     ThreadMode, ThreadOobWorld)
from ucc_tpu.schedule.progress import ProgressQueueMT


class TestThreadModeMultiple:
    def test_concurrent_rank_threads(self):
        n = 4
        iters = 5
        world = ThreadOobWorld(n)
        libs = [ucc_tpu.init(LibParams(thread_mode=ThreadMode.MULTIPLE))
                for _ in range(n)]
        ctxs = [None] * n

        def mk(r):
            ctxs[r] = Context(libs[r], ContextParams(oob=world.endpoint(r)))

        ths = [threading.Thread(target=mk, args=(r,)) for r in range(n)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert all(isinstance(c.progress_queue, ProgressQueueMT)
                   for c in ctxs)

        tw = ThreadOobWorld(n)
        teams = [None] * n
        errors = []
        results = [[None] * iters for _ in range(n)]

        def rank_main(r):
            try:
                team = ctxs[r].create_team(TeamParams(oob=tw.endpoint(r)))
                teams[r] = team
                count = 256
                for it in range(iters):
                    src = np.full(count, (r + 1) * (it + 1), np.float64)
                    dst = np.zeros(count, np.float64)
                    req = team.collective_init(CollArgs(
                        coll_type=CollType.ALLREDUCE,
                        src=BufferInfo(src, count, DataType.FLOAT64),
                        dst=BufferInfo(dst, count, DataType.FLOAT64),
                        op=ReductionOp.SUM))
                    req.post()
                    req.wait(timeout=60)
                    results[r][it] = float(dst[0])
            except Exception as e:  # noqa: BLE001
                errors.append((r, e))

        ths = [threading.Thread(target=rank_main, args=(r,))
               for r in range(n)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=120)
        assert not errors, errors
        for it in range(iters):
            expect = (it + 1) * n * (n + 1) / 2
            for r in range(n):
                assert results[r][it] == expect, (r, it)


class TestThreadModeStress:
    def test_concurrent_collectives_two_teams(self):
        """MULTIPLE-mode stress: every rank thread keeps TWO collectives
        in flight at once (one per team, posted before either is waited),
        across mixed coll types and several iterations — exercises the MT
        progress queue under genuine cross-thread concurrency."""
        n, iters = 4, 6
        world = ThreadOobWorld(n)
        libs = [ucc_tpu.init(LibParams(thread_mode=ThreadMode.MULTIPLE))
                for _ in range(n)]
        ctxs = [None] * n

        def mk(r):
            ctxs[r] = Context(libs[r], ContextParams(oob=world.endpoint(r)))

        ths = [threading.Thread(target=mk, args=(r,)) for r in range(n)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()

        tw_a, tw_b = ThreadOobWorld(n), ThreadOobWorld(n)
        errors = []
        sums = [[None] * iters for _ in range(n)]
        gathers = [[None] * iters for _ in range(n)]

        def rank_main(r):
            try:
                team_a = ctxs[r].create_team(TeamParams(oob=tw_a.endpoint(r)))
                team_b = ctxs[r].create_team(TeamParams(oob=tw_b.endpoint(r)))
                count = 128
                for it in range(iters):
                    src_a = np.full(count, (r + 1) * (it + 1), np.float64)
                    dst_a = np.zeros(count, np.float64)
                    req_a = team_a.collective_init(CollArgs(
                        coll_type=CollType.ALLREDUCE,
                        src=BufferInfo(src_a, count, DataType.FLOAT64),
                        dst=BufferInfo(dst_a, count, DataType.FLOAT64),
                        op=ReductionOp.SUM))
                    src_b = np.full(8, r * 10 + it, np.int64)
                    dst_b = np.zeros(8 * n, np.int64)
                    req_b = team_b.collective_init(CollArgs(
                        coll_type=CollType.ALLGATHER,
                        src=BufferInfo(src_b, 8, DataType.INT64),
                        dst=BufferInfo(dst_b, 8 * n, DataType.INT64)))
                    # both in flight before either completes
                    req_a.post()
                    req_b.post()
                    req_b.wait(timeout=90)
                    req_a.wait(timeout=90)
                    sums[r][it] = float(dst_a[0])
                    gathers[r][it] = dst_b.copy()
                    # interleave a barrier on team A while team B idles
                    bar = team_a.collective_init(CollArgs(
                        coll_type=CollType.BARRIER))
                    bar.post()
                    bar.wait(timeout=90)
            except Exception as e:  # noqa: BLE001
                import traceback
                errors.append((r, e, traceback.format_exc()))

        ths = [threading.Thread(target=rank_main, args=(r,))
               for r in range(n)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=240)
        assert not errors, errors[0]
        for it in range(iters):
            expect_sum = (it + 1) * n * (n + 1) / 2
            expect_g = np.concatenate(
                [np.full(8, p * 10 + it, np.int64) for p in range(n)])
            for r in range(n):
                assert sums[r][it] == expect_sum, (r, it)
                np.testing.assert_array_equal(gathers[r][it], expect_g)


class TestThreadModeFastLane:
    """MULTIPLE-mode stress of the round-3 persistent FAST RE-POST lane
    on device buffers: every rank re-posts from its own OS thread, the
    last depositor's thread launches and finishes peers in set_result
    (cross-thread super_status writes) — the exact interleaving the
    lane's no-owner-completion argument must survive."""

    def test_concurrent_persistent_device_reposts(self):
        jax = pytest.importorskip("jax")
        import jax.numpy as jnp
        from ucc_tpu import CollArgsFlags, MemoryType

        n, iters, count = 4, 12, 64
        world = ThreadOobWorld(n)
        libs = [ucc_tpu.init(LibParams(thread_mode=ThreadMode.MULTIPLE))
                for _ in range(n)]
        ctxs = [None] * n

        def mk(r):
            ctxs[r] = Context(libs[r], ContextParams(oob=world.endpoint(r)))

        ths = [threading.Thread(target=mk, args=(r,)) for r in range(n)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()

        tw = ThreadOobWorld(n)
        errors = []
        results = [[None] * iters for _ in range(n)]
        barrier = threading.Barrier(n)

        def rank_main(r):
            try:
                team = ctxs[r].create_team(TeamParams(oob=tw.endpoint(r)))
                dev = ctxs[r].tl_contexts["xla"].obj.device
                src = jax.device_put(
                    jnp.full((count,), r + 1.0, jnp.float32), dev)
                args = CollArgs(
                    coll_type=CollType.ALLREDUCE,
                    src=BufferInfo(src, count, DataType.FLOAT32,
                                   mem_type=MemoryType.TPU),
                    dst=BufferInfo(None, count, DataType.FLOAT32,
                                   mem_type=MemoryType.TPU),
                    op=ReductionOp.SUM,
                    flags=CollArgsFlags.PERSISTENT)
                req = team.collective_init(args)
                for it in range(iters):
                    barrier.wait(timeout=60)   # maximize re-post overlap
                    req.post()
                    req.wait(timeout=60)
                    results[r][it] = float(
                        np.asarray(args.dst.buffer)[0])
                req.finalize()
            except Exception as e:  # noqa: BLE001
                errors.append((r, e))

        ths = [threading.Thread(target=rank_main, args=(r,))
               for r in range(n)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=180)
        assert not errors, errors
        expect = n * (n + 1) / 2
        for r in range(n):
            for it in range(iters):
                assert results[r][it] == expect, (r, it, results[r][it])


class TestThreadModeOneSided:
    """MULTIPLE-mode stress of the one-sided path: every rank drives
    sliding-window allreduce re-posts from its own OS thread — the
    segment registry and arrival counters take concurrent puts/gets
    under the registry lock while each owner reduces in its own
    thread."""

    def test_concurrent_sliding_window_reposts(self, monkeypatch):
        from ucc_tpu import CollArgsFlags
        monkeypatch.setenv("UCC_TL_SHM_TUNE", "allreduce:@sliding_window")
        monkeypatch.setenv("UCC_TL_SHM_ALLREDUCE_SW_WINDOW", "128")
        n, iters, count = 4, 10, 300
        world = ThreadOobWorld(n)
        libs = [ucc_tpu.init(LibParams(thread_mode=ThreadMode.MULTIPLE))
                for _ in range(n)]
        ctxs = [None] * n

        def mk(r):
            ctxs[r] = Context(libs[r], ContextParams(oob=world.endpoint(r)))

        ths = [threading.Thread(target=mk, args=(r,)) for r in range(n)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()

        tw = ThreadOobWorld(n)
        srcs = [np.arange(count, dtype=np.float64) * (r + 1)
                for r in range(n)]
        dsts = [np.zeros(count, dtype=np.float64) for _ in range(n)]
        sh = [ctxs[r].mem_map(srcs[r]) for r in range(n)]
        dh = [ctxs[r].mem_map(dsts[r]) for r in range(n)]
        errors = []
        barrier = threading.Barrier(n)

        def rank_main(r):
            try:
                team = ctxs[r].create_team(TeamParams(oob=tw.endpoint(r)))
                args = CollArgs(
                    coll_type=CollType.ALLREDUCE,
                    src=BufferInfo(srcs[r], count, DataType.FLOAT64),
                    dst=BufferInfo(dsts[r], count, DataType.FLOAT64),
                    op=ReductionOp.SUM,
                    src_memh=list(sh), dst_memh=list(dh),
                    flags=(CollArgsFlags.MEM_MAP_SRC_MEMH
                           | CollArgsFlags.MEM_MAP_DST_MEMH
                           | CollArgsFlags.PERSISTENT))
                req = team.collective_init(args)
                for _ in range(iters):
                    barrier.wait(timeout=60)
                    req.post()
                    req.wait(timeout=60)
                req.finalize()
            except Exception as e:  # noqa: BLE001
                errors.append((r, e))

        ths = [threading.Thread(target=rank_main, args=(r,))
               for r in range(n)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=180)
        assert not errors, errors
        expect = np.arange(count, dtype=np.float64) * sum(
            range(1, n + 1))
        for r in range(n):
            np.testing.assert_allclose(dsts[r], expect, rtol=1e-12)
