"""Thread modes (ucc.h:493-497): MULTIPLE-mode world where every rank is
driven concurrently from its own OS thread (the deployment shape of a
one-process-per-host pod runner) over the MT progress queue."""
import threading

import numpy as np
import pytest

import ucc_tpu
from ucc_tpu import (BufferInfo, CollArgs, CollType, Context, ContextParams,
                     DataType, LibParams, ReductionOp, Status, TeamParams,
                     ThreadMode, ThreadOobWorld)
from ucc_tpu.schedule.progress import ProgressQueueMT


class TestThreadModeMultiple:
    def test_concurrent_rank_threads(self):
        n = 4
        iters = 5
        world = ThreadOobWorld(n)
        libs = [ucc_tpu.init(LibParams(thread_mode=ThreadMode.MULTIPLE))
                for _ in range(n)]
        ctxs = [None] * n

        def mk(r):
            ctxs[r] = Context(libs[r], ContextParams(oob=world.endpoint(r)))

        ths = [threading.Thread(target=mk, args=(r,)) for r in range(n)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert all(isinstance(c.progress_queue, ProgressQueueMT)
                   for c in ctxs)

        tw = ThreadOobWorld(n)
        teams = [None] * n
        errors = []
        results = [[None] * iters for _ in range(n)]

        def rank_main(r):
            try:
                team = ctxs[r].create_team(TeamParams(oob=tw.endpoint(r)))
                teams[r] = team
                count = 256
                for it in range(iters):
                    src = np.full(count, (r + 1) * (it + 1), np.float64)
                    dst = np.zeros(count, np.float64)
                    req = team.collective_init(CollArgs(
                        coll_type=CollType.ALLREDUCE,
                        src=BufferInfo(src, count, DataType.FLOAT64),
                        dst=BufferInfo(dst, count, DataType.FLOAT64),
                        op=ReductionOp.SUM))
                    req.post()
                    req.wait(timeout=60)
                    results[r][it] = float(dst[0])
            except Exception as e:  # noqa: BLE001
                errors.append((r, e))

        ths = [threading.Thread(target=rank_main, args=(r,))
               for r in range(n)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=120)
        assert not errors, errors
        for it in range(iters):
            expect = (it + 1) * n * (n + 1) / 2
            for r in range(n):
                assert results[r][it] == expect, (r, it)
