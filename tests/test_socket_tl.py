"""TL/SOCKET multi-process integration — the test/mpi-style real-transport
check (reference test/mpi sweeps colls across processes; here 3 OS
processes bootstrap via TcpStoreOob and run collectives over TCP)."""
import multiprocessing as mp
import os
import pickle
import sys

import numpy as np
import pytest


def _worker(rank: int, size: int, port: int, q):
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["UCC_TLS"] = "socket,self"   # force the TCP path
        import ucc_tpu
        from ucc_tpu import (BufferInfo, CollArgs, CollType, ContextParams,
                             DataType, ReductionOp, Status, TcpStoreOob,
                             TeamParams)

        oob = TcpStoreOob(rank, size, port=port)
        lib = ucc_tpu.init()
        ctx = ucc_tpu.Context(lib, ContextParams(oob=oob))
        team_oob = TcpStoreOob(rank, size, port=port + 1)
        team = ctx.create_team(TeamParams(oob=team_oob))

        results = {}
        # allreduce
        src = np.full(32, rank + 1.0, np.float32)
        dst = np.zeros(32, np.float32)
        req = team.collective_init(CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(src, 32, DataType.FLOAT32),
            dst=BufferInfo(dst, 32, DataType.FLOAT32),
            op=ReductionOp.SUM))
        req.post()
        req.wait(timeout=60)
        results["allreduce"] = float(dst[0])

        # bcast from rank 1
        buf = np.full(8, 42, np.int32) if rank == 1 else np.zeros(8, np.int32)
        req = team.collective_init(CollArgs(
            coll_type=CollType.BCAST, root=1,
            src=BufferInfo(buf, 8, DataType.INT32)))
        req.post()
        req.wait(timeout=60)
        results["bcast"] = int(buf[0])

        # alltoall
        total = 2 * size
        srcs = np.arange(total, dtype=np.int32) + 100 * rank
        dsta = np.zeros(total, np.int32)
        req = team.collective_init(CollArgs(
            coll_type=CollType.ALLTOALL,
            src=BufferInfo(srcs, total, DataType.INT32),
            dst=BufferInfo(dsta, total, DataType.INT32)))
        req.post()
        req.wait(timeout=60)
        results["alltoall"] = dsta.tolist()

        # barrier
        req = team.collective_init(CollArgs(coll_type=CollType.BARRIER))
        req.post()
        req.wait(timeout=60)
        results["barrier"] = "ok"

        q.put((rank, results))
        ctx.destroy()
        if rank == 0:
            oob.close()
    except Exception as e:  # noqa: BLE001
        import traceback
        q.put((rank, {"error": f"{e}\n{traceback.format_exc()}"}))


def _free_port_pair():
    import socket as _s
    while True:
        with _s.socket() as a:
            a.bind(("127.0.0.1", 0))
            port = a.getsockname()[1]
        try:
            with _s.socket() as b:
                b.bind(("127.0.0.1", port + 1))
            return port
        except OSError:
            continue


def test_socket_tl_three_processes():
    size = 3
    port = _free_port_pair()
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=_worker, args=(r, size, port, q))
             for r in range(size)]
    for p in procs:
        p.start()
    results = {}
    for _ in range(size):
        rank, res = q.get(timeout=150)
        results[rank] = res
    for p in procs:
        p.join(timeout=30)
        if p.is_alive():
            p.terminate()
    for r in range(size):
        assert "error" not in results[r], results[r].get("error")
        assert results[r]["allreduce"] == 6.0       # 1+2+3
        assert results[r]["bcast"] == 42
        assert results[r]["barrier"] == "ok"
    # alltoall: rank r's dst = concat over p of srcs[p][r*2:(r+1)*2]
    for r in range(size):
        expect = []
        for p in range(size):
            base = 100 * p
            expect += [base + r * 2, base + r * 2 + 1]
        assert results[r]["alltoall"] == expect
