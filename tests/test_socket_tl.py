"""TL/SOCKET multi-process integration — the test/mpi-style real-transport
check (reference test/mpi sweeps colls across processes; here 3 OS
processes bootstrap via TcpStoreOob and run collectives over TCP)."""
import multiprocessing as mp
import os
import pickle
import sys

import numpy as np
import pytest


def _worker(rank: int, size: int, port: int, q):
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["UCC_TLS"] = "socket,self"   # force the TCP path
        import ucc_tpu
        from ucc_tpu import (BufferInfo, CollArgs, CollType, ContextParams,
                             DataType, ReductionOp, Status, TcpStoreOob,
                             TeamParams)

        oob = TcpStoreOob(rank, size, port=port)
        lib = ucc_tpu.init()
        ctx = ucc_tpu.Context(lib, ContextParams(oob=oob))
        team_oob = TcpStoreOob(rank, size, port=port + 1)
        team = ctx.create_team(TeamParams(oob=team_oob))

        results = {}
        # allreduce
        src = np.full(32, rank + 1.0, np.float32)
        dst = np.zeros(32, np.float32)
        req = team.collective_init(CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(src, 32, DataType.FLOAT32),
            dst=BufferInfo(dst, 32, DataType.FLOAT32),
            op=ReductionOp.SUM))
        req.post()
        req.wait(timeout=60)
        results["allreduce"] = float(dst[0])

        # bcast from rank 1
        buf = np.full(8, 42, np.int32) if rank == 1 else np.zeros(8, np.int32)
        req = team.collective_init(CollArgs(
            coll_type=CollType.BCAST, root=1,
            src=BufferInfo(buf, 8, DataType.INT32)))
        req.post()
        req.wait(timeout=60)
        results["bcast"] = int(buf[0])

        # alltoall
        total = 2 * size
        srcs = np.arange(total, dtype=np.int32) + 100 * rank
        dsta = np.zeros(total, np.int32)
        req = team.collective_init(CollArgs(
            coll_type=CollType.ALLTOALL,
            src=BufferInfo(srcs, total, DataType.INT32),
            dst=BufferInfo(dsta, total, DataType.INT32)))
        req.post()
        req.wait(timeout=60)
        results["alltoall"] = dsta.tolist()

        # barrier
        req = team.collective_init(CollArgs(coll_type=CollType.BARRIER))
        req.post()
        req.wait(timeout=60)
        results["barrier"] = "ok"

        q.put((rank, results))
        ctx.destroy()
        if rank == 0:
            oob.close()
    except Exception as e:  # noqa: BLE001
        import traceback
        q.put((rank, {"error": f"{e}\n{traceback.format_exc()}"}))


def _free_port_pair():
    """Probe an adjacent port pair holding BOTH sockets simultaneously:
    the kernel's ephemeral allocator is roughly sequential, so a
    probe-release-then-bind(+1) dance hands +1 to the next listener any
    process opens (the collision class the round-5 gate caught)."""
    import socket as _s
    while True:
        a = _s.socket()
        a.bind(("127.0.0.1", 0))
        port = a.getsockname()[1]
        b = _s.socket()
        try:
            b.bind(("127.0.0.1", port + 1))
        except OSError:
            a.close()
            b.close()
            continue
        a.close()
        b.close()
        return port


def test_socket_tl_three_processes():
    size = 3
    port = _free_port_pair()
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=_worker, args=(r, size, port, q))
             for r in range(size)]
    for p in procs:
        p.start()
    results = {}
    for _ in range(size):
        rank, res = q.get(timeout=150)
        results[rank] = res
    for p in procs:
        p.join(timeout=30)
        if p.is_alive():
            p.terminate()
    for r in range(size):
        assert "error" not in results[r], results[r].get("error")
        assert results[r]["allreduce"] == 6.0       # 1+2+3
        assert results[r]["bcast"] == 42
        assert results[r]["barrier"] == "ok"
    # alltoall: rank r's dst = concat over p of srcs[p][r*2:(r+1)*2]
    for r in range(size):
        expect = []
        for p in range(size):
            base = 100 * p
            expect += [base + r * 2, base + r * 2 + 1]
        assert results[r]["alltoall"] == expect


# ---------------------------------------------------------------------------
# round-2 sweep: colls x dtypes x sizes x team shapes over real processes
# (the reference test/mpi matrix, main.cc:19-66)
# ---------------------------------------------------------------------------

def _sweep_cases(size):
    """Case list; expectations computed by the parent with numpy."""
    return [
        {"coll": "allreduce", "dt": "f32", "count": 8, "op": "sum"},
        {"coll": "allreduce", "dt": "f64", "count": 32768, "op": "avg"},
        {"coll": "allreduce", "dt": "i32", "count": 1000, "op": "max"},
        {"coll": "bcast", "dt": "i32", "count": 8, "root": 1 % size},
        {"coll": "bcast", "dt": "f64", "count": 16384, "root": size - 1},
        {"coll": "reduce", "dt": "f64", "count": 1000, "op": "sum",
         "root": 0},
        {"coll": "allgather", "dt": "i64", "count": 5},
        {"coll": "allgatherv", "dt": "i32",
         "counts": [(r % 3) + 1 for r in range(size)]},
        {"coll": "alltoall", "dt": "i32", "count": 3 * size},
        {"coll": "reduce_scatter", "dt": "f32", "count": 4 * size,
         "op": "sum"},
        {"coll": "gather", "dt": "i32", "count": 4, "root": 0},
        {"coll": "scatter", "dt": "f32", "count": 3 * size,
         "root": min(2, size - 1)},
        {"coll": "barrier"},
        # round-3 breadth (VERDICT r2 weak #8; reference bar
        # test/mpi/main.cc:19-66): v-colls, inplace, persistent re-post,
        # active-set bcast, fanin/fanout, more ops/dtypes/sizes
        {"coll": "alltoallv", "dt": "i32"},
        {"coll": "gatherv", "dt": "f32",
         "counts": [(r % 4) + 1 for r in range(size)], "root": 0},
        {"coll": "scatterv", "dt": "i32",
         "counts": [(r % 3) + 2 for r in range(size)], "root": size - 1},
        {"coll": "reduce_scatterv", "dt": "f64",
         "counts": [(r % 2) + 3 for r in range(size)], "op": "sum"},
        {"coll": "allreduce", "dt": "f32", "count": 64, "op": "sum",
         "inplace": True},
        {"coll": "allreduce", "dt": "i32", "count": 40, "op": "prod"},
        {"coll": "allreduce", "dt": "i32", "count": 100, "op": "min"},
        {"coll": "reduce", "dt": "i64", "count": 50, "op": "max",
         "root": size - 1},
        {"coll": "bcast", "dt": "i64", "count": 100000, "root": 0},
        {"coll": "allgather", "dt": "f32", "count": 4096},
        {"coll": "alltoall", "dt": "f64", "count": 8 * size},
        {"coll": "persistent_allreduce", "dt": "f32", "count": 128,
         "op": "sum", "rounds": 3},
        {"coll": "active_set_bcast", "dt": "i32", "count": 12,
         "root": 0, "set": [0, size - 1]},
        {"coll": "fanin"},
        {"coll": "fanout"},
    ]


def _a2av_matrix(size):
    """Send-counts matrix for the alltoallv case: m[p][q] = p->q count."""
    return [[(p + q) % 3 + 1 for q in range(size)] for p in range(size)]


_DTS = {"f32": ("FLOAT32", "float32"), "f64": ("FLOAT64", "float64"),
        "i32": ("INT32", "int32"), "i64": ("INT64", "int64")}


def _case_src(case, rank, size):
    nd = np.dtype(_DTS[case["dt"]][1]) if "dt" in case else None
    c = case.get("count", 0)
    coll = case["coll"]
    if coll in ("allreduce", "reduce", "reduce_scatter"):
        return (np.arange(c) % 7 + rank + 1).astype(nd)
    if coll == "bcast":
        return (np.arange(c) * 3).astype(nd) if rank == case["root"] else \
            np.zeros(c, nd)
    if coll == "allgather":
        return (np.arange(c) + 100 * rank).astype(nd)
    if coll == "allgatherv":
        return (np.arange(case["counts"][rank]) + 100 * rank).astype(nd)
    if coll == "alltoall":
        return (np.arange(c) + 100 * rank).astype(nd)
    if coll == "gather":
        return (np.arange(c) + 10 * rank).astype(nd)
    if coll == "scatter":
        return (np.arange(c) * 2).astype(nd)
    if coll == "alltoallv":
        total = sum(_a2av_matrix(size)[rank])
        return (np.arange(total) + 100 * rank).astype(nd)
    if coll == "gatherv":
        return (np.arange(case["counts"][rank]) + 100 * rank).astype(nd)
    if coll == "scatterv":
        return (np.arange(sum(case["counts"])) * 2).astype(nd)
    if coll == "reduce_scatterv":
        return (np.arange(sum(case["counts"])) % 5 + rank + 1).astype(nd)
    if coll == "persistent_allreduce":
        return (np.arange(c) % 9 + rank + 1).astype(nd)
    if coll == "active_set_bcast":
        return (np.arange(c) * 7).astype(nd) if rank == case["root"] \
            else np.zeros(c, nd)
    return None


def _sweep_worker(rank, size, port, q):
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["UCC_TLS"] = "socket,self"
        import ucc_tpu
        from ucc_tpu import (BufferInfo, BufferInfoV, CollArgs, CollType,
                             ContextParams, DataType, ReductionOp,
                             TcpStoreOob, TeamParams)
        OPS = {"sum": ReductionOp.SUM, "avg": ReductionOp.AVG,
               "max": ReductionOp.MAX, "min": ReductionOp.MIN,
               "prod": ReductionOp.PROD}
        COLLS = {"allreduce": CollType.ALLREDUCE, "bcast": CollType.BCAST,
                 "reduce": CollType.REDUCE, "allgather": CollType.ALLGATHER,
                 "allgatherv": CollType.ALLGATHERV,
                 "alltoall": CollType.ALLTOALL,
                 "reduce_scatter": CollType.REDUCE_SCATTER,
                 "gather": CollType.GATHER, "scatter": CollType.SCATTER,
                 "barrier": CollType.BARRIER,
                 "alltoallv": CollType.ALLTOALLV,
                 "gatherv": CollType.GATHERV,
                 "scatterv": CollType.SCATTERV,
                 "reduce_scatterv": CollType.REDUCE_SCATTERV}
        oob = TcpStoreOob(rank, size, port=port)
        lib = ucc_tpu.init()
        ctx = ucc_tpu.Context(lib, ContextParams(oob=oob))
        team = ctx.create_team(TeamParams(
            oob=TcpStoreOob(rank, size, port=port + 1)))
        from ucc_tpu import ActiveSet, CollArgsFlags
        results = {}
        for i, case in enumerate(_sweep_cases(size)):
            coll = case["coll"]
            if coll in ("barrier", "fanin", "fanout"):
                req = team.collective_init(CollArgs(
                    coll_type={"barrier": CollType.BARRIER,
                               "fanin": CollType.FANIN,
                               "fanout": CollType.FANOUT}[coll]))
                req.post()
                req.wait(timeout=90)
                results[i] = "ok"
                continue
            if coll == "persistent_allreduce":
                dt = getattr(DataType, _DTS[case["dt"]][0])
                nd = np.dtype(_DTS[case["dt"]][1])
                src = _case_src(case, rank, size)
                out = np.zeros(case["count"], nd)
                req = team.collective_init(CollArgs(
                    coll_type=CollType.ALLREDUCE,
                    op=ReductionOp.SUM,
                    src=BufferInfo(src, src.size, dt),
                    dst=BufferInfo(out, out.size, dt),
                    flags=CollArgsFlags.PERSISTENT))
                rounds = []
                for _ in range(case["rounds"]):
                    out[:] = 0
                    req.post()
                    req.wait(timeout=90)
                    rounds.append(out.copy())
                req.finalize()
                # every re-post must reproduce the same reduction
                for rnd in rounds[1:]:
                    assert np.array_equal(rnd, rounds[0]), "re-post drift"
                results[i] = rounds[-1].tolist()
                continue
            if coll == "active_set_bcast":
                # only the subset posts (ucc active sets, ucc.h:1890)
                members = case["set"]
                if rank not in members:
                    results[i] = "skip"
                    continue
                dt = getattr(DataType, _DTS[case["dt"]][0])
                src = _case_src(case, rank, size)
                req = team.collective_init(CollArgs(
                    coll_type=CollType.BCAST, root=case["root"],
                    src=BufferInfo(src, src.size, dt),
                    active_set=ActiveSet(
                        start=members[0],
                        stride=max(1, members[1] - members[0]),
                        size=len(members))))
                req.post()
                req.wait(timeout=90)
                results[i] = src.tolist()
                continue
            dt = getattr(DataType, _DTS[case["dt"]][0])
            nd = np.dtype(_DTS[case["dt"]][1])
            src = _case_src(case, rank, size)
            kw = {"coll_type": COLLS[coll]}
            if "op" in case:
                kw["op"] = OPS[case["op"]]
            if "root" in case:
                kw["root"] = case["root"]
            out = None
            if coll == "allreduce" and case.get("inplace"):
                out = src.copy()
                kw["dst"] = BufferInfo(out, out.size, dt)
                kw["flags"] = CollArgsFlags.IN_PLACE
            elif coll in ("allreduce",):
                out = np.zeros(case["count"], nd)
                kw["src"] = BufferInfo(src, src.size, dt)
                kw["dst"] = BufferInfo(out, out.size, dt)
            elif coll == "alltoallv":
                m = _a2av_matrix(size)
                scounts = m[rank]
                rcounts = [m[p][rank] for p in range(size)]
                out = np.zeros(sum(rcounts), nd)
                kw["src"] = BufferInfoV(src, scounts, None, dt)
                kw["dst"] = BufferInfoV(out, rcounts, None, dt)
            elif coll == "gatherv":
                counts = case["counts"]
                kw["src"] = BufferInfo(src, src.size, dt)
                if rank == case["root"]:
                    out = np.zeros(sum(counts), nd)
                    kw["dst"] = BufferInfoV(out, counts, None, dt)
                else:
                    kw["dst"] = BufferInfoV(None, counts, None, dt)
            elif coll == "scatterv":
                counts = case["counts"]
                out = np.zeros(counts[rank], nd)
                if rank == case["root"]:
                    kw["src"] = BufferInfoV(src, counts, None, dt)
                kw["dst"] = BufferInfo(out, out.size, dt)
            elif coll == "reduce_scatterv":
                counts = case["counts"]
                out = np.zeros(counts[rank], nd)
                kw["src"] = BufferInfo(src, src.size, dt)
                kw["dst"] = BufferInfoV(out, counts, None, dt)
            elif coll == "bcast":
                kw["src"] = BufferInfo(src, src.size, dt)
                out = src
            elif coll == "reduce":
                kw["src"] = BufferInfo(src, src.size, dt)
                if rank == case["root"]:
                    out = np.zeros(case["count"], nd)
                    kw["dst"] = BufferInfo(out, out.size, dt)
            elif coll == "allgather":
                out = np.zeros(case["count"] * size, nd)
                kw["src"] = BufferInfo(src, src.size, dt)
                kw["dst"] = BufferInfo(out, out.size, dt)
            elif coll == "allgatherv":
                counts = case["counts"]
                out = np.zeros(sum(counts), nd)
                kw["src"] = BufferInfo(src, src.size, dt)
                kw["dst"] = BufferInfoV(out, counts, None, dt)
            elif coll == "alltoall":
                out = np.zeros(case["count"], nd)
                kw["src"] = BufferInfo(src, src.size, dt)
                kw["dst"] = BufferInfo(out, out.size, dt)
            elif coll == "reduce_scatter":
                out = np.zeros(case["count"] // size, nd)
                kw["src"] = BufferInfo(src, src.size, dt)
                kw["dst"] = BufferInfo(out, out.size, dt)
            elif coll == "gather":
                kw["src"] = BufferInfo(src, src.size, dt)
                if rank == case["root"]:
                    out = np.zeros(case["count"] * size, nd)
                    kw["dst"] = BufferInfo(out, out.size, dt)
            elif coll == "scatter":
                out = np.zeros(case["count"] // size, nd)
                if rank == case["root"]:
                    kw["src"] = BufferInfo(src, src.size, dt)
                kw["dst"] = BufferInfo(out, out.size, dt)
            req = team.collective_init(CollArgs(**kw))
            req.post()
            req.wait(timeout=90)
            results[i] = out.tolist() if out is not None else "ok"
        q.put((rank, results))
        ctx.destroy()
        if rank == 0:
            oob.close()
    except Exception as e:  # noqa: BLE001
        import traceback
        q.put((rank, {"error": f"{e}\n{traceback.format_exc()}"}))


def _sweep_expect(case, size, rank):
    if case["coll"] in ("barrier", "fanin", "fanout"):
        return "ok"
    nd = np.dtype(_DTS[case["dt"]][1])
    srcs = [_case_src(case, r, size) for r in range(size)]
    coll = case["coll"]
    if coll == "allreduce":
        if case["op"] == "sum":
            return np.sum(srcs, axis=0).astype(nd).tolist()
        if case["op"] == "avg":
            return (np.sum(srcs, axis=0) / size).astype(nd).tolist()
        if case["op"] == "min":
            return np.min(srcs, axis=0).astype(nd).tolist()
        if case["op"] == "prod":
            return np.prod(np.stack(srcs), axis=0).astype(nd).tolist()
        return np.max(srcs, axis=0).astype(nd).tolist()
    if coll == "bcast":
        return srcs[case["root"]].tolist()
    if coll == "reduce":
        if rank != case["root"]:
            return None
        red = np.max(srcs, axis=0) if case["op"] == "max" else \
            np.sum(srcs, axis=0)
        return red.astype(nd).tolist()
    if coll == "allgather":
        return np.concatenate(srcs).tolist()
    if coll == "allgatherv":
        return np.concatenate(srcs).tolist()
    if coll == "alltoall":
        blk = case["count"] // size
        return np.concatenate(
            [srcs[p][rank * blk:(rank + 1) * blk] for p in range(size)]
        ).tolist()
    if coll == "reduce_scatter":
        blk = case["count"] // size
        full = np.sum(srcs, axis=0).astype(nd)
        return full[rank * blk:(rank + 1) * blk].tolist()
    if coll == "gather":
        return np.concatenate(srcs).tolist() if rank == case["root"] \
            else None
    if coll == "scatter":
        blk = case["count"] // size
        return srcs[case["root"]][rank * blk:(rank + 1) * blk].tolist()
    if coll == "alltoallv":
        m = _a2av_matrix(size)
        parts = []
        for p in range(size):
            displ = sum(m[p][:rank])
            parts.append(srcs[p][displ:displ + m[p][rank]])
        return np.concatenate(parts).tolist()
    if coll == "gatherv":
        return np.concatenate(srcs).tolist() if rank == case["root"] \
            else None
    if coll == "scatterv":
        counts = case["counts"]
        displ = sum(counts[:rank])
        return srcs[case["root"]][displ:displ + counts[rank]].tolist()
    if coll == "reduce_scatterv":
        counts = case["counts"]
        displ = sum(counts[:rank])
        full = np.sum(srcs, axis=0).astype(nd)
        return full[displ:displ + counts[rank]].tolist()
    if coll == "persistent_allreduce":
        return np.sum(srcs, axis=0).astype(nd).tolist()
    if coll == "active_set_bcast":
        if rank not in case["set"]:
            return "skip"
        return srcs[case["root"]].tolist()
    if coll in ("fanin", "fanout"):
        return "ok"
    raise AssertionError(coll)


@pytest.mark.parametrize("size", [2, 4])
def test_socket_tl_sweep(size):
    """27 cases x {2,4}-process teams over real TCP: coll x dtype x size
    x mode (v-colls, inplace, persistent, active-set, fanin/fanout)
    matrix in the reference test/mpi style (main.cc:19-66)."""
    port = _free_port_pair()
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=_sweep_worker, args=(r, size, port, q))
             for r in range(size)]
    for p in procs:
        p.start()
    results = {}
    for _ in range(size):
        rank, res = q.get(timeout=240)
        results[rank] = res
    for p in procs:
        p.join(timeout=30)
        if p.is_alive():
            p.terminate()
    for r in range(size):
        assert "error" not in results[r], results[r].get("error")
    for i, case in enumerate(_sweep_cases(size)):
        for r in range(size):
            expect = _sweep_expect(case, size, r)
            if expect is None:
                continue
            got = results[r][i]
            if case.get("dt", "").startswith("f"):
                np.testing.assert_allclose(got, expect, rtol=1e-6), (i, r)
            else:
                assert got == expect, (i, case, r)


def _death_worker(rank, size, port, outdir):
    import traceback
    res_path = os.path.join(outdir, f"r{rank}.txt")
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["UCC_TLS"] = "socket,self"
        import ucc_tpu
        from ucc_tpu import (BufferInfo, CollArgs, CollArgsFlags, CollType,
                             ContextParams, DataType, ReductionOp,
                             TcpStoreOob, TeamParams)
        oob = TcpStoreOob(rank, size, port=port)
        lib = ucc_tpu.init()
        ctx = ucc_tpu.Context(lib, ContextParams(oob=oob))
        team = ctx.create_team(TeamParams(
            oob=TcpStoreOob(rank, size, port=port + 1)))
        if rank == 1:
            with open(res_path, "w") as f:
                f.write("died")
            os._exit(1)     # abrupt death: no finalize, sockets reset
        src = np.full(16, 1.0, np.float32)
        dst = np.zeros(16, np.float32)
        req = team.collective_init(CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(src, 16, DataType.FLOAT32),
            dst=BufferInfo(dst, 16, DataType.FLOAT32),
            op=ReductionOp.SUM,
            flags=CollArgsFlags.TIMEOUT, timeout=3.0))
        req.post()
        try:
            st = req.wait(timeout=30)
            out = st.name
        except Exception as e:  # noqa: BLE001 - wait's own deadline
            out = f"WAIT_RAISED:{e}"
        with open(res_path, "w") as f:
            f.write(out)
    except Exception:  # noqa: BLE001
        with open(res_path, "w") as f:
            f.write("error:" + traceback.format_exc())


def _onesided_worker(rank: int, size: int, port: int, q):
    """One-sided collectives over real TCP frames: PUT/GET/flush applied
    by the passive peer's reader thread (the emulated-RDMA DCN path,
    tl/host/onesided.py; reference: test/mpi -o onesided sweeps)."""
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["UCC_TLS"] = "socket,self"
        os.environ["UCC_TL_SOCKET_TUNE"] = \
            "alltoall:@onesided#allreduce:@sliding_window"
        # tiny window: force multi-window gets/puts across the wire
        os.environ["UCC_TL_SOCKET_ALLREDUCE_SW_WINDOW"] = "64"
        import ucc_tpu
        from ucc_tpu import (BufferInfo, CollArgs, CollArgsFlags, CollType,
                             ContextParams, DataType, ReductionOp,
                             TcpStoreOob, TeamParams)

        oob = TcpStoreOob(rank, size, port=port)
        lib = ucc_tpu.init()
        ctx = ucc_tpu.Context(lib, ContextParams(oob=oob))
        team = ctx.create_team(TeamParams(
            oob=TcpStoreOob(rank, size, port=port + 1)))
        results = {}

        def exchange_handle(handle: bytes) -> list:
            """Allgather the (variable-size) handle via a fixed-size
            padded UINT8 allgather — the public-API way a runtime
            distributes rkeys."""
            pad = 1024
            assert len(handle) <= pad - 8
            blob = np.zeros(pad, np.uint8)
            blob[:8] = np.frombuffer(
                np.int64(len(handle)).tobytes(), np.uint8)
            blob[8:8 + len(handle)] = np.frombuffer(handle, np.uint8)
            out = np.zeros(pad * size, np.uint8)
            req = team.collective_init(CollArgs(
                coll_type=CollType.ALLGATHER,
                src=BufferInfo(blob, pad, DataType.UINT8),
                dst=BufferInfo(out, pad * size, DataType.UINT8)))
            req.post()
            req.wait(timeout=60)
            hs = []
            for p in range(size):
                seg = out[p * pad:(p + 1) * pad]
                ln = int(np.frombuffer(seg[:8].tobytes(), np.int64)[0])
                hs.append(seg[8:8 + ln].tobytes())
            return hs

        # --- onesided alltoall (put variant over TCP) ---
        per = 4
        total = per * size
        src = np.arange(total, dtype=np.int32) + 1000 * rank
        dst = np.zeros(total, np.int32)
        handles = exchange_handle(ctx.mem_map(dst))
        req = team.collective_init(CollArgs(
            coll_type=CollType.ALLTOALL,
            src=BufferInfo(src, total, DataType.INT32),
            dst=BufferInfo(dst, total, DataType.INT32),
            dst_memh=handles,
            flags=CollArgsFlags.MEM_MAP_DST_MEMH))
        req.post()
        req.wait(timeout=90)
        results["a2a"] = dst.tolist()

        # --- sliding-window allreduce (windowed gets + puts over TCP) ---
        count = 257        # odd: uneven partitions + window remainders
        asrc = (np.arange(count, dtype=np.float32) + rank) * 0.5
        adst = np.zeros(count, np.float32)
        sh = exchange_handle(ctx.mem_map(asrc))
        dh = exchange_handle(ctx.mem_map(adst))
        req = team.collective_init(CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(asrc, count, DataType.FLOAT32),
            dst=BufferInfo(adst, count, DataType.FLOAT32),
            op=ReductionOp.SUM, src_memh=sh, dst_memh=dh,
            flags=(CollArgsFlags.MEM_MAP_SRC_MEMH
                   | CollArgsFlags.MEM_MAP_DST_MEMH)))
        req.post()
        req.wait(timeout=90)
        results["sw_allreduce"] = adst.tolist()

        # --- bootstrap mode: NO memh args — the task mem_maps its own
        # buffers and runs the inline handle exchange over real TCP ---
        bsrc = np.arange(101, dtype=np.float64) * (rank + 1)
        bdst = np.zeros(101, np.float64)
        req = team.collective_init(CollArgs(
            coll_type=CollType.ALLREDUCE,
            src=BufferInfo(bsrc, 101, DataType.FLOAT64),
            dst=BufferInfo(bdst, 101, DataType.FLOAT64),
            op=ReductionOp.SUM))
        req.post()
        req.wait(timeout=90)
        results["sw_bootstrap"] = bdst.tolist()

        q.put((rank, results))
        ctx.destroy()
        if rank == 0:
            oob.close()
    except Exception as e:  # noqa: BLE001
        import traceback
        q.put((rank, {"error": f"{e}\n{traceback.format_exc()}"}))


def test_socket_onesided_three_processes():
    # 4 processes = 3 remote peers per rank: sliding-window gets complete
    # out of order across peers, exercising the bounded-slot free-list
    size = 4
    port = _free_port_pair()
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=_onesided_worker, args=(r, size, port, q))
             for r in range(size)]
    for p in procs:
        p.start()
    results = {}
    for _ in range(size):
        rank, res = q.get(timeout=180)
        results[rank] = res
    for p in procs:
        p.join(timeout=30)
        if p.is_alive():
            p.terminate()
    for r in range(size):
        assert "error" not in results[r], results[r].get("error")
    per = 4
    for r in range(size):
        expect = []
        for p in range(size):
            base = 1000 * p
            expect += [base + r * per + i for i in range(per)]
        assert results[r]["a2a"] == expect
    count = 257
    expect_ar = np.sum([(np.arange(count, dtype=np.float32) + p) * 0.5
                        for p in range(size)], axis=0)
    for r in range(size):
        np.testing.assert_allclose(results[r]["sw_allreduce"], expect_ar,
                                   rtol=1e-6)
    expect_bs = np.arange(101, dtype=np.float64) * sum(
        range(1, size + 1))
    for r in range(size):
        np.testing.assert_allclose(results[r]["sw_bootstrap"], expect_bs,
                                   rtol=1e-12)


def test_peer_death_surfaces_as_error(tmp_path):
    """Failure detection over DCN: a peer process dying mid-collective
    must surface as ERR_TIMED_OUT (per-coll timeout backstop) or a
    transport error on the survivor — never a hang."""
    size = 2
    port = _free_port_pair()
    ctx = mp.get_context("spawn")
    procs = [ctx.Process(target=_death_worker,
                         args=(r, size, port, str(tmp_path)))
             for r in range(size)]
    for p in procs:
        p.start()
    import time as _time
    deadline = _time.monotonic() + 120
    while any(p.is_alive() for p in procs):
        if _time.monotonic() > deadline:
            for p in procs:
                p.terminate()
            raise AssertionError("peer-death test hung")
        _time.sleep(0.2)
    r1 = (tmp_path / "r1.txt").read_text()
    assert r1 == "died"
    r0 = (tmp_path / "r0.txt").read_text()
    assert r0 in ("ERR_TIMED_OUT", "ERR_NO_MESSAGE",
                  "ERR_NO_RESOURCE"), r0


class TestReaderDesyncHardening:
    """A corrupt frame stream must drop THAT connection with one ERROR
    line — never kill the reader thread (stranding future frames) or
    allocate from a garbage header."""

    @staticmethod
    def _transport():
        from ucc_tpu.tl.sockets import SocketTransport
        return SocketTransport(bind_host="127.0.0.1")

    @staticmethod
    def _capture():
        """The ucc_tpu root logger does not propagate (utils/log.py), so
        caplog never sees it — attach a list handler directly."""
        import logging

        class _ListHandler(logging.Handler):
            def __init__(self):
                super().__init__(level=logging.ERROR)
                self.lines = []

            def emit(self, record):
                self.lines.append(record.getMessage())

        h = _ListHandler()
        logging.getLogger("ucc_tpu").addHandler(h)
        return h

    @staticmethod
    def _uncapture(h):
        import logging
        logging.getLogger("ucc_tpu").removeHandler(h)

    def _send_raw(self, tr, blob: bytes):
        import socket as pysock
        c = pysock.create_connection((tr.host, tr.port), timeout=10)
        c.sendall(blob)
        return c

    def test_implausible_header_drops_connection(self):
        import struct
        import time
        h = self._capture()
        tr = self._transport()
        try:
            # header claiming a 2.4 GB key: must be rejected BEFORE any
            # recv/allocation of that size
            bad = struct.pack("!IQIQ", 0x912CE0A1, 7, 0, 0) + b"x" * 32
            c = self._send_raw(tr, bad)
            t0 = time.monotonic()
            while time.monotonic() - t0 < 10:
                if any("desync" in ln for ln in h.lines):
                    break
                time.sleep(0.05)
            assert any("desync" in ln for ln in h.lines), "no desync log"
            # the connection is dropped: our end sees EOF or a reset
            c.settimeout(5)
            try:
                assert c.recv(1) == b""
            except ConnectionError:
                pass
            c.close()
        finally:
            tr.close()
            self._uncapture(h)

    def test_garbage_key_drops_connection_not_thread(self):
        import struct
        import time
        h = self._capture()
        tr = self._transport()
        try:
            import zlib
            # a well-formed header with a CORRECT key crc over bytes that
            # are not a pickle: the frame survives the crc gate and blows
            # up inside unpickling — the deepest point of the blast radius
            kb = b"\x00garbage-not-pickle"
            bad = struct.pack("!IQIQ", len(kb), 4,
                              zlib.crc32(kb) & 0xFFFFFFFF, 0) + kb + b"DATA"
            c = self._send_raw(tr, bad)
            t0 = time.monotonic()
            while time.monotonic() - t0 < 10:
                if any("desync" in ln for ln in h.lines):
                    break
                time.sleep(0.05)
            assert any("desync" in ln for ln in h.lines)
            c.settimeout(5)
            try:
                assert c.recv(1) == b""
            except ConnectionError:
                pass
            c.close()
            # a GOOD frame on a NEW connection still gets delivered:
            # the transport survived the poison
            key = ("team", 1, 0, 0)
            kb2 = pickle.dumps(key)
            payload = b"\x01\x02\x03\x04"
            good = struct.pack("!IQIQ", len(kb2), len(payload),
                               zlib.crc32(kb2) & 0xFFFFFFFF, 0) + kb2 + payload
            c2 = self._send_raw(tr, good)
            dst = np.zeros(4, np.uint8)
            from ucc_tpu.tl.host.transport import RecvReq
            req = RecvReq(dst)
            tr.mailbox.post_recv(key, req)
            t0 = time.monotonic()
            while not req.test():
                assert time.monotonic() - t0 < 10, "good frame not delivered"
                time.sleep(0.02)
            np.testing.assert_array_equal(dst, [1, 2, 3, 4])
            c2.close()
        finally:
            tr.close()
            self._uncapture(h)


class TestPreconnect:
    """UCC_TL_SOCKET_PRECONNECT (tl_ucp PRECONNECT role): teams at or
    under the threshold establish every TCP connection during team
    create via a zero-byte tagged exchange, so the first collective
    pays no connect latency."""

    def _job(self, monkeypatch, preconnect):
        from harness import UccJob
        monkeypatch.setenv("UCC_TLS", "socket,self")
        monkeypatch.setenv("UCC_TL_SOCKET_PRECONNECT", str(preconnect))
        return UccJob(3)

    def test_connections_up_at_team_create(self, monkeypatch):
        job = self._job(monkeypatch, 16)
        try:
            teams = job.create_team()
            # every context has outbound conns to both peers BEFORE any
            # collective was posted
            for ctx in job.contexts:
                tr = ctx.tl_contexts["socket"].obj.transport
                assert len(tr._conns) >= 2, tr._conns.keys()
            # and collectives still work on the preconnected team
            srcs = [np.full(16, r + 1.0, np.float32) for r in range(3)]
            dsts = [np.zeros(16, np.float32) for _ in range(3)]
            from ucc_tpu import (BufferInfo, CollArgs, CollType, DataType,
                                 ReductionOp)
            job.run_coll(teams, lambda r: CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(srcs[r], 16, DataType.FLOAT32),
                dst=BufferInfo(dsts[r], 16, DataType.FLOAT32),
                op=ReductionOp.SUM))
            for d in dsts:
                np.testing.assert_allclose(d, np.full(16, 6.0))
        finally:
            job.cleanup()

    def test_disabled_means_lazy(self, monkeypatch):
        """Default (0): the preconnect machinery never engages — note
        service collectives at team create may still open connections,
        so the observable is the team flag, not the conn count."""
        job = self._job(monkeypatch, 0)
        try:
            teams = job.create_team()
            for t in teams:
                for cl in t.cl_teams:
                    for tlt in getattr(cl, "tl_teams", []):
                        if tlt.NAME == "socket":
                            assert not tlt._want_preconnect
                            assert tlt._preconnect_reqs is None
        finally:
            job.cleanup()
