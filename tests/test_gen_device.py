"""Device-side compiler backend (ucc_tpu/dsl/lower_device, ISSUE 15):
verified DSL programs lowered to generated device collectives on the
xla TL — the in-jit XLA layer schedule on the virtual CPU mesh and the
Pallas remote-DMA kernels in interpret mode, cross-rank correctness vs
numpy for every registered variant (inplace, AVG, bf16, quantized
edges, every bcast root), registration/provenance, fallback behavior,
the launch-cache bound fix, and the device flight-recorder events."""
import os

import numpy as np
import pytest

import ucc_tpu
from ucc_tpu import (BufferInfo, CollArgs, CollArgsFlags, CollType,
                     DataType, MemoryType, ReductionOp, Status)

from harness import UccJob

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

N = 4


@pytest.fixture(scope="module")
def job():
    if len(jax.devices()) < 8:
        pytest.skip("needs >= 8 virtual devices")
    os.environ["UCC_GEN_DEVICE"] = "y"
    os.environ["UCC_QUANT"] = "int8"
    j = UccJob(N)
    yield j
    j.cleanup()
    os.environ.pop("UCC_GEN_DEVICE", None)
    os.environ.pop("UCC_QUANT", None)


@pytest.fixture(scope="module")
def teams(job):
    return job.create_team()


@pytest.fixture(scope="module")
def pallas_job():
    if len(jax.devices()) < N:
        pytest.skip("needs >= 4 virtual devices")
    os.environ["UCC_GEN_DEVICE"] = "y"
    os.environ["UCC_GEN_DEVICE_BACKEND"] = "pallas"
    os.environ["UCC_QUANT"] = "int8"
    j = UccJob(N)
    teams = j.create_team()
    yield j, teams
    j.cleanup()
    for k in ("UCC_GEN_DEVICE", "UCC_GEN_DEVICE_BACKEND", "UCC_QUANT"):
        os.environ.pop(k, None)


def dev_buf(job, rank, np_arr, dt):
    dev = job.contexts[rank].tl_contexts["xla"].obj.device
    arr = jax.device_put(jnp.asarray(np_arr), dev)
    return BufferInfo(arr, int(np.prod(np_arr.shape)), dt,
                      mem_type=MemoryType.TPU)


def run_forced(job, teams, alg, make_args, timeout=60.0):
    """Init pinned to candidate *alg* by name on every rank, run to
    completion, return the per-rank requests."""
    from ucc_tpu.api.types import coll_args_msgsize
    from ucc_tpu.core.coll import CollRequest, InitArgs

    n = len(teams)
    argses = [make_args(r) for r in range(n)]
    msgsize = coll_args_msgsize(argses[0], n, 0)
    coll = argses[0].coll_type
    reqs = []
    for r in range(n):
        cands = teams[r].score_map.lookup(coll, MemoryType.TPU, msgsize)
        cand = next(c for c in cands if c.alg_name == alg)
        ia = InitArgs(args=argses[r], team=teams[r],
                      mem_type=MemoryType.TPU, msgsize=msgsize)
        task = cand.init(ia, cand.team)
        task.alg_name = alg
        reqs.append(CollRequest(task, teams[r], argses[r]))
    for rq in reqs:
        rq.post()
    job.progress_until(lambda: all(
        rq.test() != Status.IN_PROGRESS for rq in reqs), timeout=timeout)
    for rq in reqs:
        assert rq.test() == Status.OK, (alg, rq.test())
    return reqs, argses


def registered_dev_algs(teams, coll, msgsize=1 << 12):
    return sorted({c.alg_name
                   for c in teams[0].score_map.lookup(
                       coll, MemoryType.TPU, msgsize)
                   if c.origin == "generated-device"})


# ---------------------------------------------------------------------------
# lowering plan units
# ---------------------------------------------------------------------------

class TestLoweringPlan:
    def test_ring_detected(self):
        from ucc_tpu.dsl import families as fam
        from ucc_tpu.dsl.lower_device import plan_rounds, ring_schedule
        p = fam.gen_ring(4, chunks=2)
        plans = plan_rounds(p, 4)
        sched = ring_schedule(plans, 4)
        assert sched is not None and len(sched) == 2 * 3
        assert all(length == 2 for length, _ in sched)

    def test_direct_exchange_not_ring(self):
        from ucc_tpu.dsl import families as fam
        from ucc_tpu.dsl.lower_device import plan_rounds, ring_schedule
        p = fam.gen_rhd(4, radix=4)
        plans = plan_rounds(p, 4)
        assert ring_schedule(plans, 4) is None
        # direct exchange reduce round: every rank receives its chunk
        # from all 3 peers, scheduled over >= 3 layers with the
        # receiver's op-stream order preserved
        assert len(plans[0].layers) >= 3

    def test_receiver_order_preserved(self):
        """Layer order must replay each receiver's op-stream order —
        the accumulate-order contract that makes device results
        bitwise-identical to the host interpreter."""
        from ucc_tpu.dsl import families as fam
        from ucc_tpu.dsl.lower_device import plan_rounds
        from ucc_tpu.dsl.ir import OpKind
        n = 8
        p = fam.gen_rhd(n, radix=n)
        plans = plan_rounds(p, n)
        for k, plan in enumerate(plans):
            seen = {q: [] for q in range(n)}
            for lay in plan.layers:
                for run in lay.runs:
                    seen[run.q].append(run.p)
            for q in range(n):
                stream = [(op.peer, op.chunk)
                          for op in p.ranks[q].rounds[k]
                          if op.kind in (OpKind.RECV, OpKind.REDUCE)]
                assert seen[q] == [pr for pr, _ in stream]

    def test_cross_round_match_refused(self):
        from ucc_tpu.dsl import families as fam
        from ucc_tpu.dsl.ir import ProgramBuilder
        from ucc_tpu.dsl.lower_device import plan_rounds
        b = ProgramBuilder("x", CollType.ALLREDUCE, 2, 1)
        b.next_round()
        b.send(0, 0, to=1, slot=99)
        b.next_round()
        b.reduce(1, 0, frm=0, slot=99)   # cross-round rendezvous
        prog = b.build("x")
        with pytest.raises(fam.Inapplicable):
            plan_rounds(prog, 2)

    def test_device_program_sweep(self):
        from ucc_tpu.dsl.lower_device import device_programs
        progs = device_programs(4, quant_mode="int8")
        names = {p.name for p in progs}
        assert {"gen_ring_c1", "gen_ring_c2", "gen_rhd_r2",
                "gen_bc_kn_r2", "gen_bc_chain_c2",
                "gen_qint8_direct"} <= names

    def test_bad_families_knob_rejected(self):
        from ucc_tpu.dsl.lower_device import parse_device_families
        with pytest.raises(ValueError):
            parse_device_families("ag_ring(1)")   # not device-lowerable
        with pytest.raises(ValueError):
            parse_device_families("nosuch(2)")


# ---------------------------------------------------------------------------
# registration & provenance
# ---------------------------------------------------------------------------

class TestRegistration:
    def test_candidates_registered(self, teams):
        algs = registered_dev_algs(teams, CollType.ALLREDUCE)
        assert "gen_dev_ring_c1" in algs
        assert "gen_dev_rhd_r2" in algs
        assert "gen_dev_qint8_direct" in algs
        assert "gen_dev_bc_kn_r2" in registered_dev_algs(
            teams, CollType.BCAST)

    def test_provenance_in_print_info(self, teams):
        info = teams[0].score_map.print_info("t")
        assert "generated-device gen:ring(chunks=1)" in info
        assert "gen_dev_ring_c1" in info
        # the quantized variant carries its precision tag
        assert "generated-device,int8" in info

    def test_off_means_absent(self):
        j = UccJob(2, lib_overrides={"GEN_DEVICE": "n"})
        try:
            tms = j.create_team()
            cands = tms[0].score_map.lookup(CollType.ALLREDUCE,
                                            MemoryType.TPU, 1 << 12)
            assert not any(c.origin == "generated-device"
                           for c in cands)
            assert not any((c.alg_name or "").startswith("gen_dev_")
                           for c in cands)
        finally:
            j.cleanup()

    def test_never_static_default(self, teams):
        cands = teams[0].score_map.lookup(CollType.ALLREDUCE,
                                          MemoryType.TPU, 1 << 12)
        assert not (cands[0].alg_name or "").startswith("gen_dev_")


# ---------------------------------------------------------------------------
# correctness: every registered variant vs numpy (XLA backend)
# ---------------------------------------------------------------------------

COUNT = 96          # divisible by every registered nchunks at n=2/4/8
RNG = np.random.default_rng(11)


def _allreduce_case(job, teams, alg, op=ReductionOp.SUM,
                    dt=DataType.FLOAT32, nd=np.float32, inplace=False,
                    count=COUNT):
    n = len(teams)
    srcs = [(RNG.standard_normal(count) * 3).astype(nd)
            for _ in range(n)]

    def mk(r):
        if inplace:
            buf = dev_buf(job, r, srcs[r], dt)
            return CollArgs(coll_type=CollType.ALLREDUCE, src=buf,
                            dst=buf, op=op, flags=CollArgsFlags.IN_PLACE)
        return CollArgs(coll_type=CollType.ALLREDUCE,
                        src=dev_buf(job, r, srcs[r], dt),
                        dst=BufferInfo(None, count, dt,
                                       mem_type=MemoryType.TPU), op=op)
    reqs, argses = run_forced(job, teams, alg, mk)
    outs = [np.asarray(a.dst.buffer) for a in argses]
    stack = np.stack([s.astype(np.float32) for s in srcs])
    ref = {ReductionOp.SUM: stack.sum(0),
           ReductionOp.AVG: stack.sum(0) / n,
           ReductionOp.MAX: stack.max(0),
           ReductionOp.MIN: stack.min(0),
           ReductionOp.PROD: stack.prod(0)}[op]
    for rq in reqs:
        rq.finalize()
    return outs, ref


class TestAllreduceXla:
    @pytest.mark.parametrize("alg", [
        "gen_dev_ring_c1", "gen_dev_ring_c2", "gen_dev_ring_c4",
        "gen_dev_rhd_r2", "gen_dev_rhd_r4"])
    def test_sum_f32(self, job, teams, alg):
        algs = registered_dev_algs(teams, CollType.ALLREDUCE)
        if alg not in algs:
            pytest.skip(f"{alg} not registered at n={N}")
        outs, ref = _allreduce_case(job, teams, alg)
        for out in outs:
            np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
        # cross-rank bitwise agreement (every rank ran the same
        # generated schedule)
        for out in outs[1:]:
            assert (out.view(np.int32) == outs[0].view(np.int32)).all()

    @pytest.mark.parametrize("op", [ReductionOp.AVG, ReductionOp.MAX,
                                    ReductionOp.PROD])
    def test_ops(self, job, teams, op):
        outs, ref = _allreduce_case(job, teams, "gen_dev_ring_c1",
                                    op=op)
        for out in outs:
            np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_inplace(self, job, teams):
        outs, ref = _allreduce_case(job, teams, "gen_dev_rhd_r2",
                                    inplace=True)
        for out in outs:
            np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_bf16(self, job, teams):
        ml_dtypes = pytest.importorskip("ml_dtypes")
        outs, ref = _allreduce_case(job, teams, "gen_dev_ring_c2",
                                    dt=DataType.BFLOAT16,
                                    nd=ml_dtypes.bfloat16)
        for out in outs:
            np.testing.assert_allclose(out.astype(np.float32), ref,
                                       rtol=0.05, atol=0.2)

    def test_quantized_budget_and_agreement(self, job, teams):
        count = 256
        srcs = [(RNG.standard_normal(count) * 2).astype(np.float32)
                for _ in range(N)]

        def mk(r):
            return CollArgs(coll_type=CollType.ALLREDUCE,
                            src=dev_buf(job, r, srcs[r],
                                        DataType.FLOAT32),
                            dst=BufferInfo(None, count, DataType.FLOAT32,
                                           mem_type=MemoryType.TPU),
                            op=ReductionOp.SUM)
        reqs, argses = run_forced(job, teams, "gen_dev_qint8_direct", mk)
        outs = [np.asarray(a.dst.buffer) for a in argses]
        for rq in reqs:
            rq.finalize()
        exact = np.stack(srcs).sum(0)
        scale = np.abs(exact).max() or 1.0
        assert np.abs(outs[0] - exact).max() / scale < 0.1
        # sender-side re-decode keeps every rank bitwise identical
        for out in outs[1:]:
            assert (out.view(np.int32) == outs[0].view(np.int32)).all()


class TestBcastXla:
    @pytest.mark.parametrize("alg", ["gen_dev_bc_kn_r2",
                                     "gen_dev_bc_linear",
                                     "gen_dev_bc_chain_c2"])
    @pytest.mark.parametrize("root", list(range(N)))
    def test_all_roots(self, job, teams, alg, root):
        data = (np.arange(COUNT) * 1.5 + 7).astype(np.float32)

        def mk(r):
            src = data if r == root else np.zeros(COUNT, np.float32)
            return CollArgs(coll_type=CollType.BCAST, root=root,
                            src=dev_buf(job, r, src, DataType.FLOAT32))
        reqs, argses = run_forced(job, teams, alg, mk)
        for r in range(N):
            np.testing.assert_array_equal(
                np.asarray(argses[r].src.buffer), data)
        for rq in reqs:
            rq.finalize()


# ---------------------------------------------------------------------------
# pallas interpret backend (same variants, remote-DMA kernels)
# ---------------------------------------------------------------------------

class TestPallasInterpret:
    @pytest.mark.parametrize("alg", [
        "gen_dev_ring_c1",            # _make_step_dma ring fast path
        "gen_dev_rhd_r4",             # generic full-perm layer path
        "gen_dev_qint8_direct"])      # in-kernel quantize/dequantize
    def test_allreduce(self, pallas_job, alg):
        job, teams = pallas_job
        count = 64
        srcs = [(RNG.standard_normal(count) * 2).astype(np.float32)
                for _ in range(N)]

        def mk(r):
            return CollArgs(coll_type=CollType.ALLREDUCE,
                            src=dev_buf(job, r, srcs[r],
                                        DataType.FLOAT32),
                            dst=BufferInfo(None, count, DataType.FLOAT32,
                                           mem_type=MemoryType.TPU),
                            op=ReductionOp.SUM)
        reqs, argses = run_forced(job, teams, alg, mk, timeout=180)
        outs = [np.asarray(a.dst.buffer) for a in argses]
        for rq in reqs:
            rq.finalize()
        exact = np.stack(srcs).sum(0)
        scale = np.abs(exact).max() or 1.0
        tol = 0.1 if "qint8" in alg else 1e-5
        assert np.abs(outs[0] - exact).max() / scale < tol
        for out in outs[1:]:
            assert (out.view(np.int32) == outs[0].view(np.int32)).all()

    def test_bcast_nonzero_root(self, pallas_job):
        job, teams = pallas_job
        count = 64
        data = np.arange(count, dtype=np.float32) + 5

        def mk(r):
            src = data if r == 2 else np.zeros(count, np.float32)
            return CollArgs(coll_type=CollType.BCAST, root=2,
                            src=dev_buf(job, r, src, DataType.FLOAT32))
        reqs, argses = run_forced(job, teams, "gen_dev_bc_chain_c2",
                                  mk, timeout=180)
        for r in range(N):
            np.testing.assert_array_equal(
                np.asarray(argses[r].src.buffer), data)
        for rq in reqs:
            rq.finalize()

    def test_matches_xla_backend_bitwise(self, job, teams, pallas_job):
        """Both backends execute the identical layer plan: same inputs
        -> bitwise-identical outputs."""
        pj, pteams = pallas_job
        count = 64
        srcs = [(RNG.standard_normal(count) * 2).astype(np.float32)
                for _ in range(N)]

        def run(j, tms):
            def mk(r):
                return CollArgs(
                    coll_type=CollType.ALLREDUCE,
                    src=dev_buf(j, r, srcs[r], DataType.FLOAT32),
                    dst=BufferInfo(None, count, DataType.FLOAT32,
                                   mem_type=MemoryType.TPU),
                    op=ReductionOp.SUM)
            reqs, argses = run_forced(j, tms, "gen_dev_rhd_r2", mk,
                                      timeout=180)
            outs = [np.asarray(a.dst.buffer).copy() for a in argses]
            for rq in reqs:
                rq.finalize()
            return outs
        a = run(job, teams)
        b = run(pj, pteams)
        for x, y in zip(a, b):
            assert (x.view(np.int32) == y.view(np.int32)).all()


# ---------------------------------------------------------------------------
# 2- and 8-rank meshes
# ---------------------------------------------------------------------------

class TestOtherTeamSizes:
    @pytest.mark.parametrize("n", [2, 8])
    def test_matrix(self, n):
        if len(jax.devices()) < n:
            pytest.skip(f"needs >= {n} virtual devices")
        os.environ["UCC_GEN_DEVICE"] = "y"
        os.environ["UCC_QUANT"] = "int8"
        j = UccJob(n)
        try:
            tms = j.create_team()
            algs = registered_dev_algs(tms, CollType.ALLREDUCE)
            assert "gen_dev_ring_c1" in algs
            srcs = [(RNG.standard_normal(COUNT) * 2).astype(np.float32)
                    for _ in range(n)]
            ref = np.stack(srcs).sum(0)
            for alg in algs:
                def mk(r):
                    return CollArgs(
                        coll_type=CollType.ALLREDUCE,
                        src=dev_buf(j, r, srcs[r], DataType.FLOAT32),
                        dst=BufferInfo(None, COUNT, DataType.FLOAT32,
                                       mem_type=MemoryType.TPU),
                        op=ReductionOp.SUM)
                reqs, argses = run_forced(j, tms, alg, mk)
                outs = [np.asarray(a.dst.buffer) for a in argses]
                for rq in reqs:
                    rq.finalize()
                tol = 0.1 * (np.abs(ref).max() or 1.0) \
                    if "qint8" in alg else 1e-4
                assert np.abs(outs[0] - ref).max() < tol, alg
                for out in outs[1:]:
                    assert (out.view(np.int32)
                            == outs[0].view(np.int32)).all(), alg
            for alg in registered_dev_algs(tms, CollType.BCAST):
                data = np.arange(COUNT, dtype=np.float32)
                root = n - 1

                def mkb(r):
                    src = data if r == root else np.zeros(COUNT,
                                                          np.float32)
                    return CollArgs(coll_type=CollType.BCAST, root=root,
                                    src=dev_buf(j, r, src,
                                                DataType.FLOAT32))
                reqs, argses = run_forced(j, tms, alg, mkb)
                for r in range(n):
                    np.testing.assert_array_equal(
                        np.asarray(argses[r].src.buffer), data, alg)
                for rq in reqs:
                    rq.finalize()
        finally:
            j.cleanup()
            os.environ.pop("UCC_GEN_DEVICE", None)
            os.environ.pop("UCC_QUANT", None)


# ---------------------------------------------------------------------------
# fallback behavior
# ---------------------------------------------------------------------------

class TestFallback:
    def test_nondivisible_count_falls_back(self, job, teams):
        """A TUNE-pinned generated-device candidate refusing a count
        (chunk divisibility) walks the fallback chain to the monolithic
        program instead of failing the collective."""
        count = 67                     # not divisible by 4 chunks
        srcs = [np.ones(count, np.float32) * (r + 1) for r in range(N)]
        argses = [CollArgs(coll_type=CollType.ALLREDUCE,
                           src=dev_buf(job, r, srcs[r],
                                       DataType.FLOAT32),
                           dst=BufferInfo(None, count, DataType.FLOAT32,
                                          mem_type=MemoryType.TPU),
                           op=ReductionOp.SUM) for r in range(N)]
        from ucc_tpu.api.types import coll_args_msgsize
        from ucc_tpu.core.coll import InitArgs
        msgsize = coll_args_msgsize(argses[0], N, 0)
        cands = teams[0].score_map.lookup(CollType.ALLREDUCE,
                                          MemoryType.TPU, msgsize)
        gen = [c for c in cands if c.alg_name == "gen_dev_rhd_r2"]
        assert gen
        ia = InitArgs(args=argses[0], team=teams[0],
                      mem_type=MemoryType.TPU, msgsize=msgsize)
        task, chosen = teams[0].score_map.init_coll(
            CollType.ALLREDUCE, MemoryType.TPU, msgsize, ia,
            gen + [c for c in cands if c.alg_name != "gen_dev_rhd_r2"])
        assert chosen.alg_name != "gen_dev_rhd_r2"

    def test_wrong_team_size_not_registered(self):
        """Programs are built per team size at registration; a 3-rank
        team registers 3-rank programs only (rhd pow-of-radix grid
        entries drop out, ring stays)."""
        if len(jax.devices()) < 3:
            pytest.skip("needs >= 3 devices")
        os.environ["UCC_GEN_DEVICE"] = "y"
        j = UccJob(3)
        try:
            tms = j.create_team()
            algs = registered_dev_algs(tms, CollType.ALLREDUCE)
            assert "gen_dev_ring_c1" in algs
            assert "gen_dev_rhd_r2" not in algs   # 3 != 2^k
        finally:
            j.cleanup()
            os.environ.pop("UCC_GEN_DEVICE", None)


# ---------------------------------------------------------------------------
# launch-cache bound (ISSUE 15 satellite)
# ---------------------------------------------------------------------------

class TestLaunchCacheBounds:
    def test_eviction_and_destroy_clear_unit(self):
        """The bound + clear semantics on a bare XlaTeamShared: oldest
        evicted at the cap, replace-in-place exempt, refcount-0 put()
        drops every cache."""
        from ucc_tpu.tl.xla import XlaTeamShared
        s = XlaTeamShared(object(), None, [], 1, cache_max=4)
        for i in range(8):
            s._cache_insert(s.launch_cache, i, f"v{i}")
            s._cache_insert(s.aot_programs, i, f"a{i}")
        assert list(s.launch_cache) == [4, 5, 6, 7]
        assert len(s.aot_programs) == 4
        # replacing a live key must not evict an unrelated entry
        s._cache_insert(s.launch_cache, 5, "v5b")
        assert list(s.launch_cache) == [4, 5, 6, 7]
        assert s.launch_cache[5] == "v5b"
        s.programs["p"] = "x"
        s.refcount = 1
        s.put()
        assert not s.launch_cache and not s.aot_programs \
            and not s.programs

    def test_bounded_and_cleared(self):
        os.environ["UCC_TL_XLA_LAUNCH_CACHE_MAX"] = "4"
        j = UccJob(2)
        try:
            tms = j.create_team()
            shared = next(t for t in tms[0].cl_teams[0].tl_teams
                          if t.name == "xla").shared
            # the shared object can be a REUSED one when an earlier
            # test leaked a team with the same (ranks, host, pid) key;
            # the bound below then checks against ITS cap
            fresh = shared.cache_max == 4
            reqs_all = []
            for i in range(8):
                count = 32 + 8 * i     # distinct shapes -> distinct
                argses = []            # programs + tags
                for r in range(2):
                    argses.append(CollArgs(
                        coll_type=CollType.ALLREDUCE,
                        src=dev_buf(j, r, np.ones(count, np.float32),
                                    DataType.FLOAT32),
                        dst=BufferInfo(None, count, DataType.FLOAT32,
                                       mem_type=MemoryType.TPU),
                        op=ReductionOp.SUM,
                        flags=CollArgsFlags.PERSISTENT))
                reqs = [tms[r].collective_init(argses[r])
                        for r in range(2)]
                for rq in reqs:
                    rq.post()
                j.progress_until(lambda: all(
                    rq.test() != Status.IN_PROGRESS for rq in reqs))
                assert all(rq.test() == Status.OK for rq in reqs)
                reqs_all.append(reqs)
            # per-team caches stay bounded at the (configured) cap
            assert len(shared.launch_cache) <= shared.cache_max
            assert len(shared.aot_programs) <= shared.cache_max
            if fresh:
                assert len(shared.launch_cache) <= 4
            for reqs in reqs_all:
                for rq in reqs:
                    rq.finalize()
            for t in tms:
                t.destroy()
            if shared.refcount <= 0:
                # team destroy cleared every cached executable + pinned
                # array (skipped when a leaked same-key team still
                # holds a reference)
                assert not shared.launch_cache
                assert not shared.aot_programs
                assert not shared.programs
            j.teams.clear()
        finally:
            j.cleanup()
            os.environ.pop("UCC_TL_XLA_LAUNCH_CACHE_MAX", None)


# ---------------------------------------------------------------------------
# flight-recorder device lifecycle events (ISSUE 15 satellite)
# ---------------------------------------------------------------------------

class TestDeviceFlightEvents:
    def test_dev_launch_and_ready_events(self):
        from ucc_tpu.obs import flight
        if not flight.ENABLED:
            pytest.skip("flight recorder disabled")
        j = UccJob(2)
        try:
            tms = j.create_team()
            count = 64
            argses = [CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=dev_buf(j, r, np.ones(count, np.float32),
                            DataType.FLOAT32),
                dst=BufferInfo(None, count, DataType.FLOAT32,
                               mem_type=MemoryType.TPU),
                op=ReductionOp.SUM) for r in range(2)]
            j.run_coll(tms, lambda r: argses[r])
            kinds = set()
            for rec in flight.recorders():
                for ev in rec.wire.events():
                    kinds.add(ev["kind"])
            assert "dev_launch" in kinds
            assert "dev_ready" in kinds
        finally:
            j.cleanup()


# ---------------------------------------------------------------------------
# device search / cost-model ICI class (ISSUE 15 satellite)
# ---------------------------------------------------------------------------

class TestDeviceSearch:
    def test_ici_link_class(self):
        from ucc_tpu.score import cost
        assert "ici" in cost.SEED_LINKS
        assert cost.link_of_device()(0, 1) == "ici"
        m = cost.CostModel()
        from ucc_tpu.dsl import families as fam
        ring = fam.gen_ring(4, chunks=1)
        direct = fam.gen_rhd(4, radix=4)
        # ICI pricing is latency-light: at tiny sizes the one-round
        # direct exchange must price below the 6-round ring
        small_r = m.predict_us(ring, 256, cost.link_of_device())
        small_d = m.predict_us(direct, 256, cost.link_of_device())
        assert small_d < small_r

    def test_propose_device_space(self):
        from ucc_tpu.dsl.search import propose, shortlist
        from ucc_tpu.score import cost
        cands = propose(CollType.ALLREDUCE, 4, quant_mode="int8",
                        target="device")
        names = {c.name for c in cands}
        assert "gen_ring_c1" in names
        assert "gen_rhd_r4" in names or "gen_rhd_r2" in names
        assert any(n.startswith("gen_qint8") for n in names)
        # nothing non-lowerable leaks in
        assert not any(c.family in ("sra", "sra_pipe", "hier")
                       for c in cands)
        sl = shortlist(cands, cost.CostModel(), 1 << 16, 4,
                       cost.link_of_device())
        assert len(sl) == 4
        assert all(c.predicted_us is not None for c in sl)
        # non-device colls refuse the device target
        assert propose(CollType.ALLGATHER, 4, target="device") == []


# ---------------------------------------------------------------------------
# real-chip gate (compiles the Pallas lowering on hardware; skips off-TPU)
# ---------------------------------------------------------------------------

class TestGenDeviceRealChip:
    """Compile (not just interpret) the lowered Pallas kernels when a
    real TPU is reachable — the standing hardware gate alongside
    TestRingDmaRealChip. A 1-chip mesh compiles the kernel scaffolding;
    multi-chip compiles the remote-DMA layer schedule itself."""

    @staticmethod
    def _tpus():
        tpus = [d for d in jax.devices()
                if d.platform not in ("cpu",)]
        if not tpus:
            pytest.skip("no TPU devices reachable")
        if len(tpus) < 2:
            pytest.skip("device lowering needs >= 2 chips")
        return tpus

    @pytest.mark.parametrize("family,param", [
        ("ring", 1), ("ring", 2), ("rhd", 0), ("bc_kn", 0),
        ("bc_chain", 2)])
    def test_compiles_on_tpu(self, family, param):
        tpus = self._tpus()
        from ucc_tpu.dsl.lower_device import build_device_program
        from ucc_tpu.dsl.registry import build_program
        n = len(tpus)
        prog = build_program(family, param, n)
        if prog is None:
            pytest.skip(f"{family}({param}) inapplicable at n={n}")
        mesh = jax.sharding.Mesh(np.array(tpus), ("r",))
        count = 128 * prog.nchunks
        op = ReductionOp.SUM
        program, padded = build_device_program(
            mesh, prog, n, count, op, np.dtype(np.float32), 0,
            "pallas", 256, "")
        assert padded == count
        from jax.sharding import NamedSharding, PartitionSpec as P
        shards = [jax.device_put(jnp.ones(count, jnp.float32), d)
                  for d in tpus]
        garr = jax.make_array_from_single_device_arrays(
            (n * count,), NamedSharding(mesh, P("r")), shards)
        out = np.asarray(jax.block_until_ready(program(garr)))
        if prog.coll == CollType.ALLREDUCE:
            np.testing.assert_allclose(out[:count], float(n))


# ---------------------------------------------------------------------------
# device-side stragglers feed the continuous scorer (ISSUE 16)
# ---------------------------------------------------------------------------

class TestDeviceStragglerScoring:
    """dev_launch/dev_ready wire events share a (team, tag, slot) key
    across ranks, so the wire-lag straggler signal — and therefore the
    continuous collector's incremental StragglerScorer — attributes a
    slow device rank even though XLA collectives post no host wire
    rounds at all."""

    @staticmethod
    def _dev_window(n=4, lag_rank=1, lag_s=0.08, n_colls=3):
        """One synthetic merged window: every rank launches the same
        device collectives; *lag_rank*'s launches trail by *lag_s*."""
        ranks = {}
        for r in range(n):
            off = lag_s if r == lag_rank else 0.0
            wire = []
            for c in range(n_colls):
                t0 = 1.0 + 0.5 * c + off
                wire.append({"t": t0, "ev": "snd", "kind": "dev_launch",
                             "tkey": "xteam", "epoch": 0, "tag": 100 + c,
                             "slot": 0, "nbytes": 4096})
                wire.append({"t": t0 + 0.01, "ev": "snd",
                             "kind": "dev_ready", "tkey": "xteam",
                             "epoch": 0, "tag": 100 + c, "slot": 1,
                             "nbytes": 4096})
            ranks[r] = {"events": [], "wire": wire}
        return {"ranks": {str(r): v for r, v in ranks.items()},
                "absent_ranks": []}

    def test_wire_lag_names_slow_device_rank(self):
        from ucc_tpu.obs import diagnose
        findings = diagnose.detect_stragglers(self._dev_window())
        lag_f = [f for f in findings if f["signal"] == "wire_lag"]
        assert lag_f and lag_f[0]["rank"] == 1
        assert lag_f[0]["lag_s"] == pytest.approx(0.08, abs=0.02)

    def test_scorer_flags_persistently_slow_device_rank(self):
        from ucc_tpu.obs import diagnose
        sc = diagnose.StragglerScorer(decay=0.5, flag_on=0.7,
                                      flag_off=0.2, windows=2)
        flagged = frozenset()
        for _ in range(4):
            flagged = sc.step(self._dev_window())
        assert flagged == frozenset({1})
        # symmetric launches never flag
        sc2 = diagnose.StragglerScorer(windows=2)
        for _ in range(4):
            assert sc2.step(self._dev_window(lag_s=0.0)) == frozenset()

    def test_live_dev_events_flow_into_scorer(self):
        """A real generated-device allreduce leaves dev_launch/dev_ready
        wire events that survive cross-rank merge and feed the scorer
        without tripping it on a healthy run. Own job: the shared module
        teams carry abandoned-init tag skew from the fallback tests."""
        from ucc_tpu.obs import diagnose, flight
        if not flight.ENABLED:
            pytest.skip("flight recorder disabled")
        if len(jax.devices()) < N:
            pytest.skip("needs >= 4 virtual devices")
        had = os.environ.get("UCC_GEN_DEVICE")
        os.environ["UCC_GEN_DEVICE"] = "y"
        j = UccJob(N)
        try:
            tms = j.create_team()
            count = 96
            srcs = [np.ones(count, np.float32) * (r + 1)
                    for r in range(N)]

            def mk(r):
                return CollArgs(coll_type=CollType.ALLREDUCE,
                                src=dev_buf(j, r, srcs[r],
                                            DataType.FLOAT32),
                                dst=BufferInfo(None, count,
                                               DataType.FLOAT32,
                                               mem_type=MemoryType.TPU),
                                op=ReductionOp.SUM)
            reqs, _ = run_forced(j, tms, "gen_dev_ring_c1", mk)
            for rq in reqs:
                rq.finalize()
            merged = flight.collect_process(j.contexts[0], "test")
        finally:
            j.cleanup()
            if had is None:
                os.environ.pop("UCC_GEN_DEVICE", None)
            else:
                os.environ["UCC_GEN_DEVICE"] = had
        kinds = {w.get("kind")
                 for snap in merged["ranks"].values()
                 for w in snap.get("wire", ())}
        assert "dev_launch" in kinds and "dev_ready" in kinds
        sc = diagnose.StragglerScorer(windows=2)
        # first window can never flag (streak < windows); the call must
        # digest device-kind wire events without raising
        assert sc.step(merged) == frozenset()
        assert sc.windows_seen == 1
