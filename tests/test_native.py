"""Native C++ runtime core v2 tests: full parity with the python
Mailbox contract (copy-free delivery in both match orders, eager/rndv
split, truncation text, cancel-skip, epoch fences), the
request-lifecycle fixes (free-at-delivery, purge), the MPMC queue, the
collective suite over the native matcher, and the UCC_FT=shrink
kill->shrink drill with the native matcher forced on. Skips cleanly
when no toolchain built the core."""
import os

import numpy as np
import pytest

from ucc_tpu import (BufferInfo, CollArgs, CollType, DataType, ReductionOp)
from ucc_tpu.native import ABI_VERSION, available, get_lib

from harness import UccJob

pytestmark = pytest.mark.skipif(not available(),
                                reason="native core not built")


def _key(tag, epoch=0, slot=0, src=0, team="t"):
    """Canonical 5-field TagKey shape (team_key, epoch, coll_tag, slot,
    src) — what the host TL actually sends."""
    return (team, epoch, tag, slot, src)


class TestNativeAbi:
    def test_abi_version_symbol(self):
        lib = get_lib()
        assert int(lib.ucc_abi_version()) == ABI_VERSION

    def test_no_symbol_probing_fallbacks(self):
        # v1 kept a `ucc_req_truncated = None` fallback for stale .so
        # files; the versioned loader must never hand out a half-bound lib
        lib = get_lib()
        for sym in ("ucc_mailbox_push", "ucc_mailbox_post_recv",
                    "ucc_mailbox_fence", "ucc_mailbox_purge",
                    "ucc_req_poll", "ucc_req_test_many", "ucc_req_cancel",
                    "ucc_req_free_many", "ucc_req_sent_nbytes"):
            assert getattr(lib, sym, None) is not None


class TestNativeMailbox:
    def test_recv_then_send_direct(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            dst = np.zeros(16, np.float32)
            r = mb.post_recv_native(_key(1), dst)
            assert not r.test()
            s, kind = mb.push_native(_key(1),
                                     np.arange(16, dtype=np.float32))
            # copy-free fast path: matched a posted recv, delivered
            # straight into dst, send complete inside the call
            assert kind == "direct"
            assert s.test() and r.test()
            np.testing.assert_array_equal(
                dst, np.arange(16, dtype=np.float32))
            assert r.nbytes == 64
        finally:
            mb.destroy()

    def test_send_then_recv_eager(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            src = np.full(4, 7.0, np.float32)
            s, kind = mb.push_native(_key(2), src)
            assert kind == "eager" and s.test()   # staged copy: complete
            src[:] = -1.0   # sender may reuse its buffer immediately
            d = np.zeros(4, np.float32)
            r = mb.post_recv_native(_key(2), d)
            assert r.test() and d[0] == 7.0
        finally:
            mb.destroy()

    def test_send_then_recv_rndv(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            big = np.arange(5000, dtype=np.float64)
            s, kind = mb.push_native(_key(3), big, 8192)
            # > eager limit and unexpected: parked zero-copy, send
            # pending until a recv lands it
            assert kind == "rndv" and not s.test()
            d = np.zeros(5000, np.float64)
            r = mb.post_recv_native(_key(3), d)
            assert r.test() and s.test()
            np.testing.assert_array_equal(d, big)
        finally:
            mb.destroy()

    def test_eager_limit_is_respected(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            data = np.zeros(100, np.uint8)
            _, kind_small = mb.push_native(_key(4), data, 100)
            _, kind_large = mb.push_native(_key(5), data, 99)
            assert kind_small == "eager" and kind_large == "rndv"
        finally:
            mb.destroy()

    def test_unexpected_message_queue_fifo(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            mb.push_native(_key(6), np.full(4, 1.0, np.float32))
            mb.push_native(_key(6), np.full(4, 2.0, np.float32))
            d1 = np.zeros(4, np.float32)
            d2 = np.zeros(4, np.float32)
            r1 = mb.post_recv_native(_key(6), d1)
            r2 = mb.post_recv_native(_key(6), d2)
            assert r1.test() and r2.test()
            assert d1[0] == 1.0 and d2[0] == 2.0
        finally:
            mb.destroy()

    def test_key_isolation(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            da = np.zeros(2, np.int32)
            ra = mb.post_recv_native(_key(7, slot=1), da)
            mb.push_native(_key(7, slot=2), np.full(2, 9, np.int32))
            assert not ra.test()   # different slot must not match
            mb.push_native(_key(7, slot=1), np.full(2, 5, np.int32))
            assert ra.test() and da[0] == 5
        finally:
            mb.destroy()

    def test_tuple_tags_and_generic_keys(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            # service tags are ("svc", n) tuples in the coll_tag position
            d = np.zeros(2, np.int64)
            r = mb.post_recv_native(("t", 0, ("svc", 3), 0, 1), d)
            mb.push_native(("t", 0, ("svc", 3), 0, 1),
                           np.full(2, 11, np.int64))
            assert r.test() and d[0] == 11
            # ...and svc tags stay isolated from each other
            r2 = mb.post_recv_native(("t", 0, ("svc", 4), 0, 1),
                                     np.zeros(2, np.int64))
            assert not r2.test()
            # non-canonical keys (tests, one-sided replies) still work
            d3 = np.zeros(2, np.int64)
            r3 = mb.post_recv_native(("odd", "key"), d3)
            mb.push_native(("odd", "key"), np.full(2, 5, np.int64))
            assert r3.test() and d3[0] == 5
        finally:
            mb.destroy()

    def test_zero_length_message(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            s, kind = mb.push_native(_key(8), np.empty(0, np.uint8))
            assert kind == "eager" and s.test()
            r = mb.post_recv_native(_key(8), np.empty(0, np.uint8))
            assert r.test() and r.nbytes == 0 and r.error is None
        finally:
            mb.destroy()


class TestNativeTruncation:
    """The C matcher must flag sends larger than the recv capacity
    (clamped copy, loud failure). Counts are labeled in BYTES — the C
    side sees only byte lengths and dst may carry any dtype, unlike the
    python matcher which flattens to uint8 before matching."""

    def test_truncated_send_sets_error(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            dst = np.zeros(4, np.uint8)
            rreq = mb.post_recv_native(_key(1), dst)
            sreq, _ = mb.push_native(_key(1), np.arange(10, dtype=np.uint8))
            assert rreq.test() and sreq.test()
            assert rreq.error is not None and "truncated" in rreq.error
            assert "sent 10 bytes" in rreq.error
            assert "4-byte recv buffer" in rreq.error
            assert rreq.nbytes == 4          # clamped to capacity
        finally:
            mb.destroy()

    def test_truncated_unexpected_order(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            mb.push_native(_key(2), np.arange(10, dtype=np.uint8))
            rreq = mb.post_recv_native(_key(2), np.zeros(4, np.uint8))
            assert rreq.test()
            assert rreq.error is not None and "truncated" in rreq.error
        finally:
            mb.destroy()

    def test_exact_size_no_error(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            dst = np.zeros(8, np.uint8)
            rreq = mb.post_recv_native(_key(3), dst)
            mb.push_native(_key(3), np.arange(8, dtype=np.uint8))
            assert rreq.test()
            assert rreq.error is None and rreq.nbytes == 8
        finally:
            mb.destroy()


class TestNativeCancel:
    def test_cancel_skip_at_match(self):
        """A cancelled posted recv must be SKIPPED at match time: the
        message goes to the next live recv (or parks), never into the
        cancelled buffer — the PR-2 recv-withdrawal contract."""
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            dead = np.zeros(4, np.uint8)
            r1 = mb.post_recv_native(_key(1), dead)
            r1.cancel()
            assert r1.test() and r1.cancelled and r1.error == "canceled"
            live = np.zeros(4, np.uint8)
            r2 = mb.post_recv_native(_key(1), live)
            s, kind = mb.push_native(_key(1), np.full(4, 3, np.uint8))
            assert kind == "direct"          # skipped straight to r2
            assert r2.test() and live[0] == 3
            assert not dead.any()            # cancelled buffer untouched
        finally:
            mb.destroy()

    def test_cancel_after_delivery_stays_delivered(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            d = np.zeros(4, np.uint8)
            r = mb.post_recv_native(_key(2), d)
            mb.push_native(_key(2), np.full(4, 9, np.uint8))
            r.cancel()
            assert r.test() and r.cancelled
            assert r.error is None and d[0] == 9   # data stands
        finally:
            mb.destroy()

    def test_cancel_only_skips_the_cancelled_entry(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            d1, d2 = np.zeros(2, np.uint8), np.zeros(2, np.uint8)
            r1 = mb.post_recv_native(_key(3), d1)
            r2 = mb.post_recv_native(_key(3), d2)
            r2.cancel()
            mb.push_native(_key(3), np.full(2, 5, np.uint8))
            assert r1.test() and d1[0] == 5
            assert r2.cancelled and not d2.any()
        finally:
            mb.destroy()


class TestNativeFence:
    """Epoch fences in the C matcher: parked stale state purged, late
    stale arrivals discarded at the match boundary — the machinery that
    lets UCC_FT=shrink run on the native matcher."""

    def test_fence_purges_parked_stale_state(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            stale = np.zeros(4, np.uint8)
            r = mb.post_recv_native(_key(1, epoch=0), stale)
            mb.push_native(_key(2, epoch=0), np.full(2, 1, np.uint8))
            purged = mb.fence("t", 1)
            assert purged == 2
            # the purged recv completes as fenced so its buffer may be
            # reclaimed; a purged unexpected send is simply gone
            assert r.test() and "fenced" in r.error and r.cancelled
            d = np.zeros(2, np.uint8)
            r2 = mb.post_recv_native(_key(2, epoch=1), d)
            assert not r2.test()   # the old-epoch send did NOT survive
        finally:
            mb.destroy()

    def test_stale_send_discarded_at_boundary(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            mb.fence("t", 1)
            s, kind = mb.push_native(_key(1, epoch=0),
                                     np.full(2, 1, np.uint8))
            assert kind == "fenced" and s.test()   # sender proceeds
            # nothing parked: a new-epoch recv must not see it
            r = mb.post_recv_native(_key(1, epoch=1), np.zeros(2, np.uint8))
            assert not r.test()
        finally:
            mb.destroy()

    def test_stale_post_recv_fails_locally(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            mb.fence("t", 2)
            r = mb.post_recv_native(_key(1, epoch=1), np.zeros(2, np.uint8))
            assert r.test() and "fenced" in r.error
        finally:
            mb.destroy()

    def test_fence_purges_rndv_send(self):
        """A parked zero-copy rndv send in a fenced epoch completes (the
        sender must stop waiting) and its C-side request is freed."""
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            big = np.zeros(100000, np.uint8)
            s, kind = mb.push_native(_key(1, epoch=0), big, 8192)
            assert kind == "rndv" and not s.test()
            assert mb.fence("t", 1) == 1
            assert s.test()
        finally:
            mb.destroy()

    def test_fence_is_team_scoped(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            other = np.zeros(2, np.uint8)
            r = mb.post_recv_native(_key(1, team="other"), other)
            assert mb.fence("t", 5) == 0
            assert not r.test()    # unrelated team untouched
            mb.push_native(_key(1, team="other"), np.full(2, 4, np.uint8))
            assert r.test() and other[0] == 4
        finally:
            mb.destroy()


class TestNativeLifecycle:
    def test_purge_reclaims_abandoned_requests(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            reqs = [mb.post_recv_native(_key(i), np.zeros(4, np.uint8))
                    for i in range(8)]
            big = np.zeros(100000, np.uint8)
            s, _ = mb.push_native(_key(99), big, 8192)   # parked rndv
            assert mb.purge() > 0
            # abandoned handles read as complete after the purge
            for r in reqs:
                assert r.test()
            assert s.test()
            assert not mb._send_keep
        finally:
            mb.destroy()

    def test_send_request_freed_at_delivery(self):
        """rndv send requests are freed when the recv lands them: the
        sender's keepalive drains at its next poll, and the mailbox does
        not accumulate C-side requests (the v1 leak-on-abandon)."""
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            big = np.zeros(100000, np.uint8)
            s, _ = mb.push_native(_key(1), big, 8192)
            assert mb._send_keep            # payload pinned while parked
            d = np.zeros(100000, np.uint8)
            r = mb.post_recv_native(_key(1), d)
            assert r.test() and s.test()
            assert not mb._send_keep        # keepalive dropped at poll
        finally:
            mb.destroy()

    def test_slot_reuse(self):
        """Completed request slots are recycled: a tight loop must not
        grow the slot table."""
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            for i in range(3000):
                d = np.zeros(4, np.uint8)
                r = mb.post_recv_native(_key(i), d)
                mb.push_native(_key(i), np.full(4, 1, np.uint8))
                assert r.test()
            # ids encode (gen<<20 | slot): slot indexes must stay small
            r = mb.post_recv_native(_key(9999), np.zeros(1, np.uint8))
            assert (r.rid & ((1 << 20) - 1)) < 2048
        finally:
            mb.destroy()

    def test_poll_pending_mixed(self):
        """poll_pending batches native requests per mailbox and falls
        back to test() for everything else."""
        from ucc_tpu.native import NativeMailbox, poll_pending

        class FakeReq:
            def __init__(self, done):
                self._d = done

            def test(self):
                return self._d

        mb = NativeMailbox()
        try:
            d = np.zeros(4, np.uint8)
            r_pend = mb.post_recv_native(_key(1), d)
            r_done = mb.post_recv_native(_key(2), np.zeros(4, np.uint8))
            mb.push_native(_key(2), np.full(4, 1, np.uint8))
            pending = poll_pending([r_pend, r_done, FakeReq(True),
                                    FakeReq(False)])
            kinds = {type(p).__name__ for p in pending}
            assert len(pending) == 2 and "FakeReq" in kinds
            assert any(p is r_pend for p in pending)
        finally:
            mb.destroy()

    def test_closed_mailbox_is_safe(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        r = mb.post_recv_native(_key(1), np.zeros(4, np.uint8))
        mb.destroy()
        assert r.test()                      # reads as complete, no crash
        s, kind = mb.push_native(_key(1), np.zeros(4, np.uint8))
        assert s.test() and kind == "eager"  # nowhere to land; no crash
        with pytest.raises(RuntimeError):
            mb.post_recv_native(_key(1), np.zeros(4, np.uint8))

    def test_destroyed_mailbox_is_parked_and_recycled(self):
        """destroy() parks the C mailbox for reuse instead of freeing it,
        so a request handle that raced destroy polls bumped generations
        (reads complete) — never freed heap — even after the mailbox is
        recycled into a new endpoint's NativeMailbox."""
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        old_ptr = mb.ptr
        r = mb.post_recv_native(_key(1), np.zeros(4, np.uint8))
        stale = (r.mb, r.rid)
        mb.destroy()
        mb2 = NativeMailbox()           # pops the parked mailbox
        try:
            assert mb2.ptr == old_ptr
            # the old-life handle still reads complete against the
            # recycled mailbox's slot table (generation mismatch)
            assert int(mb2.lib.ucc_req_poll(mb2.ptr, stale[1])) != 0
            # and the recycled mailbox works as a fresh one
            d = np.zeros(4, np.uint8)
            r2 = mb2.post_recv_native(_key(2), d)
            s2, kind = mb2.push_native(_key(2), np.ones(4, np.uint8))
            assert kind == "direct" and s2.test() and r2.test()
            assert d[0] == 1
        finally:
            mb2.destroy()

    def test_test_many_batch_poll(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            dsts = [np.zeros(4, np.uint8) for _ in range(6)]
            reqs = [mb.post_recv_native(_key(i), d)
                    for i, d in enumerate(dsts)]
            for i in (0, 2, 4):
                mb.push_native(_key(i), np.full(4, i + 1, np.uint8))
            pending = mb.test_many(list(reqs))
            assert {r.rid for r in pending} == {reqs[i].rid
                                                for i in (1, 3, 5)}
            for i in (0, 2, 4):
                assert reqs[i].test() and dsts[i][0] == i + 1
        finally:
            mb.destroy()


class TestNativeMpmc:
    def test_fifo_and_bounds(self):
        from ucc_tpu.native import NativeMpmcQueue
        q = NativeMpmcQueue(4)
        for i in range(4):
            assert q.push(i)
        assert not q.push(99)             # full
        assert [q.pop() for _ in range(4)] == [0, 1, 2, 3]
        assert q.pop() is None            # empty
        q.destroy()

    def test_threaded(self):
        import threading
        from ucc_tpu.native import NativeMpmcQueue
        q = NativeMpmcQueue(1024)
        got = []
        lock = threading.Lock()

        def producer(base):
            for i in range(100):
                while not q.push(base + i):
                    pass

        def consumer():
            for _ in range(200):
                v = None
                while v is None:
                    v = q.pop()
                with lock:
                    got.append(v)

        ts = [threading.Thread(target=producer, args=(0,)),
              threading.Thread(target=producer, args=(1000,)),
              threading.Thread(target=consumer)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert sorted(got) == sorted(list(range(100)) +
                                     list(range(1000, 1100)))
        q.destroy()


class TestTransportOverNative:
    """InProcTransport semantics with the native matcher engaged."""

    def test_native_default_on(self):
        from ucc_tpu.tl.host.transport import InProcTransport
        t = InProcTransport()
        try:
            assert t.native is not None   # default in BOTH thread modes
        finally:
            t.close()

    def test_counters_and_copy_free(self):
        from ucc_tpu.tl.host.transport import InProcTransport
        t = InProcTransport()
        try:
            key = ("tm", 0, 1, 0, 0)
            d = np.zeros(16, np.float32)
            r = t.recv_nb(key, d)
            s = t.send_nb(t, key, np.arange(16, dtype=np.float32))
            assert t.n_direct == 1 and s.test() and r.test()
            np.testing.assert_array_equal(
                d.view(np.float32), np.arange(16, dtype=np.float32))
            t.send_nb(t, ("tm", 0, 2, 0, 0), np.zeros(4, np.uint8))
            assert t.n_eager == 1
            t.send_nb(t, ("tm", 0, 3, 0, 0),
                      np.zeros(t.EAGER_THRESHOLD + 1, np.uint8))
            assert t.n_rndv == 1
        finally:
            t.close()

    def test_fence_routes_to_native_no_warning(self, caplog):
        import logging
        from ucc_tpu.tl.host.transport import InProcTransport
        t = InProcTransport()
        try:
            assert t.native is not None
            key = ("tk", 0, 1, 0, 0)
            r = t.recv_nb(key, np.zeros(4, np.uint8))
            with caplog.at_level(logging.WARNING):
                purged = t.fence("tk", 1)
            assert purged == 1 and r.test() and "fenced" in r.error
            assert not any("python matcher" in rec.message
                           for rec in caplog.records)
            # late stale send is discarded and counted
            s = t.send_nb(t, key, np.ones(4, np.uint8))
            assert s.test() and t.n_fenced == 1
        finally:
            t.close()


class TestCollectivesOverNative:
    def test_allreduce_native_transport(self, monkeypatch):
        monkeypatch.setenv("UCC_TL_SHM_NATIVE", "y")
        job = UccJob(4)
        try:
            # confirm the native matcher is actually engaged
            tl_ctx = job.contexts[0].tl_contexts["shm"].obj
            assert tl_ctx.transport.native is not None
            teams = job.create_team()
            count = 3000
            srcs = [np.full(count, r + 1.0, np.float32) for r in range(4)]
            dsts = [np.zeros(count, np.float32) for _ in range(4)]
            job.run_coll(teams, lambda r: CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(srcs[r], count, DataType.FLOAT32),
                dst=BufferInfo(dsts[r], count, DataType.FLOAT32),
                op=ReductionOp.SUM))
            for r in range(4):
                np.testing.assert_allclose(dsts[r], 10.0)
        finally:
            job.cleanup()

    def test_collective_matrix_large_msgs(self, monkeypatch):
        """Rndv-sized payloads through full collectives on the native
        matcher (zero-copy parking + keepalive discipline)."""
        monkeypatch.setenv("UCC_TL_SHM_NATIVE", "y")
        monkeypatch.setenv("UCC_HOST_EAGER_LIMIT", "1k")
        job = UccJob(4)
        try:
            teams = job.create_team()
            count = 8192          # 32KB payloads >> 1K eager limit
            srcs = [np.full(count, r + 1.0, np.float32) for r in range(4)]
            dsts = [np.zeros(4 * count, np.float32) for _ in range(4)]
            job.run_coll(teams, lambda r: CollArgs(
                coll_type=CollType.ALLGATHER,
                src=BufferInfo(srcs[r], count, DataType.FLOAT32),
                dst=BufferInfo(dsts[r], 4 * count, DataType.FLOAT32)))
            for r in range(4):
                for p in range(4):
                    np.testing.assert_allclose(
                        dsts[r][p * count:(p + 1) * count], p + 1.0)
        finally:
            job.cleanup()


class TestNativeFtShrink:
    """UCC_FT=shrink on the NATIVE matcher: the PR-4 capability fork is
    closed — kill -> agree -> shrink -> resume must pass with the native
    matcher forced on, with no python-matcher fallback warning, and a
    pre-shrink stale send must be provably fenced (n_fenced > 0)."""

    def test_kill_shrink_resume_native(self, monkeypatch, caplog):
        import logging
        from ucc_tpu.fault.soak import run_kill_shrink_soak
        monkeypatch.setenv("UCC_TL_SHM_NATIVE", "y")
        with caplog.at_level(logging.WARNING):
            report = run_kill_shrink_soak(
                n_ranks=4, kill_rank=2, pre_iters=2, post_iters=10,
                iter_deadline_s=30.0)
        assert report["violations"] == []
        assert report["post_iters"] == 10
        assert report["matcher"] == "native"
        # the stale-send probe drives n_fenced > 0 on the native matcher
        assert report["stale_send_fenced"] is True
        assert not any("python matcher" in rec.message
                       for rec in caplog.records)
