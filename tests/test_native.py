"""Native C++ runtime core tests: mailbox matching semantics (matches
the python Mailbox contract), MPMC queue, and the full collective suite
running over the native matcher (UCC_TL_SHM_NATIVE=y)."""
import os

import numpy as np
import pytest

from ucc_tpu import (BufferInfo, CollArgs, CollType, DataType, ReductionOp)
from ucc_tpu.native import available

from harness import UccJob

pytestmark = pytest.mark.skipif(not available(),
                                reason="native core not built")


class TestNativeMailbox:
    def test_recv_then_send(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        dst = np.zeros(16, np.float32)
        r = mb.post_recv_native(("t", 1, 0, 7), dst)
        assert not r.test()
        s = mb.push_native(("t", 1, 0, 7), np.arange(16, dtype=np.float32))
        assert s.test() and r.test()
        np.testing.assert_array_equal(dst, np.arange(16, dtype=np.float32))
        mb.destroy()

    def test_unexpected_message_queue(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        # two sends queue before any recv; FIFO per key
        mb.push_native(("k",), np.full(4, 1.0, np.float32))
        mb.push_native(("k",), np.full(4, 2.0, np.float32))
        d1 = np.zeros(4, np.float32)
        d2 = np.zeros(4, np.float32)
        r1 = mb.post_recv_native(("k",), d1)
        r2 = mb.post_recv_native(("k",), d2)
        assert r1.test() and r2.test()
        assert d1[0] == 1.0 and d2[0] == 2.0
        mb.destroy()

    def test_key_isolation(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        da = np.zeros(2, np.int32)
        ra = mb.post_recv_native(("a",), da)
        mb.push_native(("b",), np.full(2, 9, np.int32))
        assert not ra.test()   # different key must not match
        mb.push_native(("a",), np.full(2, 5, np.int32))
        assert ra.test() and da[0] == 5
        mb.destroy()

    def test_truncated_recv(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        dst = np.zeros(2, np.int32)       # 8 bytes capacity
        r = mb.post_recv_native(("k",), dst)
        mb.push_native(("k",), np.arange(8, dtype=np.int32))  # 32 bytes
        assert r.test()
        assert r.nbytes == 8              # clamped to capacity
        mb.destroy()


class TestNativeMpmc:
    def test_fifo_and_bounds(self):
        from ucc_tpu.native import NativeMpmcQueue
        q = NativeMpmcQueue(4)
        for i in range(4):
            assert q.push(i)
        assert not q.push(99)             # full
        assert [q.pop() for _ in range(4)] == [0, 1, 2, 3]
        assert q.pop() is None            # empty
        q.destroy()

    def test_threaded(self):
        import threading
        from ucc_tpu.native import NativeMpmcQueue
        q = NativeMpmcQueue(1024)
        got = []
        lock = threading.Lock()

        def producer(base):
            for i in range(100):
                while not q.push(base + i):
                    pass

        def consumer():
            for _ in range(200):
                v = None
                while v is None:
                    v = q.pop()
                with lock:
                    got.append(v)

        ts = [threading.Thread(target=producer, args=(0,)),
              threading.Thread(target=producer, args=(1000,)),
              threading.Thread(target=consumer)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert sorted(got) == sorted(list(range(100)) +
                                     list(range(1000, 1100)))
        q.destroy()


class TestCollectivesOverNative:
    def test_allreduce_native_transport(self, monkeypatch):
        monkeypatch.setenv("UCC_TL_SHM_NATIVE", "y")
        job = UccJob(4)
        try:
            # confirm the native matcher is actually engaged
            tl_ctx = job.contexts[0].tl_contexts["shm"].obj
            assert tl_ctx.transport.native is not None
            teams = job.create_team()
            count = 3000
            srcs = [np.full(count, r + 1.0, np.float32) for r in range(4)]
            dsts = [np.zeros(count, np.float32) for _ in range(4)]
            job.run_coll(teams, lambda r: CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(srcs[r], count, DataType.FLOAT32),
                dst=BufferInfo(dsts[r], count, DataType.FLOAT32),
                op=ReductionOp.SUM))
            for r in range(4):
                np.testing.assert_allclose(dsts[r], 10.0)
        finally:
            job.cleanup()


class TestNativeTruncation:
    """The C matcher must flag sends larger than the recv capacity
    (parity with the python Mailbox's truncation detection)."""

    def test_truncated_send_sets_error(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            dst = np.zeros(4, np.uint8)
            rreq = mb.post_recv_native(("k", 1), dst)
            sreq = mb.push_native(("k", 1), np.arange(10, dtype=np.uint8))
            assert rreq.test() and sreq.test()
            assert rreq.error is not None and "truncated" in rreq.error
        finally:
            mb.destroy()

    def test_exact_size_no_error(self):
        from ucc_tpu.native import NativeMailbox
        mb = NativeMailbox()
        try:
            dst = np.zeros(8, np.uint8)
            rreq = mb.post_recv_native(("k", 2), dst)
            mb.push_native(("k", 2), np.arange(8, dtype=np.uint8))
            assert rreq.test()
            assert rreq.error is None and rreq.nbytes == 8
        finally:
            mb.destroy()
