"""In-process multi-rank test harness.

Mirrors the reference gtest harness (test/gtest/common/test_ucc.h:123-226):
``UccJob`` = N "processes" inside one process, each with its own Lib +
Context, bootstrapped by a thread OOB; teams over subsets; ``UccReq`` posts
a collective on every rank and progresses all contexts until done.
Context creation (blocking OOB exchange) runs in threads; everything after
is driven cooperatively single-threaded.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

import ucc_tpu
from ucc_tpu import (CollArgs, Context, ContextParams, Status, TeamParams,
                     ThreadOobWorld)


class UccJob:
    def __init__(self, n: int, lib_overrides: Optional[dict] = None):
        self.n = n
        self.world = ThreadOobWorld(n)
        self.libs = [ucc_tpu.init(**(lib_overrides or {})) for _ in range(n)]
        self.contexts: List[Context] = [None] * n  # type: ignore[list-item]
        errs = []

        def make_ctx(r):
            try:
                self.contexts[r] = Context(
                    self.libs[r],
                    ContextParams(oob=self.world.endpoint(r)))
            except Exception as e:  # noqa: BLE001
                errs.append((r, e))

        threads = [threading.Thread(target=make_ctx, args=(r,))
                   for r in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        if errs:
            raise errs[0][1]
        self.teams: List[List] = []

    # ------------------------------------------------------------------
    def create_team(self, ranks: Optional[Sequence[int]] = None,
                    timeout: float = 30.0):
        """Create a team over `ranks` (default: all). Returns the per-member
        team list indexed by group rank."""
        ranks = list(ranks) if ranks is not None else list(range(self.n))
        sub_world = ThreadOobWorld(len(ranks))
        teams = [self.contexts[r].create_team_post(
            TeamParams(oob=sub_world.endpoint(i)))
            for i, r in enumerate(ranks)]
        deadline = time.monotonic() + timeout
        while True:
            sts = [t.create_test() for t in teams]
            for r in ranks:
                self.contexts[r].progress()
            if all(s == Status.OK for s in sts):
                break
            bad = [s for s in sts if s.is_error]
            if bad:
                raise ucc_tpu.UccError(bad[0], "team create failed")
            if time.monotonic() > deadline:
                raise TimeoutError("team create timed out")
        self.teams.append(teams)
        return teams

    # ------------------------------------------------------------------
    def run_coll(self, teams, make_args: Callable[[int], CollArgs],
                 timeout: float = 30.0) -> List:
        """Init+post `make_args(group_rank)` on every member, progress all
        contexts to completion, return the per-rank requests."""
        reqs = [t.collective_init(make_args(i)) for i, t in enumerate(teams)]
        for rq in reqs:
            rq.post()
        self.progress_until(lambda: all(
            rq.test() != Status.IN_PROGRESS for rq in reqs), timeout)
        for rq in reqs:
            st = rq.test()
            assert st == Status.OK, f"collective failed: {st}"
        return reqs

    def progress_until(self, cond: Callable[[], bool],
                       timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        while not cond():
            for ctx in self.contexts:
                ctx.progress()
            if time.monotonic() > deadline:
                raise TimeoutError("progress_until timed out")

    def cleanup(self) -> None:
        for teams in self.teams:
            for t in teams:
                t.destroy()
        for ctx in self.contexts:
            ctx.destroy()
