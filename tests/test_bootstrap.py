"""World bootstrap helper: one call brings up contexts + the world team
across processes (the launcher-integration layer; reference users do this
via MPI / torch.distributed stores)."""
import multiprocessing as mp
import os

import numpy as np
import pytest


def _worker(rank, nprocs, port, outdir):
    import traceback
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["UCC_BOOTSTRAP"] = f"127.0.0.1:{port}"
        os.environ["UCC_RANK"] = str(rank)
        os.environ["UCC_NPROCS"] = str(nprocs)
        os.environ["UCC_RANKS_PER_PROC"] = "2"
        from ucc_tpu.bootstrap import World
        from ucc_tpu import (BufferInfo, CollArgs, CollType, DataType,
                             ReductionOp, Status)
        world = World.from_env()
        assert world.world_size == nprocs * 2
        outs = []
        for i, team in enumerate(world.teams):
            r = rank * 2 + i
            src = np.full(8, r + 1.0, np.float64)
            dst = np.zeros(8, np.float64)
            req = team.collective_init(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(src, 8, DataType.FLOAT64),
                dst=BufferInfo(dst, 8, DataType.FLOAT64),
                op=ReductionOp.SUM))
            req.post()
            outs.append((req, dst))
        import time
        deadline = time.monotonic() + 60
        while any(rq.test() == Status.IN_PROGRESS for rq, _ in outs):
            world.progress()
            assert time.monotonic() < deadline
        n = world.world_size
        expect = n * (n + 1) / 2
        for rq, dst in outs:
            assert rq.test() == Status.OK
            np.testing.assert_allclose(dst, expect)
        world.finalize()
        with open(os.path.join(outdir, f"r{rank}.txt"), "w") as f:
            f.write("ok")
    except Exception:  # noqa: BLE001
        with open(os.path.join(outdir, f"r{rank}.txt"), "w") as f:
            f.write("error:" + traceback.format_exc())


def test_world_bootstrap_two_processes(tmp_path):
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    nprocs = 2
    ctx = mp.get_context("spawn")
    procs = [ctx.Process(target=_worker,
                         args=(r, nprocs, port, str(tmp_path)))
             for r in range(nprocs)]
    for p in procs:
        p.start()
    for p in procs:
        p.join(timeout=150)
        if p.is_alive():
            p.terminate()
            pytest.fail("bootstrap worker hung")
    for r in range(nprocs):
        out = (tmp_path / f"r{r}.txt").read_text()
        assert out == "ok", out


class TestStoreHandshake:
    """Round-5 bootstrap hardening: the store handshake must reject
    foreign listeners and strangers (a fixed store port can collide
    with ephemeral TL listener ports — observed in the wild as a TL
    frame desync)."""

    def test_client_rejects_foreign_listener(self):
        """A listener that is NOT a ucc store (sends no cookie): the
        client must NOT enroll; it retries until deadline and raises."""
        import socket as pysock
        import threading
        from ucc_tpu.core.oob import TcpStoreOob

        lsock = pysock.socket()
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(4)
        port = lsock.getsockname()[1]
        accepted = []

        def silent_accept():
            try:
                while True:
                    c, _ = lsock.accept()
                    accepted.append(c)   # never send anything
            except OSError:
                return

        t = threading.Thread(target=silent_accept, daemon=True)
        t.start()
        import time as _t
        t0 = _t.monotonic()
        with pytest.raises(OSError):
            # rank 1 (no server side); 5s magic-read timeout per try
            TcpStoreOob(1, 2, port=port, timeout_s=6)
        assert _t.monotonic() - t0 >= 4, "gave up before the magic wait"
        lsock.close()

    def test_wrong_job_cookie_rejected(self):
        """A REAL store of a different job (different key): clients of
        this job must refuse to enroll."""
        from ucc_tpu.core.oob import TcpStoreOob, _StoreServer, _store_cookie

        srv = _StoreServer(2, ("127.0.0.1", 0), _store_cookie("jobA", 2))
        port = srv.lsock.getsockname()[1]
        with pytest.raises(OSError):
            TcpStoreOob(1, 2, port=port, key="jobB", timeout_s=4)
        srv.close()

    def test_stranger_cannot_eat_slot(self):
        """A stranger that connects and hangs must not consume one of
        the size slots: real clients still bootstrap. Port selection is
        probe-then-close (TOCTOU), so the whole setup retries on a
        collision instead of flaking."""
        import socket as pysock
        import threading
        import time as _t
        from ucc_tpu.core.oob import TcpStoreOob

        last_errs = None
        for _attempt in range(3):
            ends = [None, None]
            errs = []

            def mk(r, port):
                try:
                    ends[r] = TcpStoreOob(r, 2, port=port)
                except Exception as e:  # noqa: BLE001
                    errs.append((r, e))

            probe = pysock.socket()
            probe.bind(("127.0.0.1", 0))
            port = probe.getsockname()[1]
            probe.close()
            t0 = threading.Thread(target=mk, args=(0, port))
            t0.start()
            _t.sleep(0.3)
            try:
                stranger = pysock.create_connection(("127.0.0.1", port),
                                                    timeout=5)
                stranger.sendall(b"\x00garbage")
            except OSError:
                stranger = None
            t1 = threading.Thread(target=mk, args=(1, port))
            t1.start()
            t0.join(40)
            t1.join(40)
            if errs:
                last_errs = errs         # port collision: retry fresh
                for e in ends:
                    if e is not None:
                        e.close()
                if stranger is not None:
                    stranger.close()
                continue
            assert ends[0] is not None and ends[1] is not None
            r0 = ends[0].allgather(b"a")
            r1 = ends[1].allgather(b"b")
            assert r0.result == [b"a", b"b"] == r1.result
            if stranger is not None:
                stranger.close()
            ends[0].close()
            ends[1].close()
            return
        pytest.fail(f"bootstrap failed on all attempts: {last_errs}")
