"""World bootstrap helper: one call brings up contexts + the world team
across processes (the launcher-integration layer; reference users do this
via MPI / torch.distributed stores)."""
import multiprocessing as mp
import os

import numpy as np
import pytest


def _worker(rank, nprocs, port, outdir):
    import traceback
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["UCC_BOOTSTRAP"] = f"127.0.0.1:{port}"
        os.environ["UCC_RANK"] = str(rank)
        os.environ["UCC_NPROCS"] = str(nprocs)
        os.environ["UCC_RANKS_PER_PROC"] = "2"
        from ucc_tpu.bootstrap import World
        from ucc_tpu import (BufferInfo, CollArgs, CollType, DataType,
                             ReductionOp, Status)
        world = World.from_env()
        assert world.world_size == nprocs * 2
        outs = []
        for i, team in enumerate(world.teams):
            r = rank * 2 + i
            src = np.full(8, r + 1.0, np.float64)
            dst = np.zeros(8, np.float64)
            req = team.collective_init(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(src, 8, DataType.FLOAT64),
                dst=BufferInfo(dst, 8, DataType.FLOAT64),
                op=ReductionOp.SUM))
            req.post()
            outs.append((req, dst))
        import time
        deadline = time.monotonic() + 60
        while any(rq.test() == Status.IN_PROGRESS for rq, _ in outs):
            world.progress()
            assert time.monotonic() < deadline
        n = world.world_size
        expect = n * (n + 1) / 2
        for rq, dst in outs:
            assert rq.test() == Status.OK
            np.testing.assert_allclose(dst, expect)
        world.finalize()
        with open(os.path.join(outdir, f"r{rank}.txt"), "w") as f:
            f.write("ok")
    except Exception:  # noqa: BLE001
        with open(os.path.join(outdir, f"r{rank}.txt"), "w") as f:
            f.write("error:" + traceback.format_exc())


def test_world_bootstrap_two_processes(tmp_path):
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    nprocs = 2
    ctx = mp.get_context("spawn")
    procs = [ctx.Process(target=_worker,
                         args=(r, nprocs, port, str(tmp_path)))
             for r in range(nprocs)]
    for p in procs:
        p.start()
    for p in procs:
        p.join(timeout=150)
        if p.is_alive():
            p.terminate()
            pytest.fail("bootstrap worker hung")
    for r in range(nprocs):
        out = (tmp_path / f"r{r}.txt").read_text()
        assert out == "ok", out
