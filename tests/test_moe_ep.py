"""Expert-parallel MoE routing example: dispatch/combine alltoalls through
ucc_tpu.ops inside one jitted shard_map program (the EP strategy the
reference's MoE traffic-matrix generator models, ucc_pt_config.h:98-108)."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from ucc_tpu.examples.moe_ep import make_moe_layer, reference_moe


def test_moe_ep_matches_reference():
    n = 4
    if len(jax.devices()) < n:
        pytest.skip("needs >= 4 devices")
    mesh = jax.make_mesh((n,), ("ep",))
    d, cap, tokens_per_dev = 8, 3, 6
    total = n * tokens_per_dev
    k = jax.random.PRNGKey(1)
    k1, k2, k3, k4 = jax.random.split(k, 4)
    x = jax.random.normal(k1, (total, d), jnp.float32)
    w_up = jax.random.normal(k2, (n, d, 16), jnp.float32) * 0.3
    w_dn = jax.random.normal(k3, (n, 16, d), jnp.float32) * 0.3
    assign = jax.random.randint(k4, (total,), 0, n, jnp.int32)

    layer = make_moe_layer(mesh, d, cap)
    sh = NamedSharding(mesh, P("ep"))
    y = layer(jax.device_put(x, sh), jax.device_put(w_up, sh),
              jax.device_put(w_dn, sh), jax.device_put(assign, sh))
    expect = reference_moe(x, w_up, w_dn, np.asarray(assign), cap)
    np.testing.assert_allclose(np.asarray(y), expect, rtol=2e-4, atol=2e-5)


def test_moe_ep_capacity_drop():
    """Tokens beyond a (source, expert) capacity produce zero outputs —
    the static-shape contract."""
    n = 4
    if len(jax.devices()) < n:
        pytest.skip("needs >= 4 devices")
    mesh = jax.make_mesh((n,), ("ep",))
    d, cap, tokens_per_dev = 4, 1, 4
    total = n * tokens_per_dev
    x = jnp.ones((total, d), jnp.float32)
    w_up = jnp.ones((n, d, 8), jnp.float32) * 0.1
    w_dn = jnp.ones((n, 8, d), jnp.float32) * 0.1
    assign = jnp.zeros((total,), jnp.int32)   # everyone -> expert 0
    layer = make_moe_layer(mesh, d, cap)
    sh = NamedSharding(mesh, P("ep"))
    y = np.asarray(layer(jax.device_put(x, sh), jax.device_put(w_up, sh),
                         jax.device_put(w_dn, sh),
                         jax.device_put(assign, sh)))
    # first token per device fits (capacity 1 per source), rest dropped
    for dev in range(n):
        blk = y[dev * tokens_per_dev:(dev + 1) * tokens_per_dev]
        assert np.abs(blk[0]).sum() > 0
        np.testing.assert_allclose(blk[1:], 0)
