"""Aux subsystem tests: EE/triggered post, generic datatypes, datatype
consistency checking, profiling, mem_map — mirrors reference gtest
core/test_service_coll.cc, core/test_mem_map.cc and the EE/event paths."""
import os
import struct
import time

import numpy as np
import pytest

import ucc_tpu
from ucc_tpu import (BufferInfo, CollArgs, CollType, DataType, Ee,
                     GenericDataType, ReductionOp, Status, UccEvent)
from ucc_tpu.constants import EeType

from harness import UccJob


class TestTriggeredPost:
    def test_cpu_thread_ee(self):
        job = UccJob(2)
        try:
            teams = job.create_team()
            count = 8
            srcs = [np.full(count, r + 1.0, np.float32) for r in range(2)]
            dsts = [np.zeros(count, np.float32) for _ in range(2)]
            reqs = [teams[r].collective_init(CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(srcs[r], count, DataType.FLOAT32),
                dst=BufferInfo(dsts[r], count, DataType.FLOAT32),
                op=ReductionOp.SUM)) for r in range(2)]
            ees = [Ee(teams[r], EeType.CPU_THREAD) for r in range(2)]
            evs = [UccEvent() for _ in range(2)]
            for r in range(2):
                ees[r].triggered_post(evs[r], reqs[r])
            time.sleep(0.05)
            # nothing ran yet: events not fired
            assert all(rq.test() == Status.OPERATION_INITIALIZED
                       for rq in reqs)
            for ev in evs:
                ev.set()
            deadline = time.monotonic() + 10
            while not all(rq.test() == Status.OK for rq in reqs):
                assert time.monotonic() < deadline
                time.sleep(0.005)
            for r in range(2):
                np.testing.assert_allclose(dsts[r], 3.0)
            # completion events observable
            deadline = time.monotonic() + 5
            seen = 0
            while seen < 2 and time.monotonic() < deadline:
                ev = ees[0].get_event()
                if ev is not None:
                    seen += 1
            assert seen == 2  # collective_post + collective_complete
            for ee in ees:
                ee.destroy()
        finally:
            job.cleanup()


class TestGenericDatatype:
    def test_bcast_generic(self):
        """Data movement of a user struct dtype (12-byte records)."""
        job = UccJob(3)
        try:
            teams = job.create_team()
            gdt = GenericDataType(12, name="record12")
            n_rec = 5
            root_data = np.arange(60, dtype=np.uint8)
            bufs = [root_data.copy() if r == 0 else np.zeros(60, np.uint8)
                    for r in range(3)]
            job.run_coll(teams, lambda r: CollArgs(
                coll_type=CollType.BCAST, root=0,
                src=BufferInfo(bufs[r], n_rec, gdt)))
            for r in range(3):
                np.testing.assert_array_equal(bufs[r], root_data)
        finally:
            job.cleanup()

    def test_generic_reduce_cb(self):
        """EC reduce through a user reduce callback (pairwise struct sum)."""
        from ucc_tpu.ec.cpu import EcCpu

        def reduce_cb(a: bytes, b: bytes, count: int) -> bytes:
            av = np.frombuffer(a, np.float32)
            bv = np.frombuffer(b, np.float32)
            return (av + bv).tobytes()

        gdt = GenericDataType(8, reduce_cb=reduce_cb, name="vec2f")
        ec = EcCpu()
        srcs = [np.full(4, float(i + 1), np.float32) for i in range(3)]
        dst = np.zeros(4, np.float32)
        ec.reduce(dst, srcs, 2, gdt, ReductionOp.SUM)   # 2 records of 8B
        np.testing.assert_allclose(dst, 6.0)

    def test_generic_without_reduce_cb_rejected(self):
        from ucc_tpu.ec.cpu import EcCpu
        from ucc_tpu.status import UccError
        gdt = GenericDataType(8, name="opaque")
        with pytest.raises(UccError):
            EcCpu().reduce(np.zeros(8, np.uint8),
                           [np.zeros(8, np.uint8)] * 2, 1, gdt,
                           ReductionOp.SUM)


class TestDtConsistency:
    """Rooted colls (gather/scatter family + bcast/reduce), opt-in via
    UCC_CHECK_ASYMMETRIC_DT (reference defaults it off for performance,
    ucc_global_opts.c:112, and scopes it to gather/scatter only —
    ucc_coll.c:274-277; we also wrap bcast/reduce)."""

    @pytest.mark.parametrize("coll", [CollType.BCAST, CollType.REDUCE])
    def test_asymmetric_dtype_detected_bcast_reduce(self, coll):
        job = UccJob(2, lib_overrides={"CHECK_ASYMMETRIC_DT": "y"})
        try:
            teams = job.create_team()
            count = 4
            dts = [DataType.FLOAT32, DataType.INT32]
            nds = [np.float32, np.int32]
            reqs = []
            for r in range(2):
                if coll == CollType.BCAST:
                    args = CollArgs(coll_type=coll, root=0,
                                    src=BufferInfo(np.ones(count, nds[r]),
                                                   count, dts[r]))
                else:
                    args = CollArgs(
                        coll_type=coll, root=0, op=ReductionOp.SUM,
                        src=BufferInfo(np.ones(count, nds[r]), count,
                                       dts[r]),
                        dst=BufferInfo(np.zeros(count, nds[r]), count,
                                       dts[r]) if r == 0 else None)
                reqs.append(teams[r].collective_init(args))
            for rq in reqs:
                rq.post()
            job.progress_until(lambda: all(
                rq.test() != Status.IN_PROGRESS for rq in reqs), timeout=15)
            assert reqs[0].test() == Status.ERR_INVALID_PARAM
            assert reqs[1].test() == Status.ERR_INVALID_PARAM
        finally:
            job.cleanup()

    def test_asymmetric_dtype_detected(self):
        job = UccJob(2, lib_overrides={"CHECK_ASYMMETRIC_DT": "y"})
        try:
            teams = job.create_team()
            count = 4
            dts = [DataType.FLOAT32, DataType.INT32]
            nds = [np.float32, np.int32]
            reqs = []
            for r in range(2):
                reqs.append(teams[r].collective_init(CollArgs(
                    coll_type=CollType.GATHER, root=0,
                    src=BufferInfo(np.ones(count, nds[r]), count, dts[r]),
                    dst=BufferInfo(np.zeros(count * 2, nds[r]), count * 2,
                                   dts[r]) if r == 0 else None)))
            for rq in reqs:
                rq.post()
            job.progress_until(lambda: all(
                rq.test() != Status.IN_PROGRESS for rq in reqs), timeout=15)
            assert reqs[0].test() == Status.ERR_INVALID_PARAM
            assert reqs[1].test() == Status.ERR_INVALID_PARAM
        finally:
            job.cleanup()

    def test_symmetric_passes(self):
        job = UccJob(2, lib_overrides={"CHECK_ASYMMETRIC_DT": "y"})
        try:
            teams = job.create_team()
            count = 4
            dst = np.zeros(count * 2, np.float32)
            reqs = [teams[r].collective_init(CollArgs(
                coll_type=CollType.GATHER, root=0,
                src=BufferInfo(np.ones(count, np.float32), count,
                               DataType.FLOAT32),
                dst=BufferInfo(dst, count * 2, DataType.FLOAT32) if r == 0
                else None)) for r in range(2)]
            for rq in reqs:
                rq.post()
            job.progress_until(lambda: all(
                rq.test() != Status.IN_PROGRESS for rq in reqs))
            assert all(rq.test() == Status.OK for rq in reqs)
            np.testing.assert_allclose(dst, 1.0)
        finally:
            job.cleanup()


class TestMemMap:
    def test_export_import_roundtrip(self):
        lib = ucc_tpu.init()
        ctx = ucc_tpu.Context(lib)
        buf = np.arange(16, dtype=np.float64)
        handle = ctx.mem_map(buf)
        assert isinstance(handle, bytes)
        desc = ctx.mem_import(handle)
        assert desc["nbytes"] == 128
        assert desc["buffer"] is buf        # same-process fast path
        assert ctx.mem_unmap(handle) == Status.OK
        assert ctx.mem_import(handle)["buffer"] is None
        ctx.destroy()


class TestProfiling:
    def test_profile_log(self, tmp_path, monkeypatch):
        # profiling reads env at import; reload the module with env set
        import importlib
        prof_file = tmp_path / "trace.json"
        monkeypatch.setenv("UCC_PROFILE_MODE", "log")
        monkeypatch.setenv("UCC_PROFILE_FILE", str(prof_file))
        from ucc_tpu.utils import profiling
        importlib.reload(profiling)
        assert profiling.ENABLED
        profiling.request_new("allreduce", 1)
        profiling.request_complete("allreduce", 1, status="OK")
        import json
        lines = [json.loads(line) for line in
                 prof_file.read_text().splitlines()]
        assert lines[0]["name"] == "coll_allreduce" and lines[0]["ph"] == "B"
        assert lines[1]["ph"] == "E"
        monkeypatch.delenv("UCC_PROFILE_MODE")
        importlib.reload(profiling)


class TestEeDeviceCollective:
    """Triggered-post lifecycle driving a DEVICE (TPU-memtype) collective
    end-to-end (VERDICT r1 weak #8): an EE dispatches a jax.Array
    allreduce through TL/XLA on an event signal, and completion delivers
    the rebound device result."""

    def test_triggered_device_allreduce(self):
        jax = pytest.importorskip("jax")
        import jax.numpy as jnp
        from ucc_tpu import MemoryType
        from ucc_tpu.core.ee import Ee, UccEvent
        from ucc_tpu.constants import EeType
        import time as _time
        n = 4
        if len(jax.devices()) < n:
            pytest.skip("needs >= 4 devices")
        job = UccJob(n)
        try:
            teams = job.create_team()
            count = 16
            argses, reqs = [], []
            for r in range(n):
                dev = job.contexts[r].tl_contexts["xla"].obj.device
                src = jax.device_put(
                    jnp.full((count,), r + 1.0, jnp.float32), dev)
                argses.append(CollArgs(
                    coll_type=CollType.ALLREDUCE,
                    src=BufferInfo(src, count, DataType.FLOAT32,
                                   mem_type=MemoryType.TPU),
                    dst=BufferInfo(None, count, DataType.FLOAT32,
                                   mem_type=MemoryType.TPU),
                    op=ReductionOp.SUM))
                reqs.append(teams[r].collective_init(argses[r]))
            ees = [Ee(teams[r], EeType.CPU_THREAD) for r in range(n)]
            try:
                evs = [UccEvent() for _ in range(n)]
                for r in range(n):
                    ees[r].triggered_post(evs[r], reqs[r])
                assert all(rq.test() == Status.OPERATION_INITIALIZED
                           for rq in reqs)
                for ev in evs:
                    ev.set()
                deadline = _time.monotonic() + 20
                while not all(rq.test() == Status.OK for rq in reqs):
                    assert _time.monotonic() < deadline, \
                        [rq.test() for rq in reqs]
                    _time.sleep(0.002)
                expect = n * (n + 1) / 2
                for r in range(n):
                    out = argses[r].dst.buffer
                    assert out is not None   # rebound device array
                    np.testing.assert_allclose(np.asarray(out), expect)
            finally:
                for ee in ees:
                    ee.destroy()
        finally:
            job.cleanup()


class TestOneSidedGating:
    """One-sided args gating (round 3): HOST-memory one-sided args are
    SERVED by the socket/shm RDMA-emulation path (full coverage in
    test_onesided.py); device-memory one-sided args remain honestly
    rejected — no HBM RDMA window over the TPU DCN (PARITY.md)."""

    def test_host_global_work_buffer_accepted(self):
        job = UccJob(2)
        try:
            teams = job.create_team()
            src = np.arange(4, dtype=np.float32)
            reqs = [teams[r].collective_init(CollArgs(
                coll_type=CollType.ALLTOALL,
                src=BufferInfo(src.copy(), 4, DataType.FLOAT32),
                dst=BufferInfo(np.zeros(4, np.float32), 4,
                               DataType.FLOAT32),
                global_work_buffer=np.zeros(16, np.uint8)))
                for r in range(2)]
            for rq in reqs:
                rq.post()
            job.progress_until(lambda: all(
                rq.test() != Status.IN_PROGRESS for rq in reqs))
            assert all(rq.test() == Status.OK for rq in reqs)
        finally:
            job.cleanup()

    def test_tpu_mem_mapped_flag_rejected(self):
        jax = pytest.importorskip("jax")
        import jax.numpy as jnp
        from ucc_tpu import CollArgsFlags, MemoryType
        job = UccJob(2)
        try:
            teams = job.create_team()
            x = jnp.zeros(4, dtype=jnp.float32)
            args = CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(x, 4, DataType.FLOAT32,
                               mem_type=MemoryType.TPU),
                dst=BufferInfo(x, 4, DataType.FLOAT32,
                               mem_type=MemoryType.TPU),
                op=ReductionOp.SUM,
                flags=CollArgsFlags.MEM_MAPPED_BUFFERS)
            from ucc_tpu import UccError
            with pytest.raises(UccError):
                teams[0].collective_init(args)
        finally:
            job.cleanup()


class TestTpuStreamEe:
    """EeType.TPU_STREAM: stream-ordered triggers — the collective
    dispatches when a jax array FUTURE resolves (the CUDA-stream analog:
    post after the producing kernel), driven by the normal context
    progress loop, no host signal or EE thread."""

    def test_data_readiness_trigger(self):
        jax = pytest.importorskip("jax")
        import jax.numpy as jnp
        from ucc_tpu import MemoryType
        from ucc_tpu.core.ee import Ee, UccEvent
        from ucc_tpu.constants import EeType
        n = 2
        job = UccJob(n)
        try:
            teams = job.create_team()
            count = 16
            # the producing compute: a jitted op whose RESULT triggers
            # the collective (data dependence, not host signalling)
            produced = [jax.jit(lambda x: x * 2)(
                jax.device_put(jnp.full((count,), r + 1.0, jnp.float32),
                               job.contexts[r].tl_contexts["xla"].obj.device))
                for r in range(n)]
            argses = [CollArgs(
                coll_type=CollType.ALLREDUCE,
                src=BufferInfo(produced[r], count, DataType.FLOAT32,
                               mem_type=MemoryType.TPU),
                dst=BufferInfo(None, count, DataType.FLOAT32,
                               mem_type=MemoryType.TPU),
                op=ReductionOp.SUM) for r in range(n)]
            reqs = [teams[r].collective_init(argses[r]) for r in range(n)]
            ees = [Ee(teams[r], EeType.TPU_STREAM) for r in range(n)]
            try:
                for r in range(n):
                    ees[r].triggered_post(
                        UccEvent(payload=produced[r]), reqs[r])
                job.progress_until(lambda: all(
                    rq.test() == Status.OK for rq in reqs), timeout=20)
                expect = (1 + 2) * 2.0
                for r in range(n):
                    np.testing.assert_allclose(
                        np.asarray(argses[r].dst.buffer), expect)
                # completion events observable on the out queue
                assert any(ees[r].get_event() is not None
                           for r in range(n))
            finally:
                for ee in ees:
                    ee.destroy()
        finally:
            job.cleanup()


class TestTriggeredAfterFastLane:
    """Regression: a persistent device collective whose fast re-post lane
    has been warmed (two plain posts) must still run the EE callback when
    a later post is TRIGGERED — the fast lane never runs observers, so
    the request must divert that round to the generic path (the cb is
    attached between posts; core/coll.py re-checks observers per post)."""

    def test_triggered_post_after_warm_reposts(self):
        jax = pytest.importorskip("jax")
        import jax.numpy as jnp
        from ucc_tpu import CollArgsFlags, MemoryType
        from ucc_tpu.core.ee import Ee, UccEvent
        from ucc_tpu.constants import EeType
        import time as _time
        n = 2
        job = UccJob(n)
        try:
            teams = job.create_team()
            count = 8
            argses, reqs = [], []
            for r in range(n):
                dev = job.contexts[r].tl_contexts["xla"].obj.device
                src = jax.device_put(
                    jnp.full((count,), r + 1.0, jnp.float32), dev)
                argses.append(CollArgs(
                    coll_type=CollType.ALLREDUCE,
                    src=BufferInfo(src, count, DataType.FLOAT32,
                                   mem_type=MemoryType.TPU),
                    dst=BufferInfo(None, count, DataType.FLOAT32,
                                   mem_type=MemoryType.TPU),
                    op=ReductionOp.SUM,
                    flags=CollArgsFlags.PERSISTENT))
                reqs.append(teams[r].collective_init(argses[r]))
            # two plain rounds: the second probes + arms the fast lane
            for _ in range(2):
                for rq in reqs:
                    rq.post()
                job.progress_until(lambda: all(
                    rq.test() == Status.OK for rq in reqs))
            ees = [Ee(teams[r], EeType.CPU_THREAD) for r in range(n)]
            try:
                evs = [UccEvent() for _ in range(n)]
                for r in range(n):
                    ees[r].triggered_post(evs[r], reqs[r])
                for ev in evs:
                    ev.set()
                deadline = _time.monotonic() + 20
                # the EE completion event must arrive (cb ran) — the bug
                # was a silent fast_repost that skipped the cb forever
                got = [False] * n
                while not all(got):
                    for r in range(n):
                        if not got[r] and ees[r].get_event() is not None:
                            got[r] = True
                    for c in job.contexts:
                        c.progress()
                    assert _time.monotonic() < deadline, got
                for r in range(n):
                    np.testing.assert_allclose(
                        np.asarray(argses[r].dst.buffer), 3.0)
            finally:
                for ee in ees:
                    ee.destroy()
        finally:
            job.cleanup()


class TestInfoAlgorithmListing:
    """ucc_info -a must print the full per-TL algorithm lists — the
    stub-team introspection path silently degrades to '(runtime)' if
    alg_table ever requires live-team state (caught in round 5)."""

    def test_host_tl_algs_listed(self, capsys):
        from ucc_tpu.tools.info import print_algorithms
        print_algorithms()
        out = capsys.readouterr().out
        for needle in ("sra_knomial", "sliding_window", "linear_batched",
                       "sag_knomial", "bruck"):
            assert needle in out, f"missing {needle} in -a output"
        assert "tl/shm" in out and "tl/socket" in out
        # the degraded marker must not replace every list
        assert out.count("(runtime)") < out.count(":")
