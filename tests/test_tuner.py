"""Autotuner tests (ISSUE 5): online exploration + rank-0 freeze,
topology-keyed cache round trip, offline compilation, and the
zero-cost-when-off contract."""
import json
import os

import numpy as np
import pytest

import ucc_tpu
from ucc_tpu import (BufferInfo, CollArgs, CollArgsFlags, CollType,
                     ReductionOp, Status)
from ucc_tpu.constants import DataType, MemoryType
from ucc_tpu.score.tuner import (bucket_range, cand_label,
                                 cache_entries, compile_measurements,
                                 load_cache, size_bucket, store_entries,
                                 topo_signature)
from ucc_tpu.utils.config import SIZE_INF

from harness import UccJob

COUNT = 8192                       # 32 KiB f32: the bandwidth-alg regime
NBYTES = COUNT * 4


@pytest.fixture(autouse=True)
def _fresh_session_cache():
    # decisions frozen by one test must not warm-start the next — each
    # test owns its tmp_path file cache, so the in-process session cache
    # (membership-change warm-start, PR 17) is cleared around each test
    from ucc_tpu.score import tuner
    tuner.session_reset()
    yield
    tuner.session_reset()


def _persistent_allreduce(teams, srcs, dsts):
    argses = [CollArgs(coll_type=CollType.ALLREDUCE, op=ReductionOp.SUM,
                       src=BufferInfo(srcs[r], COUNT, DataType.FLOAT32),
                       dst=BufferInfo(dsts[r], COUNT, DataType.FLOAT32),
                       flags=CollArgsFlags.PERSISTENT)
              for r in range(len(teams))]
    return [teams[r].collective_init(argses[r]) for r in range(len(teams))]


def _drive(job, reqs, rounds, dsts, n):
    for _ in range(rounds):
        for rq in reqs:
            rq.post()
        job.progress_until(lambda: all(
            rq.test() != Status.IN_PROGRESS for rq in reqs))
        for rq in reqs:
            assert rq.test() == Status.OK, rq.test()
        # exploration must never trade correctness: every round is a
        # real allreduce of ones over n ranks
        for d in dsts:
            assert abs(float(d[0]) - n) < 1e-6


# ---------------------------------------------------------------------------
# unit level
# ---------------------------------------------------------------------------

class TestUnits:
    def test_size_buckets(self):
        assert size_bucket(0) == 0
        assert bucket_range(0) == (0, 1)
        for msg in (1, 7, 4096, 32768, (1 << 20) + 3):
            lo, hi = bucket_range(size_bucket(msg))
            assert lo <= msg < hi

    def test_compile_measurements_merges_adjacent_winners(self):
        recs = []
        for size, winner in ((1024, "a"), (2048, "a"), (4096, "b")):
            for alg in ("a", "b"):
                recs.append({"coll": "allreduce", "mem": "host",
                             "alg": alg, "comp": "shm", "size_bytes": size,
                             "p50_us": 1.0 if alg == winner else 9.0})
        entries = compile_measurements(recs)
        assert entries == [
            {"coll": "allreduce", "mem": "host", "start": 0, "end": 4096,
             "alg": "a", "comp": "shm"},
            {"coll": "allreduce", "mem": "host", "start": 4096,
             "end": SIZE_INF, "alg": "b", "comp": "shm"},
        ]

    def test_compile_skips_malformed_records(self):
        entries = compile_measurements([
            {"coll": "allreduce"},                      # no size/latency
            {"size_bytes": 8, "alg": "x", "p50_us": 1}, # no coll
            {"coll": "bcast", "mem": "host", "alg": "kn",
             "size_bytes": 64, "avg_us": 2.0},          # avg fallback
        ])
        assert len(entries) == 1 and entries[0]["coll"] == "bcast"

    def test_cache_roundtrip_and_merge(self, tmp_path):
        path = str(tmp_path / "tune.json")
        e1 = {"coll": "allreduce", "mem": "host", "start": 0, "end": 4096,
              "alg": "a"}
        store_entries(path, "sigA", [e1])
        # same window replaces, new window appends, other sig untouched
        e2 = dict(e1, alg="b")
        e3 = {"coll": "allreduce", "mem": "host", "start": 4096,
              "end": 8192, "alg": "c"}
        store_entries(path, "sigA", [e2, e3], source="online")
        store_entries(path, "sigB", [e1])
        cache = load_cache(path)
        got = cache_entries(cache, "sigA")
        assert [e["alg"] for e in got] == ["b", "c"]
        assert cache_entries(cache, "sigB")[0]["alg"] == "a"
        assert cache_entries(cache, "nope") == []

    def test_load_cache_tolerates_garbage(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text("{not json")
        assert load_cache(str(p)) == {}
        assert load_cache(str(tmp_path / "missing.json")) == {}


# ---------------------------------------------------------------------------
# online mode: 4-rank convergence, agreement, cache persistence
# ---------------------------------------------------------------------------

SAMPLES = 8
# freeze point: SAMPLES exploration posts, then the decision is posted
# on the FIRST hold post (so the last exploration sample is recorded),
# then the deterministic hold window (service-bcast tree depth + 2 = 3
# for a 4-rank team), then the switch post — see the OnlineTuner
# divergence-safety docstring
FREEZE_ROUNDS = SAMPLES + 1 + 3 + 1


class TestOnline:
    def test_converges_freezes_and_agrees(self, tmp_path):
        cache = str(tmp_path / "tune.json")
        job = UccJob(4, lib_overrides={"TUNER": "online",
                                       "TUNER_SAMPLES": str(SAMPLES),
                                       "TUNER_CACHE": cache})
        try:
            teams = job.create_team()
            assert all(t.tuner is not None for t in teams)
            sigs = {topo_signature(t) for t in teams}
            assert len(sigs) == 1            # signature is rank-invariant
            srcs = [np.ones(COUNT, np.float32) for _ in range(4)]
            dsts = [np.zeros(COUNT, np.float32) for _ in range(4)]
            reqs = _persistent_allreduce(teams, srcs, dsts)
            # probe lane bound while exploring: post is an instance attr
            assert all("post" in rq.__dict__ for rq in reqs)
            _drive(job, reqs, FREEZE_ROUNDS + 1, dsts, 4)
            # converged: exploration bounded by the sample budget, then
            # the deterministic hold window, then frozen + unbound
            assert all("post" not in rq.__dict__ for rq in reqs)
            assert all(not t.tuner.exploring(
                t.tuner.key_for(CollType.ALLREDUCE, MemoryType.HOST,
                                NBYTES)) for t in teams)
            # every rank runs the SAME winner (the rank-0 decision)
            algs = {rq.task.alg_name for rq in reqs}
            assert len(algs) == 1, algs
            tops = {(t.score_map.lookup(CollType.ALLREDUCE,
                                        MemoryType.HOST, NBYTES)[0].alg_name,
                     t.score_map.lookup(CollType.ALLREDUCE,
                                        MemoryType.HOST, NBYTES)[0].origin)
                    for t in teams}
            assert len(tops) == 1
            assert next(iter(tops))[1] == "learned"
            # later rounds stay on the frozen winner
            _drive(job, reqs, 3, dsts, 4)
            assert {rq.task.alg_name for rq in reqs} == algs
            # rank 0 persisted the decision, keyed by the signature
            data = load_cache(cache)
            entries = cache_entries(data, next(iter(sigs)))
            assert entries, data
            lo, hi = bucket_range(size_bucket(NBYTES))
            assert any(e["coll"] == "allreduce" and e["start"] == lo and
                       e["end"] == hi for e in entries)
            for rq in reqs:
                rq.finalize()
        finally:
            job.cleanup()

    def test_cache_reload_starts_tuned_with_zero_exploration(self,
                                                             tmp_path):
        cache = str(tmp_path / "tune.json")
        overrides = {"TUNER": "online", "TUNER_SAMPLES": str(SAMPLES),
                     "TUNER_CACHE": cache}
        job = UccJob(4, lib_overrides=overrides)
        try:
            teams = job.create_team()
            srcs = [np.ones(COUNT, np.float32) for _ in range(4)]
            dsts = [np.zeros(COUNT, np.float32) for _ in range(4)]
            reqs = _persistent_allreduce(teams, srcs, dsts)
            _drive(job, reqs, FREEZE_ROUNDS + 1, dsts, 4)
            winner = reqs[0].task.alg_name
            for rq in reqs:
                rq.finalize()
        finally:
            job.cleanup()

        # second activation: the learned table loads at team create and
        # the key is covered — no probe lane, no exploration posts
        job2 = UccJob(4, lib_overrides=overrides)
        try:
            teams2 = job2.create_team()
            top = teams2[0].score_map.lookup(CollType.ALLREDUCE,
                                             MemoryType.HOST, NBYTES)[0]
            assert top.origin == "learned" and top.alg_name == winner
            srcs = [np.ones(COUNT, np.float32) for _ in range(4)]
            dsts = [np.zeros(COUNT, np.float32) for _ in range(4)]
            reqs = _persistent_allreduce(teams2, srcs, dsts)
            assert all("post" not in rq.__dict__ for rq in reqs)
            assert all(rq.task.alg_name == winner for rq in reqs)
            _drive(job2, reqs, 2, dsts, 4)
            assert all(not t.tuner._keys for t in teams2)  # zero explored
            for rq in reqs:
                rq.finalize()
        finally:
            job2.cleanup()

    def test_overlapped_posts_freeze_to_static_defaults(self, tmp_path):
        """Streaming apps post a key's collectives back-to-back without
        waiting; post counts then advance without completions, breaking
        the hold window's causality argument. claim() detects the
        overlap by FINALIZE order (program order, rank-invariant) and
        deterministically ends tuning for the key instead."""
        cache = str(tmp_path / "tune.json")
        job = UccJob(2, lib_overrides={"TUNER": "online",
                                       "TUNER_SAMPLES": "4",
                                       "TUNER_CACHE": cache})
        try:
            teams = job.create_team()
            srcs = [np.ones(COUNT, np.float32) for _ in range(2)]
            d1 = [np.zeros(COUNT, np.float32) for _ in range(2)]
            d2 = [np.zeros(COUNT, np.float32) for _ in range(2)]
            r1 = _persistent_allreduce(teams, srcs, d1)
            r2 = _persistent_allreduce(teams, srcs, d2)
            assert all("post" in rq.__dict__ for rq in r1 + r2)
            # overlap: post BOTH requests on every rank before waiting
            for rq in r1:
                rq.post()
            for rq in r2:
                rq.post()
            job.progress_until(lambda: all(
                rq.test() != Status.IN_PROGRESS for rq in r1 + r2))
            for rq in r1 + r2:
                assert rq.test() == Status.OK
            for d in d1 + d2:
                assert abs(float(d[0]) - 2) < 1e-6
            # the overlapped key froze to static defaults on every rank
            key = teams[0].tuner.key_for(CollType.ALLREDUCE,
                                         MemoryType.HOST, NBYTES)
            for t in teams:
                st = t.tuner._keys[key]
                assert st.frozen and st.winner is None
            top = teams[0].score_map.lookup(CollType.ALLREDUCE,
                                            MemoryType.HOST, NBYTES)[0]
            assert top.origin == "default"
            # later rounds keep working, unbound, on the same algorithm
            for _ in range(2):
                for rq in r1:
                    rq.post()
                job.progress_until(lambda: all(
                    rq.test() != Status.IN_PROGRESS for rq in r1))
            assert all("post" not in rq.__dict__ for rq in r1 + r2)
            assert len({rq.task.alg_name for rq in r1}) == 1
            for rq in r1 + r2:
                rq.finalize()
        finally:
            job.cleanup()

    def test_single_rank_team_freezes_locally(self, tmp_path):
        # size-1 teams decide through tl/self's trivial service bcast
        cache = str(tmp_path / "tune.json")
        job = UccJob(1, lib_overrides={"TUNER": "online",
                                       "TUNER_SAMPLES": "2",
                                       "TUNER_CACHE": cache})
        try:
            teams = job.create_team()
            # a 1-rank team's score map usually has a single live self
            # candidate per coll -> wants() is False and nothing binds;
            # the team must still activate and run
            srcs = [np.ones(COUNT, np.float32)]
            dsts = [np.zeros(COUNT, np.float32)]
            reqs = _persistent_allreduce(teams, srcs, dsts)
            _drive(job, reqs, 3, dsts, 1)
            for rq in reqs:
                rq.finalize()
        finally:
            job.cleanup()


class TestOffModes:
    def test_off_leaves_dispatch_unbound(self):
        job = UccJob(2)
        try:
            teams = job.create_team()
            assert all(t.tuner is None for t in teams)
            srcs = [np.ones(COUNT, np.float32) for _ in range(2)]
            dsts = [np.zeros(COUNT, np.float32) for _ in range(2)]
            reqs = _persistent_allreduce(teams, srcs, dsts)
            # no probe lane: post stays the plain class method (the
            # UCC_TUNER=off byte-identical dispatch contract)
            assert all("post" not in rq.__dict__ for rq in reqs)
            assert all(rq._tuner is None for rq in reqs)
            _drive(job, reqs, 2, dsts, 2)
            for rq in reqs:
                rq.finalize()
        finally:
            job.cleanup()

    def test_offline_applies_cache_without_exploring(self, tmp_path):
        cache = str(tmp_path / "tune.json")
        # probe the signature with a throwaway off-mode job first
        probe = UccJob(2)
        try:
            sig = topo_signature(probe.create_team()[0])
        finally:
            probe.cleanup()
        store_entries(cache, sig, [
            {"coll": "allreduce", "mem": "host", "start": 0,
             "end": SIZE_INF, "alg": "ring", "comp": "shm"}])
        job = UccJob(2, lib_overrides={"TUNER": "offline",
                                       "TUNER_CACHE": cache})
        try:
            teams = job.create_team()
            assert all(t.tuner is None for t in teams)  # no explorer
            for t in teams:
                top = t.score_map.lookup(CollType.ALLREDUCE,
                                         MemoryType.HOST, NBYTES)[0]
                assert (top.alg_name, top.origin) == ("ring", "learned")
            srcs = [np.ones(COUNT, np.float32) for _ in range(2)]
            dsts = [np.zeros(COUNT, np.float32) for _ in range(2)]
            reqs = _persistent_allreduce(teams, srcs, dsts)
            assert all(rq.task.alg_name == "ring" for rq in reqs)
            _drive(job, reqs, 2, dsts, 2)
            for rq in reqs:
                rq.finalize()
        finally:
            job.cleanup()

    def test_mismatched_signature_is_ignored(self, tmp_path):
        cache = str(tmp_path / "tune.json")
        store_entries(cache, "v1|n999|some-other-shape", [
            {"coll": "allreduce", "mem": "host", "start": 0,
             "end": SIZE_INF, "alg": "ring", "comp": "shm"}])
        job = UccJob(2, lib_overrides={"TUNER": "offline",
                                       "TUNER_CACHE": cache})
        try:
            teams = job.create_team()
            top = teams[0].score_map.lookup(CollType.ALLREDUCE,
                                            MemoryType.HOST, NBYTES)[0]
            assert top.origin == "default"
        finally:
            job.cleanup()


# ---------------------------------------------------------------------------
# offline CLI (tools/tune.py / ucc_tune)
# ---------------------------------------------------------------------------

class TestOfflineCli:
    def test_sweep_writes_cache_and_from_compiles(self, tmp_path):
        from ucc_tpu.tools.tune import main as tune_main
        cache = str(tmp_path / "cache.json")
        meas = str(tmp_path / "sweep.jsonl")
        rc = tune_main(["-p", "2", "-c", "allreduce", "-b", "1k", "-e",
                        "2k", "-n", "2", "-w", "0", "-o", cache,
                        "--measurements", meas])
        assert rc == 0
        data = load_cache(cache)
        sigs = list((data.get("signatures") or {}))
        assert len(sigs) == 1 and sigs[0].startswith("v1|n2|")
        entries = cache_entries(data, sigs[0])
        assert entries and entries[0]["coll"] == "allreduce"
        assert os.path.exists(meas)
        records = [json.loads(ln) for ln in open(meas)]
        assert all(r["bench"] == "sweep" for r in records)
        assert {r["alg"] for r in records} >= {"knomial", "ring"}
        # --from re-compiles the measurement file into a second cache
        cache2 = str(tmp_path / "cache2.json")
        rc = tune_main(["--from", meas, "--signature", sigs[0], "-o",
                        cache2])
        assert rc == 0
        assert cache_entries(load_cache(cache2), sigs[0]) == entries
